"""CNN end-to-end on the synthetic catch game — the Pong stand-in
(SURVEY.md §4 'short Pong run for reward slope sign'; round-1 verdict
item 8). The dueling Nature-CNN must learn from raw 84x84x4 uint8 pixels
through the full preprocessing stack."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    EnvConfig, LearnerConfig, NetworkConfig, ReplayConfig, get_config)
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.runtime.learner import DQNLearner, transition_item_spec
from ape_x_dqn_tpu.runtime.single_process import train_single_process
from ape_x_dqn_tpu.utils.rng import component_key


def _catch_cfg(total_frames=20_000):
    return get_config("pong").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari"),
        network=NetworkConfig(kind="nature_cnn", dueling=True,
                              compute_dtype="float32"),
        replay=ReplayConfig(kind="prioritized", capacity=32_768,
                            min_fill=1000),
        learner=LearnerConfig(batch_size=32, n_step=3, lr=2.5e-4,
                              target_sync_every=250),
        total_env_frames=total_frames,
    )


def test_cnn_learner_jit_runs_at_flagship_shapes():
    """The dueling Nature-CNN learner graph must compile and step at the
    flagship batch 512 / 84x84x4 uint8 shapes (round-1 verdict weak #5;
    bench.py measures the same graph's throughput on the real chip)."""
    cfg = _catch_cfg()
    env = make_env(cfg.env, seed=0)
    assert env.spec.obs_shape == (84, 84, 4)
    net = build_network(cfg.network, env.spec)
    params = net.init(component_key(0, "net_init"), env.reset()[None])
    replay = PrioritizedReplay(capacity=2048)
    lcfg = cfg.learner.__class__(batch_size=512)
    learner = DQNLearner(net.apply, replay, lcfg)
    spec = transition_item_spec(env.spec.obs_shape, env.spec.obs_dtype)
    state = learner.init(params, replay.init(spec), jax.random.key(0))
    rng = np.random.default_rng(0)
    items = {
        "obs": jnp.asarray(rng.integers(0, 255, (1024, 84, 84, 4)),
                           jnp.uint8),
        "action": jnp.asarray(rng.integers(0, 6, 1024), jnp.int32),
        "reward": jnp.asarray(rng.normal(size=1024), jnp.float32),
        "next_obs": jnp.asarray(rng.integers(0, 255, (1024, 84, 84, 4)),
                                jnp.uint8),
        "discount": jnp.full(1024, 0.97, jnp.float32),
    }
    state = learner.add(state, items, jnp.ones(1024))
    state, m = learner.train_step(state)
    assert np.isfinite(m["loss"])
    assert int(state.step) == 1


@pytest.mark.slow
def test_cnn_learns_catch_from_pixels():
    """Reward slope: from the random plateau (~ -4.2 per 5-ball episode)
    the CNN agent must reach a clearly positive catch rate. Measured
    dynamics: avg return passes +5 near 12k frames, +14 by 21k."""
    cfg = _catch_cfg(total_frames=20_000)
    out = train_single_process(cfg, train_every=4, solve_return=4.0)
    assert out["episodes"] > 10
    assert out["last20_return"] >= 4.0, out

@pytest.mark.slow
def test_cnn_learns_catch_kbatch():
    """Learning parity for the K-batch sampling relaxation
    (LearnerConfig.sample_chunk=4): the CNN agent must clear the same
    catch-rate bar as the exact per-step path
    (test_cnn_learns_catch_from_pixels) with identical frame budget and
    steps-per-frame ratio — within-chunk priority staleness must not
    cost learning on this task."""
    import dataclasses
    cfg = _catch_cfg(total_frames=20_000)
    cfg = cfg.replace(learner=dataclasses.replace(cfg.learner,
                                                  sample_chunk=4))
    out = train_single_process(cfg, train_every=4, solve_return=4.0)
    assert out["episodes"] > 10
    assert out["last20_return"] >= 4.0, out


@pytest.mark.slow
def test_cnn_learns_catch_prefetch():
    """Learning parity for the double-buffered sampler
    (sample_chunk=4 + sample_prefetch=True): each macro-step's sample is
    drawn against priorities predating the previous macro-step's
    write-back (one-dispatch staleness, matching the reference's async
    sampler), and the agent must still clear the same catch-rate bar as
    the exact and fused K-batch paths with identical frame budget."""
    import dataclasses
    cfg = _catch_cfg(total_frames=20_000)
    cfg = cfg.replace(learner=dataclasses.replace(
        cfg.learner, sample_chunk=4, sample_prefetch=True))
    out = train_single_process(cfg, train_every=4, solve_return=4.0)
    assert out["episodes"] > 10
    assert out["last20_return"] >= 4.0, out
