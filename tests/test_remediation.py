"""Fleet remediation plane (runtime/remediation.py, ISSUE 14).

What the plane must prove, per bound:
- hysteresis: a sensor flapping breach/clear every tick never moves an
  actuator — streaks cannot accumulate through oscillation;
- rate limits: per-target cooldown blocks re-application, the global
  token bucket suppresses non-safety actions when exhausted, and
  SAFETY actions (wedged-slot restart) bypass the bucket but not the
  cooldown;
- observe mode: the full decision pipeline runs and every decision is
  attributed (JSONL + counters), but NO actuator is ever called;
- the backpressure latch dies with the incarnation that set it: an
  epoch change on the transport clears it (satellite of the same PR);
- end to end: a real driver with the plane in enforce mode
  auto-restarts a ThreadWedge'd actor slot from inside its supervisor
  tick — no StallError, no driver exit — and the decision lands in the
  run JSONL as an attributed `remediation` event.
"""

import json
import threading
import time

import numpy as np

from ape_x_dqn_tpu.comm.socket_transport import (
    SocketIngestServer, SocketTransport)
from ape_x_dqn_tpu.configs import RemediationConfig
from ape_x_dqn_tpu.runtime.remediation import (
    Actuators, RemediationEngine)
from tools.chaos import ThreadWedge


# -- fakes ------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeObs:
    def __init__(self):
        self.counters = {}
        self.gauges = {}

    def count(self, name, n=1.0):
        self.counters[name] = self.counters.get(name, 0.0) + n

    def gauge(self, name, value):
        self.gauges[name] = value


class FakeMetrics:
    def __init__(self):
        self.records = []

    def log(self, step, **kw):
        self.records.append({"step": step, **kw})


class CallLog:
    """Actuators that record every invocation and report success."""

    def __init__(self):
        self.calls = []

    def wire(self, **override):
        def rec(name):
            return lambda *a: (self.calls.append((name, a)), True)[1]
        kw = {f: rec(f) for f in ("restart_actor", "quarantine_peer",
                                  "pause_actor", "resume_actor",
                                  "set_backpressure", "set_priority")}
        kw.update(override)
        return Actuators(**kw)

    def named(self, name):
        return [a for n, a in self.calls if n == name]


def _engine(log, clock, metrics=None, obs=None, **cfg_kw):
    cfg_kw.setdefault("mode", "enforce")
    cfg_kw.setdefault("hysteresis_ticks", 2)
    cfg_kw.setdefault("cooldown_s", 0.0)
    cfg_kw.setdefault("budget_per_min", 60.0)
    cfg = RemediationConfig(**cfg_kw)
    return RemediationEngine(cfg, obs or FakeObs(),
                             metrics or FakeMetrics(), log.wire(),
                             default_class=1, clock=clock)


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {"obs": rng.random((n, 4)).astype(np.float32),
            "action": rng.integers(0, 2, (n,)).astype(np.int32),
            "priorities": (rng.random(n) + 0.1).astype(np.float32),
            "actor": 0, "frames": n}


# -- hysteresis: flapping never trips an actuator ---------------------------

def test_flapping_sensors_never_move_actuators():
    clock, log = FakeClock(), CallLog()
    metrics = FakeMetrics()
    eng = _engine(log, clock, metrics=metrics, min_actors=1)
    breach = {"queue_depth": 100.0, "queue_slo": 10.0,
              "ingest_dropped_delta": 5.0, "running_slots": (0, 1)}
    clear = {"queue_depth": 0.0, "queue_slo": 10.0,
             "ingest_dropped_delta": 0.0, "running_slots": (0, 1)}
    for i in range(20):  # breach/clear oscillation, 10 full cycles
        eng.tick(breach if i % 2 == 0 else clear)
        clock.advance(1.0)
    assert log.calls == []  # no actuator ever moved
    # no decision was even emitted: flapping is not a policy event
    assert metrics.records == []
    assert "applied" not in eng.summary()["counts"]


def test_sustained_breach_engages_then_sustained_clear_releases():
    clock, log = FakeClock(), CallLog()
    eng = _engine(log, clock)
    breach = {"queue_depth": 100.0, "queue_slo": 10.0}
    clear = {"queue_depth": 0.0, "queue_slo": 10.0}
    for _ in range(2):  # hysteresis_ticks consecutive agreeing ticks
        eng.tick(breach)
        clock.advance(1.0)
    assert log.named("set_backpressure") == [(True,)]
    for _ in range(3):  # staying breached does not re-apply
        eng.tick(breach)
        clock.advance(1.0)
    assert log.named("set_backpressure") == [(True,)]
    for _ in range(2):
        eng.tick(clear)
        clock.advance(1.0)
    assert log.named("set_backpressure") == [(True,), (False,)]


# -- rate limits: cooldown, budget, safety bypass ---------------------------

def test_per_target_cooldown_blocks_reapplication():
    clock, log = FakeClock(), CallLog()
    eng = _engine(log, clock, cooldown_s=10.0)
    assert eng.remediate_stale_actor(0, 5.0) is True
    # inside the window the same remedy on the same target is refused —
    # the driver falls back to its own (escalating) supervisor path
    clock.advance(1.0)
    assert eng.remediate_stale_actor(0, 5.0) is False
    assert len(log.named("restart_actor")) == 1
    assert eng.summary()["counts"]["cooldown"] >= 1
    # a DIFFERENT target is not in this target's cooldown
    assert eng.remediate_stale_actor(1, 5.0) is True
    clock.advance(10.0)  # window over: the remedy is available again
    assert eng.remediate_stale_actor(0, 5.0) is True
    assert len(log.named("restart_actor")) == 3


def test_budget_exhaustion_suppresses_nonsafety_but_not_safety():
    clock, log = FakeClock(), CallLog()
    obs = FakeObs()
    eng = _engine(log, clock, obs=obs, hysteresis_ticks=1,
                  budget_per_min=1.0, min_actors=1)
    # the single token goes to the backpressure engage
    eng.tick({"queue_depth": 100.0, "queue_slo": 10.0})
    assert log.named("set_backpressure") == [(True,)]
    # bucket empty (clock frozen, no refill): the next non-safety
    # action is suppressed, attributed, and the actuator never runs
    eng.tick({"ingest_dropped_delta": 5.0, "running_slots": (0, 1)})
    assert log.named("pause_actor") == []
    assert eng.summary()["counts"]["suppressed"] >= 1
    assert obs.counters.get("remediation_suppressed", 0) >= 1
    # SAFETY bypasses the bucket: a wedged slot restarts on zero tokens
    assert eng.remediate_stale_actor(0, 9.0) is True
    assert len(log.named("restart_actor")) == 1
    # a minute later the bucket refilled and the paused rule can act
    clock.advance(60.0)
    eng.tick({"ingest_dropped_delta": 5.0, "running_slots": (0, 1)})
    assert log.named("pause_actor") == [(1,)]
    # headroom gauge published for the report's INSTRUMENTS row
    assert "remediation_budget_headroom" in obs.gauges


# -- observe mode: attributed dry run, actuators untouched ------------------

def test_observe_mode_emits_but_never_acts():
    clock, log = FakeClock(), CallLog()
    obs, metrics = FakeObs(), FakeMetrics()
    eng = _engine(log, clock, mode="observe", obs=obs, metrics=metrics,
                  hysteresis_ticks=1)
    # safety rule: decision observed, NOT handled (driver falls back)
    assert eng.remediate_stale_actor(0, 5.0) is False
    # gauge rule: full state machine runs dry (engage then release)
    eng.tick({"queue_depth": 100.0, "queue_slo": 10.0})
    clock.advance(1.0)
    eng.tick({"queue_depth": 0.0, "queue_slo": 10.0})
    assert log.calls == []  # no actuator was EVER called
    outcomes = {r["remediation_outcome"] for r in metrics.records}
    assert outcomes == {"observed"}
    labels = {r["remediation_action"] for r in metrics.records}
    assert {"restart_actor", "engage_backpressure",
            "release_backpressure"} <= labels
    assert obs.counters["remediation_observed"] == len(metrics.records)
    assert obs.gauges.get("remediation_mode") == 1.0
    # every record is fully attributed for the report's decision table
    for rec in metrics.records:
        assert rec["remediation"] and rec["remediation_target"]


def test_unwired_actuator_degrades_per_rule_not_crash():
    clock, log = FakeClock(), CallLog()
    eng = RemediationEngine(
        RemediationConfig(mode="enforce", hysteresis_ticks=1,
                          cooldown_s=0.0),
        FakeObs(), FakeMetrics(),
        log.wire(restart_actor=None), clock=clock)
    # missing callable: outcome "unwired", never an exception, and NOT
    # handled — the driver's default supervisor path takes over
    assert eng.remediate_stale_actor(0, 5.0) is False
    assert eng.summary()["counts"]["unwired"] == 1


def test_failing_actuator_is_contained_and_counted():
    clock, log = FakeClock(), CallLog()
    obs = FakeObs()

    def boom(*a):
        raise RuntimeError("actuator exploded")

    eng = RemediationEngine(
        RemediationConfig(mode="enforce", hysteresis_ticks=1,
                          cooldown_s=0.0),
        obs, FakeMetrics(), log.wire(restart_actor=boom), clock=clock)
    assert eng.remediate_stale_actor(0, 5.0) is False  # fell back
    assert eng.summary()["counts"]["failed"] == 1
    assert obs.counters["remediation_failed"] == 1


# -- the latch dies with its incarnation (satellite: transport) -------------

def test_backpressure_does_not_survive_learner_incarnation_change():
    """REGRESSION: the serving tier's backpressure latch is engaged by
    ONE learner incarnation's admission controller. Left set across an
    epoch change it would shed every send into the NEW incarnation
    forever (the controller that would release it is dead). The
    transport must clear it the moment it observes the new epoch."""
    srv1 = SocketIngestServer("127.0.0.1", 0, epoch=1)
    port = srv1.port
    client = SocketTransport("127.0.0.1", port, reconnect_base_s=0.01,
                             reconnect_cap_s=0.2)
    srv2 = None
    try:
        client.send_experience(_batch())
        assert srv1.recv_experience(timeout=5.0) is not None
        assert client.epoch == 1

        client.set_backpressure(True)
        assert client.backpressure_engaged
        client.send_experience(_batch())  # latched: host-side drop
        assert client.drop_reasons["backpressure"] >= 1

        srv1.stop()  # the incarnation that engaged the latch dies
        srv2 = SocketIngestServer("127.0.0.1", port, epoch=2)
        srv2.publish_params({"w": 1}, 0)
        # the experience path is latched shut, so the param plane is
        # where the new epoch is first observed — exactly the deadlock
        # the clear exists to break
        assert _wait(lambda: (client.get_params(),
                              client.epoch_changes >= 1)[1]), \
            "client never observed the new incarnation"
        assert not client.backpressure_engaged

        def resumed():
            client.send_experience(_batch())
            return srv2.recv_experience(timeout=0.2) is not None

        assert _wait(resumed), "ingest never resumed post-clear"
    finally:
        client.close()
        if srv2 is not None:
            srv2.stop()


def test_kick_collapses_pending_backoff_only():
    """The remediation plane's in-place restart equivalent: kick()
    zeroes a PENDING reconnect backoff so the next send retries now,
    and reports not-applicable (False -> outcome "skipped") when there
    is nothing to collapse."""
    srv = SocketIngestServer("127.0.0.1", 0, epoch=1)
    port = srv.port
    client = SocketTransport("127.0.0.1", port, reconnect_base_s=30.0,
                             reconnect_cap_s=60.0)
    try:
        client.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        assert client.kick() is False  # healthy: nothing pending
        srv.stop()

        def hard_drop():
            client.send_experience(_batch())
            r = client.drop_reasons
            return (r["reset"] + r["refused"] + r["timeout"]
                    + r["other"] >= 1)

        assert _wait(hard_drop, timeout=3.0)
        # backoff armed for ~30s: sends now drop without touching the
        # network; kick() collapses the window
        client.send_experience(_batch())
        assert client.drop_reasons["backpressure"] >= 1
        assert client.kick() is True
        assert client.kick() is False  # idempotent: already collapsed
        srv2 = SocketIngestServer("127.0.0.1", port, epoch=2)
        try:
            def resumed():
                client.send_experience(_batch())
                return srv2.recv_experience(timeout=0.2) is not None

            assert _wait(resumed), "kicked sender never resumed"
        finally:
            srv2.stop()
    finally:
        client.close()


# -- chaos e2e: a wedged actor is auto-restarted, the driver survives -------

def test_enforce_mode_auto_restarts_wedged_actor(tmp_path):
    """The tentpole loop, closed end to end on a REAL driver: an actor
    slot wedges (cooperative ThreadWedge, the wedged-not-dead fault
    shape), its heartbeat goes stale past the watchdog timeout, and the
    supervisor tick's remediation engine restarts the slot — the
    driver does not raise, does not exit, and the decision is an
    attributed `remediation` event in the run JSONL."""
    from ape_x_dqn_tpu.configs import (
        ActorConfig, InferenceConfig, LearnerConfig, ObsConfig,
        ReplayConfig, get_config)
    from ape_x_dqn_tpu.runtime.driver import ApexDriver
    from ape_x_dqn_tpu.utils.metrics import Metrics

    jsonl = str(tmp_path / "run.jsonl")
    cfg = get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=2, ingest_batch=16,
                           supervise=True, supervisor_max_restarts=2),
        replay=ReplayConfig(kind="prioritized", capacity=1024,
                            min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        obs=ObsConfig(enabled=True, heartbeat_timeout_s=0.3),
        remediation=RemediationConfig(mode="enforce",
                                      hysteresis_ticks=1,
                                      cooldown_s=0.05,
                                      budget_per_min=60.0),
        eval_every_steps=0, eval_episodes=0)
    driver = ApexDriver(cfg, metrics=Metrics(log_path=jsonl))
    assert driver.remediation is not None

    spawned = []
    real_spawn = driver._spawn_actor_slot
    driver._spawn_actor_slot = \
        lambda i, f, attempt0=0: spawned.append((i, f, attempt0))

    wedge = ThreadWedge()
    stop = threading.Event()
    driver.obs.register("actor-0")

    def actor_loop():  # the slot's heartbeat source, wedgeable
        while not stop.is_set():
            wedge.checkpoint(timeout=5.0)
            if stop.is_set():
                return
            driver.obs.beat("actor-0", "looping")
            time.sleep(0.02)

    t = threading.Thread(target=actor_loop, daemon=True)
    t.start()
    try:
        assert _wait(
            lambda: driver.obs.heartbeats.ages().get(
                "actor-0", (99.0, ""))[0] < 0.1)
        driver._slot_budget[0] = 640
        wedge.engage()  # the fault: alive thread, silent heartbeat
        time.sleep(driver.obs.watchdog.timeout_s + 0.15)

        driver._supervise_tick()  # must restart, NOT raise StallError

        assert spawned and spawned[0][0] == 0
        assert driver._slot_restarts[0] == 1
        summary = driver.remediation.summary()
        assert summary["counts"].get("applied", 0) >= 1
        assert summary["decided_by_rule"].get("actor-wedge") == 1
        assert driver.obs.registry.counter(
            "remediation_actions").value >= 1
        # the re-armed heartbeat keeps the next immediate tick green
        driver._supervise_tick()
        assert len(spawned) == 1

        events = [json.loads(line)
                  for line in open(jsonl, encoding="utf-8")
                  if "remediation" in line]
        hits = [e for e in events
                if e.get("remediation") == "actor-wedge"
                and e.get("remediation_outcome") == "applied"]
        assert hits and hits[0]["remediation_target"] == "actor-0"
        assert hits[0]["remediation_action"] == "restart_actor"
    finally:
        driver._spawn_actor_slot = real_spawn
        stop.set()
        wedge.release()
        t.join(timeout=2)
        driver.obs.clear("actor-0")
        driver.obs.close()
