"""utils/metrics.py unit coverage: Throughput windowing, JSONL
scrubbing, run-header contract, and the Atari HNS table (ISSUE 2
satellite — these behaviors were previously only exercised indirectly
through driver e2e runs)."""

import json

import pytest

from ape_x_dqn_tpu import __version__
from ape_x_dqn_tpu.configs import get_config
from ape_x_dqn_tpu.utils.metrics import (
    Metrics, Throughput, human_normalized_score, log_run_header,
    median_hns)


def test_throughput_windowing():
    """rate() covers only events inside the sliding window; total is
    lifetime. Explicit `now` args make the test clock-free."""
    tp = Throughput(window_s=10.0)
    tp.add(100, now=0.0)
    tp.add(100, now=5.0)
    # both events in window: 200 events over the 5s span
    assert tp.rate(now=5.0) == pytest.approx(200 / 5.0)
    # t=12: the t=0 event has aged out; a single survivor can't define
    # a span, so the rate degrades to 0 rather than inventing one
    assert tp.rate(now=12.0) == 0.0
    tp.add(50, now=12.0)
    assert tp.rate(now=12.0) == pytest.approx((100 + 50) / 7.0)
    # total is lifetime, unaffected by window trimming
    assert tp.total == 250


def test_throughput_total_lifetime():
    tp = Throughput(window_s=0.001)
    for _ in range(5):
        tp.add(2, now=0.0)
    tp.add(1, now=100.0)  # trims every earlier event out of the window
    assert tp.total == 11


def test_metrics_scrubs_nonfinite(tmp_path):
    """NaN/Inf are not valid JSON — the sink nulls them so a diverged
    run's JSONL stays parseable end to end."""
    path = str(tmp_path / "m.jsonl")
    m = Metrics(log_path=path)
    m.log(1, loss=float("nan"), q=float("inf"),
          neg=float("-inf"), ok=1.5)
    m.close()
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["loss"] is None
    assert rec["q"] is None
    assert rec["neg"] is None
    assert rec["ok"] == 1.5


def test_metrics_bool_passthrough(tmp_path):
    """bools survive as JSON booleans (header flags like
    sample_prefetch), not as 0.0/1.0 floats."""
    path = str(tmp_path / "m.jsonl")
    m = Metrics(log_path=path)
    m.log(0, flag_on=True, flag_off=False)
    m.close()
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["flag_on"] is True
    assert rec["flag_off"] is False


def test_log_run_header_fields(tmp_path):
    """The first record must carry the semantics that produced the
    numbers: version, sample_chunk AND sample_prefetch (round-4 verdict
    weak #6 — a JSONL read in isolation was silent about which sampling
    semantics it recorded)."""
    path = str(tmp_path / "m.jsonl")
    m = Metrics(log_path=path)
    cfg = get_config("pong")
    log_run_header(m, cfg)
    m.close()
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["run_name"] == cfg.name
    assert rec["version"] == __version__
    assert rec["sample_chunk"] == max(cfg.learner.sample_chunk, 1)
    assert rec["sample_prefetch"] is bool(cfg.learner.sample_prefetch)
    assert rec["replay_kind"] == cfg.replay.kind
    assert rec["replay_capacity"] == cfg.replay.capacity
    assert rec["batch_size"] == cfg.learner.batch_size


def test_hns_known_game():
    # pong: random -20.7, human 14.6
    assert human_normalized_score("pong", 14.6) == pytest.approx(1.0)
    assert human_normalized_score("pong", -20.7) == pytest.approx(0.0)


def test_hns_unknown_game_names_offender():
    """Typos fail loudly WITH the offending key and close matches, not
    a bare KeyError deep in a suite aggregation."""
    with pytest.raises(ValueError, match="space_invader"):
        human_normalized_score("space_invader", 100.0)
    try:
        human_normalized_score("space_invader", 100.0)
    except ValueError as e:
        assert "space_invaders" in str(e)  # difflib suggestion


def test_median_hns():
    scores = {"pong": 14.6, "breakout": 1.7, "freeway": 29.6}
    # per-game HNS: 1.0, 0.0, 1.0 -> median 1.0
    assert median_hns(scores) == pytest.approx(1.0)
    assert median_hns({}) == 0.0
