"""Pallas frame-row gather (ops/frame_gather.py): interpret-mode
correctness against the jnp reference. The TPU performance comparison
that decided AGAINST adopting it lives in PERF.md."""

import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.ops.frame_gather import (
    gather_rows_pallas, gather_rows_reference)


def test_pallas_gather_matches_reference():
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(0, 255, (500, 84, 84)), jnp.uint8)
    idx = jnp.asarray(rng.integers(0, 500, 128), jnp.int32)
    out = gather_rows_pallas(src, idx, interpret=True)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(gather_rows_reference(src, idx)))


def test_pallas_gather_duplicate_and_boundary_indices():
    rng = np.random.default_rng(1)
    src = jnp.asarray(rng.integers(0, 255, (8, 6, 6)), jnp.uint8)
    idx = jnp.asarray([0, 7, 7, 3, 0, 7], jnp.int32)
    out = gather_rows_pallas(src, idx, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(src)[
        np.asarray(idx)])
