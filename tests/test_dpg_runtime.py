"""Ape-X DPG runtime: continuous actor, fused DPG learner, and the full
driver wiring on the pendulum swing-up task (SURVEY.md §2.1 config 5)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, NetworkConfig,
    ParallelConfig, ReplayConfig, get_config)
from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.models import DPGActor, DPGCritic
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.runtime.actor import ContinuousActor
from ape_x_dqn_tpu.runtime.dpg_learner import (
    DPGLearner, continuous_item_spec)
from ape_x_dqn_tpu.runtime.driver import ApexDriver


def _dpg_cfg(num_actors=2):
    return get_config("apex_dpg").replace(
        env=EnvConfig(id="pendulum", kind="control"),
        network=NetworkConfig(kind="dpg", dpg_hidden=(64, 64),
                              compute_dtype="float32"),
        replay=ReplayConfig(kind="prioritized", capacity=16_384,
                            min_fill=256),
        learner=LearnerConfig(batch_size=64, n_step=5, gamma=0.99,
                              critic_lr=1e-3, policy_lr=5e-4, tau=0.01,
                              publish_every=25, train_chunk=4),
        actors=ActorConfig(num_actors=num_actors, ingest_batch=32,
                           noise_sigma=0.15),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        parallel=ParallelConfig(dp=1, tp=1),
        eval_every_steps=0, eval_episodes=3,
    )


def test_continuous_actor_ships_transitions():
    cfg = _dpg_cfg(num_actors=1)
    transport = LoopbackTransport()

    def query_fn(obs):
        return {"a": np.array([0.5], np.float32), "q": np.float32(1.0)}

    actor = ContinuousActor(cfg, 0, query_fn, transport)
    frames = actor.run(max_frames=300)
    assert frames == 300
    batches, total = [], 0
    while True:
        b = transport.recv_experience(timeout=0.01)
        if b is None:
            break
        batches.append(b)
        total += len(b["priorities"])
    assert batches, "actor shipped nothing"
    b0 = batches[0]
    assert b0["obs"].shape[1:] == (3,)
    assert b0["action"].shape[1:] == (1,)
    assert b0["action"].dtype == np.float32
    # exploration noise moves actions off the deterministic 0.5
    assert np.std(b0["action"]) > 0.01
    # actions stay inside the env's box
    assert (np.abs(b0["action"]) <= 2.0 + 1e-6).all()
    assert (b0["priorities"] >= 0).all()
    assert sum(b["frames"] for b in batches) == 300
    assert total > 250


def test_dpg_learner_trains_and_polyaks_targets():
    actor = DPGActor(action_dim=1, action_low=-2, action_high=2,
                     hidden=(16, 16))
    critic = DPGCritic(hidden=(16, 16))
    obs0 = jnp.zeros((1, 3), jnp.float32)
    a0 = jnp.zeros((1, 1), jnp.float32)
    actor_params = actor.init(jax.random.key(0), obs0)
    critic_params = critic.init(jax.random.key(1), obs0, a0)
    replay = PrioritizedReplay(capacity=256)
    spec = continuous_item_spec((3,), np.float32, 1)
    lcfg = LearnerConfig(batch_size=32, n_step=5, critic_lr=1e-3,
                         policy_lr=1e-4, tau=0.05)
    learner = DPGLearner(actor.apply, critic.apply, replay, lcfg)
    state = learner.init(actor_params, critic_params, replay.init(spec),
                         jax.random.key(2))
    rng = np.random.default_rng(0)
    items = {
        "obs": jnp.asarray(rng.normal(size=(64, 3)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-2, 2, (64, 1)), jnp.float32),
        "reward": jnp.asarray(rng.normal(size=(64,)), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(64, 3)), jnp.float32),
        "discount": jnp.full((64,), 0.95, jnp.float32),
    }
    state = learner.add(state, items, jnp.ones(64))
    target_before = jax.tree.map(np.asarray, state.target_critic)
    online_before = jax.tree.map(np.asarray, state.critic_params)
    state, m = learner.train_step(state)
    assert np.isfinite(m["loss"]) and np.isfinite(m["policy_loss"])
    assert int(state.step) == 1
    # Polyak: targets moved toward (but not onto) the online params
    t_after = jax.tree.leaves(jax.tree.map(np.asarray,
                                           state.target_critic))
    t_before = jax.tree.leaves(target_before)
    o_before = jax.tree.leaves(online_before)
    moved = any(not np.allclose(a, b) for a, b in zip(t_after, t_before))
    assert moved
    not_equal_online = any(
        not np.allclose(a, b)
        for a, b in zip(t_after,
                        jax.tree.leaves(jax.tree.map(
                            np.asarray, state.critic_params))))
    assert not_equal_online
    state, m = learner.train_many(state, 3)
    assert int(state.step) == 4


def test_dpg_driver_end_to_end():
    """Full continuous wiring: noisy actors -> batched mu+Q inference ->
    ingest -> fused DPG learner -> deterministic eval."""
    cfg = _dpg_cfg(num_actors=2).replace(
        learner=dataclasses.replace(_dpg_cfg().learner,
                                    steps_per_frame_cap=1.0))
    driver = ApexDriver(cfg)
    assert driver.family == "dpg"
    # run to the frame budget: pendulum episodes are 200 steps, so a
    # grad-step-capped run can end before the first episode completes
    out = driver.run(total_env_frames=2400, max_grad_steps=10**9,
                     wall_clock_limit_s=240)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 60, out
    assert out["frames"] >= 1000, out
    assert out["episodes"] > 0
    assert driver.server.params_version > 0
    assert out["eval"] is not None and out["eval"]["episodes"] > 0


def _require_dm_control():
    from ape_x_dqn_tpu.envs.control import HAVE_DM_CONTROL
    if not HAVE_DM_CONTROL:
        pytest.skip("dm_control not installed")


def test_dpg_driver_real_dm_control_e2e():
    """Full driver wiring against REAL MuJoCo physics (dm_control
    pendulum swingup — ids with an underscore route to
    DMControlAdapter): the synthetic-pendulum e2e alone cannot prove
    the flagship control path works when dm_control is present
    (round-3 verdict missing #2 / weak #5)."""
    _require_dm_control()
    cfg = _dpg_cfg(num_actors=2).replace(
        env=EnvConfig(id="pendulum_swingup", kind="control"),
        learner=dataclasses.replace(_dpg_cfg().learner,
                                    steps_per_frame_cap=1.0))
    driver = ApexDriver(cfg)
    assert driver.family == "dpg"
    # dm_control episodes are 1000 steps; run to a frame budget small
    # enough for CI but past min_fill so the learner actually trains
    out = driver.run(total_env_frames=2400, max_grad_steps=10**9,
                     wall_clock_limit_s=240)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 60, out
    assert out["frames"] >= 1000, out
    assert driver.server.params_version > 0
    # deterministic eval ran on the real physics; swingup rewards are
    # bounded [0, 1] per step so any return is finite and >= 0
    assert out["eval"] is not None and out["eval"]["episodes"] > 0
    assert 0.0 <= out["eval"]["mean_return"] <= 1000.0


def test_dpg_humanoid_stand_smoke():
    """The flagship-class domain (humanoid, 67-d obs / 21-d action)
    builds, steps, and takes finite-loss grad steps through the fused
    DPG learner — the 'humanoid-class control' claim is exercised, not
    asserted (round-3 verdict next-round #1)."""
    _require_dm_control()
    from ape_x_dqn_tpu.envs import make_env

    cfg = _dpg_cfg().replace(
        env=EnvConfig(id="humanoid_stand", kind="control"))
    env = make_env(cfg.env, seed=0)
    assert env.spec.obs_shape == (67,) and env.spec.action_dim == 21
    obs = env.reset()
    rng = np.random.default_rng(0)

    actor = DPGActor(action_dim=21, action_low=-1, action_high=1,
                     hidden=(64, 64))
    critic = DPGCritic(hidden=(64, 64))
    obs0 = jnp.zeros((1, 67), jnp.float32)
    a0 = jnp.zeros((1, 21), jnp.float32)
    learner = DPGLearner(actor.apply, critic.apply,
                         PrioritizedReplay(capacity=1024),
                         LearnerConfig(batch_size=32, n_step=5,
                                       critic_lr=1e-3, policy_lr=1e-4,
                                       tau=0.05))
    state = learner.init(actor.init(jax.random.key(0), obs0),
                         critic.init(jax.random.key(1), obs0, a0),
                         learner.replay.init(
                             continuous_item_spec((67,), np.float32, 21)),
                         jax.random.key(2))
    # real transitions from the real physics
    obs_l, act_l, rew_l, nxt_l = [], [], [], []
    for _ in range(128):
        a = rng.uniform(-1, 1, 21).astype(np.float32)
        nxt, r, done, info = env.step(a)
        obs_l.append(obs); act_l.append(a); rew_l.append(r); nxt_l.append(nxt)
        obs = env.reset() if done else nxt
    items = {
        "obs": jnp.asarray(np.stack(obs_l), jnp.float32),
        "action": jnp.asarray(np.stack(act_l), jnp.float32),
        "reward": jnp.asarray(np.asarray(rew_l), jnp.float32),
        "next_obs": jnp.asarray(np.stack(nxt_l), jnp.float32),
        "discount": jnp.full((128,), 0.99, jnp.float32),
    }
    state = learner.add(state, items, jnp.ones(128))
    state, m = learner.train_many(state, 5)
    assert int(state.step) == 5
    assert np.isfinite(m["loss"]) and np.isfinite(m["policy_loss"])


@pytest.mark.slow
def test_dpg_improves_real_pendulum():
    """Rising return on REAL dm_control pendulum swingup through the
    full driver: the trained deterministic policy must clearly beat
    the random-policy floor (swingup returns ~0-80 random; a learning
    policy passes several hundred within ~60k frames)."""
    _require_dm_control()
    cfg = _dpg_cfg(num_actors=2).replace(
        env=EnvConfig(id="pendulum_swingup", kind="control"),
        total_env_frames=60_000)
    driver = ApexDriver(cfg)
    out = driver.run(max_grad_steps=10**9, wall_clock_limit_s=600)
    assert out["actor_errors"] == [] and out["loop_errors"] == []
    assert out["eval"] is not None
    assert out["eval"]["mean_return"] > 200, out["eval"]


@pytest.mark.slow
def test_dpg_improves_pendulum():
    """Rising return on pendulum swing-up: the trained deterministic
    policy must clearly beat the random-policy plateau (~ -1400).
    Measured dynamics: greedy eval reaches ~ -43 after ~45k frames /
    4 wall-clock minutes on the CPU test harness."""
    cfg = _dpg_cfg(num_actors=2).replace(total_env_frames=60_000)
    driver = ApexDriver(cfg)
    out = driver.run(max_grad_steps=10**9, wall_clock_limit_s=600)
    assert out["actor_errors"] == [] and out["loop_errors"] == []
    assert out["eval"] is not None
    assert out["eval"]["mean_return"] > -400, out["eval"]


@pytest.mark.slow
def test_dpg_improves_real_walker_stand():
    """Rising return on REAL dm_control walker stand through the full
    driver — the second real-physics domain (round-5 verdict item 7;
    pendulum swingup is the first). Random-policy floor ~25-45; the
    round-5 measured run reached final greedy eval 124.1 (3 episodes,
    105-147) in ~24 min on this 1-core host, so the bar is set with
    headroom below that but well clear of random."""
    _require_dm_control()
    cfg = _dpg_cfg(num_actors=2).replace(
        env=EnvConfig(id="walker_stand", kind="control"),
        total_env_frames=120_000)
    driver = ApexDriver(cfg)
    out = driver.run(max_grad_steps=10**9, wall_clock_limit_s=900)
    assert out["actor_errors"] == [] and out["loop_errors"] == []
    assert out["eval"] is not None
    assert out["eval"]["mean_return"] > 90, out["eval"]
