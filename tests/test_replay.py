import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.ops import sum_tree
from ape_x_dqn_tpu.replay.prioritized import (
    PrioritizedReplay, UniformReplayDevice)
from ape_x_dqn_tpu.replay.sequence import (
    SequenceBuilder, sequence_item_spec, stack_items)


# ---------------------------------------------------------------------------
# sum-tree


def test_sum_tree_invariant_root_equals_leaf_sum():
    tree = sum_tree.init(64)
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, 64, size=40), jnp.int32)
    pri = jnp.asarray(rng.uniform(0.1, 5.0, size=40), jnp.float32)
    tree = sum_tree.update(tree, idx, pri)
    leaves = sum_tree.leaves(tree)
    np.testing.assert_allclose(sum_tree.total(tree), leaves.sum(), rtol=1e-5)
    # every internal node equals the sum of its children
    t = np.asarray(tree)
    for node in range(1, 64):
        np.testing.assert_allclose(t[node], t[2 * node] + t[2 * node + 1],
                                   rtol=1e-5, atol=1e-6)


def test_sum_tree_duplicate_indices_in_batch():
    """Duplicate leaf updates in one batch must not corrupt ancestors
    (recompute-based update: last write wins, sums stay exact)."""
    tree = sum_tree.init(8)
    idx = jnp.array([3, 3, 5], jnp.int32)
    pri = jnp.array([1.0, 2.0, 4.0])
    tree = sum_tree.update(tree, idx, pri)
    leaves = np.asarray(sum_tree.leaves(tree))
    assert leaves[3] == 2.0 and leaves[5] == 4.0  # last write wins
    np.testing.assert_allclose(sum_tree.total(tree), 6.0)


def test_sum_tree_sampling_proportional():
    """Chi-squared check: sampling frequency tracks priority mass
    (SURVEY.md §4 'sampling proportional to priority')."""
    cap = 16
    tree = sum_tree.init(cap)
    pri = jnp.asarray(np.arange(1, cap + 1), jnp.float32)  # p_i = i+1
    tree = sum_tree.update(tree, jnp.arange(cap, dtype=jnp.int32), pri)
    n_draws, batch = 200, 256
    counts = np.zeros(cap)
    for d in range(n_draws):
        leaf, probs = sum_tree.sample_jit(tree, jax.random.key(d), batch)
        counts += np.bincount(np.asarray(leaf), minlength=cap)
    total_draws = n_draws * batch
    expected = np.asarray(pri) / float(np.asarray(pri).sum()) * total_draws
    chi2 = ((counts - expected) ** 2 / expected).sum()
    # df = 15; p=0.001 critical value ~ 37.7. Allow generous headroom.
    assert chi2 < 60.0, (chi2, counts, expected)


def test_sum_tree_sample_returns_probs():
    tree = sum_tree.init(4)
    tree = sum_tree.update(tree, jnp.array([0, 1], jnp.int32),
                           jnp.array([1.0, 3.0]))
    leaf, probs = sum_tree.sample(tree, jax.random.key(0), 128)
    assert set(np.asarray(leaf).tolist()) <= {0, 1}  # zero-mass never drawn
    mask0 = np.asarray(leaf) == 0
    np.testing.assert_allclose(np.asarray(probs)[mask0], 0.25, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(probs)[~mask0], 0.75, rtol=1e-5)


def test_sum_tree_bad_capacity():
    with pytest.raises(AssertionError):
        sum_tree.init(48)


# ---------------------------------------------------------------------------
# prioritized replay


def _spec():
    return {"obs": jax.ShapeDtypeStruct((3,), jnp.float32),
            "act": jax.ShapeDtypeStruct((), jnp.int32)}


def _items(start: int, b: int):
    return {"obs": jnp.arange(start, start + b, dtype=jnp.float32
                              )[:, None].repeat(3, 1),
            "act": jnp.arange(start, start + b, dtype=jnp.int32)}


def test_replay_add_sample_roundtrip():
    rp = PrioritizedReplay(capacity=16, alpha=1.0, beta=0.5)
    state = rp.init(_spec())
    state = rp.add(state, _items(0, 4), jnp.array([1.0, 1.0, 1.0, 1.0]))
    assert int(state.size) == 4 and int(state.pos) == 4
    items, idx, w = rp.sample(state, jax.random.key(0), 32)
    # only filled slots are ever sampled (empty leaves have zero mass)
    assert np.asarray(idx).max() < 4
    # sampled item contents match what was stored at that index
    np.testing.assert_allclose(np.asarray(items["act"]), np.asarray(idx))
    assert w.shape == (32,) and float(w.max()) == 1.0


def test_replay_fifo_overwrite():
    rp = PrioritizedReplay(capacity=4, alpha=1.0)
    state = rp.init(_spec())
    state = rp.add(state, _items(0, 4), jnp.ones(4))
    state = rp.add(state, _items(100, 2), jnp.ones(2))  # wraps: slots 0,1
    assert int(state.size) == 4 and int(state.pos) == 2
    acts = np.asarray(state.storage["act"])
    np.testing.assert_array_equal(acts, [100, 101, 2, 3])


def test_replay_priority_update_shifts_sampling():
    rp = PrioritizedReplay(capacity=8, alpha=1.0, eps=0.0)
    state = rp.init(_spec())
    state = rp.add(state, _items(0, 8), jnp.ones(8))
    state = rp.update_priorities(
        state, jnp.arange(8, dtype=jnp.int32),
        jnp.array([0.0, 0.0, 0.0, 100.0, 0.0, 0.0, 0.0, 0.0]))
    items, idx, w = rp.sample(state, jax.random.key(1), 64)
    assert (np.asarray(idx) == 3).all()  # all mass on slot 3


def test_replay_is_weights_formula():
    rp = PrioritizedReplay(capacity=4, alpha=1.0, beta=1.0, eps=0.0)
    state = rp.init(_spec())
    state = rp.add(state, _items(0, 4), jnp.array([1.0, 1.0, 1.0, 5.0]))
    items, idx, w = rp.sample(state, jax.random.key(2), 256)
    # P = [1/8,1/8,1/8,5/8], N=4 -> w_raw = 1/(N*P) = [2,2,2,0.4]
    # normalized by batch max (2) -> [1,1,1,0.2]
    idx = np.asarray(idx)
    w = np.asarray(w)
    np.testing.assert_allclose(w[idx < 3], 1.0, rtol=1e-5)
    np.testing.assert_allclose(w[idx == 3], 0.2, rtol=1e-5)


def test_replay_add_jit_and_donation():
    rp = PrioritizedReplay(capacity=8)
    state = rp.init(_spec())
    state = rp.add_jit(state, _items(0, 2), jnp.ones(2))
    state = rp.update_priorities_jit(state, jnp.array([0], jnp.int32),
                                     jnp.array([2.0]))
    items, idx, w = rp.sample_jit(state, jax.random.key(0), 4)
    assert int(state.size) == 2


def test_uniform_replay_device():
    rp = UniformReplayDevice(capacity=8)
    state = rp.init(_spec())
    state = rp.add(state, _items(0, 3))
    items, idx, w = rp.sample(state, jax.random.key(0), 16)
    assert np.asarray(idx).max() < 3
    np.testing.assert_allclose(np.asarray(w), 1.0)


# ---------------------------------------------------------------------------
# sequence replay


def test_sequence_builder_overlap():
    sb = SequenceBuilder(seq_len=4, overlap=2, lstm_size=2)
    state = (np.zeros(2), np.zeros(2))
    out = []
    for t in range(8):
        pre = (np.full(2, float(t)), np.full(2, float(t)))
        out += sb.append(np.array([t]), t, float(t), False, pre)
    # emits at t=3 (steps 0-3), t=5 (steps 2-5), t=7 (steps 4-7): overlap 2
    assert len(out) == 3
    np.testing.assert_array_equal(out[0]["actions"], [0, 1, 2, 3])
    np.testing.assert_array_equal(out[1]["actions"], [2, 3, 4, 5])
    np.testing.assert_array_equal(out[2]["actions"], [4, 5, 6, 7])
    # stored init state is the pre-state of the first step of each seq
    np.testing.assert_allclose(out[0]["init_c"], 0.0)
    np.testing.assert_allclose(out[1]["init_c"], 2.0)
    np.testing.assert_allclose(out[0]["mask"], 1.0)


def test_sequence_builder_terminal_pads():
    sb = SequenceBuilder(seq_len=4, overlap=0, lstm_size=2)
    pre = (np.zeros(2), np.zeros(2))
    out = []
    out += sb.append(np.array([0]), 0, 1.0, False, pre)
    out += sb.append(np.array([1]), 1, 1.0, True, pre)  # terminal early
    assert len(out) == 1
    np.testing.assert_array_equal(out[0]["mask"], [1, 1, 0, 0])
    np.testing.assert_array_equal(out[0]["terminals"], [0, 1, 0, 0])
    assert sb._steps == []


def test_sequence_builder_no_duplicate_tail_flush():
    """Terminal exactly at a sequence boundary must not re-emit the
    retained overlap as a bogus padded sequence."""
    sb = SequenceBuilder(seq_len=4, overlap=2, lstm_size=2)
    pre = (np.zeros(2), np.zeros(2))
    out = []
    for t in range(4):
        out += sb.append(np.array([t]), t, 0.0, t == 3, pre)
    assert len(out) == 1  # the full sequence only, no overlap-only flush


def test_sequence_items_roundtrip_device():
    sb = SequenceBuilder(seq_len=4, overlap=0, lstm_size=3)
    pre = (np.ones(3), np.ones(3))
    items = []
    for t in range(8):
        items += sb.append(np.full((2,), t, np.uint8), t, 1.0, False, pre)
    assert len(items) == 2
    spec = sequence_item_spec((2,), np.uint8, 4, 3)
    rp = PrioritizedReplay(capacity=8)
    state = rp.init(spec)
    batch = {k: jnp.asarray(v) for k, v in stack_items(items).items()}
    state = rp.add(state, batch, jnp.ones(2))
    got, idx, w = rp.sample(state, jax.random.key(0), 4)
    assert got["obs"].shape == (4, 4, 2) and got["obs"].dtype == jnp.uint8
    assert got["init_c"].shape == (4, 3)


def test_sum_tree_sample_clamps_to_filled_region():
    """Descent must never land on zero-priority/unfilled leaves: float32
    rounding can push it one leaf past the live mass."""
    tree = sum_tree.init(8)
    tree = sum_tree.update(tree, jnp.arange(3, dtype=jnp.int32),
                           jnp.array([1.0, 1.0, 1.0]))
    leaf, probs = sum_tree.sample(tree, jax.random.key(0), 64,
                                  size=jnp.int32(3))
    assert int(leaf.max()) < 3 and int(leaf.min()) >= 0
    assert (np.asarray(probs) > 0).all()


def test_sum_tree_sample_empty_tree_guarded():
    """An all-zero tree must not return the rightmost (garbage) leaf."""
    tree = sum_tree.init(8)
    leaf, _ = sum_tree.sample(tree, jax.random.key(0), 4, size=jnp.int32(0))
    assert (np.asarray(leaf) == 0).all()


def test_replay_sample_partially_filled_never_returns_unfilled():
    rp = PrioritizedReplay(capacity=64)
    st = rp.init({"x": jax.ShapeDtypeStruct((), jnp.float32)})
    st = rp.add(st, {"x": jnp.arange(5, dtype=jnp.float32)},
                jnp.ones(5) * 0.001)  # tiny priorities stress rounding
    for seed in range(5):
        _, idx, w = rp.sample(st, jax.random.key(seed), 32)
        assert int(idx.max()) < 5
        assert (np.asarray(w) > 0).all()


# ---------------------------------------------------------------------------
# split sample/update entry points + deterministic packer construction


def test_replay_split_entry_points_delegate():
    """sample_state / update_state are the prefetch pipeline's split
    entry points: sample_state(state, ...) must equal sample(state, ...)
    bit-for-bit, and update_state must write ONLY the tree — storage,
    pos, and size unchanged — which is the commuting contract that lets
    a prefetched draw run before the previous chunk's write-back."""
    rp = PrioritizedReplay(capacity=16, alpha=1.0, beta=0.5)
    state = rp.init(_spec())
    state = rp.add(state, _items(0, 8), jnp.ones(8))

    a = rp.sample(state, jax.random.key(3), 16)
    b = rp.sample_state(state, jax.random.key(3), 16)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        a, b)

    _, idx, _ = a
    new = rp.update_state(state, idx, jnp.full(idx.shape, 0.25))
    ref = rp.update_priorities(state, idx, jnp.full(idx.shape, 0.25))
    np.testing.assert_array_equal(np.asarray(new.tree), np.asarray(ref.tree))
    # tree changed; everything else is untouched
    assert (np.asarray(new.tree) != np.asarray(state.tree)).any()
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        new.storage, state.storage)
    assert int(new.pos) == int(state.pos)
    assert int(new.size) == int(state.size)


def test_uniform_replay_split_entry_points():
    rp = UniformReplayDevice(capacity=16)
    state = rp.init(_spec())
    state = rp.add(state, _items(0, 8), jnp.ones(8))
    a = rp.sample(state, jax.random.key(1), 8)
    b = rp.sample_state(state, jax.random.key(1), 8)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        a, b)
    # uniform replay's priority write-back is a no-op either way
    new = rp.update_state(state, a[1], jnp.ones(a[1].shape))
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        new, state)


def test_frame_ring_split_entry_points():
    """FrameRingReplay inherits sample_state/update_state through its
    overridden sample_items/update_priorities (dead-slot guard
    included), so the prefetch pipeline works unchanged on pixel
    frame-ring storage."""
    from ape_x_dqn_tpu.replay.frame_ring import (FrameRingReplay,
                                                 FrameSegmentBuilder)

    rp = FrameRingReplay(capacity=16, seg_transitions=4, n_step=1,
                         obs_shape=(6, 6, 2))
    state = rp.init()
    builder = FrameSegmentBuilder(4, 1, 2)
    builder.on_reset(np.zeros((6, 6, 2), np.uint8))  # stacked obs
    for t in range(8):
        builder.on_step(np.full((6, 6, 2), t + 1, np.uint8))
        builder.add(0, 0.0, 0.99, 1, priority=1.0 + t)
    for seg in builder.flush():
        items = {k: jnp.asarray(seg[k]) for k in
                 ("seg_frames", "action", "reward", "discount",
                  "next_off")}
        state = rp.add(state, items, jnp.asarray(seg["priorities"]))
    a = rp.sample(state, jax.random.key(2), 8)
    b = rp.sample_state(state, jax.random.key(2), 8)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        a, b)
    new = rp.update_state(state, a[1], jnp.full(a[1].shape, 0.5))
    ref = rp.update_priorities(state, a[1], jnp.full(a[1].shape, 0.5))
    np.testing.assert_array_equal(np.asarray(new.tree),
                                  np.asarray(ref.tree))


def test_replay_constructor_item_spec():
    """Deterministic packer construction: a replay built with item_spec
    in the constructor needs no spec at init() time, and an init() with
    no spec anywhere raises a loud ValueError instead of failing later
    inside the packer (the old hidden init() side effect)."""
    rp = PrioritizedReplay(capacity=16, item_spec=_spec())
    state = rp.init()  # no spec argument needed
    state = rp.add(state, _items(0, 4), jnp.ones(4))
    items, idx, _ = rp.sample(state, jax.random.key(0), 8)
    np.testing.assert_allclose(np.asarray(items["act"]), np.asarray(idx))

    with pytest.raises(ValueError, match="item spec"):
        PrioritizedReplay(capacity=16).init()
    with pytest.raises(ValueError, match="item spec"):
        UniformReplayDevice(capacity=16).init()

    # and the constructor spec matches the init(spec) layout exactly
    s2 = PrioritizedReplay(capacity=16).init(_spec())
    jax.tree.map(
        lambda x, y: (x.shape, x.dtype) == (y.shape, y.dtype) or
        pytest.fail("layout mismatch"),
        state.storage, s2.storage)
