"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; sharding/collective code is
exercised on XLA's host-platform device emulation (SURVEY.md §4
"distributed-without-a-cluster"). Env vars must be set before jax imports.
"""

import faulthandler
import os

# the full one-command suite has a known native-side SIGSEGV near the
# end of collection-order runs (ROADMAP.md "Tier-1 invocation"); dump
# Python tracebacks on fatal signals so the crashing test is
# attributable instead of a bare exit code 139
faulthandler.enable()

os.environ["JAX_PLATFORMS"] = "cpu"

# every runtime lock is built via obs.health.make_lock; under this
# flag they become witness locks that record the lock-acquisition
# graph and raise LockOrderError the moment any test's code path
# acquires two locks in an order that closes a cycle — a deadlock
# that would otherwise need a precise interleave to reproduce
os.environ.setdefault("APEX_LOCK_WITNESS", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# This image's sitecustomize imports jax at interpreter startup (to register
# the TPU plugin), so the env var alone is too late — override the platform
# through jax.config before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# compile-telemetry hook (obs/profiling.py): when run_chunked.sh
# exports APEX_COMPILE_LOG, each pytest process appends one JSON line
# {argv, jit_compiles, jit_compile_ms} at exit — the per-file
# compile-cache growth record that turns the chunking workaround's
# SIGSEGV regime into a monitored quantity
if os.environ.get("APEX_COMPILE_LOG"):
    from ape_x_dqn_tpu.obs.profiling import install_compile_log

    install_compile_log(os.environ["APEX_COMPILE_LOG"])


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Module-boundary jax.clear_caches() — the same fix
    runtime/suite.py:train_one_game applies between games. The full
    one-command suite accumulates compiled executables across ~200
    tests and reproducibly dies in native XLA teardown near the end of
    collection-order runs (ROADMAP.md 'Tier-1 invocation'); dropping
    the compilation caches at each test module's end keeps the
    native-side footprint bounded without perturbing any single
    module's warm-jit behavior."""
    yield
    import gc

    gc.collect()
    jax.clear_caches()


def pytest_addoption(parser):
    parser.addoption("--run-slow", action="store_true", default=False,
                     help="run slow integration tests (full CartPole solve)")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--run-slow"):
        return
    skip = pytest.mark.skip(reason="needs --run-slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
