"""Eval worker + HNS suite harness (SURVEY.md §2.2 'Eval worker';
BASELINE.json metric: Atari-57 median human-normalized score)."""

import numpy as np

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, ReplayConfig,
    get_config)
from ape_x_dqn_tpu.runtime.driver import ApexDriver
from ape_x_dqn_tpu.runtime.evaluation import (
    ATARI57_GAMES, EvalWorker, evaluate_suite)
from ape_x_dqn_tpu.utils.metrics import median_hns


def test_eval_worker_cartpole_greedy_episode():
    cfg = get_config("cartpole_smoke")

    def query_fn(obs):
        # push-left policy: obs[2] is pole angle; fall fast but legally
        return np.array([1.0, 0.0], np.float32)

    worker = EvalWorker(cfg, query_fn)
    res = worker.run(episodes=3, max_frames=600)
    assert res["episodes"] == 3
    assert 1.0 <= res["mean_return"] <= 500.0
    assert res["min_return"] <= res["median_return"] <= res["max_return"]


def test_eval_worker_atari_uses_unclipped_returns():
    """Eval env must disable reward clipping and episodic-life: returns
    are raw game scores, possibly outside [-1, 1] per step."""
    cfg = get_config("pong").replace(
        env=EnvConfig(id="pong", kind="synthetic_atari"))

    def query_fn(obs):
        return np.zeros(6, np.float32)  # NOOP policy

    worker = EvalWorker(cfg, query_fn)
    assert worker.env._clip is False
    assert worker.env._episodic_life is False
    ret = worker.run_episode(max_frames=2000)
    assert np.isfinite(ret)


def test_evaluate_suite_median_hns():
    cfg = get_config("pong").replace(
        env=EnvConfig(id="pong", kind="synthetic_atari"),
        eval_episodes=1)

    def query_fn(obs):
        return np.zeros(6, np.float32)

    out = evaluate_suite(cfg, query_fn, games=("pong", "breakout"),
                         episodes_per_game=1, max_frames=500)
    assert set(out["scores"]) == {"pong", "breakout"}
    assert set(out["hns"]) == {"pong", "breakout"}
    expect = median_hns({g: out["scores"][g] for g in out["scores"]})
    assert abs(out["median_hns_synthetic"] - expect) < 1e-9


def test_synthetic_suite_never_emits_unmarked_north_star():
    """Round-2 verdict weak #2: in an image without ale_py, every game
    silently runs the synthetic stand-in — the result must mark every
    game's backend and must NOT carry the north-star 'median_hns' key
    (it appears only when the real ALE produced it)."""
    cfg = get_config("pong").replace(
        env=EnvConfig(id="pong", kind="atari"),  # asks for REAL atari
        eval_episodes=1)

    def query_fn(obs):
        return np.zeros(6, np.float32)

    out = evaluate_suite(cfg, query_fn, games=("pong",),
                         episodes_per_game=1, max_frames=300)
    assert out["backends"] == {"pong": "synthetic"}
    assert "median_hns" not in out
    assert "median_hns_synthetic" in out


def test_suite_eval_rejects_games_for_non_atari_config():
    """--games on a non-Atari config would build per-game Atari envs
    against a network sized for the config's own env; it must fail with
    a clear error instead (round-2 advisor finding)."""
    import pytest

    from ape_x_dqn_tpu.runtime.evaluation import run_suite_eval

    cfg = get_config("cartpole_smoke")
    with pytest.raises(ValueError, match="only valid for Atari"):
        run_suite_eval(cfg, games=("pong",))


def test_atari57_suite_is_57_games():
    assert len(ATARI57_GAMES) == 57


def test_driver_emits_eval_metrics():
    cfg = get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=2, base_eps=0.6, ingest_batch=16),
        replay=ReplayConfig(kind="prioritized", capacity=2048, min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        eval_every_steps=20, eval_episodes=2)
    driver = ApexDriver(cfg)
    out = driver.run(total_env_frames=1500, max_grad_steps=60,
                     wall_clock_limit_s=120)
    assert out["actor_errors"] == [] and out["loop_errors"] == [], out
    assert out["eval"] is not None, "eval never ran"
    # a shutdown can cancel eval mid-run; at least one episode completed
    assert 1 <= out["eval"]["episodes"] <= 2
    latest = driver.metrics.latest()
    assert "avg_eval_return" in latest
    # back-pressure accounting rides the periodic eval records (the
    # end-of-run fallback eval doesn't log them, so only assert when
    # the periodic loop produced this record)
    if "eval_wall_s" in latest:
        assert latest["eval_wall_s"] > 0
        assert latest["server_queue_depth_max"] >= 0


def test_run_eval_measured_samples_depth_during_eval():
    """The logged back-pressure must be the max queue depth WHILE the
    eval runs — the post-eval snapshot always reads ~0 because actors
    drain the queue the moment the eval stops querying (round-3
    advisor finding)."""
    import time

    from ape_x_dqn_tpu.runtime.evaluation import run_eval_measured

    class FakeServer:
        def __init__(self):
            self.queue_depth = 0

    class FakeWorker:
        def __init__(self, server):
            self.server = server

        def run(self, episodes, max_frames=108_000, stop_event=None,
                deadline_s=None):
            self.server.queue_depth = 7  # pressure while eval runs
            time.sleep(0.3)
            self.server.queue_depth = 0  # drained the instant it ends
            return {"episodes": episodes, "mean_return": 1.0}

    srv = FakeServer()
    res, depth_max = run_eval_measured(FakeWorker(srv), 1, srv)
    assert res["episodes"] == 1
    assert depth_max == 7  # the during-eval max, not the post-eval 0


def test_rolling_suite_score_backend_marking():
    """The rotation's rolling median must carry the same backend
    honesty as evaluate_suite: synthetic backends only ever emit the
    rolling_..._synthetic key, and the median tracks the games seen so
    far (round-3 verdict weak #7)."""
    from ape_x_dqn_tpu.runtime.evaluation import RollingSuiteScore

    cfg = get_config("atari57_apex").replace(
        env=EnvConfig(id="atari57", kind="synthetic_atari"))
    roll = RollingSuiteScore(cfg)
    out = roll.update("pong", 21.0)
    assert out["eval_games_seen"] == 1
    assert "rolling_median_hns_synthetic" in out
    assert "rolling_median_hns" not in out
    out = roll.update("breakout", 30.0)
    assert out["eval_games_seen"] == 2
    # a re-eval of the same game replaces, not appends
    out = roll.update("pong", -21.0)
    assert out["eval_games_seen"] == 2
    assert roll.scores["pong"] == -21.0


def test_eval_max_frames_caps_episode_length():
    """cfg.eval_max_frames bounds each eval episode: a policy that
    never terminates must return after exactly that many frames (an
    uncapped 108k-frame episode left slow-link hosts unable to finish
    a single eval — PERF.md 'Live multi-game')."""
    cfg = get_config("pong").replace(
        env=EnvConfig(id="pong", kind="synthetic_atari"),
        eval_max_frames=50)

    steps = {"n": 0}

    def query_fn(obs):
        steps["n"] += 1
        return np.zeros(6, np.float32)

    worker = EvalWorker(cfg, query_fn)
    res = worker.run(1, max_frames=cfg.eval_max_frames)
    assert res is not None and res["episodes"] == 1
    assert steps["n"] <= cfg.eval_max_frames


def test_eval_max_frames_counts_raw_frames():
    """eval_max_frames is specified in RAW env frames; a frame-skipped
    env consumes frame_skip raw frames per agent step, so the episode
    loop must run max_frames/frame_skip steps — counting agent steps
    against the raw budget made the cap 4x looser than documented and
    blew the final-eval deadline on slow-link hosts (round 5)."""
    from ape_x_dqn_tpu.configs import EnvConfig, get_config
    from ape_x_dqn_tpu.runtime.evaluation import EvalWorker

    cfg = get_config("pong").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari"))
    steps = {"n": 0}

    def query(obs):
        steps["n"] += 1
        return np.zeros(6, np.float32)

    worker = EvalWorker(cfg, query)
    # budgets chosen so the CAP ends the episode, not `done` (a catch
    # episode under this policy runs ~110 agent steps naturally — a
    # generous budget would pass even with the bug reverted):
    # 80 raw frames / frame_skip 4 = exactly 20 agent steps
    worker.run_episode(max_frames=80)
    assert steps["n"] == 20, steps["n"]

    # unskipped kinds count 1:1 (cartpole runs ~10 steps naturally;
    # a 5-frame budget must stop it at exactly 5)
    cfg2 = get_config("cartpole_smoke")
    steps["n"] = 0

    def query2(obs):
        steps["n"] += 1
        return np.zeros(2, np.float32)

    w2 = EvalWorker(cfg2, query2)
    w2.run_episode(max_frames=5)
    assert steps["n"] == 5, steps["n"]
