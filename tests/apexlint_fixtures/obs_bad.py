"""obs-names fixture: one emission with no table row and no waiver."""


def publish(obs, value):
    obs.observe("listed_hist", value)
    obs.count("rogue_counter")  # the finding: not in INSTRUMENTS
