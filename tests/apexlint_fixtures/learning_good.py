"""obs-names fixture: the learning-health-plane emission shape.

Mirrors obs/learning.py's publish_learn literal gauge sites plus the
facade's learn_loss histogram and the monitor's degradation counter:
every emission carries a row in the learning report fixture with the
kind the registry publishes it under. The per-tenant duplicates ride
dynamic f-string keys and are invisible to the checker by design (same
policy as the fleet plane's peer/ keys).
"""


def publish_learn(obs, vals, tenant=""):
    g = vals.get
    obs.gauge("learn_td_abs_p50", g("td_abs_p50", 0.0))
    obs.gauge("learn_td_abs_p90", g("td_abs_p90", 0.0))
    obs.gauge("learn_td_abs_p99", g("td_abs_p99", 0.0))
    obs.gauge("learn_td_signed_mean", g("td_signed_mean", 0.0))
    obs.gauge("learn_q_mean", g("q_mean", 0.0))
    obs.gauge("learn_q_max", g("q_max", 0.0))
    obs.gauge("learn_target_q_mean", g("target_q_mean", 0.0))
    obs.gauge("learn_q_gap", g("q_gap", 0.0))
    obs.gauge("learn_grad_norm", g("grad_norm", 0.0))
    obs.gauge("learn_update_ratio", g("update_ratio", 0.0))
    obs.gauge("learn_is_ess_frac", g("is_ess_frac", 1.0))
    obs.gauge("learn_priority_top_frac", g("priority_top_frac", 0.0))
    obs.gauge("learn_sample_age_p50", g("sample_age_p50", 0.0))
    obs.gauge("learn_sample_age_p90", g("sample_age_p90", 0.0))
    obs.gauge("learn_prio_staleness_frac", g("prio_staleness_frac", 0.0))
    if "shard_td_mean_min" in vals:
        obs.gauge("learn_shard_td_mean_min", vals["shard_td_mean_min"])
        obs.gauge("learn_shard_td_mean_max", vals["shard_td_mean_max"])
    if tenant:
        for k, v in vals.items():
            obs.gauge(f"learn/{tenant}/{k}", v)


def observe_loss(obs, loss):
    obs.observe("learn_loss", loss)


def fire_degradation(obs):
    obs.count("learning_degradations")
