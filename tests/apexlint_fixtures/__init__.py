"""Deliberately good/bad snippets exercising each apexlint checker.

These are parsed by the checkers, never imported or executed — the
`bad_*` modules contain real concurrency/jit bugs on purpose.
"""
