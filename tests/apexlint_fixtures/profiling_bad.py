"""obs-names fixture: the two ways a perf-plane PR drifts.

`mfu_learn_k` is emitted as a counter while the table lists a gauge
(the report would look under ctr/ and never print it); `mfu_scratch`
has no row at all (the report silently drops a new signal).
"""


def publish_stage(obs, mfu):
    obs.count("mfu_learn_k", mfu)  # kind mismatch: table says gauge
    obs.gauge("mfu_scratch", mfu)  # no INSTRUMENTS row, no waiver
