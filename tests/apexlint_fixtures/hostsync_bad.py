# apexlint-scope: hot-path
"""Hidden-host-sync BAD fixture.

Opted into hot scope via the marker above (fixture files are not in
HOT_BASENAMES). The unconditional float() on a jit output blocks the
dispatch queue every iteration — exactly one finding.
"""


def learn_loop(learner, state, steps):
    for _ in range(steps):
        state, m = learner.train_step(state)
        loss = float(m["loss"])
    return state, loss
