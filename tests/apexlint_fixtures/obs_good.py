"""obs-names fixture: every emission has a table row (or a waiver)."""


def publish(obs, value):
    obs.observe("listed_hist", value)
    obs.gauge("listed_gauge", value)
    obs.gauge("scratch_gauge", value)  # apexlint: unlisted(fixture: debug-only)
