"""obs-names fixture: mini INSTRUMENTS table for the dp-scaling plane.

Rows match multichip_good.py's emissions; `dp_scaling_efficiency` is
listed as a gauge so multichip_bad.py's counter emission is a
kind-mismatch finding.
"""

INSTRUMENTS = {
    "dp_scaling_efficiency": {"kind": "gauge"},
    "replay_shard_fill_min": {"kind": "gauge"},
    "replay_shard_fill_max": {"kind": "gauge"},
    "mfu_train_dist": {"kind": "gauge"},
    "hbm_bw_frac_train_dist": {"kind": "gauge"},
    "device_ms_train_dist": {"kind": "gauge"},
}
