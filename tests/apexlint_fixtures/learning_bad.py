"""obs-names fixture: the two ways a learning-plane PR drifts.

`learn_grad_norm` is emitted as a counter while the table lists a
gauge (the report would look under ctr/ and never print it);
`learn_scratch_frac` has no row at all (the report silently drops a
new diagnostic).
"""


def publish_learn(obs, vals):
    obs.count("learn_grad_norm", vals["grad_norm"])  # kind mismatch
    obs.gauge("learn_scratch_frac", 0.0)  # no INSTRUMENTS row, no waiver
