"""wire-protocol fixture: the server grew a MSG_PARAMS_PUSH plane but
the client dispatch chain never references it — the half-wired shape
the checker exists to catch."""

MSG_HELLO = 1
MSG_EXPERIENCE = 2
MSG_PARAMS = 3
MSG_PARAMS_PUSH = 8


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_HELLO:
            return MSG_PARAMS
        if mtype == MSG_EXPERIENCE:
            return payload
        return None

    def push_loop(self, subs, blob):
        for sock in subs:
            sock.send((MSG_PARAMS_PUSH, blob))


class Client:
    def run(self, sock):
        sock.send(MSG_HELLO)
        if sock.recv() != MSG_PARAMS:
            return False
        sock.send(MSG_EXPERIENCE)
        return True
