"""obs-names fixture: mini INSTRUMENTS table for the serving tier.

Rows match serve_good.py's emissions; `serve_queue_items` is listed
as a gauge so serve_bad.py's counter emission is a kind-mismatch
finding.
"""

INSTRUMENTS = {
    "serve_offered": {"kind": "ctr"},
    "serve_admitted": {"kind": "ctr"},
    "serve_shed": {"kind": "ctr"},
    "serve_expired": {"kind": "ctr"},
    "serve_tenants": {"kind": "gauge"},
    "serve_backpressure": {"kind": "gauge"},
    "serve_queue_items": {"kind": "gauge"},
    "infer_latency_ms": {"kind": "hist"},
}
