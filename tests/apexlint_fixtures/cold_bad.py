"""obs-names fixture: the two ways a cold-tier PR drifts.

`cold_compression_ratio` is emitted as a counter while the table lists
a gauge (the never-inflate value_min row would look under ctr/ and
never fire); `cold_recall_lag_s` has no row at all (a new recall-path
signal the report would silently drop).
"""


def publish_cold(obs, ratio, lag_s):
    obs.count("cold_compression_ratio", ratio)  # kind mismatch
    obs.gauge("cold_recall_lag_s", lag_s)  # no row, no waiver
