"""Fixture: actuator call sites with proper remediation accounting.

Every pattern here must produce ZERO remediation-accounting findings:
a counted call, a failure-path counter, and a waived delegation."""


class Engine:
    def __init__(self, obs, actuators):
        self._obs = obs
        self._act = actuators

    def apply_restart(self, slot, staleness_s):
        # the canonical shape: actuator call + counter in one scope
        try:
            out = self._act.restart_actor(slot, staleness_s)
        except Exception:  # noqa: BLE001
            self._obs.count("remediation_failed")
            return "failed"
        self._obs.count("remediation_actions")
        return "applied" if out is not False else "skipped"

    def nudge_latch(self, serving):
        # accounting lives one level up in the engine's dispatch
        return serving.force_backpressure(True)  # apexlint: unaccounted(counted centrally in Engine.apply_restart)


def watchdog(transport, obs):
    released = transport.set_backpressure(False)
    if released:
        obs.count("remediation_actions")
    return released
