"""Fixture: one actuator call with no counter bump and no waiver —
exactly ONE remediation-accounting finding (the quarantine call; the
counted restart above it must not mask the scope)."""


class Engine:
    def __init__(self, obs, actuators):
        self._obs = obs
        self._act = actuators

    def apply_restart(self, slot):
        out = self._act.restart_actor(slot, 0.0)
        self._obs.count("remediation_actions")
        return out

    def apply_quarantine(self, peer):
        # invisible action: no remediation_* counter in this scope
        return self._act.quarantine_peer(peer, 0.0)
