"""wire-protocol fixture: MSG_PONG is half-wired — the server never
references it, with no waiver. Exactly one finding."""

MSG_DATA = 1
MSG_PING = 2
MSG_PONG = 3
MSG_ERR = 4


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_DATA:
            return payload
        if mtype == MSG_PING:
            return MSG_ERR  # replies with the wrong type: PONG unwired
        return None


class Client:
    def roundtrip(self, sock):
        sock.send(MSG_PING)
        kind = sock.recv()
        if kind == MSG_PONG:
            return True
        if kind == MSG_DATA:
            return False
        if kind == MSG_ERR:
            raise RuntimeError("peer error")
        return None
