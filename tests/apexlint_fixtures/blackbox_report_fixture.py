"""obs-names fixture: mini INSTRUMENTS table for the forensics plane.

Rows match blackbox_good.py's emissions; `blackbox_dumps` is listed
as a ctr so blackbox_bad.py's gauge emission is a kind-mismatch
finding.
"""

INSTRUMENTS = {
    "blackbox_records": {"kind": "ctr"},
    "blackbox_dropped": {"kind": "ctr"},
    "blackbox_dumps": {"kind": "ctr"},
    "postmortem_bundles": {"kind": "ctr"},
}
