"""thread-lifecycle calibration: the fire-and-forget case.

The target consults a stop flag, but the Thread object is never
retained — nothing can ever join it. Exactly one finding, at the
construction line.
"""

import threading


class FireAndForget:
    def __init__(self):
        self._stop = threading.Event()

    def launch(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        while not self._stop.is_set():
            pass
