"""thread-lifecycle calibration: the unbounded-join case.

Retained, stoppable — but stop() joins without a timeout, so a wedged
worker wedges teardown (the PR 7 drain-hang class). Exactly one
finding, at the join line.
"""

import threading


class UnboundedJoiner:
    def __init__(self):
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            pass

    def stop(self):
        self._stop.set()
        self._t.join()
