"""Config-coverage GOOD fixture: every field read or waived."""

from dataclasses import dataclass


@dataclass
class ReplayConfig:
    capacity: int = 1 << 20
    fault_rate: float = 0.0  # apexlint: unread(reserved for the fault-injection harness; wired in its PR)
