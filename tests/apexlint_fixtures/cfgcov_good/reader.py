"""Reads capacity; fault_rate is waived as deliberately dormant."""


def make_ring(cfg):
    return [None] * cfg.capacity
