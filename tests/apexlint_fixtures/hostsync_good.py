# apexlint-scope: hot-path
"""Hidden-host-sync GOOD fixture.

Three sanctioned shapes: a sync inside an obs-gated branch (allowed
window), one explicit waived fused fetch, and host reads of the
already-fetched value (sanitized — free). Zero findings, one waiver.
"""

import jax


def learn_loop(learner, state, obs, steps):
    for _ in range(steps):
        state, m = learner.train_step(state)
        if obs.enabled:
            obs.gauge("loss", float(m["loss"]))
    return state


def drain_metrics(state):
    m = jax.device_get(state.metrics)  # apexlint: host-sync(one fused fetch at the log boundary)
    return float(m["loss"]), float(m["grad_norm"])
