"""obs-names fixture: the two ways a serving-tier PR drifts.

`serve_queue_items` is emitted as a counter while the table lists a
gauge (the report would look under ctr/ and never print the depth);
`serve_preempted` has no row at all (the report silently drops a new
admission outcome).
"""


def admit(obs, depth):
    obs.count("serve_queue_items", depth)  # kind mismatch
    obs.count("serve_preempted", 1)  # no INSTRUMENTS row, no waiver
