"""wire-protocol fixture: MSG_TELEMETRY declared and sent by the
client but never dispatched by the server, no waiver — the exact bug
class the checker exists for (a new frame type silently dropped by an
un-upgraded receiver). Exactly one finding, naming Server."""

MSG_HELLO = 1
MSG_EXPERIENCE = 2
MSG_PARAMS = 3
MSG_TELEMETRY = 7


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_HELLO:
            return MSG_PARAMS
        if mtype == MSG_EXPERIENCE:
            return payload
        return None  # telemetry frames fall through and vanish


class Client:
    def run(self, sock):
        sock.send(MSG_HELLO)
        if sock.recv() != MSG_PARAMS:
            return False
        sock.send(MSG_EXPERIENCE)
        sock.send(MSG_TELEMETRY)
        return True
