"""obs-names fixture: mini INSTRUMENTS table for the learning plane.

Rows match learning_good.py's emissions; `learn_grad_norm` is listed
as a gauge so learning_bad.py's counter emission is a kind-mismatch
finding.
"""

INSTRUMENTS = {
    "learn_td_abs_p50": {"kind": "gauge"},
    "learn_td_abs_p90": {"kind": "gauge"},
    "learn_td_abs_p99": {"kind": "gauge"},
    "learn_td_signed_mean": {"kind": "gauge"},
    "learn_q_mean": {"kind": "gauge"},
    "learn_q_max": {"kind": "gauge"},
    "learn_target_q_mean": {"kind": "gauge"},
    "learn_q_gap": {"kind": "gauge"},
    "learn_grad_norm": {"kind": "gauge"},
    "learn_update_ratio": {"kind": "gauge"},
    "learn_is_ess_frac": {"kind": "gauge"},
    "learn_priority_top_frac": {"kind": "gauge"},
    "learn_sample_age_p50": {"kind": "gauge"},
    "learn_sample_age_p90": {"kind": "gauge"},
    "learn_prio_staleness_frac": {"kind": "gauge"},
    "learn_shard_td_mean_min": {"kind": "gauge"},
    "learn_shard_td_mean_max": {"kind": "gauge"},
    "learn_loss": {"kind": "hist"},
    "learning_degradations": {"kind": "ctr"},
}
