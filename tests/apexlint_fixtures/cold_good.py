"""obs-names fixture: the cold-tier emission shape (ISSUE 11).

Mirrors runtime/driver.py's _emit_cold_gauges + the eviction/recall
counters: every cold-tier signal carries a row in the cold report
fixture under the kind the registry publishes it as.
"""


def publish_cold(obs, segments, nbytes, ratio):
    obs.gauge("cold_segments", segments)
    obs.gauge("cold_bytes", nbytes)
    obs.gauge("cold_compression_ratio", ratio)


def publish_cold_events(obs):
    obs.count("cold_evictions")
    obs.count("cold_recalls")
