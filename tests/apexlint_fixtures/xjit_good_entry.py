"""Cross-module jit-purity GOOD fixture, jit half: the same import +
call shape as the bad twin, but the reachable helper is pure."""

import jax

from xjit_good_util import residual_scale


@jax.jit
def train(x):
    return residual_scale(x, 0.5) + 1.0
