"""Config-coverage BAD fixture: dataclass with one dead knob."""

from dataclasses import dataclass


@dataclass
class ReplayConfig:
    capacity: int = 1 << 20
    dead_knob: float = 0.5
