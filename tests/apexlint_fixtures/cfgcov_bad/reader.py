"""Reads capacity but never dead_knob — the knob does nothing."""


def make_ring(cfg):
    return [None] * cfg.capacity
