"""obs-names fixture: the continuous-perf-plane emission shape.

Mirrors obs/profiling.py's literal if/elif gauge sites: every stage
gauge and compile/perf counter carries a row in the profiling report
fixture, each with the kind the registry publishes it under.
"""


def publish_stage(obs, stage, mfu, bw_frac, dev_ms):
    if stage == "sample_k":
        obs.gauge("mfu_sample_k", mfu)
        obs.gauge("hbm_bw_frac_sample_k", bw_frac)
        obs.gauge("device_ms_sample_k", dev_ms)
    elif stage == "learn_k":
        obs.gauge("mfu_learn_k", mfu)
    elif stage == "ingest":
        obs.gauge("hbm_bw_frac_ingest", bw_frac)
        obs.gauge("device_ms_ingest", dev_ms)


def publish_compile(obs, dn, ds, entries):
    if dn > 0:
        obs.count("jit_compiles", dn)
        obs.count("jit_compile_ms", ds * 1e3)
    obs.gauge("compile_cache_entries", entries)


def fire_degradation(obs):
    obs.count("perf_degradations")
