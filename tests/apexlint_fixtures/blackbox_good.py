"""obs-names fixture: the flight-recorder emission shape.

Mirrors obs/blackbox.py and obs/postmortem.py's literal emission
sites: the recorder counts every ring append and every overwrite
drop, each atomic dump, and the bundler counts every postmortem
bundle it writes — every name carries a ctr row in the blackbox
report fixture.
"""


def record(obs, dropped):
    obs.count("blackbox_records")
    if dropped:
        obs.count("blackbox_dropped")


def dump(obs):
    obs.count("blackbox_dumps")


def bundle(obs):
    obs.count("postmortem_bundles")
