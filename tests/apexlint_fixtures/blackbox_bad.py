"""obs-names fixture: the two ways a forensics PR drifts.

`blackbox_dumps` is emitted as a gauge while the table lists a ctr
(the report would look under gauge/ and never see a dump happen);
`blackbox_scratch` has no row at all (a new recorder quantity the
report silently drops).
"""


def dump(obs):
    obs.gauge("blackbox_dumps", 1.0)  # kind mismatch
    obs.count("blackbox_scratch", 1)  # no INSTRUMENTS row, no waiver
