"""wire-protocol fixture: MSG_PARAMS_PUSH wired into both chains —
the server's push loop ships it, the client's reader consumes it."""

MSG_HELLO = 1
MSG_EXPERIENCE = 2
MSG_PARAMS = 3
MSG_PARAMS_PUSH = 8


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_HELLO:
            return MSG_PARAMS
        if mtype == MSG_EXPERIENCE:
            return payload
        return None

    def push_loop(self, subs, blob):
        for sock in subs:
            sock.send((MSG_PARAMS_PUSH, blob))


class Client:
    def run(self, sock):
        sock.send(MSG_HELLO)
        if sock.recv() != MSG_PARAMS:
            return False
        sock.send(MSG_EXPERIENCE)
        return True

    def push_reader(self, sock):
        mtype, payload = sock.recv()
        if mtype == MSG_PARAMS_PUSH:
            self.on_push(payload)
