"""Learner-parity GOOD fixture.

Same two-learner shape; the asymmetry is DECLARED: BetaLearner's
class-line parity waiver names the missing endpoint (`add`), so the
drift is an audited decision, not silence. Zero findings, one waiver.
A waiver that did not mention `add` would not absorb the finding.
"""

from functools import partial

import jax


class AlphaLearner:
    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state):
        return state, {"diag": {}}

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add(self, state, items, pris):
        return state


class BetaLearner:  # apexlint: parity(no add — beta ingests through alpha's staging ring)
    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state):
        return state, {"diag": {}}
