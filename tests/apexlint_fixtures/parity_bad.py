"""Learner-parity BAD fixture.

Two leaf learners (both discovered via their donated jitted
train_step); BetaLearner silently lacks the add() endpoint the other
variant exposes, with no parity waiver declaring the asymmetry —
exactly one finding, at BetaLearner's class def line.
"""

from functools import partial

import jax


class AlphaLearner:
    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state):
        return state, {"diag": {}}

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add(self, state, items, pris):
        return state


class BetaLearner:
    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state):
        return state, {"diag": {}}
