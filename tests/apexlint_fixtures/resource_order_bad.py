"""resource-lifecycle calibration: the unlink-after-close case.

The acquire declares releases(_seg, unlink<close), but destroy()
closes first — exactly the PR 18 close-pins-mapping bug. Exactly one
finding, at the acquire line.
"""

from multiprocessing import shared_memory


class ClosesFirst:
    def __init__(self):
        # apexlint: releases(_seg, unlink<close)
        self._seg = shared_memory.SharedMemory(create=True, size=64)

    def destroy(self):
        self._seg.close()
        self._seg.unlink()
