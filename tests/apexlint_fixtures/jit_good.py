"""jit-purity fixture: pure jitted chain + host effects that are NOT
reachable from any jit boundary (and one waived trace-time effect)."""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(1,))
def step(x, n):
    return _helper(x) * n


def _helper(x):
    return jnp.tanh(x)


def host_loop(metrics, x):
    # never jitted: host effects are fine here
    t0 = time.time()
    y = np.asarray(step(x, 2))
    print("host loop", time.time() - t0)
    return y


@jax.jit
def traced_with_waiver(x):
    # deliberate trace-time effect, justified:
    print("tracing step")  # apexlint: host-effect(fixture: trace-time log)
    return x + 1


scale = jax.jit(lambda x: x * 2.0)
