"""wire-protocol fixture (shm doorbell): MSG_SHM_DOORBELL wired into
BOTH dispatch chains — the server validates and takes the slot, the
client posts the doorbell after packing the ring slot."""

MSG_EXPERIENCE = 1
MSG_HELLO = 2
MSG_SHM_DOORBELL = 3


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_HELLO:
            return {"shm": self.grant(payload)}
        if mtype == MSG_EXPERIENCE:
            return payload
        if mtype == MSG_SHM_DOORBELL:
            return self.take_slot(payload)
        return None

    def grant(self, payload):
        return payload

    def take_slot(self, payload):
        return payload


class Client:
    def send(self, sock, batch):
        sock.send(MSG_HELLO)
        post = self.ring_post(batch)
        if post is not None:
            sock.send(MSG_SHM_DOORBELL)
            return True
        sock.send(MSG_EXPERIENCE)
        return False

    def ring_post(self, batch):
        return batch
