"""obs-names fixture: the two ways a multichip PR drifts.

`dp_scaling_efficiency` is emitted as a counter while the table lists a
gauge (the report's SLO row would look under ctr/ and never fire);
`replay_shard_fill_median` has no row at all (a new per-shard signal
the report would silently drop).
"""


def publish_multichip(obs, efficiency, fill_med):
    obs.count("dp_scaling_efficiency", efficiency)  # kind mismatch
    obs.gauge("replay_shard_fill_median", fill_med)  # no row, no waiver
