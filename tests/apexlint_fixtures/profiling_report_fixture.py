"""obs-names fixture: mini INSTRUMENTS table for the perf plane.

Rows match profiling_good.py's emissions; `mfu_learn_k` is listed as a
gauge so profiling_bad.py's counter emission is a kind-mismatch
finding.
"""

INSTRUMENTS = {
    "mfu_sample_k": {"kind": "gauge"},
    "hbm_bw_frac_sample_k": {"kind": "gauge"},
    "device_ms_sample_k": {"kind": "gauge"},
    "mfu_learn_k": {"kind": "gauge"},
    "hbm_bw_frac_ingest": {"kind": "gauge"},
    "device_ms_ingest": {"kind": "gauge"},
    "jit_compiles": {"kind": "ctr"},
    "jit_compile_ms": {"kind": "ctr"},
    "compile_cache_entries": {"kind": "gauge"},
    "perf_degradations": {"kind": "ctr"},
}
