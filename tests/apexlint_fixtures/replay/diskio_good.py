"""retry-annotation fixture (replay scope, PR 16): every swallowed
disk-IO error on the spill path is observable — counted, attributed
via log.error, or explicitly waived as lossy."""

import logging

log = logging.getLogger(__name__)


class SpillStore:
    def __init__(self):
        self.io_errors = 0

    def append_counted(self, fh, payload):
        try:
            fh.write(payload)
            fh.flush()
        except OSError:
            self.io_errors += 1

    def read_attributed(self, fh, offset, length):
        try:
            fh.seek(offset)
            return fh.read(length)
        except OSError as err:
            log.error("spill read failed at %d: %s", offset, err)
            return None

    def close(self, fh):
        try:
            fh.close()
        except OSError:  # apexlint: lossy(handle close at shutdown)
            pass
