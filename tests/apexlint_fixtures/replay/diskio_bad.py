"""retry-annotation fixture (replay scope, PR 16): a swallowed
OSError on a disk-spill write path with no counter, no accounting
bump, and no waiver — a silently lost replay segment."""


class SpillStore:
    def append(self, fh, payload):
        try:
            fh.write(payload)
            fh.flush()
        except OSError:
            pass
