"""counter-closure calibration: the closure-leaking-path case.

The happy path bumps _stored, but the error path returns without a
_dropped bump — the declared law leaks on exactly that path. Exactly
one finding, at the _evicted bump line.
"""


class LeakyLedger:
    # apexlint: closure(_evicted == _stored + _dropped)
    def __init__(self):
        self._evicted = 0
        self._stored = 0
        self._dropped = 0

    def ship(self, batch):
        self._evicted += 1
        try:
            self._store(batch)
            self._stored += 1
        except OSError:
            return

    def _store(self, batch):
        raise OSError
