"""resource-lifecycle calibration: the compliant shapes.

An shm segment with a declared (and honored) unlink<close ordering, a
bounded queue drained on close, a file handle closed on close, and one
socket whose teardown is deliberately the caller's (waived).
"""

import queue
import socket
from multiprocessing import shared_memory


class GoodArea:
    def __init__(self, path):
        # apexlint: releases(_seg, unlink<close)
        self._seg = shared_memory.SharedMemory(create=True, size=64)
        self._q = queue.Queue(maxsize=8)
        self._fh = open(path, "a")

    def close(self):
        try:
            self._seg.unlink()
        finally:
            self._seg.close()
        while not self._q.empty():
            self._q.get_nowait()
        self._fh.close()


class SocketLender:
    def __init__(self, addr):
        self._sock = socket.create_connection(addr)  # apexlint: releases(caller owns teardown)
