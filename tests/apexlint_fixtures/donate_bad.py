"""Use-after-donate BAD fixture.

The driver calls the donating train_step and then returns the OLD
state object — its device buffers were deleted on dispatch, so the
read raises "Array has been deleted" on real TPUs (and silently works
on CPU test runs). Exactly one finding, at the post-call read line.
"""

from functools import partial

import jax


class Learner:
    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state):
        return state, {"loss": 0.0}


class Driver:
    def __init__(self, learner):
        self.learner = learner

    def step(self, state):
        new_state, metrics = self.learner.train_step(state)
        return state, metrics
