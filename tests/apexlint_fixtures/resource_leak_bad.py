"""resource-lifecycle calibration: the leak-by-construction case.

A file handle held on self with no teardown method anywhere on the
class. Exactly one finding, at the acquire line.
"""


class LeakyHolder:
    def __init__(self, path):
        self._fh = open(path, "a")

    def write(self, line):
        self._fh.write(line)
