"""wire-protocol fixture: both dispatch chains cover every MSG_*
(one via an explicit justified waiver)."""

MSG_DATA = 1
MSG_PING = 2
MSG_PONG = 3
MSG_LEGACY = 4

# apexlint: unhandled(MSG_LEGACY) — retired v0 frame, peers never send it


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_DATA:
            return payload
        if mtype == MSG_PING:
            return MSG_PONG
        return None


class Client:
    def roundtrip(self, sock):
        sock.send(MSG_PING)
        kind = sock.recv()
        if kind == MSG_PONG:
            return True
        if kind == MSG_DATA:
            return False
        return None
