"""guarded-by fixture: every annotated write is under its lock."""

import threading


class Good:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0  # guarded-by: _lock
        self._items = []  # guarded-by: _lock

    def bump(self, n):
        with self._lock:
            self._count += n
            self._items.append(n)

    def reset_waived(self):
        # single-writer teardown path, other threads already joined
        self._count = 0  # apexlint: unguarded(teardown, threads joined)

    def reinit(self):
        with self._lock:
            self._items = []
            self._items[0:0] = [1]
