"""counter-closure calibration: the compliant shapes.

Every `_evicted` bump is post-dominated by exactly one term bump —
through if/else branches, loop bodies, and an error path that
attributes its drop. One bump sits outside the law on purpose and
carries the waiver.
"""


class GoodLedger:
    # apexlint: closure(_evicted == _stored + _dropped)
    def __init__(self):
        self._evicted = 0
        self._stored = 0
        self._dropped = 0

    def ship(self, items):
        for ok in items:
            self._evicted += 1
            if ok:
                self._stored += 1
            else:
                self._dropped += 1

    def bulk(self, n, ok):
        self._evicted += n
        if ok:
            self._stored += n
            return True
        self._dropped += n
        return False

    def ship_fallible(self, batch):
        self._evicted += 1
        try:
            self._store(batch)
            self._stored += 1
        except OSError:
            self._dropped += 1

    def rebalance(self):
        self._evicted += 1  # apexlint: closure(rebalance move, not a door outcome)

    def _store(self, batch):
        raise OSError
