"""thread-lifecycle calibration: the compliant shapes.

A retained thread whose target consults a stop event and whose
teardown reaches a bounded join; a registry-retained worker; and one
deliberately detached reader carrying the waiver.
"""

import threading


class GoodOwner:
    def __init__(self):
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._workers = []
        for _ in range(2):
            w = threading.Thread(target=self._run, daemon=True)
            self._workers.append(w)

    def _run(self):
        while not self._stop.is_set():
            pass

    def stop(self):
        self._stop.set()
        self._t.join(timeout=2.0)
        for w in self._workers:
            w.join(timeout=2.0)


class DetachedOwner:
    def __init__(self, conns):
        for conn in conns:
            # apexlint: detached(reader exits when its socket dies)
            threading.Thread(target=reader, args=(conn,),
                             daemon=True).start()


def reader(conn):
    while True:
        data = conn.recv(4096)
        if not data:
            return
