"""wire-protocol fixture (shm doorbell, broken): the server grants
shm and validates doorbells, but the client never posts
MSG_SHM_DOORBELL — a granted ring no doorbell ever names, i.e. the
half-wired state the checker exists to catch (exactly one finding)."""

MSG_EXPERIENCE = 1
MSG_HELLO = 2
MSG_PARAMS = 3
MSG_SHM_DOORBELL = 4


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_HELLO:
            return {"shm": self.grant(payload)}
        if mtype == MSG_EXPERIENCE:
            return payload
        if mtype == MSG_PARAMS:
            return self.params()
        if mtype == MSG_SHM_DOORBELL:
            return self.take_slot(payload)
        return None

    def grant(self, payload):
        return payload

    def params(self):
        return None

    def take_slot(self, payload):
        return payload


class Client:
    def send(self, sock, batch):
        sock.send(MSG_HELLO)
        sock.send(MSG_EXPERIENCE)
        return sock.recv() == MSG_PARAMS
