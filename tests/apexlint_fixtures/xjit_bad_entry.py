"""Cross-module jit-purity BAD fixture, jit half.

The jit boundary is here; the host effect it reaches lives in
xjit_bad_util.residual_scale. Checked together the pair must yield
exactly one finding, anchored at the time.time() line in the util
module.
"""

import jax

from xjit_bad_util import residual_scale


@jax.jit
def train(x):
    return residual_scale(x) + 1.0
