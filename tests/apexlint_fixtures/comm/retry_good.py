"""retry-annotation fixture: every swallowed socket error is
observable — via an obs counter, an accounting bump, a _note_*
delegation, a waiver, or a re-raise."""


class Transport:
    def __init__(self):
        self._dropped = 0
        self._obs = None

    def send_counted(self, sock, data):
        try:
            sock.sendall(data)
        except OSError:
            self._obs.count("send_drops")

    def send_bumped(self, sock, data):
        try:
            sock.sendall(data)
        except ConnectionResetError:
            self._dropped += 1

    def send_delegated(self, sock, data):
        try:
            sock.sendall(data)
        except (OSError, TimeoutError) as e:
            self._note_send_failure(e)

    def close(self, sock):
        try:
            sock.close()
        except OSError:  # apexlint: lossy(close best effort)
            pass

    def send_reraising(self, sock, data):
        try:
            sock.sendall(data)
        except OSError:
            if self._obs is None:
                raise
            self._obs.count("send_drops")

    def decode(self, blob):
        try:
            return blob.decode()
        except ValueError:  # not a socket error: out of this rule's scope
            return None

    def _note_send_failure(self, exc):
        self._dropped += 1
