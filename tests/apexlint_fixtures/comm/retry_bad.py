"""retry-annotation fixture: a swallowed OSError with no counter,
no accounting bump, and no waiver — the silent-loss shape the rule
exists to catch."""


class Transport:
    def send(self, sock, data):
        try:
            sock.sendall(data)
        except OSError:
            pass
