"""wire-protocol fixture: MSG_TELEMETRY wired into both chains —
the server dispatches it, the client gates sends on negotiation."""

MSG_HELLO = 1
MSG_EXPERIENCE = 2
MSG_PARAMS = 3
MSG_TELEMETRY = 7


class Server:
    def dispatch(self, mtype, payload):
        if mtype == MSG_HELLO:
            return MSG_PARAMS
        if mtype == MSG_EXPERIENCE:
            return payload
        if mtype == MSG_TELEMETRY:
            return self.on_frame(payload)
        return None

    def on_frame(self, payload):
        return payload


class Client:
    def run(self, sock):
        sock.send(MSG_HELLO)
        if sock.recv() != MSG_PARAMS:
            return False
        sock.send(MSG_EXPERIENCE)
        if self.negotiated:
            sock.send(MSG_TELEMETRY)
        return True
