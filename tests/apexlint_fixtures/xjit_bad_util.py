"""Cross-module jit-purity BAD fixture, helper half.

Pure-looking residual helper that actually reads the host clock — the
impurity lives here, one module away from the jit boundary in
xjit_bad_entry.py, which is exactly what the v1 module-local pass
could not see.
"""

import time


def residual_scale(x):
    return x * time.time()


def double(x):
    return x * 2
