"""obs-names fixture: mini INSTRUMENTS table for the cold tier.

Rows match cold_good.py's emissions; `cold_compression_ratio` is
listed as a gauge so cold_bad.py's counter emission is a kind-mismatch
finding.
"""

INSTRUMENTS = {
    "cold_segments": {"kind": "gauge"},
    "cold_bytes": {"kind": "gauge"},
    "cold_compression_ratio": {"kind": "gauge"},
    "cold_evictions": {"kind": "ctr"},
    "cold_recalls": {"kind": "ctr"},
}
