"""guarded-by fixture: exactly one unguarded write to an annotated
attribute (`_count` in `racy_bump`)."""

import threading


class Bad:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0  # guarded-by: _lock

    def safe_bump(self):
        with self._lock:
            self._count += 1

    def racy_bump(self):
        self._count += 1  # the finding: += outside `with self._lock:`

    def closure_is_not_covered(self):
        with self._lock:
            def later():
                # runs after the with-block exits: must NOT count as
                # locked (but it is waived here, so not a finding)
                self._count = 0  # apexlint: unguarded(fixture: lexical-scope demo)
            return later
