"""obs-names fixture: the serving-tier emission shape.

Mirrors parallel/inference_server.py's MultiPolicyInferenceServer
literal emission sites: admission-controller counters, tier-level
gauges, and the shared infer_latency_ms histogram — every one carries
a row in the serve report fixture with the kind the registry
publishes it under. The per-tenant stats ride dynamic
`serve/<tenant>/<stat>` f-string keys and are invisible to the
checker by design (same policy as the learning plane's learn/ keys
and the fleet plane's peer/ keys).
"""


def admit(obs, depth, shed_n):
    obs.count("serve_offered", 1)
    for _ in range(shed_n):
        obs.count("serve_shed", 1)
    obs.gauge("serve_queue_items", float(depth))


def dispatch(obs, n_admitted, n_expired, lat_ms):
    obs.count("serve_admitted", n_admitted)
    for _ in range(n_expired):
        obs.count("serve_expired", 1)
        obs.count("serve_shed", 1)
    obs.observe("infer_latency_ms", lat_ms)


def publish_tier(obs, n_tenants, engaged):
    obs.gauge("serve_tenants", float(n_tenants))
    obs.gauge("serve_backpressure", 1.0 if engaged else 0.0)


def publish_tenant(obs, pid, stats):
    for k, v in stats.items():
        obs.gauge(f"serve/{pid}/{k}", v)
