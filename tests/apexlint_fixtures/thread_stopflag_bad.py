"""thread-lifecycle calibration: the missing-stop-flag case.

Retained and joined (bounded), but the target loop consults nothing —
only process death ends it. Exactly one finding, at the construction
line.
"""

import threading


class Unstoppable:
    def __init__(self):
        self._n = 0
        self._t = threading.Thread(target=self._spin, daemon=True)

    def _spin(self):
        while True:
            self._n += 1

    def teardown(self):
        self._t.join(timeout=2.0)
