"""wire-protocol fixture: the publisher grew a coded 'APXC' payload
shape but the parser still sniffs only 'APXV' — the half-wired state
that stalls exactly the peers that negotiated the codec. The tags are
IMPORTED (as in the real split: tags live in param_codec.py, the
client parser in socket_transport.py), calibrating that imported tag
names count toward the module's family."""

from param_codec import PARAMS_CODEC_MAGIC, PARAMS_HDR_MAGIC  # noqa: F401


class Publisher:
    def reply(self, coded, blob):
        if coded:
            return (PARAMS_CODEC_MAGIC, blob)
        return (PARAMS_HDR_MAGIC, blob)


class Parser:
    def parse(self, magic, payload):
        if magic == PARAMS_HDR_MAGIC:
            return self.parse_versioned(payload)
        return self.parse_legacy(payload)
