"""obs-names fixture: a mini report-side INSTRUMENTS table.

`listed_hist` and `listed_gauge` are emitted by the good/bad emitter
fixtures; `dead_row` is listed but emitted nowhere (the finding);
`external_row` is also unemitted but carries a justified waiver.
"""

INSTRUMENTS = {
    "listed_hist": {"kind": "hist"},
    "listed_gauge": {"kind": "gauge"},
    "dead_row": {"kind": "ctr"},
    "external_row": {"kind": "gauge"},  # apexlint: unemitted(fixture: emitted by an external probe)
}
