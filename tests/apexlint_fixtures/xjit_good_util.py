"""Cross-module jit-purity GOOD fixture, helper half: pure math only."""


def residual_scale(x, scale):
    return x * scale


def double(x):
    return x * 2
