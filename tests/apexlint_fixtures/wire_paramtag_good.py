"""wire-protocol fixture: both param payload tags fully wired — the
publisher ships raw 'APXV' and coded 'APXC' bodies, and the parser
sniffs both before falling back to the legacy pickle shape."""

PARAMS_HDR_MAGIC = 0x41505856
PARAMS_CODEC_MAGIC = 0x41505843


class Publisher:
    def reply(self, coded, blob):
        if coded:
            return (PARAMS_CODEC_MAGIC, blob)
        return (PARAMS_HDR_MAGIC, blob)


class Parser:
    def parse(self, magic, payload):
        if magic == PARAMS_CODEC_MAGIC:
            return self.apply_coded(payload)
        if magic == PARAMS_HDR_MAGIC:
            return self.parse_versioned(payload)
        return self.parse_legacy(payload)
