"""Use-after-donate GOOD fixture.

Same donating learner; the driver uses the rebind-at-call idiom
(`state, m = ...train_step(state)`) so the stale binding can never be
read, plus one audited metadata read under a donated-ok waiver.
Zero findings, one waiver.
"""

from functools import partial

import jax


class Learner:
    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state):
        return state, {"loss": 0.0}


class Driver:
    def __init__(self, learner):
        self.learner = learner

    def step(self, state):
        state, metrics = self.learner.train_step(state)
        return state, metrics

    def step_audited(self, state):
        out, metrics = self.learner.train_step(state)
        shape = state.shape  # apexlint: donated-ok(aval metadata survives donation; no buffer read)
        return out, shape
