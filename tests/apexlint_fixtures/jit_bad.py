"""jit-purity fixture: exactly one host effect reachable from a jit
boundary — `time.time()` two hops down from the jitted entrypoint."""

import time
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    return _loss(state, batch)


def _loss(state, batch):
    return _timed_residual(state, batch)


def _timed_residual(state, batch):
    t0 = time.time()  # the finding: host clock inside traced code
    del t0
    return jnp.mean((state - batch) ** 2)
