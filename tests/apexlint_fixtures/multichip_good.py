"""obs-names fixture: the dp-scaling plane's emission shape (ISSUE 9).

Mirrors obs/profiling.py's publish_multichip + the train_dist branch of
_publish_stage: every multichip gauge carries a row in the multichip
report fixture under the kind the registry publishes it as.
"""


def publish_multichip(obs, efficiency, fill_min, fill_max):
    if efficiency is not None:
        obs.gauge("dp_scaling_efficiency", efficiency)
    if fill_min is not None:
        obs.gauge("replay_shard_fill_min", fill_min)
    if fill_max is not None:
        obs.gauge("replay_shard_fill_max", fill_max)


def publish_train_dist(obs, mfu, bw_frac, dev_ms):
    obs.gauge("mfu_train_dist", mfu)
    obs.gauge("hbm_bw_frac_train_dist", bw_frac)
    obs.gauge("device_ms_train_dist", dev_ms)
