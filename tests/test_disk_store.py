"""Disk-spill rung (replay/disk_store.py, PR 16):

- bitwise offer -> writeback -> promote round-trips, heaviest first
- the disk door mirrors the RAM door (displace strictly lighter, else
  drop) and offer() NEVER blocks (full queue counts, returns False)
- file-granular promote: whole files below the displacement floor are
  skipped via the recorded per-file mass_max bound (the
  ColdSegment.mass_max consumer), and stale bounds self-tighten
- durability: reopen recovery rebuilds the index bitwise; torn tails
  (garbage, kill-mid-writeback partial records) are truncated, never
  trusted; bit-flipped payloads are rejected with an attributed error
  while intact records in the same file survive the scan
- compaction unlinks files whose live records have all left
"""

import logging
import os
import struct
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.replay.cold_store import ColdSegment
from ape_x_dqn_tpu.replay.disk_store import (
    _HEADER, _MAGIC, HEADER_BYTES, DiskStore)

LIVE = 8  # live transitions per test segment


def _seg(mass: float, tag: int, seq: int = 0) -> ColdSegment:
    """Deterministic distinct payload per tag (bitwise comparisons)."""
    rng = np.random.default_rng(1000 + tag)
    payload = rng.integers(0, 256, 96, dtype=np.uint8).tobytes()
    return ColdSegment(payload, 1, LIVE, 3 * len(payload),
                       float(mass), float(mass), seq)


def _store(tmp_path, capacity=10 * LIVE, **kw) -> DiskStore:
    return DiskStore(str(tmp_path / "disk"), capacity, **kw)


def _stopped_store(tmp_path, **kw) -> DiskStore:
    """Store with the writeback thread retired — queued offers stay
    queued, so queue behavior is testable deterministically."""
    st = _store(tmp_path, **kw)
    st._stop.set()
    st._thread.join(timeout=5.0)
    assert not st._thread.is_alive()
    return st


def test_offer_writeback_promote_bitwise(tmp_path):
    st = _store(tmp_path)
    segs = [_seg(mass=m, tag=m) for m in (3, 1, 5, 2, 4)]
    for s in segs:
        assert st.offer(s)
    st.drain(timeout=10.0)
    stats = st.stats()
    assert stats["spilled"] == 5
    assert stats["segments"] == 5
    assert stats["transitions"] == 5 * LIVE
    assert stats["queue_full"] == 0 and stats["io_errors"] == 0
    out = st.promote(5)
    # heaviest first, payloads bitwise identical to what was offered
    assert [s.mass_sum for s in out] == [5.0, 4.0, 3.0, 2.0, 1.0]
    by_mass = {s.mass_sum: s.payload for s in segs}
    for s in out:
        assert s.payload == by_mass[s.mass_sum]
        assert (s.units, s.live, s.raw_bytes) == (1, LIVE, 3 * 96)
    assert st.stats()["transitions"] == 0
    assert st.stats()["promoted"] == 5
    st.close()


def test_promote_respects_floor(tmp_path):
    st = _store(tmp_path)
    for m in (1, 2, 3, 4):
        st.offer(_seg(mass=m, tag=m))
    st.drain(timeout=10.0)
    out = st.promote(10, floor=2.5)
    assert sorted(s.mass_sum for s in out) == [3.0, 4.0]
    # the lighter segments stay resident for a later, lower floor
    assert st.stats()["segments"] == 2
    assert st.promote(10, floor=2.5) == []
    st.close()


def test_promote_skips_whole_files_below_floor(tmp_path):
    # tiny file_bytes -> one record per file, so the per-file mass_max
    # bound is exercised at file granularity
    st = _store(tmp_path, file_bytes=64)
    for m in (1, 2, 9):
        st.offer(_seg(mass=m, tag=m))
    st.drain(timeout=10.0)
    assert st.stats()["files"] == 3
    out = st.promote(10, floor=5.0)
    assert [s.mass_sum for s in out] == [9.0]
    # light files were skipped purely on their recorded bound: their
    # entries are untouched and a later floor drop frees them
    assert st.stats()["segments"] == 2
    assert [s.mass_sum for s in st.promote(10, floor=0.0)] == [2.0, 1.0]
    st.close()


def test_promote_tightens_stale_file_bound(tmp_path):
    st = _store(tmp_path, file_bytes=1 << 20)  # both in one file
    st.offer(_seg(mass=9, tag=9))
    st.offer(_seg(mass=1, tag=1))
    st.drain(timeout=10.0)
    [file_id] = list(st._files)
    assert st._files[file_id].mass_max == 9.0
    assert [s.mass_sum for s in st.promote(1, floor=0.0)] == [9.0]
    # bound is monotone (still 9.0) until a visit finds nothing above
    # the floor and tightens it to the true max of what is left
    assert st._files[file_id].mass_max == 9.0
    assert st.promote(1, floor=5.0) == []
    assert st._files[file_id].mass_max == 1.0
    st.close()


def test_disk_door_displaces_lighter_drops_heavier(tmp_path):
    st = _store(tmp_path, capacity=2 * LIVE)
    st.offer(_seg(mass=5, tag=5))
    st.offer(_seg(mass=6, tag=6))
    st.drain(timeout=10.0)
    # heavier candidate displaces the lightest stored segment
    st.offer(_seg(mass=7, tag=7))
    st.drain(timeout=10.0)
    assert st.stats()["transitions"] == 2 * LIVE
    # lighter candidate is dropped at the disk door
    st.offer(_seg(mass=1, tag=1))
    st.drain(timeout=10.0)
    stats = st.stats()
    assert stats["dropped"] == 1
    assert stats["spilled"] == 3
    masses = sorted(s.mass_sum for s in st.promote(10))
    assert masses == [6.0, 7.0]
    st.close()


def test_offer_full_queue_counts_never_blocks(tmp_path):
    st = _stopped_store(tmp_path, queue_depth=1)
    assert st.offer(_seg(mass=1, tag=1))
    t0 = time.monotonic()
    assert not st.offer(_seg(mass=2, tag=2))
    assert not st.offer(_seg(mass=3, tag=3))
    # put_nowait by construction: a refusal is immediate, not a wait
    assert time.monotonic() - t0 < 0.5
    assert st.stats()["queue_full"] == 2
    assert st.stats()["spilled"] == 0
    st.close()


def test_reopen_recovery_roundtrips_bitwise(tmp_path):
    st = _store(tmp_path)
    segs = [_seg(mass=m, tag=m) for m in (2, 7, 4)]
    for s in segs:
        st.offer(s)
    st.drain(timeout=10.0)
    before = st.stats()
    st.close()
    st2 = _store(tmp_path)
    after = st2.stats()
    assert after["segments"] == before["segments"] == 3
    assert after["transitions"] == before["transitions"]
    assert after["bytes"] == before["bytes"]
    out = st2.promote(10)
    assert [s.mass_sum for s in out] == [7.0, 4.0, 2.0]
    by_mass = {s.mass_sum: s.payload for s in segs}
    for s in out:
        assert s.payload == by_mass[s.mass_sum]
    st2.close()


def test_recovery_appends_go_to_fresh_file(tmp_path):
    st = _store(tmp_path)
    st.offer(_seg(mass=1, tag=1))
    st.drain(timeout=10.0)
    files_before = set(os.listdir(st.dir))
    st.close()
    st2 = _store(tmp_path)
    st2.offer(_seg(mass=2, tag=2))
    st2.drain(timeout=10.0)
    new = set(os.listdir(st2.dir)) - files_before
    assert len(new) == 1  # never extends a pre-crash file
    st2.close()


def _only_file(st: DiskStore) -> str:
    names = [n for n in os.listdir(st.dir) if n.endswith(".cold")]
    assert len(names) == 1
    return os.path.join(st.dir, names[0])


def test_torn_garbage_tail_truncated(tmp_path, caplog):
    st = _store(tmp_path)
    st.offer(_seg(mass=3, tag=3))
    st.drain(timeout=10.0)
    path = _only_file(st)
    st.close()
    good_size = os.path.getsize(path)
    with open(path, "ab") as fh:
        fh.write(b"\x00garbage-after-a-crash" * 4)
    with caplog.at_level(logging.WARNING,
                         logger="ape_x_dqn_tpu.replay.disk_store"):
        st2 = _store(tmp_path)
    assert os.path.getsize(path) == good_size
    assert st2.stats()["segments"] == 1
    assert any("torn tail" in r.message for r in caplog.records)
    [out] = st2.promote(1)
    assert out.payload == _seg(mass=3, tag=3).payload
    st2.close()


def test_kill_mid_writeback_partial_record_truncated(tmp_path):
    """A record torn mid-append (intact header, short payload) is the
    kill-mid-writeback shape; recovery truncates it and every earlier
    record round-trips bitwise."""
    st = _store(tmp_path)
    st.offer(_seg(mass=5, tag=5))
    st.drain(timeout=10.0)
    path = _only_file(st)
    st.close()
    good_size = os.path.getsize(path)
    torn = _seg(mass=8, tag=8)
    import zlib
    header = _HEADER.pack(_MAGIC, torn.units, torn.live, torn.mass_sum,
                          torn.mass_max, 99, torn.raw_bytes,
                          len(torn.payload), zlib.crc32(torn.payload))
    with open(path, "ab") as fh:
        fh.write(header + torn.payload[:10])  # killed 10 bytes in
    st2 = _store(tmp_path)
    assert os.path.getsize(path) == good_size  # torn record gone
    assert st2.stats()["segments"] == 1
    [out] = st2.promote(1)
    assert out.mass_sum == 5.0
    assert out.payload == _seg(mass=5, tag=5).payload
    st2.close()


def test_bitflip_rejected_attributed_scan_continues(tmp_path, caplog):
    """Bit rot inside a payload (framing intact): the record is
    rejected with an attributed error, counted, and the scan recovers
    every OTHER record in the same file."""
    st = _store(tmp_path, file_bytes=1 << 20)
    st.offer(_seg(mass=2, tag=2))
    st.offer(_seg(mass=6, tag=6))
    st.drain(timeout=10.0)
    path = _only_file(st)
    # flip one byte inside the FIRST record's payload
    with open(path, "r+b") as fh:
        fh.seek(HEADER_BYTES + 5)
        b = fh.read(1)
        fh.seek(HEADER_BYTES + 5)
        fh.write(bytes([b[0] ^ 0xFF]))
    st.close()
    with caplog.at_level(logging.ERROR,
                         logger="ape_x_dqn_tpu.replay.disk_store"):
        st2 = _store(tmp_path)
    stats = st2.stats()
    assert stats["corrupt_segments"] == 1
    assert stats["segments"] == 1
    attributed = [r for r in caplog.records
                  if "CRC mismatch" in r.message]
    assert attributed and path in attributed[0].getMessage()
    [out] = st2.promote(1)  # the intact record past the rot survives
    assert out.mass_sum == 6.0
    assert out.payload == _seg(mass=6, tag=6).payload
    st2.close()


def test_bitflip_on_read_rejected(tmp_path, caplog):
    """Rot that lands AFTER the index was built (or a stale index) is
    caught by the read-side CRC check in promote()."""
    st = _store(tmp_path, file_bytes=1 << 20)
    st.offer(_seg(mass=4, tag=4))
    st.drain(timeout=10.0)
    path = _only_file(st)
    with open(path, "r+b") as fh:
        fh.seek(HEADER_BYTES + 3)
        b = fh.read(1)
        fh.seek(HEADER_BYTES + 3)
        fh.write(bytes([b[0] ^ 0x01]))
    with caplog.at_level(logging.ERROR,
                         logger="ape_x_dqn_tpu.replay.disk_store"):
        out = st.promote(1)
    assert out == []
    assert st.stats()["corrupt_segments"] == 1
    assert any("CRC/length mismatch" in r.message
               for r in caplog.records)
    st.close()


def test_compaction_unlinks_emptied_files(tmp_path):
    # one record per file; promoting a file's only record makes its
    # dead fraction 1.0 and the next writeback pass compacts it away
    st = _store(tmp_path, file_bytes=64, compact_frac=0.5)
    for m in (1, 2, 3):
        st.offer(_seg(mass=m, tag=m))
    st.drain(timeout=10.0)
    assert st.stats()["files"] == 3
    [heavy] = st.promote(1)
    assert heavy.mass_sum == 3.0
    st.offer(_seg(mass=4, tag=4))  # writeback pass runs compaction
    st.drain(timeout=10.0)
    deadline = time.monotonic() + 10.0
    while st.stats()["compactions"] < 1 \
            and time.monotonic() < deadline:
        time.sleep(0.01)  # compaction runs after the drain handshake
    stats = st.stats()
    assert stats["compactions"] >= 1
    assert stats["segments"] == 3  # 1, 2 and the new 4
    # surviving payloads are untouched by the compaction pass
    out = {s.mass_sum: s.payload for s in st.promote(10)}
    assert out[1.0] == _seg(mass=1, tag=1).payload
    assert out[2.0] == _seg(mass=2, tag=2).payload
    st.close()


def test_displacement_floor(tmp_path):
    st = _stopped_store(tmp_path, capacity=2 * LIVE)
    assert st.displacement_floor() == 0.0
    st._write_one(_seg(mass=3, tag=3))
    assert st.displacement_floor() == 0.0  # below capacity
    st._write_one(_seg(mass=5, tag=5))
    assert st.displacement_floor() == 3.0  # at capacity: lightest mass
    st.close()


def test_drain_times_out_when_writeback_is_dead(tmp_path):
    st = _stopped_store(tmp_path)
    with pytest.raises(TimeoutError):
        st.drain(timeout=0.2)
    st.close()
