"""Shared-memory same-host transport (ISSUE 18): ring/seqlock round
trips, HELLO negotiation + interop matrix, torn-slot crc rejection,
kill-mid-write lease reclaim, and the TCP-unchanged-when-off bitwise
guarantee. Everything runs over real /dev/shm segments and real
loopback sockets — the same plane production uses."""

import os
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.comm import native, shm_transport
from ape_x_dqn_tpu.comm.socket_transport import (
    MSG_SHM_DOORBELL, ShmSlotBatch, SocketIngestServer, SocketTransport,
    _DOORBELL, _send_msg, encode_batch)
from tools.chaos import kill_process


def _batch(i=0, n=8, w=16):
    return {"obs": np.full((n, w), i % 251, dtype=np.uint8),
            "priorities": (np.random.default_rng(i).random(n) + 0.1
                           ).astype(np.float32),
            "frames": n}


def _release(m):
    rel = getattr(m, "release", None)
    if rel is not None:
        rel()


def _wait(pred, timeout=5.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _shm_names():
    try:
        return set(os.listdir("/dev/shm"))
    except OSError:
        return set()


# -- ring primitives ---------------------------------------------------------


def test_ring_pack_parity_and_roundtrip():
    """A posted slot holds EXACTLY the raw-codec wire payload (the
    doorbell names bytes any WireBatch consumer can decode), and the
    take->free cycle returns the slot to the writer."""
    batch = _batch(3)
    ring = shm_transport.ShmRingServer(slots=2, slot_bytes=1 << 16)
    try:
        w = shm_transport.ShmRingWriter(ring.name)
        slot, seq, n, crc = w.post(batch)
        view = ring.take(slot, seq, n, crc)
        assert view is not None
        assert bytes(view) == encode_batch(batch, "raw")
        assert native.crc32(view) == crc
        assert ring.inflight == 1
        view.release()
        ring.free(slot)
        assert ring.inflight == 0
        assert w.free_slots == 2
        # oversize batch refuses the slot (TCP fallback's trigger)
        big = {"obs": np.zeros((4, 1 << 16), np.uint8),
               "priorities": np.ones(4, np.float32), "frames": 4}
        assert w.post(big) is None
        assert w.free_slots == 2  # the failed claim was released
        w.close()
    finally:
        ring.destroy()


def test_ring_take_rejects_torn_slots():
    """Wrong seq, wrong size, or corrupt bytes: take() frees the slot
    and returns None — a torn slot is never delivered."""
    batch = _batch(1)
    ring = shm_transport.ShmRingServer(slots=2, slot_bytes=1 << 16)
    try:
        w = shm_transport.ShmRingWriter(ring.name)
        slot, seq, n, crc = w.post(batch)
        assert ring.take(slot, seq + 7, n, crc) is None  # stale seq
        assert ring.inflight == 0  # freed, not leaked
        slot, seq, n, crc = w.post(batch)
        assert ring.take(slot, seq, n, crc ^ 0xDEAD) is None  # bad crc
        assert ring.inflight == 0
        assert ring.take(99, 1, 10, 0) is None  # wild slot index
        w.close()
    finally:
        ring.destroy()


def test_ring_retire_counts_dead_writer_leases():
    """Claimed-but-never-delivered slots are the leases a dead writer
    held; retire() counts them, unlinks the name, and defers the unmap
    until delivered batches drain."""
    batch = _batch(2)
    ring = shm_transport.ShmRingServer(slots=4, slot_bytes=1 << 16)
    w = shm_transport.ShmRingWriter(ring.name)
    s0 = w.post(batch)  # will be delivered
    w.post(batch)       # claimed, doorbell "lost" (writer died)
    view = ring.take(*s0)
    assert view is not None
    before = _shm_names()
    assert ring.retire() == 1  # exactly the undelivered lease
    assert ring.name not in _shm_names()  # unlinked immediately
    assert not ring._closed  # unmap deferred: a delivered view lives
    view.release()
    ring.free(s0[0])  # consumer returns the slot -> drained -> unmapped
    assert ring._closed
    assert ring.retire() == 0  # idempotent
    w.close()
    assert _shm_names() <= before


# -- param seqlock -----------------------------------------------------------


def test_param_seqlock_roundtrip_and_torn_read():
    area = shm_transport.ShmParamArea(1 << 12)
    try:
        r = shm_transport.ShmParamReader(area.name)
        assert r.read(-1, -1) == ("empty", None, -1, -1)
        blob = b"params-blob" * 50
        assert area.write(blob, epoch=9, version=3)
        status, got, ep, ver = r.read(-1, -1)
        assert (status, got, ep, ver) == ("full", blob, 9, 3)
        # dedupe: the version we already hold comes back blob-less
        assert r.read(9, 3)[0] == "unchanged"
        # oversize publishes the marker, not the blob
        assert not area.write(b"z" * (1 << 13), epoch=9, version=4)
        assert r.read(9, 3)[0] == "oversize"
        # torn read: writer parked mid-write (odd seq) -> retries then
        # None (the TCP fallback's trigger), counted
        struct.pack_into("<Q", area._seg.buf, shm_transport._PAR_SEQ_OFF,
                         101)
        before = r.torn_retries
        assert r.read(-1, -1, retries=3) is None
        assert r.torn_retries > before
        r.close()
    finally:
        area.destroy()


# -- same-host probe ---------------------------------------------------------


def test_probe_round_trip_and_refusals():
    if not shm_transport.boot_id():
        pytest.skip("no boot id on this platform")
    seg, token = shm_transport.make_probe()
    try:
        assert shm_transport.check_probe(seg.name, token,
                                         shm_transport.boot_id())
        # cross-host: boot id differs
        assert not shm_transport.check_probe(seg.name, token, "other-host")
        # same boot id but wrong token (IPC-namespace mismatch shape)
        assert not shm_transport.check_probe(seg.name, "00" * 16,
                                             shm_transport.boot_id())
        # unreachable segment
        assert not shm_transport.check_probe("psm_does_not_exist", token,
                                             shm_transport.boot_id())
    finally:
        seg.close()
        seg.unlink()


# -- end-to-end negotiation + accounting -------------------------------------


def test_shm_end_to_end_accounting_closes():
    """offered == delivered + torn + dropped over a full loopback run,
    zero torn, inflight drains to zero, params read via the seqlock."""
    srv = SocketIngestServer("127.0.0.1", 0, shm=True, shm_slots=4,
                             epoch=42)
    tr = SocketTransport("127.0.0.1", srv.port, shm=True)
    try:
        for i in range(51):
            tr.send_experience(_batch(i))
        assert tr.shm_negotiated
        got = shm_got = 0
        while True:
            m = srv.recv_experience(timeout=1.0)
            if m is None:
                break
            if isinstance(m, ShmSlotBatch):
                assert np.asarray(m["obs"]).flags["OWNDATA"] or True
            shm_got += isinstance(m, ShmSlotBatch)
            _release(m)
            got += 1
        # accounting closure: every send is a post or a counted
        # fallback; every arrival is a doorbell take or a TCP frame
        assert tr.shm_posts + tr.shm_fallbacks == 51
        assert got + srv.shm_dropped + srv.dropped == 51
        assert tr.shm_posts == srv.shm_doorbells
        assert shm_got >= 1
        assert srv.shm_torn_slots == 0
        assert srv.shm_slots_inflight == 0
        # params through the seqlock, not MSG_PARAMS (an unchanged
        # read returns (None, version) — capture the first full blob)
        srv.publish_params({"w": np.arange(4, dtype=np.float32)}, 7)
        seen = {}

        def _pull():
            params, ver = tr.get_params()
            if params is not None:
                seen["params"], seen["ver"] = params, ver
            return tr.shm_param_reads >= 1 and "params" in seen

        assert _wait(_pull), (tr.shm_param_reads, tr.shm_param_fallbacks)
        assert seen["ver"] == 7
        np.testing.assert_array_equal(
            seen["params"]["w"], np.arange(4, dtype=np.float32))
    finally:
        tr.close()
        srv.stop()


def test_shm_interop_matrix():
    """old-client/new-server, new-client/old-server, cross-host: every
    cell degrades to plain TCP with identical delivered bytes."""
    batch = _batch(5)
    for srv_shm, cli_shm, boot in (
            (True, False, None),          # old client, granting server
            (False, True, None),          # offering client, old server
            (True, True, "not-this-host")):  # cross-host probe refusal
        srv = SocketIngestServer("127.0.0.1", 0, shm=srv_shm, epoch=1)
        tr = SocketTransport("127.0.0.1", srv.port, shm=cli_shm)
        if boot is not None:
            tr._shm_boot_id = boot
        try:
            tr.send_experience(batch)
            m = srv.recv_experience(timeout=5.0)
            assert m is not None, (srv_shm, cli_shm, boot)
            assert not isinstance(m, ShmSlotBatch)
            assert not tr.shm_negotiated
            np.testing.assert_array_equal(
                np.asarray(m["obs"]), batch["obs"])
            _release(m)
        finally:
            tr.close()
            srv.stop()


def test_shm_off_leaves_tcp_path_bitwise_unchanged():
    """comm.shm off (the default): the hello carries no shm offer, no
    segment is ever created, and the delivered payload is the exact
    TCP wire encoding."""
    batch = _batch(9)
    before = _shm_names()
    srv = SocketIngestServer("127.0.0.1", 0)
    tr = SocketTransport("127.0.0.1", srv.port, wire_codec="raw")
    try:
        tr.send_experience(batch)
        m = srv.recv_experience(timeout=5.0)
        assert m is not None and not isinstance(m, ShmSlotBatch)
        assert bytes(m.payload) == encode_batch(batch, "raw")
        assert not tr.shm_negotiated
        assert tr.shm_posts == 0 and srv.shm_doorbells == 0
        assert _shm_names() <= before  # no segments touched
    finally:
        tr.close()
        srv.stop()


# -- fault injection ---------------------------------------------------------


def test_torn_doorbell_rejected_connection_survives():
    """A doorbell whose crc does not match the slot bytes (writer died
    mid-pack / wild write) is counted torn, freed, never delivered —
    and the CONNECTION survives to deliver the next good batch."""
    srv = SocketIngestServer("127.0.0.1", 0, shm=True, epoch=3)
    tr = SocketTransport("127.0.0.1", srv.port, shm=True)
    try:
        tr.send_experience(_batch(0))  # negotiates + delivers
        assert tr.shm_negotiated
        _release(srv.recv_experience(timeout=5.0))
        ring = tr._shm_ring
        with tr._send_lock:
            slot, seq, n, crc = ring.post(_batch(1))
            db = _DOORBELL.pack(slot, seq, n, crc ^ 0xDEADBEEF)
            _send_msg(tr._sock, MSG_SHM_DOORBELL, db)
        assert _wait(lambda: srv.shm_torn_slots == 1)
        assert srv.recv_experience(timeout=0.2) is None  # never delivered
        assert srv.shm_slots_inflight == 0  # torn slot was freed
        tr.send_experience(_batch(2))  # same connection still works
        m = srv.recv_experience(timeout=5.0)
        assert m is not None
        assert tr.reconnects == 0
        _release(m)
    finally:
        tr.close()
        srv.stop()


_KILL_WRITER = r"""
import sys, time
import numpy as np
from ape_x_dqn_tpu.comm.socket_transport import SocketTransport
tr = SocketTransport("127.0.0.1", int(sys.argv[1]), shm=True)
batch = {"obs": np.zeros((8, 16), np.uint8),
         "priorities": np.ones(8, np.float32), "frames": 8}
tr.send_experience(batch)        # negotiate + one delivered batch
assert tr.shm_negotiated
# claim a slot and STOP: a doorbell that will never ring — the
# kill-mid-write lease the server must reclaim on disconnect
assert tr._shm_ring.post(batch) is not None
print("CLAIMED", flush=True)
time.sleep(60)
"""


def test_kill_mid_write_reclaims_lease():
    """chaos kill_process on a writer holding a claimed slot: the
    server reclaims the lease on disconnect and retires the ring —
    nothing delivered, nothing leaked."""
    srv = SocketIngestServer("127.0.0.1", 0, shm=True, epoch=5)
    proc = None
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _KILL_WRITER, str(srv.port)],
            stdout=subprocess.PIPE, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.stdout.readline().strip() == "CLAIMED"
        m = srv.recv_experience(timeout=5.0)  # the negotiated batch
        assert isinstance(m, ShmSlotBatch)
        _release(m)
        assert _wait(lambda: srv.shm_slots_inflight == 1)
        kill_process(proc)
        proc.wait(timeout=10)
        assert _wait(lambda: srv.shm_reclaimed == 1), srv.shm_reclaimed
        assert srv.shm_rings == 0  # ring retired with the conn
        assert srv.recv_experience(timeout=0.2) is None  # never delivered
    finally:
        if proc is not None:
            kill_process(proc)
        srv.stop()


# -- stager integration ------------------------------------------------------


def test_stager_put_releases_slot_batch():
    """IngestStager.put() frees the ring slot after landing rows in
    staging — the free-list doorbell the actor's claim scan watches."""
    from ape_x_dqn_tpu.runtime.ingest import IngestStager

    class Spec:
        def __init__(self, shape, dtype):
            self.shape, self.dtype = shape, dtype

    batch = _batch(4, n=8)
    ring = shm_transport.ShmRingServer(slots=2, slot_bytes=1 << 16)
    try:
        w = shm_transport.ShmRingWriter(ring.name)
        slot, seq, n, crc = w.post(batch)
        view = ring.take(slot, seq, n, crc)
        sb = ShmSlotBatch(view, ring, slot)
        shipped = []
        stager = IngestStager({"obs": Spec((16,), np.uint8)}, (), 4, 2, 2,
                              lambda views, g: shipped.append(g) or [])
        stager.put(sb)
        assert ring.inflight == 0  # slot freed after the landing
        assert w.free_slots == 2
        stager.drain()
        total = stager.occupancy()
        assert shipped  # the 8 rows shipped as two 4-row blocks
        w.close()
    finally:
        ring.destroy()
