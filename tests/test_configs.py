import jax
import pytest

from ape_x_dqn_tpu.configs import PRESETS, get_config
from ape_x_dqn_tpu.utils.rng import RngStream, component_key
from ape_x_dqn_tpu.utils.metrics import (
    Metrics, Throughput, human_normalized_score, median_hns,
    ATARI_HUMAN_RANDOM)


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_five_presets_exist():
    # The five attested reference configs (SURVEY.md §2.1).
    assert set(PRESETS) == {
        "cartpole_smoke", "pong", "atari57_apex", "r2d2", "apex_dpg"}


def test_preset_fields():
    cp = get_config("cartpole_smoke")
    assert cp.replay.kind == "uniform" and cp.actors.num_actors == 1
    pong = get_config("pong")
    assert pong.replay.kind == "prioritized" and pong.actors.num_actors == 8
    apex = get_config("atari57_apex")
    assert apex.actors.num_actors == 256
    assert apex.network.dueling and apex.learner.double_dqn
    r2d2 = get_config("r2d2")
    assert r2d2.replay.kind == "sequence"
    assert r2d2.replay.seq_length == 80 and r2d2.replay.burn_in == 40
    dpg = get_config("apex_dpg")
    assert dpg.network.kind == "dpg"


def test_config_override():
    cfg = get_config("pong", seed=7)
    assert cfg.seed == 7
    cfg2 = cfg.replace(total_env_frames=123)
    assert cfg2.total_env_frames == 123 and cfg.total_env_frames != 123


def test_unknown_config():
    with pytest.raises(KeyError):
        get_config("nope")


def test_rng_determinism():
    a = RngStream(0, "actor", 3)
    b = RngStream(0, "actor", 3)
    assert a.next_uint32() == b.next_uint32()
    c = RngStream(0, "actor", 4)
    assert a.next_uint32() != c.next_uint32()  # different actor index
    k1 = component_key(0, "learner")
    k2 = component_key(0, "replay")
    assert (jax.random.bits(k1, (), "uint32")
            != jax.random.bits(k2, (), "uint32"))


def test_metrics_and_throughput(tmp_path):
    m = Metrics(str(tmp_path / "log.jsonl"))
    m.log(1, loss=0.5, frames=100)
    assert m.latest()["loss"] == 0.5
    m.close()
    t = Throughput(window_s=100.0)
    t.add(10, now=0.0)
    t.add(10, now=1.0)
    assert abs(t.rate(now=1.0) - 20.0) < 1e-6


def test_metrics_tensorboard_sink(tmp_path):
    """Optional TB event-file sink (SURVEY.md §5 metrics row): scalars
    land in event files while JSONL stays canonical."""
    import pytest
    pytest.importorskip("torch.utils.tensorboard")
    tb_dir = tmp_path / "tb"
    m = Metrics(log_path=str(tmp_path / "log.jsonl"),
                tensorboard_dir=str(tb_dir))
    m.log(1, loss=0.5, note=None)  # non-scalars must be skipped, not die
    m.log(2, loss=0.25, frames=128)
    m.close()
    events = list(tb_dir.glob("events.out.tfevents.*"))
    assert events and events[0].stat().st_size > 0
    # JSONL canonical stream still intact
    import json
    recs = [json.loads(ln) for ln
            in (tmp_path / "log.jsonl").read_text().splitlines()]
    assert recs[-1]["loss"] == 0.25 and recs[-1]["frames"] == 128


def test_hns():
    assert len(ATARI_HUMAN_RANDOM) == 57
    assert abs(human_normalized_score("pong", 14.6) - 1.0) < 1e-9
    assert abs(median_hns({"pong": 14.6, "breakout": 30.5}) - 1.0) < 1e-9


def test_sample_chunk_gated_for_unimplemented_families():
    """Families without the K-batch relaxation must reject
    sample_chunk>1 loudly, not silently train exact semantics under a
    config that claims otherwise. (Round 5: the SequenceLearner now
    implements K-batch — tests/test_r2d2_runtime.py covers its
    mechanics — so only DPG keeps the gate.)"""
    import pytest

    from ape_x_dqn_tpu.configs import LearnerConfig
    from ape_x_dqn_tpu.models import DPGActor, DPGCritic
    from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
    from ape_x_dqn_tpu.runtime.dpg_learner import DPGLearner

    lcfg = LearnerConfig(batch_size=8, sample_chunk=4)
    actor = DPGActor(action_dim=1, action_low=-1, action_high=1)
    critic = DPGCritic()
    with pytest.raises(ValueError, match="sample_chunk"):
        DPGLearner(actor.apply, critic.apply,
                   PrioritizedReplay(capacity=64), lcfg)

    # same gate for the double-buffered sampling pipeline
    lcfg = LearnerConfig(batch_size=8, sample_prefetch=True)
    with pytest.raises(ValueError, match="sample_prefetch"):
        DPGLearner(actor.apply, critic.apply,
                   PrioritizedReplay(capacity=64), lcfg)


def test_final_eval_deadline_is_configurable():
    """The end-of-run eval backstop budget must come from RunConfig —
    a hard-coded 60s deadline silently discarded fully-trained suite
    games on slow-link hosts (round-5 suite-learning run: eval=null
    after 45k frames of training)."""
    from ape_x_dqn_tpu.configs import get_config

    cfg = get_config("pong")
    assert cfg.final_eval_deadline_s >= 300.0
    assert get_config("pong", final_eval_deadline_s=30.0) \
        .final_eval_deadline_s == 30.0
