"""Contract tests for the real-backend adapters (ALERawEnv,
DMControlAdapter) against FAKE ale_py / dm_control modules.

These adapters gate on imports that don't exist in this image, so until
round 3 they had never executed anywhere (round-2 verdict missing #5) —
their first run would have been a production deployment. The fakes
below pin the exact call sequences the real libraries expose (ALE's
minimal action set indirection, lives accounting, reset/act order;
dm_control's timestep protocol, observation dicts, discount-based
terminals) so a drift in the adapters breaks HERE first.
"""

import sys
import types

import numpy as np
import pytest

from ape_x_dqn_tpu.configs import EnvConfig
from ape_x_dqn_tpu.envs import atari, control, make_env


# -- fake ale_py ------------------------------------------------------------

class _FakeALE:
    """Mimics ale_py.ALEInterface for a 3-life, reward-every-4th-act
    game. Asserts the adapter's contract: configuration before loadROM,
    acts only with codes from the minimal action set, no act after
    game_over without reset_game."""

    MINIMAL_SET = [0, 11, 12]  # ALE codes: NOOP, and two moves

    def __init__(self):
        self.ints: dict = {}
        self.floats: dict = {}
        self.rom = None
        self._acts = 0
        self._lives = 3
        self._over = True  # must reset_game before acting
        self._allowed: set = set(self.MINIMAL_SET)

    # configuration
    def setInt(self, key, value):
        assert self.rom is None, "setInt must precede loadROM"
        self.ints[key] = value

    def setFloat(self, key, value):
        assert self.rom is None, "setFloat must precede loadROM"
        self.floats[key] = value

    def loadROM(self, path):
        assert self.ints.get("random_seed") is not None, \
            "seed must be configured before loadROM"
        self.rom = path

    def getMinimalActionSet(self):
        assert self.rom is not None, "loadROM before getMinimalActionSet"
        self._allowed = set(self.MINIMAL_SET)
        return list(self.MINIMAL_SET)

    def getLegalActionSet(self):
        assert self.rom is not None, "loadROM before getLegalActionSet"
        self._allowed = set(range(18))  # ALE's full legal set: 18
        return sorted(self._allowed)

    # game loop
    def reset_game(self):
        self._acts = 0
        self._lives = 3
        self._over = False

    def getScreenRGB(self):
        frame = np.zeros((210, 160, 3), np.uint8)
        # a moving sprite so preprocessing sees changing content
        x = (self._acts * 7) % 150
        frame[100:110, x:x + 10] = 200
        return frame

    def act(self, code):
        assert code in self._allowed, \
            f"act({code}) outside the requested action set"
        assert not self._over, "act() after game_over without reset_game"
        self._acts += 1
        reward = 0.0
        if self._acts % 4 == 0:
            reward = 2.0  # unclipped magnitude: exercises reward clip
        if self._acts % 20 == 0:
            self._lives -= 1
            if self._lives == 0:
                self._over = True
        return reward

    def game_over(self):
        return self._over

    def lives(self):
        return self._lives


@pytest.fixture
def fake_ale(monkeypatch):
    instances: list[_FakeALE] = []

    class _Iface(_FakeALE):
        def __init__(self):
            super().__init__()
            instances.append(self)

    mod = types.ModuleType("ale_py")
    mod.ALEInterface = _Iface
    roms = types.ModuleType("ale_py.roms")
    roms.get_rom_path = lambda game: f"/fake/roms/{game}.bin"
    mod.roms = roms
    monkeypatch.setitem(sys.modules, "ale_py", mod)
    monkeypatch.setitem(sys.modules, "ale_py.roms", roms)
    monkeypatch.setattr(atari, "HAVE_ALE", True)
    return instances


def test_ale_adapter_raw_contract(fake_ale):
    env = atari.ALERawEnv("pong", seed=7)
    ale = fake_ale[0]
    assert ale.ints["random_seed"] == 7
    assert "repeat_action_probability" in ale.floats
    assert ale.rom == "/fake/roms/pong.bin"
    assert env.num_actions == 3
    frame = env.reset()
    assert frame.shape == (210, 160, 3) and frame.dtype == np.uint8
    f2, r, done = env.step(1)  # adapter maps index 1 -> ALE code 11
    assert f2.shape == (210, 160, 3)
    assert isinstance(r, float) and not done
    assert env.lives == 3


def test_ale_through_full_preprocessing_stack(fake_ale):
    """make_env kind='atari' with a (fake) ALE present must select the
    real adapter and run the whole DQN pipeline on it: frame-skip
    max-pool, 84x84x4 uint8, episodic life, reward clip. Noop starts
    are disabled here so the lives/acts accounting is deterministic
    (covered separately below)."""
    cfg = EnvConfig(id="PongNoFrameskip-v4", kind="atari",
                    max_noop_start=0)
    assert atari.atari_backend(cfg.kind) == "ale"
    env = make_env(cfg, seed=3)
    ale = fake_ale[0]
    # the gym id was translated to the snake_case rom name
    assert ale.rom == "/fake/roms/pong.bin"
    obs = env.reset()
    assert obs.shape == (84, 84, 4) and obs.dtype == np.uint8
    rewards, infos = [], []
    done = False
    for _ in range(200):
        obs, r, done, info = env.step(1)
        rewards.append(r)
        infos.append(info)
        if done:
            break
    # 4 acts per step, life lost at act 20 -> episodic-life end, step 5
    assert done and len(rewards) == 5
    # reward clipping bound the +2.0 raw rewards
    assert set(np.unique(rewards)) <= {0.0, 1.0, -1.0}
    assert any(r == 1.0 for r in rewards), "clipped reward never arrived"
    # the life loss surfaced as an episodic-life terminal with raw
    # lives accounting
    assert infos[-1]["terminal"] is True
    assert infos[-1]["lives"] == 2
    # raw (unclipped) rewards ride alongside for eval/HNS
    assert any(i["raw_reward"] >= 2.0 for i in infos)
    # pseudo-reset continues the same raw episode (no reset_game call):
    before = ale._acts
    env.reset()
    assert ale._acts == before + 1  # the single pseudo-reset noop step
    assert env.spec.num_actions == 3


def test_ale_noop_starts_step_raw_noops(fake_ale):
    env = make_env(EnvConfig(id="PongNoFrameskip-v4", kind="atari"),
                   seed=3)
    env.reset()
    # noop starts consumed raw frames (code 0 acts) before the first obs
    assert 1 <= fake_ale[0]._acts <= 30


def test_ale_full_game_over(fake_ale):
    """Full-episode drive to raw game over across the 3 lives."""
    cfg = EnvConfig(id="BreakoutNoFrameskip-v4", kind="atari",
                    episodic_life=False)
    env = make_env(cfg, seed=1)
    env.reset()
    done, steps, info = False, 0, {}
    while not done and steps < 100:
        _, _, done, info = env.step(2)
        steps += 1
    assert done and info["terminal"] is True
    assert "episode_return" in info and "episode_length" in info
    assert fake_ale[0].game_over()


def test_atari57_spreads_actors_across_games(fake_ale):
    """The flagship id 'atari57' assigns each global actor slot a game
    round-robin over the 57-game suite (SURVEY.md §2.1 config 3) —
    without this a real-ALE deployment would ask for a rom literally
    named 'atari57'."""
    from ape_x_dqn_tpu.utils.metrics import ATARI_HUMAN_RANDOM

    games = sorted(ATARI_HUMAN_RANDOM)
    cfg = EnvConfig(id="atari57", kind="atari", max_noop_start=0)
    for slot in (0, 3, 56, 57):
        env = make_env(cfg, seed=1, actor_index=slot)
        rom = fake_ale[-1].rom
        assert rom == f"/fake/roms/{games[slot % 57]}.bin", (slot, rom)
        # multi-game fleets share one Q-net: every game exposes the
        # 18-action LEGAL set, not its own minimal set — without this
        # a breakout actor argmaxing an 18-dim Q vector from an
        # alien-sized probe net steps out of range
        assert env.spec.num_actions == 18
        env.reset()
        env.step(17)  # the highest shared index is valid everywhere


def test_atari57_eval_worker_keeps_full_action_set(fake_ale):
    """A per-game EvalWorker built from a multi-game config must keep
    the 18-action legal set the shared net was sized for — replacing
    id='atari57' with a specific game would otherwise shrink the env
    to that game's minimal set and misalign action indices."""
    from ape_x_dqn_tpu.configs import get_config
    from ape_x_dqn_tpu.runtime.evaluation import EvalWorker

    cfg = get_config("atari57_apex").replace(
        env=EnvConfig(id="atari57", kind="atari", max_noop_start=0))

    worker = EvalWorker(cfg, lambda obs: np.zeros(18, np.float32),
                        game="pong")
    assert worker.env.spec.num_actions == 18
    assert fake_ale[-1].rom == "/fake/roms/pong.bin"


# -- fake dm_control --------------------------------------------------------

class _FakeTimestep:
    def __init__(self, obs, reward, discount, last):
        self.observation = obs
        self.reward = reward
        self.discount = discount
        self._last = last

    def last(self):
        return self._last


class _FakeDMEnv:
    """Mimics a dm_control.suite env: dict observations, box action
    spec, timestep protocol with discount-carrying terminals."""

    def __init__(self, terminal_discount: float, horizon: int = 8):
        self._t = 0
        self._terminal_discount = terminal_discount
        self._horizon = horizon
        self.actions: list[np.ndarray] = []

    def action_spec(self):
        return types.SimpleNamespace(
            shape=(2,), minimum=np.array([-1.0, -1.0]),
            maximum=np.array([1.0, 1.0]))

    def _obs(self):
        # two blocks of different shapes: flattening must concatenate
        return {"position": np.full((3,), float(self._t)),
                "velocity": np.full((2, 2), 0.5)}

    def reset(self):
        self._t = 0
        return _FakeTimestep(self._obs(), None, 1.0, False)

    def step(self, action):
        self.actions.append(np.asarray(action))
        self._t += 1
        last = self._t >= self._horizon
        return _FakeTimestep(
            self._obs(), 0.25,
            self._terminal_discount if last else 1.0, last)


@pytest.fixture
def fake_dm(monkeypatch):
    made = {}

    def load(domain, task, task_kwargs=None):
        env = _FakeDMEnv(made.pop("terminal_discount", 0.0))
        made["env"] = env
        made["args"] = (domain, task, task_kwargs)
        return env

    suite = types.SimpleNamespace(load=load)
    monkeypatch.setattr(control, "suite", suite, raising=False)
    monkeypatch.setattr(control, "HAVE_DM_CONTROL", True)
    return made


def test_dm_control_adapter_contract(fake_dm):
    env = make_env(EnvConfig(id="humanoid_stand", kind="control"), seed=11)
    assert isinstance(env, control.DMControlAdapter)
    domain, task, kwargs = fake_dm["args"]
    assert (domain, task) == ("humanoid", "stand")
    assert kwargs == {"random": 11}
    # observation flattening: 3 + 2*2 = 7 dims
    assert env.spec.obs_shape == (7,)
    assert env.spec.action_dim == 2
    assert env.spec.action_low == -1.0 and env.spec.action_high == 1.0
    obs = env.reset()
    assert obs.shape == (7,) and obs.dtype == np.float32
    np.testing.assert_allclose(obs, [0, 0, 0, 0.5, 0.5, 0.5, 0.5])
    obs, r, done, info = env.step(np.array([0.3, -0.3]))
    assert r == 0.25 and not done
    np.testing.assert_allclose(fake_dm["env"].actions[0], [0.3, -0.3])
    # run to the terminal: discount 0.0 at last() -> terminal=True
    for _ in range(10):
        obs, r, done, info = env.step(np.zeros(2))
        if done:
            break
    assert done and info["terminal"] is True
    assert info["episode_return"] == pytest.approx(0.25 * 8)


def test_dm_control_time_limit_is_not_terminal(fake_dm):
    """last() with discount 1.0 is a time limit: done but NOT terminal
    (the n-step builder bootstraps through it)."""
    fake_dm["terminal_discount"] = 1.0
    env = make_env(EnvConfig(id="cartpole_swingup", kind="control"), seed=0)
    env.reset()
    done = False
    for _ in range(10):
        _, _, done, info = env.step(np.zeros(2))
        if done:
            break
    assert done and info["terminal"] is False
