"""Chaos lane (ISSUE 7): fault injection against the elastic fleet
runtime. Fast variants run tier-1 (seconds, loopback sockets, real
wire); the full soak is slow-marked.

What the lane proves, per fault family:
- learner loss mid-run: clients classify the drops, back off with
  jitter, reconnect to the NEW incarnation (epoch bump observed),
  and ingest resumes — the learner side never crashes;
- wire damage (garble/truncate/fuzz): every bad frame is an
  ATTRIBUTED counter (wire_decode_errors + on_decode_error hook),
  never an unhandled exception in a reader thread;
- wedged local actors: the driver's fleet supervisor restarts the
  slot within its budget, then quarantines — a restart storm
  degrades, it does not crash-loop;
- quiesce debounce: a fleet riding out a blip (clients in capped
  backoff) never reads as quiesced, because the backoff cap is
  pinned BELOW the server's idle grace.
"""

import inspect
import json
import pickle
import random
import socket as socket_mod
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.comm import socket_transport as st
from ape_x_dqn_tpu.comm.socket_transport import (
    MSG_EXPERIENCE, MSG_PARAMS_REQ, MSG_TELEMETRY,
    SocketIngestServer, SocketTransport, _recv_msg, _send_msg)
from ape_x_dqn_tpu.configs import CommConfig, ObsConfig
from tools.chaos import (ChaosProxy, CORRUPTION_MODES, ThreadWedge,
                         corrupt_frame, kill_process)
from tools.chaos.faults import frame as good_frame

PEER = "chaos-host-1-a0"


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {"obs": rng.random((n, 4)).astype(np.float32),
            "action": rng.integers(0, 2, (n,)).astype(np.int32),
            "priorities": (rng.random(n) + 0.1).astype(np.float32),
            "actor": 0, "frames": n}


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def _client(port, **kw):
    kw.setdefault("reconnect_base_s", 0.01)
    kw.setdefault("reconnect_cap_s", 0.2)
    return SocketTransport("127.0.0.1", port, **kw)


# -- fault primitives -------------------------------------------------------

def test_thread_wedge_blocks_and_releases():
    wedge = ThreadWedge()
    beats = []

    def worker():
        for i in range(1000):
            wedge.checkpoint(timeout=5.0)
            beats.append(i)
            if stop.is_set():
                return
            time.sleep(0.005)

    stop = threading.Event()
    t = threading.Thread(target=worker, daemon=True)
    t.start()
    assert _wait(lambda: len(beats) >= 3)
    wedge.engage()
    time.sleep(0.05)
    n = len(beats)
    time.sleep(0.2)
    assert len(beats) <= n + 1  # silent while engaged
    assert wedge.engaged
    wedge.release()
    assert _wait(lambda: len(beats) > n + 1)  # resumed, not dead
    stop.set()
    t.join(timeout=2)


def test_kill_process_tolerates_already_dead():
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait(timeout=10)
    kill_process(proc)  # reaped: must not raise
    kill_process(None)


def test_corrupt_frame_modes_all_differ_from_good():
    rng = random.Random(7)
    good = good_frame(MSG_EXPERIENCE, b"payload-bytes" * 4)
    for mode in CORRUPTION_MODES:
        bad = corrupt_frame(MSG_EXPERIENCE, b"payload-bytes" * 4,
                            mode, rng)
        assert bad != good, mode
    with pytest.raises(ValueError):
        corrupt_frame(MSG_EXPERIENCE, b"x", "no-such-mode")


# -- learner loss: reconnect, epoch, drop classification --------------------

def test_server_restart_reconnect_and_epoch_bump():
    """The headline fault: the learner dies mid-run and a NEW
    incarnation binds the same port. Clients classify the outage
    drops, reconnect under backoff, observe the epoch change, and
    ingest resumes — no client-side exception escapes."""
    srv1 = SocketIngestServer("127.0.0.1", 0, epoch=1)
    port = srv1.port
    client = _client(port)
    try:
        client.send_experience(_batch())
        assert srv1.recv_experience(timeout=5.0) is not None
        assert client.epoch == 1 and client.epoch_changes == 0
        srv1.stop()

        for i in range(6):  # outage: drops classified, never raised
            client.send_experience(_batch(seed=i))
            time.sleep(0.02)
        assert client.dropped >= 1
        assert sum(client.drop_reasons.values()) == client.dropped

        srv2 = SocketIngestServer("127.0.0.1", port, epoch=2)
        try:
            got = None

            def resumed():
                nonlocal got
                client.send_experience(_batch())
                got = srv2.recv_experience(timeout=0.2)
                return got is not None

            assert _wait(resumed), "ingest never resumed after restart"
            assert client.reconnects >= 1
            assert client.reconnect_latencies  # outage length sampled
            assert client.epoch == 2 and client.epoch_changes == 1
        finally:
            srv2.stop()
    finally:
        client.close()


def test_sends_during_backoff_drop_as_backpressure():
    srv = SocketIngestServer("127.0.0.1", 0, epoch=1)
    port = srv.port
    # long cap: after the first failure the backoff window is open for
    # the whole test, so the second send must take the cheap gate
    client = _client(port, reconnect_base_s=5.0, reconnect_cap_s=10.0)
    try:
        client.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        srv.stop()

        def hard_drop():
            # the first post-mortem send can land in the socket buffer
            # before the RST arrives — keep sending until one faults
            client.send_experience(_batch())
            r = client.drop_reasons
            return (r["reset"] + r["refused"] + r["timeout"]
                    + r["other"] >= 1)

        assert _wait(hard_drop, timeout=3.0)
        client.send_experience(_batch())  # backoff window: backpressure
        assert client.drop_reasons["backpressure"] >= 1
        assert sum(client.drop_reasons.values()) == client.dropped
    finally:
        client.close()


def test_proxy_cut_forces_reconnect():
    srv = SocketIngestServer("127.0.0.1", 0, epoch=3)
    proxy = ChaosProxy("127.0.0.1", srv.port)
    client = _client(proxy.port)
    try:
        client.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        assert proxy.cut() >= 2
        assert _wait(lambda: (client.send_experience(_batch()),
                              client.reconnects >= 1)[1])
        # same incarnation behind the blip: NO epoch change
        assert client.epoch == 3 and client.epoch_changes == 0
    finally:
        client.close()
        proxy.stop()
        srv.stop()


# -- versioned param plane --------------------------------------------------

def test_conditional_param_pull_cycle():
    """Full pull -> header-only 'unchanged' -> full on new version ->
    forced full on epoch bump (version counters restart across
    incarnations, so the epoch keys the update)."""
    srv = SocketIngestServer("127.0.0.1", 0, epoch=5)
    client = _client(srv.port)
    try:
        srv.publish_params({"w": 0}, 0)
        p, v = client.get_params()
        assert p == {"w": 0} and v == 0
        p, v = client.get_params()  # nothing new: header-only reply
        assert p is None and v == 0
        assert client.param_unchanged >= 1

        srv.publish_params({"w": 1}, 1)
        p, v = client.get_params()
        assert p == {"w": 1} and v == 1

        srv.bump_epoch()  # "new incarnation" without the restart
        p, v = client.get_params()  # epoch mismatch: full reply again
        assert p == {"w": 1} and v == 1
        assert client.epoch_changes == 1
        assert client.param_epoch == 6
    finally:
        client.close()
        srv.stop()


def test_params_push_delivery():
    srv = SocketIngestServer("127.0.0.1", 0, epoch=9)
    client = _client(srv.port, params_push=True)
    try:
        client.send_experience(_batch())  # connect + negotiate
        assert srv.recv_experience(timeout=5.0) is not None
        assert client.params_push_negotiated
        assert srv.push_subscribers == 1
        srv.publish_params({"w": 2}, 3)
        assert _wait(lambda: client.param_pushes_in >= 1)
        p, v = client.poll_pushed_params()
        assert p == {"w": 2} and v == 3
        p, v = client.poll_pushed_params()  # consumed
        assert p is None and v == -1
        assert srv.param_pushes >= 1
    finally:
        client.close()
        srv.stop()


def test_pending_publish_does_not_preempt_hello_ack():
    """A publish already pending at connect time must not let the push
    thread win the conn's send lock and ship MSG_PARAMS_PUSH as the
    connection's FIRST frame: the client reads the first frame as the
    hello ack, so a push there silently degrades negotiation to raw
    and leaves the server pushing blobs nobody drains. The server
    therefore sends the ack BEFORE subscribing the conn — and the late
    subscriber still receives the pending publish."""
    srv = SocketIngestServer("127.0.0.1", 0, epoch=11)
    srv.publish_params({"w": 5}, 1)  # push pending before any connect
    ack_saw_sub = []
    real_send_on = srv._send_on

    def spy(conn, mtype, payload):
        if mtype == st.MSG_HELLO_ACK:
            with srv._conns_lock:
                ack_saw_sub.append(id(conn) in srv._push_subs)
        return real_send_on(conn, mtype, payload)

    srv._send_on = spy
    client = _client(srv.port, params_push=True)
    try:
        client.send_experience(_batch())  # connect + negotiate
        assert srv.recv_experience(timeout=5.0) is not None
        assert client.params_push_negotiated  # ack was the first frame
        assert ack_saw_sub == [False]  # subscribed only after the ack
        # the pending publish still reaches the late subscriber
        assert _wait(lambda: client.param_pushes_in >= 1)
        p, v = client.poll_pushed_params()
        assert p == {"w": 5} and v == 1
    finally:
        client.close()
        srv.stop()


def test_pull_failure_bumps_param_pull_errors():
    srv = SocketIngestServer("127.0.0.1", 0)
    port = srv.port
    srv.publish_params({"w": 0}, 0)
    client = _client(port)
    try:
        p, _ = client.get_params()
        assert p == {"w": 0}
        srv.stop()
        p, v = client.get_params()  # learner gone: error, not raise
        assert p is None and v == -1
        assert client.param_pull_errors >= 1
    finally:
        client.close()


# -- wire damage: attributed, never fatal -----------------------------------

def test_garbled_frame_counted_and_attributed():
    srv = SocketIngestServer("127.0.0.1", 0)
    seen = []
    srv.on_decode_error = lambda peer, reason: seen.append((peer, reason))
    sock = socket_mod.create_connection(("127.0.0.1", srv.port))
    try:
        # identify the connection first (telemetry names the peer),
        # then damage it: the decode error must carry the peer name
        _send_msg(sock, MSG_TELEMETRY,
                  json.dumps({"peer": PEER, "seq": 0}).encode())
        assert _wait(lambda: srv.telemetry_frames >= 1)
        sock.sendall(corrupt_frame(MSG_EXPERIENCE, b"x" * 64, "bad-crc"))
        assert _wait(lambda: srv.wire_decode_errors >= 1)
        assert seen and seen[0][0] == PEER
        assert "checksum" in seen[0][1]
    finally:
        sock.close()
        srv.stop()


def test_unidentified_peer_decode_error_attribution():
    srv = SocketIngestServer("127.0.0.1", 0)
    seen = []
    srv.on_decode_error = lambda peer, reason: seen.append((peer, reason))
    sock = socket_mod.create_connection(("127.0.0.1", srv.port))
    try:
        sock.sendall(corrupt_frame(MSG_EXPERIENCE, b"x" * 64,
                                   "bad-magic"))
        assert _wait(lambda: srv.wire_decode_errors >= 1)
        assert seen and seen[0][0] == "unidentified"
    finally:
        sock.close()
        srv.stop()


def test_fuzzed_frames_never_crash_server():
    """~50 corrupted frames across every corruption mode, then a clean
    client proves the server still serves: damage costs connections
    and counters, never the process."""
    srv = SocketIngestServer("127.0.0.1", 0, epoch=1)
    rng = random.Random(1234)
    payloads = [b"", b"\x00" * 7, b"garbage" * 19,
                pickle.dumps({"not": "a batch"})]
    try:
        for i in range(50):
            mode = CORRUPTION_MODES[i % len(CORRUPTION_MODES)]
            mtype = rng.choice([MSG_EXPERIENCE, MSG_PARAMS_REQ,
                                MSG_TELEMETRY, 0, 255])
            data = corrupt_frame(mtype, rng.choice(payloads), mode, rng)
            sock = socket_mod.create_connection(("127.0.0.1", srv.port))
            try:
                sock.sendall(data)
            finally:
                sock.close()
        # raw junk that is not even a frame
        sock = socket_mod.create_connection(("127.0.0.1", srv.port))
        sock.sendall(bytes(rng.randrange(256) for _ in range(333)))
        sock.close()

        client = _client(srv.port)
        try:
            client.send_experience(_batch())
            assert srv.recv_experience(timeout=5.0) is not None
        finally:
            client.close()
        assert srv.wire_decode_errors >= 1
    finally:
        srv.stop()


# -- quiesce debounce vs the reconnect loop ---------------------------------

def test_quiesced_debounce_and_ever_connected():
    srv = SocketIngestServer("127.0.0.1", 0, idle_grace_s=0.3)
    try:
        # never-connected server is quiesced (boot grace is the
        # driver's job, keyed on ever_connected)
        assert not srv.ever_connected
        assert srv.quiesced()

        client = _client(srv.port)
        client.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        assert srv.ever_connected
        assert not srv.quiesced()  # live producer

        client.close()  # the blip
        assert _wait(lambda: srv.active_connections == 0)
        # inside the grace window a vanished producer is NOT quiesced
        assert not srv.quiesced()
        assert _wait(lambda: srv.quiesced(), timeout=2.0)  # grace over
        assert srv.ever_connected  # latched for good
    finally:
        srv.stop()


def test_param_probe_does_not_latch_ever_connected():
    srv = SocketIngestServer("127.0.0.1", 0)
    client = _client(srv.port)
    try:
        srv.publish_params({"w": 0}, 0)
        client.get_params()  # param-only probe: not a producer
        assert not srv.ever_connected
        client.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        assert srv.ever_connected
    finally:
        client.close()
        srv.stop()


def test_reconnect_cap_pinned_below_idle_grace():
    """INVARIANT (socket_transport.quiesced docstring): a client's
    backoff cap must stay below the server's idle grace, so a fleet
    riding out a blip reconnects inside the grace window its own
    disconnect opened and the server never reads quiesced mid-blip."""
    cap = CommConfig().reconnect_cap_s
    grace = inspect.signature(
        SocketIngestServer.__init__).parameters["idle_grace_s"].default
    client_cap = inspect.signature(
        SocketTransport.__init__).parameters["reconnect_cap_s"].default
    assert cap < grace, (cap, grace)
    assert client_cap < grace, (client_cap, grace)
    assert cap == client_cap  # config default mirrors the transport


def test_backing_off_fleet_does_not_quiesce_server():
    """Clients in capped backoff behind a cut link re-enter within one
    cap interval: the server side sees the reconnect before the grace
    expires and never reports quiesced during the blip."""
    srv = SocketIngestServer("127.0.0.1", 0, idle_grace_s=1.5)
    proxy = ChaosProxy("127.0.0.1", srv.port)
    client = _client(proxy.port, reconnect_base_s=0.01,
                     reconnect_cap_s=0.2)  # cap << grace
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            client.send_experience(_batch())
            time.sleep(0.02)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        assert srv.recv_experience(timeout=5.0) is not None
        quiesced_seen = False
        proxy.cut()
        deadline = time.monotonic() + 1.0  # blip < grace
        while time.monotonic() < deadline:
            quiesced_seen = quiesced_seen or srv.quiesced()
            time.sleep(0.02)
        assert not quiesced_seen, \
            "server read quiesced while the fleet was mid-backoff"
        assert _wait(lambda: srv.recv_experience(timeout=0.2)
                     is not None), "ingest never resumed"
    finally:
        stop.set()
        t.join(timeout=2)
        client.close()
        proxy.stop()
        srv.stop()


# -- driver fleet supervisor ------------------------------------------------

@pytest.fixture(scope="module")
def supervised_driver():
    from ape_x_dqn_tpu.configs import (
        ActorConfig, InferenceConfig, LearnerConfig, ReplayConfig,
        get_config)
    from ape_x_dqn_tpu.runtime.driver import ApexDriver
    cfg = get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=2, ingest_batch=16,
                           supervise=True, supervisor_max_restarts=2),
        replay=ReplayConfig(kind="prioritized", capacity=1024,
                            min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        obs=ObsConfig(enabled=True, heartbeat_timeout_s=0.3),
        eval_every_steps=0, eval_episodes=0)
    driver = ApexDriver(cfg)
    yield driver
    driver.obs.close()


def _age_heartbeat(driver, name, keep_alive=True):
    """Let `name`'s heartbeat go stale past the watchdog timeout while
    keeping every OTHER registered component fresh (the tick must not
    trip over e.g. an idle inference-server heartbeat)."""
    driver.obs.register(name)
    time.sleep(driver.obs.watchdog.timeout_s + 0.15)
    if keep_alive:
        for other in list(driver.obs.heartbeats.ages()):
            if other != name:
                driver.obs.beat(other, "test keep-alive")


def test_supervisor_restarts_wedged_actor_slot(supervised_driver):
    driver = supervised_driver
    spawned = []
    real_spawn = driver._spawn_actor_slot
    driver._spawn_actor_slot = \
        lambda i, f, attempt0=0: spawned.append((i, f, attempt0))
    try:
        driver._slot_budget[0] = 640
        before = driver.obs.registry.counter("supervisor_restarts").value
        _age_heartbeat(driver, "actor-0")
        driver._supervise_tick()  # must NOT raise: restart instead
        assert spawned == [(0, 640, 101)]
        assert driver._slot_restarts[0] == 1
        assert driver.obs.registry.counter(
            "supervisor_restarts").value == before + 1
        # the re-armed heartbeat keeps the next immediate tick green
        driver._supervise_tick()
        assert len(spawned) == 1
    finally:
        driver._spawn_actor_slot = real_spawn
        driver.obs.clear("actor-0")


def test_supervisor_quarantines_after_restart_budget(supervised_driver):
    driver = supervised_driver
    spawned = []
    real_spawn = driver._spawn_actor_slot
    driver._spawn_actor_slot = \
        lambda i, f, attempt0=0: spawned.append((i, f))
    try:
        driver._slot_restarts[1] = \
            driver.cfg.actors.supervisor_max_restarts  # budget burned
        before = driver.obs.registry.counter("actor_quarantines").value
        _age_heartbeat(driver, "actor-1")
        driver._supervise_tick()  # exhausted: quarantine, not restart
        assert spawned == []
        assert 1 in driver._quarantined
        assert driver.obs.registry.counter(
            "actor_quarantines").value == before + 1
        assert "actor-1" not in driver.obs.heartbeats.ages()  # cleared
        driver._supervise_tick()  # idempotent: stays quarantined
        assert driver.obs.registry.counter(
            "actor_quarantines").value == before + 1
    finally:
        driver._spawn_actor_slot = real_spawn


def test_quarantine_releases_slot_liveness(supervised_driver):
    """Quarantining a wedged slot must also drop its thread from the
    liveness bookkeeping: run()'s drain check is any(is_alive) over
    _slot_threads, and a wedged thread never finishes — left in the
    dict it would turn the documented degraded-but-terminating path
    into an unattributed infinite hang (the quarantine already cleared
    the heartbeat, so check_stalled can't fire either)."""
    driver = supervised_driver
    wedged_stop = threading.Event()
    t = threading.Thread(target=wedged_stop.wait, daemon=True)
    t.start()
    driver._slot_threads[0] = t
    driver._slot_stops[0] = wedged_stop
    driver._slot_restarts[0] = \
        driver.cfg.actors.supervisor_max_restarts  # budget burned
    try:
        _age_heartbeat(driver, "actor-0")
        driver._supervise_tick()
        assert 0 in driver._quarantined
        # the wedged thread no longer counts toward the drain check
        assert 0 not in driver._slot_threads
        assert 0 not in driver._slot_stops
        assert not any(th.is_alive() for th in driver._actor_threads()
                       if th is t)
        # its generation event was set so it exits if it ever un-wedges
        assert wedged_stop.is_set()
        t.join(timeout=5)
        assert not t.is_alive()
        # a late beat from the superseded thread must not let the
        # fallthrough check_stalled convert the quarantine to a raise
        _age_heartbeat(driver, "actor-0")
        driver._supervise_tick()
        assert "actor-0" not in driver.obs.heartbeats.ages()
    finally:
        wedged_stop.set()
        driver.obs.clear("actor-0")
        driver._quarantined.discard(0)
        driver._slot_restarts.pop(0, None)


def test_supervisor_budget_counts_prior_attempts(supervised_driver):
    """The remaining-budget estimate must subtract frames from EVERY
    attempt of the slot's generation (_slot_done accumulates finished
    crash-restart attempts), not just the wedged current attempt —
    else a supervised restart over-produces frames."""
    driver = supervised_driver

    class _FakeActor:
        frames = 40

    spawned = []
    real_spawn = driver._spawn_actor_slot
    driver._spawn_actor_slot = \
        lambda i, f, attempt0=0: spawned.append((i, f))
    try:
        driver._slot_restarts.pop(0, None)
        driver._slot_budget[0] = 640
        driver._slot_done[0] = 100  # earlier crash-restart attempts
        driver._slot_actor_obj[0] = _FakeActor()
        _age_heartbeat(driver, "actor-0")
        driver._supervise_tick()
        assert spawned == [(0, 640 - 100 - 40)]
    finally:
        driver._spawn_actor_slot = real_spawn
        driver._slot_done.pop(0, None)
        driver._slot_restarts.pop(0, None)
        driver.obs.clear("actor-0")


def test_supervisor_quarantines_stalled_remote_peer(supervised_driver):
    driver = supervised_driver
    peer = f"{PEER}/actor-7"
    before = driver.obs.registry.counter("peer_stall_events").value
    _age_heartbeat(driver, peer)
    driver._supervise_tick()  # remote: count + clear, never raise
    assert driver.obs.registry.counter(
        "peer_stall_events").value == before + 1
    assert peer not in driver.obs.heartbeats.ages()


def test_supervisor_still_raises_for_fatal_local(supervised_driver):
    from ape_x_dqn_tpu.obs.health import StallError
    driver = supervised_driver
    _age_heartbeat(driver, "learner")
    try:
        with pytest.raises(StallError) as ei:
            driver._supervise_tick()
        assert ei.value.component == "learner"
    finally:
        driver.obs.clear("learner")


# -- interop: the chaos harness itself --------------------------------------

def test_chaos_proxy_stats_and_runtime_fault_swap():
    srv = SocketIngestServer("127.0.0.1", 0)
    proxy = ChaosProxy("127.0.0.1", srv.port, seed=3)
    client = _client(proxy.port)
    try:
        client.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        assert proxy.stats["connections"] >= 1
        assert proxy.stats["garbled"] == 0
        proxy.set_fault(garble_rate=1.0)
        for i in range(10):
            client.send_experience(_batch(seed=i))
            time.sleep(0.01)
        assert _wait(lambda: proxy.stats["garbled"] >= 1)
        assert _wait(lambda: srv.wire_decode_errors >= 1)
        proxy.clean()
        assert _wait(lambda: (client.send_experience(_batch()),
                              srv.recv_experience(timeout=0.2)
                              is not None)[1])
    finally:
        client.close()
        proxy.stop()
        srv.stop()


# -- the full soak (slow) ---------------------------------------------------

@pytest.mark.slow
def test_chaos_soak_learner_restart_and_wire_faults():
    """The acceptance soak: a small fleet of sender threads pushes
    through a chaos proxy while the learner is killed and restarted
    (new epoch, same port) and the link degrades through garble and
    cut phases. Afterwards: ingest resumed on the new incarnation,
    every client re-converged to the live epoch, faults are
    attributed, and neither incarnation's server ever crashed."""
    srv = SocketIngestServer("127.0.0.1", 0, epoch=1, idle_grace_s=5.0)
    port = srv.port
    upstream_port = srv.port
    proxy = ChaosProxy("127.0.0.1", upstream_port, seed=11)
    srv.publish_params({"w": 0}, 0)

    n_clients = 3
    clients = [_client(proxy.port, reconnect_base_s=0.01,
                       reconnect_cap_s=0.3) for _ in range(n_clients)]
    stop = threading.Event()
    errors: list[BaseException] = []
    received = [0]
    received_lock = threading.Lock()

    def pump(c, k):
        i = 0
        while not stop.is_set():
            try:
                c.send_experience(_batch(seed=(k * 1000 + i) % 97))
                c.get_params()
            except BaseException as e:  # noqa: BLE001 - soak invariant
                errors.append(e)
                return
            i += 1
            time.sleep(0.01)

    threads = [threading.Thread(target=pump, args=(c, k), daemon=True)
               for k, c in enumerate(clients)]
    for t in threads:
        t.start()

    def drain(server, seconds):
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            if server.recv_experience(timeout=0.1) is not None:
                with received_lock:
                    received[0] += 1

    try:
        drain(srv, 1.0)
        with received_lock:
            assert received[0] > 0

        proxy.set_fault(garble_rate=0.05)  # degraded-link phase
        drain(srv, 1.0)

        proxy.clean()
        srv.stop()  # the learner dies mid-run
        time.sleep(0.5)  # clients ride the outage in backoff
        srv2 = SocketIngestServer("127.0.0.1", port, epoch=2,
                                  idle_grace_s=5.0)
        srv2.publish_params({"w": 1}, 0)
        with received_lock:
            received[0] = 0
        drain(srv2, 2.0)
        with received_lock:
            assert received[0] > 0, "ingest never resumed post-restart"

        proxy.cut()  # one more blip against the new incarnation
        drain(srv2, 1.0)

        assert errors == [], errors  # no client thread ever raised
        for c in clients:
            assert c.reconnects >= 1
            assert _wait(lambda: (c.get_params(),
                                  c.epoch == 2)[1]), \
                f"client never converged to live epoch: {c.epoch}"
            assert c.epoch_changes >= 1
            assert sum(c.drop_reasons.values()) == c.dropped
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=2)
        for c in clients:
            c.close()
        proxy.stop()
        try:
            srv2.stop()
        except NameError:
            pass
