"""Multi-host learner (parallel/multihost.py + runtime/multihost_driver
.py): two REAL OS processes form a global 8-device mesh over the JAX
distributed runtime (Gloo as the DCN stand-in on CPU) and train in SPMD
lockstep — the NCCL/MPI process-group equivalent (SURVEY.md §5
"distributed communication backend")."""

import functools
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_PROBE = textwrap.dedent("""\
    import sys
    import jax
    jax.distributed.initialize(coordinator_address=sys.argv[1],
                               num_processes=2,
                               process_id=int(sys.argv[2]))
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("probe")
""")


@functools.cache
def _two_process_supported() -> bool:
    """Probe whether this jax build can actually form a two-process
    Gloo group on the CPU backend (some wheels ship without the
    distributed CPU collectives; the real tests would then fail on
    environment grounds, not code grounds). One cached probe per
    pytest process: two tiny subprocesses initialize + barrier."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE, f"127.0.0.1:{port}", str(pid)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for pid in range(2)]
    try:
        return all(p.wait(timeout=120) == 0 for p in procs)
    except subprocess.TimeoutExpired:
        return False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def _require_two_process():
    if not _two_process_supported():
        pytest.skip("two-process jax.distributed group unsupported on "
                    "this host's CPU backend (probe failed)")


_SETS = [
    "parallel.dp=8", "parallel.tp=1",
    "replay.kind=prioritized", "replay.capacity=4096",
    "replay.min_fill=64",
    "learner.batch_size=32", "learner.n_step=3",
    "learner.target_sync_every=100", "learner.publish_every=10",
    "learner.train_chunk=2",
    # envs_per_actor=2 routes the multihost local-actor path through
    # the vectorized actor (one query_batch per vector step)
    "actors.num_actors=1", "actors.base_eps=0.6", "actors.ingest_batch=8",
    "actors.envs_per_actor=2",
    "inference.max_batch=8", "inference.deadline_ms=1.0",
    "eval_every_steps=0", "eval_episodes=0",
]


def _launch(port, pid, extra, config="cartpole_smoke", sets=_SETS):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # 4 local devices per process -> dp=8 rows across two processes
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    return subprocess.Popen(
        [sys.executable, "-m", "ape_x_dqn_tpu.runtime.train",
         "--config", config,
         "--coordinator", f"127.0.0.1:{port}",
         "--num-processes", "2", "--process-id", str(pid)]
        + [a for s in sets for a in ("--set", s)]
        + extra,  # after sets: later --set wins
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_frame_budget_terminates_when_total_unreachable():
    """Per-actor budget truncation (1001 frames / 2 procs / 3 actors ->
    at most 996 produced) must not hang the frame-budget round loop:
    the all-hosts-idle check breaks it (regression: frames_global could
    never reach `total` and every process spun forever)."""
    _require_two_process()
    port = _free_port()
    procs = [_launch(port, pid,
                     ["--total-env-frames", "1001",
                      "--set", "actors.num_actors=3"])
             for pid in range(2)]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=300)
        assert p.returncode == 0, stderr[-3000:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))
    # per-actor truncation: 1001 // 2 procs // 3 actors = 166 each
    assert outs[0]["frames"] == outs[1]["frames"] <= 996
    assert outs[0]["frames"] > 0


def test_stall_watchdog_fires_and_aborts():
    """StallWatchdog (round-2 verdict weak #8): silence past the
    timeout emits a diagnostic naming the process; two consecutive
    silent windows invoke the fatal action; stamps reset strikes."""
    import time as _time

    from ape_x_dqn_tpu.runtime.multihost_driver import StallWatchdog

    events, codes = [], []
    wd = StallWatchdog(1.2, describe=lambda: "state-snapshot",
                       fatal=codes.append, emit=events.append)
    wd.start()
    try:
        # keep stamping well inside the window: must never fire (wide
        # margins — this box runs tests under heavy contention)
        for _ in range(4):
            _time.sleep(0.2)
            wd.stamp()
        assert events == [] and codes == []
        # go silent: strike 1 (diagnostic), then strike 2 (fatal)
        deadline = _time.monotonic() + 5
        while len(codes) == 0 and _time.monotonic() < deadline:
            _time.sleep(0.05)
        assert len(events) >= 2, events
        assert "state-snapshot" in events[0]
        assert "no round progress" in events[0]
        assert codes == [70], codes
    finally:
        wd.stop()


def test_stall_watchdog_disabled_at_zero():
    from ape_x_dqn_tpu.runtime.multihost_driver import StallWatchdog

    wd = StallWatchdog(0.0, describe=lambda: "",
                       fatal=lambda c: None, emit=lambda m: None)
    wd.start()  # must not start a thread
    assert not wd._thread.is_alive()
    wd.stop()


def test_stall_watchdog_stop_joins_thread():
    """Regression (apexlint v3 thread-lifecycle sweep): stop() must
    JOIN the watch thread, not just set the event — a watcher still
    running after stop() returns can fire a spurious diagnostic (or
    the fatal) into interpreter teardown."""
    from ape_x_dqn_tpu.runtime.multihost_driver import StallWatchdog

    wd = StallWatchdog(30.0, describe=lambda: "",
                       fatal=lambda c: None, emit=lambda m: None)
    wd.start()
    assert wd._thread.is_alive()
    wd.stop()
    assert not wd._thread.is_alive()


def test_multihost_steps_per_frame_cap_binds():
    """learner.steps_per_frame_cap must pace the lockstep learner to
    the GLOBAL frame count (and the fleet must still terminate when the
    cap binds forever after actors finish)."""
    _require_two_process()
    cap = 0.05
    port = _free_port()
    procs = [_launch(port, pid,
                     ["--total-env-frames", "800",
                      "--set", f"learner.steps_per_frame_cap={cap}"])
             for pid in range(2)]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=300)
        assert p.returncode == 0, stderr[-3000:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))
    assert outs[0]["grad_steps"] == outs[1]["grad_steps"]
    assert outs[0]["grad_steps"] > 0, outs
    # pacing rechecks before each <= train_chunk dispatch
    assert outs[0]["grad_steps"] <= cap * outs[0]["frames"] + 2, outs


def test_two_process_lockstep_training(tmp_path):
    _require_two_process()
    port = _free_port()
    procs = [_launch(port, pid,
                     ["--total-env-frames", "1600",
                      "--max-grad-steps", "20",
                      "--metrics-file", str(tmp_path / f"m{pid}.jsonl"),
                      # eval on process 0 (host-local, collective-free)
                      "--set", "eval_every_steps=5",
                      "--set", "eval_episodes=1"])
             for pid in range(2)]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=540)
        assert p.returncode == 0, stderr[-3000:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))
    for out in outs:
        assert out["grad_steps"] >= 20, out
        assert out["actor_errors"] == [], out
        assert out["frames"] > 0
        assert out["replay_filled"] >= 64
    # lockstep invariants: global quantities agree across processes,
    # and the final loss (computed from the same global batch) matches
    assert outs[0]["grad_steps"] == outs[1]["grad_steps"]
    assert outs[0]["frames"] == outs[1]["frames"]
    assert outs[0]["loss"] == pytest.approx(outs[1]["loss"], rel=1e-5)
    # both hosts actually contributed experience
    assert outs[0]["frames_local"] > 0 and outs[1]["frames_local"] > 0
    # eval ran on process 0 only, without perturbing the lockstep (the
    # grad_steps/frames/loss agreement above IS the non-perturbation
    # check), and its record carries a real return
    assert outs[0]["eval_error"] is None, outs[0]
    assert outs[0]["eval"] is not None and \
        outs[0]["eval"]["episodes"] >= 1, outs[0]
    assert outs[1]["eval"] is None, outs[1]
    # per-round metrics stream to --metrics-file (publish cadence)
    for pid in range(2):
        lines = (tmp_path / f"m{pid}.jsonl").read_text().splitlines()
        recs = [json.loads(ln) for ln in lines]
        assert any("loss" in r for r in recs), recs


_R2D2_SETS = [
    "parallel.dp=8", "parallel.tp=1",
    "env.id=CartPolePO", "env.kind=cartpole_po",
    "network.lstm_size=32", "network.torso_dense=64",
    "network.compute_dtype=float32",
    "replay.capacity=512", "replay.seq_length=16", "replay.seq_overlap=8",
    "replay.burn_in=4", "replay.min_fill=16", "replay.storage=flat",
    "learner.batch_size=16", "learner.n_step=3", "learner.lr=1e-3",
    "learner.target_sync_every=100", "learner.publish_every=10",
    "learner.train_chunk=2",
    # envs_per_actor=2 routes through RecurrentVectorActor
    "actors.num_actors=1", "actors.base_eps=0.4", "actors.ingest_batch=64",
    "actors.envs_per_actor=2",
    "inference.max_batch=8", "inference.deadline_ms=1.0",
    "eval_every_steps=0", "eval_episodes=0",
]


def test_two_process_lockstep_r2d2():
    """R2D2 over the lockstep round loop: two OS processes, sequence
    replay shards + the LSTM sequence loss on one global 8-device mesh,
    recurrent actors querying stateful {obs,c,h} inference."""
    _require_two_process()
    port = _free_port()
    procs = [_launch(port, pid,
                     ["--total-env-frames", "2400",
                      "--max-grad-steps", "10"],
                     config="r2d2", sets=_R2D2_SETS)
             for pid in range(2)]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=540)
        assert p.returncode == 0, stderr[-3000:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))
    for out in outs:
        assert out["grad_steps"] >= 10, out
        assert out["actor_errors"] == [], out
        assert out["frames"] > 0
    # lockstep invariants hold for the sequence learner too
    assert outs[0]["grad_steps"] == outs[1]["grad_steps"]
    assert outs[0]["frames"] == outs[1]["frames"]
    assert outs[0]["loss"] == pytest.approx(outs[1]["loss"], rel=1e-5)
    assert outs[0]["frames_local"] > 0 and outs[1]["frames_local"] > 0


def test_multihost_checkpoint_resume(tmp_path):
    """Checkpoint/resume over the lockstep loop: run 1 trains 20 steps
    into a shared checkpoint dir (collective gather, process-0 write);
    run 2 restores on construction (min-agreement on the step) and
    continues the grad-step counter to a higher target."""
    _require_two_process()
    ckpt = str(tmp_path / "ckpt")
    extra = ["--total-env-frames", "100000", "--checkpoint-dir", ckpt]
    port = _free_port()
    procs = [_launch(port, pid, extra + ["--max-grad-steps", "20"])
             for pid in range(2)]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=420)
        assert p.returncode == 0, stderr[-3000:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))
    assert outs[0]["grad_steps"] == outs[1]["grad_steps"] == 20
    assert outs[0]["restored_step"] is None  # run 1 started fresh

    port = _free_port()
    procs = [_launch(port, pid, extra + ["--max-grad-steps", "30"])
             for pid in range(2)]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=420)
        assert p.returncode == 0, stderr[-3000:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))
    # resumed at 20 (marker proves restore actually fired, not a
    # silent fresh 0->30 run), trained on to 30, in lockstep
    assert outs[0]["restored_step"] == outs[1]["restored_step"] == 20
    assert outs[0]["grad_steps"] == outs[1]["grad_steps"] == 30
    assert outs[0]["loss"] == pytest.approx(outs[1]["loss"], rel=1e-5)
