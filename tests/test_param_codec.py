"""Param-plane codec (comm/param_codec.py, ISSUE 19): delta+q8 chain
encode/decode, never-inflate floors, resync-on-missed-version and
epoch-bump semantics, old<->new interop in both directions, the raw
escape hatch's bitwise compatibility, cross-impl quantizer bit-parity
(a wire contract — native kernel vs numpy fallback), per-subscriber
fan-out isolation, and the cross-plane consistency of the one
versioned-blob provider (legacy blob == APXV reply == coded full ==
local get_params)."""

import json
import pickle
import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.comm import native
from ape_x_dqn_tpu.comm import socket_transport as st
from ape_x_dqn_tpu.comm.param_codec import (
    _CODEC_HDR, _PARAMS_HDR, PARAMS_CODEC_MAGIC, PARAMS_HDR_MAGIC,
    ParamBlobProvider, ParamChainDecoder, check_param_codec)
from ape_x_dqn_tpu.comm.socket_transport import (
    MSG_PARAMS_REQ, SocketIngestServer, SocketTransport)


def _bf16(x: np.ndarray) -> np.ndarray:
    import ml_dtypes
    return x.astype(ml_dtypes.bfloat16).astype(np.float32)


def _tree(rng, n=257):
    """Mixed tree: two f32 leaves, one int leaf (non-float path)."""
    return {"w": (rng.standard_normal((n,)) * 0.1).astype(np.float32),
            "k": {"b": (rng.standard_normal((7, 5)) * 0.1
                        ).astype(np.float32),
                  "steps": np.array([3], np.int64)}}


def _step(tree, rng):
    """Heavy-tailed f32 update; the int leaf stays put ("s" path)."""
    return {"w": (tree["w"] + 0.01 * rng.standard_normal(
        tree["w"].shape) ** 3).astype(np.float32),
        "k": {"b": (tree["k"]["b"] + 0.01 * rng.standard_normal(
            tree["k"]["b"].shape) ** 3).astype(np.float32),
        "steps": tree["k"]["steps"]}}


def _flat(tree):
    return [tree["w"], tree["k"]["b"], tree["k"]["steps"]]


def _max_err(a, b):
    return max(float(np.abs(x.astype(np.float64)
                            - y.astype(np.float64)).max())
               for x, y in zip(_flat(a), _flat(b)))


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def _client(port, **kw):
    kw.setdefault("connect_timeout", 5.0)
    return SocketTransport("127.0.0.1", port, **kw)


def _batch(n=4):
    return {"obs": np.zeros((n, 4), np.float32),
            "action": np.zeros((n,), np.int32),
            "priorities": np.ones((n,), np.float32),
            "actor": 0, "frames": n}


# -- provider/decoder units --------------------------------------------------


def test_check_param_codec_rejects_unknown():
    assert check_param_codec("raw") == "raw"
    assert check_param_codec("delta-q8") == "delta-q8"
    with pytest.raises(ValueError):
        check_param_codec("zstd")


def test_full_then_delta_roundtrip():
    rng = np.random.default_rng(0)
    t0 = _tree(rng)
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    decoder = ParamChainDecoder()
    provider.publish(t0, 0)
    payload, kind, ver, raw_cost = provider.coded_reply(7, -1, 7)
    assert kind in ("full", "raw_full") and ver == 0
    assert len(payload) <= raw_cost
    status, got, ver, ep = decoder.apply(payload)
    assert status == "full" and ver == 0 and ep == 7
    # a coded full is BITWISE the wire tree (bf16 roundtrip on f32,
    # exact on everything else) — same values the raw path delivers
    assert np.array_equal(got["w"], _bf16(t0["w"]))
    assert np.array_equal(got["k"]["b"], _bf16(t0["k"]["b"]))
    assert np.array_equal(got["k"]["steps"], t0["k"]["steps"])
    assert got["k"]["steps"].dtype == np.int64

    t1 = _step(t0, rng)
    provider.publish(t1, 1)
    payload, kind, ver, raw_cost = provider.coded_reply(7, 0, 7)
    assert kind == "delta" and ver == 1
    assert len(payload) < raw_cost  # the point of the codec
    status, got, ver, _ = decoder.apply(payload)
    assert status == "full" and ver == 1
    # delta error: half a quantization step plus bf16 rounding
    assert _max_err(got, {"w": _bf16(t1["w"]),
                          "k": {"b": _bf16(t1["k"]["b"]),
                                "steps": t1["k"]["steps"]}}) < 4e-3
    assert np.array_equal(got["k"]["steps"], t1["k"]["steps"])


def test_delta_error_does_not_accumulate():
    """The encoder advances its chain through the DEQUANTIZED delta, so
    a 40-step chain carries the same error bound as a 1-step chain."""
    rng = np.random.default_rng(1)
    t = _tree(rng)
    provider = ParamBlobProvider("bfloat16", "delta-q8", window=4)
    decoder = ParamChainDecoder()
    provider.publish(t, 0)
    status, _, _, _ = decoder.apply(provider.coded_reply(0, -1, 0)[0])
    assert status == "full"
    have = 0
    for v in range(1, 41):
        t = _step(t, rng)
        provider.publish(t, v)
        payload, kind, ver, _ = provider.coded_reply(0, have, 0)
        assert kind == "delta"
        status, got, ver, _ = decoder.apply(payload)
        assert status == "full" and ver == v
        have = v
        wire = {"w": _bf16(t["w"]), "k": {"b": _bf16(t["k"]["b"]),
                                          "steps": t["k"]["steps"]}}
        assert _max_err(got, wire) < 4e-3, f"error grew by step {v}"


def test_constant_shift_ships_zero_bytes():
    """A global +c shift is a "z" leaf: bias in the meta, no buffer —
    the whole delta payload stays near header-sized."""
    rng = np.random.default_rng(2)
    # multiples of 0.25 are exact in bf16, and stay exact under a
    # +0.25 shift -- the wire-space delta is EXACTLY constant
    t0 = {"w": (rng.integers(0, 64, 4096) * 0.25).astype(np.float32)}
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    decoder = ParamChainDecoder()
    provider.publish(t0, 0)
    decoder.apply(provider.coded_reply(0, -1, 0)[0])
    t1 = {"w": (t0["w"] + np.float32(0.25)).astype(np.float32)}
    provider.publish(t1, 1)
    payload, kind, _, _ = provider.coded_reply(0, 0, 0)
    assert kind == "delta" and len(payload) < 256
    status, got, ver, _ = decoder.apply(payload)
    assert status == "full" and ver == 1
    assert np.allclose(got["w"], _bf16(t1["w"]), atol=1e-6)


def test_unchanged_is_header_only_both_planes():
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    provider.publish({"w": np.ones(8, np.float32)}, 5)
    payload, kind, ver, raw_cost = provider.coded_reply(3, 5, 3)
    assert kind == "unchanged" and ver == 5
    assert len(payload) == _PARAMS_HDR.size == raw_cost
    payload, kind, _, _ = provider.versioned_reply(3, 5, 3)
    assert kind == "unchanged" and len(payload) == _PARAMS_HDR.size


def test_blob_level_never_inflate():
    """Adversarial (incompressible, full-range) trees: every coded
    reply still fits under the raw APXV cost — the ratio >= 1.0 floor
    obs --check gates can't be broken by payload choice."""
    rng = np.random.default_rng(3)
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    decoder = ParamChainDecoder()
    have = -1
    for v in range(4):
        t = {"w": rng.uniform(-1e6, 1e6, 2048).astype(np.float32),
             "blob": rng.integers(0, 256, 4096).astype(np.uint8)}
        provider.publish(t, v)
        payload, kind, ver, raw_cost = provider.coded_reply(0, have, 0)
        assert len(payload) <= raw_cost, f"inflated at v{v} ({kind})"
        status, _, ver, _ = decoder.apply(payload)
        assert status == "full" and ver == v
        have = v


def test_decoder_resync_on_unknown_base_and_epoch():
    rng = np.random.default_rng(4)
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    provider.publish(_tree(rng), 0)
    provider.coded_reply(0, -1, 0)  # make v0 a chain node
    provider.publish(_step(_tree(rng), rng), 1)
    delta, kind, _, _ = provider.coded_reply(0, 0, 0)
    assert kind == "delta"

    cold = ParamChainDecoder()  # no state at all
    status, got, ver, _ = cold.apply(delta)
    assert status == "resync" and got is None and ver == 1

    seeded = ParamChainDecoder()
    seeded.apply(provider.coded_reply(0, -1, 0)[0])  # holds v1 now
    wrong_base = ParamChainDecoder()
    wrong_base.apply(provider.coded_reply(0, -1, 0)[0])
    wrong_base._version = 7  # pretend it holds a version never encoded
    assert wrong_base.apply(delta)[0] == "resync"

    stale_epoch = ParamChainDecoder()
    stale_epoch.apply(provider.coded_reply(0, -1, 0)[0])
    stale_epoch._epoch = 99  # chain from a dead incarnation
    assert stale_epoch.apply(delta)[0] == "resync"


def test_window_overrun_and_epoch_bump_force_full():
    rng = np.random.default_rng(5)
    t = _tree(rng)
    provider = ParamBlobProvider("bfloat16", "delta-q8", window=2)
    provider.publish(t, 0)
    provider.coded_reply(0, -1, 0)
    for v in range(1, 5):
        t = _step(t, rng)
        provider.publish(t, v)
        provider.coded_reply(0, v - 1, 0)  # encode each step
    assert provider.chain_len == 2  # window trims the tail
    # base v0 fell out of the window: full resync, not a delta
    payload, kind, ver, _ = provider.coded_reply(0, 0, 0)
    assert kind in ("full", "raw_full") and ver == 4
    # recent base still rides the chain
    assert provider.coded_reply(0, 3, 0)[1] == "delta"
    # epoch bump: even a perfect base resyncs full
    payload, kind, ver, _ = provider.coded_reply(0, 3, 1)
    assert kind in ("full", "raw_full")
    decoder = ParamChainDecoder()
    status, got, ver, ep = decoder.apply(
        provider.coded_reply(1, -1, 1)[0])
    assert status == "full" and ep == 1
    assert np.array_equal(got["w"], _bf16(t["w"]))


def test_structure_change_resets_chain():
    """Model surgery (leaf shape change) between versions: the chain
    restarts, outstanding bases get a full, nothing corrupts."""
    rng = np.random.default_rng(6)
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    provider.publish({"w": np.ones(16, np.float32)}, 0)
    provider.coded_reply(0, -1, 0)
    provider.publish({"w": np.ones(32, np.float32)}, 1)  # new shape
    payload, kind, ver, _ = provider.coded_reply(0, 0, 0)
    assert kind in ("full", "raw_full") and ver == 1
    decoder = ParamChainDecoder()
    status, got, _, _ = decoder.apply(provider.coded_reply(0, -1, 0)[0])
    assert status == "full" and got["w"].shape == (32,)


def test_q8_native_numpy_bit_parity(monkeypatch):
    """Wire contract: a native-enabled learner and a Python-only actor
    host must reconstruct the SAME chain bytes. Both q8 directions are
    compared bit-for-bit against the numpy mirror."""
    if not native.have_q8_native():
        pytest.skip("native q8 kernels unavailable")
    rng = np.random.default_rng(7)
    d = (rng.standard_normal(10007) ** 3 * 0.01).astype(np.float32)
    lo = float(d.min())
    scale = float(np.float32((float(d.max()) - lo) / 254.0))
    q_native = native.q8_encode(d, lo, scale)
    base_native = (rng.standard_normal(10007) * 0.1).astype(np.float32)
    base_numpy = base_native.copy()
    native.q8_dequant_add(base_native, np.frombuffer(q_native, np.int8),
                          lo, scale)
    monkeypatch.setattr(native, "_has_q8", False)
    q_numpy = native.q8_encode(d, lo, scale)
    assert q_native == q_numpy
    native.q8_dequant_add(base_numpy, np.frombuffer(q_numpy, np.int8),
                          lo, scale)
    assert np.array_equal(base_native, base_numpy)


def test_cross_plane_consistency():
    """The one versioned-blob provider: legacy blob, APXV reply body,
    coded full and local get_tree all agree bitwise for a version."""
    rng = np.random.default_rng(8)
    t = _tree(rng)
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    provider.publish(t, 3)
    blob = provider.raw_blob()
    apxv, kind, ver, _ = provider.versioned_reply(-1, -1, 9)
    assert kind == "raw_full" and ver == 3
    assert bytes(apxv[_PARAMS_HDR.size:]) == blob
    blob2, ver2, _ = provider.raw_blob_versioned()
    assert blob2 == blob and ver2 == 3
    from ape_x_dqn_tpu.comm.param_codec import _upcast_bf16
    blob_tree = _upcast_bf16(pickle.loads(blob)[0])
    local_tree, ver3 = provider.get_tree()
    assert ver3 == 3
    decoder = ParamChainDecoder()
    _, coded_tree, _, _ = decoder.apply(provider.coded_reply(9, -1, 9)[0])
    for a, b, c in zip(_flat(blob_tree), _flat(local_tree),
                       _flat(coded_tree)):
        assert np.array_equal(a, b) and np.array_equal(a, c)


def test_quantized_policy_greedy_parity():
    """Learning-parity smoke (PARITY.md row): greedy actions from a
    chain-reconstructed policy match the fp32 policy >= 0.99 of the
    time after a 12-step delta chain."""
    rng = np.random.default_rng(9)
    dims = (32, 64, 18)
    w = {f"l{i}": (rng.standard_normal((a, b)) * 0.3).astype(np.float32)
         for i, (a, b) in enumerate(zip(dims[:-1], dims[1:]))}
    provider = ParamBlobProvider("bfloat16", "delta-q8")
    decoder = ParamChainDecoder()
    have = -1
    for v in range(13):
        if v:
            w = {k: (a + 0.01 * rng.standard_normal(a.shape) ** 3
                     ).astype(np.float32) for k, a in w.items()}
        provider.publish(w, v)
        status, _, ver, _ = decoder.apply(
            provider.coded_reply(0, have, 0)[0])
        assert status == "full"
        have = ver

    def greedy(params, x):
        h = np.maximum(x @ params["l0"], 0.0)
        return (h @ params["l1"]).argmax(axis=1)

    states = rng.standard_normal((512, dims[0])).astype(np.float32)
    got = decoder._tree()
    agree = float((greedy(w, states) == greedy(got, states)).mean())
    assert agree >= 0.99, f"greedy agreement {agree}"


# -- socket integration ------------------------------------------------------


@pytest.mark.parametrize("server_codec,client_codec", [
    ("delta-q8", "delta-q8"), ("delta-q8", "raw"),
    ("raw", "delta-q8"), ("raw", "raw")])
def test_pull_interop_matrix(server_codec, client_codec):
    """Every old<->new pairing pulls correct values; only the
    both-coded cell compresses, every other cell degrades silently to
    the raw APXV plane (ratio exactly 1.0)."""
    rng = np.random.default_rng(10)
    t0 = _tree(rng)
    srv = SocketIngestServer("127.0.0.1", 0, param_codec=server_codec)
    client = _client(srv.port, param_codec=client_codec)
    try:
        srv.publish_params(t0, 0)
        p, v = client.get_params()
        assert v == 0
        assert np.array_equal(p["w"], _bf16(t0["w"]))
        assert np.array_equal(p["k"]["steps"], t0["k"]["steps"])
        p, v = client.get_params()  # conditional pull: header only
        assert p is None and v == 0
        t1 = _step(t0, rng)
        srv.publish_params(t1, 1)
        p, v = client.get_params()
        assert v == 1
        wire = {"w": _bf16(t1["w"]), "k": {"b": _bf16(t1["k"]["b"]),
                                           "steps": t1["k"]["steps"]}}
        coded = server_codec == client_codec == "delta-q8"
        assert _max_err(p, wire) < (4e-3 if coded else 1e-12)
        if coded:
            assert srv.param_compression_ratio > 1.0
        else:
            assert srv.param_compression_ratio == pytest.approx(1.0)
    finally:
        client.close()
        srv.stop()


def test_raw_escape_hatch_is_bitwise_precodec(monkeypatch):
    """param_codec="raw": the pull request carries exactly the
    pre-codec {v, epoch} JSON (no codec key — bitwise what an old
    build sends) and every reply is plain APXV."""
    sent = []
    real_send = st._send_msg

    def spy(sock, mtype, payload):
        if mtype == MSG_PARAMS_REQ:
            sent.append(bytes(payload))
        return real_send(sock, mtype, payload)

    monkeypatch.setattr(st, "_send_msg", spy)
    srv = SocketIngestServer("127.0.0.1", 0, param_codec="raw")
    client = _client(srv.port, param_codec="raw")
    try:
        srv.publish_params({"w": np.ones(64, np.float32)}, 0)
        p, v = client.get_params()
        assert v == 0 and p is not None
        assert sent, "no MSG_PARAMS_REQ captured"
        assert set(json.loads(sent[0])) == {"v", "epoch"}
        assert srv.param_compression_ratio == pytest.approx(1.0)
    finally:
        client.close()
        srv.stop()


def test_pull_resync_counted_and_retried():
    """A delta whose base the client no longer holds: the client counts
    param_resyncs, clears its chain, and the immediate retry lands the
    full — one get_params call, correct params out."""
    rng = np.random.default_rng(11)
    t0 = _tree(rng)
    srv = SocketIngestServer("127.0.0.1", 0, param_codec="delta-q8")
    client = _client(srv.port, param_codec="delta-q8")
    try:
        srv.publish_params(t0, 0)
        p, v = client.get_params()
        assert v == 0
        t1 = _step(t0, rng)
        srv.publish_params(t1, 1)
        real_reply = srv._provider.coded_reply
        fired = []

        def bogus_base_once(have_ep, have_v, epoch):
            if not fired:
                fired.append(1)
                payload = _CODEC_HDR.pack(
                    PARAMS_CODEC_MAGIC, epoch, 1, 555) \
                    + native.pack_records([])
                return payload, "delta", 1, len(payload)
            return real_reply(have_ep, have_v, epoch)

        srv._provider.coded_reply = bogus_base_once
        p, v = client.get_params()
        assert v == 1 and p is not None
        assert _max_err(p, {"w": _bf16(t1["w"]),
                            "k": {"b": _bf16(t1["k"]["b"]),
                                  "steps": t1["k"]["steps"]}}) < 1e-3
        assert client.param_resyncs == 1
    finally:
        client.close()
        srv.stop()


def test_server_counts_resyncs_on_window_overrun():
    """Client B parked on v0 while client A's pulls advance a window=2
    chain past it: B's next pull is a counted full resync with correct
    values — a routine overrun costs one full, never a wrong tree."""
    rng = np.random.default_rng(12)
    t = _tree(rng)
    srv = SocketIngestServer("127.0.0.1", 0, param_codec="delta-q8",
                             param_delta_window=2)
    a = _client(srv.port, param_codec="delta-q8")
    b = _client(srv.port, param_codec="delta-q8")
    try:
        srv.publish_params(t, 0)
        assert a.get_params()[1] == 0
        assert b.get_params()[1] == 0
        for v in range(1, 5):
            t = _step(t, rng)
            srv.publish_params(t, v)
            assert a.get_params()[1] == v  # encodes each chain step
        assert srv.param_resyncs == 0
        p, v = b.get_params()  # base v0 is out of the window
        assert v == 4
        assert np.array_equal(p["w"], _bf16(t["w"]))  # full => bitwise
        assert srv.param_resyncs == 1
        assert b.param_resyncs == 0  # server-side full, no client churn
    finally:
        a.close()
        b.close()
        srv.stop()


def test_push_delta_chain_and_epoch_bump():
    """Coded pushes: negotiate, receive the seed full, ride deltas
    version to version, then resync across a server epoch bump."""
    rng = np.random.default_rng(13)
    t = _tree(rng, n=8192)  # big enough that meta overhead is noise
    srv = SocketIngestServer("127.0.0.1", 0, epoch=4,
                             param_codec="delta-q8")
    client = _client(srv.port, params_push=True, param_codec="delta-q8")
    try:
        client.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        assert client.params_push_negotiated
        assert client.param_codec_negotiated
        srv.publish_params(t, 0)
        assert _wait(lambda: client.poll_pushed_params()[1] == 0)
        for v in range(1, 4):
            t = _step(t, rng)
            srv.publish_params(t, v)
            assert _wait(
                lambda v=v: client.poll_pushed_params()[1] == v)
        # one seed full + three q8 deltas (~half a bf16 full each)
        # must beat four raw fulls by a clear margin
        assert srv.param_compression_ratio > 1.3
        srv.bump_epoch()
        t = _step(t, rng)
        srv.publish_params(t, 0)  # version counter restarted
        got = {}

        def seen_new_epoch():
            p, v = client.poll_pushed_params()
            if p is not None and v == 0:
                got["p"] = p
                return True
            return False

        assert _wait(seen_new_epoch)
        assert np.array_equal(got["p"]["w"], _bf16(t["w"]))
    finally:
        client.close()
        srv.stop()


def test_slow_subscriber_does_not_stall_fanout():
    """One wedged subscriber (its push sends blocked) must not delay
    the healthy peer: deposits to the wedged peer supersede in its
    one-deep cell (counted per-reason) while the healthy peer keeps
    consuming every version."""
    rng = np.random.default_rng(14)
    t = _tree(rng)
    srv = SocketIngestServer("127.0.0.1", 0, param_codec="delta-q8")
    wedge = threading.Event()
    wedged = _client(srv.port, params_push=True, param_codec="delta-q8")
    try:
        wedged.send_experience(_batch())
        assert srv.recv_experience(timeout=5.0) is not None
        assert _wait(lambda: len(srv._push_subs) == 1)
        with srv._conns_lock:
            wedged_ids = set(srv._push_subs)
        real_send_on = srv._send_on

        def send_on(conn, mtype, payload):
            if (mtype == st.MSG_PARAMS_PUSH
                    and id(conn) in wedged_ids):
                wedge.wait(timeout=30.0)
            return real_send_on(conn, mtype, payload)

        srv._send_on = send_on
        healthy = _client(srv.port, params_push=True,
                          param_codec="delta-q8")
        try:
            healthy.send_experience(_batch())
            assert srv.recv_experience(timeout=5.0) is not None
            assert _wait(lambda: len(srv._push_subs) == 2)
            for v in range(5):
                t = _step(t, rng)
                srv.publish_params(t, v)
                assert _wait(
                    lambda v=v: healthy.poll_pushed_params()[1] == v), \
                    f"healthy subscriber starved at v{v}"
            drops = srv.param_push_queue_drops
            assert drops["superseded"] >= 1, drops
            assert healthy.param_resyncs == 0
        finally:
            wedge.set()
            healthy.close()
    finally:
        wedge.set()
        wedged.close()
        srv.stop()
