"""Cross-host transport: native framing codec, TCP ingest/param paths,
remote actor hosts, and actor-loss fault injection (SURVEY.md §2.3 item
3 "gRPC -> DCN ingest", §5 "failure detection")."""

import dataclasses
import os
import signal
import subprocess
import sys
import threading
import time
import zlib

import numpy as np
import pytest

from ape_x_dqn_tpu.comm import native
from ape_x_dqn_tpu.comm.socket_transport import (
    SocketIngestServer, SocketTransport, decode_batch, encode_batch)
from ape_x_dqn_tpu.configs import (
    ActorConfig, InferenceConfig, LearnerConfig, ReplayConfig, get_config)
from ape_x_dqn_tpu.runtime.driver import ApexDriver


# -- native codec ------------------------------------------------------------


def test_native_codec_compiles_and_loads():
    """g++ is in this image: the C++ data plane must actually build."""
    assert native.have_native()


def test_native_crc32_matches_zlib():
    data = os.urandom(4096)
    assert native.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF
    assert native.crc32(b"") == 0
    # seeded/rolling form matches too
    a, b = data[:100], data[100:]
    assert native.crc32(b, native.crc32(a)) == zlib.crc32(data) & 0xFFFFFFFF


def test_pack_unpack_roundtrip():
    chunks = [b"", b"x", os.urandom(1000), b"tail"]
    frame = native.pack_records(chunks)
    assert native.unpack_records(frame) == chunks
    with pytest.raises(ValueError):
        native.unpack_records(frame[:-1])  # truncated record


def test_batch_codec_roundtrip():
    batch = {
        "obs": np.random.randint(0, 255, (7, 84, 84, 4), dtype=np.uint8),
        "action": np.arange(7, dtype=np.int32),
        "priorities": np.random.rand(7).astype(np.float32),
        "actor": 3,
        "frames": 42,
    }
    out = decode_batch(encode_batch(batch))
    assert out["actor"] == 3 and out["frames"] == 42
    for k in ("obs", "action", "priorities"):
        np.testing.assert_array_equal(out[k], batch[k])
        assert out[k].dtype == batch[k].dtype


def test_unpack_records_mv_zero_copy():
    """The memoryview unpack path returns views ALIASING the frame (no
    copies) with contents identical to the copying path."""
    chunks = [b"", b"x", os.urandom(1000), b"tail"]
    frame = native.pack_records(chunks)
    mvs = native.unpack_records_mv(frame)
    assert [bytes(m) for m in mvs] == chunks
    for m in mvs:
        assert isinstance(m, memoryview)
        assert m.obj is frame  # view into the frame itself, not a copy
    # bytearray frames (what _recv_exact returns) work identically
    mvs2 = native.unpack_records_mv(bytearray(frame))
    assert [bytes(m) for m in mvs2] == chunks
    with pytest.raises(ValueError):
        native.unpack_records_mv(frame[:-1])


def test_decode_batch_into_matches_decode_batch():
    """Decode-into-staging lands bitwise what decode_batch returns, for
    the whole batch and for arbitrary [start, start+limit) windows at
    arbitrary staging offsets."""
    from ape_x_dqn_tpu.comm.socket_transport import decode_batch_into
    batch = {
        "obs": np.random.randint(0, 255, (7, 8, 8, 2), dtype=np.uint8),
        "action": np.arange(7, dtype=np.int32),
        "priorities": np.random.rand(7).astype(np.float32),
        "actor": 3, "frames": 42,
    }
    payload = encode_batch(batch)
    ref = decode_batch(payload)

    def fresh(cap):
        return {k: np.zeros((cap,) + v.shape[1:], v.dtype)
                for k, v in ref.items() if isinstance(v, np.ndarray)}

    dest = fresh(7)
    k, rows, scalars = decode_batch_into(payload, dest, 0)
    assert (k, rows) == (7, 7)
    assert scalars == {"actor": 3, "frames": 42}
    for key, v in dest.items():
        np.testing.assert_array_equal(v, ref[key], err_msg=key)
    # partial window [2, 5) landing at offset 4
    dest = fresh(16)
    k, rows, _ = decode_batch_into(payload, dest, 4, start=2, limit=3)
    assert (k, rows) == (3, 7)
    for key, v in dest.items():
        np.testing.assert_array_equal(v[4:7], ref[key][2:5], err_msg=key)
        assert not v[:4].any() and not v[7:].any(), key
    # limit past the end clamps
    dest = fresh(16)
    k, _, _ = decode_batch_into(payload, dest, 0, start=5, limit=99)
    assert k == 2


def test_wire_batch_dict_protocol():
    """WireBatch serves every consumer that treated the queue payload as
    a decoded dict: item access, .get defaults, scalars, row count."""
    from ape_x_dqn_tpu.comm.socket_transport import WireBatch, batch_rows
    batch = {
        "obs": np.random.rand(5, 3).astype(np.float32),
        "priorities": np.random.rand(5).astype(np.float32),
        "actor": 1, "frames": 9,
    }
    wb = WireBatch(encode_batch(batch))
    assert wb.rows == 5 and batch_rows(wb) == 5
    assert batch_rows(batch) == 5  # dict form reads priorities
    assert wb.get("frames", 5) == 9 and wb.get("missing") is None
    assert wb["actor"] == 1
    np.testing.assert_array_equal(wb["obs"], batch["obs"])
    assert wb["obs"] is wb["obs"]  # materialized arrays are cached
    assert "priorities" in wb and "nope" not in wb
    assert set(wb.keys()) == set(batch.keys())
    with pytest.raises(KeyError):
        wb["nope"]


def test_server_get_params_caches_deserialized_tree():
    """The learner-host local param pull must not pay a pickle
    round-trip per call: the deserialized tree is cached per version
    and invalidated on the next publish."""
    server = SocketIngestServer("127.0.0.1", 0)
    try:
        server.publish_params({"w": np.ones(3, np.float32)}, 5)
        p1, v1 = server.get_params()
        p2, v2 = server.get_params()
        assert v1 == v2 == 5
        assert p1["w"] is p2["w"]  # cached, not re-deserialized
        server.publish_params({"w": np.full(3, 2.0, np.float32)}, 6)
        p3, v3 = server.get_params()
        assert v3 == 6
        np.testing.assert_array_equal(p3["w"], np.full(3, 2.0))
    finally:
        server.stop()


def test_server_stop_drains_parked_batches():
    """Regression (apexlint v3 resource-lifecycle sweep): stop() must
    drain the bounded ingest queue and release() whatever is parked in
    it — a batch stranded there at shutdown pins its resources (for an
    shm slot batch, the ring slot AND the mapping; the PR 18 bug
    class in queue form)."""
    class Releasable(dict):
        released = 0

        def release(self):
            type(self).released += 1

    server = SocketIngestServer("127.0.0.1", 0)
    try:
        for i in range(3):
            server.send_experience(Releasable(actor=i))
        assert server._q.qsize() == 3
    finally:
        server.stop()
    assert server._q.qsize() == 0
    assert Releasable.released == 3


def test_loopback_close_drains_queue():
    """Regression (same sweep): LoopbackTransport gained close() so
    batches parked in the bounded queue are not pinned by a transport
    nobody will read again; drivers call close() symmetrically."""
    from ape_x_dqn_tpu.comm.transport import LoopbackTransport

    t = LoopbackTransport(max_pending=4)
    for i in range(3):
        t.send_experience({"actor": i})
    assert t.pending == 3
    t.close()
    assert t.pending == 0
    t.close()  # idempotent on an empty queue


# -- wire codec (delta-deflate experience compression) ----------------------


def _codec_batch(seed=0, n=16):
    """Frame-heavy batch with every leaf class the codec handles:
    frame-like uint8 (xd), bools (bp), small ints (d), floats (raw)."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 60, (4, 84, 84), dtype=np.uint8)
    frames = np.stack([np.roll(base, i, axis=1) for i in range(n)])
    return {
        "seg_frames": frames,
        "action": rng.integers(0, 18, (n,)).astype(np.int32),
        "done": rng.random(n) < 0.1,
        "priorities": (rng.random(n) + 0.1).astype(np.float32),
        "actor": 1, "frames": n,
    }


def _assert_batches_equal(got, batch):
    for k, v in batch.items():
        if isinstance(v, np.ndarray):
            assert got[k].dtype == v.dtype, k
            np.testing.assert_array_equal(got[k], v, err_msg=k)
        else:
            assert got[k] == v, k


def test_wire_codec_shrinks_and_roundtrips():
    """Frame traffic must compress >=2x (the adoption bar) and decode
    bitwise-identically, through both decode forms."""
    from ape_x_dqn_tpu.comm.socket_transport import (
        WireBatch, decode_batch_into)

    batch = _codec_batch()
    raw = encode_batch(batch, "raw")
    comp = encode_batch(batch, "delta-deflate")
    assert len(comp) * 2 < len(raw)
    _assert_batches_equal(decode_batch(comp), batch)
    wb = WireBatch(comp)
    assert wb.raw_nbytes > wb.wire_nbytes
    dest = {k: np.zeros_like(v) for k, v in batch.items()
            if isinstance(v, np.ndarray)}
    k1, rows, scalars = decode_batch_into(comp, dest, 0, 0, 9)
    wb.decode_into(dest, 9, 9)  # split continuation on a fresh WireBatch
    assert rows == 16 and scalars["actor"] == 1
    for k in dest:
        np.testing.assert_array_equal(dest[k], batch[k], err_msg=k)


def test_decode_leaf_full_copies_are_load_bearing():
    """The .copy()s in _decode_leaf_full are ownership, not
    convenience (ISSUE 18 satellite): a materialized leaf must survive
    its source buffer being scribbled over — a ShmSlotBatch's ring
    slot is REUSED by the writer the moment release() frees it, and a
    zlib-inflated codec leaf lives in a per-payload cache the array
    must outlive — and "xd" leaves need writable memory for the
    in-place XOR undo. Dropping either copy silently corrupts
    delivered batches; this pins them."""
    from ape_x_dqn_tpu.comm.socket_transport import WireBatch

    batch = _codec_batch(seed=11)
    # raw path: decode from a writable buffer (what a ring slot is),
    # then scribble over it as a reusing writer would
    payload = bytearray(encode_batch(batch, "raw"))
    wb = WireBatch(memoryview(payload))
    frames = wb["seg_frames"]
    pris = wb["priorities"]
    want_f, want_p = batch["seg_frames"].copy(), batch["priorities"].copy()
    payload[:] = b"\xaa" * len(payload)  # slot reuse
    np.testing.assert_array_equal(frames, want_f)
    np.testing.assert_array_equal(pris, want_p)
    # ownership, not a view into the (now-scribbled) transport buffer
    assert frames.base is None or frames.flags["OWNDATA"]
    # codec path: "d"/"xd" leaves must come back writable (the xd
    # decode XORs rows in place; a frombuffer view of immutable zlib
    # output would raise) and detached from the decode cache
    comp = encode_batch(batch, "delta-deflate")
    wc = WireBatch(comp)
    arr = wc["seg_frames"]
    np.testing.assert_array_equal(arr, want_f)
    assert arr.flags["WRITEABLE"]
    arr[0, 0, 0, 0] ^= 0xFF  # must not raise, must not poison the cache
    np.testing.assert_array_equal(wc["action"], batch["action"])


def test_wire_codec_interop_matrix():
    """Every (server wire_codec) x (client wire_codec) combination over
    a REAL socket pair delivers bitwise-identical experience, and the
    negotiated codec is delta-deflate iff both sides want it."""
    batch = _codec_batch(seed=3)
    for srv_codec in ("raw", "delta-deflate"):
        for cli_codec in ("raw", "delta-deflate"):
            server = SocketIngestServer("127.0.0.1", 0,
                                        wire_codec=srv_codec)
            client = SocketTransport("127.0.0.1", server.port,
                                     wire_codec=cli_codec)
            try:
                client.send_experience(batch)
                got = server.recv_experience(timeout=5.0)
                assert got is not None, (srv_codec, cli_codec)
                _assert_batches_equal(got, batch)
                want = "delta-deflate" \
                    if srv_codec == cli_codec == "delta-deflate" else "raw"
                assert client.negotiated_codec == want
                if want == "delta-deflate":
                    assert server.wire_compression_ratio > 1.5
                    assert client.wire_compression_ratio > 1.5
            finally:
                client.close()
                server.stop()


def test_wire_codec_raw_fallback_on_silent_server():
    """An OLD server never acks MSG_HELLO (unknown types fall through
    its reader) — the client must time out and degrade to raw, and the
    raw message must still arrive. Simulated with a minimal reader that
    ignores everything but experience messages."""
    import socket as socket_mod

    from ape_x_dqn_tpu.comm.socket_transport import (
        MSG_EXPERIENCE, _recv_msg)

    listener = socket_mod.socket(socket_mod.AF_INET,
                                 socket_mod.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    got: list = []

    def old_server():
        conn, _ = listener.accept()
        while True:
            msg = _recv_msg(conn)
            if msg is None:
                return
            if msg[0] == MSG_EXPERIENCE:  # hellos silently ignored
                got.append(msg[1])
                return

    thread = threading.Thread(target=old_server, daemon=True)
    thread.start()
    client = SocketTransport("127.0.0.1", listener.getsockname()[1],
                             hello_timeout=0.3)
    try:
        batch = _codec_batch(seed=4)
        client.send_experience(batch)
        assert client.negotiated_codec == "raw"
        thread.join(timeout=5)
        assert got, "old server never received the raw experience"
        _assert_batches_equal(decode_batch(got[0]), batch)
    finally:
        client.close()
        listener.close()


def test_wire_codec_cross_decode_native_python(monkeypatch):
    """The C++ delta transform and the numpy fallback must be
    wire-compatible in BOTH directions: payloads encoded with one must
    decode bitwise through the other (a C++-enabled learner host talks
    to a Python-only actor host and vice versa)."""
    if not native.have_delta_native():
        pytest.skip("native delta unavailable; nothing to cross-check")
    batch = _codec_batch(seed=5)
    native_payload = encode_batch(batch, "delta-deflate")
    native_decode = decode_batch(native_payload)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    monkeypatch.setattr(native, "_has_delta", False)
    python_payload = encode_batch(batch, "delta-deflate")
    _assert_batches_equal(decode_batch(native_payload), batch)
    monkeypatch.undo()
    assert native.have_delta_native()
    _assert_batches_equal(decode_batch(python_payload), batch)
    _assert_batches_equal(native_decode, batch)


@pytest.mark.parametrize("seed", range(4))
def test_wire_codec_fuzz_roundtrip(seed):
    """Random leaf shapes/dtypes/row sizes round-trip bitwise under the
    codec, through decode_batch AND the staged decode_batch_into with a
    random split point."""
    from ape_x_dqn_tpu.comm.socket_transport import decode_batch_into

    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 40))
    batch = {"priorities": rng.random(n).astype(np.float32)}
    dtypes = [np.uint8, np.int8, np.int32, np.int64, np.float32,
              np.float64, np.bool_]
    for i in range(int(rng.integers(1, 6))):
        nd = int(rng.integers(0, 3))
        tail = tuple(int(rng.integers(1, 64)) for _ in range(nd))
        dt = dtypes[int(rng.integers(0, len(dtypes)))]
        shape = (n,) + tail
        if dt == np.bool_:
            batch[f"leaf{i}"] = rng.random(shape) < 0.2
        elif np.issubdtype(dt, np.integer):
            batch[f"leaf{i}"] = rng.integers(0, 7, shape).astype(dt)
        else:
            batch[f"leaf{i}"] = rng.random(shape).astype(dt)
    payload = encode_batch(batch, "delta-deflate")
    _assert_batches_equal(decode_batch(payload), batch)
    dest = {k: np.zeros_like(v) for k, v in batch.items()}
    cut = int(rng.integers(0, n + 1))
    decode_batch_into(payload, dest, 0, 0, cut)
    decode_batch_into(payload, dest, cut, cut)
    for k in dest:
        np.testing.assert_array_equal(dest[k], batch[k], err_msg=k)


def test_wire_codec_truncated_and_corrupt_rejected():
    """Corrupt/truncated codec streams must reject with ValueError (the
    server reader drops such connections), never decode garbage."""
    import json as json_mod

    from ape_x_dqn_tpu.comm import native as native_mod

    batch = _codec_batch(seed=7)
    payload = encode_batch(batch, "delta-deflate")
    # flip bytes inside the compressed frame region
    corrupt = bytearray(payload)
    corrupt[len(corrupt) // 2] ^= 0xFF
    corrupt[-100] ^= 0xFF
    with pytest.raises(ValueError):
        decode_batch(bytes(corrupt))
    # truncate a leaf's deflate stream but keep the framing valid:
    # re-pack with the last record cut short
    recs = [bytes(r) for r in native_mod.unpack_records_mv(payload)]
    meta = json_mod.loads(recs[0])
    assert any(m.get("enc") for m in meta)  # codec leaves present
    truncated = native_mod.pack_records(recs[:-1] + [recs[-1][:10]])
    with pytest.raises(ValueError):
        decode_batch(truncated)
    # a stream inflating to the WRONG size (valid zlib, bad length):
    # swap one encoded leaf's bytes for a short valid deflate stream
    xd_idx = 1 + [j for j, m in enumerate(
        [m for m in meta if m["nd"]]) if m.get("enc") == "xd"][0]
    recs[xd_idx] = zlib.compress(b"short", 1)
    with pytest.raises(ValueError):
        decode_batch(native_mod.pack_records(recs))


# -- socket transport --------------------------------------------------------


def test_socket_transport_experience_and_params():
    server = SocketIngestServer("127.0.0.1", 0)
    client = SocketTransport("127.0.0.1", server.port)
    try:
        # params flow learner -> actor
        server.publish_params({"w": np.ones(3, np.float32)}, 5)
        params, version = client.get_params()
        assert version == 5
        np.testing.assert_array_equal(params["w"], np.ones(3))

        # experience flows actor -> learner
        batch = {"obs": np.zeros((4, 2), np.float32),
                 "priorities": np.ones(4, np.float32), "actor": 0,
                 "frames": 4}
        client.send_experience(batch)
        got = server.recv_experience(timeout=5.0)
        assert got is not None and got["frames"] == 4
        np.testing.assert_array_equal(got["priorities"], batch["priorities"])
    finally:
        client.close()
        server.stop()


def test_param_wire_dtype_bf16_halves_blob():
    """DCN weight broadcast ships f32 params as bf16 (half the bytes —
    the soak measured param pulls saturating the link) and the
    receiver upcasts back to f32 with only bf16 rounding applied."""
    import ml_dtypes

    rng = np.random.default_rng(0)
    params = {"w": rng.normal(size=(256, 256)).astype(np.float32),
              "b": rng.normal(size=256).astype(np.float32),
              "frames": np.zeros((4, 4), np.uint8)}  # non-float: as-is
    bf = SocketIngestServer("127.0.0.1", 0)  # default bfloat16
    f32 = SocketIngestServer("127.0.0.1", 0,
                             param_wire_dtype="float32")
    try:
        bf.publish_params(params, 3)
        f32.publish_params(params, 3)
        assert len(bf._param_blob()) < 0.6 * len(f32._param_blob())
        got, version = bf.get_params()
        assert version == 3
        assert got["w"].dtype == np.float32  # receiver upcasts
        assert got["frames"].dtype == np.uint8
        # values survive with bf16 rounding only (~2^-8 relative)
        np.testing.assert_allclose(got["w"], params["w"],
                                   rtol=1 / 128, atol=1e-6)
        exact = np.asarray(params["w"]).astype(
            ml_dtypes.bfloat16).astype(np.float32)
        np.testing.assert_array_equal(got["w"], exact)
        # the exact path stays bit-identical
        got32, _ = f32.get_params()
        np.testing.assert_array_equal(got32["w"], params["w"])
    finally:
        bf.stop()
        f32.stop()


def test_native_bf16_leaves_keep_dtype_on_the_wire():
    """Only leaves the SENDER downcast are upcast at the receiver: a
    param tree with genuinely-bf16 leaves (e.g. a bf16-param network)
    must keep them bf16 across the wire under BOTH wire dtypes
    (round-3 advisor finding: the old receiver upcast every bf16 leaf
    unconditionally)."""
    import ml_dtypes

    params = {"w32": np.ones((8, 8), np.float32),
              "wbf": np.full((8, 8), 1.5, ml_dtypes.bfloat16)}
    for wire in ("bfloat16", "float32"):
        srv = SocketIngestServer("127.0.0.1", 0, param_wire_dtype=wire)
        try:
            srv.publish_params(params, 1)
            got, _ = srv.get_params()
            assert got["w32"].dtype == np.float32, wire
            assert got["wbf"].dtype == ml_dtypes.bfloat16, wire
            np.testing.assert_array_equal(
                got["wbf"].astype(np.float32), 1.5)
        finally:
            srv.stop()


def test_conn_tracking_under_connect_disconnect_hammer():
    """_conns is mutated by the accept + reader threads while the
    multihost idle check reads it (round-2 verdict weak #6): hammer
    connect/disconnect cycles against concurrent active_connections /
    quiesced readers and assert the count settles to exactly zero with
    the debounce behaving."""
    import socket as socketlib
    import threading
    import time

    server = SocketIngestServer("127.0.0.1", 0, idle_grace_s=2.0)
    stop = threading.Event()
    snapshots: list[int] = []

    def reader():
        while not stop.is_set():
            n = server.active_connections
            assert n >= 0
            snapshots.append(n)
            server.quiesced()  # must never raise mid-churn

    rthreads = [threading.Thread(target=reader, daemon=True)
                for _ in range(2)]
    for t in rthreads:
        t.start()
    try:
        saw_open = False
        for it in range(30):
            socks = [socketlib.create_connection(("127.0.0.1", server.port),
                                                 timeout=5)
                     for _ in range(4)]
            if not saw_open:
                # observe a live count at least once while socks are open
                # (the accept thread needs a moment on a 1-core host)
                deadline = time.monotonic() + 5
                while (server.active_connections == 0
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                saw_open = server.active_connections > 0
            for s in socks:
                s.close()
        assert saw_open, "accept loop never registered a connection"
        deadline = time.monotonic() + 5
        while server.active_connections and time.monotonic() < deadline:
            time.sleep(0.05)
        assert server.active_connections == 0
        assert snapshots, "concurrent readers never ran"
        # a disconnect just happened: the idle verdict must debounce
        assert not server.quiesced()
        # ... and eventually clear. Poll rather than a single sleep:
        # sockets closed before being accepted can be accepted LATE by
        # the 0.2s-poll accept loop, refreshing the disconnect stamp
        # after the settle check (seen flaky under full-suite load)
        deadline = time.monotonic() + 20
        while not server.quiesced() and time.monotonic() < deadline:
            time.sleep(0.2)
        assert server.quiesced()
    finally:
        stop.set()
        for t in rthreads:
            t.join(timeout=2)
        server.stop()


def test_socket_client_survives_dead_server():
    """Ingest is lossy-tolerant: a broken connection must not raise into
    the actor loop — batches count as dropped."""
    server = SocketIngestServer("127.0.0.1", 0)
    port = server.port
    client = SocketTransport("127.0.0.1", port)
    batch = {"x": np.ones(2, np.float32), "priorities": np.ones(2),
             "actor": 0}
    client.send_experience(batch)
    assert server.recv_experience(timeout=5.0) is not None
    server.stop()
    time.sleep(0.2)
    # the first sends may land in the kernel buffer before the RST
    # surfaces; keep sending until the client notices and starts dropping
    for _ in range(20):
        client.send_experience(batch)  # must never raise
        if client.dropped:
            break
        time.sleep(0.05)
    assert client.dropped >= 1
    client.close()


def _learner_cfg(num_local_actors=1):
    return get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=num_local_actors, base_eps=0.6,
                           ingest_batch=16),
        replay=ReplayConfig(kind="prioritized", capacity=2048, min_fill=64),
        # steps_per_frame_cap: this host shares ONE core with the remote
        # actor process; a free-running learner starves the ingest thread
        # and the bounded queue drops most of the experience stream
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20,
                              steps_per_frame_cap=1.0),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        eval_every_steps=0, eval_episodes=0,
    )


def _spawn_actor_host(port: int, frames: int, offset: int = 1):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "ape_x_dqn_tpu.runtime.actor_host",
         "--config", "cartpole_smoke", "--connect", f"127.0.0.1:{port}",
         "--actors", "1", "--actor-offset", str(offset),
         "--frames-per-actor", str(frames),
         "--set", "actors.ingest_batch=16",
         "--set", "inference.deadline_ms=1.0"],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_two_process_training_over_tcp():
    """A remote actor host (separate OS process) feeds the learner over
    the socket transport and pulls params; training proceeds on the
    combined experience stream."""
    cfg = _learner_cfg(num_local_actors=1)
    server = SocketIngestServer("127.0.0.1", 0)
    # constructing the driver publishes params v0, which the remote host
    # blocks on — so the remote can run its whole 300-frame budget before
    # the timed local run starts; its ~19 batches of 16 park in the ingest
    # queue (max_pending=64) and drain when run() begins. This removes the
    # race between remote JAX startup (~10s import) and the local budget.
    driver = ApexDriver(cfg, transport=server)
    proc = _spawn_actor_host(server.port, frames=300)
    try:
        stdout, stderr = proc.communicate(timeout=120)
        assert proc.returncode == 0, stderr[-2000:]
        assert "'errors': []" in stdout
        assert server.pending > 0, "remote experience never reached the queue"
        out = driver.run(total_env_frames=4000, max_grad_steps=10**9,
                         wall_clock_limit_s=240)
        assert out["actor_errors"] == [], out["actor_errors"]
        assert out["loop_errors"] == [], out["loop_errors"]
        assert out["grad_steps"] > 0, out
        # drop-accounting closure, not an exact frame count: the old
        # `frames > 4050` was load-flaky — a contended host legitimately
        # drops bounded-queue messages, and those frames are not lost,
        # they are COUNTED. Every produced frame is either ingested
        # (out["frames"]), inside a dropped queue message (server.dropped
        # messages of <= ingest_batch frames each), or in the staged
        # sub-block tail discarded at teardown (_stage_dropped,
        # frame-denominated in flat mode). The closure still fails if
        # the remote stream silently vanishes without being accounted.
        accounted = (out["frames"]
                     + server.dropped * cfg.actors.ingest_batch
                     + driver._stage_dropped)
        assert accounted > 4050, (out["frames"], server.dropped,
                                  driver._stage_dropped)
    finally:
        if proc.poll() is None:
            proc.kill()
        server.stop()


_R2D2_SETS = [
    "env.kind=cartpole_po", "env.id=CartPolePO",
    "replay.storage=flat",  # preset is frame_ring, needs pixel obs
    "network.lstm_size=32", "network.torso_dense=64",
    "network.compute_dtype=float32",
    "replay.capacity=512", "replay.seq_length=16", "replay.seq_overlap=8",
    "replay.burn_in=4", "replay.min_fill=24",
    "learner.batch_size=16", "learner.publish_every=20",
    "learner.train_chunk=4",
    "actors.ingest_batch=64", "inference.max_batch=8",
    "inference.deadline_ms=1.0",
    "parallel.dp=1", "parallel.tp=1",
    "eval_every_steps=0", "eval_episodes=0",
]


def test_two_process_r2d2_training_over_tcp():
    """A remote RECURRENT actor host feeds stored-state sequences over
    the socket transport (runtime/family.py dispatch shared with the
    driver); the sequence learner trains on the combined stream."""
    from ape_x_dqn_tpu.runtime.train import apply_overrides

    cfg = apply_overrides(get_config("r2d2"), _R2D2_SETS)
    cfg = cfg.replace(actors=dataclasses.replace(cfg.actors, num_actors=1))
    server = SocketIngestServer("127.0.0.1", 0)
    driver = ApexDriver(cfg, transport=server)  # publishes params v0
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ape_x_dqn_tpu.runtime.actor_host",
         "--config", "r2d2", "--connect", f"127.0.0.1:{server.port}",
         "--actors", "1", "--actor-offset", "1",
         "--frames-per-actor", "400"]
        + [a for s in _R2D2_SETS for a in ("--set", s)],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        stdout, stderr = proc.communicate(timeout=180)
        assert proc.returncode == 0, stderr[-2000:]
        assert "'errors': []" in stdout
        assert server.pending > 0, "remote sequences never reached the queue"
        out = driver.run(total_env_frames=2000, max_grad_steps=10**9,
                         wall_clock_limit_s=240)
        assert out["actor_errors"] == [], out["actor_errors"]
        assert out["loop_errors"] == [], out["loop_errors"]
        assert out["grad_steps"] > 0, out
        # the remote host's 400 frames arrived on top of the local 2000
        assert out["frames"] > 2100, out
    finally:
        if proc.poll() is None:
            proc.kill()
        server.stop()


def test_dpg_remote_actor_host_ships_continuous_experience():
    """The DPG family over the remote-host path (runtime/family.py):
    {actor, critic} params distribute through the transport's pickle
    channel, the host's server evaluates {a: mu(s), q: Q(s, mu(s))},
    and ContinuousActor ships float-action transitions."""
    from ape_x_dqn_tpu.configs import get_config as _get
    from ape_x_dqn_tpu.runtime.actor_host import run_actor_host
    from ape_x_dqn_tpu.runtime.driver import ApexDriver as _Driver

    cfg = _get("apex_dpg").replace(
        env=dataclasses.replace(_get("apex_dpg").env,
                                id="pendulum", kind="control"),
        actors=ActorConfig(num_actors=1, ingest_batch=16,
                           noise_sigma=0.15),
        inference=InferenceConfig(max_batch=4, deadline_ms=1.0),
        eval_every_steps=0, eval_episodes=0,
    )
    server = SocketIngestServer("127.0.0.1", 0)
    driver = _Driver(cfg, transport=server)  # publishes dpg params v0
    try:
        out = run_actor_host(cfg, "127.0.0.1", server.port, num_actors=1,
                             actor_offset=1, frames_per_actor=120)
        assert out["errors"] == [], out["errors"]
        assert out["frames"] == 120
        assert out["last_param_version"] >= 0
        got = server.recv_experience(timeout=5.0)
        assert got is not None
        assert got["action"].dtype == np.float32  # continuous actions
        assert got["action"].ndim == 2            # [B, action_dim]
        assert (got["priorities"] >= 0).all()
    finally:
        driver.server.stop()
        server.stop()


def test_remote_only_learner_waits_then_quiesces():
    """A learner with ZERO local actors (the soak/deployment topology)
    must (a) survive the window before any actor host connects (boot
    grace), (b) train on late-arriving remote experience, and (c)
    self-terminate via the QUIESCE path once the remote disconnects and
    the grace window passes — instead of either exiting at t=0 or
    spinning forever. max_grad_steps stays at the 10**9 sentinel, so
    only (c) can end the run before the wall-clock limit."""
    cfg = _learner_cfg(num_local_actors=0).replace(
        actors=ActorConfig(num_actors=0, remote_boot_grace_s=60.0),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20,
                              train_chunk=4))
    server = SocketIngestServer("127.0.0.1", 0, idle_grace_s=1.0)
    driver = ApexDriver(cfg, transport=server)

    def late_remote():
        time.sleep(1.5)  # the learner must still be waiting
        client = SocketTransport("127.0.0.1", server.port)
        rng = np.random.default_rng(0)
        for _ in range(10):
            n = 32
            client.send_experience({
                "obs": rng.normal(size=(n, 4)).astype(np.float32),
                "action": rng.integers(0, 2, n).astype(np.int32),
                "reward": rng.normal(size=n).astype(np.float32),
                "next_obs": rng.normal(size=(n, 4)).astype(np.float32),
                "discount": np.full(n, 0.97, np.float32),
                "priorities": rng.random(n).astype(np.float32) + 0.1,
                "actor": 0, "frames": n,
            })
        time.sleep(0.5)  # let the reader drain before the socket dies
        client.close()

    t = threading.Thread(target=late_remote, daemon=True)
    t.start()
    try:
        out = driver.run(total_env_frames=10**9, max_grad_steps=10**9,
                         wall_clock_limit_s=120)
        t.join(timeout=10)
        assert out["loop_errors"] == [], out["loop_errors"]
        # (a)+(b): the boot grace held the learner alive long enough to
        # ingest the late remote's 320 transitions and train on them
        assert out["grad_steps"] > 0, out
        assert out["frames"] >= 64, out
        # (c): with no finite step target, only the quiesce/stuck path
        # can end the run this early — a regression that spins forever
        # would hit the 120s wall clock instead
        assert out["wall_s"] < 60, out
    finally:
        server.stop()


def test_actor_loss_fault_injection():
    """SURVEY.md §5: killing an actor host mid-run must not disturb the
    learner — training reaches its target with no errors."""
    cfg = _learner_cfg(num_local_actors=1)
    server = SocketIngestServer("127.0.0.1", 0)
    driver = ApexDriver(cfg, transport=server)
    proc = _spawn_actor_host(server.port, frames=10**7)  # would run forever

    def killer():
        time.sleep(6.0)
        proc.send_signal(signal.SIGKILL)

    threading.Thread(target=killer, daemon=True).start()
    try:
        out = driver.run(total_env_frames=1500, max_grad_steps=60,
                         wall_clock_limit_s=180)
        assert proc.poll() is not None, "actor host was not killed"
        assert out["actor_errors"] == [], out["actor_errors"]
        assert out["loop_errors"] == [], out["loop_errors"]
        assert out["grad_steps"] >= 60, out
    finally:
        if proc.poll() is None:
            proc.kill()
        server.stop()


def test_param_only_probe_is_not_a_producer():
    """ever_connected must latch on the first EXPERIENCE message, not
    on accept: a param-only client (monitoring probe, or an actor host
    that died waiting for params) that comes and goes during learner
    construction would otherwise skip the boot grace AND read as a
    departed producer — observed terminating a remote-only learner
    0.1s into its run (round-4 soak)."""
    server = SocketIngestServer("127.0.0.1", 0)
    client = SocketTransport("127.0.0.1", server.port)
    try:
        server.publish_params({"w": np.ones(2, np.float32)}, 1)
        params, _ = client.get_params()   # param-only connection
        assert params is not None
        client.close()
        time.sleep(0.3)
        assert server.ever_connected is False  # probe, not producer

        client2 = SocketTransport("127.0.0.1", server.port)
        client2.send_experience({"obs": np.zeros((2, 2), np.float32),
                                 "priorities": np.ones(2, np.float32),
                                 "frames": 2})
        got = server.recv_experience(timeout=5.0)
        assert got is not None
        assert server.ever_connected is True   # real producer
        client2.close()
    finally:
        client.close()
        server.stop()


def test_param_probe_does_not_end_learner_boot_grace():
    """A remote-only learner (0 local actors) must hold its full boot
    grace even when a param-only client touches the listener: probes
    polled active_connections into saw_remote and the learner
    self-terminated 88s into a 300s grace (observed live, round 4)."""
    from ape_x_dqn_tpu.configs import get_config

    cfg = get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=0, remote_boot_grace_s=4.0),
        replay=ReplayConfig(kind="prioritized", capacity=512, min_fill=64),
        learner=LearnerConfig(batch_size=16, publish_every=20),
        inference=InferenceConfig(max_batch=4, deadline_ms=1.0),
        eval_every_steps=0, eval_episodes=0)
    server = SocketIngestServer("127.0.0.1", 0)
    driver = ApexDriver(cfg, transport=server)
    probe = SocketTransport("127.0.0.1", server.port)
    t_run = {}

    def run():
        t0 = time.monotonic()
        driver.run(total_env_frames=10**9, max_grad_steps=10**9,
                   wall_clock_limit_s=20.0)
        t_run["wall"] = time.monotonic() - t0

    th = threading.Thread(target=run, daemon=True)
    th.start()
    # poke the listener with param-only pulls through the grace window
    for _ in range(6):
        probe.get_params()
        time.sleep(0.25)
    probe.close()
    th.join(timeout=60)
    assert not th.is_alive(), "driver.run never returned"
    # the run must have survived at least the grace (it exits when the
    # grace lapses with no producer, NOT when the probe disconnects)
    assert t_run["wall"] >= 3.5, t_run
    server.stop()
