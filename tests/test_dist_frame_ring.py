"""dp-sharded frame-ring replay (ISSUE 9 tentpole (a)).

The dist driver has run frame-ring configs over the mesh since the
flagship e2e test; these tests pin the SEMANTICS of that path:

- dp=1 bitwise parity: the sharded state (leading [dp] axis, lockstep
  adds, vmapped single-shard sampling/write-back) at dp=1 must be the
  single-chip FrameRingReplay bit for bit — sharding is a layout
  decision, never a numerics decision.
- skewed-shard-fill IS weights: the global-N recipe from
  tests/test_parallel.py::test_skewed_shard_is_weights, re-proven on
  frame-ring storage where shard fills (not just priority masses) can
  diverge and dead episode-pad slots must train with weight 0.
- shard_stats: the per-shard fill/mass observability surface the
  multichip lane (bench.py --multichip) and the run report consume.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.configs import LearnerConfig
from ape_x_dqn_tpu.parallel.dist_learner import DistDQNLearner
from ape_x_dqn_tpu.parallel.mesh import make_mesh
from ape_x_dqn_tpu.replay.frame_ring import FrameRingReplay

OBS_SHAPE = (6, 6, 4)


def _ring(cap=64, seg=8, **kw):
    return FrameRingReplay(capacity=cap, seg_transitions=seg, n_step=3,
                           obs_shape=OBS_SHAPE, **kw)


def _segs(replay, g, rng, next_off=3):
    b, f = replay.B, replay.F
    items = {
        "seg_frames": jnp.asarray(
            rng.integers(0, 255, (g, f, *OBS_SHAPE[:2])), jnp.uint8),
        "action": jnp.asarray(rng.integers(0, 4, (g, b)), jnp.int32),
        "reward": jnp.asarray(rng.normal(size=(g, b)), jnp.float32),
        "discount": jnp.full((g, b), 0.97, jnp.float32),
        "next_off": jnp.full((g, b), next_off, jnp.int32),
    }
    pris = jnp.asarray(rng.uniform(0.1, 2.0, (g, b)), jnp.float32)
    return items, pris


def _stack1(tree):
    return jax.tree.map(lambda x: x[None], tree)


def _assert_state_eq(single, sharded_dp1):
    """Every sharded leaf is the single-chip leaf under a leading [1]."""
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)[0]), single, sharded_dp1)


# -- dp=1 bitwise parity ---------------------------------------------------


def test_dp1_lockstep_add_sample_update_parity():
    """add_lockstep / vmapped sample_items / vmapped update_priorities
    at dp=1 land the same bits as the single-chip ops under the same
    seed — storage, sum-tree, indices, probs, gathered stacks, all of
    it."""
    replay = _ring()
    rng = np.random.default_rng(0)
    items, pris = _segs(replay, 4, rng)

    s1 = replay.add(replay.init(), items, pris)
    sd = replay.add_lockstep(_stack1(replay.init()), _stack1(items),
                             pris[None])
    _assert_state_eq(s1, sd)

    # same key bits on both paths: split once, shard 0 IS the key
    keys = jax.random.split(jax.random.key(42), 1)
    it1, idx1, p1 = replay.sample_items(s1, keys[0], 16)
    itd, idxd, pd = jax.vmap(
        lambda rs, k: replay.sample_items(rs, k, 16))(sd, keys)
    np.testing.assert_array_equal(np.asarray(idx1), np.asarray(idxd)[0])
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(pd)[0])
    _assert_state_eq(it1, itd)

    td = jnp.asarray(np.random.default_rng(3).uniform(0.1, 1.0, 16),
                     jnp.float32)
    u1 = replay.update_priorities(s1, idx1, td)
    ud = jax.vmap(replay.update_priorities)(sd, idxd, td[None])
    _assert_state_eq(u1, ud)


def test_dp1_add_many_matches_single_chip_adds():
    """The dist learner's coalesced add_many ([g, dp, ...] unrolled
    lockstep chain) at dp=1 equals g sequential single-chip adds."""
    replay = _ring()
    mesh = make_mesh(dp=1, tp=1)
    lcfg = LearnerConfig(batch_size=16)
    learner = DistDQNLearner(lambda p, o: o, replay, lcfg, mesh)
    params = {"w": jnp.zeros((4,), jnp.float32)}
    state = learner.init(params, None, jax.random.key(0))

    rng = np.random.default_rng(7)
    blocks = [_segs(replay, 2, rng) for _ in range(3)]
    grp_items = jax.tree.map(lambda *xs: jnp.stack(xs)[:, None],
                             *[b[0] for b in blocks])
    grp_td = jnp.stack([b[1] for b in blocks])[:, None]
    state = learner.add_many(state, grp_items, grp_td)

    s1 = replay.init()
    for items, pris in blocks:
        s1 = replay.add(s1, items, pris)
    _assert_state_eq(s1, state.replay)


# -- skewed shard fills ----------------------------------------------------


def test_skewed_shard_fill_is_weights_frame_ring():
    """Frame-ring twin of test_parallel.py::test_skewed_shard_is_weights,
    with the skew in the FILL (shard 0 holds 2 segments, shard 1 is
    full) as well as the priority mass (1000x starved). Constant
    per-shard values + priorities make the beta=1 weighted estimate
    zero-variance, so one vmapped draw must recover the exact uniform
    mean over the GLOBAL live pool — the global-N recipe of
    _sample_weighted."""
    dp, cap, seg = 2, 64, 8
    replay = _ring(cap=cap, seg=seg, alpha=1.0, beta=1.0, eps=0.0)
    mesh = make_mesh(dp=dp, tp=1)
    learner = DistDQNLearner(lambda p, o: o,
                             replay, LearnerConfig(batch_size=64), mesh)

    masses = [1e-3, 1.0]
    n_segs = [2, cap // seg]
    rng = np.random.default_rng(0)
    states = []
    for d in range(dp):
        g = n_segs[d]
        items, _ = _segs(replay, g, rng)
        # shard value g_d = d+1 rides the action field
        items["action"] = jnp.full((g, seg), d + 1, jnp.int32)
        live = g * seg
        pris = jnp.full((g, seg), masses[d] / live, jnp.float32)
        states.append(replay.add(replay.init(), items, pris))
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *states)

    keys = jax.random.split(jax.random.key(0), dp)
    items, idx, w = learner._sample_weighted(state, keys, 32)
    w = np.asarray(w, np.float64)
    g_val = np.asarray(items["action"]).astype(np.float64)

    # all drawn slots are live, so every weight is positive and exactly
    # the valid_mask-gated formula weight
    valid = np.asarray(jax.vmap(replay.valid_mask)(state, idx))
    assert (valid == 1.0).all()
    assert (w > 0.0).all() and np.isfinite(w).all()

    n0, n1 = n_segs[0] * seg, n_segs[1] * seg
    uniform_mean = (n0 * 1.0 + n1 * 2.0) / (n0 + n1)
    est = float((w * g_val).mean())
    assert abs(est - uniform_mean) < 1e-3, (est, uniform_mean)


def test_dead_pad_slots_sample_with_zero_weight():
    """A shard whose tail segment is all episode pads (next_off == 0)
    keeps those slots out of training: any draw landing on one gets IS
    weight exactly 0 via the vmapped valid_mask gate."""
    dp, cap, seg = 2, 32, 8
    replay = _ring(cap=cap, seg=seg, alpha=1.0, beta=1.0, eps=0.0)
    mesh = make_mesh(dp=dp, tp=1)
    learner = DistDQNLearner(lambda p, o: o,
                             replay, LearnerConfig(batch_size=64), mesh)
    rng = np.random.default_rng(1)
    states = []
    for d in range(dp):
        items, pris = _segs(replay, 2, rng,
                            next_off=3 if d == 0 else 0)
        states.append(replay.add(replay.init(), items, pris))
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    keys = jax.random.split(jax.random.key(5), dp)
    _, idx, w = learner._sample_weighted(state, keys, 32)
    w = np.asarray(w)
    valid = np.asarray(jax.vmap(replay.valid_mask)(state, idx))
    # shard 1 is ALL pads: every one of its weights must be zeroed
    assert (valid[1] == 0.0).all()
    np.testing.assert_array_equal(w[1], np.zeros_like(w[1]))
    assert (w[0] > 0.0).all()


# -- per-shard observability -----------------------------------------------


def test_shard_stats_reports_per_shard_fill_and_mass():
    """shard_stats: sizes/live/fill/tree_mass per shard, with frame-ring
    live counts excluding dead pads — the numbers the multichip lane
    and the run report publish."""
    dp, cap, seg = 2, 32, 8
    replay = _ring(cap=cap, seg=seg)
    mesh = make_mesh(dp=dp, tp=1)
    learner = DistDQNLearner(lambda p, o: o,
                             replay, LearnerConfig(batch_size=16), mesh)
    state = learner.init({"w": jnp.zeros((2,), jnp.float32)}, None,
                         jax.random.key(0))
    rng = np.random.default_rng(2)
    items, pris = _segs(replay, 2, rng)
    # shard-varying liveness: shard 0 fully live, shard 1 half pads
    no = np.broadcast_to(np.asarray(items["next_off"]),
                         (dp, 2, seg)).copy()
    no[1, :, seg // 2:] = 0
    d_items = {k: jnp.broadcast_to(v, (dp,) + v.shape)
               for k, v in items.items()}
    d_items["next_off"] = jnp.asarray(no)
    state = learner.add(state, d_items,
                        jnp.broadcast_to(pris, (dp,) + pris.shape))
    stats = learner.shard_stats(state)
    assert stats["sizes"] == [16, 16]
    assert stats["live"] == [16, 8]
    assert stats["fill"] == [0.5, 0.5]
    assert stats["fill_min"] == stats["fill_max"] == 0.5
    assert len(stats["tree_mass"]) == dp
    assert all(m > 0 for m in stats["tree_mass"])


def test_live_transitions_single_and_sharded():
    """live_transitions reduces only the slot axis: scalar on a
    single-chip state, [dp] on the stacked lockstep state."""
    replay = _ring(cap=32, seg=8)
    rng = np.random.default_rng(4)
    items, pris = _segs(replay, 2, rng)
    s1 = replay.add(replay.init(), items, pris)
    assert int(replay.live_transitions(s1)) == 16
    sd = replay.add_lockstep(_stack1(replay.init()), _stack1(items),
                             pris[None])
    assert np.asarray(replay.live_transitions(sd)).tolist() == [16]


def test_multichip_baseline_comparable_shapes_only(tmp_path, monkeypatch):
    """The --multichip anti-ratchet gate only compares like with like:
    same device mode, same dp set, real curve artifacts only. A
    cross-mode or cross-shape artifact (or a pre-curve raw capture like
    MULTICHIP_r01.json) is skipped, never compared."""
    import importlib
    import json as _json
    import sys as _sys

    repo_root = __file__.rsplit("/tests/", 1)[0]
    if repo_root not in _sys.path:
        _sys.path.insert(0, repo_root)
    bench = importlib.import_module("bench")
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))

    def _write(name, doc):
        (tmp_path / name).write_text(_json.dumps(doc))

    # pre-curve raw capture: no metric/value -> never a baseline
    _write("MULTICHIP_r01.json", {"ok": False, "n_devices": 1})
    # real-device curve: wrong mode for a virtual run
    _write("MULTICHIP_r02.json",
           {"metric": "multichip_dp_scaling_efficiency", "value": 0.9,
            "virtual_devices": False, "dp": [1, 2, 4, 8]})
    path, doc = bench._load_multichip_baseline(
        smoke=False, virtual=True, dp_list=[1, 2, 4, 8])
    assert path is None and doc is None

    # comparable virtual curve, but a different dp set -> skipped
    _write("MULTICHIP_r03.json",
           {"metric": "multichip_dp_scaling_efficiency", "value": 0.5,
            "virtual_devices": True, "dp": [1, 2]})
    path, doc = bench._load_multichip_baseline(
        smoke=False, virtual=True, dp_list=[1, 2, 4, 8])
    assert path is None and doc is None

    # the genuinely comparable artifact wins
    _write("MULTICHIP_r04.json",
           {"metric": "multichip_dp_scaling_efficiency", "value": 0.5,
            "virtual_devices": True, "dp": [8, 4, 2, 1]})  # order-free
    path, doc = bench._load_multichip_baseline(
        smoke=False, virtual=True, dp_list=[1, 2, 4, 8])
    assert path is not None and path.endswith("MULTICHIP_r04.json")
    assert doc["value"] == 0.5

    # smoke class never reads the full-shape artifacts
    path, doc = bench._load_multichip_baseline(
        smoke=True, virtual=True, dp_list=[1, 2, 4, 8])
    assert path is None and doc is None
