import numpy as np
import pytest

from ape_x_dqn_tpu.configs import EnvConfig
from ape_x_dqn_tpu.envs import make_env, SyncVectorEnv
from ape_x_dqn_tpu.envs.atari import (
    AtariPreprocessing, SyntheticAtari, bilinear_resize, grayscale)
from ape_x_dqn_tpu.envs.cartpole import CartPole
from ape_x_dqn_tpu.envs.control import PendulumSwingUp


def test_cartpole_shapes_and_episode():
    env = CartPole(seed=0)
    obs = env.reset()
    assert obs.shape == (4,) and obs.dtype == np.float32
    total, steps, done = 0.0, 0, False
    while not done:
        obs, r, done, info = env.step(steps % 2)
        total += r
        steps += 1
        assert steps <= 500
    assert info["episode_return"] == total
    # alternating actions should fail well before the 500-step cap
    assert info["terminal"] or steps == 500


def test_cartpole_determinism():
    a, b = CartPole(seed=3), CartPole(seed=3)
    oa, ob = a.reset(), b.reset()
    np.testing.assert_array_equal(oa, ob)
    for t in range(50):
        ra = a.step(t % 2)
        rb = b.step(t % 2)
        np.testing.assert_array_equal(ra[0], rb[0])
        if ra[2]:
            break


def test_bilinear_resize_constant_and_range():
    img = np.full((210, 160), 117.0)
    out = bilinear_resize(img, 84, 84)
    assert out.shape == (84, 84)
    np.testing.assert_allclose(out, 117.0, atol=1e-4)
    grad = np.tile(np.arange(160, dtype=np.float32), (210, 1))
    outg = bilinear_resize(grad, 84, 84)
    assert outg.min() >= 0 and outg.max() <= 159
    assert outg[0, -1] > outg[0, 0]  # preserves monotone gradient


def test_synthetic_atari_raw():
    raw = SyntheticAtari(seed=0)
    frame = raw.reset()
    assert frame.shape == (210, 160, 3) and frame.dtype == np.uint8
    assert raw.lives == 5
    # ball is drawn on even raw frames, absent on odd ones (flicker)
    f1, _, _ = raw.step(0)  # frame_count 1 (odd) -> no ball
    f2, _, _ = raw.step(0)  # frame_count 2 (even) -> ball
    assert (f2 == 236).sum() > (f1 == 236).sum()


def test_synthetic_atari_episode_ends():
    raw = SyntheticAtari(seed=1)
    raw.reset()
    done, total_r, steps = False, 0.0, 0
    while not done:
        frame, r, done = raw.step(0)  # never move: will miss often
        total_r += r
        steps += 1
        assert steps < 100_000
    assert raw.lives == 0


def test_atari_preprocessing_pipeline():
    cfg = EnvConfig(id="PongNoFrameskip-v4", kind="atari")
    env = make_env(cfg, seed=0)
    obs = env.reset()
    assert obs.shape == (84, 84, 4) and obs.dtype == np.uint8
    assert env.spec.num_actions == 6
    obs2, r, done, info = env.step(0)
    assert obs2.shape == (84, 84, 4)
    assert r in (-1.0, 0.0, 1.0)  # clipped
    assert "lives" in info and "terminal" in info
    # frame stack shifts by one plane per step
    obs3, _, _, _ = env.step(0)
    np.testing.assert_array_equal(obs3[..., 2], obs2[..., 3])
    np.testing.assert_array_equal(obs3[..., 1], obs2[..., 2])


def test_atari_maxpool_defeats_flicker():
    """With frame-skip+max-pool the ball must be visible in every obs."""
    cfg = EnvConfig(kind="atari", max_noop_start=0, episodic_life=False)
    env = make_env(cfg, seed=0)
    env.reset()
    ball_visible = []
    for _ in range(20):
        obs, _, done, _ = env.step(0)
        newest = obs[..., -1].astype(np.int32)
        # ball gray level ~236 vs paddle ~117 vs bg ~13
        ball_visible.append((newest > 200).sum() > 0)
        if done:
            env.reset()
    assert all(ball_visible)


def test_atari_episodic_life():
    cfg = EnvConfig(kind="atari", max_noop_start=0, episodic_life=True)
    env = make_env(cfg, seed=0)
    env.reset()
    # run until first life loss
    for _ in range(2000):
        obs, r, done, info = env.step(0)
        if done:
            break
    assert done and info["terminal"] and info["lives"] == 4
    # pseudo-reset continues same raw episode (lives stay at 4)
    env.reset()
    _, _, _, info2 = env.step(0)
    assert info2["lives"] in (3, 4)


def test_grayscale_weights():
    frame = np.zeros((2, 2, 3), np.uint8)
    frame[..., 1] = 100
    np.testing.assert_allclose(grayscale(frame), 58.7)


def test_pendulum():
    env = PendulumSwingUp(seed=0)
    obs = env.reset()
    assert obs.shape == (3,)
    assert abs(float(np.hypot(obs[0], obs[1])) - 1.0) < 1e-5
    total = 0.0
    for _ in range(200):
        obs, r, done, info = env.step(np.array([0.5]))
        assert r <= 0.0
        total += r
    assert done and abs(info["episode_return"] - total) < 1e-6


def test_vector_env_autoreset():
    envs = SyncVectorEnv([CartPole(seed=i) for i in range(4)])
    obs = envs.reset()
    assert obs.shape == (4, 4)
    saw_done = False
    for t in range(600):
        obs, r, dones, infos = envs.step(np.ones(4, np.int32))
        assert obs.shape == (4, 4) and dones.shape == (4,)
        if dones.any():
            saw_done = True
            i = int(np.argmax(dones))
            assert "episode_return" in infos[i]
            break
    assert saw_done


def test_make_env_unknown_kind():
    with pytest.raises(ValueError):
        make_env(EnvConfig(kind="doom"), seed=0)


def test_dm_control_adapter_if_available():
    from ape_x_dqn_tpu.envs.control import HAVE_DM_CONTROL, make_control
    if not HAVE_DM_CONTROL:
        pytest.skip("dm_control not installed")
    from ape_x_dqn_tpu.configs import EnvConfig
    env = make_control(EnvConfig(id="cartpole_balance", kind="control"),
                       seed=0)
    obs = env.reset()
    assert obs.dtype == np.float32 and obs.shape == env.spec.obs_shape
    o, r, done, info = env.step(np.zeros(env.spec.action_dim, np.float32))
    assert o.shape == env.spec.obs_shape and "terminal" in info


def test_atari_truncation_full_resets_with_episodic_life():
    """Regression: time-limit truncation with episodic_life must force a
    full raw reset instead of pseudo-resetting forever."""
    cfg = EnvConfig(kind="atari", max_noop_start=0, episodic_life=True,
                    max_episode_frames=12)
    env = make_env(cfg, seed=0)
    env.reset()
    for _ in range(3):
        _, _, done, info = env.step(0)
    assert done and "episode_return" in info
    env.reset()
    # after the forced full reset the frame counter restarts
    _, _, done2, info2 = env.step(0)
    assert not done2


def test_vector_env_keeps_terminal_obs():
    envs = SyncVectorEnv([CartPole(seed=i) for i in range(2)])
    envs.reset()
    for _ in range(600):
        obs, r, dones, infos = envs.step(np.zeros(2, np.int32))
        if dones.any():
            i = int(np.argmax(dones))
            assert "terminal_obs" in infos[i]
            # reset obs differs from the terminal obs it replaced
            assert not np.array_equal(infos[i]["terminal_obs"], obs[i])
            break
    else:
        raise AssertionError("no episode ended")


def test_native_preproc_matches_numpy():
    """The fused C++ observation kernel (cpp/preproc.cpp) must be
    bit-identical to the numpy grayscale+bilinear_resize path, so the
    two are interchangeable mid-run (envs/atari.py _observe)."""
    from ape_x_dqn_tpu.envs import native
    from ape_x_dqn_tpu.envs.atari import bilinear_resize, grayscale

    if not native.available():
        pytest.skip("no g++ toolchain for the native kernel")
    rng = np.random.default_rng(0)
    for h, w, out in [(210, 160, 84), (64, 48, 84), (84, 84, 84),
                      (37, 91, 10)]:
        f0 = rng.integers(0, 256, (h, w, 3)).astype(np.uint8)
        f1 = rng.integers(0, 256, (h, w, 3)).astype(np.uint8)
        # pair (max-pooled) and single-frame calls
        for a, b in [(f0, f1), (f0, None)]:
            fm = a if b is None else np.maximum(a, b)
            ref = np.clip(bilinear_resize(grayscale(fm), out, out),
                          0, 255).astype(np.uint8)
            got = native.preproc(a, b, out, out)
            np.testing.assert_array_equal(got, ref,
                                          err_msg=f"{h}x{w}->{out}")


def test_explicit_dm_control_id_errors_without_dm_control(monkeypatch):
    """An underscore id explicitly names a dm_control task; with
    dm_control absent it must raise, not silently train the 3-d
    synthetic pendulum under the requested label."""
    from ape_x_dqn_tpu.envs import control

    monkeypatch.setattr(control, "HAVE_DM_CONTROL", False)
    with pytest.raises(ImportError, match="dm_control"):
        control.make_control(EnvConfig(id="humanoid_stand",
                                       kind="control"), seed=0)
    # the no-underscore native stand-in still works
    env = control.make_control(EnvConfig(id="pendulum", kind="control"),
                               seed=0)
    assert env.spec.obs_shape == (3,)
