"""obs/ layer coverage (ISSUE 2): span tracer, metric registry,
heartbeat watchdog, the obs facade, the report CLI, and the two
integration bars — a single-process catch run with tracing + watchdog
ON producing a loadable Perfetto trace with non-empty staleness
histograms, and a deliberately-stalled actor turning a silent driver
hang into an attributed StallError."""

import json
import subprocess
import sys
import threading

import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig,
    NetworkConfig, ObsConfig, ReplayConfig, get_config)
from ape_x_dqn_tpu.obs.core import (
    NULL_OBS, Obs, SampleAgeTracker, build_obs)
from ape_x_dqn_tpu.obs.health import (
    HeartbeatRegistry, HeartbeatWatchdog, StallError)
from ape_x_dqn_tpu.obs.registry import (
    Histogram, MetricRegistry, geometric_edges)
from ape_x_dqn_tpu.obs.report import format_report, summarize
from ape_x_dqn_tpu.obs.trace import SpanTracer, load_trace, span_names
from ape_x_dqn_tpu.utils.metrics import Metrics


# -- tracer ----------------------------------------------------------------

def test_tracer_writes_valid_perfetto_json(tmp_path):
    path = str(tmp_path / "trace.json")
    tracer = SpanTracer(path)
    with tracer.span("learner.train", k=4):
        with tracer.span("replay.sample"):
            pass
    tracer.mark("learner.target_sync", fused_into="learner.train")

    def worker():
        with tracer.span("actor.step"):
            pass

    t = threading.Thread(target=worker, name="actor-0")
    t.start()
    t.join()
    tracer.close()
    trace = load_trace(path)  # json.load would raise on a broken file
    assert span_names(trace) == {"learner.train", "replay.sample",
                                 "learner.target_sync", "actor.step"}
    evs = trace["traceEvents"]
    # thread metadata rows name the tracks (Perfetto track labels)
    tnames = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
    assert "actor-0" in tnames
    sync = next(e for e in evs if e["name"] == "learner.target_sync")
    assert sync["args"]["fused_into"] == "learner.train"
    # spans nest: the inner sample sits inside the outer train window
    train = next(e for e in evs if e["name"] == "learner.train")
    sample = next(e for e in evs if e["name"] == "replay.sample")
    assert train["ts"] <= sample["ts"]
    assert sample["ts"] + sample["dur"] <= train["ts"] + train["dur"] + 1


def test_tracer_bounded_buffer(tmp_path):
    path = str(tmp_path / "trace.json")
    tracer = SpanTracer(path, max_events=5)
    for _ in range(12):
        with tracer.span("s"):
            pass
    tracer.close()
    trace = load_trace(path)
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(spans) == 5
    assert trace["otherData"]["dropped_events"] == 7
    # aggregates keep counting past the buffer cap
    assert tracer.aggregates()["s"]["count"] == 12


# -- registry --------------------------------------------------------------

def test_geometric_edges_span_orders_of_magnitude():
    edges = geometric_edges(1.0, 1e3, per_decade=2)
    assert edges[0] == pytest.approx(1.0)
    assert edges[-1] == pytest.approx(1e3)
    assert len(edges) == 7  # 3 decades x 2 + 1
    assert all(a < b for a, b in zip(edges, edges[1:]))


def test_histogram_observe_and_percentiles():
    h = Histogram("h", edges=(1.0, 10.0, 100.0))
    for v in (0.5, 2.0, 3.0, 50.0):
        h.observe(v)
    h.observe(float("nan"))  # diverged TD must not poison buckets
    h.observe_many(np.array([5.0, 500.0, np.nan]))
    assert h.count == 6
    snap = h.snapshot()
    assert snap["count"] == 6
    assert snap["min"] == 0.5 and snap["max"] == 500.0
    # counts: <=1, (1,10], (10,100], >100
    assert snap["counts"] == [1, 3, 1, 1]
    assert snap["sum"] == pytest.approx(560.5)
    # p50 lands in the (1, 10] bucket -> its upper edge
    assert snap["p50"] == 10.0
    # p99 beyond the last edge degrades to the observed max
    assert snap["p99"] == 500.0
    json.dumps(snap)  # snapshot must be directly JSON-serializable


def test_histogram_scalar_bulk_agree():
    vals = np.concatenate([np.random.default_rng(0).uniform(0.1, 2e5, 500),
                           [0.0, 1e7]])
    a = Histogram("a", geometric_edges())
    b = Histogram("b", geometric_edges())
    for v in vals:
        a.observe(v)
    b.observe_many(vals)
    sa, sb = a.snapshot(), b.snapshot()
    assert sa["counts"] == sb["counts"]
    assert (sa["count"], sa["min"], sa["max"]) == \
        (sb["count"], sb["min"], sb["max"])
    assert sa["sum"] == pytest.approx(sb["sum"])  # accumulation order
    assert (sa["p50"], sa["p90"], sa["p99"]) == \
        (sb["p50"], sb["p90"], sb["p99"])


def test_registry_publish_one_jsonl_record(tmp_path):
    path = str(tmp_path / "m.jsonl")
    metrics = Metrics(log_path=path)
    reg = MetricRegistry()
    reg.counter("adds").inc(3)
    reg.gauge("occupancy").set(128)
    reg.histogram("age", (1.0, 10.0)).observe(4.0)
    reg.publish(metrics, step=7, extra={"span/learner.train":
                                        {"count": 2, "total_s": 0.5}})
    metrics.close()
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["step"] == 7
    assert rec["ctr/adds"] == 3.0
    assert rec["gauge/occupancy"] == 128.0
    assert rec["hist/age"]["count"] == 1
    assert rec["span/learner.train"]["count"] == 2


# -- heartbeats / watchdog -------------------------------------------------

def test_heartbeat_watchdog_attributes_stalest():
    reg = HeartbeatRegistry()
    reg.register("actor-0", now=0.0)
    reg.register("learner", now=0.0)
    reg.beat("learner", "grad_step 100", now=9.5)
    wd = HeartbeatWatchdog(reg, timeout_s=5.0)
    wd.check(now=4.0)  # nobody stale yet
    with pytest.raises(StallError) as ei:
        wd.check(now=10.0)  # actor-0 silent 10s, learner only 0.5s
    e = ei.value
    assert e.component == "actor-0"
    assert e.staleness_s == pytest.approx(10.0)
    assert "actor-0" in str(e) and "10.0s" in str(e)
    # a cleared (legitimately finished) component is never attributed
    reg.clear("actor-0")
    wd.check(now=10.0)


def test_registered_but_never_beating_component_is_attributed():
    """register() seeds the stamp: a component wedged before its first
    loop iteration still gets named."""
    reg = HeartbeatRegistry()
    reg.register("ingest", now=0.0)
    with pytest.raises(StallError, match="ingest"):
        HeartbeatWatchdog(reg, timeout_s=1.0).check(now=2.0)


# -- facade ----------------------------------------------------------------

def test_null_obs_method_parity():
    """Runtime code calls the facade unconditionally; every public Obs
    method must exist on NullObs (and vice versa) or the disabled path
    diverges from the enabled one."""
    def methods(cls):
        return {n for n in dir(cls)
                if not n.startswith("_") and callable(getattr(cls, n))}

    assert methods(Obs) == methods(type(NULL_OBS))


def test_build_obs_gating(tmp_path):
    metrics = Metrics()
    assert build_obs(None, metrics) is NULL_OBS
    assert build_obs(ObsConfig(enabled=False), metrics) is NULL_OBS
    obs = build_obs(ObsConfig(enabled=True), metrics)
    assert isinstance(obs, Obs) and obs.enabled


def test_sample_age_tracker_skip_to_head():
    """The host mirror must match replay/packing.ring_write_start: a
    block that would cross the ring boundary restarts at slot 0."""
    tr = SampleAgeTracker(capacity=8)
    tr.on_add(6, grad_step=10)   # slots 0..5 @ step 10
    tr.on_add(4, grad_step=20)   # 6+4 > 8: skip to head, slots 0..3 @ 20
    ages = tr.ages(np.array([0, 3, 4, 5]), grad_step=25)
    assert list(ages) == [5, 5, 15, 15]


def test_obs_param_lag_and_publish(tmp_path):
    path = str(tmp_path / "m.jsonl")
    metrics = Metrics(log_path=path)
    obs = build_obs(ObsConfig(enabled=True, heartbeat_timeout_s=0.0),
                    metrics)
    obs.set_learner_step(120)
    obs.on_server_batch(items=16, params_version=100, queue_depth=2)
    obs.observe("td_abs", 0.5)
    obs.count("replay_adds", 64)
    obs.close(120)
    metrics.close()
    recs = [json.loads(l) for l in open(path)]
    final = recs[-1]
    assert final["hist/param_lag_steps"]["count"] == 1
    assert final["hist/param_lag_steps"]["max"] == 20.0
    assert final["hist/server_batch_items"]["count"] == 1
    assert final["ctr/replay_adds"] == 64.0
    assert final["gauge/server_queue_depth"] == 2.0
    # pre-seeded instruments publish even when empty (self-describing
    # stream: a missing key and an empty histogram are different facts)
    assert final["hist/sample_age_steps"]["count"] == 0


# -- report ----------------------------------------------------------------

def _synthetic_records():
    return [
        {"step": 0, "run_name": "t", "version": "0.2.0",
         "sample_chunk": 4, "sample_prefetch": False},
        {"step": 500, "frames": 10_000, "frames_per_s": 950.0,
         "grad_steps_per_s": 120.0, "loss": 0.02,
         "span/learner.train": {"count": 125, "total_s": 3.5,
                                "max_s": 0.2},
         "span/replay.add": {"count": 40, "total_s": 1.0, "max_s": 0.1},
         "hist/sample_age_steps": {
             "count": 1000, "sum": 5e8, "min": 10.0, "max": 900_000.0,
             "edges": [1.0, 1e6], "counts": [0, 990, 10],
             "p50": 1e6, "p90": 1e6, "p99": 1_000_000.0},
         "hist/param_lag_steps": {
             "count": 50, "sum": 500.0, "min": 0.0, "max": 40.0,
             "edges": [1.0, 1e5], "counts": [10, 40, 0],
             "p50": 40.0, "p90": 40.0, "p99": 40.0}},
        {"step": 510, "stall_component": "actor-3",
         "stall_staleness_s": 131.0, "stall_note": "frame 9000"},
    ]


def test_report_summarize_and_format():
    s = summarize(_synthetic_records())
    assert s["header"]["version"] == "0.2.0"
    assert s["throughput"]["grad_steps_per_s"] == 120.0
    assert set(s["spans"]) == {"learner.train", "replay.add"}
    assert s["stalls"] == [{"step": 510, "component": "actor-3",
                            "staleness_s": 131.0, "note": "frame 9000"}]
    text = format_report(s)
    assert "learner.train" in text
    assert "sample_age_steps" in text
    # the unhealthy p99 (beyond HEALTHY's 200k bound) gets flagged
    assert "exceeds healthy" in text
    assert "component=actor-3" in text


def test_report_multichip_section():
    """The dp-scaling records the bench lane writes (`multichip/dpN/*`
    keys + the top-level virtual_devices flag) regroup into a per-dp
    curve and render as the multichip table, with the below-healthy
    efficiency warn and the virtual-device framing."""
    recs = [
        {"step": 0, "multichip/dp1/grad_steps_per_s": 0.9,
         "multichip/dp1/efficiency": 1.0,
         "multichip/dp1/shard_fill_min": 1.0,
         "multichip/dp1/shard_fill_max": 1.0,
         "multichip/dp1/ingest_rows_per_s": 5000.0},
        {"step": 1, "multichip/dp2/grad_steps_per_s": 0.7,
         "multichip/dp2/efficiency": 0.39,
         "multichip/dp2/shard_fill_min": 0.98,
         "multichip/dp2/shard_fill_max": 1.0,
         "multichip/dp2/mfu_train_dist": 0.012,
         "multichip/dp2/device_ms_train_dist": 45.0,
         "multichip/dp2/ingest_rows_per_s": 4000.0},
        {"step": 2, "virtual_devices": True,
         "gauge/dp_scaling_efficiency": 0.39},
    ]
    s = summarize(recs)
    assert sorted(s["multichip"]) == [1, 2]
    assert s["multichip"][2]["efficiency"] == 0.39
    assert s["virtual_devices"] is True
    text = format_report(s)
    assert "multichip scaling" in text
    assert "virtual devices" in text
    assert "0.39x" in text
    assert "below healthy" in text  # dp=2 efficiency warn fires


def test_report_cold_tier_section_and_thrash_check():
    """The tiered-replay section renders door + disk-rung lines from
    the cold_* instruments, and the bespoke check_violations row fires
    when door drops outrun displacements AND the disk rung did not
    absorb them — but stays quiet once spills keep pace (PR 16)."""
    from ape_x_dqn_tpu.obs.report import check_violations
    rec = {"step": 0,
           "gauge/cold_segments": 12.0, "gauge/cold_bytes": 4096.0,
           "gauge/cold_compression_ratio": 3.1,
           "gauge/cold_disk_segments": 16.0,
           "gauge/cold_disk_transitions": 2048.0,
           "gauge/cold_disk_bytes": 65536.0,
           "ctr/cold_evictions": 100.0, "ctr/cold_recalls": 5.0,
           "ctr/cold_displaced": 10.0, "ctr/cold_dropped": 40.0,
           "ctr/cold_disk_spills": 3.0,
           "ctr/cold_disk_promotions": 2.0,
           "ctr/cold_disk_queue_full": 1.0}
    s = summarize([rec])
    text = format_report(s)
    assert "tiered replay" in text
    assert "disk rung" in text
    assert "spills=3" in text
    assert "door drops outrun displacements" in text  # ⚠ warn line
    viols = check_violations(s)
    assert any("cold_dropped" in v and "thrashing" in v for v in viols)
    # disk rung absorbing the overflow (spills >= drops) clears both
    # the section warning and the check violation
    rec["ctr/cold_disk_spills"] = 64.0
    s2 = summarize([rec])
    assert "door drops outrun" not in format_report(s2)
    assert not any("cold_dropped" in v for v in check_violations(s2))


def test_report_cli_subprocess(tmp_path):
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = tmp_path / "run.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in _synthetic_records())
                    + "\n{torn tail")
    out = subprocess.run(
        [sys.executable, "-m", "ape_x_dqn_tpu.obs.report", str(path)],
        capture_output=True, text=True, timeout=120, cwd=repo_root)
    assert out.returncode == 0, out.stderr
    assert "stage-time breakdown" in out.stdout
    assert "stall events: 1" in out.stdout
    js = subprocess.run(
        [sys.executable, "-m", "ape_x_dqn_tpu.obs.report", str(path),
         "--json"], capture_output=True, text=True, timeout=120,
        cwd=repo_root)
    assert js.returncode == 0, js.stderr
    assert json.loads(js.stdout)["header"]["run_name"] == "t"


# -- integration: traced single-process run --------------------------------

def test_single_process_catch_traced(tmp_path):
    """Tier-1 acceptance (ISSUE 2): a short catch run with tracing +
    watchdog ON produces a loadable Perfetto trace containing spans for
    every named stage, and non-empty staleness histograms in the
    JSONL."""
    from ape_x_dqn_tpu.runtime.single_process import train_single_process

    trace = str(tmp_path / "trace.json")
    jsonl = str(tmp_path / "run.jsonl")
    cfg = get_config("pong").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari"),
        network=NetworkConfig(kind="nature_cnn", dueling=True,
                              compute_dtype="float32"),
        replay=ReplayConfig(kind="prioritized", capacity=2048,
                            min_fill=300),
        learner=LearnerConfig(batch_size=16, n_step=3,
                              target_sync_every=16, sample_chunk=2),
        obs=ObsConfig(enabled=True, trace_path=trace,
                      publish_every_steps=50, heartbeat_timeout_s=120.0),
    )
    metrics = Metrics(log_path=jsonl)
    out = train_single_process(cfg, total_env_frames=420, metrics=metrics,
                               train_every=2)
    metrics.close()
    assert out["grad_steps"] > 0
    names = span_names(load_trace(trace))
    assert names >= {"actor.step", "replay.add", "replay.sample",
                     "learner.learn", "replay.priority_update",
                     "learner.target_sync"}, names
    recs = [json.loads(l) for l in open(jsonl)]
    hists = [r for r in recs if "hist/sample_age_steps" in r]
    assert hists, "no registry snapshot reached the JSONL"
    last = hists[-1]
    assert last["hist/sample_age_steps"]["count"] > 0
    assert last["hist/param_lag_steps"]["count"] > 0
    assert last["hist/td_abs"]["count"] > 0
    # sampled ages are bounded by what was ever written
    assert last["hist/sample_age_steps"]["max"] <= out["grad_steps"]
    # the span aggregates rode along for the offline report
    assert any(k.startswith("span/replay.sample") for k in last)


# -- integration: stalled actor raises, not hangs --------------------------

class _StallingActor:
    """Accepts the real actor constructor signature, then wedges: never
    beats, never ships experience. The driver must convert this into an
    attributed StallError instead of hanging forever."""

    def __init__(self, cfg, index, query_fn, transport, seed=0,
                 episode_callback=None, obs=None):
        self.index = index
        self.frames = 0

    def run(self, max_frames, stop_event=None):
        import time
        while stop_event is None or not stop_event.is_set():
            time.sleep(0.02)
        return self.frames


def test_driver_stalled_actor_raises_attributed(monkeypatch, tmp_path):
    """ISSUE 2 acceptance: with the watchdog enabled, a wedged actor
    produces StallError naming the component and its staleness — and
    the trace/metrics artifacts still get flushed on the crash path."""
    from ape_x_dqn_tpu.runtime.driver import ApexDriver

    monkeypatch.setattr("ape_x_dqn_tpu.runtime.family.Actor",
                        _StallingActor)
    trace = str(tmp_path / "trace.json")
    jsonl = str(tmp_path / "m.jsonl")
    cfg = get_config("cartpole_smoke").replace(
        # supervise=False: this test pins the legacy fatal path (wedged
        # actor -> attributed StallError). With supervision on (the
        # default) the supervisor restarts then quarantines the slot
        # instead of raising — that path is tests/test_chaos.py's.
        actors=ActorConfig(num_actors=1, base_eps=0.6, ingest_batch=16,
                           supervise=False),
        replay=ReplayConfig(kind="prioritized", capacity=2048,
                            min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        obs=ObsConfig(enabled=True, trace_path=trace,
                      heartbeat_timeout_s=1.5),
    )
    driver = ApexDriver(cfg, metrics=Metrics(log_path=jsonl))
    with pytest.raises(StallError) as ei:
        driver.run(total_env_frames=600, max_grad_steps=30,
                   wall_clock_limit_s=120)
    e = ei.value
    assert e.component == "actor-0", e.component
    assert e.staleness_s >= 1.5
    # crash-path artifacts: the stall rode the JSONL and the trace flushed
    recs = [json.loads(l) for l in open(jsonl)]
    stall = [r for r in recs if r.get("stall_component")]
    assert stall and stall[-1]["stall_component"] == "actor-0"
    load_trace(trace)  # valid JSON even on the crash path
