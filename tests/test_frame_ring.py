"""Frame-ring replay (replay/frame_ring.py): segment assembly, device
reconstruction, learner integration, and flat-vs-frame actor equivalence
(SURVEY.md §7 hard part 2 "ingest bandwidth"; §2.2 replay capacity)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, NetworkConfig,
    ReplayConfig, RunConfig)
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.replay.frame_ring import (
    FrameRingReplay, FrameSegmentBuilder, frame_segment_spec)
from ape_x_dqn_tpu.runtime.actor import Actor


H = W = 6
STACK = 4
N_STEP = 3
B = 4  # tiny segments so episode-end padding is exercised often


def _frame(i):
    """Distinct deterministic frame per step index."""
    return np.full((H, W), i % 251, np.uint8)


class _ScriptedEpisodes:
    """Feeds the builder like an actor would, tracking the oracle frame
    log host-side so reconstructions can be checked exactly."""

    def __init__(self, builder: FrameSegmentBuilder):
        self.b = builder
        self.oracle = {}  # global transition counter -> (obs, next_obs)
        self.meta = {}    # counter -> (action, reward, discount)
        self.count = 0

    def run_episode(self, length: int, first_frame: int,
                    spans=None) -> None:
        # wrapper semantics: full reset -> zero-padded stack
        log = [np.zeros((H, W), np.uint8)] * (STACK - 1) \
            + [_frame(first_frame)]
        reset_obs = np.stack(log, axis=-1)
        self.b.on_reset(reset_obs)
        for t in range(length):
            log.append(_frame(first_frame + t + 1))
            self.b.on_step(np.stack(log[-STACK:], axis=-1))
        # emit transitions in start order with the episode's spans
        for t in range(length):
            span = (spans[t] if spans is not None
                    else min(N_STEP, length - t))
            if t + span > length:
                span = length - t
            action, reward, disc = t % 4, float(t), 0.5  # 4 = test env's
            # num_actions: out-of-range actions NaN the gathered Q
            self.b.add(action, reward, disc, span, priority=1.0 + t)
            obs = np.stack(log[t:t + STACK], axis=-1)
            nxt = np.stack(log[t + span:t + span + STACK], axis=-1)
            self.meta[self.count] = (action, reward, disc)
            self.oracle[self.count] = (obs, nxt)
            self.count += 1


def test_segment_builder_shapes_and_padding():
    b = FrameSegmentBuilder(B, N_STEP, STACK)
    s = _ScriptedEpisodes(b)
    s.run_episode(length=6, first_frame=10)  # 6 = B + 2 -> one pad segment
    segs = b.flush()
    assert len(segs) == 2
    F = B + N_STEP + STACK - 1
    for seg in segs:
        assert seg["seg_frames"].shape == (1, F, H, W)
        assert seg["action"].shape == (1, B)
    # second segment: 2 live + 2 dead pads
    assert list(segs[1]["next_off"][0] > 0) == [True, True, False, False]
    assert list(segs[1]["priorities"][0][2:]) == [0.0, 0.0]


def test_device_reconstruction_matches_oracle():
    """Every stack rebuilt on device equals the actor-side stack it
    encodes — across segment padding, short episodes, and ring wrap."""
    replay = FrameRingReplay(capacity=32, seg_transitions=B, n_step=N_STEP,
                             obs_shape=(H, W, STACK))
    state = replay.init()
    b = FrameSegmentBuilder(B, N_STEP, STACK)
    s = _ScriptedEpisodes(b)
    s.run_episode(length=6, first_frame=10)
    s.run_episode(length=3, first_frame=50)   # shorter than B
    s.run_episode(length=9, first_frame=100)
    segs = b.flush()

    slot = {}  # transition slot -> oracle counter
    counter = 0
    for gseg, seg in enumerate(segs):
        items = {k: jnp.asarray(seg[k]) for k in
                 ("seg_frames", "action", "reward", "discount", "next_off")}
        state = replay.add(state, items, jnp.asarray(seg["priorities"]))
        for j in range(B):
            if seg["next_off"][0][j] > 0:
                slot[gseg * B + j] = counter
                counter += 1
    assert counter == s.count

    idx = jnp.asarray(sorted(slot), jnp.int32)
    got = replay._gather(state, idx)
    for row, i in enumerate(sorted(slot)):
        obs, nxt = s.oracle[slot[i]]
        action, reward, disc = s.meta[slot[i]]
        np.testing.assert_array_equal(np.asarray(got["obs"][row]), obs,
                                      err_msg=f"obs slot {i}")
        np.testing.assert_array_equal(np.asarray(got["next_obs"][row]), nxt,
                                      err_msg=f"next_obs slot {i}")
        assert int(got["action"][row]) == action
        assert float(got["reward"][row]) == reward
        assert float(got["discount"][row]) == disc


def test_ring_wrap_overwrites_whole_segments():
    replay = FrameRingReplay(capacity=8, seg_transitions=4, n_step=N_STEP,
                             obs_shape=(H, W, STACK))  # S = 2 segments
    state = replay.init()
    b = FrameSegmentBuilder(4, N_STEP, STACK)
    s = _ScriptedEpisodes(b)
    s.run_episode(length=12, first_frame=0)  # 3 segments -> wraps
    segs = b.flush()
    for seg in segs:
        items = {k: jnp.asarray(seg[k]) for k in
                 ("seg_frames", "action", "reward", "discount", "next_off")}
        state = replay.add(state, items, jnp.asarray(seg["priorities"]))
    assert int(state.size) == 8
    assert int(state.pos) == 1  # 3 segments into 2 slots
    # slot 0 now holds the THIRD segment (starts 8..11)
    got = replay._gather(state, jnp.asarray([0], jnp.int32))
    obs, _ = s.oracle[8]
    np.testing.assert_array_equal(np.asarray(got["obs"][0]), obs)


def test_dead_slots_never_sampled_and_stay_dead():
    replay = FrameRingReplay(capacity=8, seg_transitions=4, n_step=N_STEP,
                             obs_shape=(H, W, STACK))
    state = replay.init()
    b = FrameSegmentBuilder(4, N_STEP, STACK)
    s = _ScriptedEpisodes(b)
    s.run_episode(length=2, first_frame=0)  # 2 live + 2 dead in segment 0
    (seg,) = b.flush()
    items = {k: jnp.asarray(seg[k]) for k in
             ("seg_frames", "action", "reward", "discount", "next_off")}
    state = replay.add(state, items, jnp.asarray(seg["priorities"]))
    _, idx, w = replay.sample(state, jax.random.key(0), 256)
    assert np.all(np.asarray(idx) <= 1), "sampled a dead/pad slot"
    assert np.all(np.asarray(w) > 0)
    # priority write-back at a dead slot must not resurrect it
    state2 = replay.update_priorities(
        state, jnp.asarray([2, 3], jnp.int32),
        jnp.asarray([9.9, 9.9], jnp.float32))
    leaves = np.asarray(state2.tree[8:])
    assert leaves[2] == 0.0 and leaves[3] == 0.0


def test_learner_runs_on_frame_ring():
    """DQNLearner train_step over frame-ring storage: loss finite,
    priorities written back, donation-safe."""
    from ape_x_dqn_tpu.envs.base import EnvSpec
    from ape_x_dqn_tpu.models import build_network
    from ape_x_dqn_tpu.runtime.learner import DQNLearner
    from ape_x_dqn_tpu.utils.rng import component_key

    spec = EnvSpec(obs_shape=(H, W, STACK), obs_dtype=np.dtype(np.uint8),
                   discrete=True, num_actions=4)
    net = build_network(NetworkConfig(kind="mlp", mlp_hidden=(16,),
                                      dueling=False,
                                      compute_dtype="float32"), spec)
    params = net.init(component_key(0, "net_init"),
                      jnp.zeros((1, H, W, STACK), jnp.uint8))
    replay = FrameRingReplay(capacity=64, seg_transitions=B, n_step=N_STEP,
                             obs_shape=(H, W, STACK))
    lcfg = LearnerConfig(batch_size=16, n_step=N_STEP,
                         target_sync_every=10)
    learner = DQNLearner(net.apply, replay, lcfg)
    state = learner.init(params, replay.init(), component_key(0, "learner"))

    b = FrameSegmentBuilder(B, N_STEP, STACK)
    s = _ScriptedEpisodes(b)
    for e in range(8):
        s.run_episode(length=8, first_frame=e * 16)
    for seg in b.flush():
        items = {k: jnp.asarray(seg[k]) for k in
                 ("seg_frames", "action", "reward", "discount", "next_off")}
        state = learner.add(state, items, jnp.asarray(seg["priorities"]))
    assert int(state.replay.size) == 64
    tree_before = np.asarray(state.replay.tree).copy()
    state, m = learner.train_step(state)
    assert np.isfinite(float(m["loss"]))
    assert not np.array_equal(np.asarray(state.replay.tree), tree_before), \
        "train_step must write back updated priorities"
    state, m = learner.train_many(state, 3)
    assert np.isfinite(float(m["loss"]))


# -- actor equivalence: the gold test ---------------------------------------


def _catch_cfg(storage: str) -> RunConfig:
    return RunConfig(
        name="catch",
        env=EnvConfig(id="catch", kind="synthetic_atari", frame_skip=4,
                      max_noop_start=4),
        network=NetworkConfig(kind="nature_cnn", dueling=True),
        replay=ReplayConfig(kind="prioritized", capacity=4096, min_fill=128,
                            storage=storage, seg_transitions=8,
                            segs_per_add=2),
        learner=LearnerConfig(batch_size=32, n_step=N_STEP,
                              target_sync_every=100, publish_every=20),
        actors=ActorConfig(num_actors=1, base_eps=0.5, ingest_batch=8),
        inference=InferenceConfig(max_batch=4, deadline_ms=0.5),
        eval_every_steps=0, eval_episodes=0,
    )


class _CaptureTransport:
    def __init__(self):
        self.batches = []

    def send_experience(self, batch):
        self.batches.append(batch)


def _zero_query(obs):
    return np.zeros(18, np.float32)  # greedy ties -> argmax 0, same both


def test_actor_equivalence_flat_vs_frame_ring():
    """Identical env + seed + policy: the frame-ring actor's segments,
    reconstructed, must equal the flat actor's shipped transitions
    field-for-field (including pixels) in the same order."""
    flat_t, ring_t = _CaptureTransport(), _CaptureTransport()
    a_flat = Actor(_catch_cfg("flat"), 0, _zero_query, flat_t)
    a_ring = Actor(_catch_cfg("frame_ring"), 0, _zero_query, ring_t)
    assert a_ring._seg is not None and a_flat._seg is None
    a_flat.run(max_frames=150)
    a_ring.run(max_frames=150)

    # flatten the flat actor's stream
    flat = {k: np.concatenate([b[k] for b in flat_t.batches])
            for k in ("obs", "action", "reward", "next_obs", "discount",
                      "priorities")}

    # reconstruct the ring actor's stream through the real device path
    replay = FrameRingReplay(capacity=1024, seg_transitions=8,
                             n_step=N_STEP, obs_shape=(84, 84, 4))
    state = replay.init()
    order = []  # global transition idx in ship order
    for g, seg in enumerate(ring_t.batches):
        items = {k: jnp.asarray(seg[k]) for k in
                 ("seg_frames", "action", "reward", "discount", "next_off")}
        state = replay.add(state, items, jnp.asarray(seg["priorities"]))
        order.extend(g * 8 + j for j in range(8)
                     if seg["next_off"][0][j] > 0)
    assert len(order) == flat["action"].shape[0], \
        "live transition counts differ"
    got = replay._gather(state, jnp.asarray(order, jnp.int32))
    np.testing.assert_array_equal(np.asarray(got["action"]), flat["action"])
    np.testing.assert_allclose(np.asarray(got["reward"]), flat["reward"],
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got["discount"]),
                               flat["discount"], rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(got["obs"]), flat["obs"])
    np.testing.assert_array_equal(np.asarray(got["next_obs"]),
                                  flat["next_obs"])
    # priorities ship identically too (dead pads excluded)
    ring_pris = np.concatenate(
        [seg["priorities"][0][np.asarray(seg["next_off"][0]) > 0]
         for seg in ring_t.batches])
    np.testing.assert_allclose(ring_pris, flat["priorities"], rtol=1e-6)


def test_frame_segment_spec_shapes():
    spec = frame_segment_spec(16, 3, (84, 84, 4), np.uint8)
    assert spec["seg_frames"].shape == (22, 84, 84)
    assert spec["action"].shape == (16,)


def test_apex_driver_end_to_end_frame_ring():
    """Full wiring over frame-ring storage: actors ship frame segments,
    ingest stages whole segments, the learner trains off reconstructed
    stacks — no errors, params published."""
    from ape_x_dqn_tpu.runtime.driver import ApexDriver

    cfg = _catch_cfg("frame_ring")
    driver = ApexDriver(cfg)
    assert driver._frame_mode
    out = driver.run(total_env_frames=1200, max_grad_steps=40,
                     wall_clock_limit_s=180)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 40, out
    # min_fill counts transitions (pads included), so env frames at the
    # moment training starts can sit just under it
    assert out["frames"] >= 100, out
    assert driver.server.params_version > 0


def test_apex_dist_driver_end_to_end_frame_ring():
    """The flagship layout (frame-ring replay shards over a dp=4 x tp=2
    mesh, segment round-robin across shards) end to end on the virtual
    8-device mesh."""
    from ape_x_dqn_tpu.configs import ParallelConfig
    from ape_x_dqn_tpu.runtime.driver import ApexDriver

    cfg = _catch_cfg("frame_ring")
    cfg = cfg.replace(
        parallel=ParallelConfig(dp=4, tp=2),
        # 42x42 frames (conv pyramid stays valid) keep the 8-virtual-
        # device CPU compile + step cost inside the test budget
        env=dataclasses.replace(cfg.env, resize=42))
    driver = ApexDriver(cfg)
    assert driver.is_dist and driver._frame_mode
    out = driver.run(total_env_frames=2400, max_grad_steps=30,
                     wall_clock_limit_s=240)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 30, out
    sizes = np.asarray(driver.state.replay.size)
    assert sizes.shape == (4,) and (sizes > 0).all(), sizes


def test_driver_rejects_frame_ring_for_non_dqn():
    from ape_x_dqn_tpu.runtime.driver import ApexDriver
    from ape_x_dqn_tpu.configs import get_config
    cfg = get_config("apex_dpg")
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay,
                                                 storage="frame_ring"))
    with pytest.raises(NotImplementedError):
        ApexDriver(cfg)
