"""Per-game suite trainer (runtime/suite.py): the north-star protocol
runner — per-game training runs, per-game checkpoints/metrics, honest
backend-marked aggregation, shard math, and resume-skip."""

import json

import pytest

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, ReplayConfig,
    get_config)
from ape_x_dqn_tpu.runtime.suite import (
    aggregate_suite, main as suite_main, run_suite_training, suite_games)


def test_suite_games_shard_partition():
    games = suite_games()
    assert len(games) == 57
    shards = [suite_games(shard=(i, 4)) for i in range(4)]
    assert sum(len(s) for s in shards) == 57
    assert sorted(g for s in shards for g in s) == sorted(games)
    with pytest.raises(ValueError):
        suite_games(shard=(4, 4))


def _suite_cfg():
    return get_config("pong").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari"),
        replay=ReplayConfig(kind="prioritized", capacity=4096,
                            min_fill=64, storage="frame_ring",
                            seg_transitions=8, segs_per_add=2),
        learner=LearnerConfig(batch_size=16, n_step=3,
                              target_sync_every=100, publish_every=20,
                              train_chunk=4),
        actors=ActorConfig(num_actors=1, envs_per_actor=2,
                           ingest_batch=16),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        parallel=get_config("cartpole_smoke").parallel,  # dp=1, tp=1
        eval_every_steps=0, eval_episodes=1,
    )


def test_suite_training_two_games(tmp_path):
    out = run_suite_training(
        _suite_cfg(), str(tmp_path / "suite"),
        games=("pong", "breakout"),
        max_grad_steps_per_game=30,
        wall_clock_limit_s_per_game=120)
    assert set(out["scores"]) == {"pong", "breakout"}
    assert out["backends"] == {"pong": "synthetic",
                               "breakout": "synthetic"}
    # synthetic backends can never emit the unmarked north-star key
    assert "median_hns" not in out and "median_hns_synthetic" in out
    assert out["complete"] is True
    for g in ("pong", "breakout"):
        assert not out["per_game"][g]["errors"], out["per_game"][g]
        assert out["per_game"][g]["grad_steps"] >= 30
        assert (tmp_path / "suite" / g / "result.json").exists()
        assert (tmp_path / "suite" / g / "metrics.jsonl").exists()
        assert (tmp_path / "suite" / g / "ckpt").exists()
    assert (tmp_path / "suite" / "suite.json").exists()

    # resume: completed games are skipped (result.json short-circuits;
    # a retrained game would need >=30 more grad steps of wall time)
    import time
    t0 = time.monotonic()
    out2 = run_suite_training(
        _suite_cfg(), str(tmp_path / "suite"),
        games=("pong", "breakout"),
        max_grad_steps_per_game=30,
        wall_clock_limit_s_per_game=120)
    assert time.monotonic() - t0 < 5.0, "resume retrained a done game"
    assert out2["scores"] == out["scores"]


def test_suite_rejects_no_eval():
    with pytest.raises(ValueError, match="eval_episodes"):
        run_suite_training(_suite_cfg().replace(eval_episodes=0),
                           "/tmp/unused", games=("pong",))


def test_suite_rejects_oversized_mesh_early():
    """atari57_apex carries dp=4 x tp=2; on a host without 8 chips the
    suite must fail before training with an actionable message, not
    deep inside mesh construction (round-3 verdict weak #6). Tests run
    with 8 virtual devices, so ask for more than 8."""
    from ape_x_dqn_tpu.configs import ParallelConfig
    cfg = _suite_cfg().replace(parallel=ParallelConfig(dp=8, tp=2))
    with pytest.raises(ValueError, match="parallel.dp=1"):
        run_suite_training(cfg, "/tmp/unused", games=("pong",))


def test_sharded_suite_writes_per_shard_files(tmp_path):
    """Shards sharing --out must not overwrite each other's aggregate,
    and a shard median must never appear under the suite-level key
    (round-3 advisor finding). The full suite.json comes only from
    aggregate_suite over the per-game result.json files."""
    out_dir = str(tmp_path / "suite")
    games = ("pong", "breakout")
    # two 1-game shards of the same 2-game list into the SAME out dir
    for i in range(2):
        agg = run_suite_training(
            _suite_cfg(), out_dir, games=games, shard=(i, 2),
            max_grad_steps_per_game=30,
            wall_clock_limit_s_per_game=120)
        assert agg["shard"] == [i, 2]
        assert "median_hns" not in agg
        assert "median_hns_synthetic" not in agg
        assert "shard_median_hns_synthetic" in agg
        assert (tmp_path / "suite" / f"suite.{i}of2.json").exists()
    assert not (tmp_path / "suite" / "suite.json").exists()

    # an aggregate over games still missing results must qualify its
    # median as partial — never the suite-level key
    part = aggregate_suite(out_dir, games=games + ("qbert",))
    assert part["complete"] is False
    assert "median_hns_synthetic" not in part
    assert "partial_median_hns_synthetic" in part

    full = aggregate_suite(out_dir, games=games)
    assert (tmp_path / "suite" / "suite.json").exists()
    assert full["complete"] is True
    assert set(full["scores"]) == set(games)
    assert "median_hns_synthetic" in full and "shard" not in full

    # --aggregate-only CLI reaches the same path
    rc = suite_main(["--out", out_dir, "--aggregate-only",
                     "--games", ",".join(games)])
    assert rc == 0
