"""CLI entry point (runtime/train.py): override plumbing + smoke runs."""

import json

import numpy as np

import pytest

from ape_x_dqn_tpu.configs import get_config
from ape_x_dqn_tpu.runtime.train import apply_overrides, main


def test_apply_overrides_typed():
    cfg = get_config("pong")
    cfg = apply_overrides(cfg, [
        "learner.batch_size=64",
        "learner.lr=0.001",
        "replay.kind=uniform",
        "network.dueling=false",
        "network.mlp_hidden=(32,16)",
        "actors.num_actors=3",
        "eval_every_steps=0",
    ])
    assert cfg.learner.batch_size == 64
    assert cfg.learner.lr == pytest.approx(1e-3)
    assert cfg.replay.kind == "uniform"
    assert cfg.network.dueling is False
    assert cfg.network.mlp_hidden == (32, 16)
    assert cfg.actors.num_actors == 3
    assert cfg.eval_every_steps == 0


def test_apply_overrides_optional_fields():
    """`float | None` fields have no reference value to coerce against;
    the literal itself must be parsed (regression: '1.0' landed as a
    string and the learner pacing check crashed with TypeError)."""
    cfg = get_config("pong")
    cfg = apply_overrides(cfg, ["learner.steps_per_frame_cap=1.0"])
    assert cfg.learner.steps_per_frame_cap == pytest.approx(1.0)
    assert isinstance(cfg.learner.steps_per_frame_cap, float)
    cfg = apply_overrides(cfg, ["learner.steps_per_frame_cap=none"])
    assert cfg.learner.steps_per_frame_cap is None


def test_apply_overrides_rejects_unknown_field():
    cfg = get_config("pong")
    with pytest.raises(KeyError):
        apply_overrides(cfg, ["learner.not_a_field=3"])
    with pytest.raises(ValueError):
        apply_overrides(cfg, ["learner.batch_size"])  # missing '='


def test_cli_single_process_smoke(capsys, tmp_path):
    rc = main([
        "--config", "cartpole_smoke", "--single-process",
        "--total-env-frames", "3000",
        "--metrics-file", str(tmp_path / "m.jsonl"),
        "--set", "replay.min_fill=200",
        "--set", "learner.batch_size=32",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["frames"] == 3000
    assert out["grad_steps"] > 0
    assert (tmp_path / "m.jsonl").exists()


def test_cli_driver_smoke(capsys):
    rc = main([
        "--config", "cartpole_smoke",
        "--total-env-frames", "900",
        "--max-grad-steps", "30",
        "--wall-clock-limit", "120",
        "--actors", "1",
        "--set", "replay.kind=prioritized",
        "--set", "replay.capacity=2048",
        "--set", "replay.min_fill=64",
        "--set", "learner.batch_size=32",
        "--set", "learner.publish_every=20",
        "--set", "inference.max_batch=8",
        "--set", "eval_every_steps=0",
        "--set", "eval_episodes=0",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, out
    assert out["grad_steps"] >= 30
    assert out["actor_errors"] == [] and out["loop_errors"] == []


def test_cli_eval_only_restores_checkpoint(capsys, tmp_path):
    """--eval-only: train briefly with checkpoints, then evaluate the
    saved policy standalone (no learner/actors) through the same CLI.
    Non-Atari configs evaluate their own env instead of the HNS suite."""
    ckpt = str(tmp_path / "ckpt")
    rc = main([
        "--config", "cartpole_smoke",
        "--total-env-frames", "900",
        "--max-grad-steps", "30",
        "--actors", "1",
        "--checkpoint-dir", ckpt,
        "--set", "replay.kind=prioritized",
        "--set", "replay.capacity=2048",
        "--set", "replay.min_fill=64",
        "--set", "learner.batch_size=32",
        "--set", "inference.max_batch=8",
        "--set", "eval_every_steps=0",
        "--set", "eval_episodes=0",
    ])
    capsys.readouterr()
    assert rc == 0
    rc = main([
        "--config", "cartpole_smoke", "--eval-only",
        "--checkpoint-dir", ckpt,
        "--set", "eval_episodes=2",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["restored_step"] is not None and out["restored_step"] >= 30
    assert out["episodes"] == 2 and out["mean_return"] > 0


def test_cli_eval_only_suite_games(capsys):
    """--eval-only --games on an Atari config runs the HNS harness over
    the named games (synthetic env stands in for ALE here)."""
    rc = main([
        "--config", "pong", "--eval-only",
        "--games", "pong,breakout",
        "--set", "eval_episodes=1",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert set(out["scores"]) == {"pong", "breakout"}
    # synthetic stand-in ran: the north-star key must be namespaced
    assert "median_hns_synthetic" in out and "median_hns" not in out
    assert out["restored_step"] is None


def test_cli_eval_only_r2d2_restores_checkpoint(capsys, tmp_path):
    """--eval-only on the recurrent family: restore an R2D2 checkpoint
    and run the stateful {obs,c,h} eval policy standalone."""
    ckpt = str(tmp_path / "ckpt")
    common = [
        "--config", "r2d2",
        "--set", "env.id=CartPolePO", "--set", "env.kind=cartpole_po",
        "--set", "network.lstm_size=16", "--set", "network.torso_dense=32",
        "--set", "network.compute_dtype=float32",
        "--set", "replay.capacity=256", "--set", "replay.seq_length=8",
        "--set", "replay.seq_overlap=4", "--set", "replay.burn_in=2",
        "--set", "replay.min_fill=8", "--set", "replay.storage=flat",
        "--set", "learner.batch_size=8",
        "--set", "parallel.dp=1", "--set", "parallel.tp=1",
        "--set", "actors.num_actors=1",
        "--set", "eval_every_steps=0", "--set", "eval_episodes=0",
    ]
    rc = main(common + ["--total-env-frames", "600",
                        "--max-grad-steps", "10",
                        "--checkpoint-dir", ckpt])
    capsys.readouterr()
    assert rc == 0
    rc = main(common + ["--eval-only", "--checkpoint-dir", ckpt,
                        "--set", "eval_episodes=1"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["restored_step"] is not None
    assert out["episodes"] == 1 and out["mean_return"] > 0


def test_cli_eval_only_dpg_restores_checkpoint(capsys, tmp_path):
    """--eval-only on the continuous family: actor/critic params map
    from the DPG checkpoint into the deterministic mu(s) eval policy."""
    ckpt = str(tmp_path / "ckpt")
    common = [
        "--config", "apex_dpg",
        "--set", "replay.capacity=512", "--set", "replay.min_fill=64",
        "--set", "learner.batch_size=16",
        "--set", "actors.num_actors=1",
        "--set", "eval_every_steps=0", "--set", "eval_episodes=0",
    ]
    rc = main(common + ["--total-env-frames", "600",
                        "--max-grad-steps", "10",
                        "--checkpoint-dir", ckpt])
    capsys.readouterr()
    assert rc == 0
    rc = main(common + ["--eval-only", "--checkpoint-dir", ckpt,
                        "--set", "eval_episodes=1"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["restored_step"] is not None
    assert out["episodes"] == 1 and np.isfinite(out["mean_return"])
