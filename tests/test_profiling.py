"""Continuous perf plane (obs/profiling.py, ISSUE 8).

Four behaviors from the issue's test checklist: live MFU/roofline
gauges are sane on the catch smoke, the compile watcher counts fresh
jit compiles (delta-published per run), PerfDegradation fires on a
synthetically throttled rate with the right stage name (and per-peer
attribution), and disabled obs emits nothing while never taking any
compiling code path.
"""

import json

from ape_x_dqn_tpu.configs import (EnvConfig, LearnerConfig,
                                   NetworkConfig, ObsConfig,
                                   ReplayConfig, get_config)
from ape_x_dqn_tpu.obs.core import NULL_OBS, build_obs
from ape_x_dqn_tpu.utils.metrics import Metrics


def _smoke_cfg(enabled: bool = True):
    """Catch smoke at test_obs.py's shapes: sample_chunk=2 routes the
    observed run through the split sample_k/learn_k macro-dispatch."""
    return get_config("pong").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari"),
        network=NetworkConfig(kind="nature_cnn", dueling=True,
                              compute_dtype="float32"),
        replay=ReplayConfig(kind="prioritized", capacity=2048,
                            min_fill=300),
        learner=LearnerConfig(batch_size=16, n_step=3,
                              target_sync_every=16, sample_chunk=2),
        obs=ObsConfig(enabled=enabled, publish_every_steps=50,
                      heartbeat_timeout_s=120.0),
    )


# -- device-time attribution / roofline gauges ------------------------------

def test_mfu_gauges_on_catch_smoke(tmp_path):
    """The live roofline: a real observed catch run publishes per-stage
    mfu/hbm_bw_frac/device_ms gauges with sane values (0 < mfu < 1
    needs cost_analysis FLOPs AND a detected peak), and the offline
    report renders the roofline section from the same JSONL."""
    from ape_x_dqn_tpu.obs import report
    from ape_x_dqn_tpu.runtime.single_process import train_single_process

    jsonl = str(tmp_path / "run.jsonl")
    metrics = Metrics(log_path=jsonl)
    out = train_single_process(_smoke_cfg(), total_env_frames=420,
                               metrics=metrics, train_every=2)
    metrics.close()
    assert out["grad_steps"] > 0
    recs = [json.loads(line) for line in open(jsonl)]
    snaps = [r for r in recs if "gauge/mfu_sample_k" in r]
    assert snaps, "no roofline gauges reached the JSONL"
    last = snaps[-1]
    for key in ("gauge/mfu_sample_k", "gauge/mfu_learn_k"):
        assert 0.0 < last[key] < 1.0, (key, last[key])
    for key in ("gauge/device_ms_sample_k", "gauge/device_ms_learn_k",
                "gauge/hbm_bw_frac_sample_k",
                "gauge/hbm_bw_frac_learn_k"):
        assert last[key] > 0.0, (key, last[key])
    # compile telemetry rode the same publish stream: this run compiled
    # fresh jits, so at least one snapshot carries a nonzero counter
    assert any(r.get("ctr/jit_compiles", 0) > 0 for r in recs)
    assert last["gauge/compile_cache_entries"] > 0
    # the offline report renders a roofline section with both stages
    text = report.format_report(report.summarize(recs))
    assert "roofline" in text
    assert "sample_k" in text and "learn_k" in text
    assert "compile telemetry:" in text


def test_stage_profiler_cost_analysis_present():
    """attach() captures nonzero FLOP/byte roofs from a real compiled
    executable on this backend (the gauge denominators)."""
    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.obs.profiling import compiled_cost

    def f(x):
        return (x @ x.T).sum()

    compiled = jax.jit(f).lower(
        jnp.ones((64, 64), jnp.float32)).compile()
    flops, nbytes = compiled_cost(compiled)
    assert flops > 0.0
    assert nbytes > 0.0


# -- compile telemetry ------------------------------------------------------

def test_compile_watcher_counts_fresh_jit():
    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.obs.profiling import CompileWatcher

    watcher = CompileWatcher.install()
    assert CompileWatcher.install() is watcher  # process singleton
    n0, s0 = watcher.snapshot()

    @jax.jit
    def f(x):
        return x * 2.0 + 1.0

    f(jnp.arange(7, dtype=jnp.float32)).block_until_ready()
    n1, s1 = watcher.snapshot()
    assert n1 > n0
    assert s1 > s0
    assert watcher.entries == n1  # monotonic compile-work ledger


class _RecorderObs:
    def __init__(self):
        self.counts: dict = {}
        self.gauges: dict = {}

    def count(self, name, n=1.0):
        self.counts[name] = self.counts.get(name, 0.0) + n

    def gauge(self, name, value):
        self.gauges[name] = value


def test_compile_telemetry_publishes_delta_only():
    """A run's JSONL carries only ITS compiles: the per-Obs view
    publishes deltas since construction/last publish, while the cache
    gauge stays the process-cumulative count."""
    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.obs.profiling import CompileTelemetry

    ct = CompileTelemetry()

    @jax.jit
    def g(x):
        return x - 3.0

    g(jnp.arange(5, dtype=jnp.float32)).block_until_ready()
    rec = _RecorderObs()
    ct.publish_into(rec)
    assert rec.counts.get("jit_compiles", 0) >= 1
    assert rec.counts.get("jit_compile_ms", 0) > 0
    assert rec.gauges["compile_cache_entries"] >= rec.counts["jit_compiles"]
    # no new compiles since: counters stay silent, the gauge persists
    rec2 = _RecorderObs()
    ct.publish_into(rec2)
    assert "jit_compiles" not in rec2.counts
    assert rec2.gauges["compile_cache_entries"] == \
        rec.gauges["compile_cache_entries"]


# -- perf-regression engine -------------------------------------------------

def test_perf_degradation_fires_with_stage_name(tmp_path):
    """A synthetically throttled rate fires ONE attributed warn-only
    event carrying the right series name (and the peer id for fleet
    baselines); the run continues — nothing raises."""
    jsonl = str(tmp_path / "perf.jsonl")
    metrics = Metrics(log_path=jsonl)
    obs = build_obs(ObsConfig(enabled=True, heartbeat_timeout_s=0.0,
                              perf_min_samples=4, perf_cooldown_s=0.0),
                    metrics)
    for _ in range(6):
        obs.perf_rate("grad_steps_per_s", 100.0, step=1)
    obs.perf_rate("grad_steps_per_s", 5.0, step=7)  # throttled stage
    for _ in range(6):
        obs.perf_rate("ingest_rows_per_s", 1000.0, step=1, peer="host-3")
    obs.perf_rate("ingest_rows_per_s", 10.0, step=9, peer="host-3")
    obs.close(9)
    metrics.close()
    recs = [json.loads(line) for line in open(jsonl)]
    events = [r for r in recs if r.get("perf_degradation")]
    local = [e for e in events if e["perf_degradation"]
             == "grad_steps_per_s"]
    assert local, events
    assert local[0].get("perf_peer") is None
    assert local[0]["perf_value"] < local[0]["perf_baseline"]
    peer_ev = [e for e in events if e.get("perf_peer") == "host-3"]
    assert peer_ev and peer_ev[0]["perf_degradation"] == \
        "ingest_rows_per_s"
    # the counter rode the close() publish
    assert any(r.get("ctr/perf_degradations", 0) >= 2 for r in recs)
    # and the offline report lists both with attribution
    from ape_x_dqn_tpu.obs import report
    text = report.format_report(report.summarize(recs))
    assert "perf-degradation events" in text
    assert "peer=host-3" in text


def test_perf_monitor_respects_cooldown_and_min_samples():
    from ape_x_dqn_tpu.obs.profiling import PerfMonitor

    class _M:
        def __init__(self):
            self.records = []

        def log(self, step, **kw):
            self.records.append(kw)

    rec, m = _RecorderObs(), _M()
    mon = PerfMonitor(rec, m, frac=0.5, min_samples=4, cooldown_s=3600.0)
    # below min_samples nothing can fire, however deep the drop
    mon.observe("env_fps", 100.0)
    mon.observe("env_fps", 1.0)
    assert m.records == []
    for _ in range(4):
        mon.observe("env_fps", 100.0)
    mon.observe("env_fps", 1.0)
    assert len(m.records) == 1
    # inside the cooldown a persistent slowdown does not re-fire
    mon.observe("env_fps", 1.0)
    assert len(m.records) == 1


# -- disabled obs stays untouched -------------------------------------------

def test_disabled_obs_emits_nothing_and_never_compiles(tmp_path):
    """The acceptance bar from PR 2 extended to the perf plane: with
    ObsConfig disabled the runtime goes through NullObs, which never
    invokes a stage compile_fn (so no jit is touched, let alone
    re-compiled) and emits no obs records at all."""
    from ape_x_dqn_tpu.runtime.single_process import train_single_process

    assert build_obs(ObsConfig(enabled=False), None) is NULL_OBS
    # stage_attached pretends attached, so drivers skip the (compiling)
    # attach path entirely; an attach called anyway must not compile
    called = []
    assert NULL_OBS.stage_attached("sample_k") is True
    NULL_OBS.stage_attach("sample_k", 4,
                          compile_fn=lambda: called.append(1))
    assert called == []
    with NULL_OBS.stage_window("learn_k", 4):
        pass
    NULL_OBS.perf_rate("env_fps", 100.0)
    assert NULL_OBS.profiler is None and NULL_OBS.perf is None
    # end-to-end: the disabled run's JSONL carries no obs records
    jsonl = str(tmp_path / "off.jsonl")
    metrics = Metrics(log_path=jsonl)
    out = train_single_process(_smoke_cfg(enabled=False),
                               total_env_frames=420, metrics=metrics,
                               train_every=2)
    metrics.close()
    assert out["grad_steps"] > 0
    obs_keys = [k for line in open(jsonl)
                for k in json.loads(line)
                if k.startswith(("gauge/", "ctr/", "hist/", "span/"))]
    assert obs_keys == []
