"""Inference server, actor loop, and full Ape-X driver wiring
(SURVEY.md §4 'distributed-without-a-cluster': loopback transport,
in-process queues standing in for gRPC/DCN)."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, NetworkConfig,
    ReplayConfig, get_config)
from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.parallel.inference_server import BatchedInferenceServer
from ape_x_dqn_tpu.runtime.actor import Actor, actor_epsilon
from ape_x_dqn_tpu.runtime.driver import ApexDriver


def test_actor_epsilon_schedule():
    # Horgan et al. 2018: eps_i = 0.4 ** (1 + 7 i / (N-1))
    n = 8
    eps = [actor_epsilon(i, n) for i in range(n)]
    assert abs(eps[0] - 0.4) < 1e-9
    assert abs(eps[-1] - 0.4**8) < 1e-9
    assert all(a > b for a, b in zip(eps, eps[1:]))  # monotone decreasing
    assert actor_epsilon(0, 1) == 0.4  # single actor: base


def test_inference_server_batches_and_serves():
    def apply_fn(params, obs):
        return obs @ params

    params = jnp.eye(4)
    server = BatchedInferenceServer(apply_fn, params, max_batch=16,
                                    deadline_ms=5.0)
    try:
        results = {}

        def client(i):
            obs = np.full(4, float(i), np.float32)
            results[i] = server.query(obs)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(10):
            np.testing.assert_allclose(results[i], np.full(4, float(i)),
                                       rtol=1e-6)
        st = server.stats
        assert st["items"] == 10
        assert st["batches"] <= 10  # at least some batching happened
    finally:
        server.stop()


def test_inference_server_param_update():
    def apply_fn(params, obs):
        return obs * params

    server = BatchedInferenceServer(apply_fn, jnp.float32(1.0))
    try:
        out1 = server.query(np.ones(3, np.float32))
        np.testing.assert_allclose(out1, 1.0)
        server.update_params(jnp.float32(2.0), version=1)
        out2 = server.query(np.ones(3, np.float32))
        np.testing.assert_allclose(out2, 2.0)
        assert server.params_version == 1
    finally:
        server.stop()


def test_inference_server_propagates_errors():
    def apply_fn(params, obs):
        return obs @ params  # shape mismatch for bad input

    server = BatchedInferenceServer(apply_fn, jnp.eye(4))
    try:
        with pytest.raises(Exception):
            server.query(np.ones(7, np.float32))  # wrong obs dim
        # server keeps serving after an error
        ok = server.query(np.ones(4, np.float32))
        assert ok.shape == (4,)
    finally:
        server.stop()


def _tiny_cfg(num_actors=2):
    return get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=num_actors, base_eps=0.6,
                           ingest_batch=16),
        replay=ReplayConfig(kind="prioritized", capacity=2048, min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
    )


def test_actor_ships_prioritized_batches():
    cfg = _tiny_cfg(num_actors=1)
    transport = LoopbackTransport()

    def query_fn(obs):
        return np.array([0.1, 0.2], np.float32)  # fixed Q-values

    actor = Actor(cfg, 0, query_fn, transport)
    frames = actor.run(max_frames=200)
    assert frames == 200
    batches, total = [], 0
    while True:
        b = transport.recv_experience(timeout=0.01)
        if b is None:
            break
        batches.append(b)
        total += len(b["priorities"])
    assert batches, "actor shipped nothing"
    b0 = batches[0]
    assert b0["obs"].shape[1:] == (4,) and b0["priorities"].dtype == np.float32
    assert (b0["priorities"] >= 0).all()
    # n-step=3 over 200 frames: nearly every step yields a transition
    assert total > 150


def test_apex_driver_end_to_end(tmp_path):
    """Full wiring: actors -> server -> transport -> ingest -> learner."""
    import json

    from ape_x_dqn_tpu.utils.metrics import Metrics

    cfg = _tiny_cfg(num_actors=2)
    log_path = str(tmp_path / "metrics.jsonl")
    driver = ApexDriver(cfg, metrics=Metrics(log_path=log_path))
    out = driver.run(total_env_frames=1200, max_grad_steps=50,
                     wall_clock_limit_s=120)
    # the JSONL is self-describing: the first record carries the
    # sampling semantics + storage layout that produced the run
    # (utils/metrics.log_run_header)
    with open(log_path) as fh:
        head = json.loads(fh.readline())
    assert head["sample_chunk"] == 1
    assert head["replay_storage"] == "flat"
    assert head["replay_kind"] == "prioritized"
    assert head["run_name"] == cfg.name
    # no actor may die mid-run (round-1 verdict: a use-after-donate crash
    # killed an actor and this test still passed)
    assert out["actor_errors"] == [], out["actor_errors"]
    # train_many chunks reach the grad-step target fast, so the run can
    # end well before actors produce many frames; min_fill (64) is all
    # the wiring guarantees — under full-suite CPU contention the
    # learner can finish its 50 steps before actors ship another block
    assert out["frames"] >= 64, out
    assert out["grad_steps"] >= 50, out
    assert out["episodes"] > 0
    assert out["server"]["items"] > 0
    # params were published to the inference server at least once
    assert driver.server.params_version > 0


def test_apex_dist_driver_end_to_end():
    """ApexDriver with dp=4 x tp=2 over the virtual 8-device mesh:
    round-robin ingest across dp replay shards, train_many chunks,
    replicated param publication (round-1 verdict item 4)."""
    from ape_x_dqn_tpu.configs import ParallelConfig

    cfg = _tiny_cfg(num_actors=2).replace(
        parallel=ParallelConfig(dp=4, tp=2),
        replay=ReplayConfig(kind="prioritized", capacity=4096, min_fill=128),
        learner=LearnerConfig(batch_size=32, n_step=3, target_sync_every=100,
                              publish_every=20, train_chunk=4),
    )
    driver = ApexDriver(cfg)
    assert driver.is_dist and driver.mesh.shape == {"dp": 4, "tp": 2}
    out = driver.run(total_env_frames=2000, max_grad_steps=60,
                     wall_clock_limit_s=180)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["frames"] > 300, out
    assert out["grad_steps"] >= 60, out
    assert driver.server.params_version > 0
    # every dp shard of the replay actually received transitions
    sizes = np.asarray(driver.state.replay.size)
    assert sizes.shape == (4,) and (sizes > 0).all(), sizes


class _FlakyActor(Actor):
    """Crashes the first actor-0 run; behaves normally after."""

    crashed: dict = {}

    def run(self, max_frames, stop_event=None):
        if self.index == 0 and not _FlakyActor.crashed.get("done"):
            _FlakyActor.crashed["done"] = True
            raise RuntimeError("injected actor crash")
        return super().run(max_frames, stop_event)


def test_actor_crash_recovery(monkeypatch):
    """SURVEY.md §5 elastic recovery: a crashed in-driver actor is
    rebuilt and the run completes with no actor_errors."""
    _FlakyActor.crashed = {}
    monkeypatch.setattr("ape_x_dqn_tpu.runtime.family.Actor", _FlakyActor)
    cfg = _tiny_cfg(num_actors=2)
    driver = ApexDriver(cfg)
    out = driver.run(total_env_frames=1200, max_grad_steps=50,
                     wall_clock_limit_s=120)
    assert _FlakyActor.crashed.get("done")
    assert out["actor_errors"] == [], out["actor_errors"]
    assert [i for i, _ in out["actor_restarts"]] == [0], out
    assert out["grad_steps"] >= 50, out


def test_actor_crash_exhausts_restart_budget(monkeypatch):
    """max_restarts=0: the crash surfaces as an actor error instead of
    recovering (the failure is not silently retried forever)."""
    _FlakyActor.crashed = {}
    monkeypatch.setattr("ape_x_dqn_tpu.runtime.family.Actor", _FlakyActor)
    cfg = _tiny_cfg(num_actors=2)
    cfg = cfg.replace(actors=ActorConfig(
        num_actors=2, base_eps=0.6, ingest_batch=16, max_restarts=0))
    driver = ApexDriver(cfg)
    out = driver.run(total_env_frames=600, max_grad_steps=30,
                     wall_clock_limit_s=120)
    assert [i for i, _ in out["actor_errors"]] == [0], out
    assert out["actor_restarts"] == []


def test_profile_trace_capture(tmp_path):
    """SURVEY.md §5 tracing: profile_dir captures a JAX profiler trace
    of the learner hot loop."""
    import os
    cfg = _tiny_cfg(num_actors=1).replace(
        profile_dir=str(tmp_path / "trace"), profile_steps=8)
    driver = ApexDriver(cfg)
    out = driver.run(total_env_frames=900, max_grad_steps=30,
                     wall_clock_limit_s=120)
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 30
    trace_files = [os.path.join(r, f)
                   for r, _, fs in os.walk(tmp_path / "trace") for f in fs]
    assert trace_files, "no profiler trace written"


def test_apex_driver_shuts_down_when_learner_cannot_progress():
    """Actors finish before replay reaches min_fill + finite grad-step
    target: run() must return instead of spinning forever."""
    cfg = _tiny_cfg(num_actors=1).replace(
        replay=ReplayConfig(kind="prioritized", capacity=2048,
                            min_fill=2000))
    driver = ApexDriver(cfg)
    out = driver.run(total_env_frames=100, max_grad_steps=50,
                     wall_clock_limit_s=60)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["grad_steps"] == 0
    assert out["wall_s"] < 50  # returned well before the wall-clock limit


def test_steps_per_frame_cap_binds_when_actors_stall():
    """Round-2 verdict weak #5: with steps_per_frame_cap set, the
    learner must pace itself to the ingested frame count instead of
    free-running on replay once actors stop producing."""
    cap = 0.05
    cfg = _tiny_cfg(num_actors=1).replace(
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20,
                              train_chunk=4, steps_per_frame_cap=cap),
        eval_every_steps=0, eval_episodes=0)
    driver = ApexDriver(cfg)
    out = driver.run(total_env_frames=1200, max_grad_steps=10**9,
                     wall_clock_limit_s=120)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] > 0, "cap starved the learner entirely"
    # the pacing check runs before each dispatch of <= train_chunk
    # steps, so the cap can overshoot by at most one chunk
    assert out["grad_steps"] <= cap * out["frames"] + cfg.learner.train_chunk, out


def test_flagship_presets_pin_replay_ratio():
    """The pong/atari57 presets carry the Ape-X effective replay ratio
    (~1.6e-3 grad-steps per ingested env step) and vector actors."""
    for name in ("pong", "atari57_apex"):
        cfg = get_config(name)
        assert cfg.learner.steps_per_frame_cap == pytest.approx(1.6e-3), name
        assert cfg.actors.envs_per_actor > 1, name


def test_learner_fixed_seed_bitwise_deterministic():
    """SURVEY.md §4 determinism: identical seed + identical ingest ->
    bitwise-identical params after N fused train steps on CPU (the
    whole sample->loss->opt->priority->sync cycle is one jit with its
    RNG threaded through the state, so there is no hidden entropy)."""
    import jax

    from ape_x_dqn_tpu.envs.base import EnvSpec
    from ape_x_dqn_tpu.models import build_network
    from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
    from ape_x_dqn_tpu.runtime.learner import (DQNLearner,
                                               transition_item_spec)
    from ape_x_dqn_tpu.utils.rng import component_key

    spec = EnvSpec(obs_shape=(4,), obs_dtype=np.dtype(np.float32),
                   discrete=True, num_actions=2)
    rng = np.random.default_rng(7)
    n = 256
    items = {
        "obs": rng.standard_normal((n, 4)).astype(np.float32),
        "action": rng.integers(0, 2, n).astype(np.int32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, 4)).astype(np.float32),
        "discount": np.full(n, 0.97, np.float32),
    }
    pris = rng.random(n).astype(np.float32) + 0.1

    def run_once():
        net = build_network(
            NetworkConfig(kind="mlp", mlp_hidden=(32,)), spec)
        params = net.init(component_key(3, "net"),
                          np.zeros((1, 4), np.float32))
        learner = DQNLearner(net.apply, PrioritizedReplay(capacity=512),
                             LearnerConfig(batch_size=32))
        state = learner.init(
            params,
            learner.replay.init(transition_item_spec(spec.obs_shape,
                                                     spec.obs_dtype)),
            component_key(3, "learner"))
        state = learner.add(state, items, pris)
        state, _ = learner.train_many(state, 50)
        return jax.tree.map(np.asarray, state.params)

    a, b = run_once(), run_once()
    jax.tree.map(np.testing.assert_array_equal, a, b)


def test_kbatch_train_many_mechanics():
    """sample_chunk=K routes train_many through the K-batch relaxation:
    one stratified K*B sample + one priority write-back per K
    grad-steps. Step counts, metrics, tree repair, and the
    remainder (n % K) path must all hold."""
    import dataclasses as _dc

    import jax

    from ape_x_dqn_tpu.envs.cartpole import CartPole
    from ape_x_dqn_tpu.models import build_network
    from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
    from ape_x_dqn_tpu.runtime.learner import (DQNLearner,
                                               transition_item_spec)
    from ape_x_dqn_tpu.utils.rng import component_key

    spec = CartPole().spec
    rng = np.random.default_rng(11)
    n = 256
    items = {
        "obs": rng.standard_normal((n, 4)).astype(np.float32),
        "action": rng.integers(0, 2, n).astype(np.int32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, 4)).astype(np.float32),
        "discount": np.full(n, 0.97, np.float32),
    }
    net = build_network(NetworkConfig(kind="mlp", mlp_hidden=(32,)), spec)
    params = net.init(component_key(5, "net"), np.zeros((1, 4), np.float32))
    lcfg = LearnerConfig(batch_size=32, sample_chunk=4,
                         target_sync_every=3)
    learner = DQNLearner(net.apply, PrioritizedReplay(capacity=512), lcfg)
    state = learner.init(
        params,
        learner.replay.init(transition_item_spec(spec.obs_shape,
                                                 spec.obs_dtype)),
        component_key(5, "learner"))
    state = learner.add(state, items, rng.random(n).astype(np.float32) + 0.1)
    tree_before = np.asarray(state.replay.tree)

    # n divisible by K: pure macro-steps
    state, m = learner.train_many(state, 8)
    assert int(state.step) == 8
    assert np.isfinite(m["loss"]) and np.isfinite(m["grad_norm"])
    # priorities were written back (root total changed)
    assert np.asarray(state.replay.tree)[1] != tree_before[1]

    # remainder path: 10 = 2 macro-steps of 4 + 2 exact steps
    state, m = learner.train_many(state, 10)
    assert int(state.step) == 18
    assert np.isfinite(m["loss"])

    # target sync fired inside the K-batch path: step 18 lands exactly
    # on a sync boundary (sync_every=3), so targets == online params
    t, p = (jax.tree.leaves(jax.tree.map(np.asarray, state.target_params)),
            jax.tree.leaves(jax.tree.map(np.asarray, state.params)))
    for a, b in zip(t, p):
        np.testing.assert_array_equal(a, b)

    # determinism: same seed, same result, through the K-batch path
    def run_once():
        net2 = build_network(NetworkConfig(kind="mlp", mlp_hidden=(32,)),
                             spec)
        p2 = net2.init(component_key(6, "net"),
                       np.zeros((1, 4), np.float32))
        lrn = DQNLearner(net2.apply, PrioritizedReplay(capacity=512),
                         _dc.replace(lcfg, sample_chunk=4))
        st = lrn.init(p2, lrn.replay.init(
            transition_item_spec(spec.obs_shape, spec.obs_dtype)),
            component_key(6, "learner"))
        st = lrn.add(st, items, np.ones(n, np.float32))
        st, _ = lrn.train_many(st, 12)
        return jax.tree.map(np.asarray, st.params)

    a, b = run_once(), run_once()
    jax.tree.map(np.testing.assert_array_equal, a, b)


def test_kbatch_chunks_span_full_priority_range():
    """Each K-batch chunk must take INTERLEAVED strata {j, j+K, ...}:
    stratified descent maps cumulative mass ~monotonically onto ring
    position, so a contiguous split would hand chunk 0 only the oldest
    1/K of the replay and chunk K-1 only the newest (round-4 review
    finding). With uniform priorities, every chunk's sampled leaf
    indices must span (nearly) the whole filled region."""
    import jax

    from ape_x_dqn_tpu.ops import sum_tree

    cap, k, b = 1024, 4, 64
    tree = sum_tree.init(cap)
    tree = sum_tree.update(tree, jnp.arange(cap, dtype=jnp.int32),
                           jnp.ones(cap))
    idx, _ = sum_tree.sample(tree, jax.random.key(0), k * b)
    idx_k = np.asarray(idx).reshape(b, k).swapaxes(0, 1)  # learner's split
    for j in range(k):
        lo, hi = idx_k[j].min(), idx_k[j].max()
        assert lo < cap * 0.1 and hi > cap * 0.9, \
            f"chunk {j} covers only [{lo}, {hi}] of {cap}"
    # and the contiguous split WOULD be age-biased (sanity of the test)
    contig = np.asarray(idx).reshape(k, b)
    assert contig[0].max() < cap * 0.5


def _prefetch_learner(sample_prefetch, seed=5, sample_chunk=4):
    """Small DQNLearner + filled replay for the prefetch pipeline tests.
    Identical construction across calls so the prefetch=True/False arms
    start from bit-identical state."""
    from ape_x_dqn_tpu.envs.cartpole import CartPole
    from ape_x_dqn_tpu.models import build_network
    from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
    from ape_x_dqn_tpu.runtime.learner import (DQNLearner,
                                               transition_item_spec)
    from ape_x_dqn_tpu.utils.rng import component_key

    spec = CartPole().spec
    rng = np.random.default_rng(seed)
    n = 256
    items = {
        "obs": rng.standard_normal((n, 4)).astype(np.float32),
        "action": rng.integers(0, 2, n).astype(np.int32),
        "reward": rng.standard_normal(n).astype(np.float32),
        "next_obs": rng.standard_normal((n, 4)).astype(np.float32),
        "discount": np.full(n, 0.97, np.float32),
    }
    net = build_network(NetworkConfig(kind="mlp", mlp_hidden=(32,)), spec)
    params = net.init(component_key(seed, "net"),
                      np.zeros((1, 4), np.float32))
    lcfg = LearnerConfig(batch_size=32, sample_chunk=sample_chunk,
                         sample_prefetch=sample_prefetch,
                         target_sync_every=3)
    learner = DQNLearner(net.apply, PrioritizedReplay(capacity=512), lcfg)
    state = learner.init(
        params,
        learner.replay.init(transition_item_spec(spec.obs_shape,
                                                 spec.obs_dtype)),
        component_key(seed, "learner"))
    state = learner.add(state, items,
                        rng.random(n).astype(np.float32) + 0.1)
    return learner, state


def test_prefetch_train_many_mechanics():
    """sample_prefetch=True routes train_many through the double-buffered
    pipeline: the scan body draws macro-step n+1's sample against the
    priorities BEFORE macro-step n's write-back. Step counts, metrics,
    tree repair, the remainder (n % K) path, the target-sync boundary,
    and run-twice determinism must all hold — mirroring
    test_kbatch_train_many_mechanics for the fused path."""
    import jax

    learner, state = _prefetch_learner(True)
    tree_before = np.asarray(state.replay.tree)

    state, m = learner.train_many(state, 8)   # pure macro-steps
    assert int(state.step) == 8
    assert np.isfinite(m["loss"]) and np.isfinite(m["grad_norm"])
    assert np.asarray(state.replay.tree)[1] != tree_before[1]

    state, m = learner.train_many(state, 10)  # 2 exact + 2 macro-steps
    assert int(state.step) == 18
    assert np.isfinite(m["loss"])

    # step 18 is a sync boundary (sync_every=3): targets == online
    t, p = (jax.tree.leaves(jax.tree.map(np.asarray, state.target_params)),
            jax.tree.leaves(jax.tree.map(np.asarray, state.params)))
    for a, b in zip(t, p):
        np.testing.assert_array_equal(a, b)

    def run_once():
        lrn, st = _prefetch_learner(True, seed=6)
        st, _ = lrn.train_many(st, 12)
        return jax.tree.map(np.asarray, st.params)

    a, b = run_once(), run_once()
    jax.tree.map(np.testing.assert_array_equal, a, b)

    # k=1 + prefetch degenerates cleanly (every macro-step is one SGD
    # step; the pipeline still draws one sample ahead)
    lrn1, st1 = _prefetch_learner(True, seed=7, sample_chunk=1)
    st1, m1 = lrn1.train_many(st1, 5)
    assert int(st1.step) == 5 and np.isfinite(m1["loss"])


def test_prefetch_first_macro_step_matches_fused():
    """The pipeline prologue draws its first sample from the SAME
    priorities the fused path would (no staleness yet), so one
    macro-step through the prefetch train_many is bit-identical to one
    train_step_k on the same initial state — params AND written-back
    tree. This pins the prefetch path to the fused semantics everywhere
    except the documented one-dispatch priority staleness."""
    import jax

    l1, s1 = _prefetch_learner(True)
    l2, s2 = _prefetch_learner(False)
    s1, _ = l1.train_many(s1, 4)
    s2, _ = l2.train_step_k(s2, 4)
    assert int(s1.step) == int(s2.step) == 4
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s1.params, s2.params)
    np.testing.assert_array_equal(np.asarray(s1.replay.tree),
                                  np.asarray(s2.replay.tree))


def test_prefetch_sample_learn_split_matches_fused():
    """sample_k + learn_k composed on the host (the single_process.py
    double-buffer prologue) reproduce train_step_k bit-exactly: the
    split stages are the fused cycle cut at the sample/learn seam, with
    the same RNG discipline."""
    import jax

    l1, s1 = _prefetch_learner(False)
    l2, s2 = _prefetch_learner(False)
    sample, rng2 = l1.sample_k(s1, 4)
    s1, m1 = l1.learn_k(s1._replace(rng=rng2), sample, 4)
    s2, m2 = l2.train_step_k(s2, 4)
    assert int(s1.step) == int(s2.step) == 4
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s1.params, s2.params)
    np.testing.assert_array_equal(np.asarray(s1.replay.tree),
                                  np.asarray(s2.replay.tree))
    assert np.isfinite(m1["loss"]) and np.isfinite(m2["loss"])


def test_eval_rotation_survives_transient_timeout(tmp_path, monkeypatch):
    """A transient inference-server TimeoutError during one rotation
    eval must not kill the eval thread for the rest of the run (the
    round-5 live 57-game rotation died 14 games in on one stalled
    query): the failed slot is logged as eval_error and later
    rotations still produce eval records."""
    import json

    from ape_x_dqn_tpu.runtime import evaluation as ev
    from ape_x_dqn_tpu.utils.metrics import Metrics

    calls = {"n": 0}
    real = ev.run_eval_measured

    def flaky(worker, episodes, server, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise TimeoutError("inference server did not reply")
        return real(worker, episodes, server, **kw)

    monkeypatch.setattr(ev, "run_eval_measured", flaky)
    cfg = _tiny_cfg(num_actors=1).replace(
        eval_every_steps=5, eval_episodes=1, eval_max_frames=60)
    log_path = str(tmp_path / "metrics.jsonl")
    driver = ApexDriver(cfg, metrics=Metrics(log_path=log_path))
    out = driver.run(total_env_frames=2500, max_grad_steps=10**9,
                     wall_clock_limit_s=180)
    assert calls["n"] >= 2, calls  # the loop came back after the raise
    assert not any("eval" in e for e in
                   (repr(x) for x in out["loop_errors"])), out["loop_errors"]
    recs = [json.loads(l) for l in open(log_path)]
    assert any("eval_error" in r for r in recs)
    assert any("avg_eval_return" in r for r in recs)
