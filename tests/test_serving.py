"""Multi-tenant serving tier (ISSUE 13): continuous-batching admission,
priority load-shedding, coalesced forwards, and the tagged-request wire
interop — plus the BatchedInferenceServer satellite fixes (head-of-line
collection, warm-bucket dedupe across epochs)."""

import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.parallel.inference_server import (
    BatchedInferenceServer, MultiPolicyInferenceServer,
    ServeDeadlineExceeded, ServeShed, _Request, build_serving_tier)


def _scale_apply(params, x):
    return x * params["w"]


def _w(v):
    return {"w": np.float32(v)}


# -- satellite 1: _collect head-of-line fix ---------------------------------


class _NoServe(BatchedInferenceServer):
    """Server whose serve thread exits immediately: the queue and the
    held deque are driven by calling _collect directly, so collection
    semantics are testable without racing a consumer."""

    def _serve_loop(self):
        return


def test_collect_oversize_does_not_starve_fitting_requests():
    """Regression (ISSUE 13 satellite): a held-back K-item vector
    request must not block smaller requests that still fit the current
    bucket — they keep admitting around it, and the vector serves in
    the NEXT batch, alone, in arrival order."""
    server = _NoServe(_scale_apply, _w(1.0), max_batch=8, deadline_ms=1.0)
    try:
        singles_a = [_Request(np.zeros(2, np.float32)) for _ in range(4)]
        vector = _Request(np.zeros((6, 2), np.float32), n=6)
        singles_b = [_Request(np.zeros(2, np.float32)) for _ in range(4)]
        for r in [*singles_a, vector, *singles_b]:
            server._q.put(r)
        first = server._collect()
        # 4 singles, the 6-item vector is parked (4+6 > 8), then the
        # remaining 4 singles fill the batch to exactly max_batch
        assert first == [*singles_a, *singles_b]
        assert sum(r.items for r in first) == 8
        second = server._collect()
        assert second == [vector]
    finally:
        server.stop()


def test_collect_oversize_request_serves_alone():
    """A single request larger than max_batch still serves (alone, in
    its own warmed bucket) instead of being parked forever."""
    server = _NoServe(_scale_apply, _w(1.0), max_batch=4, deadline_ms=1.0)
    try:
        big = _Request(np.zeros((9, 2), np.float32), n=9)
        small = _Request(np.zeros(2, np.float32))
        server._q.put(big)
        server._q.put(small)
        first = server._collect()
        assert first == [big]
        assert server._collect() == [small]
    finally:
        server.stop()


def test_collect_preserves_arrival_order_among_held():
    """Parked requests re-enter in arrival order ahead of new queue
    traffic once capacity frees."""
    server = _NoServe(_scale_apply, _w(1.0), max_batch=4, deadline_ms=1.0)
    try:
        v1 = _Request(np.zeros((3, 2), np.float32), n=3)
        v2 = _Request(np.zeros((3, 2), np.float32), n=3)
        v3 = _Request(np.zeros((3, 2), np.float32), n=3)
        for r in (v1, v2, v3):
            server._q.put(r)
        assert server._collect() == [v1]  # v2/v3 parked (3+3 > 4)
        assert server._collect() == [v2]
        assert server._collect() == [v3]
    finally:
        server.stop()


# -- satellite 2: warm-bucket dedupe across epochs --------------------------


def test_warmup_dedupes_across_update_params_epochs():
    """An epoch bump changes param VALUES, not shapes: re-warming after
    update_params must re-pay zero AOT compiles (asserted via the
    compile-telemetry delta, PR 8)."""
    from ape_x_dqn_tpu.obs.profiling import CompileWatcher

    watcher = CompileWatcher.install()
    server = BatchedInferenceServer(_scale_apply, _w(1.0),
                                    max_batch=8, deadline_ms=1.0)
    try:
        example = np.zeros(3, np.float32)
        server.warmup(example, extra_sizes=(5,))
        warm, _ = watcher.snapshot()
        assert warm > 0  # the first warmup really compiled
        server.update_params(_w(2.0), 1)
        server.warmup(example, extra_sizes=(5,))
        again, _ = watcher.snapshot()
        assert again == warm, "epoch bump re-paid AOT compiles"
        # a NEW bucket size still compiles exactly that bucket
        server.warmup(example, extra_sizes=(3 * 8,))
        grown, _ = watcher.snapshot()
        assert grown > again
        out = server.query(np.full(3, 2.0, np.float32))
        np.testing.assert_allclose(np.asarray(out), 4.0)
    finally:
        server.stop()


# -- admission semantics ----------------------------------------------------


class _NoDispatch(MultiPolicyInferenceServer):
    """Tier whose dispatch thread exits immediately: the REAL admission
    thread runs (offer/shed/backpressure accounting), while batches are
    taken synchronously from the test via _take_batch — deterministic
    under saturation."""

    def _dispatch_loop(self):
        return


def _wait_depth(tier, depth, timeout=5.0):
    """Wait until admission has drained the intake queue into the
    pending deques and the pending depth reads `depth`."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if tier._q.qsize() == 0 and tier.queue_depth == depth:
            return
        time.sleep(0.002)
    raise AssertionError(
        f"queue never reached depth {depth}: at {tier.queue_depth}")


def test_priority_ordering_under_saturation():
    """With more pending than one batch holds, class 0 is served first
    (FIFO within the class); lower classes fill the remainder oldest
    first. deadline_ms=0 makes _take_batch dispatch unconditionally."""
    tier = _NoDispatch(max_batch=4, deadline_ms=0.0,
                       priority_classes=3, queue_slo_items=100)
    try:
        c = [tier.register_policy(f"p{i}", _scale_apply, _w(i + 1),
                                  family="mlp", priority=i)
             for i in range(3)]
        x = np.zeros(2, np.float32)
        low = [c[2].submit(x) for _ in range(4)]
        mid = [c[1].submit(x) for _ in range(2)]
        top = [c[0].submit(x) for _ in range(2)]
        _wait_depth(tier, 8)
        fam, reqs, items = tier._take_batch()
        assert items == 4
        # both class-0 requests, then both class-1, before any class-2
        assert [r.policy for r in reqs] == ["p0", "p0", "p1", "p1"]
        fam, reqs, items = tier._take_batch()
        assert [r.policy for r in reqs] == ["p2"] * 4
        assert [id(r) for r in reqs] == [id(r) for r in low]  # FIFO
        del mid, top
    finally:
        tier.stop()


def test_shed_accounting_closure_and_class_protection():
    """Overload sheds newest-first from the LOWEST class only, class 0
    is never shed, and the books close: offered == admitted +
    sum(shed_by_class) once the queue is drained."""
    tier = _NoDispatch(max_batch=4, deadline_ms=0.0,
                       priority_classes=3, queue_slo_items=6)
    try:
        c = [tier.register_policy(f"p{i}", _scale_apply, _w(i + 1),
                                  family="mlp", priority=i)
             for i in range(3)]
        x = np.zeros(2, np.float32)
        tickets = []
        for _ in range(5):
            tickets.append(c[0].submit(x))
        for _ in range(5):
            tickets.append(c[1].submit(x))
        for _ in range(5):
            tickets.append(c[2].submit(x))
        deadline = time.monotonic() + 5.0
        while tier._q.qsize() and time.monotonic() < deadline:
            time.sleep(0.002)
        assert tier.queue_depth <= 6  # controller held the SLO line
        shed_errs = []
        for t in tickets:
            if t.event.is_set() and isinstance(t.result, ServeShed):
                shed_errs.append(t.result)
        assert shed_errs, "2.5x-SLO offered load must shed"
        assert all(e.priority > 0 for e in shed_errs)  # class 0 immune
        while tier._take_batch() is not None:
            pass
        s = tier.stats
        assert s["offered"] == 15
        assert s["shed_by_class"][0] == 0
        assert s["offered"] == s["admitted"] + sum(s["shed_by_class"])
        # shed errors carry the attribution the client logs
        e = shed_errs[0]
        assert e.policy_id in ("p1", "p2")
    finally:
        tier.stop()


def test_deadline_expiry_names_policy():
    """A request idling past serving.request_deadline_ms raises an
    attributed ServeDeadlineExceeded naming the policy and class."""
    tier = _NoDispatch(max_batch=4, deadline_ms=0.0,
                       priority_classes=2, queue_slo_items=100,
                       request_deadline_ms=20.0)
    try:
        client = tier.register_policy("breakout", _scale_apply,
                                      _w(1.0), priority=1)
        ticket = client.submit(np.zeros(2, np.float32))
        _wait_depth(tier, 1)
        time.sleep(0.05)
        assert tier._take_batch() is None  # the sweep, nothing to serve
        with pytest.raises(ServeDeadlineExceeded) as ei:
            ticket.wait(timeout=1.0)
        assert "breakout" in str(ei.value)
        assert "class 1" in str(ei.value)
        s = tier.stats
        assert s["expired"] == 1
        assert s["offered"] == s["admitted"] + sum(s["shed_by_class"])
    finally:
        tier.stop()


def test_unknown_policy_rejected_with_attribution():
    tier = MultiPolicyInferenceServer(max_batch=4, deadline_ms=1.0)
    try:
        tier.register_policy("known", _scale_apply, _w(1.0))
        ticket = tier.submit("ghost", 0, np.zeros(2, np.float32))
        with pytest.raises(KeyError, match="ghost"):
            ticket.wait(timeout=2.0)
    finally:
        tier.stop()


def test_backpressure_hysteresis_transitions():
    """Crossing the SLO line fires on_backpressure(True); it releases
    only once the queue drains to half the line (hysteresis)."""
    tier = _NoDispatch(max_batch=2, deadline_ms=0.0,
                       priority_classes=2, queue_slo_items=6)
    events: list[bool] = []
    tier.on_backpressure = events.append
    try:
        client = tier.register_policy("p", _scale_apply, _w(1.0),
                                      priority=1)
        x = np.zeros(2, np.float32)
        for _ in range(7):
            client.submit(x)
        deadline = time.monotonic() + 5.0
        while not events and time.monotonic() < deadline:
            time.sleep(0.002)
        assert events == [True]
        # draining one batch leaves depth 4 > slo//2=3: still engaged
        assert tier._take_batch() is not None
        assert events == [True]
        while tier._take_batch() is not None:
            pass
        assert events == [True, False]
    finally:
        tier.stop()


# -- coalesced multi-tenant forwards ----------------------------------------


def test_coalesced_forward_per_tenant_params():
    """Same-family tenants coalesce into one gather-indexed forward;
    each request still sees ITS tenant's params, for singles and for
    vector requests, across an update_params epoch bump."""
    tier = MultiPolicyInferenceServer(max_batch=16, deadline_ms=2.0,
                                      priority_classes=2)
    try:
        clients = [tier.register_policy(f"pol{i}", _scale_apply,
                                        _w(i + 1), family="mlp")
                   for i in range(8)]
        for c in clients:
            c.warmup(np.zeros(3, np.float32))
        x = np.full(3, 2.0, np.float32)
        results = [None] * 8

        def ask(i):
            results[i] = np.asarray(clients[i].query(x))

        threads = [threading.Thread(target=ask, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, out in enumerate(results):
            np.testing.assert_allclose(out, 2.0 * (i + 1), err_msg=str(i))
        vec = np.asarray(clients[3].query_batch(
            np.ones((5, 3), np.float32), 5))
        assert vec.shape == (5, 3)
        np.testing.assert_allclose(vec, 4.0)
        clients[3].update_params(_w(100.0), version=9)
        assert clients[3].params_version == 9
        np.testing.assert_allclose(
            np.asarray(clients[3].query(np.ones(3, np.float32))), 100.0)
        assert tier.stats["tenants"] == 8
    finally:
        tier.stop()


def test_build_serving_tier_reads_config():
    from ape_x_dqn_tpu.configs import ServingConfig

    scfg = ServingConfig(multi_tenant=True, priority_classes=5,
                         queue_slo_items=32, request_deadline_ms=250.0,
                         coalesce=False)
    tier = build_serving_tier(scfg, max_batch=8, deadline_ms=1.0)
    try:
        assert tier._classes == 5
        assert tier._slo_items == 32
        assert tier._req_deadline_s == pytest.approx(0.25)
        assert not tier._coalesce
    finally:
        tier.stop()


def test_stop_fails_leftover_tickets():
    tier = _NoDispatch(max_batch=4, deadline_ms=0.0,
                       queue_slo_items=100)
    client = tier.register_policy("p", _scale_apply, _w(1.0))
    ticket = client.submit(np.zeros(2, np.float32))
    _wait_depth(tier, 1)
    tier.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        ticket.wait(timeout=1.0)


# -- tagged-request wire interop --------------------------------------------


def _mini_batch():
    return {"obs": np.zeros((2, 4), np.uint8),
            "priorities": np.ones(2, np.float32), "actor": 0}


def test_serve_tags_negotiated_new_client_new_server():
    from ape_x_dqn_tpu.comm.socket_transport import (
        SocketIngestServer, SocketTransport)

    server = SocketIngestServer("127.0.0.1", 0)
    client = SocketTransport("127.0.0.1", server.port,
                             serve_policy="pong", serve_class=1)
    try:
        client.send_experience(_mini_batch())
        assert server.recv_experience(timeout=5.0) is not None
        assert client.serve_negotiated
        assert server.serve_peers == {"pong": 1}
    finally:
        client.close()
        server.stop()
        assert server.serve_peers == {}


def test_serve_tags_old_client_new_server():
    """A client that never offers a serve tag (old build / tenancy off)
    negotiates exactly as before: no serve peers, experience flows."""
    from ape_x_dqn_tpu.comm.socket_transport import (
        SocketIngestServer, SocketTransport)

    server = SocketIngestServer("127.0.0.1", 0)
    client = SocketTransport("127.0.0.1", server.port)
    try:
        client.send_experience(_mini_batch())
        assert server.recv_experience(timeout=5.0) is not None
        assert not client.serve_negotiated
        assert server.serve_peers == {}
    finally:
        client.close()
        server.stop()


def test_serve_tags_new_client_old_server():
    """An OLD server ignores MSG_HELLO entirely: the tagged client must
    degrade (serve_negotiated False, raw codec) and its experience must
    still arrive."""
    import socket as socket_mod

    from ape_x_dqn_tpu.comm.socket_transport import (
        MSG_EXPERIENCE, SocketTransport, _recv_msg, decode_batch)

    listener = socket_mod.socket(socket_mod.AF_INET,
                                 socket_mod.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    got: list = []

    def old_server():
        conn, _ = listener.accept()
        while True:
            msg = _recv_msg(conn)
            if msg is None:
                return
            if msg[0] == MSG_EXPERIENCE:  # hellos silently ignored
                got.append(msg[1])
                return

    thread = threading.Thread(target=old_server, daemon=True)
    thread.start()
    client = SocketTransport("127.0.0.1", listener.getsockname()[1],
                             hello_timeout=0.3,
                             serve_policy="pong", serve_class=0)
    try:
        batch = _mini_batch()
        client.send_experience(batch)
        assert not client.serve_negotiated
        thread.join(timeout=5)
        assert got, "old server never received the raw experience"
        np.testing.assert_array_equal(decode_batch(got[0])["obs"],
                                      batch["obs"])
    finally:
        client.close()
        listener.close()


def test_transport_backpressure_gate_drops_and_releases():
    """set_backpressure(True) — the serving tier's SLO signal — makes
    send_experience drop (attributed to the 'backpressure' bucket)
    without touching the socket; release resumes delivery."""
    from ape_x_dqn_tpu.comm.socket_transport import (
        SocketIngestServer, SocketTransport)

    server = SocketIngestServer("127.0.0.1", 0)
    client = SocketTransport("127.0.0.1", server.port,
                             serve_policy="pong")
    try:
        client.send_experience(_mini_batch())
        assert server.recv_experience(timeout=5.0) is not None
        client.set_backpressure(True)
        before = client.dropped
        client.send_experience(_mini_batch())
        client.send_experience(_mini_batch())
        assert client.dropped == before + 2
        assert client.drop_reasons["backpressure"] == 2
        assert server.recv_experience(timeout=0.2) is None
        client.set_backpressure(False)
        client.send_experience(_mini_batch())
        assert server.recv_experience(timeout=5.0) is not None
    finally:
        client.close()
        server.stop()
