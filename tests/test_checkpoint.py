"""Checkpoint/resume: Orbax round-trip and driver resume continuity
(SURVEY.md §5 "Checkpoint / resume")."""

import jax
import numpy as np

from ape_x_dqn_tpu.configs import (
    ActorConfig, InferenceConfig, LearnerConfig, ReplayConfig, get_config)
from ape_x_dqn_tpu.runtime.driver import ApexDriver
from ape_x_dqn_tpu.utils.checkpoint import CheckpointManager


def _ckpt_cfg(tmp_path, **kw):
    return get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=1, base_eps=0.6, ingest_batch=16),
        replay=ReplayConfig(kind="prioritized", capacity=2048, min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=20,
        eval_every_steps=0, eval_episodes=0,
        **kw)


def test_checkpoint_manager_roundtrip(tmp_path):
    mngr = CheckpointManager(str(tmp_path / "m"))
    payload = {"params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
               "step": np.int32(7)}
    mngr.save(7, payload, wait=True)
    assert mngr.latest_step() == 7
    got = mngr.restore(template=jax.tree.map(np.zeros_like, payload))
    np.testing.assert_array_equal(got["params"]["w"], payload["params"]["w"])
    assert int(got["step"]) == 7
    mngr.close()


def test_driver_saves_and_resumes(tmp_path):
    cfg = _ckpt_cfg(tmp_path)
    d1 = ApexDriver(cfg)
    out1 = d1.run(total_env_frames=1500, max_grad_steps=50,
                  wall_clock_limit_s=120)
    assert out1["actor_errors"] == [] and out1["loop_errors"] == []
    assert out1["grad_steps"] >= 50
    assert d1.ckpt.latest_step() == out1["grad_steps"]
    final_params = jax.tree.map(np.asarray, d1.state.params)

    # a fresh driver restores the latest checkpoint bitwise and resumes
    # the grad-step counter
    d2 = ApexDriver(cfg)
    assert d2._grad_steps_total == out1["grad_steps"]
    restored = jax.tree.map(np.asarray, d2.state.params)
    jax.tree.map(np.testing.assert_array_equal, final_params, restored)
    # restored params were published to the fresh inference server
    assert d2.server.params_version == out1["grad_steps"]

    # the resumed run continues to an ABSOLUTE grad-step target
    out2 = d2.run(total_env_frames=1500,
                  max_grad_steps=out1["grad_steps"] + 20,
                  wall_clock_limit_s=120)
    assert out2["actor_errors"] == [] and out2["loop_errors"] == []
    assert out2["grad_steps"] >= out1["grad_steps"] + 20
    assert d2.ckpt.latest_step() == out2["grad_steps"]


def test_replay_contents_checkpoint_skips_min_fill(tmp_path):
    """Opt-in replay checkpointing (SURVEY.md §5 'and (optionally)
    replay contents'): a resumed driver restores the device ReplayState
    and can train IMMEDIATELY — no re-ingest, no min_fill stall."""
    cfg = _ckpt_cfg(tmp_path, checkpoint_replay=True)
    d1 = ApexDriver(cfg)
    out1 = d1.run(total_env_frames=1500, max_grad_steps=50,
                  wall_clock_limit_s=120)
    assert out1["actor_errors"] == [] and out1["loop_errors"] == []
    filled1 = d1._replay_filled
    assert filled1 >= cfg.replay.min_fill
    tree1 = np.asarray(d1.state.replay.tree)

    d2 = ApexDriver(cfg)
    try:
        # the restored fill mirror already clears min_fill: the learner
        # loop would dispatch on its first iteration without any ingest
        assert d2._replay_filled == filled1
        assert d2._replay_filled >= d2._min_fill()
        # device replay state round-trips bitwise (sum-tree included)
        np.testing.assert_array_equal(np.asarray(d2.state.replay.tree),
                                      tree1)
        # and training off the restored contents actually works
        state, m = d2.learner.train_step(d2.state)
        assert np.isfinite(float(m["loss"]))
    finally:
        d2.server.stop()


def test_checkpoint_replay_flag_toggle_does_not_brick_resume(tmp_path):
    """checkpoint_replay governs SAVES; restores follow what the file
    contains — toggling the flag between runs must neither crash the
    Orbax template restore nor lose the saved replay contents."""
    cfg_off = _ckpt_cfg(tmp_path)
    d1 = ApexDriver(cfg_off)
    out1 = d1.run(total_env_frames=1500, max_grad_steps=40,
                  wall_clock_limit_s=120)
    assert out1["actor_errors"] == [] and out1["loop_errors"] == []

    # replay-less checkpoint, flag now ON: restore must not mismatch
    cfg_on = cfg_off.replace(checkpoint_replay=True)
    d2 = ApexDriver(cfg_on)
    assert d2._grad_steps_total == out1["grad_steps"]
    out2 = d2.run(total_env_frames=1500,
                  max_grad_steps=out1["grad_steps"] + 20,
                  wall_clock_limit_s=120)
    assert out2["actor_errors"] == [] and out2["loop_errors"] == []

    # d2's final save carried replay; flag OFF again: the contents
    # still restore (and future saves would drop them)
    d3 = ApexDriver(cfg_off)
    try:
        assert d3._grad_steps_total == out2["grad_steps"]
        assert d3._replay_filled > 0
    finally:
        d3.server.stop()


def test_multihost_rejects_checkpoint_replay():
    """The multihost driver must reject checkpoint_replay loudly (a
    silent no-op would break the config's resume promise). The gate
    sits before the process-count check so it is unit-testable."""
    import pytest

    from ape_x_dqn_tpu.configs import get_config
    from ape_x_dqn_tpu.runtime.multihost_driver import MultihostApexDriver

    cfg = get_config("cartpole_smoke").replace(checkpoint_replay=True)
    with pytest.raises(NotImplementedError, match="single-host only"):
        MultihostApexDriver(cfg)


def test_driver_without_checkpoint_dir_has_no_manager():
    cfg = get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=1),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0))
    d = ApexDriver(cfg)
    try:
        assert d.ckpt is None
    finally:
        d.server.stop()


def test_checkpoint_layout_version_stamp_transparent(tmp_path):
    """Every dict payload carries a storage-layout version stamp on
    disk, yet callers never see it: restore() strips it after checking,
    and item_keys() excludes it (the driver builds restore templates
    from item_keys, so the stamp must stay invisible there)."""
    import pytest

    from ape_x_dqn_tpu.utils import checkpoint as ckpt_mod

    mngr = CheckpointManager(str(tmp_path / "m"))
    payload = {"params": {"w": np.ones((2, 3), np.float32)},
               "step": np.asarray(5, np.int32)}
    mngr.save(5, payload, wait=True)

    # the stamp IS on disk...
    raw = mngr._raw_item_keys(5)
    assert raw is not None and ckpt_mod._LAYOUT_KEY in raw
    # ...but item_keys() (the driver's template source) never shows it
    assert mngr.item_keys(5) == {"params", "step"}
    # ...and restore() strips it from the returned payload
    got = mngr.restore(template=jax.tree.map(np.zeros_like, payload))
    assert ckpt_mod._LAYOUT_KEY not in got
    np.testing.assert_array_equal(got["params"]["w"], payload["params"]["w"])

    # a version mismatch fails loudly WITH the recovery guidance
    mngr.save(6, {**payload,
                  ckpt_mod._LAYOUT_KEY: np.asarray(999, np.int32)},
              wait=True)
    with pytest.raises(RuntimeError, match="storage layout v999"):
        mngr.restore(step=6, template=jax.tree.map(np.zeros_like, payload))
    mngr.close()


def test_checkpoint_structure_mismatch_guidance(tmp_path):
    """An Orbax structure mismatch (e.g. a replay-bearing checkpoint
    written under the pre-versioning layout restored into new-layout
    shapes) surfaces as a RuntimeError carrying the documented recovery
    guidance, not a raw Orbax traceback."""
    import pytest

    mngr = CheckpointManager(str(tmp_path / "m"))
    mngr.save(3, {"params": {"w": np.ones((4, 4), np.float32)},
                  "step": np.asarray(3, np.int32)}, wait=True)
    bad_template = {"params": {"w": np.zeros((4, 4), np.float32)},
                    "replay_frames": np.zeros((8, 128), np.uint8),
                    "step": np.asarray(0, np.int32)}
    with pytest.raises(RuntimeError, match="restart the run fresh"):
        mngr.restore(step=3, template=bad_template)
    mngr.close()
