"""Checkpoint/resume: Orbax round-trip and driver resume continuity
(SURVEY.md §5 "Checkpoint / resume")."""

import jax
import numpy as np

from ape_x_dqn_tpu.configs import (
    ActorConfig, InferenceConfig, LearnerConfig, ReplayConfig, get_config)
from ape_x_dqn_tpu.runtime.driver import ApexDriver
from ape_x_dqn_tpu.utils.checkpoint import CheckpointManager


def _ckpt_cfg(tmp_path, **kw):
    return get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=1, base_eps=0.6, ingest_batch=16),
        replay=ReplayConfig(kind="prioritized", capacity=2048, min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=20,
        eval_every_steps=0, eval_episodes=0,
        **kw)


def test_checkpoint_manager_roundtrip(tmp_path):
    mngr = CheckpointManager(str(tmp_path / "m"))
    payload = {"params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
               "step": np.int32(7)}
    mngr.save(7, payload, wait=True)
    assert mngr.latest_step() == 7
    got = mngr.restore(template=jax.tree.map(np.zeros_like, payload))
    np.testing.assert_array_equal(got["params"]["w"], payload["params"]["w"])
    assert int(got["step"]) == 7
    mngr.close()


def test_driver_saves_and_resumes(tmp_path):
    cfg = _ckpt_cfg(tmp_path)
    d1 = ApexDriver(cfg)
    out1 = d1.run(total_env_frames=1500, max_grad_steps=50,
                  wall_clock_limit_s=120)
    assert out1["actor_errors"] == [] and out1["loop_errors"] == []
    assert out1["grad_steps"] >= 50
    assert d1.ckpt.latest_step() == out1["grad_steps"]
    final_params = jax.tree.map(np.asarray, d1.state.params)

    # a fresh driver restores the latest checkpoint bitwise and resumes
    # the grad-step counter
    d2 = ApexDriver(cfg)
    assert d2._grad_steps_total == out1["grad_steps"]
    restored = jax.tree.map(np.asarray, d2.state.params)
    jax.tree.map(np.testing.assert_array_equal, final_params, restored)
    # restored params were published to the fresh inference server
    assert d2.server.params_version == out1["grad_steps"]

    # the resumed run continues to an ABSOLUTE grad-step target
    out2 = d2.run(total_env_frames=1500,
                  max_grad_steps=out1["grad_steps"] + 20,
                  wall_clock_limit_s=120)
    assert out2["actor_errors"] == [] and out2["loop_errors"] == []
    assert out2["grad_steps"] >= out1["grad_steps"] + 20
    assert d2.ckpt.latest_step() == out2["grad_steps"]


def test_driver_without_checkpoint_dir_has_no_manager():
    cfg = get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=1),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0))
    d = ApexDriver(cfg)
    try:
        assert d.ckpt is None
    finally:
        d.server.stop()
