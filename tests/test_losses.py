import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.ops import value_rescale
from ape_x_dqn_tpu.ops.losses import (
    ContinuousBatch, SequenceBatch, TransitionBatch, dqn_td_error, huber,
    make_dqn_loss, make_dpg_losses, make_r2d2_loss,
    nstep_targets_in_sequence)
from ape_x_dqn_tpu.ops.nstep import NStepBuilder


def test_huber_values():
    x = jnp.array([0.5, 1.0, 2.0, -3.0])
    expected = jnp.array([0.125, 0.5, 1.5, 2.5])  # delta=1
    np.testing.assert_allclose(huber(x), expected, rtol=1e-6)


def test_value_rescale_inverse():
    x = jnp.linspace(-50.0, 50.0, 101)
    np.testing.assert_allclose(value_rescale.h_inv(value_rescale.h(x)), x,
                               rtol=1e-4, atol=1e-4)


def test_dqn_td_error_hand_computed():
    """Tiny hand-worked example (SURVEY.md §4 'loss value against a tiny
    hand-computed example')."""
    q_s = jnp.array([[1.0, 2.0]])          # Q(s,.), action taken = 0 -> 1.0
    q_sp_online = jnp.array([[0.5, 3.0]])  # argmax -> action 1
    q_sp_target = jnp.array([[10.0, 4.0]])  # double-DQN evaluates -> 4.0
    batch = TransitionBatch(
        obs=None, actions=jnp.array([0]), rewards=jnp.array([1.5]),
        next_obs=None, discounts=jnp.array([0.9]))
    td = dqn_td_error(q_s, q_sp_online, q_sp_target, batch, double=True)
    # target = 1.5 + 0.9 * 4.0 = 5.1; td = 1.0 - 5.1 = -4.1
    np.testing.assert_allclose(td, [-4.1], rtol=1e-6)
    td_plain = dqn_td_error(q_s, q_sp_online, q_sp_target, batch,
                            double=False)
    # plain DQN: max target Q = 10.0 -> target 10.5; td = -9.5
    np.testing.assert_allclose(td_plain, [-9.5], rtol=1e-6)


def test_dqn_loss_is_weighting():
    def net_apply(params, obs):
        return obs @ params  # linear "net": obs [B,2] @ [2,2]

    params = jnp.eye(2)
    target_params = jnp.eye(2)
    loss_fn = make_dqn_loss(net_apply, double=True)
    batch = TransitionBatch(
        obs=jnp.array([[1.0, 0.0], [0.0, 1.0]]),
        actions=jnp.array([0, 1]),
        rewards=jnp.array([0.0, 0.0]),
        next_obs=jnp.zeros((2, 2)),
        discounts=jnp.array([0.0, 0.0]))
    # q_sa = [1, 1], target = 0 -> td = 1 -> huber = 0.5 each
    loss_eq, aux = loss_fn(params, target_params, batch, jnp.array([1.0, 1.0]))
    np.testing.assert_allclose(loss_eq, 0.5, rtol=1e-6)
    np.testing.assert_allclose(aux["td_abs"], [1.0, 1.0], rtol=1e-6)
    # doubling one IS weight scales its contribution
    loss_w, _ = loss_fn(params, target_params, batch, jnp.array([2.0, 0.0]))
    np.testing.assert_allclose(loss_w, 0.5, rtol=1e-6)  # (2*0.5 + 0)/2


def test_dqn_loss_grad_flows():
    def net_apply(params, obs):
        return obs @ params

    loss_fn = make_dqn_loss(net_apply)
    batch = TransitionBatch(
        obs=jnp.array([[1.0, 2.0]]), actions=jnp.array([0]),
        rewards=jnp.array([1.0]), next_obs=jnp.array([[0.5, 0.5]]),
        discounts=jnp.array([0.9]))
    g = jax.grad(lambda p: loss_fn(p, jnp.eye(2), batch,
                                   jnp.ones(1))[0])(jnp.eye(2))
    assert jnp.abs(g).sum() > 0  # online net receives gradient
    # target params get no gradient (stop_gradient on target)
    g_t = jax.grad(lambda tp: loss_fn(jnp.eye(2), tp, batch,
                                      jnp.ones(1))[0])(jnp.eye(2))
    np.testing.assert_allclose(g_t, 0.0)


def test_nstep_targets_in_sequence_hand_computed():
    gamma = 0.5
    rewards = jnp.array([[1.0, 2.0, 4.0, 8.0]])
    terminals = jnp.zeros((1, 4))
    boot = jnp.array([[10.0, 20.0, 30.0, 40.0]])
    mask = jnp.ones((1, 4))
    target, valid = nstep_targets_in_sequence(
        rewards, terminals, boot, mask, n_step=2, gamma=gamma, rescale=False)
    # t=0: 1 + 0.5*2 + 0.25*boot[2] = 2 + 7.5 = 9.5
    # t=1: 2 + 0.5*4 + 0.25*boot[3] = 4 + 10 = 14
    np.testing.assert_allclose(target[0, :2], [9.5, 14.0], rtol=1e-6)
    np.testing.assert_allclose(valid[0], [1, 1, 0, 0])


def test_nstep_targets_respect_terminals():
    gamma = 0.9
    rewards = jnp.array([[1.0, 5.0, 7.0]])
    terminals = jnp.array([[1.0, 0.0, 0.0]])  # episode ends at t=0
    boot = jnp.full((1, 3), 100.0)
    mask = jnp.ones((1, 3))
    target, valid = nstep_targets_in_sequence(
        rewards, terminals, boot, mask, n_step=2, gamma=gamma, rescale=False)
    # t=0: r0 = 1, then terminal: no r1, no bootstrap -> target = 1
    np.testing.assert_allclose(target[0, 0], 1.0, rtol=1e-6)


def test_r2d2_loss_runs_and_masks():
    # trivial "net": q[t] = params * obs[t] summed, state passthrough
    def net_apply_seq(params, obs, state):
        q = jnp.einsum("btd,da->bta", obs, params)
        return q, state

    params = jnp.ones((3, 2))
    loss_fn = make_r2d2_loss(net_apply_seq, burn_in=2, n_step=1, gamma=0.9,
                             rescale=False)
    b, length = 2, 6
    batch = SequenceBatch(
        obs=jax.random.normal(jax.random.key(0), (b, length, 3)),
        actions=jnp.zeros((b, length), jnp.int32),
        rewards=jnp.ones((b, length)),
        terminals=jnp.zeros((b, length)),
        mask=jnp.ones((b, length)),
        init_state=(jnp.zeros((b, 4)), jnp.zeros((b, 4))))
    loss, aux = loss_fn(params, params, batch, jnp.ones(b))
    assert jnp.isfinite(loss)
    assert aux["td_abs"].shape == (b,)  # per-sequence priorities
    # gradient flows to params
    g = jax.grad(lambda p: loss_fn(p, params, batch, jnp.ones(b))[0])(params)
    assert jnp.abs(g).sum() > 0


def test_dpg_losses():
    def actor_apply(p, obs):
        return jnp.tanh(obs @ p)

    def critic_apply(p, obs, act):
        return (obs @ p).sum(-1) + act.sum(-1)

    critic_loss, policy_loss = make_dpg_losses(actor_apply, critic_apply)
    batch = ContinuousBatch(
        obs=jnp.array([[1.0, 0.0]]), actions=jnp.array([[0.3]]),
        rewards=jnp.array([2.0]), next_obs=jnp.array([[0.0, 1.0]]),
        discounts=jnp.array([0.9]))
    p = jnp.ones((2, 1))
    loss, aux = critic_loss(p, p, p, batch, jnp.ones(1))
    assert jnp.isfinite(loss) and aux["td_abs"].shape == (1,)
    pl, _ = policy_loss(p, p, batch)
    g = jax.grad(lambda ap: policy_loss(ap, p, batch)[0])(p)
    assert jnp.abs(g).sum() > 0


def test_nstep_builder_hand_computed():
    b = NStepBuilder(n_step=3, gamma=0.5)
    obs = [np.array([float(i)]) for i in range(10)]
    out = []
    out += b.append(obs[0], 0, 1.0, obs[1], False)
    out += b.append(obs[1], 1, 2.0, obs[2], False)
    assert not out  # window not yet full
    out += b.append(obs[2], 0, 4.0, obs[3], False)
    assert len(out) == 1
    t = out[0]
    # R_3 = 1 + 0.5*2 + 0.25*4 = 3.0; discount = 0.5^3
    assert t.reward == 3.0 and t.discount == 0.125
    assert t.obs[0] == 0.0 and t.next_obs[0] == 3.0 and t.action == 0


def test_nstep_builder_terminal_flush():
    b = NStepBuilder(n_step=3, gamma=0.5)
    obs = [np.array([float(i)]) for i in range(5)]
    out = []
    out += b.append(obs[0], 0, 1.0, obs[1], False)
    out += b.append(obs[1], 0, 2.0, obs[2], True)  # terminal at step 2
    # flush: two transitions, both with discount 0
    assert len(out) == 2
    assert out[0].reward == 1.0 + 0.5 * 2.0 and out[0].discount == 0.0
    assert out[1].reward == 2.0 and out[1].discount == 0.0
    assert len(b._window) == 0


def test_nstep_builder_truncation_keeps_bootstrap():
    b = NStepBuilder(n_step=3, gamma=0.5)
    obs = [np.array([float(i)]) for i in range(5)]
    out = b.append(obs[0], 0, 1.0, obs[1], False, truncated=True)
    assert len(out) == 1
    # truncated: bootstrap kept, discount = gamma^1
    assert out[0].discount == 0.5


def test_nstep_builder_terminal_on_window_full():
    """Terminal arriving exactly when the window fills must zero the
    bootstrap for ALL flushed transitions (regression: the full-window
    emit used to bootstrap past the terminal)."""
    b = NStepBuilder(n_step=3, gamma=0.5)
    obs = [np.array([float(i)]) for i in range(5)]
    out = []
    out += b.append(obs[0], 0, 1.0, obs[1], False)
    out += b.append(obs[1], 0, 2.0, obs[2], False)
    out += b.append(obs[2], 0, 4.0, obs[3], True)  # terminal as window fills
    assert len(out) == 3
    assert all(t.discount == 0.0 for t in out)
    assert out[0].reward == 1.0 + 0.5 * 2.0 + 0.25 * 4.0


def test_sequence_targets_never_bootstrap_from_padding():
    rewards = jnp.array([[1.0, 1.0, 1.0, 0.0]])
    terminals = jnp.zeros((1, 4))
    mask = jnp.array([[1.0, 1.0, 1.0, 0.0]])  # last step is padding
    boot = jnp.full((1, 4), 100.0)
    _, valid = nstep_targets_in_sequence(
        rewards, terminals, boot, mask, n_step=1, gamma=0.9, rescale=False)
    # t=2 would bootstrap from padded t=3 -> must be invalid
    np.testing.assert_allclose(valid[0], [1.0, 1.0, 0.0, 0.0])


def test_nstep_targets_terminal_window_valid_at_sequence_end():
    """A terminal inside [t, t+n) fully determines the target even when
    t+n hangs off the sequence end — the last n transitions of every
    episode (including the terminal-reward step) must be trained on."""
    gamma = 0.5
    rewards = jnp.array([[1.0, 2.0, 4.0, 8.0]])
    terminals = jnp.array([[0.0, 0.0, 0.0, 1.0]])
    boot = jnp.full((1, 4), 100.0)
    mask = jnp.ones((1, 4))
    target, valid = nstep_targets_in_sequence(
        rewards, terminals, boot, mask, n_step=2, gamma=gamma, rescale=False)
    # t=2: 4 + 0.5*8, terminal at t=3 kills the bootstrap -> grounded
    # t=3: window [3,5) off the end BUT terminal at t=3 -> target = 8
    np.testing.assert_allclose(target[0, 2:], [8.0, 8.0], rtol=1e-6)
    np.testing.assert_allclose(valid[0], [1, 1, 1, 1])


def test_nstep_targets_terminal_then_padding():
    """Typical terminal-flushed sequence: padding after the terminal.
    Steps whose window reaches into padding stay valid iff grounded."""
    gamma = 1.0
    rewards = jnp.array([[1.0, 2.0, 4.0, 0.0]])
    terminals = jnp.array([[0.0, 0.0, 1.0, 0.0]])
    mask = jnp.array([[1.0, 1.0, 1.0, 0.0]])
    boot = jnp.full((1, 4), 100.0)
    target, valid = nstep_targets_in_sequence(
        rewards, terminals, boot, mask, n_step=2, gamma=gamma, rescale=False)
    # t=0: 1 + 2 + boot[2] = 103 (bootstrap real, in range)
    # t=1: 2 + 4, terminal at t=2 -> grounded (boot position 3 is padding)
    # t=2: 4, terminal at t=2 -> grounded
    # t=3: padding -> invalid
    np.testing.assert_allclose(target[0, :3], [103.0, 6.0, 4.0], rtol=1e-6)
    np.testing.assert_allclose(valid[0], [1, 1, 1, 0])


def test_nstep_targets_no_wraparound_leak():
    """jnp.roll wraps; a terminal at t=0 must not leak into windows
    hanging off the tail (which would mark them spuriously valid)."""
    gamma = 1.0
    rewards = jnp.array([[1.0, 2.0, 4.0, 8.0]])
    terminals = jnp.array([[1.0, 0.0, 0.0, 0.0]])
    boot = jnp.zeros((1, 4))  # zero bootstrap isolates the reward sums
    mask = jnp.ones((1, 4))
    target, valid = nstep_targets_in_sequence(
        rewards, terminals, boot, mask, n_step=2, gamma=gamma, rescale=False)
    # t=2 and t=3: no terminal in window, bootstrap off the end -> invalid
    np.testing.assert_allclose(valid[0], [1, 1, 0, 0])
    # and the wrapped reward r[0] must not appear in t=3's return
    np.testing.assert_allclose(target[0, 3], 8.0, rtol=1e-6)
