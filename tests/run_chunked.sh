#!/usr/bin/env bash
# Chunked test-suite runner: one pytest process per test file.
#
# Why: the documented one-command `pytest tests/` invocation
# reproducibly SIGSEGVs at ~85% inside XLA's backend_compile_and_load
# on this image (VERDICT.md round 5) — an accumulation crash in the
# long-lived XLA CPU client, not a test failure. Running each file in
# its own interpreter bounds per-process compile-cache growth and makes
# the full tier-2 suite (including -m slow, if you drop the filter)
# completable in one command. The tier-1 command in ROADMAP.md stays
# authoritative for CI gating; this script is the local full-suite
# convenience.
#
# Usage:
#   tests/run_chunked.sh                 # tier-1 scope, per-file
#   tests/run_chunked.sh -m ''           # include slow tests
#   tests/run_chunked.sh -k kbatch       # extra pytest args pass through
set -u
cd "$(dirname "$0")/.."

fail=0
failed_files=()

# Compile-telemetry ledger (obs/profiling.py): each pytest process
# appends one JSON line {argv, jit_compiles, jit_compile_ms} at exit,
# making the per-file compile-cache growth this chunking exists to
# bound a printed, monitored quantity instead of folklore.
compile_log="$(mktemp "${TMPDIR:-/tmp}/apex_compile_log.XXXXXX")"
export APEX_COMPILE_LOG="${compile_log}"

# Static-analysis gate first: cheap (stdlib-only, no jax import) and a
# finding here usually explains the test failure that would follow.
# The JSON is piped through a per_checker key assertion so a refactor
# that silently drops a checker (v3's lifecycle/closure three
# included) fails HERE, not in a review months later.
echo "=== tools/apexlint"
lint_json="$(python -m tools.apexlint ape_x_dqn_tpu/ --format=json)"
lint_rc=$?
printf '%s\n' "${lint_json}"
if [ "${lint_rc}" -ne 0 ] || ! printf '%s' "${lint_json}" | python -c '
import json, sys
summary = json.load(sys.stdin)
required = {"guarded-by", "jit-purity", "wire-protocol", "obs-names",
            "retry-annotation", "remediation-accounting",
            "use-after-donate", "host-sync", "config-coverage",
            "learner-parity", "thread-lifecycle", "resource-lifecycle",
            "counter-closure"}
missing = required - set(summary["per_checker"])
if missing:
    sys.exit(f"apexlint checkers missing from run: {sorted(missing)}")
'; then
    fail=1
    failed_files+=("tools/apexlint")
fi
echo
for f in tests/test_*.py; do
    echo "=== ${f}"
    lines_before=$(wc -l < "${compile_log}" 2>/dev/null || echo 0)
    if ! env JAX_PLATFORMS=cpu python -m pytest "${f}" -q -m 'not slow' \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"; then
        fail=1
        failed_files+=("${f}")
    fi
    # crash-safe: only lines this file's process appended (a SIGSEGV
    # before atexit simply prints nothing here)
    tail -n +"$((lines_before + 1))" "${compile_log}" 2>/dev/null \
        | sed 's/^/    compile growth: /'
done

# Perf-regression gate: the smoke bench compares against the last
# committed BENCH_SMOKE.json artifact and exits nonzero on a >30%
# throughput drop — warn-only gauges above, a hard gate here.
echo
echo "=== bench.py --perf-gate --smoke"
if ! python bench.py --perf-gate --smoke; then
    fail=1
    failed_files+=("bench.py --perf-gate --smoke")
fi

# Learning-health smoke: two short synthetic-Atari tenants through the
# single-process driver with obs on, then the report's --check mode
# gates the published learn_* gauges against the INSTRUMENTS
# healthy-range rows. The lane itself is warn-only (exit 0 as long as
# the plane publishes); --check is where health becomes a hard gate.
echo
echo "=== bench.py --learn-health --smoke"
if ! python bench.py --learn-health --smoke; then
    fail=1
    failed_files+=("bench.py --learn-health --smoke")
elif ! python -m ape_x_dqn_tpu.obs.report LEARN_HEALTH_SMOKE.jsonl --check; then
    fail=1
    failed_files+=("obs.report LEARN_HEALTH_SMOKE.jsonl --check")
fi

# Multi-chip smoke: dp=1,2 over virtual devices (the lane
# self-provisions --xla_force_host_platform_device_count in child
# processes). Proves the sharded ingest/train path end-to-end and
# anti-ratchets dp-scaling efficiency against the last comparable
# (same dp set, same device mode) MULTICHIP_SMOKE.json — incomparable
# baselines are skipped, never compared across shapes.
echo
echo "=== bench.py --multichip dp=1,2 --smoke"
if ! python bench.py --multichip dp=1,2 --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --multichip dp=1,2 --smoke")
fi

# Tiered-replay smoke: the eviction-swap A/B + capacity soak
# (replay/cold_store.py). The lane's own criteria (cold tier holds 8x
# the ring at < 1/8 of its bytes/transition) are hard, and --perf-gate
# anti-ratchets the on-arm grad-steps/s against the last comparable
# (same storage/capacity/smoke class) TIERED_SMOKE.json; failing runs
# never reseed the baseline.
echo
echo "=== bench.py --tiered-ab --smoke"
if ! python bench.py --tiered-ab --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --tiered-ab --smoke")
fi

# Disk-arm smoke (replay/disk_store.py, PR 16): the same swap loop
# with admission-door losers spilling to the async disk writeback vs
# spill off, plus the retention soak (disk holds 8x the cold tier's
# capacity) and promote() readback. Hard criteria: retention >= 8x,
# zero io_errors/corrupt segments; --perf-gate anti-ratchets the
# on-arm grad-steps/s against the last comparable (same storage/ring/
# cold capacity/smoke class) TIERED_DISK_SMOKE.json; failing runs
# never reseed the baseline.
echo
echo "=== bench.py --tiered-ab --tiered-disk --smoke"
if ! python bench.py --tiered-ab --tiered-disk --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --tiered-ab --tiered-disk --smoke")
fi

# Serving-tier smoke: the multi-tenant A/B + 2x-overload shedding
# phase (parallel/inference_server.py serving tier). The lane's own
# criteria are hard (multi/single >= 0.9 both orders pooled, top-class
# p99 inside the INSTRUMENTS healthy range, class-0 shed == 0,
# accounting closure), and --perf-gate anti-ratchets aggregate
# forwards/s against the last comparable (same tenants/max_batch/
# vector/smoke class) SERVE_SMOKE.json; failing runs never reseed.
echo
echo "=== bench.py --serve-ab --smoke"
if ! python bench.py --serve-ab --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --serve-ab --smoke")
fi

# Shared-memory transport smoke: the same-host shm ring + doorbell
# plane vs plain TCP loopback (comm/shm_transport.py, ISSUE 18), both
# orders, uncapped + contended (3-producer) arms. The lane's own
# criteria are hard (shm >= 2x TCP contended items/s in BOTH orders,
# slot/drop accounting closed, zero torn slots delivered), and
# --perf-gate anti-ratchets contended shm items/s against the last
# comparable (same producers/units-per-msg/smoke class) SHM_SMOKE.json;
# failing runs never reseed the baseline.
echo
echo "=== bench.py --shm-ab --smoke"
if ! python bench.py --shm-ab --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --shm-ab --smoke")
fi

# Param-plane codec smoke: delta-q8 vs raw weight broadcast to real
# push subscribers (comm/param_codec.py, ISSUE 19), both orders, plus
# the capped-link run, the quantized-policy greedy-parity smoke and
# the slow-subscriber isolation arm. The lane's own criteria are hard
# (>= 3x bytes/publish cut in BOTH orders, parity >= 0.99, healthy
# peers unmoved by a wedged one), and --perf-gate anti-ratchets the
# reduction against the last comparable (same subs/param-count/smoke
# class) PARAMS_SMOKE.json; failing runs never reseed the baseline.
echo
echo "=== bench.py --params-ab --smoke"
if ! python bench.py --params-ab --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --params-ab --smoke")
fi

# Flight-recorder smoke: the recorder on/off overhead A/B
# (obs/blackbox.py) plus the dump round-trip and no-stray-dump
# checks. The full lane gates the on/off grad-steps/s ratio at the
# 0.95 PERF.md floor; the smoke lane anti-ratchets against the last
# comparable (same frames/smoke class) BLACKBOX_SMOKE.json — failing
# runs never reseed the baseline.
echo
echo "=== bench.py --blackbox-ab --smoke"
if ! python bench.py --blackbox-ab --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --blackbox-ab --smoke")
fi

# Chaos-remediation smoke: the three-arm availability drill (clean /
# chaos / chaos+remediation) from bench.py --chaos-ab. The remediated
# arm must beat the last comparable (same window/clients)
# CHAOS_SMOKE.json under --perf-gate — the anti-ratchet proves the
# remediation plane keeps EARNING its availability win, not just that
# it once did; failing runs never reseed the baseline. (The 0.822
# PERF.md floor applies only to the full lane — the smoke window is
# too short for an absolute bound.) The drill also hard-gates its own
# forensics: the postmortem bundle must exist and its root-cause walk
# must attribute the injected kill/wedge by component name.
echo
echo "=== bench.py --chaos-ab --smoke"
if ! python bench.py --chaos-ab --smoke --perf-gate; then
    fail=1
    failed_files+=("bench.py --chaos-ab --smoke")
fi

echo
if [ "${fail}" -ne 0 ]; then
    echo "FAILED files: ${failed_files[*]}"
else
    echo "all files passed"
fi
exit "${fail}"
