#!/usr/bin/env bash
# Chunked test-suite runner: one pytest process per test file.
#
# Why: the documented one-command `pytest tests/` invocation
# reproducibly SIGSEGVs at ~85% inside XLA's backend_compile_and_load
# on this image (VERDICT.md round 5) — an accumulation crash in the
# long-lived XLA CPU client, not a test failure. Running each file in
# its own interpreter bounds per-process compile-cache growth and makes
# the full tier-2 suite (including -m slow, if you drop the filter)
# completable in one command. The tier-1 command in ROADMAP.md stays
# authoritative for CI gating; this script is the local full-suite
# convenience.
#
# Usage:
#   tests/run_chunked.sh                 # tier-1 scope, per-file
#   tests/run_chunked.sh -m ''           # include slow tests
#   tests/run_chunked.sh -k kbatch       # extra pytest args pass through
set -u
cd "$(dirname "$0")/.."

fail=0
failed_files=()

# Static-analysis gate first: cheap (stdlib-only, no jax import) and a
# finding here usually explains the test failure that would follow.
echo "=== tools/apexlint"
if ! python -m tools.apexlint ape_x_dqn_tpu/ --format=json; then
    fail=1
    failed_files+=("tools/apexlint")
fi
echo
for f in tests/test_*.py; do
    echo "=== ${f}"
    if ! env JAX_PLATFORMS=cpu python -m pytest "${f}" -q -m 'not slow' \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"; then
        fail=1
        failed_files+=("${f}")
    fi
done

echo
if [ "${fail}" -ne 0 ]; then
    echo "FAILED files: ${failed_files[*]}"
else
    echo "all files passed"
fi
exit "${fail}"
