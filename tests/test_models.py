import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import NetworkConfig
from ape_x_dqn_tpu.envs.base import EnvSpec
from ape_x_dqn_tpu.models import (
    ApeXLSTMQNet, DPGActor, DPGCritic, MLPQNet, NatureDQN, build_network,
    hard_update, param_count, soft_update)

ATARI_SPEC = EnvSpec(obs_shape=(84, 84, 4), obs_dtype=np.dtype(np.uint8),
                     discrete=True, num_actions=6)
VEC_SPEC = EnvSpec(obs_shape=(4,), obs_dtype=np.dtype(np.float32),
                   discrete=True, num_actions=2)
CTRL_SPEC = EnvSpec(obs_shape=(3,), obs_dtype=np.dtype(np.float32),
                    discrete=False, action_dim=1, action_low=-2.0,
                    action_high=2.0)


def test_mlp_qnet():
    net = MLPQNet(num_actions=2, hidden=(32, 32))
    obs = jnp.zeros((5, 4))
    params = net.init(jax.random.key(0), obs)
    q = net.apply(params, obs)
    assert q.shape == (5, 2) and q.dtype == jnp.float32


def test_nature_dqn_shapes_and_dtype():
    net = NatureDQN(num_actions=6)
    obs = jnp.zeros((2, 84, 84, 4), jnp.uint8)
    params = net.init(jax.random.key(0), obs)
    q = net.apply(params, obs)
    assert q.shape == (2, 6) and q.dtype == jnp.float32
    # conv kernels stored f32 (params), compute dtype bf16 internally
    leaf = jax.tree.leaves(params)[0]
    assert leaf.dtype == jnp.float32
    # Nature-CNN torso size: known parameter count ballpark (~1.69M)
    n = param_count(params)
    assert 1_500_000 < n < 2_000_000


def test_dueling_identity():
    """Dueling merge: mean over actions of (Q - V) must be 0."""
    net = NatureDQN(num_actions=6, dueling=True, compute_dtype="float32")
    obs = jax.random.randint(jax.random.key(1), (3, 84, 84, 4), 0, 255,
                             jnp.uint8)
    params = net.init(jax.random.key(0), obs)
    q = net.apply(params, obs)
    # Q = V + A - mean(A) implies mean_a Q = V; so Q - mean(Q) = A - mean(A)
    # and the advantage head's contribution is zero-mean:
    centered = q - q.mean(axis=-1, keepdims=True)
    assert jnp.abs(centered.mean(axis=-1)).max() < 1e-4


def test_lstm_qnet_unroll_matches_stepwise():
    """Full-sequence unroll == repeated single steps (same params/state)."""
    net = ApeXLSTMQNet(num_actions=3, lstm_size=16, mlp_torso=True,
                       mlp_hidden=8, compute_dtype="float32")
    b, t = 2, 5
    obs_seq = jax.random.normal(jax.random.key(2), (b, t, 4))
    state0 = net.initial_state(b)
    params = net.init(jax.random.key(0), obs_seq, state0)
    q_seq, final = net.apply(params, obs_seq, state0)
    assert q_seq.shape == (b, t, 3)

    state = state0
    qs = []
    for i in range(t):
        q, state = net.apply(params, obs_seq[:, i], state, method=net.step)
        qs.append(q)
    q_steps = jnp.stack(qs, axis=1)
    np.testing.assert_allclose(q_seq, q_steps, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(final[0], state[0], rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(final[1], state[1], rtol=2e-5, atol=2e-5)


def test_lstm_state_roundtrip_float32():
    net = ApeXLSTMQNet(num_actions=3, lstm_size=8, mlp_torso=True,
                       mlp_hidden=8)
    s = net.initial_state(4)
    assert s[0].dtype == jnp.float32 and s[0].shape == (4, 8)
    obs = jnp.zeros((4, 4))
    params = net.init(jax.random.key(0), obs[:, None], s)
    _, s2 = net.apply(params, obs[:, None], s)
    assert s2[0].dtype == jnp.float32  # replay stores states in f32


def test_dpg_actor_critic():
    actor = DPGActor(action_dim=1, action_low=-2.0, action_high=2.0,
                     hidden=(32, 32))
    critic = DPGCritic(hidden=(32, 32))
    obs = jax.random.normal(jax.random.key(0), (7, 3))
    ap = actor.init(jax.random.key(1), obs)
    a = actor.apply(ap, obs)
    assert a.shape == (7, 1)
    assert (jnp.abs(a) <= 2.0).all()  # bounded by tanh scaling
    cp = critic.init(jax.random.key(2), obs, a)
    q = critic.apply(cp, obs, a)
    assert q.shape == (7,) and q.dtype == jnp.float32


def test_target_updates():
    p = {"w": jnp.ones(3)}
    t = {"w": jnp.zeros(3)}
    assert (hard_update(t, p)["w"] == 1.0).all()
    soft = soft_update(t, p, tau=0.1)
    np.testing.assert_allclose(soft["w"], 0.1)


def test_build_network_factory():
    assert isinstance(
        build_network(NetworkConfig(kind="mlp"), VEC_SPEC), MLPQNet)
    assert isinstance(
        build_network(NetworkConfig(kind="nature_cnn"), ATARI_SPEC),
        NatureDQN)
    lstm = build_network(NetworkConfig(kind="lstm_q"), VEC_SPEC)
    assert isinstance(lstm, ApeXLSTMQNet) and lstm.mlp_torso
    actor, critic = build_network(NetworkConfig(kind="dpg"), CTRL_SPEC)
    assert isinstance(actor, DPGActor) and isinstance(critic, DPGCritic)
    with pytest.raises(ValueError):
        build_network(NetworkConfig(kind="transformer"), VEC_SPEC)
