"""Zero-copy ingest staging (runtime/ingest.py + driver integration):

- partial-tail drop accounting at _flush_stage(force=True) in all three
  denominations (flat units, frame-ring live transitions, r2d2 sequence
  upper bound), on BOTH staging paths (legacy list-append and zero-copy
  stager) — the accounting must survive the staging rewrite exactly
- bitwise ingest parity: the same recorded wire stream lands identical
  replay-bound blocks through decode-into-staging as through the legacy
  decode_batch + concatenate path, for flat + frame-ring + r2d2 — and
  the delta-deflate wire codec must land the same bits as raw through
  both paths (split decodes exercise the delta continuation cache)
- IngestStager unit behavior: boundary splitting, coalesced ships,
  drain compaction, tail exposure
"""

import dataclasses

import jax
import numpy as np
import pytest

from ape_x_dqn_tpu.comm.socket_transport import WireBatch, encode_batch
from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, NetworkConfig,
    ParallelConfig, ReplayConfig, RunConfig, get_config)
from ape_x_dqn_tpu.runtime.driver import ApexDriver
from ape_x_dqn_tpu.runtime.ingest import IngestStager


def _flat_cfg(**replay_kw):
    return get_config("cartpole_smoke").replace(
        replay=ReplayConfig(kind="prioritized", capacity=2048, min_fill=64,
                            **replay_kw),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        actors=ActorConfig(num_actors=1, base_eps=0.5, ingest_batch=16),
        inference=InferenceConfig(max_batch=4, deadline_ms=0.5),
        eval_every_steps=0, eval_episodes=0,
    )


def _ring_cfg(**replay_kw):
    return RunConfig(
        name="catch",
        env=EnvConfig(id="catch", kind="synthetic_atari", frame_skip=4,
                      max_noop_start=4),
        network=NetworkConfig(kind="nature_cnn", dueling=True),
        replay=ReplayConfig(kind="prioritized", capacity=4096, min_fill=128,
                            storage="frame_ring", seg_transitions=8,
                            segs_per_add=2, **replay_kw),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        actors=ActorConfig(num_actors=1, base_eps=0.5, ingest_batch=8),
        inference=InferenceConfig(max_batch=4, deadline_ms=0.5),
        eval_every_steps=0, eval_episodes=0,
    )


def _r2d2_cfg(**replay_kw):
    return get_config("r2d2").replace(
        env=EnvConfig(id="CartPolePO", kind="cartpole_po"),
        network=NetworkConfig(kind="lstm_q", lstm_size=32, torso_dense=64,
                              dueling=True, compute_dtype="float32"),
        replay=ReplayConfig(kind="sequence", capacity=512, seq_length=16,
                            seq_overlap=8, burn_in=4, min_fill=32,
                            priority_eta=0.9, **replay_kw),
        learner=LearnerConfig(batch_size=16, n_step=3, value_rescale=True,
                              target_sync_every=100, lr=1e-3,
                              publish_every=25, train_chunk=4),
        actors=ActorConfig(num_actors=1, base_eps=0.4, ingest_batch=64),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        parallel=ParallelConfig(dp=1, tp=1),
        eval_every_steps=0, eval_episodes=0,
    )


def _synth_batch(driver, n, seed=0, frames=None):
    """Item-spec-conforming random batch of n staging units."""
    rng = np.random.default_rng(seed)
    batch = {}
    for k, s in driver._item_spec.items():
        shape = (n,) + tuple(s.shape)
        if np.issubdtype(np.dtype(s.dtype), np.integer):
            batch[k] = rng.integers(0, 3, size=shape).astype(s.dtype)
        else:
            batch[k] = (rng.random(shape) * 4).astype(s.dtype)
    ptail = (driver.cfg.replay.seg_transitions,) if driver._frame_mode \
        else ()
    batch["priorities"] = rng.random((n,) + ptail).astype(np.float32)
    if frames is not None:
        batch["frames"] = frames
    return batch


# -- drop accounting (pins the legacy semantics; the stager must match) ----


@pytest.mark.parametrize("zero_copy", [False, True])
def test_flat_tail_drop_accounting(zero_copy):
    """Flat denomination: 1 unit = 1 env frame; the dropped tail comes
    OFF _frames_total so frames reconcile with replay contents."""
    d = ApexDriver(_flat_cfg(ingest_zero_copy=zero_copy))
    assert (d._stager is not None) == zero_copy
    block = d.dp * d._stage_chunk
    tail = 3
    d._ingest_one(_synth_batch(d, block + tail), block + tail)
    d._flush_stage(force=True)
    assert d._stage_dropped == tail
    assert d._frames_total == block  # ingested minus dropped tail
    assert d._replay_filled == block * d._unit_items


@pytest.mark.parametrize("zero_copy", [False, True])
def test_frame_ring_tail_drop_accounting(zero_copy):
    """Frame-ring denomination: dropped segments count their LIVE
    transitions (next_off > 0); _frames_total stays (env frames ride
    ingest messages separately in frame mode)."""
    d = ApexDriver(_ring_cfg(ingest_zero_copy=zero_copy))
    block = d.dp * d._stage_chunk
    tail = 1
    batch = _synth_batch(d, block + tail, frames=37)
    # make the tail segment's liveness pattern explicit
    batch["next_off"][block:] = 0
    batch["next_off"][block:, :5] = 2  # 5 live transitions in the tail
    d._ingest_one(batch, block + tail)
    d._flush_stage(force=True)
    assert d._stage_dropped == 5
    assert d._frames_total == 37  # untouched by the drop
    assert d._replay_filled == block * d._unit_items


@pytest.mark.parametrize("zero_copy", [False, True])
def test_r2d2_tail_drop_accounting(zero_copy):
    """R2D2 denomination: units are sequences; drops count seq_length
    transitions per sequence (upper bound); _frames_total stays."""
    d = ApexDriver(_r2d2_cfg(ingest_zero_copy=zero_copy))
    block = d.dp * d._stage_chunk
    tail = 2
    d._ingest_one(_synth_batch(d, block + tail, frames=29), block + tail)
    d._flush_stage(force=True)
    assert d._stage_dropped == tail * d.cfg.replay.seq_length
    assert d._frames_total == 29
    assert d._replay_filled == block * d._unit_items


# -- per-shard drop closure under the [dp, chunk] round-robin split --------
# (ISSUE 9 satellite 3): the same three denominations, attributed to
# the shard each tail unit WOULD have landed on (unit i -> shard
# i // stage_chunk), with sum(per_shard) == dropped exactly.


def _dp2(cfg):
    return cfg.replace(parallel=ParallelConfig(dp=2, tp=1))


@pytest.mark.parametrize("zero_copy", [False, True])
def test_flat_per_shard_drop_closure_dp2(zero_copy):
    d = ApexDriver(_dp2(_flat_cfg(ingest_zero_copy=zero_copy)))
    assert d.is_dist and d.dp == 2
    chunk = d._stage_chunk
    block = d.dp * chunk
    tail = chunk + 2  # spans shard 0 fully + 2 units into shard 1
    assert block > tail  # a tail is always shorter than one block
    d._ingest_one(_synth_batch(d, block + tail), block + tail)
    d._flush_stage(force=True)
    assert d._stage_dropped == tail
    assert d._stage_dropped_per_shard.tolist() == [chunk, 2]
    assert int(d._stage_dropped_per_shard.sum()) == d._stage_dropped
    assert d._frames_total == block


@pytest.mark.parametrize("zero_copy", [False, True])
def test_frame_ring_per_shard_drop_closure_dp2(zero_copy):
    """Frame-ring denomination per shard: each dropped tail segment
    contributes its LIVE transition count to the shard it was bound
    for."""
    d = ApexDriver(_dp2(_ring_cfg(ingest_zero_copy=zero_copy)))
    assert d.is_dist and d._frame_mode
    chunk = d._stage_chunk
    block = d.dp * chunk
    tail = chunk + 1
    assert block > tail
    batch = _synth_batch(d, block + tail, frames=11)
    # tail unit j carries exactly j+1 live transitions
    batch["next_off"][block:] = 0
    for j in range(tail):
        batch["next_off"][block + j, :j + 1] = 2
    d._ingest_one(batch, block + tail)
    d._flush_stage(force=True)
    assert d._stage_dropped == sum(j + 1 for j in range(tail))
    assert d._stage_dropped_per_shard.tolist() == [
        sum(j + 1 for j in range(chunk)), chunk + 1]
    assert int(d._stage_dropped_per_shard.sum()) == d._stage_dropped
    assert d._frames_total == 11  # untouched by frame-mode drops


@pytest.mark.parametrize("zero_copy", [False, True])
def test_r2d2_per_shard_drop_closure_dp2(zero_copy):
    d = ApexDriver(_dp2(_r2d2_cfg(ingest_zero_copy=zero_copy)))
    assert d.is_dist and d.family == "r2d2"
    chunk = d._stage_chunk
    block = d.dp * chunk
    tail = chunk + 1
    assert block > tail
    d._ingest_one(_synth_batch(d, block + tail, frames=29), block + tail)
    d._flush_stage(force=True)
    seq = d.cfg.replay.seq_length
    assert d._stage_dropped == tail * seq
    assert d._stage_dropped_per_shard.tolist() == [chunk * seq, seq]
    assert int(d._stage_dropped_per_shard.sum()) == d._stage_dropped
    assert d._frames_total == 29


def test_stager_tail_shard_units_round_robin():
    """IngestStager.tail_shard_units mirrors the [block] -> [dp, chunk]
    C-order reshape: tail unit i belongs to shard i // chunk."""
    st, _ = _unit_stager(block=8, coalesce=2)
    st.put(_rows(8 + 5, 0))
    assert st.drain() == 1  # ships the complete block, compacts 5
    assert st.tail_units() == 5
    assert st.tail_shard_units(2) == [4, 1]  # chunk = 4
    assert st.tail_shard_units(4) == [2, 2, 1, 0]  # chunk = 2
    assert st.tail_shard_units(1) == [5]
    st.discard_tail()
    assert st.tail_shard_units(2) == [0, 0]


def test_drop_accounting_in_run_report():
    """_stage_dropped reaches the run report's ingest_dropped."""
    d = ApexDriver(_flat_cfg(ingest_zero_copy=True))
    block = d.dp * d._stage_chunk
    d._ingest_one(_synth_batch(d, block + 2), block + 2)
    d._flush_stage(force=True)
    assert d._stage_dropped == 2


# -- tiered cold-store denomination (ISSUE 11 satellite 2) -----------------
# With the tier on and the ring full, every ship becomes an eviction
# swap; the pinned closure is evicted == cold_stored + cold_dropped
# (transitions, door outcomes), and recall refills ride the SAME
# staging accounting as fresh ingest (ingest_rows / _replay_filled).


def _cold_ring_cfg(**replay_kw):
    cfg = _ring_cfg()
    kw = dict(capacity=128, min_fill=32, cold_tier_capacity=1024)
    kw.update(replay_kw)
    return cfg.replace(replay=dataclasses.replace(cfg.replay, **kw))


def _fill_ring(d, seed0=0):
    block = d.dp * d._stage_chunk
    for i in range(d.capacity // d._unit_items // block):
        d._ingest_one(_synth_batch(d, block, seed=seed0 + i), block)
    d._stager.drain()
    assert d._replay_filled == d.capacity
    return block


def test_cold_tier_eviction_closure():
    d = ApexDriver(_cold_ring_cfg())
    assert d._cold is not None
    block = _fill_ring(d)
    assert d._cold_evicted == 0  # filling evicts nothing
    for i in range(4):
        d._ingest_one(_synth_batch(d, block, seed=50 + i), block)
    d._stager.drain()
    assert d._cold_evicted > 0
    assert d._cold_evicted == d._cold_stored + d._cold_dropped
    assert d._cold.transitions <= d.cfg.replay.cold_tier_capacity
    # evictions swap slots 1:1 — the hot ring stays exactly full
    assert d._replay_filled == d.capacity


def test_cold_tier_recall_rides_staging_accounting():
    d = ApexDriver(_cold_ring_cfg())
    block = _fill_ring(d)
    for i in range(4):
        d._ingest_one(_synth_batch(d, block, seed=80 + i), block)
    d._stager.drain()
    stored_segs = len(d._cold)
    assert stored_segs > 0
    before = (d._cold_evicted, d._cold_stored + d._cold_dropped)
    assert before[0] == before[1]
    d._cold_refill_tick()   # the ingest loop's idle hook
    d._stager.drain()
    assert d._cold_recalled > 0
    # a recalled block restages through the eviction swap (ring still
    # full), so the closure keeps holding through the churn
    assert d._cold_evicted == d._cold_stored + d._cold_dropped
    assert d._cold_evicted > before[0]
    assert d._replay_filled == d.capacity


def test_cold_off_never_routes_to_eviction_ship():
    """Default path untouched: with the tier off, a full ring keeps
    shipping through the plain add path (blind FIFO)."""
    d = ApexDriver(_cold_ring_cfg(cold_tier_capacity=0))
    assert d._cold is None

    def boom(views, g):  # pragma: no cover - the assertion is the point
        raise AssertionError("cold ship path used with the tier off")

    d._ship_staged_cold = boom
    block = _fill_ring(d)
    for i in range(2):
        d._ingest_one(_synth_batch(d, block, seed=50 + i), block)
    d._stager.drain()
    assert d._replay_filled == d.capacity
    # an idle tick with no cold store is a no-op, not an error
    d._cold_refill_tick()


def test_cold_tier_rejects_legacy_staging():
    with pytest.raises(ValueError, match="ingest_zero_copy"):
        ApexDriver(_cold_ring_cfg(ingest_zero_copy=False))


# -- bitwise ingest parity: zero-copy vs legacy on a recorded stream -------


def _record_stream(cfg_fn, sizes, payloads):
    """Feed the same recorded wire payloads through one driver built
    from cfg_fn, with device shipping stubbed to capture host blocks;
    returns (per-key concatenated rows, dropped, frames_total)."""
    cfg = cfg_fn()
    d = ApexDriver(cfg)
    recorded = []
    if d._stager is not None:
        def ship(views, g):
            recorded.append({k: np.array(v) for k, v in views.items()})
            return []
        d._stager._ship = ship
    else:
        def add_block(take, count):
            recorded.append({k: np.array(v) for k, v in take.items()})
        d._add_block = add_block
    from ape_x_dqn_tpu.comm.socket_transport import decode_batch
    for n, payload in zip(sizes, payloads):
        batch = WireBatch(payload) if d._stager is not None \
            else decode_batch(payload)
        d._ingest_one(batch, n)
    d._flush_stage(force=True)
    keys = d._item_keys + ("priorities",)
    rows = {k: (np.concatenate([r[k] for r in recorded])
                if recorded else None) for k in keys}
    return rows, d._stage_dropped, d._frames_total


@pytest.mark.parametrize("cfg_fn", [_flat_cfg, _ring_cfg, _r2d2_cfg],
                         ids=["flat", "frame_ring", "r2d2"])
def test_ingest_parity_zero_copy_vs_legacy(cfg_fn):
    """The SAME recorded wire stream (ragged batch sizes, so staging
    boundaries are crossed mid-batch) must land bitwise-identical
    replay-bound blocks through both staging paths, with identical
    drop accounting."""
    probe = ApexDriver(cfg_fn())
    sizes = [3, 7, 1, 6, 5, 2]
    payloads = []
    for i, n in enumerate(sizes):
        b = _synth_batch(probe, n, seed=100 + i, frames=n)
        payloads.append(encode_batch(b))
    del probe
    new = _record_stream(lambda: cfg_fn(), sizes, payloads)
    old = _record_stream(
        lambda: cfg_fn().replace(
            replay=dataclasses.replace(cfg_fn().replay,
                                       ingest_zero_copy=False)),
        sizes, payloads)
    assert new[1] == old[1]  # dropped
    assert new[2] == old[2]  # frames_total
    for k in new[0]:
        a, b = new[0][k], old[0][k]
        assert (a is None) == (b is None), k
        if a is not None:
            assert a.dtype == b.dtype, k
            np.testing.assert_array_equal(a, b, err_msg=k)


@pytest.mark.parametrize("cfg_fn", [_flat_cfg, _ring_cfg, _r2d2_cfg],
                         ids=["flat", "frame_ring", "r2d2"])
def test_ingest_parity_codec_vs_raw(cfg_fn):
    """The delta-deflate wire codec must be invisible to replay: the
    SAME recorded stream encoded raw vs codec lands bitwise-identical
    blocks through the zero-copy staging path (split decodes, delta
    continuation across buffer boundaries and all) AND through the
    legacy decode_batch path, in every denomination."""
    probe = ApexDriver(cfg_fn())
    sizes = [3, 7, 1, 6, 5, 2]
    raw_payloads, codec_payloads = [], []
    for i, n in enumerate(sizes):
        b = _synth_batch(probe, n, seed=100 + i, frames=n)
        raw_payloads.append(encode_batch(b, "raw"))
        codec_payloads.append(encode_batch(b, "delta-deflate"))
    del probe
    raw = _record_stream(lambda: cfg_fn(), sizes, raw_payloads)
    codec = _record_stream(lambda: cfg_fn(), sizes, codec_payloads)
    legacy = _record_stream(
        lambda: cfg_fn().replace(
            replay=dataclasses.replace(cfg_fn().replay,
                                       ingest_zero_copy=False)),
        sizes, codec_payloads)
    for other in (codec, legacy):
        assert raw[1] == other[1]  # dropped
        assert raw[2] == other[2]  # frames_total
        for k in raw[0]:
            a, b = raw[0][k], other[0][k]
            assert (a is None) == (b is None), k
            if a is not None:
                assert a.dtype == b.dtype, k
                np.testing.assert_array_equal(a, b, err_msg=k)


# -- IngestStager unit behavior --------------------------------------------


def _unit_stager(block=4, coalesce=2, buffers=2):
    spec = {"x": jax.ShapeDtypeStruct((2,), np.float32),
            "y": jax.ShapeDtypeStruct((), np.int32)}
    shipped = []

    def ship(views, g):
        shipped.append((g, {k: np.array(v) for k, v in views.items()}))
        return []

    return IngestStager(spec, (), block, coalesce, buffers, ship), shipped


def _rows(n, base):
    return {"x": np.arange(n * 2, dtype=np.float32).reshape(n, 2) + base,
            "y": np.arange(n, dtype=np.int32) + base,
            "priorities": np.arange(n, dtype=np.float32) + base}


def test_stager_coalesced_ship_and_boundary_split():
    st, shipped = _unit_stager(block=4, coalesce=2)
    st.put(_rows(3, 0))          # cursor 3
    st.put(_rows(7, 100))        # fills 8 (ship g=2) + 2 into next buffer
    assert len(shipped) == 1
    g, views = shipped[0]
    assert g == 2 and views["x"].shape == (8, 2)
    # the 8 shipped rows are the stream's first 8, in order
    expect = np.concatenate([_rows(3, 0)["x"], _rows(7, 100)["x"][:5]])
    np.testing.assert_array_equal(views["x"], expect)
    assert st.tail_units() == 2
    assert st.occupancy() == pytest.approx(2 / 8)


def test_stager_drain_ships_blocks_and_compacts():
    st, shipped = _unit_stager(block=4, coalesce=2)
    st.put(_rows(6, 0))          # cursor 6: one full block + 2 rem
    assert st.drain() == 1
    assert len(shipped) == 1 and shipped[0][0] == 1
    np.testing.assert_array_equal(shipped[0][1]["x"], _rows(6, 0)["x"][:4])
    # remainder compacted to the buffer front
    assert st.tail_units() == 2
    np.testing.assert_array_equal(st.tail_view("x"), _rows(6, 0)["x"][4:])
    # draining again with no complete block is a no-op
    assert st.drain() == 0
    # the compacted rows still flow into the next coalesced group
    st.put(_rows(6, 50))
    assert len(shipped) == 2 and shipped[1][0] == 2
    expect = np.concatenate([_rows(6, 0)["x"][4:], _rows(6, 50)["x"]])
    np.testing.assert_array_equal(shipped[1][1]["x"], expect)
    assert st.tail_units() == 0


def test_stager_wire_batch_decode_into():
    """WireBatch payloads land via decode_into (the zero-copy path) and
    match what the dict path stages bitwise."""
    st_wire, shipped_wire = _unit_stager(block=4, coalesce=1)
    st_dict, shipped_dict = _unit_stager(block=4, coalesce=1)
    for i, n in enumerate([3, 5, 4]):
        rows = _rows(n, 10 * i)
        st_wire.put(WireBatch(encode_batch(rows)))
        st_dict.put(rows)
    assert len(shipped_wire) == len(shipped_dict) == 3
    for (gw, vw), (gd, vd) in zip(shipped_wire, shipped_dict):
        assert gw == gd
        for k in vw:
            np.testing.assert_array_equal(vw[k], vd[k], err_msg=k)


def test_stager_discard_tail():
    st, shipped = _unit_stager(block=4, coalesce=2)
    st.put(_rows(3, 0))
    assert st.tail_units() == 3
    st.discard_tail()
    assert st.tail_units() == 0 and shipped == []


# -- per-shard cold-door closure + the disk rung (PR 16) -------------------
# The dist eviction swap runs per dp shard, so the closure holds PER
# SHARD: evicted[d] == stored[d] + dropped[d], sums matching the
# scalar counters exactly. The disk rung hangs off the RAM door and
# never perturbs that closure (spills/promotions are side traffic).


def test_cold_tier_dp2_per_shard_closure():
    d = ApexDriver(_dp2(_cold_ring_cfg()))
    assert d.is_dist and d.dp == 2 and d._cold is not None
    block = _fill_ring(d)
    for i in range(4):
        d._ingest_one(_synth_batch(d, block, seed=60 + i), block)
    d._stager.drain()
    assert d._cold_evicted > 0
    per_ev = d._cold_evicted_per_shard
    assert per_ev.shape == (2,) and (per_ev > 0).all()
    np.testing.assert_array_equal(
        per_ev, d._cold_stored_per_shard + d._cold_dropped_per_shard)
    assert int(per_ev.sum()) == d._cold_evicted
    assert int(d._cold_stored_per_shard.sum()) == d._cold_stored
    assert int(d._cold_dropped_per_shard.sum()) == d._cold_dropped
    assert d._cold_evicted == d._cold_stored + d._cold_dropped
    assert d._replay_filled == d.capacity
    # per-shard ring sizes stay full through the swap churn
    sizes = np.asarray(d.state.replay.size)
    assert sizes.shape == (2,)
    assert (sizes == d.capacity // d.dp).all()


def test_cold_tier_dp2_recall_keeps_per_shard_closure():
    d = ApexDriver(_dp2(_cold_ring_cfg()))
    block = _fill_ring(d)
    for i in range(4):
        d._ingest_one(_synth_batch(d, block, seed=70 + i), block)
    d._stager.drain()
    assert len(d._cold) > 0
    d._cold_refill_tick()
    d._stager.drain()
    assert d._cold_recalled > 0
    np.testing.assert_array_equal(
        d._cold_evicted_per_shard,
        d._cold_stored_per_shard + d._cold_dropped_per_shard)
    assert int(d._cold_evicted_per_shard.sum()) == d._cold_evicted


def _disk_cfg(tmp_path, **replay_kw):
    kw = dict(cold_tier_capacity=32,  # ~3 eviction blocks' worth of
              # live transitions: later puts displace or drop -> spills
              cold_tier_disk_capacity=1 << 16,
              cold_tier_disk_dir=str(tmp_path / "spill"))
    kw.update(replay_kw)
    return _cold_ring_cfg(**kw)


def test_cold_disk_captures_door_losers(tmp_path):
    d = ApexDriver(_disk_cfg(tmp_path))
    assert d._disk is not None
    block = _fill_ring(d)
    for i in range(8):
        d._ingest_one(_synth_batch(d, block, seed=90 + i), block)
    d._stager.drain()
    d._disk.drain(timeout=10.0)
    s = d._disk.stats()
    assert d._cold.spilled > 0
    assert s["spilled"] == d._cold.spilled  # queue never refused here
    assert s["transitions"] > 0 and s["io_errors"] == 0
    # the eviction closure is untouched by spill traffic
    assert d._cold_evicted == d._cold_stored + d._cold_dropped
    assert d._cold.transitions <= d.cfg.replay.cold_tier_capacity
    d._disk.close()


def test_cold_disk_refill_tick_promotes(tmp_path):
    d = ApexDriver(_disk_cfg(tmp_path))
    block = _fill_ring(d)
    for i in range(8):
        d._ingest_one(_synth_batch(d, block, seed=110 + i), block)
    d._stager.drain()
    d._disk.drain(timeout=10.0)
    assert d._disk.stats()["segments"] > 0
    # the idle tick recalls RAM segments first (making door room), then
    # promotes the heaviest disk segment back through put_segment
    d._cold_refill_tick()
    d._stager.drain()
    assert d._disk.stats()["promoted"] >= 1
    assert d._cold_evicted == d._cold_stored + d._cold_dropped
    d._disk.close()


def test_cold_disk_dp2_per_shard_closure(tmp_path):
    d = ApexDriver(_dp2(_disk_cfg(tmp_path)))
    assert d.is_dist and d._disk is not None
    block = _fill_ring(d)
    for i in range(6):
        d._ingest_one(_synth_batch(d, block, seed=130 + i), block)
    d._stager.drain()
    d._disk.drain(timeout=10.0)
    assert d._cold.spilled > 0
    np.testing.assert_array_equal(
        d._cold_evicted_per_shard,
        d._cold_stored_per_shard + d._cold_dropped_per_shard)
    assert int(d._cold_evicted_per_shard.sum()) == d._cold_evicted
    d._disk.close()


def test_cold_disk_stats_reach_run_report_shape(tmp_path):
    """The disk block in the driver's run() output mirrors
    DiskStore.stats() — pin the keys the bench and obs read."""
    d = ApexDriver(_disk_cfg(tmp_path))
    s = d._disk.stats()
    assert set(s) >= {"segments", "transitions", "bytes", "files",
                      "spilled", "promoted", "dropped", "queue_full",
                      "io_errors", "corrupt_segments", "compactions"}
    d._disk.close()
