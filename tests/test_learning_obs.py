"""Learning-health plane (ISSUE 10, obs/learning.py): in-graph
diagnostics on all four learner cycles, per-tenant gauge publication
through a real catch run, the dp-sharded per-shard closure, and the
warn-only LearnMonitor anomaly engine."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    EnvConfig, LearnerConfig, NetworkConfig, ObsConfig, ReplayConfig,
    get_config)
from ape_x_dqn_tpu.envs.base import EnvSpec
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.obs.core import NULL_OBS, build_obs
from ape_x_dqn_tpu.obs.learning import LearnMonitor
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.runtime.learner import (
    DQNLearner, transition_item_spec)
from ape_x_dqn_tpu.utils.metrics import Metrics
from ape_x_dqn_tpu.utils.rng import component_key

VEC_SPEC = EnvSpec(obs_shape=(4,), obs_dtype=np.dtype(np.float32),
                   discrete=True, num_actions=2)

# every key sgd_diag + replay_health put on the single-chip diag pytree
DIAG_KEYS = {
    "td_abs_p50", "td_abs_p90", "td_abs_p99", "td_signed_mean",
    "q_mean", "q_max", "target_q_mean", "q_gap", "grad_norm",
    "update_ratio", "is_ess_frac", "sample_age_p50", "sample_age_p90",
    "prio_staleness_frac", "priority_top_frac",
}


def _flat_items(rng, n):
    return {
        "obs": jnp.asarray(rng.standard_normal((n, 4)), jnp.float32),
        "action": jnp.asarray(rng.integers(0, 2, n), jnp.int32),
        "reward": jnp.asarray(rng.standard_normal(n), jnp.float32),
        "next_obs": jnp.asarray(rng.standard_normal((n, 4)),
                                jnp.float32),
        "discount": jnp.full((n,), 0.97, jnp.float32),
    }


def _assert_diag(diag, extra=()):
    assert set(DIAG_KEYS) | set(extra) == set(diag), sorted(diag)
    for k, v in diag.items():
        v = float(v)
        assert np.isfinite(v), (k, v)
    assert 0.0 < float(diag["is_ess_frac"]) <= 1.0 + 1e-6
    assert float(diag["td_abs_p50"]) <= float(diag["td_abs_p90"]) \
        <= float(diag["td_abs_p99"])
    assert 0.0 <= float(diag["priority_top_frac"]) <= 1.0 + 1e-6


# -- in-graph diagnostics on each learner cycle ---------------------------

def test_dqn_learner_diag_finite():
    net = build_network(NetworkConfig(kind="mlp", mlp_hidden=(32,)),
                        VEC_SPEC)
    params = net.init(component_key(3, "net"),
                      np.zeros((1, 4), np.float32))
    learner = DQNLearner(net.apply, PrioritizedReplay(capacity=512),
                         LearnerConfig(batch_size=32))
    state = learner.init(
        params, learner.replay.init(
            transition_item_spec(VEC_SPEC.obs_shape,
                                 VEC_SPEC.obs_dtype)),
        component_key(3, "learner"))
    rng = np.random.default_rng(7)
    state = learner.add(state, _flat_items(rng, 256), jnp.ones(256))
    state, m = learner.train_step(state)
    assert "diag" in m
    _assert_diag(m["diag"])
    # fused path: draw and write-back see the same tree
    assert float(m["diag"]["prio_staleness_frac"]) == 0.0
    # the diag pytree rides the train_many scan (last-step fold)
    state, m = learner.train_many(state, 3)
    _assert_diag(m["diag"])


def test_sequence_learner_diag_finite():
    from ape_x_dqn_tpu.models import ApeXLSTMQNet
    from ape_x_dqn_tpu.replay.sequence import sequence_item_spec
    from ape_x_dqn_tpu.runtime.sequence_learner import SequenceLearner

    net = ApeXLSTMQNet(num_actions=2, lstm_size=8, dense=16,
                       compute_dtype="float32", mlp_torso=True)
    z = jnp.zeros((1, 8), jnp.float32)
    params = net.init(jax.random.key(0),
                      jnp.zeros((1, 4, 2), jnp.float32), (z, z))
    replay = PrioritizedReplay(capacity=64)
    spec = sequence_item_spec((2,), np.float32, 4, 8)
    lcfg = LearnerConfig(batch_size=8, n_step=2, value_rescale=True,
                         target_sync_every=10, lr=1e-3)
    rcfg = ReplayConfig(seq_length=4, burn_in=1)
    learner = SequenceLearner(lambda p, o, s: net.apply(p, o, s),
                              replay, lcfg, rcfg)
    state = learner.init(params, replay.init(spec), jax.random.key(1))
    rng = np.random.default_rng(0)
    items = {
        "obs": jnp.asarray(rng.normal(size=(16, 4, 2)), jnp.float32),
        "actions": jnp.asarray(rng.integers(0, 2, (16, 4)), jnp.int32),
        "rewards": jnp.asarray(rng.normal(size=(16, 4)), jnp.float32),
        "terminals": jnp.zeros((16, 4), jnp.float32),
        "mask": jnp.ones((16, 4), jnp.float32),
        "init_c": jnp.zeros((16, 8), jnp.float32),
        "init_h": jnp.zeros((16, 8), jnp.float32),
    }
    state = learner.add(state, items, jnp.ones(16))
    state, m = learner.train_step(state)
    _assert_diag(m["diag"])


def test_dpg_learner_diag_finite():
    from ape_x_dqn_tpu.models import DPGActor, DPGCritic
    from ape_x_dqn_tpu.runtime.dpg_learner import (
        DPGLearner, continuous_item_spec)

    actor = DPGActor(action_dim=1, action_low=-2, action_high=2,
                     hidden=(16, 16))
    critic = DPGCritic(hidden=(16, 16))
    obs0 = jnp.zeros((1, 3), jnp.float32)
    a0 = jnp.zeros((1, 1), jnp.float32)
    actor_params = actor.init(jax.random.key(0), obs0)
    critic_params = critic.init(jax.random.key(1), obs0, a0)
    replay = PrioritizedReplay(capacity=256)
    spec = continuous_item_spec((3,), np.float32, 1)
    lcfg = LearnerConfig(batch_size=32, n_step=5, critic_lr=1e-3,
                         policy_lr=1e-4, tau=0.05)
    learner = DPGLearner(actor.apply, critic.apply, replay, lcfg)
    state = learner.init(actor_params, critic_params, replay.init(spec),
                         jax.random.key(2))
    rng = np.random.default_rng(0)
    items = {
        "obs": jnp.asarray(rng.normal(size=(64, 3)), jnp.float32),
        "action": jnp.asarray(rng.uniform(-2, 2, (64, 1)), jnp.float32),
        "reward": jnp.asarray(rng.normal(size=(64,)), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(64, 3)), jnp.float32),
        "discount": jnp.full((64,), 0.95, jnp.float32),
    }
    state = learner.add(state, items, jnp.ones(64))
    state, m = learner.train_step(state)
    _assert_diag(m["diag"])


def test_dist_learner_diag_shard_closure():
    """dp=2 dist learner: diag scalars are finite and the per-shard
    mean-|TD| envelope closes over the global mean (the min/max are the
    psum'd extremes of exactly the per-shard means the global averages,
    so min <= global <= max is an identity, not a tolerance)."""
    from ape_x_dqn_tpu.parallel.dist_learner import DistDQNLearner
    from ape_x_dqn_tpu.parallel.mesh import make_mesh

    dp = 2
    mesh = make_mesh(dp=dp, tp=1)
    net = build_network(
        NetworkConfig(kind="mlp", mlp_hidden=(64,), dueling=False,
                      compute_dtype="float32"), VEC_SPEC)
    params = net.init(jax.random.key(0), jnp.zeros((1, 4)))
    learner = DistDQNLearner(
        net.apply, PrioritizedReplay(capacity=64, alpha=0.6, beta=0.4),
        LearnerConfig(batch_size=32, target_sync_every=10), mesh)
    state = learner.init(params,
                         transition_item_spec((4,), jnp.float32),
                         jax.random.key(1))
    rng = np.random.default_rng(0)
    n = 16
    items = {
        "obs": jnp.asarray(rng.normal(size=(dp, n, 4)), jnp.float32),
        "action": jnp.asarray(rng.integers(0, 2, (dp, n)), jnp.int32),
        "reward": jnp.asarray(rng.normal(size=(dp, n)), jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(dp, n, 4)),
                                jnp.float32),
        "discount": jnp.full((dp, n), 0.99, jnp.float32),
    }
    state = learner.add(state, items, jnp.ones((dp, n)))
    state, m = learner.train_step(state)
    diag = m["diag"]
    _assert_diag(diag, extra=("shard_td_mean_min", "shard_td_mean_max"))
    lo, hi = float(diag["shard_td_mean_min"]), \
        float(diag["shard_td_mean_max"])
    g = float(m["td_abs_mean"])
    assert lo <= g + 1e-6 and g <= hi + 1e-6, (lo, g, hi)


# -- end-to-end: catch run publishes the plane ----------------------------

def test_single_process_catch_publishes_learn_gauges(tmp_path):
    """Tier-1 acceptance (ISSUE 10): a short catch run with obs ON
    publishes finite, in-healthy-range learn_* gauges plus the
    tenant-prefixed duplicates, and a clean learner fires zero
    degradation events."""
    from ape_x_dqn_tpu.obs.report import summarize
    from ape_x_dqn_tpu.runtime.single_process import train_single_process

    jsonl = str(tmp_path / "run.jsonl")
    cfg = get_config("pong").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari"),
        network=NetworkConfig(kind="nature_cnn", dueling=True,
                              compute_dtype="float32"),
        replay=ReplayConfig(kind="prioritized", capacity=2048,
                            min_fill=300),
        learner=LearnerConfig(batch_size=16, n_step=3,
                              target_sync_every=16, sample_chunk=2),
        obs=ObsConfig(enabled=True, publish_every_steps=50,
                      heartbeat_timeout_s=120.0),
    )
    metrics = Metrics(log_path=jsonl)
    out = train_single_process(cfg, total_env_frames=420,
                               metrics=metrics, train_every=2)
    metrics.close()
    assert out["grad_steps"] > 0
    recs = [json.loads(ln) for ln in open(jsonl)]
    gauges = {}
    for r in recs:
        gauges.update({k: v for k, v in r.items()
                       if k.startswith("gauge/learn")})
    for key in DIAG_KEYS:
        v = gauges.get(f"gauge/learn_{key}")
        assert v is not None, f"learn_{key} never published"
        assert np.isfinite(v), (key, v)
        # tenant duplicate under the env-family prefix
        assert gauges.get(f"gauge/learn/catch/{key}") == v, key
    # a healthy catch learner sits inside every monitor bound
    assert abs(gauges["gauge/learn_q_max"]) < 1e3
    assert gauges["gauge/learn_is_ess_frac"] > 0.05
    assert gauges["gauge/learn_update_ratio"] > 1e-9
    assert gauges["gauge/learn_priority_top_frac"] < 0.5
    assert not any("learning_degradation" in r for r in recs)
    # the report regroups the tenant keys and collects no events
    summary = summarize(recs)
    assert "catch" in summary["tenants"]
    assert summary["tenants"]["catch"]["q_mean"] == \
        gauges["gauge/learn_q_mean"]
    assert summary["learn_events"] == []


# -- the anomaly engine ---------------------------------------------------

class _FakeObs:
    def __init__(self):
        self.counts = []

    def count(self, name, n=1):
        self.counts.append(name)


class _FakeMetrics:
    def __init__(self):
        self.records = []

    def log(self, step, **kw):
        self.records.append({"step": step, **kw})


def test_learn_monitor_loss_spike_once_per_cooldown():
    obs, metrics = _FakeObs(), _FakeMetrics()
    mon = LearnMonitor(obs, metrics, spike_mult=10.0, alpha=0.2,
                       min_samples=3, cooldown_s=3600.0)
    for _ in range(3):
        mon.observe({}, 1.0, step=1, tenant="pong")
    assert metrics.records == []  # baseline warm-up never fires
    # injected spike: two consecutive spikes, one cooldown window ->
    # exactly one attributed event + one counter bump
    mon.observe({}, 100.0, step=2, tenant="pong")
    mon.observe({}, 100.0, step=3, tenant="pong")
    assert obs.counts == ["learning_degradations"]
    assert len(metrics.records) == 1
    ev = metrics.records[0]
    assert ev["learning_degradation"] == "loss_spike"
    assert ev["learn_tenant"] == "pong"
    assert ev["learn_value"] == pytest.approx(100.0)
    assert 0.0 < ev["learn_baseline"] < 10.0


def test_learn_monitor_q_blowup_attributed():
    obs, metrics = _FakeObs(), _FakeMetrics()
    mon = LearnMonitor(obs, metrics, cooldown_s=3600.0)
    mon.observe({"q_max": 5e3, "is_ess_frac": 0.9,
                 "update_ratio": 1e-3, "priority_top_frac": 0.01},
                0.5, step=7, tenant="breakout")
    assert len(metrics.records) == 1
    ev = metrics.records[0]
    assert ev["learning_degradation"] == "q_blowup"
    assert ev["learn_tenant"] == "breakout"
    assert ev["step"] == 7
    # cooldowns are per (tenant, rule): another tenant still fires
    mon.observe({"q_max": -5e3}, 0.5, step=8, tenant="pong")
    assert [r["learn_tenant"] for r in metrics.records] == \
        ["breakout", "pong"]


def test_learn_monitor_absolute_rules():
    obs, metrics = _FakeObs(), _FakeMetrics()
    mon = LearnMonitor(obs, metrics, cooldown_s=3600.0)
    mon.observe({"is_ess_frac": 0.01}, 0.5, tenant="a")
    mon.observe({"update_ratio": 0.0}, 0.5, tenant="b")
    mon.observe({"priority_top_frac": 0.9}, 0.5, tenant="c")
    rules = [r["learning_degradation"] for r in metrics.records]
    assert rules == ["ess_collapse", "dead_gradients",
                     "priority_collapse"]
    # NaN diagnostics never fire (and never poison the EWMA)
    mon.observe({"q_max": float("nan")}, float("nan"), tenant="d")
    assert len(metrics.records) == 3


# -- disabled obs emits nothing -------------------------------------------

def test_disabled_obs_learn_health_is_noop(tmp_path):
    jsonl = str(tmp_path / "off.jsonl")
    metrics = Metrics(log_path=jsonl)
    obs = build_obs(ObsConfig(enabled=False), metrics)
    assert obs is NULL_OBS
    assert obs.learn is None
    obs.learn_health({"q_max": 5e3}, 100.0, step=1, tenant="pong")
    metrics.close()
    recs = [json.loads(ln) for ln in open(jsonl)]
    assert not any(k.startswith(("gauge/learn", "hist/learn", "ctr/"))
                   for r in recs for k in r)


def test_obs_learn_health_toggle_off(tmp_path):
    """ObsConfig(learn_health=False): the gauges still publish (they
    are cheap host reads) but no monitor exists, so injected anomalies
    produce no degradation events."""
    jsonl = str(tmp_path / "toggle.jsonl")
    metrics = Metrics(log_path=jsonl)
    obs = build_obs(ObsConfig(enabled=True, learn_health=False,
                              heartbeat_timeout_s=0.0), metrics)
    assert obs.learn is None
    obs.learn_health({"q_max": 5e3}, 100.0, step=1, tenant="pong")
    obs.publish(1)
    obs.close(1)
    metrics.close()
    recs = [json.loads(ln) for ln in open(jsonl)]
    assert any("gauge/learn_q_max" in r for r in recs)
    assert not any("learning_degradation" in r for r in recs)
