"""Tile-exact pixel packing + in-place ring-write semantics
(replay/packing.py) and the HBM budget check (utils/hbm.py).

These encode the round-5 HBM findings: on TPU a [cap, H, W] u8 buffer
pads 1.6x under the (32, 128) tile and XLA inserts a full-buffer
relayout copy in every gather/scatter program over it (measured 25.1GB
for the pong preset's 9.47GB ring — OOM), while packed byte rows +
dynamic_update_slice ring writes compile to temp=0 in-place graphs.
CPU tests can't see layouts, so they pin the SEMANTICS (roundtrips,
skip-to-head wrap, budget math); the compiled-memory numbers live in
PERF.md "HBM budget".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import get_config
from ape_x_dqn_tpu.replay.packing import (PixelPacker, pad128, packable,
                                          ring_write_start)
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.utils import hbm


# ---------------------------------------------------------------------------
# PixelPacker


def test_pad128():
    assert pad128(7056) == 7168
    assert pad128(128) == 128
    assert pad128(1) == 128


def test_packable_selects_large_u8_leaves_only():
    sds = jax.ShapeDtypeStruct
    assert packable(sds((84, 84, 4), jnp.uint8))
    assert packable(sds((22, 84, 84), jnp.uint8))
    assert not packable(sds((4,), jnp.float32))       # small f32 vector
    assert not packable(sds((84, 84), jnp.float32))   # not u8
    assert not packable(sds((8, 8), jnp.uint8))       # too small to matter


def test_packer_roundtrip_preserves_pixels():
    spec = {
        "obs": jax.ShapeDtypeStruct((84, 84, 4), jnp.uint8),
        "action": jax.ShapeDtypeStruct((), jnp.int32),
    }
    packer = PixelPacker(spec)
    assert packer.packs_anything
    stored = packer.storage_spec(spec)
    assert stored["obs"].shape == (pad128(84 * 84 * 4),)
    assert stored["obs"].dtype == jnp.uint8
    assert stored["action"].shape == ()  # untouched

    rng = np.random.default_rng(0)
    items = {
        "obs": jnp.asarray(rng.integers(0, 255, (5, 84, 84, 4)), jnp.uint8),
        "action": jnp.asarray(rng.integers(0, 4, 5), jnp.int32),
    }
    rows = packer.encode(items)
    assert rows["obs"].shape == (5, pad128(84 * 84 * 4))
    back = packer.decode(rows)
    np.testing.assert_array_equal(np.asarray(back["obs"]),
                                  np.asarray(items["obs"]))
    np.testing.assert_array_equal(np.asarray(back["action"]),
                                  np.asarray(items["action"]))


# ---------------------------------------------------------------------------
# skip-to-head ring writes


def test_ring_write_start_no_wrap_is_identity():
    for pos in (0, 4, 12):
        assert int(ring_write_start(jnp.int32(pos), 4, 16)) == pos


def test_ring_write_start_wrap_skips_to_head():
    assert int(ring_write_start(jnp.int32(14), 4, 16)) == 0
    assert int(ring_write_start(jnp.int32(15), 2, 16)) == 0


def _items(b, base):
    return {
        "x": jnp.arange(base, base + b, dtype=jnp.float32),
    }


def test_replay_skip_to_head_keeps_tree_storage_consistent():
    """A wrapping add writes at slot 0; every tree leaf must keep
    pointing at the item actually stored in its slot (the consistency
    the modular ring guaranteed)."""
    replay = PrioritizedReplay(capacity=8)
    state = replay.init({"x": jax.ShapeDtypeStruct((), jnp.float32)})
    # two adds of 3: pos 0 -> 3 -> 6; third add of 3 would wrap -> head
    for k in range(3):
        state = replay.add(state, _items(3, 10 * k),
                           jnp.full(3, float(k + 1)))
    assert int(state.pos) == 3  # skip-to-head: restarted at 0, +3
    stored = np.asarray(state.storage["x"])
    # adds land at 0, 3, then (skip) 0 again: slots 0..2 hold the third
    # add (overwrote the first), 3..5 the second, 6..7 never written
    np.testing.assert_array_equal(stored[0:3], [20.0, 21.0, 22.0])
    np.testing.assert_array_equal(stored[3:6], [10.0, 11.0, 12.0])
    from ape_x_dqn_tpu.ops import sum_tree
    leaves = np.asarray(sum_tree.leaves(state.tree))
    eps, alpha = replay.eps, replay.alpha
    np.testing.assert_allclose(leaves[0:3], (3.0 + eps) ** alpha, rtol=1e-5)
    np.testing.assert_allclose(leaves[3:6], (2.0 + eps) ** alpha, rtol=1e-5)
    # the skipped tail slots stay empty AND unsampleable (priority 0),
    # and size does NOT count them as filled (never-written slots would
    # otherwise be sampleable in uniform replay and inflate IS-weight N)
    np.testing.assert_array_equal(leaves[6:8], 0.0)
    assert int(state.size) == 6


def test_replay_block_dividing_capacity_matches_modular_ring():
    """When the block divides the capacity (every fixed-block staging),
    skip-to-head never fires and eviction is plain FIFO."""
    replay = PrioritizedReplay(capacity=8)
    state = replay.init({"x": jax.ShapeDtypeStruct((), jnp.float32)})
    for k in range(3):  # 12 items through an 8-ring in blocks of 4
        state = replay.add(state, _items(4, 10 * k),
                           jnp.ones(4))
    stored = np.asarray(state.storage["x"])
    np.testing.assert_array_equal(stored[0:4], [20.0, 21.0, 22.0, 23.0])
    np.testing.assert_array_equal(stored[4:8], [10.0, 11.0, 12.0, 13.0])
    assert int(state.pos) == 4 and int(state.size) == 8


def test_prioritized_replay_packs_pixel_items_transparently():
    """Pixel items round-trip through packed byte-row storage."""
    replay = PrioritizedReplay(capacity=16)
    spec = {
        "obs": jax.ShapeDtypeStruct((32, 32, 4), jnp.uint8),
        "action": jax.ShapeDtypeStruct((), jnp.int32),
    }
    state = replay.init(spec)
    assert state.storage["obs"].shape == (16, pad128(32 * 32 * 4))
    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.integers(0, 255, (4, 32, 32, 4)), jnp.uint8)
    items = {"obs": obs, "action": jnp.arange(4, dtype=jnp.int32)}
    state = replay.add(state, items, jnp.ones(4))
    got, idx, w = replay.sample(state, jax.random.key(0), 8)
    assert got["obs"].shape == (8, 32, 32, 4)
    # every sampled obs equals the stored item at its index
    for i, src in enumerate(np.asarray(idx)):
        np.testing.assert_array_equal(np.asarray(got["obs"][i]),
                                      np.asarray(obs[src]))


# ---------------------------------------------------------------------------
# HBM budget


def test_budget_pong_preset_fits_16g_chip():
    cfg = get_config("pong")
    b = hbm.run_budget(cfg, (84, 84, 4), np.uint8, param_count=1_700_000)
    # 2^20 transitions as byte-row frame ring: (2^20/16)*22 rows * 7168B
    assert b.capacity == 1 << 20
    frames = (1 << 20) // 16 * 22 * 7168
    assert b.replay_storage == frames + (1 << 20) * 16
    assert b.total < 15.75 * 1024 ** 3  # fits the v5e chip
    assert "TOTAL" in b.table()


def test_budget_r2d2_preset_fits_per_shard():
    cfg = get_config("r2d2")
    b = hbm.run_budget(cfg, (84, 84, 4), np.uint8, param_count=6_500_000)
    assert b.capacity == 16_384  # 65536 sequences over dp=4
    assert b.total < 15.75 * 1024 ** 3


def test_budget_atari57_preset_fits_per_shard():
    cfg = get_config("atari57_apex")
    b = hbm.run_budget(cfg, (84, 84, 4), np.uint8, param_count=1_700_000)
    assert b.capacity == 1 << 19  # 2M over dp=4
    assert b.total < 15.75 * 1024 ** 3


def test_check_hbm_fits_raises_loudly_when_oversized():
    cfg = get_config("pong")
    with pytest.raises(ValueError, match="GiB per device"):
        hbm.check_hbm_fits(cfg, (84, 84, 4), np.uint8,
                           hbm_bytes=4 * 1024 ** 3)  # pretend a 4GiB chip


def test_check_hbm_fits_silent_without_memory_stats():
    cfg = get_config("pong")
    # no hbm_bytes and a backend without memory stats -> returns budget
    b = hbm.check_hbm_fits(cfg, (84, 84, 4), np.uint8, hbm_bytes=None)
    assert b.total > 0


def test_frame_mode_predicate_shared():
    """sequence_frame_mode and frame_ring_mode are the SAME function
    object (packing.frame_mode) — the two modules alias one predicate,
    so single-frame-storage eligibility can never drift between the
    sequence and flat frame-ring paths."""
    from ape_x_dqn_tpu.replay.frame_ring import frame_ring_mode
    from ape_x_dqn_tpu.replay.packing import frame_mode
    from ape_x_dqn_tpu.replay.sequence import sequence_frame_mode

    assert sequence_frame_mode is frame_mode
    assert frame_ring_mode is frame_mode
    assert frame_mode("frame_ring", (84, 84, 4))
    assert not frame_mode("flat", (84, 84, 4))
    assert not frame_mode("frame_ring", (4,))


def test_replay_non_dividing_block_retires_tail_slots():
    """The default ActorConfig.ingest_batch=50 does not divide a
    power-of-two capacity, so skip-to-head wrap DOES fire on the flat
    ingest path (the docstring's 'never occurs' only covers the
    frame-ring/segment paths): up to block-1 tail slots are permanently
    retired — priority 0, never sampled, never counted in size — a
    bounded capacity loss, not a correctness hazard."""
    cap, block = 64, 50
    replay = PrioritizedReplay(capacity=cap)
    state = replay.init({"x": jax.ShapeDtypeStruct((), jnp.float32)})
    state = replay.add(state, _items(block, 0), jnp.ones(block))
    assert int(state.pos) == 50 and int(state.size) == 50
    # second block wraps: skip-to-head restarts at 0
    state = replay.add(state, _items(block, 100), jnp.ones(block))
    assert int(state.pos) == 50
    # tail slots 50..63 were retired, never filled: size stays 50
    assert int(state.size) == 50
    from ape_x_dqn_tpu.ops import sum_tree
    leaves = np.asarray(sum_tree.leaves(state.tree))
    np.testing.assert_array_equal(leaves[50:64], 0.0)
    # and retired slots are never sampled even over many draws
    _, idx, _ = replay.sample(state, jax.random.key(0), 512)
    assert np.asarray(idx).max() < 50
    # steady state: every further block lands at 0..49
    state = replay.add(state, _items(block, 200), jnp.ones(block))
    assert int(state.pos) == 50 and int(state.size) == 50
    stored = np.asarray(state.storage["x"])
    np.testing.assert_array_equal(stored[:50], np.arange(200, 250))
