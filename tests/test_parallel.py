"""Distributed learner on the virtual 8-device CPU mesh (SURVEY.md §4
"distributed-without-a-cluster")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import LearnerConfig, NetworkConfig
from ape_x_dqn_tpu.envs.base import EnvSpec
from ape_x_dqn_tpu.models import build_network
from ape_x_dqn_tpu.parallel.dist_learner import DistDQNLearner
from ape_x_dqn_tpu.parallel.mesh import make_mesh
from ape_x_dqn_tpu.parallel.sharding import make_param_shardings
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.runtime.learner import transition_item_spec

VEC_SPEC = EnvSpec(obs_shape=(4,), obs_dtype=np.dtype(np.float32),
                   discrete=True, num_actions=2)


def _make_dist(dp=4, tp=2, batch=32):
    mesh = make_mesh(dp=dp, tp=tp)
    net = build_network(
        NetworkConfig(kind="mlp", mlp_hidden=(256,), dueling=False,
                      compute_dtype="float32"), VEC_SPEC)
    params = net.init(jax.random.key(0), jnp.zeros((1, 4)))
    lcfg = LearnerConfig(batch_size=batch, target_sync_every=10)
    replay = PrioritizedReplay(capacity=64, alpha=0.6, beta=0.4)
    learner = DistDQNLearner(net.apply, replay, lcfg, mesh)
    spec = transition_item_spec((4,), jnp.float32)
    state = learner.init(params, spec, jax.random.key(1))
    return mesh, learner, state


def _ingest(learner, state, dp, n_per_shard, seed=0):
    rng = np.random.default_rng(seed)
    items = {
        "obs": jnp.asarray(rng.normal(size=(dp, n_per_shard, 4)),
                           jnp.float32),
        "action": jnp.asarray(rng.integers(0, 2, (dp, n_per_shard)),
                              jnp.int32),
        "reward": jnp.asarray(rng.normal(size=(dp, n_per_shard)),
                              jnp.float32),
        "next_obs": jnp.asarray(rng.normal(size=(dp, n_per_shard, 4)),
                                jnp.float32),
        "discount": jnp.full((dp, n_per_shard), 0.99, jnp.float32),
    }
    return learner.add(state, items, jnp.ones((dp, n_per_shard)))


def test_mesh_construction():
    mesh = make_mesh(dp=4, tp=2)
    assert mesh.shape == {"dp": 4, "tp": 2}
    with pytest.raises(AssertionError):
        make_mesh(dp=3, tp=3)


def test_param_shardings_tp():
    mesh = make_mesh(dp=4, tp=2)
    net = build_network(
        NetworkConfig(kind="mlp", mlp_hidden=(256,), dueling=False,
                      compute_dtype="float32"), VEC_SPEC)
    params = net.init(jax.random.key(0), jnp.zeros((1, 4)))
    sh = make_param_shardings(params, mesh)
    flat = jax.tree_util.tree_flatten_with_path(sh)[0]
    specs = {jax.tree_util.keystr(p): s.spec for p, s in flat}
    # the 4x256 hidden kernel is column-sharded; the 256x2 head replicated
    assert any(s == jax.sharding.PartitionSpec(None, "tp")
               for s in specs.values())


def test_dist_replay_state_sharded():
    dp = 4
    mesh, learner, state = _make_dist(dp=dp, tp=2)
    assert state.replay.tree.shape == (dp, 2 * 64)
    assert state.rng.shape[0] == dp
    # storage leaves carry the leading dp axis and a dp sharding
    assert state.replay.storage["obs"].shape == (dp, 64, 4)
    spec = state.replay.storage["obs"].sharding.spec
    assert spec and spec[0] == "dp"


def test_dist_train_step_runs_and_syncs():
    dp = 4
    mesh, learner, state = _make_dist(dp=dp, tp=2, batch=32)
    state = _ingest(learner, state, dp, 16)
    assert int(np.asarray(state.replay.size).sum()) == dp * 16
    p0 = np.asarray(jax.tree.leaves(state.params)[0])  # copy: state is donated
    for _ in range(3):
        state, m = learner.train_step(state)
    assert np.isfinite(float(m["loss"]))
    assert int(state.step) == 3
    # params changed
    p1 = np.asarray(jax.tree.leaves(state.params)[0])
    assert not np.allclose(p0, p1)
    # target sync at step 10
    for _ in range(7):
        state, m = learner.train_step(state)
    tp_, pp_ = jax.tree.leaves(state.target_params), jax.tree.leaves(
        state.params)
    for a, b in zip(tp_, pp_):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_dist_matches_priorities_locally():
    """Priority write-back stays shard-local: sampled indices from shard
    d update shard d's tree only."""
    dp = 2
    mesh, learner, state = _make_dist(dp=dp, tp=1, batch=8)
    state = _ingest(learner, state, dp, 8)
    trees_before = np.asarray(state.replay.tree)
    state, m = learner.train_step(state)
    trees_after = np.asarray(state.replay.tree)
    # both shard trees were touched (each shard sampled and updated)
    assert not np.allclose(trees_before[0], trees_after[0])
    assert not np.allclose(trees_before[1], trees_after[1])


def test_train_many_scan():
    dp = 4
    mesh, learner, state = _make_dist(dp=dp, tp=2, batch=32)
    state = _ingest(learner, state, dp, 16)
    state, m = learner.train_many(state, 5)
    assert int(state.step) == 5 and np.isfinite(float(m["loss"]))


def test_publish_params_replicated():
    mesh, learner, state = _make_dist(dp=4, tp=2)
    pub = learner.publish_params(state)
    for leaf in jax.tree.leaves(pub):
        assert leaf.sharding.is_fully_replicated


def test_sharded_inference_server():
    """Mesh mode: batch leading axis split over all 8 devices, params
    replicated, replies identical to the unsharded forward; buckets are
    multiples of the mesh size so every shard gets identical work."""
    import threading

    from ape_x_dqn_tpu.parallel.inference_server import \
        BatchedInferenceServer

    mesh = make_mesh(dp=4, tp=2)

    def apply_fn(params, obs):
        return obs @ params

    params = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
    server = BatchedInferenceServer(apply_fn, params, max_batch=16,
                                    deadline_ms=5.0, mesh=mesh)
    try:
        assert server._bucket(1) == 8  # rounded up to mesh.size
        assert server._bucket(9) == 16
        results = {}

        def client(i):
            obs = np.full(4, float(i), np.float32)
            results[i] = server.query(obs)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(11)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(11):
            expect = np.full(4, float(i), np.float32) @ np.asarray(params)
            np.testing.assert_allclose(results[i], expect, rtol=1e-6)
        assert server.stats["items"] == 11
    finally:
        server.stop()


def test_sharded_inference_server_pytree_requests():
    """Recurrent-style (obs, (c, h)) request pytrees shard per-leaf on
    dim 0 under the mesh (the prefix-sharding contract)."""
    from ape_x_dqn_tpu.parallel.inference_server import \
        BatchedInferenceServer

    mesh = make_mesh(dp=4, tp=2)

    def apply_fn(params, inputs):
        obs, (c, h) = inputs
        q = obs @ params
        return q, (c + 1.0, h * 2.0)

    params = jnp.eye(4)
    server = BatchedInferenceServer(apply_fn, params, max_batch=8,
                                    deadline_ms=5.0, mesh=mesh)
    try:
        obs = np.arange(4, dtype=np.float32)
        c = np.zeros(3, np.float32)
        h = np.ones(3, np.float32)
        q, (c2, h2) = server.query((obs, (c, h)))
        np.testing.assert_allclose(q, obs, rtol=1e-6)
        np.testing.assert_allclose(c2, np.ones(3), rtol=1e-6)
        np.testing.assert_allclose(h2, np.full(3, 2.0), rtol=1e-6)
    finally:
        server.stop()


def test_skewed_shard_is_weights():
    """Round-2 verdict weak #3: the dist IS weights under DELIBERATELY
    unbalanced shard priority masses (one shard starved 1000x — the
    dead-actor-host failure mode the transport tolerates).

    The dist learner weights by the ACTUAL stratified sampling
    probability P(i) = probs/dp. Two properties pin it down:

    1. beta=1 unbiasedness under skew: the weighted estimate of a
       per-item value recovers the exact uniform mean — while the
       'single global tree' probability p_i/M (the oracle the round-2
       verdict suggested psum-ing) is provably biased for this sampler.
    2. The per-item deviation between dist and oracle weights is
       EXACTLY (M/(dp*m_d))^-beta — bounded and analytic, not an
       unbounded approximation error.
    """
    dp, cap, b_local = 4, 64, 32
    replay = PrioritizedReplay(capacity=cap, alpha=1.0, beta=1.0, eps=0.0)
    spec = {"g": jax.ShapeDtypeStruct((), jnp.float32)}
    # shard d: EVERY item has value g=d+1 and the same priority; shard 0
    # starved 1000x. Constant-per-shard values+priorities make the
    # estimators below zero-variance, so one draw is exact.
    masses = np.array([1e-3, 1.0, 1.0, 2.0], np.float64)
    states = []
    for d in range(dp):
        st = replay.init(spec)
        st = replay.add(
            st, {"g": jnp.full(cap, d + 1.0, jnp.float32)},
            jnp.full(cap, masses[d] / cap, jnp.float32))
        states.append(st)
    state = jax.tree.map(lambda *xs: jnp.stack(xs), *states)

    n_global = float(dp * cap)
    keys = jax.random.split(jax.random.key(0), dp)
    items, idx, probs = jax.vmap(
        lambda rs, k: replay.sample_items(rs, k, b_local))(state, keys)
    g = np.asarray(items["g"])          # [dp, b]
    probs = np.asarray(probs)           # [dp, b] = p_i / m_d

    # (1) the dist learner's weights (beta=1, pre-normalization)
    w_dist = (n_global * probs / dp) ** -1.0
    est = float((w_dist * g).mean())
    uniform_mean = float(np.mean([d + 1.0 for d in range(dp)]))
    assert abs(est - uniform_mean) < 1e-3, (est, uniform_mean)

    # ... while oracle global-mass weights bias the starved shard's
    # contribution by M/(dp*m_0) ~ 250x
    m = masses.astype(np.float32)
    big_m = float(m.sum())
    w_oracle = (n_global * probs * (m[:, None] / big_m)) ** -1.0
    est_oracle = float((w_oracle * g).mean())
    assert abs(est_oracle - uniform_mean) > 10.0, est_oracle

    # (2) exact analytic deviation bound at the recipe's beta=0.4
    beta = 0.4
    wd = (n_global * probs / dp) ** -beta
    wo = (n_global * probs * (m[:, None] / big_m)) ** -beta
    # wd/wo = [(probs/dp) / (probs*m_d/M)]^-beta = (dp*m_d/M)^beta
    expect_ratio = (dp * m / big_m) ** beta  # [dp]
    np.testing.assert_allclose(wd / wo, np.broadcast_to(
        expect_ratio[:, None], wd.shape), rtol=1e-4)


def test_global_stats_packed_reduction():
    """global_stats packs (all_ready, all_idle, exact frame sum) into
    one collective; the frame limbs must stay exact far past f32's
    2^24 integer range."""
    from ape_x_dqn_tpu.parallel import multihost

    mesh = make_mesh(dp=8, tp=1)
    frames = 123_456_789_012  # ~2^37: rounds badly in a single f32
    ready, idle, total = multihost.global_stats(mesh, 1.0, 0.0,
                                                float(frames))
    assert ready is True and idle is False
    # the base-2^16 limbs ride on exactly ONE row per process (zeros on
    # its other rows), so the un-normalized row-sum counts each process
    # once and recombines exactly in Python ints
    assert total == float(frames)


def test_dist_kbatch_train_step_k():
    """K-batch relaxation on the (dp, tp) mesh: one per-shard
    stratified K*b_local sample + one per-shard write-back per K
    grad-steps, interleaved strata per chunk, remainder path, and
    determinism — the dist mirror of the single-chip
    test_kbatch_train_many_mechanics."""
    import dataclasses

    mesh = make_mesh(dp=4, tp=2)
    net = build_network(
        NetworkConfig(kind="mlp", mlp_hidden=(256,), dueling=False,
                      compute_dtype="float32"), VEC_SPEC)
    params = net.init(jax.random.key(0), jnp.zeros((1, 4)))
    lcfg = LearnerConfig(batch_size=32, target_sync_every=3,
                         sample_chunk=4)
    learner = DistDQNLearner(net.apply, PrioritizedReplay(capacity=64),
                             lcfg, mesh)
    spec = transition_item_spec((4,), jnp.float32)
    state = learner.init(params, spec, jax.random.key(1))
    state = _ingest(learner, state, 4, 48)
    tree_root_before = np.asarray(state.replay.tree)[:, 1].copy()

    state, m = learner.train_step_k(state, 4)
    assert int(state.step) == 4
    assert np.isfinite(float(m["loss"]))
    # every shard's tree total changed (per-shard write-back ran)
    root_after = np.asarray(state.replay.tree)[:, 1]
    assert (root_after != tree_root_before).all()

    # train_many routes through macro-steps + remainder (10 = 2x4 + 2)
    state, m = learner.train_many(state, 10)
    assert int(state.step) == 14
    assert np.isfinite(float(m["loss"]))

    # determinism through the dist K-batch path
    def run_once():
        net2 = build_network(
            NetworkConfig(kind="mlp", mlp_hidden=(256,), dueling=False,
                          compute_dtype="float32"), VEC_SPEC)
        p2 = net2.init(jax.random.key(0), jnp.zeros((1, 4)))
        lrn = DistDQNLearner(net2.apply, PrioritizedReplay(capacity=64),
                             lcfg, mesh)
        st = lrn.init(p2, spec, jax.random.key(1))
        st = _ingest(lrn, st, 4, 48)
        st, _ = lrn.train_step_k(st, 4)
        return jax.tree.map(np.asarray, st.params)

    a, b = run_once(), run_once()
    jax.tree.map(np.testing.assert_array_equal, a, b)


def test_dist_prefetch_train_many():
    """Double-buffered sampling on the (dp, tp) mesh: with
    sample_prefetch=True train_many pipelines each macro-step's
    per-shard stratified sample against the priorities predating the
    previous macro-step's write-back. Mechanics (step counts, per-shard
    tree repair, remainder path), first-macro equivalence to the fused
    dist K-batch path, and run-twice determinism — the dist mirror of
    test_runtime.test_prefetch_train_many_mechanics."""
    import dataclasses

    mesh = make_mesh(dp=4, tp=2)
    spec = transition_item_spec((4,), jnp.float32)
    lcfg = LearnerConfig(batch_size=32, target_sync_every=3,
                         sample_chunk=4, sample_prefetch=True)

    def build(prefetch=True):
        net = build_network(
            NetworkConfig(kind="mlp", mlp_hidden=(256,), dueling=False,
                          compute_dtype="float32"), VEC_SPEC)
        params = net.init(jax.random.key(0), jnp.zeros((1, 4)))
        lrn = DistDQNLearner(
            net.apply, PrioritizedReplay(capacity=64),
            dataclasses.replace(lcfg, sample_prefetch=prefetch), mesh)
        st = lrn.init(params, spec, jax.random.key(1))
        return lrn, _ingest(lrn, st, 4, 48)

    learner, state = build()
    root_before = np.asarray(state.replay.tree)[:, 1].copy()

    # 10 = 2 exact remainder steps + 2 pipelined macro-steps of 4
    state, m = learner.train_many(state, 10)
    assert int(state.step) == 10
    assert np.isfinite(float(m["loss"]))
    # every shard's tree total changed (per-shard write-back ran)
    assert (np.asarray(state.replay.tree)[:, 1] != root_before).all()

    # first-macro equivalence: one pipelined macro-step == one fused
    # train_step_k on the same initial state (params AND shard trees)
    l1, s1 = build(True)
    l2, s2 = build(False)
    s1, _ = l1.train_many(s1, 4)
    s2, _ = l2.train_step_k(s2, 4)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s1.params, s2.params)
    np.testing.assert_array_equal(np.asarray(s1.replay.tree),
                                  np.asarray(s2.replay.tree))

    # determinism through the dist prefetch pipeline
    def run_once():
        lrn, st = build()
        st, _ = lrn.train_many(st, 12)
        return jax.tree.map(np.asarray, st.params)

    a, b = run_once(), run_once()
    jax.tree.map(np.testing.assert_array_equal, a, b)
