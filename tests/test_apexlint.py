"""apexlint gate + checker self-tests + lock-order witness tests.

Three layers:
- the tier-1 gate: the CLI over the real package must report ZERO
  findings (waivers are allowed — they are justified in-line);
- checker calibration: the deliberately-broken fixtures under
  tests/apexlint_fixtures/ must each produce exactly the expected
  finding, and the good twins exactly none (a checker that goes quiet
  or noisy fails here, not silently in review);
- the dynamic companion: the lock-order witness must raise on an
  A->B / B->A acquisition cycle and stay silent on consistent order.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO_ROOT, "tests", "apexlint_fixtures")

sys.path.insert(0, REPO_ROOT)  # tools/ is repo-local, not installed

from tools.apexlint import run as apexlint_run  # noqa: E402
from tools.apexlint import config_coverage, counter_closure, guarded_by, \
    host_sync, jit_purity, learner_parity, obs_names, \
    remediation_accounting, resource_lifecycle, retry_annotation, \
    thread_lifecycle, use_after_donate, wire_protocol  # noqa: E402


def _fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


# -- the tier-1 gate ------------------------------------------------------

def test_package_has_zero_findings():
    summary = apexlint_run(os.path.join(REPO_ROOT, "ape_x_dqn_tpu"))
    assert summary["findings"] == [], (
        "apexlint found violations in the package:\n" + "\n".join(
            f"{f['path']}:{f['line']}: [{f['checker']}] {f['message']}"
            for f in summary["findings"]))
    # waivers exist (each justified in-line); creep shows up in bench
    assert summary["checked_files"] > 50


def test_cli_json_subprocess():
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "ape_x_dqn_tpu/",
         "--format=json"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads(out.stdout)
    assert summary["findings"] == []
    assert set(summary["per_checker"]) == {
        "guarded-by", "jit-purity", "wire-protocol", "obs-names",
        "retry-annotation", "remediation-accounting",
        "use-after-donate", "host-sync",
        "config-coverage", "learner-parity",
        "thread-lifecycle", "resource-lifecycle", "counter-closure"}
    # per-checker shape feeds bench.py's secondary.apexlint lane;
    # "ms" is the wall-clock CI watches for a checker gone slow
    for counts in summary["per_checker"].values():
        assert set(counts) == {"findings", "waivers", "ms"}
        assert counts["ms"] >= 0
    # the verified conservation laws ride the summary for the runtime
    # hook; the package declares at least the cold-door and drop ones
    exprs = {c["expr"] for c in summary["closures"]}
    assert "_cold_evicted == _cold_stored + _cold_dropped" in exprs
    assert "_dropped == _drop_reasons" in exprs


def test_cli_sarif_subprocess():
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "ape_x_dqn_tpu/",
         "--format=sarif"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    sarif = json.loads(out.stdout)
    assert sarif["version"] == "2.1.0"
    driver = sarif["runs"][0]["tool"]["driver"]
    assert driver["name"] == "apexlint"
    assert {r["id"] for r in driver["rules"]} >= {
        "use-after-donate", "host-sync", "learner-parity",
        "thread-lifecycle", "resource-lifecycle", "counter-closure"}
    # per-rule timing properties (satellite: CI spots a slow checker)
    for r in driver["rules"]:
        assert set(r["properties"]) == {"findings", "waivers", "ms"}
    assert sarif["runs"][0]["results"] == []


def test_cli_changed_only_filters_and_annotates():
    # vs HEAD with a clean tree the package has no changed findings
    # either way (the gate is already zero); the mode must still run
    # the whole-program analysis and annotate the summary
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "ape_x_dqn_tpu/",
         "--changed-only", "HEAD", "--format=json"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads(out.stdout)
    assert summary["findings"] == []
    assert summary["changed_only"]["ref"] == "HEAD"
    # analysis stayed whole-program: all files scanned, all checkers ran
    assert summary["checked_files"] > 50
    assert "learner-parity" in summary["per_checker"]


def test_cli_self_dogfood():
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "--self"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout


def test_cli_self_asserts_chaos_coverage():
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", "--self",
         "--format=json"],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads(out.stdout)
    # the dogfood run must actually sweep the fault injectors — the
    # thread/resource checkers exist for exactly that kind of code
    assert summary["self_scope"]["tools/chaos"] >= 3


def test_cli_text_nonzero_exit_on_findings(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "racy.py").write_text(
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = None\n"
        "        self._n = 0  # guarded-by: _lock\n"
        "    def bump(self):\n"
        "        self._n += 1\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.apexlint", str(pkg)],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT)
    assert out.returncode == 1
    assert "guarded-by" in out.stdout


# -- checker calibration on fixtures --------------------------------------

def test_guarded_by_fixtures():
    good = guarded_by.check_paths([_fx("guarded_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the justified teardown write

    bad = guarded_by.check_paths([_fx("guarded_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "guarded-by"
    assert "self._count" in f.message and "_lock" in f.message
    assert bad.waivers == 1  # the waived closure write


def test_jit_purity_fixtures():
    good = jit_purity.check_paths([_fx("jit_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the justified trace-time print

    bad = jit_purity.check_paths([_fx("jit_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "jit-purity"
    assert "time.time" in f.message
    assert "_timed_residual" in f.message  # names the reachable hop


def test_jit_purity_cross_module_fixtures():
    """v2: the jit boundary and the host effect live in DIFFERENT
    modules — the checker must follow `from x import y` through the
    call graph and anchor the finding at the effect's line in the
    helper module."""
    good = jit_purity.check_paths(
        [_fx("xjit_good_entry.py"), _fx("xjit_good_util.py")])
    assert good.findings == []
    assert good.waivers == 0

    bad = jit_purity.check_paths(
        [_fx("xjit_bad_entry.py"), _fx("xjit_bad_util.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "jit-purity"
    assert "time.time" in f.message
    assert "residual_scale" in f.message  # names the cross-module hop
    assert f.path.endswith("xjit_bad_util.py")  # anchored at the effect

    # module-local degeneration: the entry file alone cannot see the
    # impurity (the import resolves to nothing and stays opaque)
    alone = jit_purity.check_paths([_fx("xjit_bad_entry.py")])
    assert alone.findings == []


def test_use_after_donate_fixtures():
    good = use_after_donate.check_paths([_fx("donate_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the audited metadata read

    bad = use_after_donate.check_paths([_fx("donate_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "use-after-donate"
    assert "state" in f.message and "train_step" in f.message
    assert "deleted" in f.message


def test_host_sync_fixtures():
    good = host_sync.check_paths([_fx("hostsync_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the one explicit fused fetch

    bad = host_sync.check_paths([_fx("hostsync_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "host-sync"
    assert "float()" in f.message
    assert "learn_loop" in f.message


def test_host_sync_scope_is_opt_in(tmp_path):
    # the same sync OUTSIDE a hot module (no marker, basename not in
    # HOT_BASENAMES) is not flagged: checkpointing and teardown code
    # legitimately syncs
    bad_src = open(_fx("hostsync_bad.py"), encoding="utf-8").read()
    elsewhere = tmp_path / "elsewhere.py"
    elsewhere.write_text(bad_src.replace("# apexlint-scope: hot-path", ""))
    res = host_sync.check_paths([str(elsewhere)])
    assert res.findings == []


def test_config_coverage_fixtures():
    good_dir = _fx("cfgcov_good")
    good_paths = [os.path.join(good_dir, n)
                  for n in ("configs.py", "reader.py")]
    good = config_coverage.check(
        good_paths, readme_path=os.path.join(good_dir, "README.md"))
    assert good.findings == []
    assert good.waivers == 1  # the declared-dormant fault_rate

    bad_dir = _fx("cfgcov_bad")
    bad_paths = [os.path.join(bad_dir, n)
                 for n in ("configs.py", "reader.py")]
    bad = config_coverage.check(
        bad_paths, readme_path=os.path.join(bad_dir, "README.md"))
    msgs = [f.message for f in bad.findings]
    assert any("dead_knob" in m and "read nowhere" in m for m in msgs)
    assert any("phantom_knob" in m and "no field" in m for m in msgs)
    assert len(bad.findings) == 2


def test_learner_parity_fixtures():
    good = learner_parity.check_paths([_fx("parity_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the declared add() asymmetry

    bad = learner_parity.check_paths([_fx("parity_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "learner-parity"
    assert "BetaLearner" in f.message and "add()" in f.message
    assert "AlphaLearner" in f.message  # names who has the endpoint


def test_learner_parity_waiver_must_name_endpoint(tmp_path):
    # a parity waiver that does not MENTION the drifted endpoint does
    # not absorb the finding — blanket waivers can't hide future drift
    src = open(_fx("parity_good.py"), encoding="utf-8").read()
    blanket = tmp_path / "parity_blanket.py"
    blanket.write_text(src.replace(
        "parity(no add — beta ingests through alpha's staging ring)",
        "parity(beta is special)"))
    res = learner_parity.check_paths([str(blanket)])
    assert len(res.findings) == 1
    assert "add()" in res.findings[0].message


def test_wire_protocol_fixtures():
    good = wire_protocol.check_paths([_fx("wire_good.py")])
    assert good.findings == []
    assert good.waivers == 2  # MSG_LEGACY waived in both chains

    bad = wire_protocol.check_paths([_fx("wire_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "wire-protocol"
    assert "MSG_PONG" in f.message and "Server" in f.message


def test_wire_protocol_telemetry_fixtures():
    good = wire_protocol.check_paths([_fx("wire_telemetry_good.py")])
    assert good.findings == []
    assert good.waivers == 0  # fully wired, nothing to excuse

    bad = wire_protocol.check_paths([_fx("wire_telemetry_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "wire-protocol"
    assert "MSG_TELEMETRY" in f.message and "Server" in f.message


def test_wire_protocol_push_fixtures():
    good = wire_protocol.check_paths([_fx("wire_push_good.py")])
    assert good.findings == []
    assert good.waivers == 0  # push wired into both chains

    bad = wire_protocol.check_paths([_fx("wire_push_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "wire-protocol"
    assert "MSG_PARAMS_PUSH" in f.message and "Client" in f.message


def test_wire_protocol_shm_fixtures():
    # ISSUE 18: the doorbell frame rides the SAME dispatch chains as
    # every other MSG_* — a server that grants rings but a client that
    # never posts doorbells is the half-wired state the checker exists
    # to catch
    good = wire_protocol.check_paths([_fx("wire_shm_good.py")])
    assert good.findings == []
    assert good.waivers == 0  # doorbell wired into both chains

    bad = wire_protocol.check_paths([_fx("wire_shm_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "wire-protocol"
    assert "MSG_SHM_DOORBELL" in f.message and "Client" in f.message


def test_wire_protocol_paramtag_fixtures():
    # ISSUE 19: the param payload TAG ('APXV' raw-versioned vs 'APXC'
    # delta-coded) is a protocol family one level below MSG_* — a
    # parser sniffing one tag while the publisher ships both stalls
    # exactly the peers that negotiated the codec. The bad fixture
    # also IMPORTS its tags (the real split: tags in param_codec.py,
    # parser in socket_transport.py), so it calibrates that imported
    # names count toward the module's tag family.
    good = wire_protocol.check_paths([_fx("wire_paramtag_good.py")])
    assert good.findings == []
    assert good.waivers == 0  # both tags routed, nothing to excuse

    bad = wire_protocol.check_paths([_fx("wire_paramtag_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "wire-protocol"
    assert "PARAMS_CODEC_MAGIC" in f.message and "Parser" in f.message
    assert "payload-tag" in f.message


def test_retry_annotation_fixtures():
    good = retry_annotation.check_paths(
        [_fx(os.path.join("comm", "retry_good.py"))])
    assert good.findings == []
    assert good.waivers == 1  # the justified close-path waiver

    bad = retry_annotation.check_paths(
        [_fx(os.path.join("comm", "retry_bad.py"))])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "retry-annotation"
    assert "OSError" in f.message and "lossy" in f.message


def test_retry_annotation_replay_fixtures():
    # PR 16 extends the checker's scope to replay/ — the disk spill
    # rung does real file IO and a swallowed OSError there is a
    # silently lost segment
    good = retry_annotation.check_paths(
        [_fx(os.path.join("replay", "diskio_good.py"))])
    assert good.findings == []
    assert good.waivers == 1  # the justified shutdown-close waiver

    bad = retry_annotation.check_paths(
        [_fx(os.path.join("replay", "diskio_bad.py"))])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "retry-annotation"
    assert "OSError" in f.message and "lossy" in f.message


def test_retry_annotation_scope_is_comm_runtime_replay(tmp_path):
    # the same silent swallow OUTSIDE comm/, runtime/, or replay/ is
    # not flagged: the rule is about the transport/runtime/spill loss
    # contract, not a repo-wide style ban
    bad_src = open(
        _fx(os.path.join("comm", "retry_bad.py")), encoding="utf-8").read()
    elsewhere = tmp_path / "elsewhere.py"
    elsewhere.write_text(bad_src)
    res = retry_annotation.check_paths([str(elsewhere)])
    assert res.findings == []


def test_remediation_accounting_fixtures():
    good = remediation_accounting.check_paths(
        [_fx(os.path.join("runtime", "remediation_good.py"))])
    assert good.findings == []
    assert good.waivers == 1  # the justified central-dispatch waiver

    bad = remediation_accounting.check_paths(
        [_fx(os.path.join("runtime", "remediation_bad.py"))])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "remediation-accounting"
    assert "quarantine_peer" in f.message and "unaccounted" in f.message


def test_remediation_accounting_scope_is_runtime(tmp_path):
    # an uncounted actuator call OUTSIDE runtime/ is not flagged: the
    # rule enforces the remediation plane's audit-trail contract, not a
    # repo-wide naming ban (bench.py wires bare actuators on purpose)
    bad_src = open(
        _fx(os.path.join("runtime", "remediation_bad.py")),
        encoding="utf-8").read()
    elsewhere = tmp_path / "elsewhere.py"
    elsewhere.write_text(bad_src)
    res = remediation_accounting.check_paths([str(elsewhere)])
    assert res.findings == []


def test_obs_names_fixtures():
    report = _fx("obs_report_fixture.py")
    good = obs_names.check([_fx("obs_good.py")], report)
    # dead_row is listed-but-unemitted even against the good emitter
    assert [f for f in good.findings if "dead_row" not in f.message] == []
    assert good.waivers == 2  # scratch_gauge emission + external_row row

    bad = obs_names.check([_fx("obs_good.py"), _fx("obs_bad.py")], report)
    msgs = [f.message for f in bad.findings]
    assert any("rogue_counter" in m for m in msgs)
    assert any("dead_row" in m for m in msgs)
    assert len(bad.findings) == 2


def test_obs_names_profiling_fixtures():
    """The perf-plane fixture pair (ISSUE 8): the good emitter's
    literal if/elif stage gauges + compile counters cross-reference
    cleanly; the bad emitter drifts both ways (kind mismatch on an
    existing row, a brand-new gauge with no row)."""
    report = _fx("profiling_report_fixture.py")
    good = obs_names.check([_fx("profiling_good.py")], report)
    assert good.findings == []
    assert good.waivers == 0

    bad = obs_names.check(
        [_fx("profiling_good.py"), _fx("profiling_bad.py")], report)
    msgs = [f.message for f in bad.findings]
    assert any("mfu_learn_k" in m for m in msgs)  # gauge-vs-ctr drift
    assert any("mfu_scratch" in m for m in msgs)  # unlisted emission
    assert len(bad.findings) == 2


def test_obs_names_learning_fixtures():
    """The learning-plane fixture pair (ISSUE 10): the good emitter's
    publish_learn literal gauges + loss histogram + degradation counter
    cross-reference cleanly (tenant-prefixed f-string keys invisible by
    design); the bad emitter drifts both ways (grad_norm emitted as a
    counter, an unlisted diagnostic gauge)."""
    report = _fx("learning_report_fixture.py")
    good = obs_names.check([_fx("learning_good.py")], report)
    assert good.findings == []
    assert good.waivers == 0

    bad = obs_names.check(
        [_fx("learning_good.py"), _fx("learning_bad.py")], report)
    msgs = [f.message for f in bad.findings]
    assert any("learn_grad_norm" in m for m in msgs)  # gauge-vs-ctr
    assert any("learn_scratch_frac" in m for m in msgs)  # unlisted
    assert len(bad.findings) == 2


def test_obs_names_multichip_fixtures():
    """The dp-scaling fixture pair (ISSUE 9): the good emitter's
    publish_multichip + train_dist literal gauges cross-reference
    cleanly against the mini table; the bad emitter drifts both ways
    (efficiency emitted as a counter, an unlisted per-shard gauge)."""
    report = _fx("multichip_report_fixture.py")
    good = obs_names.check([_fx("multichip_good.py")], report)
    assert good.findings == []
    assert good.waivers == 0

    bad = obs_names.check(
        [_fx("multichip_good.py"), _fx("multichip_bad.py")], report)
    msgs = [f.message for f in bad.findings]
    assert any("dp_scaling_efficiency" in m for m in msgs)
    assert any("replay_shard_fill_median" in m for m in msgs)
    assert len(bad.findings) == 2


def test_obs_names_cold_fixtures():
    """The cold-tier fixture pair (ISSUE 11): the good emitter's
    occupancy/ratio gauges + eviction/recall counters cross-reference
    cleanly against the mini table; the bad emitter drifts both ways
    (the ratio emitted as a counter, an unlisted recall-lag gauge)."""
    report = _fx("cold_report_fixture.py")
    good = obs_names.check([_fx("cold_good.py")], report)
    assert good.findings == []
    assert good.waivers == 0

    bad = obs_names.check(
        [_fx("cold_good.py"), _fx("cold_bad.py")], report)
    msgs = [f.message for f in bad.findings]
    assert any("cold_compression_ratio" in m for m in msgs)  # kind
    assert any("cold_recall_lag_s" in m for m in msgs)  # unlisted
    assert len(bad.findings) == 2


def test_obs_names_serve_fixtures():
    """The serving-tier fixture pair (ISSUE 13): the good emitter's
    admission counters + tier gauges + latency histogram
    cross-reference cleanly (per-tenant serve/<tenant>/ f-string keys
    invisible by design); the bad emitter drifts both ways (queue
    depth emitted as a counter, an unlisted admission-outcome
    counter)."""
    report = _fx("serve_report_fixture.py")
    good = obs_names.check([_fx("serve_good.py")], report)
    assert good.findings == []
    assert good.waivers == 0

    bad = obs_names.check(
        [_fx("serve_good.py"), _fx("serve_bad.py")], report)
    msgs = [f.message for f in bad.findings]
    assert any("serve_queue_items" in m for m in msgs)  # gauge-vs-ctr
    assert any("serve_preempted" in m for m in msgs)  # unlisted
    assert len(bad.findings) == 2


def test_obs_names_blackbox_fixtures():
    """The forensics fixture pair (ISSUE 17): the good emitter's
    ring/dump/bundle counters cross-reference cleanly against the
    mini table; the bad emitter drifts both ways (dumps emitted as a
    gauge, an unlisted scratch counter)."""
    report = _fx("blackbox_report_fixture.py")
    good = obs_names.check([_fx("blackbox_good.py")], report)
    assert good.findings == []
    assert good.waivers == 0

    bad = obs_names.check(
        [_fx("blackbox_good.py"), _fx("blackbox_bad.py")], report)
    msgs = [f.message for f in bad.findings]
    assert any("blackbox_dumps" in m for m in msgs)  # ctr-vs-gauge
    assert any("blackbox_scratch" in m for m in msgs)  # unlisted
    assert len(bad.findings) == 2


def test_config_coverage_serving_scope(tmp_path):
    """ServingConfig is in the README-knob scope (ISSUE 13): a README
    naming a nonexistent serving.<knob> fails, a real knob passes, and
    an unread ServingConfig field fails direction 1."""
    from tools.apexlint import config_coverage

    configs = tmp_path / "configs.py"
    configs.write_text(
        "from dataclasses import dataclass\n"
        "@dataclass\nclass ServingConfig:\n"
        "    multi_tenant: bool = False\n"
        "    dead_knob: int = 0\n")
    reader = tmp_path / "reader.py"
    reader.write_text("def f(cfg):\n    return cfg.multi_tenant\n")
    readme = tmp_path / "README.md"
    readme.write_text("set serving.multi_tenant, not "
                      "serving.imaginary_knob\n")
    res = config_coverage.check(
        [str(configs), str(reader)], configs_path=str(configs),
        readme_path=str(readme))
    msgs = [f.message for f in res.findings]
    assert any("serving.imaginary_knob" in m for m in msgs)
    assert any("ServingConfig.dead_knob" in m for m in msgs)
    assert not any("multi_tenant" in m for m in msgs)
    assert len(res.findings) == 2


def test_config_coverage_param_codec_scope(tmp_path):
    """ISSUE 19 knobs stay in scope: `comm.param_codec` read through
    getattr counts as a read (train.py reads the codec knobs exactly
    that way, for configs checkpointed before the field existed), a
    dead param_* knob still flags, and a README naming a nonexistent
    comm.param_* knob flags the phantom direction."""
    from tools.apexlint import config_coverage

    configs = tmp_path / "configs.py"
    configs.write_text(
        "from dataclasses import dataclass\n"
        "@dataclass\nclass CommConfig:\n"
        "    param_codec: str = 'delta-q8'\n"
        "    param_delta_window: int = 8\n"
        "    param_dead_knob: int = 0\n")
    reader = tmp_path / "reader.py"
    reader.write_text(
        "def f(cfg):\n"
        "    codec = getattr(cfg, 'param_codec', 'raw')\n"
        "    return codec, cfg.param_delta_window\n")
    readme = tmp_path / "README.md"
    readme.write_text(
        "set comm.param_codec and comm.param_delta_window, "
        "not comm.param_phantom_knob\n")
    res = config_coverage.check(
        [str(configs), str(reader)], configs_path=str(configs),
        readme_path=str(readme))
    msgs = [f.message for f in res.findings]
    assert any("comm.param_phantom_knob" in m for m in msgs)
    assert any("CommConfig.param_dead_knob" in m for m in msgs)
    assert not any("param_codec" in m or "param_delta_window" in m
                   for m in msgs)
    assert len(res.findings) == 2


def test_obs_names_kind_mismatch(tmp_path):
    emit = tmp_path / "emit.py"
    emit.write_text("def f(obs):\n    obs.gauge('x_name', 1)\n")
    report = tmp_path / "report.py"
    report.write_text("INSTRUMENTS = {'x_name': {'kind': 'ctr'}}\n")
    res = obs_names.check([str(emit)], str(report))
    assert len(res.findings) == 1
    assert "listed as ctr but emitted as gauge" in res.findings[0].message


# -- v3 checker calibration (thread/resource lifecycle, closures) ---------

def test_thread_lifecycle_fixtures():
    good = thread_lifecycle.check_paths([_fx("thread_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the deliberately detached reader

    bad = thread_lifecycle.check_paths(
        [_fx("thread_unbounded_join_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "thread-lifecycle"
    assert "unbounded .join()" in f.message
    assert f.line == 22  # the join line, not the construction


def test_thread_lifecycle_stopflag_fixture():
    bad = thread_lifecycle.check_paths([_fx("thread_stopflag_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "thread-lifecycle"
    assert "never consults a stop signal" in f.message


def test_thread_lifecycle_fireforget_fixture():
    bad = thread_lifecycle.check_paths(
        [_fx("thread_fireforget_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "thread-lifecycle"
    assert "fire-and-forget" in f.message


def test_resource_lifecycle_fixtures():
    good = resource_lifecycle.check_paths([_fx("resource_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the caller-owned socket

    bad = resource_lifecycle.check_paths([_fx("resource_order_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "resource-lifecycle"
    assert "out of declared order" in f.message
    assert "close() runs before unlink()" in f.message
    assert "PR 18" in f.message


def test_resource_lifecycle_leak_fixture():
    bad = resource_lifecycle.check_paths([_fx("resource_leak_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "resource-lifecycle"
    assert "defines no teardown method" in f.message


def test_counter_closure_fixtures():
    good = counter_closure.check_paths([_fx("closure_good.py")])
    assert good.findings == []
    assert good.waivers == 1  # the rebalance move outside the law

    bad = counter_closure.check_paths([_fx("closure_leak_bad.py")])
    assert len(bad.findings) == 1
    f = bad.findings[0]
    assert f.checker == "counter-closure"
    assert "a path leaks (0 term bumps)" in f.message
    assert f.line == 17  # the _evicted bump whose error path leaks


def test_counter_closure_runtime_hook():
    decls = counter_closure.declarations([_fx("closure_good.py")])
    assert [d["expr"] for d in decls] == \
        ["_evicted == _stored + _dropped"]
    decl = decls[0]

    class Ledger:
        pass

    obj = Ledger()
    obj._evicted, obj._stored, obj._dropped = 5, 3, 2
    counter_closure.check_object(obj, decl)  # holds: silent
    obj._dropped = {"reset": 1, "timeout": 0}  # dict terms sum
    obj._evicted = 4
    counter_closure.check_object(obj, decl)
    obj._evicted = 9
    with pytest.raises(AssertionError) as ei:
        counter_closure.check_object(obj, decl)
    assert "_evicted == _stored + _dropped" in str(ei.value)


def test_v3_fixed_modules_stay_clean():
    """Regression pins for the real findings the seeding sweep fixed:
    the unbounded actor join + fire-and-forget bp watchdog
    (runtime/actor_host.py), the never-joined stall watchdog
    (obs/health.py), the undrained ingest queue
    (comm/socket_transport.py), and the teardown-less loopback queue
    (comm/transport.py). Single-file re-lints keep each fix honest
    even if the package-wide gate's scope ever changes."""
    pkg = os.path.join(REPO_ROOT, "ape_x_dqn_tpu")
    for rel in ("runtime/actor_host.py", "obs/health.py"):
        res = thread_lifecycle.check_paths([os.path.join(pkg, rel)])
        assert res.findings == [], (rel, [str(f) for f in res.findings])
    for rel in ("comm/socket_transport.py", "comm/transport.py"):
        res = resource_lifecycle.check_paths([os.path.join(pkg, rel)])
        assert res.findings == [], (rel, [str(f) for f in res.findings])


# -- lock-order witness ---------------------------------------------------

def _witness_pair():
    from ape_x_dqn_tpu.obs.health import LockOrderRecorder, WitnessLock
    rec = LockOrderRecorder()
    return (WitnessLock("A", rec), WitnessLock("B", rec),
            WitnessLock("C", rec))


def test_lock_order_cycle_raises():
    from ape_x_dqn_tpu.obs.health import LockOrderError
    a, b, _ = _witness_pair()
    with a:
        with b:
            pass
    with pytest.raises(LockOrderError) as ei:
        with b:
            with a:  # pragma: no cover - raises before entering
                pass
    assert "'A'" in str(ei.value) and "'B'" in str(ei.value)


def test_lock_order_transitive_cycle_raises():
    from ape_x_dqn_tpu.obs.health import LockOrderError
    a, b, c = _witness_pair()
    with a, b:
        pass
    with b, c:
        pass
    with pytest.raises(LockOrderError):
        with c, a:
            pass


def test_lock_order_consistent_is_silent():
    a, b, c = _witness_pair()
    for _ in range(3):
        with a, b, c:
            pass
        with a, c:
            pass
        with b, c:
            pass


def test_lock_order_same_name_self_edge_ignored():
    from ape_x_dqn_tpu.obs.health import LockOrderRecorder, WitnessLock
    rec = LockOrderRecorder()
    x1 = WitnessLock("leaf", rec)
    x2 = WitnessLock("leaf", rec)
    with x1:
        with x2:  # distinct instances, shared name: no self-edge
            pass


def test_make_lock_is_witness_under_tests():
    # conftest sets APEX_LOCK_WITNESS=1 before any package import
    from ape_x_dqn_tpu.obs.health import WitnessLock, make_lock
    lock = make_lock("test.lock")
    assert isinstance(lock, WitnessLock)
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_witness_acquire_release_api():
    from ape_x_dqn_tpu.obs.health import LockOrderRecorder, WitnessLock
    rec = LockOrderRecorder()
    lock = WitnessLock("api", rec)
    assert lock.acquire(blocking=False)
    assert not lock.acquire(blocking=False)  # non-reentrant, held
    lock.release()
    assert not lock.locked()
