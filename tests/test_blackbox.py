"""Flight-recorder + postmortem forensics coverage (ISSUE 17): ring
bounding and drop accounting, the crash/stall/SIGUSR2 dump paths, the
torn-partial tolerance of the bundler, merge ordering across two
real-socket peers with retained telemetry frames, `report
--postmortem` root-cause naming, the `--check` forensics rows, and
the disabled-config no-op contract."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.comm.socket_transport import (
    SocketIngestServer, SocketTransport)
from ape_x_dqn_tpu.configs import ObsConfig
from ape_x_dqn_tpu.obs import postmortem, report
from ape_x_dqn_tpu.obs.blackbox import (
    NULL_BLACKBOX, FlightRecorder, default_peer)
from ape_x_dqn_tpu.obs.core import NULL_OBS, build_obs
from ape_x_dqn_tpu.obs.fleet import (
    FleetAggregator, StampingTransport, TelemetryEmitter)
from ape_x_dqn_tpu.obs.health import StallError
from ape_x_dqn_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Sink:
    """Minimal obs facade: the recorder only needs .count."""

    def __init__(self):
        self.ctr: dict[str, int] = {}

    def count(self, name, n=1):
        self.ctr[name] = self.ctr.get(name, 0) + n


def _experience_batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {"obs": rng.random((n, 4)).astype(np.float32),
            "action": rng.integers(0, 2, (n,)).astype(np.int32),
            "priorities": (rng.random(n) + 0.1).astype(np.float32),
            "actor": 0, "frames": n}


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


# -- ring bounding ----------------------------------------------------------

def test_ring_bounds_and_drop_accounting(tmp_path):
    """50 records through a capacity-8 ring: the dump holds exactly
    the LAST 8, the 42 overwrites are counted as drops, and the
    published counters agree with the ring's own accounting."""
    sink = _Sink()
    rec = FlightRecorder(sink, peer="p0", out_dir=str(tmp_path),
                         capacity=8)
    for i in range(50):
        rec.record("publish", step=i)
    path = rec.dump("test")
    assert path and os.path.exists(path)
    d = json.load(open(path))
    assert [r["step"] for r in d["records"]] == list(range(42, 50))
    assert d["recorded"] == 50 and d["dropped"] == 42
    assert sink.ctr["blackbox_records"] == 50
    assert sink.ctr["blackbox_dropped"] == 42
    assert sink.ctr["blackbox_dumps"] == 1


def test_dump_payload_is_complete_and_atomic(tmp_path):
    """A dump carries the ring, the log tail, per-thread stacks, and
    provider context — and leaves no .tmp behind."""
    sink = _Sink()
    rec = FlightRecorder(sink, peer="p1", out_dir=str(tmp_path))
    rec.record("wedge", component="sender-0")
    rec.log_line("last words")
    rec.add_context_provider(lambda: {"transport": {"reconnects": 3}})
    path = rec.dump("sigusr2", component="sender-0", step=7,
                    extra={"note": "drill"})
    d = json.load(open(path))
    assert d["blackbox"] == 1 and d["peer"] == "p1"
    assert d["reason"] == "sigusr2" and d["step"] == 7
    assert d["records"][0]["kind"] == "wedge"
    assert d["records"][0]["component"] == "sender-0"
    assert d["log_tail"][-1][1] == "last words"
    assert d["transport"] == {"reconnects": 3}
    assert d["extra"] == {"note": "drill"}
    # every live thread contributes a stack snapshot
    assert threading.current_thread().name in d["threads"]
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    assert default_peer().endswith(f"-{os.getpid()}")


# -- crash paths ------------------------------------------------------------

def test_stall_error_archives_the_ring(tmp_path):
    """check_stalled: the StallError is dumped (reason=stall, the
    stale component named) BEFORE the obs closes and the error
    propagates — and the run JSONL cross-references the dump so
    `report --check`'s forensics row can demand it."""
    jsonl = str(tmp_path / "run.jsonl")
    metrics = Metrics(log_path=jsonl)
    obs = build_obs(ObsConfig(enabled=True, heartbeat_timeout_s=0.05,
                              blackbox_dir=str(tmp_path)), metrics)
    obs.beat("learner", "step 3")
    time.sleep(0.12)
    with pytest.raises(StallError):
        obs.check_stalled()
    metrics.close()
    dump_path = obs.blackbox.path
    assert os.path.exists(dump_path)
    d = json.load(open(dump_path))
    assert d["reason"] == "stall" and d["component"] == "learner"
    assert any(r["kind"] == "stall" for r in d["records"])
    recs = [json.loads(l) for l in open(jsonl)]
    s = report.summarize(recs)
    assert s["stalls"] and s["blackbox_dumps"]
    assert s["blackbox_dumps"][0]["path"] == dump_path
    # dump on disk: the forensics row is satisfied
    assert not [v for v in report.check_violations(s)
                if v.startswith("blackbox_dumps")]


def test_unhandled_crash_dumps_via_excepthook(tmp_path):
    """A raising loop in a real child process: the chained excepthook
    archives the ring with the exception type as the component and
    the traceback in extra, then the process still dies nonzero."""
    code = (
        "from ape_x_dqn_tpu.obs.blackbox import FlightRecorder\n"
        "class S:\n"
        "    def count(self, name, n=1): pass\n"
        f"rec = FlightRecorder(S(), peer='crasher', "
        f"out_dir={str(tmp_path)!r})\n"
        "rec.install(signals=False)\n"
        "rec.record('actor_error', component='actor-3', error='boom')\n"
        "raise ValueError('boom')\n")
    p = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert p.returncode != 0
    assert "ValueError: boom" in p.stderr  # chained to the default hook
    d = json.load(open(tmp_path / "blackbox-crasher.json"))
    assert d["reason"] == "crash" and d["component"] == "ValueError"
    assert any(r["kind"] == "crash" for r in d["records"])
    assert any("boom" in line for line in d["extra"]["traceback"])


@pytest.mark.skipif(not hasattr(signal, "SIGUSR2"),
                    reason="no SIGUSR2 on this platform")
def test_sigusr2_dumps_live_without_dying(tmp_path):
    """The live 'explain yourself' path: SIGUSR2 dumps the ring and
    the process keeps running; uninstall restores the old handler."""
    if threading.current_thread() is not threading.main_thread():
        pytest.skip("signal installation needs the main thread")
    sink = _Sink()
    rec = FlightRecorder(sink, peer="live", out_dir=str(tmp_path))
    prev = signal.getsignal(signal.SIGUSR2)
    rec.install()
    try:
        rec.record("publish", step=1)
        os.kill(os.getpid(), signal.SIGUSR2)
        assert _wait(lambda: os.path.exists(rec.path))
        d = json.load(open(rec.path))
        assert d["reason"] == "sigusr2"
        assert any(r["kind"] == "sigusr2" for r in d["records"])
    finally:
        rec.uninstall()
    assert signal.getsignal(signal.SIGUSR2) == prev


# -- bundler ----------------------------------------------------------------

def test_torn_partial_is_skipped_counted_and_named(tmp_path):
    """A kill mid-dump leaves a torn file (and maybe a stray .tmp):
    the bundler skips BOTH, counts them, names them — and still
    bundles the good dumps."""
    sink = _Sink()
    rec = FlightRecorder(sink, peer="good", out_dir=str(tmp_path))
    rec.record("wedge", component="sender-0")
    rec.dump("drill")
    (tmp_path / "blackbox-torn.json").write_text('{"peer": "torn", ')
    (tmp_path / "blackbox-killed.json.tmp").write_text('{"pe')
    bundle = postmortem.build_bundle(
        str(tmp_path), out_path=str(tmp_path / "POSTMORTEM.json"),
        obs=sink)
    assert [d["peer"] for d in bundle["dumps"]] == ["good"]
    skipped = {s["file"]: s["reason"] for s in bundle["skipped_dumps"]}
    assert skipped["blackbox-torn.json"] == "truncated/unparseable"
    assert "incomplete" in skipped["blackbox-killed.json.tmp"]
    assert sink.ctr["postmortem_bundles"] == 1
    ondisk = json.load(open(bundle["path"]))
    assert ondisk["postmortem"] == 1
    assert len(ondisk["skipped_dumps"]) == 2


def test_bundle_merges_two_socket_peers_in_causal_order(tmp_path):
    """Two actor hosts over REAL loopback sockets, each with its own
    flight recorder; the learner's aggregator retains their last
    telemetry frames. The bundle merges dumps + run JSONL + frames
    into one wall-clock-sorted timeline, and the root-cause walk
    blames peer A's wedge for peer B's later terminal error."""
    jsonl = str(tmp_path / "run.jsonl")
    learner_metrics = Metrics(log_path=jsonl)
    learner_obs = build_obs(
        ObsConfig(enabled=True, heartbeat_timeout_s=0.0,
                  blackbox_dir=str(tmp_path)), learner_metrics)
    server = SocketIngestServer("127.0.0.1", 0)
    agg = FleetAggregator(learner_obs)
    assert agg.install(server)
    peers = ["hostA-1-a0", "hostB-2-a1"]
    actors = []
    try:
        for name in peers:
            actor_obs = build_obs(
                ObsConfig(enabled=True, heartbeat_timeout_s=0.0,
                          blackbox_dir=str(tmp_path)), Metrics())
            actor_obs.blackbox.set_peer(name)
            client = SocketTransport("127.0.0.1", server.port)
            stamper = StampingTransport(client, name)
            emitter = TelemetryEmitter(stamper, actor_obs, name,
                                       interval_s=0)
            stamper.send_experience(_experience_batch())
            assert server.recv_experience(timeout=5.0) is not None
            assert emitter.pump_once()
            actors.append((actor_obs, client))
        assert _wait(lambda: sorted(agg.peers) == peers)
        # the incident: A wedges, then B dies — each archives its ring
        obs_a, obs_b = actors[0][0], actors[1][0]
        obs_a.blackbox.record("wedge", component="sender-0")
        assert obs_a.blackbox.dump("supervisor_request")
        time.sleep(0.05)
        obs_b.blackbox.record("actor_error", component="actor-1",
                              error="RuntimeError('dead')")
        assert obs_b.blackbox.dump("actor_error", component="actor-1")
        frames = agg.retained_frames()
        assert sorted(frames) == peers
        for st in frames.values():
            assert isinstance(st["frame"], dict)
            assert st["recv_unix"] > 0 and st["connected"]
        bundle = postmortem.build_bundle(
            str(tmp_path), jsonl_path=jsonl, frames=frames,
            out_path=str(tmp_path / "POSTMORTEM.json"),
            obs=learner_obs)
    finally:
        for actor_obs, client in actors:
            client.close()
        server.stop()
        for actor_obs, client in actors:
            actor_obs.close()
        learner_obs.close()
        learner_metrics.close()
    assert sorted(bundle["peers"]) == peers
    ts = [e["t"] for e in bundle["timeline"]]
    assert ts == sorted(ts)
    kinds = {(e["kind"], e["peer"]) for e in bundle["timeline"]}
    assert ("telemetry_frame", peers[0]) in kinds
    assert ("telemetry_frame", peers[1]) in kinds
    root = report.postmortem_root_cause(bundle)
    assert root["terminal"]["kind"] == "actor_error"
    assert root["terminal"]["peer"] == peers[1]
    assert root["anomaly"]["kind"] == "wedge"
    assert root["anomaly"]["component"] == "sender-0"
    assert root["gap_s"] > 0


# -- report --postmortem ----------------------------------------------------

def test_report_postmortem_names_root_cause(tmp_path, capsys):
    """The CLI on a synthetic bundle: the inventory names the skipped
    partial, and the final line walks back from the terminal
    quarantine to the wedge that preceded it."""
    sink = _Sink()
    rec_a = FlightRecorder(sink, peer="actor-7", out_dir=str(tmp_path))
    rec_a.record("wedge", component="sender-0")
    rec_a.dump("supervisor_request")
    time.sleep(0.02)
    rec_d = FlightRecorder(sink, peer="driver-1", out_dir=str(tmp_path))
    rec_d.record("quarantine", component="actor-7", staleness_s=9.0)
    rec_d.dump("quarantine", component="actor-7")
    (tmp_path / "blackbox-torn.json").write_text('{"peer": "to')
    bpath = str(tmp_path / "POSTMORTEM.json")
    postmortem.build_bundle(str(tmp_path), out_path=bpath)
    assert report.main([bpath, "--postmortem"]) == 0
    out = capsys.readouterr().out
    assert "skipped dump: blackbox-torn.json" in out
    last = out.strip().splitlines()[-1]
    assert last.startswith("root cause:")
    assert "wedge" in last and "component=sender-0" in last
    assert "quarantine" in last and "component=actor-7" in last
    # --json mode: machine-checkable attribution for the chaos lane
    assert report.main([bpath, "--postmortem", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["root_cause"]["anomaly"]["component"] == "sender-0"
    assert doc["dumps"] == 2 and len(doc["skipped_dumps"]) == 1


# -- --check forensics rows -------------------------------------------------

def test_check_demands_dump_for_terminal_events(tmp_path):
    """A terminal quarantine with NO black-box dump on disk fails
    --check naming the component; the same stream plus a dump that
    exists passes the forensics row."""
    recs = [{"step": 1, "time": 1.0, "actor_quarantined": 3,
             "stall_staleness_s": 7.0}]
    v = [x for x in report.check_violations(report.summarize(recs))
         if x.startswith("blackbox_dumps")]
    assert len(v) == 1 and "quarantine:actor-3" in v[0]
    dump = tmp_path / "blackbox-driver-1.json"
    dump.write_text("{}")
    recs.append({"step": 1, "time": 1.1,
                 "blackbox_dump": str(dump),
                 "blackbox_reason": "quarantine",
                 "blackbox_peer": "driver-1",
                 "blackbox_component": "actor-3"})
    assert not [x for x in
                report.check_violations(report.summarize(recs))
                if x.startswith("blackbox_dumps")]


def test_check_flags_dump_that_lost_its_window(tmp_path):
    """Per-dump ring-drop row: a dump that overwrote most of its ring
    before dumping is flagged; normal steady-state overwriting on a
    healthy dump is not."""
    dump = tmp_path / "blackbox-p.json"
    dump.write_text("{}")
    base = {"step": 1, "time": 1.0, "blackbox_dump": str(dump),
            "blackbox_reason": "stall"}
    lossy = dict(base, blackbox_ring_recorded=100,
                 blackbox_ring_dropped=80)
    v = [x for x in report.check_violations(report.summarize([lossy]))
         if x.startswith("blackbox_dropped")]
    assert len(v) == 1 and "blackbox_capacity" in v[0]
    healthy = dict(base, blackbox_ring_recorded=100,
                   blackbox_ring_dropped=20)
    assert not [x for x in
                report.check_violations(report.summarize([healthy]))
                if x.startswith("blackbox_dropped")]


# -- disabled contract ------------------------------------------------------

def test_disabled_blackbox_is_a_noop(tmp_path):
    """ObsConfig.blackbox=False: the facade carries NULL_BLACKBOX —
    recording and dumping do nothing, no files appear, and the
    config-off contract matches NULL_OBS (build_obs(None, ...))."""
    obs = build_obs(ObsConfig(enabled=True, blackbox=False,
                              blackbox_dir=str(tmp_path)), Metrics())
    assert obs.blackbox is NULL_BLACKBOX
    obs.blackbox.record("wedge", component="x")
    obs.blackbox.log_line("nope")
    assert obs.blackbox.dump("test") is None
    obs.blackbox.install()
    obs.publish(1)  # the publish anchor must not revive the recorder
    obs.close()
    assert not [f for f in os.listdir(tmp_path)
                if f.startswith("blackbox-")]
    assert NULL_OBS.blackbox is NULL_BLACKBOX
    assert build_obs(None, Metrics()) is NULL_OBS
