"""R2D2 runtime: recurrent actor, sequence learner, and the full driver
wiring over stored-state sequence replay (SURVEY.md §2.1 config 4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, NetworkConfig,
    ParallelConfig, ReplayConfig, get_config)
from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.envs import make_env
from ape_x_dqn_tpu.models import ApeXLSTMQNet
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.replay.sequence import (
    SequenceBuilder, sequence_item_spec, split_priorities)
from ape_x_dqn_tpu.runtime.actor import RecurrentActor
from ape_x_dqn_tpu.runtime.driver import ApexDriver
from ape_x_dqn_tpu.runtime.sequence_learner import SequenceLearner


def _r2d2_cfg(num_actors=2, lstm=32, seq=16, overlap=8, burn_in=4):
    return get_config("r2d2").replace(
        env=EnvConfig(id="CartPolePO", kind="cartpole_po"),
        network=NetworkConfig(kind="lstm_q", lstm_size=lstm, torso_dense=64,
                              dueling=True, compute_dtype="float32"),
        replay=ReplayConfig(kind="sequence", capacity=512, seq_length=seq,
                            seq_overlap=overlap, burn_in=burn_in,
                            min_fill=32, priority_eta=0.9),
        learner=LearnerConfig(batch_size=16, n_step=3, value_rescale=True,
                              target_sync_every=100, lr=1e-3,
                              publish_every=25, train_chunk=4),
        actors=ActorConfig(num_actors=num_actors, base_eps=0.4,
                           ingest_batch=64),
        inference=InferenceConfig(max_batch=8, deadline_ms=1.0),
        parallel=ParallelConfig(dp=1, tp=1),
        eval_every_steps=0,
    )


def test_masked_cartpole_hides_velocities():
    env = make_env(EnvConfig(kind="cartpole_po"), seed=0)
    obs = env.reset()
    assert obs.shape == (2,)
    obs2, r, done, info = env.step(1)
    assert obs2.shape == (2,) and r == 1.0


def test_sequence_builder_actor_side_priority():
    sb = SequenceBuilder(seq_len=4, overlap=0, lstm_size=2,
                         priority_eta=0.9)
    pre = (np.zeros(2), np.zeros(2))
    out = []
    for t, td in enumerate([1.0, 2.0, 3.0, 4.0]):
        out += sb.append(np.array([t]), t, 0.0, False, pre, td=td)
    assert len(out) == 1
    # eta-mix: 0.9*max + 0.1*mean = 0.9*4 + 0.1*2.5
    np.testing.assert_allclose(out[0]["priority"], 0.9 * 4 + 0.1 * 2.5)
    items, pris = split_priorities(out)
    assert "priority" not in items[0]
    np.testing.assert_allclose(pris, [out[0]["priority"]])


def test_recurrent_actor_ships_sequences():
    cfg = _r2d2_cfg(num_actors=1, seq=8, overlap=4)
    transport = LoopbackTransport()
    lstm = cfg.network.lstm_size

    def query_fn(inp):
        # fake recurrent net: state accumulates, q fixed
        return {"q": np.array([0.1, 0.2], np.float32),
                "c": np.asarray(inp["c"]) + 1.0,
                "h": np.asarray(inp["h"]) + 1.0}

    actor = RecurrentActor(cfg, 0, query_fn, transport)
    frames = actor.run(max_frames=100)
    assert frames == 100
    batches, total = [], 0
    while True:
        b = transport.recv_experience(timeout=0.01)
        if b is None:
            break
        batches.append(b)
        total += len(b["priorities"])
    assert batches, "actor shipped nothing"
    b0 = batches[0]
    seq = cfg.replay.seq_length
    assert b0["obs"].shape[1:] == (seq, 2)
    assert b0["actions"].shape[1:] == (seq,)
    assert b0["init_c"].shape[1:] == (lstm,)
    assert (b0["priorities"] > 0).all()
    assert (b0["mask"].sum(axis=1) >= 1).all()
    # frames are accounted separately from sequence counts
    assert sum(b["frames"] for b in batches) == 100
    # init states advance with the fake recurrence except at episode
    # starts (zeros)
    assert any(np.any(b["init_c"] != 0) for b in batches)


def test_sequence_learner_trains_and_updates_priorities():
    cfg = _r2d2_cfg()
    net = ApeXLSTMQNet(num_actions=2, lstm_size=8, dense=16,
                       compute_dtype="float32", mlp_torso=True)
    z = jnp.zeros((1, 8), jnp.float32)
    params = net.init(jax.random.key(0),
                      jnp.zeros((1, 4, 2), jnp.float32), (z, z))
    replay = PrioritizedReplay(capacity=64)
    spec = sequence_item_spec((2,), np.float32, 4, 8)
    lcfg = cfg.learner.__class__(batch_size=8, n_step=2, value_rescale=True,
                                 target_sync_every=10, lr=1e-3)
    rcfg = cfg.replay.__class__(seq_length=4, burn_in=1)
    learner = SequenceLearner(lambda p, o, s: net.apply(p, o, s),
                              replay, lcfg, rcfg)
    state = learner.init(params, replay.init(spec), jax.random.key(1))
    rng = np.random.default_rng(0)
    items = {
        "obs": jnp.asarray(rng.normal(size=(16, 4, 2)), jnp.float32),
        "actions": jnp.asarray(rng.integers(0, 2, (16, 4)), jnp.int32),
        "rewards": jnp.asarray(rng.normal(size=(16, 4)), jnp.float32),
        "terminals": jnp.zeros((16, 4), jnp.float32),
        "mask": jnp.ones((16, 4), jnp.float32),
        "init_c": jnp.zeros((16, 8), jnp.float32),
        "init_h": jnp.zeros((16, 8), jnp.float32),
    }
    state = learner.add(state, items, jnp.ones(16))
    assert int(state.replay.size) == 16
    tree_before = np.asarray(state.replay.tree).copy()
    state, m = learner.train_step(state)
    assert np.isfinite(m["loss"])
    assert int(state.step) == 1
    # priorities were written back into the sum-tree
    assert not np.allclose(np.asarray(state.replay.tree), tree_before)
    state, m = learner.train_many(state, 3)
    assert int(state.step) == 4
    assert np.isfinite(m["loss"]) and m["valid_frac"] > 0


def test_r2d2_driver_end_to_end():
    """Full recurrent wiring: recurrent actors -> batched stateful
    inference -> sequence ingest -> sequence learner -> recurrent eval."""
    cfg = _r2d2_cfg(num_actors=2).replace(eval_every_steps=50,
                                          eval_episodes=2)
    driver = ApexDriver(cfg)
    assert driver.family == "r2d2"
    out = driver.run(total_env_frames=2500, max_grad_steps=60,
                     wall_clock_limit_s=240)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 60, out
    assert out["frames"] >= 100, out
    assert out["episodes"] > 0
    assert driver.server.params_version > 0
    # the guaranteed end-of-training eval ran with the recurrent policy
    assert out["eval"] is not None and out["eval"]["episodes"] > 0


def test_r2d2_dist_driver_end_to_end():
    """Distributed R2D2 (SURVEY.md §2.1 config 4 attests dp=4 x tp=2):
    sequence-replay shards + LSTM sequence loss over the virtual
    8-device mesh, sequence round-robin ingest, replicated publication."""
    from ape_x_dqn_tpu.parallel.dist_learner import DistSequenceLearner

    cfg = _r2d2_cfg(num_actors=2).replace(
        parallel=ParallelConfig(dp=4, tp=2))
    driver = ApexDriver(cfg)
    assert driver.is_dist and driver.family == "r2d2"
    assert isinstance(driver.learner, DistSequenceLearner)
    out = driver.run(total_env_frames=2500, max_grad_steps=40,
                     wall_clock_limit_s=240)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 40, out
    assert driver.server.params_version > 0
    # every dp shard of the sequence replay received sequences
    sizes = np.asarray(driver.state.replay.size)
    assert sizes.shape == (4,) and (sizes > 0).all(), sizes


def _fake_pixel_episode(length, stack=4, h=6, w=6, seed=0):
    """Sliding-stack observations like the Atari wrapper produces:
    frame log [0]*3 + [f0, f1, ...]; obs_t = log[t:t+stack]."""
    rng = np.random.default_rng(seed)
    log = [np.zeros((h, w), np.uint8)] * (stack - 1)
    log += [rng.integers(0, 255, (h, w)).astype(np.uint8)
            for _ in range(length + 1)]
    return [np.stack(log[t:t + stack], axis=-1) for t in range(length + 1)]


def test_sequence_builder_frame_mode_matches_stacked():
    """Feeding the same episode, the frame-mode builder's sequences
    reconstruct to exactly the stacked builder's obs arrays."""
    from ape_x_dqn_tpu.replay.sequence import batch_to_sequence_batch

    seq, overlap, stack = 8, 4, 4
    flat_b = SequenceBuilder(seq, overlap, lstm_size=2)
    ring_b = SequenceBuilder(seq, overlap, lstm_size=2, frame_mode=True)
    obs_seq = _fake_pixel_episode(21, stack=stack)
    pre = (np.zeros(2, np.float32), np.zeros(2, np.float32))
    flat_items, ring_items = [], []
    for t in range(21):
        end = t == 20
        flat_items += flat_b.append(obs_seq[t], t % 4, 1.0, end, pre,
                                    td=1.0)
        ring_items += ring_b.append(obs_seq[t], t % 4, 1.0, end, pre,
                                    td=1.0)
    assert len(flat_items) == len(ring_items) > 1
    for fi, ri in zip(flat_items, ring_items):
        assert "obs" not in ri and "seq_frames" in ri
        assert ri["seq_frames"].shape == (seq + stack - 1, 6, 6)
        np.testing.assert_array_equal(fi["actions"], ri["actions"])
        np.testing.assert_array_equal(fi["mask"], ri["mask"])
        # device-side reconstruction == stacked storage, on live steps
        batch = {k: jnp.asarray(v)[None] for k, v in ri.items()
                 if k != "priority"}
        rebuilt = np.asarray(batch_to_sequence_batch(batch).obs[0])
        live = fi["mask"].astype(bool)
        np.testing.assert_array_equal(rebuilt[live], fi["obs"][live])


def test_r2d2_driver_end_to_end_frame_sequences_dist():
    """The full flagship R2D2 layout: pixel CNN-torso LSTM on the
    synthetic Atari env, FRAME-MODE sequence storage, sharded over the
    dp=4 x tp=2 virtual mesh — single-frame sequences round-robin
    through dist ingest, stacks rebuilt inside the sharded sequence-
    learner jit."""
    cfg = get_config("r2d2").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari", resize=42,
                      max_noop_start=4),
        network=NetworkConfig(kind="lstm_q", lstm_size=32, torso_dense=64,
                              dueling=True, compute_dtype="float32"),
        replay=ReplayConfig(kind="sequence", capacity=256, seq_length=16,
                            seq_overlap=8, burn_in=4, min_fill=16,
                            storage="frame_ring"),
        learner=LearnerConfig(batch_size=8, n_step=3, value_rescale=True,
                              target_sync_every=100, lr=1e-3,
                              publish_every=10, train_chunk=2),
        actors=ActorConfig(num_actors=1, base_eps=0.4, ingest_batch=32),
        inference=InferenceConfig(max_batch=4, deadline_ms=1.0),
        parallel=ParallelConfig(dp=4, tp=2),
        eval_every_steps=0, eval_episodes=0,
    )
    driver = ApexDriver(cfg)
    assert driver.family == "r2d2" and driver.is_dist
    assert not driver._frame_mode  # segment staging is flat-family-only
    assert "seq_frames" in driver._item_keys
    out = driver.run(total_env_frames=1600, max_grad_steps=10,
                     wall_clock_limit_s=300)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 10, out
    assert driver.server.params_version > 0
    sizes = np.asarray(driver.state.replay.size)
    assert sizes.shape == (4,) and (sizes > 0).all(), sizes


def test_r2d2_frame_sequences_reject_vector_obs():
    """The frame_ring r2d2 preset on a vector-obs env must fail with a
    clear message at driver construction, not an unpack crash."""
    cfg = _r2d2_cfg()
    cfg = cfg.replace(replay=dataclasses.replace(cfg.replay,
                                                 storage="frame_ring"))
    with pytest.raises(ValueError, match="pixel obs"):
        ApexDriver(cfg)


@pytest.mark.slow
def test_r2d2_improves_masked_cartpole():
    """Reward slope on the POMDP task: the recurrent agent must beat the
    random plateau (~22 per episode) by a clear margin. Measured
    dynamics: behaviour avg return reaches ~60-70 inside 7 wall-clock
    minutes on the CPU test harness."""
    cfg = _r2d2_cfg(num_actors=2, lstm=64).replace(
        eval_every_steps=0, eval_episodes=10, total_env_frames=40_000)
    driver = ApexDriver(cfg)
    out = driver.run(max_grad_steps=10**9, wall_clock_limit_s=480)
    assert out["actor_errors"] == [] and out["loop_errors"] == []
    # the greedy recurrent eval is high-variance on this tiny task (single
    # episodes span 9..500); 10 episodes + a margin over the untrained
    # plateau (~22) keeps the slope assertion robust
    assert out["eval"] is not None
    assert out["eval"]["mean_return"] > 35, out["eval"]


def _seq_learner_with_items(sample_chunk=1, n_items=64, seed=0,
                            sample_prefetch=False):
    """Small SequenceLearner + filled replay for mechanics tests."""
    net = ApeXLSTMQNet(num_actions=2, lstm_size=8, dense=16,
                       compute_dtype="float32", mlp_torso=True)
    z = jnp.zeros((1, 8), jnp.float32)
    params = net.init(jax.random.key(0),
                      jnp.zeros((1, 4, 2), jnp.float32), (z, z))
    replay = PrioritizedReplay(capacity=128)
    spec = sequence_item_spec((2,), np.float32, 4, 8)
    lcfg = LearnerConfig(batch_size=8, n_step=2, value_rescale=True,
                         target_sync_every=3, lr=1e-3,
                         sample_chunk=sample_chunk,
                         sample_prefetch=sample_prefetch)
    rcfg = ReplayConfig(kind="sequence", seq_length=4, burn_in=1)
    learner = SequenceLearner(lambda p, o, s: net.apply(p, o, s),
                              replay, lcfg, rcfg)
    state = learner.init(params, replay.init(spec), jax.random.key(1))
    rng = np.random.default_rng(seed)
    items = {
        "obs": jnp.asarray(rng.normal(size=(n_items, 4, 2)), jnp.float32),
        "actions": jnp.asarray(rng.integers(0, 2, (n_items, 4)), jnp.int32),
        "rewards": jnp.asarray(rng.normal(size=(n_items, 4)), jnp.float32),
        "terminals": jnp.zeros((n_items, 4), jnp.float32),
        "mask": jnp.ones((n_items, 4), jnp.float32),
        "init_c": jnp.zeros((n_items, 8), jnp.float32),
        "init_h": jnp.zeros((n_items, 8), jnp.float32),
    }
    state = learner.add(
        state, items,
        jnp.asarray(rng.random(n_items) + 0.1, jnp.float32))
    return learner, state


def test_sequence_kbatch_train_many_mechanics():
    """sample_chunk=K on the SequenceLearner (round-5 verdict item 5):
    one stratified K*B sequence sample + one priority write-back per K
    grad-steps; step counts, the remainder path, target sync inside the
    macro-step, and tree repair must all hold — mirroring
    test_runtime.test_kbatch_train_many_mechanics for flat DQN."""
    learner, state = _seq_learner_with_items(sample_chunk=4)
    tree_before = np.asarray(state.replay.tree).copy()

    state, m = learner.train_many(state, 8)   # pure macro-steps
    assert int(state.step) == 8
    assert np.isfinite(m["loss"]) and m["valid_frac"] > 0
    assert np.asarray(state.replay.tree)[1] != tree_before[1]

    state, m = learner.train_many(state, 10)  # 2 exact + 2 macro-steps
    assert int(state.step) == 18
    assert np.isfinite(m["loss"])

    # step 18 is a sync boundary (sync_every=3): targets == online
    t = jax.tree.leaves(jax.tree.map(np.asarray, state.target_params))
    p = jax.tree.leaves(jax.tree.map(np.asarray, state.params))
    for a, b in zip(t, p):
        np.testing.assert_array_equal(a, b)


def test_sequence_kbatch_determinism():
    """Same seed, same params through the sequence K-batch path."""
    def run():
        learner, state = _seq_learner_with_items(sample_chunk=4, seed=3)
        state, _ = learner.train_many(state, 12)
        return jax.tree.map(np.asarray, state.params)
    a, b = run(), run()
    jax.tree.map(np.testing.assert_array_equal, a, b)


def test_sequence_prefetch_train_many_mechanics():
    """sample_prefetch on the SequenceLearner: the double-buffered
    train_many pipeline (next chunk's sequence sample drawn before this
    chunk's priority write-back) holds the same step-count, remainder,
    and sync-boundary contract as the fused K-batch path, and its first
    macro-step is bit-identical to train_step_k (the prologue draw sees
    the same priorities the fused path would)."""
    learner, state = _seq_learner_with_items(sample_chunk=4,
                                             sample_prefetch=True)
    tree_before = np.asarray(state.replay.tree).copy()

    state, m = learner.train_many(state, 8)   # pure macro-steps
    assert int(state.step) == 8
    assert np.isfinite(m["loss"]) and m["valid_frac"] > 0
    assert np.asarray(state.replay.tree)[1] != tree_before[1]

    state, m = learner.train_many(state, 10)  # 2 exact + 2 macro-steps
    assert int(state.step) == 18
    assert np.isfinite(m["loss"])

    # step 18 is a sync boundary (sync_every=3): targets == online
    t = jax.tree.leaves(jax.tree.map(np.asarray, state.target_params))
    p = jax.tree.leaves(jax.tree.map(np.asarray, state.params))
    for a, b in zip(t, p):
        np.testing.assert_array_equal(a, b)

    # first-macro equivalence against the fused path
    l1, s1 = _seq_learner_with_items(sample_chunk=4, seed=2,
                                     sample_prefetch=True)
    l2, s2 = _seq_learner_with_items(sample_chunk=4, seed=2)
    s1, _ = l1.train_many(s1, 4)
    s2, _ = l2.train_step_k(s2, 4)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        s1.params, s2.params)
    np.testing.assert_array_equal(np.asarray(s1.replay.tree),
                                  np.asarray(s2.replay.tree))


def test_sequence_prefetch_determinism():
    """Same seed, same params through the sequence prefetch pipeline."""
    def run():
        learner, state = _seq_learner_with_items(sample_chunk=4, seed=3,
                                                 sample_prefetch=True)
        state, _ = learner.train_many(state, 12)
        return jax.tree.map(np.asarray, state.params)
    a, b = run(), run()
    jax.tree.map(np.testing.assert_array_equal, a, b)


@pytest.mark.slow
def test_r2d2_improves_masked_cartpole_prefetch():
    """Learning parity for the double-buffered sampler on the recurrent
    family: with sample_chunk=4 + sample_prefetch=True the masked
    CartPole agent must clear the same eval bar as the exact path
    (test_r2d2_improves_masked_cartpole) — the one-dispatch priority
    staleness must not cost learning on the POMDP task."""
    cfg = _r2d2_cfg(num_actors=2, lstm=64).replace(
        eval_every_steps=0, eval_episodes=10, total_env_frames=40_000)
    cfg = cfg.replace(learner=dataclasses.replace(
        cfg.learner, sample_chunk=4, sample_prefetch=True))
    driver = ApexDriver(cfg)
    out = driver.run(max_grad_steps=10**9, wall_clock_limit_s=480)
    assert out["actor_errors"] == [] and out["loop_errors"] == []
    assert out["eval"] is not None
    assert out["eval"]["mean_return"] > 35, out["eval"]


def test_dist_sequence_kbatch_train_step_k():
    """K-batch mechanics on the DIST sequence learner (round-4 advisor
    finding: DistSequenceLearner inherited the K path with no test):
    the dp=4 x tp=2 driver trains with sample_chunk=4 through
    train_many, steps count correctly, and every shard's tree is
    repaired."""
    from ape_x_dqn_tpu.parallel.dist_learner import DistSequenceLearner

    cfg = _r2d2_cfg(num_actors=2).replace(
        parallel=ParallelConfig(dp=4, tp=2))
    cfg = cfg.replace(learner=dataclasses.replace(cfg.learner,
                                                  sample_chunk=4))
    driver = ApexDriver(cfg)
    assert isinstance(driver.learner, DistSequenceLearner)
    out = driver.run(total_env_frames=2500, max_grad_steps=40,
                     wall_clock_limit_s=240)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 40, out
    sizes = np.asarray(driver.state.replay.size)
    assert sizes.shape == (4,) and (sizes > 0).all(), sizes
