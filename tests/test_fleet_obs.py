"""Fleet telemetry plane coverage (ISSUE 6): per-peer obs snapshots
over MSG_TELEMETRY, cross-process trace correlation via stamped
batch_ids, remote stall attribution through re-beaten heartbeat ages,
disconnect attribution, and the old-peer negotiation fallbacks — all
over REAL loopback sockets where the wire is involved.

The epoch-handshake interop matrix (ISSUE 7) lives at the bottom:
old client vs new server, new client vs old server, and a mid-run
epoch bump — both directions must keep ingest and param pulls
flowing against a pre-epoch build."""

import json
import pickle
import socket as socket_mod
import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.comm.socket_transport import (
    MSG_HELLO, MSG_HELLO_ACK, MSG_PARAMS, MSG_PARAMS_REQ,
    SocketIngestServer, SocketTransport, _recv_msg, _send_msg)
from ape_x_dqn_tpu.configs import ObsConfig
from ape_x_dqn_tpu.obs.core import build_obs
from ape_x_dqn_tpu.obs.fleet import (
    FleetAggregator, StampingTransport, TelemetryEmitter, build_frame)
from ape_x_dqn_tpu.obs.health import StallError
from ape_x_dqn_tpu.obs.report import format_report, summarize
from ape_x_dqn_tpu.obs.trace import load_trace
from ape_x_dqn_tpu.utils.metrics import Metrics

PEER = "hostA-1234-a0"


def _experience_batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {"obs": rng.random((n, 4)).astype(np.float32),
            "action": rng.integers(0, 2, (n,)).astype(np.int32),
            "priorities": (rng.random(n) + 0.1).astype(np.float32),
            "actor": 0, "frames": n}


def _actor_obs():
    """Actor-host-side obs: in-memory metrics, no trace file (frames
    carry the snapshot; the learner's JSONL is the run artifact)."""
    obs = build_obs(ObsConfig(enabled=True, heartbeat_timeout_s=0.0),
                    Metrics())
    obs.beat("actor-0", "frame 128")
    obs.count("replay_adds", 8)
    obs.observe("infer_latency_ms", 3.0)
    return obs


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


# -- stamping + frame building ---------------------------------------------

def test_stamping_transport_assigns_monotonic_batch_ids():
    shipped = []

    class _Sink:
        def send_experience(self, batch):
            shipped.append(batch)

    st = StampingTransport(_Sink(), PEER)
    for _ in range(3):
        st.send_experience(_experience_batch())
    assert [b["batch_id"] for b in shipped] == [0, 1, 2]
    assert all(b["peer"] == PEER for b in shipped)
    assert st.rows_out == 24
    events = st.drain_events()
    assert [e[3]["batch_id"] for e in events] == [0, 1, 2]
    assert events[0][0] == "actor.ship" and events[0][3]["rows"] == 8
    assert st.drain_events() == []  # drained: the ring was cleared


def test_build_frame_is_json_safe_and_complete():
    obs = _actor_obs()
    frame = build_frame(obs, PEER, 7, events=[["actor.ship", 0.0, 0.1,
                                               {"batch_id": 0}]],
                        rows_out=64)
    json.dumps(frame)  # the wire form: must serialize as-is
    assert frame["peer"] == PEER and frame["seq"] == 7
    assert frame["hb"]["actor-0"][1] == "frame 128"
    assert frame["ctr"]["replay_adds"] == 8.0
    assert frame["hist"]["infer_latency_ms"]["count"] == 1
    assert frame["rows_out"] == 64
    obs.close()


# -- merged JSONL + per-peer report ----------------------------------------

def test_telemetry_frames_merge_into_single_run_jsonl(tmp_path):
    """Acceptance bar: a remote peer over a real socket lands in the
    learner's ONE JSONL as peer/<id>/ rows, and the report prints a
    per-peer stage breakdown with ingest rate and heartbeat ages."""
    jsonl = str(tmp_path / "run.jsonl")
    learner_metrics = Metrics(log_path=jsonl)
    learner_obs = build_obs(
        ObsConfig(enabled=True, heartbeat_timeout_s=0.0), learner_metrics)
    server = SocketIngestServer("127.0.0.1", 0)
    agg = FleetAggregator(learner_obs)
    assert agg.install(server)

    actor_obs = _actor_obs()
    client = SocketTransport("127.0.0.1", server.port)
    stamper = StampingTransport(client, PEER)
    emitter = TelemetryEmitter(stamper, actor_obs, PEER, interval_s=0)
    try:
        stamper.send_experience(_experience_batch())
        assert server.recv_experience(timeout=5.0) is not None
        assert emitter.pump_once()  # negotiated on first contact
        assert _wait(lambda: server.telemetry_frames >= 1)
        assert _wait(lambda: agg.peers == [PEER])
        time.sleep(0.05)
        assert emitter.pump_once()  # second frame: rate delta defined
        assert _wait(lambda: server.telemetry_frames >= 2)
        # remote heartbeats re-beaten into the learner's registry
        ages = learner_obs.heartbeats.ages()
        assert PEER in ages and f"{PEER}/actor-0" in ages
    finally:
        client.close()
        server.stop()
        actor_obs.close()
        learner_obs.close()
        learner_metrics.close()

    recs = [json.loads(l) for l in open(jsonl)]
    frames = [r for r in recs if f"peer/{PEER}/seq" in r]
    assert len(frames) >= 2
    assert frames[-1][f"peer/{PEER}/ctr/replay_adds"] == 8.0
    assert frames[-1][f"peer/{PEER}/hist/infer_latency_ms"]["count"] == 1
    assert f"peer/{PEER}/gauge/ingest_rate" in frames[-1]
    assert f"peer/{PEER}/hb/actor-0" in frames[-1]
    s = summarize(recs)
    assert PEER in s["peers"]
    text = format_report(s)
    assert "fleet peers" in text and PEER in text
    assert "ingest rate" in text and "heartbeat ages" in text


# -- cross-process trace correlation ---------------------------------------

def test_cross_process_trace_shares_batch_id(tmp_path):
    """A transition batch's journey reconstructs as ONE trace: the
    actor's ship event (replayed onto a peer/<id> track) and the
    learner's ingest span carry the same batch_id."""
    trace = str(tmp_path / "trace.json")
    learner_obs = build_obs(
        ObsConfig(enabled=True, trace_path=trace,
                  heartbeat_timeout_s=0.0), Metrics())
    server = SocketIngestServer("127.0.0.1", 0)
    agg = FleetAggregator(learner_obs)
    assert agg.install(server)

    actor_obs = _actor_obs()
    client = SocketTransport("127.0.0.1", server.port)
    stamper = StampingTransport(client, PEER)
    emitter = TelemetryEmitter(stamper, actor_obs, PEER, interval_s=0)
    try:
        stamper.send_experience(_experience_batch())
        got = server.recv_experience(timeout=5.0)
        assert got is not None
        bid = int(got["batch_id"])
        assert got["peer"] == PEER and bid == 0
        # the driver's ingest path stamps this span (runtime/driver.py
        # _ingest_one); here the learner half is written directly
        with learner_obs.span("ingest.batch", batch_id=bid, peer=PEER,
                              rows=8):
            pass
        assert emitter.pump_once()
        assert _wait(lambda: server.telemetry_frames >= 1)
    finally:
        client.close()
        server.stop()
        actor_obs.close()
        learner_obs.close()

    evs = load_trace(trace)["traceEvents"]
    ship = [e for e in evs if e.get("ph") == "X"
            and e["name"] == "actor.ship"]
    ingest = [e for e in evs if e.get("ph") == "X"
              and e["name"] == "ingest.batch"]
    assert ship and ingest
    assert ship[0]["args"]["batch_id"] == ingest[0]["args"]["batch_id"]
    assert ship[0]["args"]["peer"] == PEER
    # the replayed span landed on a labeled synthetic peer track
    tracks = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
    assert f"peer/{PEER}" in tracks


# -- remote stall attribution ----------------------------------------------

def test_wedged_remote_actor_raises_attributed_stall():
    """A peer whose frame reports a stale component heartbeat trips the
    learner's LOCAL watchdog with the fleet-qualified name — a wedged
    remote actor is a named StallError, not silence."""
    obs = build_obs(ObsConfig(enabled=True, heartbeat_timeout_s=1.0),
                    Metrics())
    agg = FleetAggregator(obs)
    agg.on_frame(PEER, {"peer": PEER, "seq": 0,
                        "hb": {"actor-0": [5.0, "frame 9000"]}})
    with pytest.raises(StallError) as ei:
        obs.watchdog.check()
    e = ei.value
    assert e.component == f"{PEER}/actor-0"
    assert e.staleness_s == pytest.approx(5.0, abs=0.5)
    assert "frame 9000" in str(e)


def test_disconnect_is_counted_and_attributed(tmp_path):
    """Killing the actor host mid-run: the server names the peer, the
    aggregator counts it, and the JSONL carries the attribution."""
    jsonl = str(tmp_path / "run.jsonl")
    metrics = Metrics(log_path=jsonl)
    obs = build_obs(ObsConfig(enabled=True, heartbeat_timeout_s=0.0),
                    metrics)
    server = SocketIngestServer("127.0.0.1", 0)
    agg = FleetAggregator(obs)
    assert agg.install(server)
    actor_obs = _actor_obs()
    client = SocketTransport("127.0.0.1", server.port)
    emitter = TelemetryEmitter(client, actor_obs, PEER, interval_s=0)
    try:
        assert emitter.pump_once()
        assert _wait(lambda: server.telemetry_frames >= 1)
        client.close()  # the "kill" — connection drops mid-run
        assert _wait(lambda: server.peer_disconnects >= 1)
        assert _wait(
            lambda: obs.registry.counter("peer_disconnects").value >= 1)
    finally:
        server.stop()
        actor_obs.close()
        obs.close()
        metrics.close()
    recs = [json.loads(l) for l in open(jsonl)]
    assert any(r.get("peer_disconnect") == PEER for r in recs)
    s = summarize(recs)
    assert s["disconnects"] and s["disconnects"][-1]["peer"] == PEER


# -- negotiation fallbacks --------------------------------------------------

def test_old_client_new_server_drops_telemetry_cleanly():
    """telemetry=False models an old actor build: experience flows,
    no frames are expected, and send_telemetry reports un-negotiated
    instead of writing junk the server would fault on."""
    server = SocketIngestServer("127.0.0.1", 0)
    client = SocketTransport("127.0.0.1", server.port, telemetry=False)
    try:
        client.send_experience(_experience_batch())
        assert server.recv_experience(timeout=5.0) is not None
        assert not client.telemetry_negotiated
        assert client.send_telemetry({"peer": PEER, "seq": 0}) is False
        assert client.telemetry_frames_out == 0
        assert server.telemetry_frames == 0
    finally:
        client.close()
        server.stop()


def test_epoch_interop_old_client_new_server():
    """Pre-epoch client build: never hellos, sends an EMPTY
    MSG_PARAMS_REQ. The new server must reply the legacy raw pickle
    (no versioned header) so the old build's pickle.loads keeps
    working — and experience from the same build keeps ingesting."""
    server = SocketIngestServer("127.0.0.1", 0, epoch=77,
                                param_wire_dtype="float32")
    server.publish_params({"w": np.float32(1.5)}, 4)
    sock = socket_mod.create_connection(("127.0.0.1", server.port))
    try:
        _send_msg(sock, MSG_PARAMS_REQ, b"")  # the old build's request
        mtype, payload = _recv_msg(sock)
        assert mtype == MSG_PARAMS
        params, version = pickle.loads(bytes(payload))  # raw legacy blob
        assert version == 4 and params["w"] == np.float32(1.5)
    finally:
        sock.close()
        server.stop()


def _old_param_server(listener, params, version, stop):
    """A pre-epoch server: acks hellos WITHOUT an epoch field and
    answers every MSG_PARAMS_REQ with the legacy raw pickle,
    ignoring the request payload it does not understand."""
    blob = pickle.dumps((params, version))
    conns = []
    listener.settimeout(0.2)
    while not stop.is_set():
        try:
            conn, _ = listener.accept()
        except socket_mod.timeout:
            continue
        except OSError:
            return
        conns.append(conn)

        def serve(c=conn):
            try:
                while True:
                    msg = _recv_msg(c)
                    if msg is None:
                        return
                    mtype, _payload = msg
                    if mtype == MSG_HELLO:
                        _send_msg(c, MSG_HELLO_ACK,
                                  json.dumps({"codec": "raw"}).encode())
                    elif mtype == MSG_PARAMS_REQ:
                        _send_msg(c, MSG_PARAMS, blob)
            except (OSError, ValueError):
                return

        threading.Thread(target=serve, daemon=True).start()


def test_epoch_interop_new_client_old_server():
    """New client against a pre-epoch server: the JSON request payload
    is ignored, the raw-pickle reply parses through the same path,
    the epoch stays unknown (-1, no spurious epoch-change events),
    and every pull ships the full blob (no 'unchanged' economy)."""
    listener = socket_mod.socket(socket_mod.AF_INET,
                                 socket_mod.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(4)
    stop = threading.Event()
    t = threading.Thread(
        target=_old_param_server,
        args=(listener, {"w": 3.0}, 9, stop), daemon=True)
    t.start()
    client = SocketTransport("127.0.0.1", listener.getsockname()[1],
                             params_push=True)  # offer ignored by old
    try:
        for _ in range(2):  # EVERY pull is a full blob against old
            p, v = client.get_params()
            assert p == {"w": 3.0} and v == 9
        assert client.param_unchanged == 0
        assert client.epoch == -1 and client.epoch_changes == 0
        assert client.param_epoch == -1
    finally:
        stop.set()
        client.close()
        listener.close()
        t.join(timeout=2)


def test_epoch_interop_mid_run_bump_keeps_ingest_flowing():
    """bump_epoch() on a LIVE server (config repush, failover drill):
    connected clients observe exactly one epoch change through their
    next pull, and experience ingest never skips a beat."""
    server = SocketIngestServer("127.0.0.1", 0, epoch=10)
    server.publish_params({"w": 0.0}, 0)
    client = SocketTransport("127.0.0.1", server.port)
    try:
        client.send_experience(_experience_batch())
        assert server.recv_experience(timeout=5.0) is not None
        p, _ = client.get_params()
        assert p is not None and client.epoch == 10

        server.bump_epoch()
        p, v = client.get_params()  # epoch mismatch: full reply
        assert p == {"w": 0.0} and v == 0
        assert client.epoch == 11 and client.epoch_changes == 1
        # the experience connection survived the bump untouched
        client.send_experience(_experience_batch(seed=1))
        assert server.recv_experience(timeout=5.0) is not None
        assert client.reconnects == 0
    finally:
        client.close()
        server.stop()


def test_new_client_old_server_degrades_to_no_telemetry():
    """An old server never acks the hello: the client times out, keeps
    raw experience flowing, and the emitter's pump reports unsent."""
    listener = socket_mod.socket(socket_mod.AF_INET,
                                 socket_mod.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    client = SocketTransport("127.0.0.1", listener.getsockname()[1],
                             hello_timeout=0.3)
    accepted = []

    def accept():
        conn, _ = listener.accept()
        accepted.append(conn)  # accept, then say nothing (old build)

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    try:
        actor_obs = _actor_obs()
        emitter = TelemetryEmitter(client, actor_obs, PEER, interval_s=0)
        assert emitter.pump_once() is False  # hello timed out: no grant
        assert not client.telemetry_negotiated
        assert client.negotiated_codec == "raw"
        actor_obs.close()
    finally:
        client.close()
        for c in accepted:
            c.close()
        listener.close()
