"""Vectorized actor runtime (runtime/vector_actor.py) and the
inference server's multi-item query path that serves it
(SURVEY.md §2.4 "inference batching parallelism", §7 hard part 3)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import (
    ActorConfig, EnvConfig, InferenceConfig, LearnerConfig, NetworkConfig,
    ReplayConfig, get_config)
from ape_x_dqn_tpu.comm.transport import LoopbackTransport
from ape_x_dqn_tpu.parallel.inference_server import BatchedInferenceServer
from ape_x_dqn_tpu.runtime.actor import Actor, actor_epsilon
from ape_x_dqn_tpu.runtime.driver import ApexDriver
from ape_x_dqn_tpu.runtime.vector_actor import VectorActor


# -- server query_batch ----------------------------------------------------

def test_query_batch_slices_match_items():
    """Mixed single + multi-item requests scatter the right slices."""
    def apply_fn(params, obs):
        return obs * params

    server = BatchedInferenceServer(apply_fn, jnp.float32(2.0),
                                    max_batch=16, deadline_ms=5.0)
    try:
        results = {}

        def single(i):
            results[("s", i)] = server.query(
                np.full(3, float(i), np.float32))

        def batch(i, n):
            inp = np.stack([np.full(3, 100.0 * i + j, np.float32)
                            for j in range(n)])
            results[("b", i)] = server.query_batch(inp, n)

        threads = ([threading.Thread(target=single, args=(i,))
                    for i in range(4)]
                   + [threading.Thread(target=batch, args=(i, 5))
                      for i in range(3)])
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(4):
            np.testing.assert_allclose(results[("s", i)],
                                       np.full(3, 2.0 * i), rtol=1e-6)
        for i in range(3):
            want = np.stack([np.full(3, 2.0 * (100.0 * i + j), np.float32)
                             for j in range(5)])
            np.testing.assert_allclose(results[("b", i)], want, rtol=1e-6)
        assert server.stats["items"] == 4 + 3 * 5
    finally:
        server.stop()


def test_query_batch_larger_than_max_batch():
    """A vector request may exceed max_batch; the bucket pads past it."""
    def apply_fn(params, obs):
        return obs + params

    server = BatchedInferenceServer(apply_fn, jnp.float32(1.0),
                                    max_batch=4, deadline_ms=1.0)
    try:
        inp = np.arange(10, dtype=np.float32).reshape(10, 1)
        out = server.query_batch(inp, 10)
        np.testing.assert_allclose(out, inp + 1.0, rtol=1e-6)
    finally:
        server.stop()


# -- vector actor ----------------------------------------------------------

def _vec_cfg(num_actors=1, envs_per_actor=4):
    return get_config("cartpole_smoke").replace(
        actors=ActorConfig(num_actors=num_actors, base_eps=0.6,
                           envs_per_actor=envs_per_actor, ingest_batch=16),
        replay=ReplayConfig(kind="prioritized", capacity=2048, min_fill=64),
        learner=LearnerConfig(batch_size=32, n_step=3,
                              target_sync_every=100, publish_every=20),
        inference=InferenceConfig(max_batch=16, deadline_ms=1.0),
    )


def test_vector_actor_ships_prioritized_batches():
    cfg = _vec_cfg(envs_per_actor=4)
    transport = LoopbackTransport()
    calls = {"n": []}

    def query_fn(obs, n):
        calls["n"].append(n)
        assert obs.shape == (n, 4)
        return np.tile(np.array([0.1, 0.2], np.float32), (n, 1))

    actor = VectorActor(cfg, 0, query_fn, transport)
    frames = actor.run(max_frames=200)
    assert frames >= 200 and frames % 4 == 0
    # one K-item query per vector step (plus rare truncation queries)
    assert calls["n"].count(4) >= frames // 4
    batches, total = [], 0
    while True:
        b = transport.recv_experience(timeout=0.01)
        if b is None:
            break
        batches.append(b)
        total += len(b["priorities"])
    assert batches, "vector actor shipped nothing"
    b0 = batches[0]
    assert b0["obs"].shape[1:] == (4,)
    assert b0["priorities"].dtype == np.float32
    assert (b0["priorities"] >= 0).all()
    assert np.isfinite(b0["priorities"]).all()
    # n-step=3 over >=200 frames across 4 envs: most steps emit
    assert total > 120
    # frame accounting reconciles: shipped frames == stepped frames
    assert sum(b["frames"] for b in batches) == frames


def test_vector_actor_eps_spans_global_slots():
    """Actor i's env j sits at global eps slot i*K+j of N*K."""
    cfg = _vec_cfg(num_actors=2, envs_per_actor=3)

    def query_fn(obs, n):
        return np.zeros((n, 2), np.float32)

    a1 = VectorActor(cfg, 1, query_fn, LoopbackTransport())
    want = [actor_epsilon(1 * 3 + j, 6, 0.6, cfg.actors.eps_alpha)
            for j in range(3)]
    got = [c.eps for c in a1.cores]
    np.testing.assert_allclose(got, want)


def test_vector_actor_matches_scalar_nstep_semantics():
    """A K=1 vector actor and a scalar actor given identical Q-values
    and seeds ship identical transition streams (same n-step math,
    same priorities)."""
    cfg = _vec_cfg(num_actors=1, envs_per_actor=1)

    def scalar_q(obs):
        return np.array([0.3, -0.1], np.float32)

    def vec_q(obs, n):
        return np.tile(np.array([0.3, -0.1], np.float32), (n, 1))

    t_s, t_v = LoopbackTransport(), LoopbackTransport()
    Actor(cfg, 0, scalar_q, t_s, seed=5).run(max_frames=120)
    VectorActor(cfg, 0, vec_q, t_v, seed=5).run(max_frames=120)

    def drain(t):
        out = []
        while True:
            b = t.recv_experience(timeout=0.01)
            if b is None:
                return out
            out.append(b)

    bs, bv = drain(t_s), drain(t_v)
    cat = lambda bl, k: np.concatenate([np.asarray(b[k]) for b in bl])
    for k in ("obs", "action", "reward", "next_obs", "discount",
              "priorities"):
        np.testing.assert_allclose(cat(bs, k), cat(bv, k), rtol=1e-6,
                                   err_msg=k)


def test_vector_actor_frame_ring_segments():
    """Frame-ring mode: per-env segment builders ship valid segments
    through the vector loop (synthetic-atari pixels)."""
    cfg = get_config("pong").replace(
        env=EnvConfig(id="catch", kind="synthetic_atari"),
        actors=ActorConfig(num_actors=1, envs_per_actor=3,
                           ingest_batch=16),
        replay=ReplayConfig(kind="prioritized", capacity=4096,
                            min_fill=64, storage="frame_ring",
                            seg_transitions=8),
        learner=LearnerConfig(batch_size=16, n_step=3),
    )
    transport = LoopbackTransport()

    def query_fn(obs, n):
        assert obs.shape[0] == n and obs.shape[1:] == (84, 84, 4)
        return np.zeros((n, 6), np.float32)

    actor = VectorActor(cfg, 0, query_fn, transport)
    frames = actor.run(max_frames=300)
    assert frames >= 300
    segs = []
    while True:
        b = transport.recv_experience(timeout=0.01)
        if b is None:
            break
        segs.append(b)
    assert segs, "no segments shipped"
    s0 = segs[0]
    f = cfg.replay.seg_transitions + cfg.learner.n_step + 4 - 1
    assert s0["seg_frames"].shape == (1, f, 84, 84)
    assert s0["action"].shape == (1, 8)
    assert (s0["priorities"] >= 0).all()
    assert sum(s["frames"] for s in segs) <= frames


def _r2d2_vec_cfg(num_actors=1, envs_per_actor=3, seq=8, overlap=4):
    from ape_x_dqn_tpu.configs import EnvConfig, ParallelConfig
    return get_config("r2d2").replace(
        env=EnvConfig(id="CartPolePO", kind="cartpole_po"),
        network=NetworkConfig(kind="lstm_q", lstm_size=32, torso_dense=64,
                              dueling=True, compute_dtype="float32"),
        replay=ReplayConfig(kind="sequence", capacity=512, seq_length=seq,
                            seq_overlap=overlap, burn_in=4,
                            min_fill=32, priority_eta=0.9, storage="flat"),
        learner=LearnerConfig(batch_size=16, n_step=3, value_rescale=True,
                              target_sync_every=100, lr=1e-3,
                              publish_every=25, train_chunk=4),
        actors=ActorConfig(num_actors=num_actors, base_eps=0.4,
                           envs_per_actor=envs_per_actor, ingest_batch=64),
        inference=InferenceConfig(max_batch=16, deadline_ms=1.0),
        parallel=ParallelConfig(dp=1, tp=1),
        eval_every_steps=0, eval_episodes=0,
    )


def test_recurrent_vector_actor_ships_sequences():
    from ape_x_dqn_tpu.runtime.vector_actor import RecurrentVectorActor

    cfg = _r2d2_vec_cfg(envs_per_actor=3)
    transport = LoopbackTransport()
    lstm = cfg.network.lstm_size

    def query_fn(inp, n):
        assert inp["obs"].shape[0] == n and inp["c"].shape == (n, lstm)
        return {"q": np.tile(np.array([0.1, 0.2], np.float32), (n, 1)),
                "c": np.asarray(inp["c"]) + 1.0,
                "h": np.asarray(inp["h"]) + 1.0}

    actor = RecurrentVectorActor(cfg, 0, query_fn, transport)
    frames = actor.run(max_frames=120)
    assert frames >= 120 and frames % 3 == 0
    batches, total = [], 0
    while True:
        b = transport.recv_experience(timeout=0.01)
        if b is None:
            break
        batches.append(b)
        total += len(b["priorities"])
    assert batches, "vector recurrent actor shipped nothing"
    b0 = batches[0]
    seq = cfg.replay.seq_length
    assert b0["obs"].shape[1:] == (seq, 2)
    assert b0["actions"].shape[1:] == (seq,)
    assert b0["init_c"].shape[1:] == (lstm,)
    assert (b0["priorities"] > 0).all()
    assert (b0["mask"].sum(axis=1) >= 1).all()
    assert sum(b["frames"] for b in batches) == frames
    # init states advance with the fake recurrence except at episode
    # starts (zeros)
    assert any(np.any(b["init_c"] != 0) for b in batches)


def test_recurrent_vector_matches_scalar_semantics():
    """A K=1 recurrent vector actor and the scalar RecurrentActor with
    identical fake Q/recurrence and seeds ship identical sequence
    streams (same TD seeds, same stored states, same priorities)."""
    from ape_x_dqn_tpu.runtime.actor import RecurrentActor
    from ape_x_dqn_tpu.runtime.vector_actor import RecurrentVectorActor

    cfg = _r2d2_vec_cfg(num_actors=1, envs_per_actor=1)
    lstm = cfg.network.lstm_size

    def scalar_q(inp):
        return {"q": np.array([0.3, -0.1], np.float32),
                "c": np.asarray(inp["c"]) + 1.0,
                "h": np.asarray(inp["h"]) - 1.0}

    def vec_q(inp, n):
        return {"q": np.tile(np.array([0.3, -0.1], np.float32), (n, 1)),
                "c": np.asarray(inp["c"]) + 1.0,
                "h": np.asarray(inp["h"]) - 1.0}

    t_s, t_v = LoopbackTransport(), LoopbackTransport()
    RecurrentActor(cfg, 0, scalar_q, t_s, seed=5).run(max_frames=90)
    RecurrentVectorActor(cfg, 0, vec_q, t_v, seed=5).run(max_frames=90)

    def drain(t):
        out = []
        while True:
            b = t.recv_experience(timeout=0.01)
            if b is None:
                return out
            out.append(b)

    bs, bv = drain(t_s), drain(t_v)
    cat = lambda bl, k: np.concatenate([np.asarray(b[k]) for b in bl])
    for k in ("obs", "actions", "rewards", "terminals", "mask",
              "init_c", "init_h", "priorities"):
        np.testing.assert_allclose(cat(bs, k), cat(bv, k), rtol=1e-6,
                                   err_msg=k)


def test_r2d2_driver_vector_end_to_end():
    """Recurrent vector actors through the real driver: batched
    stateful inference -> sequence ingest -> sequence learner."""
    cfg = _r2d2_vec_cfg(num_actors=1, envs_per_actor=3)
    driver = ApexDriver(cfg)
    assert driver.family == "r2d2"
    out = driver.run(total_env_frames=2000, max_grad_steps=40,
                     wall_clock_limit_s=240)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["grad_steps"] >= 40, out
    assert out["frames"] >= 100, out
    assert out["server"]["avg_batch"] > 1.5, out["server"]


def test_apex_driver_vector_end_to_end():
    """Full wiring with vector actors: one thread, 4 envs, batched
    queries through the real inference server into the learner."""
    cfg = _vec_cfg(num_actors=1, envs_per_actor=4).replace(
        eval_every_steps=0, eval_episodes=0)  # eval's single-item
    # queries would dilute the avg_batch assertion below
    driver = ApexDriver(cfg)
    out = driver.run(total_env_frames=1600, max_grad_steps=50,
                     wall_clock_limit_s=120)
    assert out["actor_errors"] == [], out["actor_errors"]
    assert out["loop_errors"] == [], out["loop_errors"]
    assert out["frames"] >= 64, out
    assert out["grad_steps"] >= 50, out
    assert out["episodes"] > 0
    # the server saw multi-item requests: avg batch well above 1
    assert out["server"]["avg_batch"] > 2.0, out["server"]
