"""Tiered cold replay (replay/cold_store.py + the replay-layer hooks):

- bitwise round-trip parity: a region evicted through
  evict_plan -> read_region -> cold_pack -> ColdStore -> recall ->
  restage -> add lands transitions bit-identical to the never-evicted
  originals, on BOTH storage layouts (frame-ring segment packer and
  the flat PixelPacker byte-row packer)
- priority-mass eviction picks the lowest-mass contiguous region, and
  the default (cold off) add keeps blind FIFO — the tier changes
  nothing unless switched on
- ColdStore admission: mass-ordered displacement, door drops, the
  never-inflate compression-ratio floor
- ReplayConfig.cold_tier_* validation (guided errors, satellite 6)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.configs import ReplayConfig
from ape_x_dqn_tpu.replay import cold_store as cold_store_mod
from ape_x_dqn_tpu.replay.cold_store import ColdStore, codec_status
from ape_x_dqn_tpu.replay.frame_ring import (FrameRingReplay,
                                             frame_segment_spec)
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay
from ape_x_dqn_tpu.runtime.learner import transition_item_spec

OBS_SHAPE = (84, 84, 4)


def _ring():
    # capacity 64 transitions, B=8 -> 8 segments
    return FrameRingReplay(64, seg_transitions=8, n_step=3,
                           obs_shape=OBS_SHAPE)


def _seg_batch(r, g, rng, compressible=True):
    """g staging segments; compressible frames exercise the delta path
    (consecutive frames differ in a few pixels, like real Atari)."""
    if compressible:
        base = rng.integers(0, 255, (84, 84)).astype(np.uint8)
        frames = np.broadcast_to(base, (g, r.F, 84, 84)).copy()
        frames[:, :, ::7, ::11] = rng.integers(
            0, 255, frames[:, :, ::7, ::11].shape)
    else:
        frames = rng.integers(0, 255, (g, r.F, 84, 84)).astype(np.uint8)
    return {
        "seg_frames": frames.astype(np.uint8),
        "action": rng.integers(0, 18, (g, r.B)).astype(np.int32),
        "reward": rng.standard_normal((g, r.B)).astype(np.float32),
        "discount": np.full((g, r.B), 0.99, np.float32),
        "next_off": rng.integers(1, 4, (g, r.B)).astype(np.int32),
    }


def _flat_batch(n, rng):
    return {
        "obs": rng.integers(0, 255, (n, *OBS_SHAPE)).astype(np.uint8),
        "action": rng.integers(0, 18, (n,)).astype(np.int32),
        "reward": rng.standard_normal((n,)).astype(np.float32),
        "next_obs": rng.integers(0, 255, (n, *OBS_SHAPE)).astype(np.uint8),
        "discount": np.full((n,), 0.99, np.float32),
    }


def _gather_all(r, state, idx):
    return jax.tree.map(np.asarray, r._gather(state, jnp.asarray(idx)))


# -- bitwise round-trip parity (the tentpole invariant) --------------------


def test_frame_ring_cold_round_trip_bitwise():
    """Evict the lowest-mass segment through the full cold cycle and
    restage it into a SECOND ring: every reconstructed transition
    (obs/next_obs stacks included) is bit-identical to sampling the
    original ring at the original slots."""
    rng = np.random.default_rng(0)
    r = _ring()
    st = r.init()
    g = 2  # eviction block: 2 segments, like segs_per_add=2 staging
    tds = [0.7, 0.05, 0.9, 0.4]  # block starting at seg 2 is lightest
    batches = [_seg_batch(r, g, rng) for _ in tds]
    for b, td in zip(batches, tds):
        st = r.add(st, b, np.full((g, r.B), td, np.float32))
    seg0 = int(r.evict_plan(st, g))
    assert seg0 == 2  # the td=0.05 block (segments 2,3)
    items, pri = r.read_region(st, jnp.int32(seg0), g)
    items = jax.tree.map(np.asarray, items)
    pri = np.asarray(pri)

    cold = ColdStore(frame_segment_spec(r.B, r.n, OBS_SHAPE, np.uint8),
                     capacity_transitions=1024, unit_items=r.B,
                     ptail=(r.B,))
    assert cold.put(items, pri, live=int((pri > 0).sum())) == "stored"
    [back] = cold.recall(1)
    # payload round trip is exact, priorities included
    for k in items:
        assert back[k].dtype == items[k].dtype, k
        np.testing.assert_array_equal(back[k], items[k], err_msg=k)
    np.testing.assert_array_equal(back["priorities"], pri)

    # restage into a fresh ring through the normal add path (the same
    # graph add_many unrolls), with the stored mass inverted to |td|
    td_back = np.maximum(
        np.asarray(back["priorities"]) ** (1.0 / r.alpha) - r.eps, 0.0
    ).astype(np.float32)
    r2 = _ring()
    st2 = r2.add(r2.init(),
                 {k: v for k, v in back.items() if k != "priorities"},
                 td_back)
    idx_orig = seg0 * r.B + np.arange(g * r.B)
    idx_new = np.arange(g * r.B)
    got = _gather_all(r2, st2, idx_new)
    want = _gather_all(r, st, idx_orig)
    for k in want:
        assert got[k].dtype == want[k].dtype, k
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    # restaged priorities match eviction-time mass (float round trip
    # through the alpha inversion, so allclose rather than bit-equal)
    np.testing.assert_allclose(
        np.asarray(st2.tree[r2.capacity:r2.capacity + g * r.B]),
        pri.reshape(-1), rtol=1e-5)


def test_flat_cold_round_trip_bitwise():
    """Same invariant on the flat layout: the PixelPacker byte-row
    storage decodes through read_region, survives the cold codec, and
    restages bit-identically."""
    rng = np.random.default_rng(1)
    spec = transition_item_spec(OBS_SHAPE, np.uint8)
    r = PrioritizedReplay(16, item_spec=spec)
    st = r.init()
    blocks = [_flat_batch(4, rng) for _ in range(4)]
    tds = [0.6, 0.8, 0.02, 0.5]  # block 2 is lightest
    for b, td in zip(blocks, tds):
        st = r.add(st, b, np.full((4,), td, np.float32))
    start = int(r.evict_plan(st, 4))
    assert start == 8
    items, pri = r.read_region(st, jnp.int32(start), 4)
    items = jax.tree.map(np.asarray, items)
    pri = np.asarray(pri)
    for k in blocks[2]:  # read_region already round-trips the packer
        np.testing.assert_array_equal(items[k], blocks[2][k], err_msg=k)

    cold = ColdStore(spec, capacity_transitions=64)
    assert cold.put(items, pri, live=4) == "stored"
    [back] = cold.recall(1)
    td_back = np.maximum(
        np.asarray(back["priorities"]) ** (1.0 / r.alpha) - r.eps, 0.0
    ).astype(np.float32)
    r2 = PrioritizedReplay(16, item_spec=spec)
    st2 = r2.add(r2.init(),
                 {k: v for k, v in back.items() if k != "priorities"},
                 td_back)
    got, _ = r2.read_region(st2, jnp.int32(0), 4)
    for k in blocks[2]:
        a = np.asarray(got[k])
        assert a.dtype == blocks[2][k].dtype, k
        np.testing.assert_array_equal(a, blocks[2][k], err_msg=k)


# -- eviction placement + the cold-off FIFO pin ----------------------------


def test_evict_plan_picks_lowest_mass_region():
    rng = np.random.default_rng(2)
    r = _ring()
    st = r.init()
    for td in (0.3, 0.6, 0.01, 0.02, 0.9, 0.8, 0.7, 0.5):
        st = r.add(st, _seg_batch(r, 1, rng),
                   np.full((1, r.B), td, np.float32))
    # window of 2 contiguous segments with least mass: segments 2+3
    assert int(r.evict_plan(st, 2)) == 2
    # flat analog
    spec = transition_item_spec(OBS_SHAPE, np.uint8)
    fr = PrioritizedReplay(16, item_spec=spec)
    fst = fr.init()
    for td in (0.5, 0.01, 0.9, 0.7):
        fst = fr.add(fst, _flat_batch(4, rng), np.full((4,), td))
    assert int(fr.evict_plan(fst, 4)) == 4


def test_cold_off_add_stays_fifo():
    """With the tier off nothing consults priority mass: a full ring's
    next default add overwrites the FIFO cursor position even when a
    far lower-mass region exists — the pre-PR behavior, bit for bit."""
    rng = np.random.default_rng(3)
    r = _ring()
    st = r.init()
    batches = [_seg_batch(r, 1, rng) for _ in range(8)]
    tds = (0.9, 0.001, 0.9, 0.9, 0.9, 0.9, 0.9, 0.9)  # seg 1 lightest
    for b, td in zip(batches, tds):
        st = r.add(st, b, np.full((1, r.B), td, np.float32))
    assert int(st.pos) == 0 and int(st.size) == r.capacity
    fresh = _seg_batch(r, 1, rng)
    st = r.add(st, fresh, np.full((1, r.B), 0.5, np.float32))
    # FIFO landed on segment 0, NOT on the lowest-mass segment 1
    got0, _ = r.read_region(st, jnp.int32(0), 1)
    got1, _ = r.read_region(st, jnp.int32(1), 1)
    for k in fresh:
        np.testing.assert_array_equal(np.asarray(got0[k]), fresh[k],
                                      err_msg=k)
        np.testing.assert_array_equal(np.asarray(got1[k]), batches[1][k],
                                      err_msg=k)


# -- ColdStore admission policy --------------------------------------------


def _tiny_store(cap=16):
    spec = {"x": jax.ShapeDtypeStruct((4, 1024), np.uint8)}
    return ColdStore(spec, capacity_transitions=cap, unit_items=4,
                     ptail=(4,))


def _tiny_seg(rng, mass):
    items = {"x": rng.integers(0, 4, (1, 4, 1024)).astype(np.uint8)}
    pri = np.full((1, 4), mass, np.float32)
    return items, pri


def test_cold_store_mass_ordered_displacement_and_door_drop():
    rng = np.random.default_rng(4)
    cs = _tiny_store(cap=16)  # 4 segments of 4 live transitions
    for mass in (0.4, 0.2, 0.8, 0.6):
        items, pri = _tiny_seg(rng, mass)
        assert cs.put(items, pri, live=4) == "stored"
    assert len(cs) == 4 and cs.transitions == 16
    # lighter than the lightest stored -> dropped at the door
    items, pri = _tiny_seg(rng, 0.1)
    assert cs.put(items, pri, live=4) == "dropped"
    assert cs.dropped == 1 and len(cs) == 4
    # heavier -> displaces the lightest (mass 0.2)
    items, pri = _tiny_seg(rng, 0.9)
    assert cs.put(items, pri, live=4) == "stored"
    assert cs.displaced == 1 and len(cs) == 4
    # recall pops highest mass first: 0.9*4, then 0.8*4
    [a] = cs.recall(1)
    assert a["priorities"][0, 0] == np.float32(0.9)
    [b] = cs.recall(1)
    assert b["priorities"][0, 0] == np.float32(0.8)
    assert cs.recalled == 2
    # all-dead regions are dropped without storing
    items, pri = _tiny_seg(rng, 0.0)
    assert cs.put(items, pri, live=0) == "dropped"
    # door closure bookkeeping is the caller's (driver) denomination;
    # the store's own counters close in segment units
    assert cs.stored == 5 and cs.dropped == 2


def test_cold_store_compression_ratio_floor():
    """Incompressible data hits the per-leaf never-inflate guard (raw
    mode): the resident ratio never reads below 1.0."""
    rng = np.random.default_rng(5)
    spec = {"x": jax.ShapeDtypeStruct((4, 4096), np.uint8)}
    cs = ColdStore(spec, capacity_transitions=64, unit_items=4,
                   ptail=(4,))
    items = {"x": rng.integers(0, 256, (2, 4, 4096)).astype(np.uint8)}
    pri = np.full((2, 4), 0.5, np.float32)
    assert cs.put(items, pri, live=8) == "stored"
    assert cs.compression_ratio() >= 1.0
    # payload may exceed raw only by the constant per-leaf framing
    assert cs.bytes_compressed <= cs.bytes_raw + 9 * 2


def test_codec_status_reports_available():
    ok, detail = codec_status()
    assert ok
    assert detail in ("native", "numpy-fallback")


# -- ReplayConfig validation (satellite 6) ---------------------------------


def test_replay_config_rejects_negative_cold_capacity():
    with pytest.raises(ValueError, match="cold_tier_capacity"):
        ReplayConfig(cold_tier_capacity=-1)


def test_replay_config_guided_error_without_codec(monkeypatch):
    monkeypatch.setattr(cold_store_mod, "codec_status",
                        lambda: (False, "ImportError: no comm.native"))
    with pytest.raises(ValueError, match="numpy fallback"):
        ReplayConfig(cold_tier_capacity=1 << 16)


def test_replay_config_cold_defaults_off():
    cfg = ReplayConfig()
    assert cfg.cold_tier_capacity == 0
    assert dataclasses.replace(cfg).cold_tier_capacity == 0


# -- disk-spill hook (PR 16: ColdStore -> replay/disk_store.py) ------------


class _FakeSpill:
    """Records offers; configurable accept so queue-full refusal paths
    are testable without a real writeback thread."""

    def __init__(self, accept=True):
        self.offers = []
        self.accept = accept

    def offer(self, seg):
        self.offers.append(seg)
        return self.accept


def _spill_store(cap=16, accept=True):
    spec = {"x": jax.ShapeDtypeStruct((4, 1024), np.uint8)}
    spill = _FakeSpill(accept)
    cs = ColdStore(spec, capacity_transitions=cap, unit_items=4,
                   ptail=(4,), spill=spill)
    return cs, spill


def _fill(cs, rng, masses):
    for mass in masses:
        items, pri = _tiny_seg(rng, mass)
        assert cs.put(items, pri, live=4) == "stored"


def test_cold_spill_door_dropped_candidate_is_offered():
    rng = np.random.default_rng(6)
    cs, spill = _spill_store(cap=16)
    _fill(cs, rng, (0.4, 0.5, 0.6, 0.7))
    items, pri = _tiny_seg(rng, 0.1)  # lighter than everything stored
    assert cs.put(items, pri, live=4) == "dropped"
    assert cs.dropped == 1 and cs.spilled == 1
    [seg] = spill.offers
    assert seg.mass_sum == pytest.approx(0.1 * 4)
    assert seg.live == 4 and len(seg.payload) > 0


def test_cold_spill_displacement_victims_are_offered():
    rng = np.random.default_rng(7)
    cs, spill = _spill_store(cap=16)
    _fill(cs, rng, (0.2, 0.5, 0.6, 0.7))
    items, pri = _tiny_seg(rng, 0.9)  # displaces the 0.2 segment
    assert cs.put(items, pri, live=4) == "stored"
    assert cs.displaced == 1 and cs.spilled == 1
    [victim] = spill.offers
    assert victim.mass_sum == pytest.approx(0.2 * 4)


def test_cold_spill_refusal_not_counted_as_spilled():
    rng = np.random.default_rng(8)
    cs, spill = _spill_store(cap=16, accept=False)
    _fill(cs, rng, (0.4, 0.5, 0.6, 0.7))
    items, pri = _tiny_seg(rng, 0.1)
    assert cs.put(items, pri, live=4) == "dropped"
    assert len(spill.offers) == 1  # offered, refused (queue full)
    assert cs.spilled == 0


def test_cold_spill_all_dead_regions_never_offered():
    rng = np.random.default_rng(9)
    cs, spill = _spill_store(cap=16)
    items, pri = _tiny_seg(rng, 0.0)
    assert cs.put(items, pri, live=0) == "dropped"
    assert spill.offers == []  # zero mass: nothing worth disk bytes


def test_put_segment_door_without_touching_eviction_counters():
    from ape_x_dqn_tpu.replay.cold_store import ColdSegment
    rng = np.random.default_rng(10)
    cs, spill = _spill_store(cap=16)
    _fill(cs, rng, (0.3, 0.5, 0.6, 0.7))
    stored0, dropped0 = cs.stored, cs.dropped
    # a promoted segment heavier than the lightest resident: admitted,
    # victim spills back down, stored/dropped stay untouched (the
    # driver closure is denominated in ring evictions, not promotions)
    heavy = ColdSegment(b"promoted-bytes", 1, 4, 48, 0.4 * 4, 0.4, 7)
    assert cs.put_segment(heavy) == "stored"
    assert cs.displaced == 1
    [victim] = spill.offers
    assert victim.mass_sum == pytest.approx(0.3 * 4)
    # a promoted segment lighter than the floor: dropped, NOT
    # re-spilled (ping-pong prevention)
    light = ColdSegment(b"light-bytes", 1, 4, 48, 0.01, 0.01, 8)
    assert cs.put_segment(light) == "dropped"
    assert len(spill.offers) == 1
    assert (cs.stored, cs.dropped) == (stored0, dropped0)


def test_displacement_floor_tracks_lightest_at_capacity():
    rng = np.random.default_rng(11)
    cs, _ = _spill_store(cap=16)
    assert cs.displacement_floor() == 0.0
    _fill(cs, rng, (0.4, 0.6))
    assert cs.displacement_floor() == 0.0  # below capacity
    _fill(cs, rng, (0.5, 0.7))
    assert cs.displacement_floor() == pytest.approx(0.4 * 4)


# -- ReplayConfig disk-knob validation (PR 16) -----------------------------


def test_replay_config_rejects_negative_disk_capacity():
    with pytest.raises(ValueError, match="cold_tier_disk_capacity"):
        ReplayConfig(cold_tier_disk_capacity=-1)


def test_replay_config_disk_requires_ram_tier():
    with pytest.raises(ValueError, match="cold_tier_capacity > 0"):
        ReplayConfig(cold_tier_disk_capacity=1 << 20)


def test_replay_config_disk_requires_dir():
    with pytest.raises(ValueError, match="cold_tier_disk_dir"):
        ReplayConfig(cold_tier_capacity=1 << 16,
                     cold_tier_disk_capacity=1 << 20)


def test_replay_config_disk_knob_bounds():
    kw = dict(cold_tier_capacity=1 << 16,
              cold_tier_disk_capacity=1 << 20,
              cold_tier_disk_dir="/tmp/x")
    assert ReplayConfig(**kw).cold_tier_disk_queue == 16
    with pytest.raises(ValueError, match="cold_tier_disk_queue"):
        ReplayConfig(**kw, cold_tier_disk_queue=0)
    with pytest.raises(ValueError, match="cold_tier_disk_file_bytes"):
        ReplayConfig(**kw, cold_tier_disk_file_bytes=100)
    with pytest.raises(ValueError, match="cold_tier_disk_compact_frac"):
        ReplayConfig(**kw, cold_tier_disk_compact_frac=1.5)
    with pytest.raises(ValueError, match="cold_tier_disk_promote"):
        ReplayConfig(**kw, cold_tier_disk_promote=-1)


def test_replay_config_disk_defaults_off():
    cfg = ReplayConfig()
    assert cfg.cold_tier_disk_capacity == 0
    assert dataclasses.replace(cfg).cold_tier_disk_capacity == 0
