"""Config-1 end-to-end integration (SURVEY.md §4 'integration').

The full act -> store -> sample -> jit-update -> target-sync loop in one
process. The quick test asserts learning progress; the slow test is the
canonical solve (>= 475 average over last 20 episodes).
"""

import numpy as np
import pytest

from ape_x_dqn_tpu.configs import get_config
from ape_x_dqn_tpu.runtime.single_process import train_single_process


def test_cartpole_learns_quick():
    cfg = get_config("cartpole_smoke", seed=0)
    out = train_single_process(cfg, total_env_frames=9_000)
    # untrained/random policy averages ~20; require clear learning signal
    assert out["episodes"] >= 5
    assert out["last20_return"] > 60.0, out


@pytest.mark.slow
def test_cartpole_solves():
    cfg = get_config("cartpole_smoke", seed=0)
    out = train_single_process(cfg, total_env_frames=120_000,
                               solve_return=475.0)
    assert out["last20_return"] >= 475.0, out
    assert out["frames"] < 120_000  # early-stopped on solve
