"""Deterministic-policy-gradient actor-critic for continuous control.

Ape-X DPG (Horgan et al. 2018 §"Ape-X DPG"; SURVEY.md §2.2 "DPG
actor-critic"): a deterministic policy network mu(s) with a tanh-squashed
bounded output, and a Q(s, a) critic; both have target copies updated by
Polyak averaging (models.base.soft_update).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ape_x_dqn_tpu.models.base import dtype_of, preprocess_obs


class DPGActor(nn.Module):
    action_dim: int
    action_low: float = -1.0
    action_high: float = 1.0
    hidden: Sequence[int] = (300, 200)
    compute_dtype: str = "float32"

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        dt = dtype_of(self.compute_dtype)
        x = preprocess_obs(obs, dt)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h, dtype=dt)(x))
        a = jnp.tanh(nn.Dense(self.action_dim, dtype=dt)(x))
        mid = (self.action_high + self.action_low) / 2.0
        half = (self.action_high - self.action_low) / 2.0
        return (mid + half * a).astype(jnp.float32)


class DPGCritic(nn.Module):
    hidden: Sequence[int] = (300, 200)
    compute_dtype: str = "float32"

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        dt = dtype_of(self.compute_dtype)
        x = jnp.concatenate(
            [preprocess_obs(obs, dt), action.astype(dt)], axis=-1)
        for h in self.hidden:
            x = nn.relu(nn.Dense(h, dtype=dt)(x))
        q = nn.Dense(1, dtype=dt)(x)
        return jnp.squeeze(q, -1).astype(jnp.float32)
