"""Model-layer helpers.

Networks are flax.linen modules held as pure functions + param pytrees.
Params live in float32; the forward/backward compute dtype is bfloat16 by
default (NetworkConfig.compute_dtype) so matmuls/convs hit the MXU at
full rate, with Q-value outputs cast back to float32 for the loss.

Reference parity: SURVEY.md §2.2 rows "MLP Q-net", "Nature-CNN",
"Dueling heads", "LSTM Q-net", "DPG actor-critic".
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def dtype_of(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16}[name]


def preprocess_obs(obs: jax.Array, compute_dtype) -> jax.Array:
    """uint8 image obs -> scaled float in compute dtype; float obs -> cast.

    Scaling to [0,1] happens on-device so replay stores uint8 (4x HBM
    saving + 4x ingest bandwidth saving vs float32 frames).
    """
    if obs.dtype == jnp.uint8:
        return obs.astype(compute_dtype) / jnp.asarray(255.0, compute_dtype)
    return obs.astype(compute_dtype)


def init_params(module, rng: jax.Array, sample_obs: jax.Array,
                **extra) -> Any:
    return module.init(rng, sample_obs, **extra)


def hard_update(target_params: Any, online_params: Any) -> Any:
    """Target-network hard sync (every K learner steps)."""
    del target_params
    return jax.tree.map(lambda p: p, online_params)


def soft_update(target_params: Any, online_params: Any, tau: float) -> Any:
    """Polyak averaging for DPG target nets."""
    return jax.tree.map(lambda t, p: (1.0 - tau) * t + tau * p,
                        target_params, online_params)


def param_count(params: Any) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
