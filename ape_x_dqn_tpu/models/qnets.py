"""Q-networks: MLP (CartPole), Nature-CNN with dueling heads (Atari).

TPU-first choices:
- NHWC conv layout (XLA's native TPU layout) with uint8 obs dequantized
  on-device (models.base.preprocess_obs).
- bfloat16 compute / float32 params; Q outputs in float32.
- Dueling merge Q = V + A - mean(A) (Wang et al. 2016), as attested for
  the reference (SURVEY.md §2.2 "Dueling heads").
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ape_x_dqn_tpu.models.base import dtype_of, preprocess_obs


class DuelingHead(nn.Module):
    num_actions: int
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        v = nn.Dense(1, dtype=self.dtype, name="value")(x)
        a = nn.Dense(self.num_actions, dtype=self.dtype, name="advantage")(x)
        q = v + a - jnp.mean(a, axis=-1, keepdims=True)
        return q.astype(jnp.float32)


class MLPQNet(nn.Module):
    """Dense Q-network for low-dimensional observations (config 1)."""

    num_actions: int
    hidden: Sequence[int] = (256, 256)
    dueling: bool = False
    compute_dtype: str = "float32"

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        dt = dtype_of(self.compute_dtype)
        x = preprocess_obs(obs, dt)
        x = x.reshape(x.shape[0], -1)  # flatten any multi-dim obs
        for h in self.hidden:
            x = nn.relu(nn.Dense(h, dtype=dt)(x))
        if self.dueling:
            return DuelingHead(self.num_actions, dtype=dt)(x)
        return nn.Dense(self.num_actions, dtype=dt)(x).astype(jnp.float32)


class NatureCNNTorso(nn.Module):
    """The classic DQN conv stack (Mnih et al. 2015): 32x8s4, 64x4s2,
    64x3s1, dense 512 — attested for the reference (SURVEY.md §2.2)."""

    channels: Sequence[int] = (32, 64, 64)
    kernels: Sequence[int] = (8, 4, 3)
    strides: Sequence[int] = (4, 2, 1)
    dense: int = 512
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for ch, k, s in zip(self.channels, self.kernels, self.strides):
            x = nn.Conv(ch, (k, k), strides=(s, s), padding="VALID",
                        dtype=self.dtype)(x)
            x = nn.relu(x)
        x = x.reshape((*x.shape[:-3], -1))
        x = nn.relu(nn.Dense(self.dense, dtype=self.dtype, name="torso_out")(x))
        return x


class NatureDQN(nn.Module):
    """Nature-CNN torso + (dueling) Q head over uint8 NHWC frames."""

    num_actions: int
    channels: Sequence[int] = (32, 64, 64)
    kernels: Sequence[int] = (8, 4, 3)
    strides: Sequence[int] = (4, 2, 1)
    dense: int = 512
    dueling: bool = True
    compute_dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        dt = dtype_of(self.compute_dtype)
        x = preprocess_obs(obs, dt)
        x = NatureCNNTorso(self.channels, self.kernels, self.strides,
                           self.dense, dtype=dt, name="torso")(x)
        if self.dueling:
            return DuelingHead(self.num_actions, dtype=dt)(x)
        return nn.Dense(self.num_actions, dtype=dt)(x).astype(jnp.float32)
