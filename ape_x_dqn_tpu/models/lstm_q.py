"""Recurrent LSTM Q-network for the R2D2 config.

CNN torso -> LSTM -> dueling heads with replay-stored recurrent state
(SURVEY.md §2.2 "LSTM Q-net", §3.4). The time unroll is `nn.scan` over an
`OptimizedLSTMCell`, i.e. a `lax.scan` inside the learner jit — static
sequence length, no Python-level recurrence (XLA-friendly control flow).

Two entry points sharing parameters (same submodule names):
- `__call__(obs[B,T,...], state)` — full-sequence unroll for the learner
  (burn-in + train segments are sliced by the loss, not the net).
- `step(obs[B,...], state)` — single step for actors / inference server.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ape_x_dqn_tpu.models.base import dtype_of, preprocess_obs
from ape_x_dqn_tpu.models.qnets import DuelingHead, NatureCNNTorso

LSTMState = tuple[jax.Array, jax.Array]  # (c, h), float32 in replay


class ApeXLSTMQNet(nn.Module):
    num_actions: int
    lstm_size: int = 512
    dense: int = 512
    dueling: bool = True
    compute_dtype: str = "bfloat16"
    mlp_torso: bool = False  # dense torso for vector-obs tests/smoke
    mlp_hidden: int = 128

    def _torso(self, obs: jax.Array, dt) -> jax.Array:
        x = preprocess_obs(obs, dt)
        if self.mlp_torso:
            return nn.relu(nn.Dense(self.mlp_hidden, dtype=dt,
                                    name="torso")(x))
        return NatureCNNTorso(dense=self.dense, dtype=dt, name="torso")(x)

    def _head(self, x: jax.Array, dt) -> jax.Array:
        if self.dueling:
            return DuelingHead(self.num_actions, dtype=dt, name="head")(x)
        return nn.Dense(self.num_actions, dtype=dt,
                        name="head")(x).astype(jnp.float32)

    @nn.compact
    def __call__(self, obs: jax.Array, state: LSTMState
                 ) -> tuple[jax.Array, LSTMState]:
        """obs: [B, T, ...] -> (q: [B, T, A] float32, final_state)."""
        dt = dtype_of(self.compute_dtype)
        b, t = obs.shape[:2]
        feats = self._torso(obs.reshape(b * t, *obs.shape[2:]), dt)
        feats = feats.reshape(b, t, -1).swapaxes(0, 1)  # [T, B, F]
        scan_cell = nn.scan(
            nn.OptimizedLSTMCell,
            variable_broadcast="params", split_rngs={"params": False},
            in_axes=0, out_axes=0,
        )(self.lstm_size, dtype=dt, name="lstm")
        state = tuple(s.astype(dt) for s in state)
        final_state, ys = scan_cell(state, feats)  # ys: [T, B, H]
        q = self._head(ys.swapaxes(0, 1).reshape(b * t, -1), dt)
        q = q.reshape(b, t, self.num_actions)
        return q, tuple(s.astype(jnp.float32) for s in final_state)

    @nn.compact
    def step(self, obs: jax.Array, state: LSTMState
             ) -> tuple[jax.Array, LSTMState]:
        """obs: [B, ...] single timestep for acting."""
        dt = dtype_of(self.compute_dtype)
        feats = self._torso(obs, dt)
        cell = nn.OptimizedLSTMCell(self.lstm_size, dtype=dt, name="lstm")
        state = tuple(s.astype(dt) for s in state)
        new_state, y = cell(state, feats)
        q = self._head(y, dt)
        return q, tuple(s.astype(jnp.float32) for s in new_state)

    def initial_state(self, batch: int) -> LSTMState:
        z = jnp.zeros((batch, self.lstm_size), jnp.float32)
        return (z, z)
