"""Network factory + model helpers."""

from ape_x_dqn_tpu.models.base import (
    hard_update, init_params, param_count, preprocess_obs, soft_update)
from ape_x_dqn_tpu.models.qnets import MLPQNet, NatureDQN, DuelingHead
from ape_x_dqn_tpu.models.lstm_q import ApeXLSTMQNet, LSTMState
from ape_x_dqn_tpu.models.dpg import DPGActor, DPGCritic


def build_network(net_cfg, spec):
    """Build the module matching a NetworkConfig for an EnvSpec.

    For kind='dpg' returns (actor, critic); otherwise a single Q-network.
    """
    if net_cfg.kind == "mlp":
        return MLPQNet(num_actions=spec.num_actions,
                       hidden=tuple(net_cfg.mlp_hidden),
                       dueling=net_cfg.dueling,
                       compute_dtype=net_cfg.compute_dtype)
    if net_cfg.kind == "nature_cnn":
        return NatureDQN(num_actions=spec.num_actions,
                         channels=tuple(net_cfg.cnn_channels),
                         kernels=tuple(net_cfg.cnn_kernels),
                         strides=tuple(net_cfg.cnn_strides),
                         dense=net_cfg.torso_dense,
                         dueling=net_cfg.dueling,
                         compute_dtype=net_cfg.compute_dtype)
    if net_cfg.kind == "lstm_q":
        return ApeXLSTMQNet(num_actions=spec.num_actions,
                            lstm_size=net_cfg.lstm_size,
                            dense=net_cfg.torso_dense,
                            dueling=net_cfg.dueling,
                            compute_dtype=net_cfg.compute_dtype,
                            mlp_torso=len(spec.obs_shape) == 1)
    if net_cfg.kind == "dpg":
        actor = DPGActor(action_dim=spec.action_dim,
                         action_low=spec.action_low,
                         action_high=spec.action_high,
                         hidden=tuple(net_cfg.dpg_hidden),
                         compute_dtype=net_cfg.compute_dtype)
        critic = DPGCritic(hidden=tuple(net_cfg.dpg_hidden),
                           compute_dtype=net_cfg.compute_dtype)
        return actor, critic
    raise ValueError(f"unknown network kind {net_cfg.kind!r}")
