"""Parameter-plane codec: delta + int8-quantized param broadcast.

The experience direction has enjoyed a negotiated per-leaf codec since
PR 4; params — `model_bytes x peers x publish_rate` of learner egress —
were still raw pickled blobs (bf16-downcast at best). This module is
the param-plane analogue (ISSUE 19): a versioned-blob PROVIDER that is
the single source of truth for param bytes at a given (epoch, version)
— the legacy pickled blob, the shm seqlock area, local get_params, the
poll replies and every push subscriber all read it, so pull and push
can never disagree about the bytes for a version — plus a chain codec
("delta-q8") that ships each publish as a per-leaf delta against the
previous published version:

  - float32 leaves: delta vs the reconstruction chain, int8 AFFINE
    quantization (256 bins across the delta's [min, max] span; scale
    and offset ride the JSON meta), then deflate. The encoder advances
    its own chain through the DEQUANTIZED delta — exactly what every
    decoder holds — so quantization error never compounds across
    versions (each step's error is that step's residual alone,
    <= scale/2 per element).
  - constant deltas (unchanged leaves, global shifts) ship as a bias
    scalar in the meta — zero payload bytes ("z").
  - non-float leaves ship raw-if-changed ("a"), nothing if bytewise
    identical ("s").
  - per-leaf never-inflate guard: a quantized delta that would not
    undercut the absolute downcast leaf ships absolute instead; a
    whole payload that would not undercut the legacy APXV reply
    degrades to it (the codec can never inflate the param path, which
    is the `param_compression_ratio >= 1.0` floor obs --check gates).

Catch-up and resync: the provider caches the last `window` encoded
segments as a chain; a client that missed versions replays the chain
segments from its base in one payload. A base outside the window, an
unknown base, or an epoch bump (new learner incarnation) gets a FULL
resync payload (absolute leaves + the pytree structure), counted in
`param_resyncs`. Optimizer state never touches this path — only the
actor-side policy copy rides it, and the documented tolerance is
pinned by the quantized-policy parity smoke (PARITY.md).

Precision contract: coded reconstruction tracks the wire-dtype tree
(bf16-roundtripped f32 under the default param_wire_dtype) within one
quantization step of the latest delta; a client that seeded its chain
from a raw/APXV full starts within wire rounding of the provider's
chain and the offset stays CONSTANT (deltas are additive), collapsing
to zero at every full resync. Cross-implementation bit-parity of the
quantizer (native kernel vs numpy fallback, cpp/framing.cpp) is a wire
contract pinned by test_param_codec.py.

Wire shape (rides MSG_PARAMS / MSG_PARAMS_PUSH): a coded payload leads
with PARAMS_CODEC_MAGIC — distinct from the versioned-header magic
('APXV') and from a legacy pickle (0x80 first byte), so every receiver
build sniffs the right parser — followed by packed segments, each a
pack_records frame of [JSON head, buffers...]. Coded payloads are only
ever sent to peers that ASKED for the codec (hello "param_codecs"
offer for pushes, a "codec" field in the MSG_PARAMS_REQ JSON for
pulls); old<->new interop degrades silently to the raw paths both
ways, and the same-host shm seqlock area always carries the raw blob
(local bandwidth is free; cross-plane consistency is tested).
"""

from __future__ import annotations

import json
import pickle
import struct
import zlib
from collections import deque
from typing import Any

import numpy as np

from ape_x_dqn_tpu.comm import native
from ape_x_dqn_tpu.obs.health import make_lock

PARAM_CODECS = ("raw", "delta-q8")

# coded payload prefix: magic, membership epoch, version this payload
# reconstructs, base version the chain starts from (-1 = full resync).
# The magic's first wire byte (0x43 'C') collides with neither a legacy
# pickle (0x80) nor an APXV versioned header (0x56 'V').
_CODEC_HDR = struct.Struct("<Iqqq")
PARAMS_CODEC_MAGIC = 0x41505843  # 'APXC'

# versioned (non-coded) reply prefix — shared with socket_transport;
# defined here so the provider can emit both reply shapes
_PARAMS_HDR = struct.Struct("<Iqq")
PARAMS_HDR_MAGIC = 0x41505856  # 'APXV'

_Q8_SPAN = 254.0  # quantization bins spanning the delta's [min, max]
# params are a low-rate path (one encode per publish, not per batch):
# spend more deflate effort than the experience codec's Z_BEST_SPEED
_DEFLATE_LEVEL = 6


def check_param_codec(codec: str) -> str:
    if codec not in PARAM_CODECS:
        raise ValueError(
            f"param_codec must be one of {PARAM_CODECS}, got {codec!r}")
    return codec


# -- wire dtype helpers (shared with socket_transport) ----------------------


def jax_to_numpy(params: Any) -> Any:
    import jax
    return jax.tree.map(np.asarray, params) if params is not None else None


class _Bf16Wire:
    """Marker wrapping a leaf the SENDER downcast f32->bf16 for the
    wire. The receiver upcasts exactly these leaves back to float32 and
    leaves everything else — including params that are legitimately
    bfloat16 in the model — untouched, so the wire never silently
    changes a tree's native dtypes (round-3 advisor finding)."""

    __slots__ = ("a",)

    def __init__(self, a):
        self.a = a


def _downcast_f32(tree: Any) -> Any:
    """float32 leaves -> bf16 wrapped in _Bf16Wire for the wire (half
    the bytes; other dtypes — uint8 frames, ints, f64, native bf16 —
    pass through untouched and untagged)."""
    import jax
    import ml_dtypes

    def one(x):
        x = np.asarray(x)
        return _Bf16Wire(x.astype(ml_dtypes.bfloat16)) \
            if x.dtype == np.float32 else x

    return jax.tree.map(one, tree) if tree is not None else None


def _upcast_bf16(tree: Any) -> Any:
    """Restore sender-downcast leaves (_Bf16Wire markers) to float32;
    every other leaf keeps its wire dtype exactly (values carry the
    bf16 rounding; exactness is not a wire contract — see
    SocketIngestServer.param_wire_dtype)."""
    import jax

    def one(x):
        return np.asarray(x.a, dtype=np.float32) \
            if isinstance(x, _Bf16Wire) else x

    return jax.tree.map(one, tree) if tree is not None else None


# -- leaf encode/decode ------------------------------------------------------


def _decode_abs(m: dict, buf) -> np.ndarray:
    """Materialize one absolute ("a") leaf as a fresh writable array
    (it becomes chain state the q8 path mutates in place)."""
    raw = zlib.decompress(buf) if m.get("zl") else buf
    sh = m["sh"]
    if m.get("w") == "bf16":
        import ml_dtypes
        arr = np.frombuffer(raw, dtype=ml_dtypes.bfloat16)
        if arr.size != int(np.prod(sh, dtype=np.int64)):
            raise ValueError(f"abs leaf inflates to {arr.size} elements, "
                             f"expected shape {sh}")
        return arr.astype(np.float32).reshape(sh)
    arr = np.frombuffer(raw, dtype=np.dtype(m["dt"]))
    if arr.size != int(np.prod(sh, dtype=np.int64)):
        raise ValueError(f"abs leaf inflates to {arr.size} elements, "
                         f"expected shape {sh}")
    return arr.reshape(sh).copy()


def _deflate_maybe(m: dict, buf: bytes) -> bytes:
    """Per-leaf never-inflate deflate: tag "zl" only when it shrinks."""
    comp = zlib.compress(buf, _DEFLATE_LEVEL)
    if len(comp) < len(buf):
        m["zl"] = 1
        return comp
    return buf


def _abs_leaf(w: np.ndarray, wire_dtype: str) -> tuple[dict, bytes]:
    """Absolute leaf: f32 downcast to the wire dtype, everything else
    raw bytes; deflated when that shrinks it."""
    if w.dtype == np.float32 and wire_dtype == "bfloat16":
        import ml_dtypes
        m: dict = {"e": "a", "sh": list(w.shape), "dt": w.dtype.str,
                   "w": "bf16"}
        return m, _deflate_maybe(m, w.astype(ml_dtypes.bfloat16).tobytes())
    m = {"e": "a", "sh": list(w.shape), "dt": w.dtype.str}
    return m, _deflate_maybe(m, w.tobytes())


# -- server side: the one versioned-blob provider ---------------------------


class ParamBlobProvider:
    """Single source of truth for param bytes per (epoch, version).

    Owns the published tree, the legacy pickled blob (lazy, cached per
    version — also what the shm seqlock area and legacy/raw clients
    get), the local get_params tree cache (blob-roundtripped, so local
    and remote pulls see bit-identical values), and — when the codec is
    on — the delta chain: the float32 reconstruction every negotiated
    decoder holds, plus the last `window` encoded segments for
    catch-up. One lock guards all of it, so a pull reply, a push frame
    and the shm write can never pair a blob with the wrong version."""

    def __init__(self, wire_dtype: str = "bfloat16",
                 codec: str = "raw", window: int = 8):
        if wire_dtype not in ("bfloat16", "float32"):
            raise ValueError(
                f"param_wire_dtype must be 'bfloat16' or 'float32', "
                f"got {wire_dtype!r}")
        self._wire_dtype = wire_dtype
        self.codec = check_param_codec(codec)
        self._window = max(1, int(window))
        self._lock = make_lock("param_provider._lock")
        self._params: tuple[Any, int] = (None, -1)  # guarded-by: _lock
        self._blob: bytes | None = pickle.dumps((None, -1))  # guarded-by: _lock
        self._tree_cache: tuple[Any, int] | None = None  # guarded-by: _lock
        # delta-chain state (all guarded-by: _lock): the reconstruction
        # leaves R (what every decoder holds after applying the chain),
        # the version/epoch R corresponds to, the pytree structure, the
        # recent segments, and the cached full-resync payload
        self._chain_epoch = -1  # guarded-by: _lock
        self._chain: deque[tuple[int, int, bytes]] = deque()  # guarded-by: _lock
        self._recon: list[np.ndarray] | None = None  # guarded-by: _lock
        self._recon_version = -1  # guarded-by: _lock
        self._treedef: Any = None  # guarded-by: _lock
        self._full: tuple[tuple[int, int], bytes] | None = None  # guarded-by: _lock

    @property
    def version(self) -> int:
        with self._lock:
            return self._params[1]

    @property
    def chain_len(self) -> int:
        """Encoded segments currently cached (test/obs seam)."""
        with self._lock:
            return len(self._chain)

    def publish(self, params: Any, version: int) -> None:
        """Store the tree; serialization/encoding stay lazy until the
        first reply needs them (publishing must not stall the learner
        thread on a multi-MB pickle when nobody is connected)."""
        with self._lock:
            self._params = (params, version)
            self._blob = None
            self._tree_cache = None

    # raw (legacy/APXV) plane

    def _build_blob_locked(self) -> bytes:
        """(Re)build the pickled param blob; caller holds self._lock.
        Reply paths read (blob, version) ATOMICALLY under the lock —
        pairing a blob with the version of a concurrent publish would
        let an up-to-date client skip a real update."""
        if self._blob is None:
            params, version = self._params
            host = jax_to_numpy(params)
            if self._wire_dtype == "bfloat16":
                host = _downcast_f32(host)
            self._blob = pickle.dumps(  # apexlint: unguarded(caller holds _lock)
                (host, version), protocol=pickle.HIGHEST_PROTOCOL)
        return self._blob

    def raw_blob(self) -> bytes:
        """Legacy pickled (tree, version) blob — what empty-payload
        (pre-versioning) clients receive verbatim."""
        with self._lock:
            return self._build_blob_locked()

    def raw_blob_versioned(self) -> tuple[bytes, int, Any]:
        """(blob, version, tree_cache_key) read atomically — the shm
        param-area writer's pairing."""
        with self._lock:
            blob = self._build_blob_locked()
            return blob, self._params[1], blob

    def get_tree(self) -> tuple[Any, int]:
        """Local loopback callers get the deserialized tree directly,
        cached per published version — no pickle round-trip per pull;
        the pickled blob stays wire-only. The cache still holds the
        BLOB-roundtripped values (bf16 wire rounding and all), so local
        and remote pulls see bit-identical params."""
        with self._lock:
            if self._tree_cache is not None:
                return self._tree_cache
        blob = self.raw_blob()
        params, version = pickle.loads(blob)
        out = (_upcast_bf16(params), version)
        with self._lock:
            # cache only if no newer publish invalidated the blob while
            # we deserialized outside the lock
            if self._blob is blob:
                self._tree_cache = out
        return out

    def versioned_reply(self, have_epoch: int, have_version: int,
                        epoch: int) -> tuple[bytes, str, int, int]:
        """APXV reply: [magic, epoch, version] header, plus the pickled
        blob only when the client is behind. Returns (payload, kind,
        version, raw_cost) — raw_cost is what the reply costs with no
        codec, the compression-ratio denominator's counterpart."""
        with self._lock:
            blob = self._build_blob_locked()
            version = self._params[1]
        hdr = _PARAMS_HDR.pack(PARAMS_HDR_MAGIC, epoch, version)
        if have_epoch == epoch and have_version == version:
            return hdr, "unchanged", version, len(hdr)
        return hdr + blob, "raw_full", version, len(hdr) + len(blob)

    # coded plane

    def coded_reply(self, have_epoch: int, have_version: int,
                    epoch: int) -> tuple[bytes, str, int, int]:
        """Best coded reply for a client holding (have_epoch,
        have_version): header-only "unchanged", a "delta" chain from
        the client's base, a coded "full" resync, or — whenever the
        coded form would not undercut it (payload-level never-inflate)
        — the APXV "raw_full". Returns (payload, kind, version,
        raw_cost)."""
        with self._lock:
            version = self._params[1]
            if version < 0:
                blob = self._build_blob_locked()
                hdr = _PARAMS_HDR.pack(PARAMS_HDR_MAGIC, epoch, version)
                return hdr + blob, "raw_full", version, \
                    _PARAMS_HDR.size + len(blob)
            if have_epoch == epoch and have_version == version:
                hdr = _PARAMS_HDR.pack(PARAMS_HDR_MAGIC, epoch, version)
                return hdr, "unchanged", version, len(hdr)
            self._extend_chain_locked(epoch)
            raw_cost = _PARAMS_HDR.size + len(self._build_blob_locked())
            if have_epoch == epoch and have_version >= 0:
                segs = self._segments_from_locked(have_version)
                if segs:
                    payload = _CODEC_HDR.pack(
                        PARAMS_CODEC_MAGIC, epoch, version,
                        have_version) + native.pack_records(segs)
                    if len(payload) < raw_cost:
                        return payload, "delta", version, raw_cost
            full = self._full_payload_locked(epoch)
            if len(full) < raw_cost:
                return full, "full", version, raw_cost
            hdr = _PARAMS_HDR.pack(PARAMS_HDR_MAGIC, epoch, version)
            return hdr + self._build_blob_locked(), "raw_full", \
                version, raw_cost

    def _wire_leaves_locked(self) -> tuple[list[np.ndarray], Any]:
        """Flatten the published tree to the WIRE-dtype leaves W the
        codec targets: f32 leaves bf16-roundtripped under the default
        wire dtype (identical values to what the raw path delivers),
        everything else as-is. Fulls and deltas both aim at W, so
        every entry point converges on the same values."""
        import jax
        params, _ = self._params
        leaves, treedef = jax.tree_util.tree_flatten(jax_to_numpy(params))
        out = []
        for x in leaves:
            a = np.ascontiguousarray(x)
            if a.dtype == np.float32 and self._wire_dtype == "bfloat16":
                import ml_dtypes
                a = a.astype(ml_dtypes.bfloat16).astype(np.float32)
            out.append(a)
        return out, treedef

    def _reset_chain_locked(self, epoch: int,
                            leaves: list[np.ndarray] | None = None,
                            treedef: Any = None,
                            version: int = -1) -> None:
        self._chain.clear()
        self._full = None  # apexlint: unguarded(caller holds _lock)
        self._chain_epoch = epoch  # apexlint: unguarded(caller holds _lock)
        # owned copies: chain leaves are mutated in place by the q8
        # advance, and under a float32 wire dtype the flatten may alias
        # the learner's own arrays
        recon = None if leaves is None else [np.array(x) for x in leaves]
        self._recon = recon  # apexlint: unguarded(caller holds _lock)
        self._treedef = treedef  # apexlint: unguarded(caller holds _lock)
        self._recon_version = version  # apexlint: unguarded(caller holds _lock)

    def _extend_chain_locked(self, epoch: int) -> None:
        """Advance the reconstruction chain to the published version,
        encoding one segment from wherever the chain last stood (the
        chain skips versions nobody ever requested — its nodes are the
        versions clients actually hold). Caller holds self._lock."""
        params, version = self._params
        if epoch != self._chain_epoch:
            # epoch bump: the old chain's bases belong to a dead
            # incarnation — every client crossing it resyncs full
            self._reset_chain_locked(epoch)
        if version < 0 or (self._recon is not None
                           and version == self._recon_version):
            return
        leaves, treedef = self._wire_leaves_locked()
        compatible = (
            self._recon is not None and treedef == self._treedef
            and len(leaves) == len(self._recon)
            and all(a.shape == b.shape and a.dtype == b.dtype
                    for a, b in zip(leaves, self._recon)))
        if not compatible:
            # first publish, or model surgery changed the structure:
            # the chain restarts here and outstanding bases resync
            self._reset_chain_locked(epoch, leaves, treedef, version)
            return
        seg, new_recon = self._encode_segment_locked(leaves, version)
        self._chain.append((self._recon_version, version, seg))
        while len(self._chain) > self._window:
            self._chain.popleft()
        self._recon = new_recon  # apexlint: unguarded(caller holds _lock)
        self._recon_version = version  # apexlint: unguarded(caller holds _lock)
        self._full = None  # apexlint: unguarded(caller holds _lock)

    def _encode_segment_locked(
            self, wire_leaves: list[np.ndarray],
            to_version: int) -> tuple[bytes, list[np.ndarray]]:
        metas: list[dict] = []
        bufs: list[bytes] = []
        new_recon: list[np.ndarray] = []
        assert self._recon is not None
        for r, w in zip(self._recon, wire_leaves):
            if w.dtype != np.float32:
                if np.array_equal(r, w):
                    metas.append({"e": "s"})
                    new_recon.append(r)
                else:
                    m, buf = _abs_leaf(w, self._wire_dtype)
                    metas.append(m)
                    bufs.append(buf)
                    new_recon.append(np.array(w))
                continue
            d = w - r
            lo = float(d.min()) if d.size else 0.0
            hi = float(d.max()) if d.size else 0.0
            if not (np.isfinite(lo) and np.isfinite(hi)):
                # non-finite deltas (inf/nan params) cannot quantize;
                # ship the leaf absolute and move on
                m, buf = _abs_leaf(w, self._wire_dtype)
                metas.append(m)
                bufs.append(buf)
                new_recon.append(np.array(w))
                continue
            if lo == hi:
                # constant delta (unchanged leaf / global shift): the
                # bias rides the meta, zero payload bytes
                metas.append({"e": "z", "b": lo})
                new_recon.append(r + np.float32(lo) if lo != 0.0 else r)
                continue
            scale = float(np.float32((hi - lo) / _Q8_SPAN))
            q = native.q8_encode(d, lo, scale)
            m = {"e": "q8", "lo": lo, "sc": scale}
            buf = _deflate_maybe(m, q)
            # per-leaf never-inflate guard: a quantized delta that does
            # not undercut the absolute downcast leaf ships absolute
            abs_bytes = w.size * (2 if self._wire_dtype == "bfloat16"
                                  else 4)
            if len(buf) >= abs_bytes:
                m, buf = _abs_leaf(w, self._wire_dtype)
                metas.append(m)
                bufs.append(buf)
                new_recon.append(np.array(w))
                continue
            metas.append(m)
            bufs.append(buf)
            # advance through the DEQUANTIZED delta — exactly what
            # every decoder computes — so error never compounds
            r2 = np.array(r)
            native.q8_dequant_add(r2, np.frombuffer(q, np.int8),
                                  lo, scale)
            new_recon.append(r2)
        head = {"full": 0, "v": to_version, "leaves": metas}
        seg = native.pack_records([json.dumps(head).encode()] + bufs)
        return seg, new_recon

    def _segments_from_locked(self, base_version: int) -> list[bytes] | None:
        """Chain segments replaying base_version -> current, or None
        when the base is not a cached chain node (out of window, never
        encoded, pre-reset) — the caller then resyncs full."""
        out: list[bytes] = []
        found = False
        for from_v, _to_v, seg in self._chain:
            if not found:
                if from_v != base_version:
                    continue
                found = True
            out.append(seg)
        return out if found else None

    def _full_payload_locked(self, epoch: int) -> bytes:
        """Coded full-resync payload: absolute wire-dtype leaves plus
        the pytree structure (a pickled leaf-index skeleton — the same
        container types the raw blob pickles anyway). Cached per
        (epoch, version)."""
        import jax
        version = self._params[1]
        key = (epoch, version)
        if self._full is not None and self._full[0] == key:
            return self._full[1]
        leaves, treedef = self._wire_leaves_locked()
        metas, bufs = [], []
        for w in leaves:
            m, buf = _abs_leaf(w, self._wire_dtype)
            metas.append(m)
            bufs.append(buf)
        head = {"full": 1, "v": version, "leaves": metas}
        skeleton = jax.tree_util.tree_unflatten(
            treedef, list(range(len(leaves))))
        seg = native.pack_records(
            [json.dumps(head).encode(),
             pickle.dumps(skeleton, protocol=pickle.HIGHEST_PROTOCOL)]
            + bufs)
        payload = _CODEC_HDR.pack(PARAMS_CODEC_MAGIC, epoch, version,
                                  -1) + native.pack_records([seg])
        self._full = (key, payload)  # apexlint: unguarded(caller holds _lock)
        return payload


# -- client side: chain decoder ---------------------------------------------


# apexlint: unhandled(PARAMS_HDR_MAGIC) — the decoder only ever sees
# APXC bodies: the transport sniffs the tag first and routes raw APXV
# fulls through its legacy parser, seeding this chain via note_full()
class ParamChainDecoder:
    """Reconstruction state for coded param payloads: the float32
    leaves the chain stands at, the structure to unflatten them with,
    and the (epoch, version) they correspond to. NOT thread-safe — the
    owning transport serializes access (its pull and push-reader
    threads both land here)."""

    def __init__(self):
        self._leaves: list[np.ndarray] | None = None
        self._treedef: Any = None
        self._epoch = -1
        self._version = -1

    @property
    def version(self) -> int:
        return self._version

    def reset(self) -> None:
        self._leaves = None
        self._treedef = None
        self._epoch = -1
        self._version = -1

    def note_full(self, tree: Any, version: int, epoch: int) -> None:
        """Seed/refresh the chain base from a raw-path full (legacy or
        APXV blob): a client bootstrapped over the raw plane can still
        ride deltas afterwards. The seeded base sits within wire
        rounding of the provider's chain; the offset is constant and
        collapses at the next full."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        self._leaves = [np.array(np.asarray(x)) for x in leaves]
        self._treedef = treedef
        self._version = int(version)
        self._epoch = int(epoch)

    def _tree(self) -> Any:
        import jax
        assert self._leaves is not None
        return jax.tree_util.tree_unflatten(
            self._treedef, [x.copy() for x in self._leaves])

    def apply(self, payload) -> tuple[str, Any, int, int]:
        """Apply one coded payload: ("full", tree, version, epoch) on
        success (delta chains and full resyncs both land here — the
        tree is a fresh copy, safe to hand to the actor), or
        ("resync", None, version, epoch) when the payload's base is not
        what this chain holds (missed version / epoch bump / no state)
        — the caller must then re-pull with no base. Malformed payloads
        raise ValueError."""
        mv = memoryview(payload)
        if len(mv) < _CODEC_HDR.size:
            raise ValueError("coded param payload too short")
        magic, ep, ver, base = _CODEC_HDR.unpack_from(mv)
        if magic != PARAMS_CODEC_MAGIC:
            raise ValueError("not a coded param payload")
        segs = native.unpack_records_mv(mv[_CODEC_HDR.size:])
        if base == -1:
            if len(segs) != 1:
                raise ValueError(
                    f"full resync carries {len(segs)} segments")
            self._apply_full(segs[0], ver, ep)
            return "full", self._tree(), ver, ep
        if (self._leaves is None or self._epoch != ep
                or self._version != base):
            return "resync", None, ver, ep
        v = base
        for seg in segs:
            v = self._apply_delta(seg)
        if v != ver:
            raise ValueError(
                f"chain reached version {v}, payload advertised {ver}")
        self._version = ver
        self._epoch = ep
        return "full", self._tree(), ver, ep

    def _apply_full(self, seg, ver: int, ep: int) -> None:
        import jax
        recs = native.unpack_records_mv(seg)
        head = json.loads(bytes(recs[0]))
        if not head.get("full"):
            raise ValueError("resync payload without a full segment")
        skeleton = pickle.loads(recs[1])
        treedef = jax.tree_util.tree_structure(skeleton)
        metas = head["leaves"]
        if treedef.num_leaves != len(metas):
            raise ValueError(
                f"structure has {treedef.num_leaves} leaves, "
                f"payload {len(metas)}")
        if len(recs) != 2 + len(metas):
            raise ValueError("full segment record count mismatch")
        leaves = []
        for i, m in enumerate(metas):
            if m.get("e") != "a":
                raise ValueError(
                    f"unexpected leaf encoding {m.get('e')!r} in full")
            leaves.append(_decode_abs(m, recs[2 + i]))
        self._leaves = leaves
        self._treedef = treedef
        self._version = ver
        self._epoch = ep

    def _apply_delta(self, seg) -> int:
        recs = native.unpack_records_mv(seg)
        head = json.loads(bytes(recs[0]))
        if head.get("full"):
            raise ValueError("unexpected full segment mid-chain")
        metas = head["leaves"]
        assert self._leaves is not None
        if len(metas) != len(self._leaves):
            raise ValueError(
                f"chain holds {len(self._leaves)} leaves, "
                f"segment carries {len(metas)}")
        bi = 1
        for i, m in enumerate(metas):
            e = m.get("e")
            if e == "s":
                continue
            if e == "z":
                b = float(m["b"])
                if b != 0.0:
                    self._leaves[i] += np.float32(b)
            elif e == "q8":
                buf = recs[bi]
                bi += 1
                q = zlib.decompress(buf) if m.get("zl") else buf
                leaf = self._leaves[i]
                if leaf.dtype != np.float32:
                    raise ValueError(
                        f"q8 delta against non-f32 leaf {leaf.dtype}")
                native.q8_dequant_add(leaf, np.frombuffer(q, np.int8),
                                      float(m["lo"]), float(m["sc"]))
            elif e == "a":
                buf = recs[bi]
                bi += 1
                self._leaves[i] = _decode_abs(m, buf)
            else:
                raise ValueError(f"unknown param leaf encoding {e!r}")
        if bi != len(recs):
            raise ValueError("delta segment record count mismatch")
        return int(head["v"])
