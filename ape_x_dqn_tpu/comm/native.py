"""ctypes bindings for the native framing codec (cpp/framing.cpp).

Compiled lazily via utils/native_build.py; if no compiler is available
the pure-Python fallbacks (zlib.crc32 + bytes joins) are
wire-compatible, so a C++-enabled learner host can talk to a
Python-only actor host.
"""

from __future__ import annotations

import ctypes
import os
import zlib

from ape_x_dqn_tpu.utils.native_build import build_and_load

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "cpp", "framing.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libapex_framing.so")


_lib: ctypes.CDLL | None = None
_tried = False


def _load() -> ctypes.CDLL | None:
    # module-level cache: the codec runs per ingest message; don't
    # re-enter build_and_load's lock or rebind argtypes per call
    global _lib, _tried
    if _tried:
        return _lib
    lib = build_and_load(_SRC, _SO)
    if lib is not None:
        try:
            lib.apex_crc32.restype = ctypes.c_uint32
            lib.apex_crc32.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_uint32]
            lib.apex_pack.restype = ctypes.c_uint64
            lib.apex_pack.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
            lib.apex_unpack_offsets.restype = ctypes.c_uint64
            lib.apex_unpack_offsets.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
        except AttributeError:
            lib = None  # stale .so missing a symbol: Python fallback
    _lib, _tried = lib, True
    return _lib


def have_native() -> bool:
    return _load() is not None


def crc32(data: bytes | memoryview, seed: int = 0) -> int:
    lib = _load()
    if lib is None:
        return zlib.crc32(bytes(data), seed) & 0xFFFFFFFF
    buf = bytes(data) if isinstance(data, memoryview) else data
    return int(lib.apex_crc32(buf, len(buf), seed))


def pack_records(chunks: list[bytes]) -> bytes:
    """Gather chunks into one [u64 len][bytes]* frame (native memcpy)."""
    lib = _load()
    if lib is None:
        out = bytearray()
        for c in chunks:
            out += len(c).to_bytes(8, "little") + c
        return bytes(out)
    total = sum(len(c) for c in chunks) + 8 * len(chunks)
    dst = ctypes.create_string_buffer(total)
    n = len(chunks)
    srcs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    # keep refs so the buffers stay alive across the call
    keep = []
    for i, c in enumerate(chunks):
        b = c if isinstance(c, bytes) else bytes(c)
        keep.append(b)
        srcs[i] = ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p)
        lens[i] = len(b)
    wrote = lib.apex_pack(ctypes.cast(dst, ctypes.c_void_p), srcs, lens, n)
    assert wrote == total, (wrote, total)
    return dst.raw


def unpack_records(frame: bytes, max_records: int = 4096) -> list[bytes]:
    """Inverse of pack_records; raises ValueError on malformed frames."""
    lib = _load()
    if lib is None:
        out, off = [], 0
        ln = len(frame)
        while off < ln:
            if off + 8 > ln:
                raise ValueError("malformed frame")
            rec = int.from_bytes(frame[off:off + 8], "little")
            off += 8
            if off + rec > ln:
                raise ValueError("malformed frame")
            out.append(frame[off:off + rec])
            off += rec
        return out
    offs = (ctypes.c_uint64 * max_records)()
    lens = (ctypes.c_uint64 * max_records)()
    n = lib.apex_unpack_offsets(frame, len(frame), offs, lens, max_records)
    if n == ctypes.c_uint64(-1).value:
        raise ValueError("malformed frame")
    return [frame[offs[i]:offs[i] + lens[i]] for i in range(n)]
