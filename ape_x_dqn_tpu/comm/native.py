"""ctypes bindings for the native framing codec (cpp/framing.cpp).

Compiled lazily via utils/native_build.py; if no compiler is available
the pure-Python fallbacks (zlib.crc32 + bytes joins) are
wire-compatible, so a C++-enabled learner host can talk to a
Python-only actor host.

Every entry point accepts bytes, bytearray, or (1-D, contiguous)
memoryview without copying: the ingest hot path hands `socket.recv_into`
buffers and numpy array views straight through, so the only per-message
copy left is the wire->staging landing itself (see
socket_transport.decode_batch_into).
"""

from __future__ import annotations

import ctypes
import os
import zlib

from ape_x_dqn_tpu.utils.native_build import build_and_load

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "cpp", "framing.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "libapex_framing.so")


_lib: ctypes.CDLL | None = None
_tried = False
_has_delta = False
_has_q8 = False

Buffer = bytes | bytearray | memoryview


def _load() -> ctypes.CDLL | None:
    # module-level cache: the codec runs per ingest message; don't
    # re-enter build_and_load's lock or rebind argtypes per call
    global _lib, _tried, _has_delta, _has_q8
    if _tried:
        return _lib
    lib = build_and_load(_SRC, _SO)
    if lib is not None:
        try:
            # c_void_p (not c_char_p) for the data pointers so writable
            # buffers (bytearray, numpy views) pass without a bytes copy
            lib.apex_crc32.restype = ctypes.c_uint32
            lib.apex_crc32.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_uint32]
            lib.apex_pack.restype = ctypes.c_uint64
            lib.apex_pack.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
            lib.apex_unpack_offsets.restype = ctypes.c_uint64
            lib.apex_unpack_offsets.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
        except AttributeError:
            lib = None  # stale .so missing a symbol: Python fallback
    if lib is not None:
        try:
            # delta symbols bound separately: a stale .so predating the
            # wire codec still serves crc/pack, and only the delta
            # transform falls back to numpy (wire-compatible either way)
            lib.apex_delta_encode.restype = None
            lib.apex_delta_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_uint64]
            lib.apex_delta_undo.restype = None
            lib.apex_delta_undo.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
            _has_delta = True
        except AttributeError:
            _has_delta = False
    if lib is not None:
        try:
            # q8 symbols likewise bound separately (param-plane codec,
            # comm/param_codec.py): a stale .so predating it degrades
            # only the quantizer to the bit-identical numpy fallback
            lib.apex_q8_encode.restype = None
            lib.apex_q8_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_float, ctypes.c_float]
            lib.apex_q8_dequant_add.restype = None
            lib.apex_q8_dequant_add.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_float, ctypes.c_float]
            _has_q8 = True
        except AttributeError:
            _has_q8 = False
    _lib, _tried = lib, True
    return _lib


def have_native() -> bool:
    return _load() is not None


def have_delta_native() -> bool:
    _load()
    return _has_delta


def have_q8_native() -> bool:
    _load()
    return _has_q8


def _addr(data: Buffer) -> tuple[ctypes.c_void_p, int, object]:
    """(pointer, length, keepalive) for a bytes-like object, copy-free
    where the buffer protocol allows it. The keepalive object must stay
    referenced for the duration of the native call."""
    if isinstance(data, bytes):
        return (ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p),
                len(data), data)
    mv = data if isinstance(data, memoryview) else memoryview(data)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    if not mv.contiguous:
        b = mv.tobytes()  # non-contiguous: copy is unavoidable
        return (ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p),
                len(b), b)
    n = mv.nbytes
    if n == 0:
        return ctypes.c_void_p(0), 0, mv
    if mv.readonly:
        # ctypes' from_buffer needs a writable buffer; a readonly view
        # over bytes already has a stable address via the bytes object
        obj = mv.obj
        if isinstance(obj, bytes) and len(obj) == n:
            return (ctypes.cast(ctypes.c_char_p(obj), ctypes.c_void_p),
                    n, obj)
        b = mv.tobytes()
        return (ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p),
                len(b), b)
    arr = (ctypes.c_ubyte * n).from_buffer(mv)
    # addressof, NOT ctypes.cast(arr, ...): cast's keepalive bookkeeping
    # puts the array into a reference cycle, so the buffer export it
    # holds survives until a gc pass — which pins shared-memory
    # segments (BufferError on SharedMemory.close) long after the call
    # returned. addressof is a plain int; the _keep tuple alone bounds
    # the export's lifetime to this call, released by refcount.
    return ctypes.c_void_p(ctypes.addressof(arr)), n, (arr, mv)


def crc32(data: Buffer, seed: int = 0) -> int:
    lib = _load()
    if lib is None:
        return zlib.crc32(data, seed) & 0xFFFFFFFF
    ptr, n, _keep = _addr(data)
    return int(lib.apex_crc32(ptr, n, seed))


def pack_records(chunks: list[Buffer]) -> bytes:
    """Gather chunks into one [u64 len][bytes]* frame (native memcpy)."""
    lib = _load()
    if lib is None:
        out = bytearray()
        for c in chunks:
            mv = c if isinstance(c, (bytes, bytearray)) \
                else memoryview(c).cast("B")
            out += len(mv).to_bytes(8, "little") + mv
        return bytes(out)
    n = len(chunks)
    srcs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    # keep refs so the buffers stay alive across the call
    keep = []
    total = 0
    for i, c in enumerate(chunks):
        ptr, ln, ka = _addr(c)
        keep.append(ka)
        srcs[i] = ptr
        lens[i] = ln
        total += ln + 8
    dst = ctypes.create_string_buffer(total)
    wrote = lib.apex_pack(ctypes.cast(dst, ctypes.c_void_p), srcs, lens, n)
    assert wrote == total, (wrote, total)
    return dst.raw


def _unpack_offsets(frame: Buffer,
                    max_records: int) -> list[tuple[int, int]]:
    """[(offset, length)] per record — the shared walk behind both the
    copying and memoryview unpack forms."""
    lib = _load()
    if lib is None:
        out, off = [], 0
        mv = frame if isinstance(frame, (bytes, bytearray)) \
            else memoryview(frame).cast("B")
        ln = len(mv)
        while off < ln:
            if off + 8 > ln:
                raise ValueError("malformed frame")
            rec = int.from_bytes(mv[off:off + 8], "little")
            off += 8
            if off + rec > ln:
                raise ValueError("malformed frame")
            out.append((off, rec))
            off += rec
        return out
    offs = (ctypes.c_uint64 * max_records)()
    lens = (ctypes.c_uint64 * max_records)()
    ptr, ln, _keep = _addr(frame)
    n = lib.apex_unpack_offsets(ptr, ln, offs, lens, max_records)
    if n == ctypes.c_uint64(-1).value:
        raise ValueError("malformed frame")
    return [(offs[i], lens[i]) for i in range(n)]


def unpack_records(frame: Buffer, max_records: int = 4096) -> list[bytes]:
    """Inverse of pack_records; raises ValueError on malformed frames."""
    return [bytes(frame[o:o + ln])
            for o, ln in _unpack_offsets(frame, max_records)]


def unpack_records_mv(frame: Buffer,
                      max_records: int = 4096) -> list[memoryview]:
    """Zero-copy unpack: memoryview slices into `frame` itself. The
    views alias the frame — the caller must keep the frame alive and
    unmodified while they are in use (the ingest staging path copies
    them into the staging block immediately; that landing is the ONE
    copy per wire byte)."""
    mv = memoryview(frame)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    return [mv[o:o + ln] for o, ln in _unpack_offsets(frame, max_records)]


# -- XOR-delta transform (wire codec "delta-deflate") -----------------------


def delta_encode(rows2d) -> "bytes":
    """XOR-delta a C-contiguous (rows, row_bytes) uint8 array along its
    leading axis: out[0] = rows2d[0], out[i] = rows2d[i] ^ rows2d[i-1].
    Returns the delta bytes (the deflate input on the encode side)."""
    import numpy as np

    a = np.ascontiguousarray(rows2d, dtype=np.uint8)
    lib = _load()
    if lib is None or not _has_delta or a.shape[0] == 0:
        out = np.empty_like(a)
        if a.shape[0]:
            out[0] = a[0]
            np.bitwise_xor(a[1:], a[:-1], out=out[1:])
        return out.tobytes()
    out = np.empty_like(a)
    dptr, _, dkeep = _addr(memoryview(out).cast("B"))
    sptr, _, skeep = _addr(memoryview(a).cast("B"))
    lib.apex_delta_encode(dptr, sptr, a.shape[0], a.shape[1])
    del dkeep, skeep
    return out.tobytes()


def delta_undo_inplace(rows2d) -> None:
    """Prefix-XOR undo IN PLACE on a writable C-contiguous
    (rows, row_bytes) uint8 array: rows2d[i] ^= rows2d[i-1] for
    i = 1..rows-1. Row 0 must already be absolute — on the ingest path
    the caller lands delta rows straight in the staging block, fixes
    row 0 up against the previous landed row, then calls this."""
    import numpy as np

    a = rows2d
    if a.shape[0] <= 1:
        return
    lib = _load()
    if lib is None or not _has_delta:
        # ufunc accumulate is the vectorized-per-row C path in numpy:
        # absolute[i] = delta[0] ^ delta[1] ^ ... ^ delta[i]
        np.bitwise_xor.accumulate(a, axis=0, out=a)
        return
    ptr, _, keep = _addr(memoryview(a).cast("B"))
    lib.apex_delta_undo(ptr, a.shape[0], a.shape[1])
    del keep


# -- int8 affine quantization (param codec "delta-q8") ----------------------
#
# The numpy fallbacks mirror the C kernels operation-for-operation in
# strict float32 (np.rint and nearbyintf both round half to even), so a
# native-enabled learner and a Python-only actor host reconstruct the
# SAME chain base — cross-impl parity is a wire contract here, pinned
# by test_param_codec.py.


def q8_encode(delta, lo: float, scale: float) -> bytes:
    """Quantize a C-contiguous float32 array to int8 bins:
    q = clip(rint((x - lo) / scale) - 127, -128, 127)."""
    import numpy as np

    a = np.ascontiguousarray(delta, dtype=np.float32).reshape(-1)
    lib = _load()
    if lib is None or not _has_q8 or a.size == 0:
        lo32, scale32 = np.float32(lo), np.float32(scale)
        q = np.rint((a - lo32) / scale32)
        return np.clip(q - np.float32(127.0), -128.0,
                       127.0).astype(np.int8).tobytes()
    out = np.empty(a.size, dtype=np.int8)
    dptr, _, dkeep = _addr(memoryview(out).cast("B"))
    sptr, _, skeep = _addr(memoryview(a).cast("B"))
    lib.apex_q8_encode(dptr, sptr, a.size,
                       ctypes.c_float(lo), ctypes.c_float(scale))
    del dkeep, skeep
    return out.tobytes()


def q8_dequant_add(base, q, lo: float, scale: float) -> None:
    """Dequantize-and-accumulate IN PLACE into a writable C-contiguous
    float32 array: base += (q + 127) * scale + lo — the decode side of
    q8_encode and the encoder's own chain advance."""
    import numpy as np

    b = base.reshape(-1)
    qa = np.frombuffer(q, dtype=np.int8) if not isinstance(q, np.ndarray) \
        else q.reshape(-1)
    if b.size != qa.size:
        raise ValueError(f"q8 length mismatch: base {b.size} vs q {qa.size}")
    lib = _load()
    if lib is None or not _has_q8 or b.size == 0:
        lo32, scale32 = np.float32(lo), np.float32(scale)
        d = (qa.astype(np.float32) + np.float32(127.0)) * scale32
        d += lo32
        b += d
        return
    bptr, _, bkeep = _addr(memoryview(b).cast("B"))
    qptr, _, qkeep = _addr(memoryview(qa).cast("B"))
    lib.apex_q8_dequant_add(bptr, qptr, b.size,
                            ctypes.c_float(lo), ctypes.c_float(scale))
    del bkeep, qkeep
