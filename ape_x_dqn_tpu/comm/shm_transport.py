"""Same-host shared-memory data plane: experience ring + seqlock params.

Every actor->learner byte on a single host otherwise pays the full TCP
loopback tax — encode into a send buffer, kernel copy down, kernel copy
up, decode into staging — plus the delta-deflate codec built for
bandwidth-constrained links. This module is the mechanism half of the
shm transport (PR 18): fixed-slot rings and a seqlock param area in
`multiprocessing.shared_memory` segments, so a same-host peer ships
experience as ONE copy (actor arrays -> claimed slot; the learner's
staging landing is the same one copy the TCP mv path already pays) and
pulls params with zero per-client serialization.

The PROTOCOL half stays in socket_transport.py: segments are negotiated
over the existing MSG_HELLO/MSG_HELLO_ACK capability exchange, data
slots are announced with tiny MSG_SHM_DOORBELL frames on the existing
TCP control socket (so reconnect/backoff, epoch machinery, backpressure
latches, chaos injection and drop accounting all keep working
untouched), and every shm failure mode degrades to plain TCP.

Correctness model (no cross-process locks anywhere):

- Ring slots are single-writer/single-freeer: the CLIENT is the only
  process that marks a slot claimed (its sends serialize under the
  transport's _send_lock), the SERVER is the only one that marks it
  free. The slot-state byte array in the segment IS the free-list
  doorbell — freeing is one byte store, claiming is a scan for
  SLOT_FREE.
- A doorbell carries (slot, seq, nbytes, crc); the server re-reads the
  slot header and re-checksums the payload before delivering. A writer
  dying mid-write either never rings (the server reclaims the lease on
  disconnect) or rings with a mismatched crc/seq — the torn slot is
  counted and freed, NEVER delivered.
- The param area is a classic even/odd seqlock: the server bumps the
  sequence to odd, writes blob+metadata, bumps to even. A reader that
  observes an odd or changed sequence (or a crc mismatch) retries and
  eventually falls back to the TCP param path.
"""

from __future__ import annotations

import json
import secrets
import struct
import time
from multiprocessing import resource_tracker, shared_memory

import numpy as np

from ape_x_dqn_tpu.comm import native
from ape_x_dqn_tpu.obs.health import make_lock

RING_MAGIC = 0x53524E47   # 'SRNG'
PARAM_MAGIC = 0x53505231  # 'SPR1'

_RING_HDR = struct.Struct("<IIQ")  # magic, slot count, slot payload bytes
_SLOT_HDR = struct.Struct("<QQ")   # seq, payload nbytes

SLOT_FREE = 0
SLOT_CLAIMED = 1

# param-area header layout (fixed offsets, not one packed struct: the
# seq field is written twice per publish and read standalone)
_PAR_MAGIC_OFF = 0     # u32
_PAR_SEQ_OFF = 8       # u64, even = stable, odd = write in progress
_PAR_NBYTES_OFF = 16   # u64, 0 = no blob (unpublished or oversize)
_PAR_CRC_OFF = 24      # u32 over the blob bytes
_PAR_EPOCH_OFF = 32    # i64 membership epoch of the held blob
_PAR_VERSION_OFF = 40  # i64 param version of the held blob
_PAR_HDR_SIZE = 48

_PROBE_BYTES = 16

_BOOT_ID: str | None = None


def boot_id() -> str:
    """This host's boot id — the cheap first gate of the same-host
    probe (two processes on one boot share it; distinct hosts or a
    rebooted peer cannot). Empty string when unreadable, which refuses
    shm on both sides."""
    global _BOOT_ID
    if _BOOT_ID is None:
        try:
            with open("/proc/sys/kernel/random/boot_id") as fh:
                _BOOT_ID = fh.read().strip()
        except OSError:  # apexlint: lossy(no boot id -> shm never negotiates, TCP fallback)
            _BOOT_ID = ""
    return _BOOT_ID


_ATTACH_LOCK = make_lock("shm_transport._ATTACH_LOCK")


def attach(name: str) -> shared_memory.SharedMemory:
    """Attach an existing segment WITHOUT adopting cleanup ownership.

    Python 3.10's SharedMemory registers every attach with the
    resource tracker, which then unlinks segments it never owned at
    interpreter exit (fixed by track=False in 3.13, unavailable here) —
    an attacher that outlives the creator would tear the segment out
    from under other peers and spam leak warnings. Registration is
    suppressed for the attach only (unregistering after the fact would
    double-unregister when creator and attacher share a process, e.g.
    every loopback test); creator-side registration is kept, so if the
    owning process dies the tracker still reclaims /dev/shm space."""
    with _ATTACH_LOCK:
        orig = resource_tracker.register
        try:
            resource_tracker.register = lambda *a, **k: None
            seg = shared_memory.SharedMemory(name=name)
        finally:
            resource_tracker.register = orig
    return seg


def make_probe() -> tuple[shared_memory.SharedMemory, str]:
    """Create the client's namespace probe: a tiny segment holding a
    random token. A server that can attach it and read the token back
    shares the client's /dev/shm namespace (containers on one host
    with private IPC namespaces fail here even though boot ids match).
    The client unlinks the probe after the hello exchange."""
    seg = shared_memory.SharedMemory(create=True, size=_PROBE_BYTES)
    token = secrets.token_bytes(_PROBE_BYTES)
    seg.buf[:_PROBE_BYTES] = token
    return seg, token.hex()


def check_probe(name: str, token_hex: str, peer_boot: str) -> bool:
    """Server-side same-host verification of a hello shm offer: boot
    ids must match AND the client's probe segment must be attachable
    with the advertised token. Any failure refuses the grant (the
    connection stays plain TCP)."""
    if not name or not token_hex or not peer_boot \
            or peer_boot != boot_id():
        return False
    try:
        seg = attach(name)
    except (OSError, ValueError):  # apexlint: lossy(probe unreachable -> different namespace, grant refused)
        return False
    try:
        return bytes(seg.buf[:_PROBE_BYTES]).hex() == token_hex
    finally:
        seg.close()


def pack_batch_into(batch: dict, dest: memoryview) -> int | None:
    """Pack an experience dict into `dest` in EXACTLY the raw
    encode_batch wire layout (pack_records framing, JSON meta as the
    first record) — a slot decodes with the same WireBatch machinery
    as a TCP payload. Returns bytes written, or None when the batch
    does not fit (the caller ships that batch over TCP instead).

    This is the actor-side half of the one-copy invariant: each array's
    bytes move STRAIGHT from the actor's buffer into the shared
    segment — no codec, no intermediate frame, no sendall."""
    meta: list[dict] = []
    arrays: list[np.ndarray] = []
    for k, v in batch.items():
        if isinstance(v, np.ndarray):
            if not v.flags["C_CONTIGUOUS"]:
                v = np.ascontiguousarray(v)
            meta.append({"k": k, "nd": True, "dt": v.dtype.str,
                         "sh": list(v.shape)})
            arrays.append(v)
        else:
            meta.append({"k": k, "nd": False, "v": v})
    hdr = json.dumps(meta).encode()
    total = 8 + len(hdr) + sum(8 + a.nbytes for a in arrays)
    if total > len(dest):
        return None
    off = 0
    dest[off:off + 8] = len(hdr).to_bytes(8, "little")
    off += 8
    dest[off:off + len(hdr)] = hdr
    off += len(hdr)
    for a in arrays:
        n = a.nbytes
        dest[off:off + 8] = n.to_bytes(8, "little")
        off += 8
        if n:
            dest[off:off + n] = memoryview(a).cast("B")
            off += n
    return off


class ShmRingServer:
    """Server-owned experience ring: creates the segment, validates
    doorbells against the in-slot header + crc, and frees slots once
    the consumer has landed the rows (ShmSlotBatch.release). Lives
    exactly as long as its client connection; `retire` reclaims the
    leases of a dead writer.

    Segment layout:
        [_RING_HDR][state byte x slots][(_SLOT_HDR + slot_bytes) x slots]
    """

    def __init__(self, slots: int, slot_bytes: int):
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        size = _RING_HDR.size + self.slots \
            + self.slots * (_SLOT_HDR.size + self.slot_bytes)
        # apexlint: releases(_seg, unlink<close)
        self._seg = shared_memory.SharedMemory(create=True, size=size)
        buf = self._seg.buf
        _RING_HDR.pack_into(buf, 0, RING_MAGIC, self.slots,
                            self.slot_bytes)
        for i in range(self.slots):
            buf[_RING_HDR.size + i] = SLOT_FREE
        self.name = self._seg.name
        self._lock = make_lock("shm_ring._lock")
        # slots delivered to the consumer and not yet freed — they pin
        # the mapping open past retire() (their memoryviews alias it)
        self._delivered: set[int] = set()  # guarded-by: _lock
        self._doomed = False  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock

    def _slot_off(self, slot: int) -> int:
        return _RING_HDR.size + self.slots \
            + slot * (_SLOT_HDR.size + self.slot_bytes)

    def take(self, slot: int, seq: int, nbytes: int,
             crc: int) -> memoryview | None:
        """Validate one doorbell and return the slot's payload
        memoryview (zero-copy; freed via free()), or None when the
        slot is torn — wrong index/size, header mismatch, or crc
        failure. A torn slot is freed here and never delivered."""
        if not (0 <= slot < self.slots) \
                or not (0 < nbytes <= self.slot_bytes):
            return None
        with self._lock:
            if self._closed or slot in self._delivered:
                return None
            off = self._slot_off(slot)
            sseq, snbytes = _SLOT_HDR.unpack_from(self._seg.buf, off)
            if sseq != seq or snbytes != nbytes:
                self._free_locked(slot)
                return None
            view = self._seg.buf[off + _SLOT_HDR.size:
                                 off + _SLOT_HDR.size + nbytes]
            if native.crc32(view) != crc:
                view.release()
                self._free_locked(slot)
                return None
            self._delivered.add(slot)
        return view

    def free(self, slot: int) -> None:
        """Return a slot to the writer's free list (idempotent) — the
        one-byte state store IS the free-list doorbell the client's
        claim scan watches."""
        if not (0 <= slot < self.slots):
            return
        with self._lock:
            self._free_locked(slot)

    def _free_locked(self, slot: int) -> None:
        self._delivered.discard(slot)  # apexlint: unguarded(caller holds _lock)
        if not self._closed:
            self._seg.buf[_RING_HDR.size + slot] = SLOT_FREE
        self._close_if_drained_locked()

    @property
    def inflight(self) -> int:
        """Slots currently claimed by the writer (including delivered
        batches the consumer has not freed yet)."""
        with self._lock:
            if self._closed:
                return 0
            base = _RING_HDR.size
            return sum(1 for i in range(self.slots)
                       if self._seg.buf[base + i] != SLOT_FREE)

    def retire(self) -> int:
        """Reclaim the ring when its writer's connection is gone:
        unlink the segment name and count the leases the dead writer
        held (claimed but never delivered — a doorbell that DID arrive
        is either queued, consumed, or was counted torn). The unmap is
        deferred until delivered-but-unconsumed batches drain; their
        views stay valid because unlink only removes the name."""
        with self._lock:
            if self._doomed:
                return 0
            self._doomed = True  # apexlint: unguarded(holds _lock)
            base = _RING_HDR.size
            claimed = 0 if self._closed else \
                sum(1 for i in range(self.slots)
                    if self._seg.buf[base + i] != SLOT_FREE)
            reclaimed = max(claimed - len(self._delivered), 0)
            try:
                self._seg.unlink()
            except OSError:  # apexlint: lossy(name already gone; nothing left to reclaim)
                pass
            self._close_if_drained_locked()
        return reclaimed

    def _close_if_drained_locked(self) -> None:
        if self._doomed and not self._delivered and not self._closed:
            try:
                self._seg.close()
                self._closed = True  # apexlint: unguarded(caller holds _lock)
            except BufferError:
                # a stray exported view (e.g. an unreleased batch held
                # by a test) still pins the mapping; the next free()
                # retries, process exit unmaps regardless
                pass

    def destroy(self) -> None:
        """Server-shutdown teardown: retire if not already retired."""
        self.retire()


class ShmRingWriter:
    """Client half of the ring: attaches the server-granted segment
    and packs batches straight into claimed slots. Single-threaded by
    contract — the transport's sends serialize under _send_lock."""

    def __init__(self, name: str):
        self._seg = attach(name)
        magic, slots, slot_bytes = _RING_HDR.unpack_from(self._seg.buf, 0)
        if magic != RING_MAGIC:
            self._seg.close()
            raise ValueError("not a shm ring segment")
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self._seq = 0
        self._hint = 0
        self._closed = False

    def _claim(self) -> int | None:
        base = _RING_HDR.size
        buf = self._seg.buf
        for d in range(self.slots):
            i = (self._hint + d) % self.slots
            if buf[base + i] == SLOT_FREE:
                buf[base + i] = SLOT_CLAIMED
                self._hint = (i + 1) % self.slots
                return i
        return None

    def post(self, batch: dict) -> tuple[int, int, int, int] | None:
        """Claim a slot and pack `batch` into it (the one copy).
        Returns the doorbell tuple (slot, seq, nbytes, crc), or None
        when every slot is in flight or the batch outsizes a slot —
        the caller ships that batch over TCP and counts the
        fallback."""
        if self._closed:
            return None
        slot = self._claim()
        if slot is None:
            return None
        off = _RING_HDR.size + self.slots \
            + slot * (_SLOT_HDR.size + self.slot_bytes)
        payload = self._seg.buf[off + _SLOT_HDR.size:
                                off + _SLOT_HDR.size + self.slot_bytes]
        try:
            n = pack_batch_into(batch, payload)
            if n is None:
                self.release(slot)
                return None
            self._seq += 1
            _SLOT_HDR.pack_into(self._seg.buf, off, self._seq, n)
            crc = native.crc32(payload[:n])
        finally:
            payload.release()
        return slot, self._seq, n, crc

    def release(self, slot: int) -> None:
        """Undo a claim whose doorbell never reached the server (send
        failure, oversize batch) so the slot is not leaked."""
        if 0 <= slot < self.slots and not self._closed:
            self._seg.buf[_RING_HDR.size + slot] = SLOT_FREE

    @property
    def free_slots(self) -> int:
        if self._closed:
            return 0
        base = _RING_HDR.size
        return sum(1 for i in range(self.slots)
                   if self._seg.buf[base + i] == SLOT_FREE)

    def close(self) -> None:
        """Detach (never unlink — the server owns the segment)."""
        if not self._closed:
            self._closed = True
            try:
                self._seg.close()
            except BufferError:
                pass  # stray view; process exit unmaps


class ShmParamArea:
    """Server-side seqlock param publication area: ONE region every
    local client reads, replacing per-client pickled MSG_PARAMS blobs.
    Written only by the server's push thread; torn reads are the
    reader's problem by design (detected via seq/crc, retried)."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        # apexlint: releases(_seg, unlink<close)
        self._seg = shared_memory.SharedMemory(
            create=True, size=_PAR_HDR_SIZE + self.capacity)
        buf = self._seg.buf
        struct.pack_into("<I", buf, _PAR_MAGIC_OFF, PARAM_MAGIC)
        struct.pack_into("<Q", buf, _PAR_SEQ_OFF, 0)
        struct.pack_into("<Q", buf, _PAR_NBYTES_OFF, 0)
        struct.pack_into("<q", buf, _PAR_EPOCH_OFF, -1)
        struct.pack_into("<q", buf, _PAR_VERSION_OFF, -1)
        self.name = self._seg.name
        # (epoch, version) currently held — the push loop's dedupe, so
        # a late shm grant can republish current params without a new
        # publish_params call
        self.holds: tuple[int, int] = (-1, -1)
        self.writes = 0
        self._seq = 0
        self._destroyed = False

    def write(self, blob: bytes, epoch: int, version: int) -> bool:
        """Publish one blob under the seqlock. An oversize blob
        publishes an nbytes=0 marker instead — readers see the fresh
        (epoch, version), find no blob, and fall back to the TCP param
        path. Returns whether the blob itself landed."""
        if self._destroyed:
            return False
        buf = self._seg.buf
        n = len(blob)
        fits = n <= self.capacity
        self._seq += 1  # odd: write in progress
        struct.pack_into("<Q", buf, _PAR_SEQ_OFF, self._seq)
        if fits:
            buf[_PAR_HDR_SIZE:_PAR_HDR_SIZE + n] = blob
        struct.pack_into("<Q", buf, _PAR_NBYTES_OFF, n if fits else 0)
        struct.pack_into("<I", buf, _PAR_CRC_OFF,
                         native.crc32(blob) if fits else 0)
        struct.pack_into("<q", buf, _PAR_EPOCH_OFF, epoch)
        struct.pack_into("<q", buf, _PAR_VERSION_OFF, version)
        self._seq += 1  # even: stable
        struct.pack_into("<Q", buf, _PAR_SEQ_OFF, self._seq)
        self.holds = (epoch, version)
        self.writes += 1
        return fits

    def destroy(self) -> None:
        if not self._destroyed:
            self._destroyed = True
            try:
                self._seg.unlink()
            except OSError:  # apexlint: lossy(name already gone)
                pass
            try:
                self._seg.close()
            except BufferError:
                pass  # stray reader view in-process; exit unmaps


class ShmParamReader:
    """Client half of the param seqlock: attaches the server's area
    and reads (blob, epoch, version) snapshots, detecting torn reads
    via the sequence counter and the blob crc."""

    def __init__(self, name: str):
        self._seg = attach(name)
        (magic,) = struct.unpack_from("<I", self._seg.buf, _PAR_MAGIC_OFF)
        if magic != PARAM_MAGIC:
            self._seg.close()
            raise ValueError("not a shm param area")
        self.capacity = self._seg.size - _PAR_HDR_SIZE
        self.torn_retries = 0
        self._closed = False

    def _hdr(self) -> tuple[int, int, int, int, int]:
        buf = self._seg.buf
        (seq,) = struct.unpack_from("<Q", buf, _PAR_SEQ_OFF)
        (n,) = struct.unpack_from("<Q", buf, _PAR_NBYTES_OFF)
        (crc,) = struct.unpack_from("<I", buf, _PAR_CRC_OFF)
        (ep,) = struct.unpack_from("<q", buf, _PAR_EPOCH_OFF)
        (ver,) = struct.unpack_from("<q", buf, _PAR_VERSION_OFF)
        return seq, n, crc, ep, ver

    def _seq_now(self) -> int:
        (seq,) = struct.unpack_from("<Q", self._seg.buf, _PAR_SEQ_OFF)
        return seq

    def read(self, have_epoch: int, have_version: int,
             retries: int = 8) -> tuple[str, bytes | None, int, int] | None:
        """One coherent snapshot: (status, blob, epoch, version) with
        status "full" (blob attached), "unchanged" (caller already
        holds this (epoch, version)), "empty" (nothing published yet)
        or "oversize" (blob only available over TCP). None after
        `retries` torn attempts — the caller falls back to the TCP
        param path, which is always correct."""
        if self._closed:
            return None
        for attempt in range(retries):
            if attempt:
                self.torn_retries += 1
                time.sleep(0.0002 * attempt)  # let the writer finish
            seq0, n, crc, ep, ver = self._hdr()
            if seq0 & 1:
                continue  # writer mid-publish
            if (ep, ver) == (-1, -1):
                if self._seq_now() != seq0:
                    continue
                return "empty", None, -1, -1
            if (ep, ver) == (have_epoch, have_version):
                if self._seq_now() != seq0:
                    continue
                return "unchanged", None, ep, ver
            if n == 0:
                if self._seq_now() != seq0:
                    continue
                return "oversize", None, ep, ver
            if n > self.capacity:
                continue  # header torn across a resize-free area: retry
            blob = bytes(self._seg.buf[_PAR_HDR_SIZE:_PAR_HDR_SIZE + n])
            if self._seq_now() != seq0 or native.crc32(blob) != crc:
                continue
            return "full", blob, ep, ver
        return None

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._seg.close()
            except BufferError:
                pass  # stray view; process exit unmaps
