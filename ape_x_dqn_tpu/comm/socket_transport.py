"""TCP transport: actor hosts -> learner host over DCN.

The reference crosses hosts with gRPC (SURVEY.md §2.2 "Comm: gRPC",
§2.3 item 3); the TPU-native runtime keeps ICI for learner collectives
and weight publication (parallel/dist_learner.py) and uses this plain
TCP layer only for the host-side paths: experience ingest into the
learner host and parameter pulls by actor hosts.

Wire format (both directions), assembled/verified by the native codec
(comm/native.py -> cpp/framing.cpp, Python-fallback compatible):

    [u32 magic 'APEX'][u8 type][u32 crc32(payload)][u64 len][payload]

Experience payloads are pack_records([json header, raw array bytes...])
— zero pickle on the hot path. Parameter payloads (low-rate control
plane) are pickled pytrees.

Semantics match LoopbackTransport: ingest is lossy-tolerant (bounded
queue, drop-oldest under backpressure; a dead learner connection drops
batches rather than killing the actor), so actor loss / learner restart
degrade gracefully (SURVEY.md §5 failure detection).

WIRE-FORMAT COMPATIBILITY: the round-4 bf16 param wire is a pickle-level
break — param blobs now carry _Bf16Wire marker objects, which a PRE-bf16
actor-host build cannot unpickle (its get_params fails the load and the
actor silently stays on stale params; only builds at/after the change
log the skew warning). Mixed-build fleets must either upgrade actor
hosts first or run the learner with --param-wire-dtype float32, whose
blobs remain loadable by every build. Same-build fleets (the supported
deployment) are unaffected.

WIRE CODEC ("delta-deflate", default-on, CommConfig.wire_codec): the
ingest wire is the measured #1 live bottleneck (PERF.md round-4 re-soak:
10.5 MB/s sustained, ~9.7KB/transition), so experience leaves are
compressed per-leaf before framing: uint8 frame rows ship as XOR-delta
against the previous row in the block (temporally adjacent frames ->
mostly-zero deltas; native fast path in cpp/framing.cpp) followed by
stdlib zlib deflate; bool leaves bit-pack (np.packbits) + deflate;
integer leaves deflate (RLE-grade on action/done streams); float leaves
stay raw (incompressible). Each leaf's encoding rides the JSON meta
header ("enc" tag), with a per-leaf raw fallback whenever compression
would not shrink it — so a codec payload is fully self-describing.
Codec payloads use a distinct message type (MSG_EXPERIENCE_C) and are
only sent after a connect-time hello/ack negotiation: a new client
offers its codec (MSG_HELLO), a new server answers with the agreed
choice (MSG_HELLO_ACK), an OLD server silently ignores the hello (its
reader drops unknown types) and the client falls back to raw on the ack
timeout. Old clients never send a hello and keep sending raw
MSG_EXPERIENCE, which every server still accepts — old<->new peers
interoperate in both directions.

TELEMETRY (MSG_TELEMETRY): per-peer obs snapshot frames — JSON objects
carrying a peer id, heartbeat ages, counter/gauge scalars, histogram
snapshots, and span aggregates — ride the experience socket as a
low-rate control plane, so the learner's fleet aggregator
(obs/fleet.py) can merge every peer's instruments into the single run
JSONL and feed remote heartbeats to the stall watchdog. The capability
negotiates over the same hello/ack: a new client adds "telemetry" to
its offer (sending the hello even when its codec is raw), a new server
echoes the grant in the ack, an old server times the hello out (the
client then never ships frames), and an old client never offers it.
A connection that carried at least one telemetry frame is an
IDENTIFIED peer: its socket closing is attributed (peer_disconnects +
a warning naming the peer + the on_disconnect hook) instead of being
silent actor loss.
"""

from __future__ import annotations

import json
import logging
import pickle
import queue
import socket
import struct
import threading
import time
import zlib
from typing import Any

import numpy as np

from ape_x_dqn_tpu.comm import native
from ape_x_dqn_tpu.obs.health import make_lock

MAGIC = 0x41504558  # 'APEX'
MSG_EXPERIENCE = 1
MSG_PARAMS_REQ = 2
MSG_PARAMS = 3
MSG_HELLO = 4          # client codec offer (JSON), sent on connect
MSG_HELLO_ACK = 5      # server's codec choice (JSON)
MSG_EXPERIENCE_C = 6   # experience payload with codec-encoded leaves
MSG_TELEMETRY = 7      # per-peer obs snapshot frame (JSON), negotiated

WIRE_CODECS = ("raw", "delta-deflate")

_HDR = struct.Struct("<IBIQ")  # magic, type, crc, payload_len
MAX_PAYLOAD = 1 << 31
_WARNED_BAD_BLOB = False

# delta+deflate only pays on frame-sized rows; small rows (actions,
# rewards) would spend more header than they save
_DELTA_MIN_ROW_BYTES = 1024
# Z_BEST_SPEED: the encoder runs on actor-host CPUs next to env
# stepping; on mostly-zero XOR deltas level 1 already collapses runs,
# higher levels buy single-digit % ratio for multiples of encode time
_DEFLATE_LEVEL = 1


def _check_codec(codec: str) -> str:
    if codec not in WIRE_CODECS:
        raise ValueError(
            f"wire_codec must be one of {WIRE_CODECS}, got {codec!r}")
    return codec


# -- codec ------------------------------------------------------------------


def _encode_leaf(v: np.ndarray) -> tuple[str, bytes] | None:
    """(enc tag, compressed bytes) for one array leaf under the
    delta-deflate codec, or None to ship it raw. Per-leaf policy:
    frame-like uint8 rows -> XOR-delta vs the previous row + deflate
    ("xd"); bools -> bit-pack + deflate ("bp"); other integers ->
    deflate ("d"); floats raw. Any leaf whose compressed form would not
    shrink falls back to raw — the codec can never inflate a message."""
    if v.dtype == np.uint8 and v.ndim >= 2 and v.shape[0] >= 2 \
            and v[0].nbytes >= _DELTA_MIN_ROW_BYTES:
        delta = native.delta_encode(v.reshape(v.shape[0], -1))
        comp = zlib.compress(delta, _DEFLATE_LEVEL)
        return ("xd", comp) if len(comp) < v.nbytes else None
    if v.dtype == np.bool_:
        comp = zlib.compress(np.packbits(v.reshape(-1)).tobytes(),
                             _DEFLATE_LEVEL)
        return ("bp", comp) if len(comp) < v.nbytes else None
    if np.issubdtype(v.dtype, np.integer):
        buf = memoryview(v).cast("B") if v.flags["WRITEABLE"] \
            else v.tobytes()
        comp = zlib.compress(buf, _DEFLATE_LEVEL)
        return ("d", comp) if len(comp) < v.nbytes else None
    return None


def encode_batch(batch: dict, codec: str = "raw") -> bytes:
    """Experience dict (numpy arrays + scalars) -> framed payload.

    Already-contiguous arrays hand their buffer straight to
    pack_records (which memcpys into the frame) — zero extra copies;
    the old ascontiguousarray + tobytes() path copied every array
    twice before the frame copy.

    codec="delta-deflate" compresses leaves per _encode_leaf's policy
    and tags each compressed leaf in the JSON meta ("enc"), keeping the
    payload self-describing; callers must only ship such payloads to
    peers that negotiated the codec (as MSG_EXPERIENCE_C)."""
    _check_codec(codec)
    meta, arrays = [], []
    for k, v in batch.items():
        if isinstance(v, np.ndarray):
            if not v.flags["C_CONTIGUOUS"]:
                v = np.ascontiguousarray(v)
            m = {"k": k, "nd": True, "dt": v.dtype.str, "sh": list(v.shape)}
            encoded = _encode_leaf(v) if codec != "raw" else None
            if encoded is not None:
                m["enc"] = encoded[0]
                arrays.append(encoded[1])
            else:
                arrays.append(memoryview(v).cast("B")
                              if v.flags["WRITEABLE"] else v.tobytes())
            meta.append(m)
        else:
            meta.append({"k": k, "nd": False, "v": v})
    return native.pack_records([json.dumps(meta).encode()] + arrays)


def _leaf_nbytes(m: dict) -> int:
    """Decoded (raw) byte size of an array leaf, from its meta alone."""
    return int(np.prod(m["sh"], dtype=np.int64)) * np.dtype(m["dt"]).itemsize


def _new_cache() -> dict:
    """Per-payload decode scratch for codec leaves: inflated deflate
    streams (reused by every decode_into split of the same payload),
    per-leaf delta continuation (next expected start row + the last
    decoded ABSOLUTE row — the XOR anchor when a batch splits across
    staging buffers), and fully-materialized small leaves."""
    return {"inflated": {}, "prev": {}, "full": {}}


def _inflate_leaf(cache: dict, m: dict, rec) -> bytes:
    """Inflate one compressed leaf record, cached per payload. The
    inflate OUTPUT takes over the wire buffer's role on the zero-copy
    path: landing it in the staging block stays the one copy per
    (decoded) byte. Truncated/corrupt streams reject with ValueError —
    the server reader drops such a connection like any misframed one."""
    key = m["k"]
    buf = cache["inflated"].get(key)
    if buf is None:
        expected = _leaf_nbytes(m) if m["enc"] != "bp" \
            else (int(np.prod(m["sh"], dtype=np.int64)) + 7) // 8
        try:
            buf = zlib.decompress(rec)
        except zlib.error as e:
            raise ValueError(f"corrupt codec stream for leaf {key!r}: {e}")
        if len(buf) != expected:
            raise ValueError(
                f"codec stream for leaf {key!r} inflates to {len(buf)} "
                f"bytes, expected {expected}")
        cache["inflated"][key] = buf
    return buf


def _decode_leaf_full(m: dict, rec, cache: dict | None = None) -> np.ndarray:
    """Materialize one array leaf (any encoding) as a fresh array."""
    dt, sh, enc = np.dtype(m["dt"]), m["sh"], m.get("enc")
    if enc is None:
        return np.frombuffer(rec, dtype=dt).reshape(sh).copy()
    cache = cache if cache is not None else _new_cache()
    full = cache["full"].get(m["k"])
    if full is not None:
        return full
    buf = _inflate_leaf(cache, m, rec)
    if enc == "bp":
        n = int(np.prod(sh, dtype=np.int64))
        arr = np.unpackbits(np.frombuffer(buf, np.uint8),
                            count=n).view(np.bool_).reshape(sh)
    elif enc in ("d", "xd"):
        arr = np.frombuffer(buf, dtype=dt).reshape(sh).copy()
        if enc == "xd" and arr.shape[0] > 1:
            native.delta_undo_inplace(
                arr.reshape(arr.shape[0], -1).view(np.uint8))
    else:
        raise ValueError(f"unknown wire codec leaf encoding {enc!r}")
    cache["full"][m["k"]] = arr
    return arr


def decode_batch(payload) -> dict:
    meta, recs = _parse_payload(payload)
    out: dict = {}
    i = 1
    for m in meta:
        if m["nd"]:
            out[m["k"]] = _decode_leaf_full(m, recs[i])
            i += 1
        else:
            out[m["k"]] = m["v"]
    return out


def _parse_payload(payload) -> tuple[list, list[memoryview]]:
    """(meta, per-array memoryview records) of a wire payload — the
    zero-copy front half shared by every decode form."""
    recs = native.unpack_records_mv(payload)
    meta = json.loads(bytes(recs[0]))
    return meta, recs


def _land_delta_rows(m: dict, dslice: np.ndarray, buf: bytes, start: int,
                     k: int, cache: dict) -> None:
    """Land delta rows [start, start+k) of an "xd" leaf at dslice and
    undo the XOR IN PLACE in the staging memory: copy the inflated
    delta rows in (the one landing copy), XOR row 0 against the
    previous landed ABSOLUTE row when the batch split across staging
    buffers, then prefix-undo the rest (native fast path, numpy
    accumulate fallback)."""
    sh = m["sh"]
    dt = np.dtype(m["dt"])
    row = int(np.prod(sh[1:], dtype=np.int64))
    src = np.frombuffer(buf, dtype=dt, count=k * row,
                        offset=start * row * dt.itemsize)
    dslice[...] = src.reshape((k, *sh[1:]))
    flat = dslice.reshape(k, -1).view(np.uint8)
    if start > 0:
        prev = None
        cont = cache["prev"].get(m["k"])
        if cont is not None and cont[0] == start:
            prev = cont[1]
        if prev is None:
            # non-sequential access (no continuation): the absolute
            # row before `start` is the XOR-prefix of all delta rows
            # up to it — rare path, the stager always advances start
            # sequentially
            allrows = np.frombuffer(buf, dtype=np.uint8,
                                    count=start * row * dt.itemsize)
            prev = np.bitwise_xor.reduce(
                allrows.reshape(start, -1), axis=0)
        np.bitwise_xor(flat[0], prev, out=flat[0])
    native.delta_undo_inplace(flat)
    cache["prev"][m["k"]] = (start + k, flat[-1].copy())


def _decode_rows_into(meta: list, recs: list[memoryview], dest: dict,
                      offset: int, start: int, limit: int,
                      cache: dict | None = None) -> int:
    """Land rows [start, start+k) of every array record directly in
    dest[key][offset:offset+k] — ONE copy per (decoded) wire byte,
    contiguous by construction. Returns k (rows written). Wire arrays
    without a matching dest key are skipped (the legacy stage likewise
    only read the item keys it knew). Codec leaves ("enc" meta tag)
    inflate once per payload (cached) and land with the delta-undo
    applied in place in the staging rows."""
    written = None
    i = 1
    for m in meta:
        if not m["nd"]:
            continue
        rec, i = recs[i], i + 1
        d = dest.get(m["k"])
        if d is None:
            continue
        sh = m["sh"]
        total = int(sh[0]) if sh else 0
        k = max(min(limit, total - start), 0)
        enc = m.get("enc")
        if enc is None:
            dt = np.dtype(m["dt"])
            row = int(np.prod(sh[1:], dtype=np.int64))
            src = np.frombuffer(rec, dtype=dt, count=k * row,
                                offset=start * row * dt.itemsize)
            d[offset:offset + k] = src.reshape((k, *sh[1:]))
        elif k > 0:
            if cache is None:
                cache = _new_cache()
            if enc == "xd":
                buf = _inflate_leaf(cache, m, rec)
                _land_delta_rows(m, d[offset:offset + k], buf, start, k,
                                 cache)
            elif enc == "d":
                buf = _inflate_leaf(cache, m, rec)
                dt = np.dtype(m["dt"])
                row = int(np.prod(sh[1:], dtype=np.int64))
                src = np.frombuffer(buf, dtype=dt, count=k * row,
                                    offset=start * row * dt.itemsize)
                d[offset:offset + k] = src.reshape((k, *sh[1:]))
            else:
                # bit-packed bools (tiny leaves): materialize once per
                # payload, then row-slice — not worth a fused landing
                full = _decode_leaf_full(m, rec, cache)
                d[offset:offset + k] = full[start:start + k]
        written = k
    return written or 0


def decode_batch_into(payload, dest: dict, offset: int, start: int = 0,
                      limit: int | None = None) -> tuple[int, int, dict]:
    """Decode a wire experience payload DIRECTLY into preallocated
    staging arrays at a write cursor.

    dest maps array keys -> preallocated [cap, ...] numpy rows; rows
    [start, start+k) of the batch land at dest[key][offset:offset+k],
    where k = min(limit, rows-start). Returns (k, rows, scalars) —
    scalars are the non-array entries (e.g. "frames", "actor"). Callers
    split a batch across staging-buffer boundaries by calling again
    with an advanced `start` (use WireBatch.decode_into for split
    decodes of codec payloads — it carries the inflate + delta
    continuation cache across calls)."""
    meta, recs = _parse_payload(payload)
    rows = batch_rows_meta(meta)
    if limit is None:
        limit = rows
    k = _decode_rows_into(meta, recs, dest, offset, start, limit)
    scalars = {m["k"]: m["v"] for m in meta if not m["nd"]}
    return k, rows, scalars


def batch_rows_meta(meta: list) -> int:
    """Staging units in a wire batch: priorities' leading dim (the
    driver's unit count), falling back to the first array record."""
    first = None
    for m in meta:
        if m["nd"]:
            if first is None:
                first = int(m["sh"][0]) if m["sh"] else 0
            if m["k"] == "priorities":
                return int(m["sh"][0])
    return first or 0


class WireBatch:
    """A received experience payload, decoded lazily.

    The ingest staging fast path (runtime/ingest.py) calls decode_into
    to land the wire bytes straight in a staging block with one copy;
    every other consumer (the multihost driver's stage, tests reading
    the queue directly) treats it like the dict decode_batch used to
    return — item access materializes arrays on demand and caches them.
    Scalar metadata ("frames", "actor") and the row count come from the
    JSON header alone, with no array copies.

    Codec payloads (MSG_EXPERIENCE_C) decode through the same interface:
    _cache holds the per-leaf inflate output and the delta-undo
    continuation so a batch split across staging buffers inflates each
    leaf ONCE and chains the XOR across decode_into calls."""

    __slots__ = ("payload", "_meta", "_recs", "_arrays", "_cache")

    def __init__(self, payload):
        self.payload = payload
        self._meta: list | None = None
        self._recs: list[memoryview] | None = None
        self._arrays: dict = {}
        self._cache: dict | None = None

    def _parsed(self) -> tuple[list, list[memoryview]]:
        if self._meta is None:
            self._meta, self._recs = _parse_payload(self.payload)
        return self._meta, self._recs

    @property
    def rows(self) -> int:
        """Staging units in this batch (header-only, no array copies)."""
        meta, _ = self._parsed()
        return batch_rows_meta(meta)

    @property
    def wire_nbytes(self) -> int:
        """Bytes this batch occupied on the wire (payload size)."""
        return len(self.payload)

    @property
    def raw_nbytes(self) -> int:
        """Bytes the array leaves would occupy uncompressed — the
        numerator of the wire compression ratio (header-only)."""
        meta, _ = self._parsed()
        return sum(_leaf_nbytes(m) for m in meta if m["nd"])

    def decode_into(self, dest: dict, offset: int, start: int = 0,
                    limit: int | None = None) -> int:
        """One-copy landing of rows [start, start+k) at dest[...][offset:].
        Returns k. See decode_batch_into."""
        meta, recs = self._parsed()
        if limit is None:
            limit = self.rows
        if self._cache is None:
            self._cache = _new_cache()
        return _decode_rows_into(meta, recs, dest, offset, start, limit,
                                 self._cache)

    def __getitem__(self, key):
        if key in self._arrays:
            return self._arrays[key]
        meta, recs = self._parsed()
        i = 1
        for m in meta:
            if m["nd"]:
                if m["k"] == key:
                    if self._cache is None:
                        self._cache = _new_cache()
                    arr = _decode_leaf_full(m, recs[i], self._cache)
                    self._arrays[key] = arr
                    return arr
                i += 1
            elif m["k"] == key:
                return m["v"]
        raise KeyError(key)

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def keys(self):
        meta, _ = self._parsed()
        return [m["k"] for m in meta]

    def __contains__(self, key) -> bool:
        meta, _ = self._parsed()
        return any(m["k"] == key for m in meta)


def batch_rows(batch) -> int:
    """Staging units in an ingest message, cheap for both forms: wire
    batches read their JSON header; dict batches read priorities."""
    if isinstance(batch, WireBatch):
        return batch.rows
    return int(batch["priorities"].shape[0])


def _send_msg(sock: socket.socket, mtype: int, payload: bytes) -> None:
    hdr = _HDR.pack(MAGIC, mtype, native.crc32(payload), len(payload))
    sock.sendall(hdr + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray | None:
    """Read exactly n bytes into ONE preallocated buffer via recv_into —
    multi-MB experience frames land without per-chunk copies or
    bytearray regrowth. Returns the bytearray itself (crc32, struct
    unpack, and the record walk all take buffers, so no bytes() copy)."""
    buf = bytearray(n)
    mv = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(mv[got:], n - got)
        if r == 0:
            return None
        got += r
    return buf


def _recv_msg(sock: socket.socket) -> tuple[int, bytearray] | None:
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    magic, mtype, crc, ln = _HDR.unpack(hdr)
    if magic != MAGIC or ln > MAX_PAYLOAD:
        raise ValueError("bad frame header")
    payload = _recv_exact(sock, ln)
    if payload is None:
        return None
    if native.crc32(payload) != crc:
        raise ValueError("checksum mismatch")
    return mtype, payload


# -- learner-host side ------------------------------------------------------


class SocketIngestServer:
    """Transport implementation that listens for remote actor hosts.

    Drop-in for LoopbackTransport on the learner host: recv_experience
    drains a bounded queue fed by per-connection reader threads;
    publish_params caches a pickled blob that MSG_PARAMS_REQ replies
    serve without re-serializing per client.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 max_pending: int = 64, idle_grace_s: float = 5.0,
                 param_wire_dtype: str = "bfloat16",
                 wire_codec: str = "delta-deflate"):
        """param_wire_dtype: dtype for float params on the wire.
        "bfloat16" (default) halves the weight-broadcast bytes — the
        round-3 soak measured param pulls saturating a bandwidth-
        constrained link (PERF.md "Live soak" item 3), and actors
        compute in bf16 anyway (the receiver upcasts to f32, so only
        the bf16 rounding of the values survives — a behavior-policy
        perturbation far below the eps-greedy noise floor). Set
        "float32" for bit-exact distribution.

        wire_codec: experience codec this server is willing to grant in
        the connect-time hello negotiation ("delta-deflate" default;
        "raw" is the escape hatch that forces every peer to plain
        payloads). Decode is always codec-capable — the setting only
        controls what MSG_HELLO_ACK offers."""
        if param_wire_dtype not in ("bfloat16", "float32"):
            raise ValueError(
                f"param_wire_dtype must be 'bfloat16' or 'float32', "
                f"got {param_wire_dtype!r}")
        self._wire_dtype = param_wire_dtype
        self._codec = _check_codec(wire_codec)
        self._q: queue.Queue[dict] = queue.Queue(maxsize=max_pending)
        self._dropped = 0  # guarded-by: _conns_lock
        # wire accounting (payload bytes; headers are ~17B noise):
        # lets a soak/driver publish the link's MB/s budget —
        # experience in vs params out is THE contended resource on
        # bandwidth-constrained links (PERF.md "Live soak")
        self._bytes_in = 0  # guarded-by: _conns_lock
        self._raw_bytes_in = 0  # guarded-by: _conns_lock
        self._bytes_out = 0  # guarded-by: _conns_lock
        self._params: tuple[Any, int] = (None, -1)  # guarded-by: _lock
        self._params_blob: bytes | None = pickle.dumps((None, -1))  # guarded-by: _lock
        self._params_cache: tuple[Any, int] | None = None  # guarded-by: _lock
        self._lock = make_lock("ingest_server._lock")
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self._listener.settimeout(0.2)
        self.port = self._listener.getsockname()[1]
        # _conns is mutated by the accept thread and every reader thread
        # and read by the driver's idle/termination check — the check is
        # load-bearing for fleet lifetime (a stale read can terminate a
        # multihost run early), so mutations take an explicit lock
        # rather than leaning on the GIL's list-op atomicity
        self._conns: list[socket.socket] = []  # guarded-by: _conns_lock
        self._conns_lock = make_lock("ingest_server._conns_lock")
        self._idle_grace_s = idle_grace_s
        # fleet telemetry plane: a connection that ships at least one
        # MSG_TELEMETRY frame identifies itself as a peer; its loss is
        # then attributed (counter + warning + hook) instead of silent
        self._conn_peers: dict[int, str] = {}  # guarded-by: _conns_lock
        self._telemetry_frames = 0  # guarded-by: _conns_lock
        self._telemetry_bytes_in = 0  # guarded-by: _conns_lock
        self._peer_disconnects = 0  # guarded-by: _conns_lock
        # hooks the driver installs before traffic; called from reader
        # threads, so implementations must be thread-safe
        self.on_telemetry: Any = None  # (peer_id: str, frame: dict) -> None
        self.on_disconnect: Any = None  # (peer_id: str) -> None
        self._last_disconnect: float | None = None  # guarded-by: _conns_lock
        self._ever_connected = False  # guarded-by: _conns_lock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ingest-accept", daemon=True)
        self._accept_thread.start()

    # Transport interface (learner side)

    def recv_experience(self, timeout: float | None = None) -> dict | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def send_experience(self, batch: dict) -> None:
        """Local actors on the learner host share the same queue."""
        while True:
            try:
                self._q.put_nowait(batch)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    # every reader thread and local actors land here on
                    # a full queue; a bare += across threads loses drops
                    with self._conns_lock:
                        self._dropped += 1
                except queue.Empty:
                    pass

    def publish_params(self, params: Any, version: int) -> None:
        # store the tree and serialize lazily on the first MSG_PARAMS_REQ
        # per version: device->host transfer + pickling a multi-MB CNN
        # tree would otherwise run synchronously on the learner thread at
        # every publish boundary, stalling training dispatches — and is
        # pure waste when no remote host is connected
        with self._lock:
            self._params = (params, version)
            self._params_blob = None
            self._params_cache = None

    def _param_blob(self) -> bytes:
        with self._lock:
            if self._params_blob is None:
                params, version = self._params
                host = jax_to_numpy(params)
                if self._wire_dtype == "bfloat16":
                    host = _downcast_f32(host)
                self._params_blob = pickle.dumps(
                    (host, version), protocol=pickle.HIGHEST_PROTOCOL)
            return self._params_blob

    def get_params(self) -> tuple[Any, int]:
        """Local loopback callers get the deserialized tree directly,
        cached per published version — no pickle round-trip per pull;
        the pickled blob stays wire-only. The cache still holds the
        BLOB-roundtripped values (bf16 wire rounding and all), so local
        and remote pulls see bit-identical params."""
        with self._lock:
            if self._params_cache is not None:
                return self._params_cache
        blob = self._param_blob()
        params, version = pickle.loads(blob)
        out = (_upcast_bf16(params), version)
        with self._lock:
            # cache only if no newer publish invalidated the blob while
            # we deserialized outside the lock
            if self._params_blob is blob:
                self._params_cache = out
        return out

    @property
    def dropped(self) -> int:
        return self._dropped

    @property
    def bytes_in(self) -> int:
        """Experience payload bytes received from remote actor hosts."""
        return self._bytes_in

    @property
    def raw_bytes_in(self) -> int:
        """What bytes_in would have been with no wire codec (the
        decoded size of every received experience leaf)."""
        return self._raw_bytes_in

    @property
    def wire_compression_ratio(self) -> float:
        """raw/wire byte ratio over all experience received so far
        (1.0 = no savings; larger is better). 0.0 before any traffic."""
        with self._conns_lock:
            return (self._raw_bytes_in / self._bytes_in
                    if self._bytes_in else 0.0)

    @property
    def bytes_out(self) -> int:
        """Param blob bytes served to remote actor hosts."""
        return self._bytes_out

    @property
    def telemetry_frames(self) -> int:
        """MSG_TELEMETRY frames received from remote peers."""
        with self._conns_lock:
            return self._telemetry_frames

    @property
    def telemetry_bytes_in(self) -> int:
        """Telemetry payload bytes received (control-plane budget)."""
        with self._conns_lock:
            return self._telemetry_bytes_in

    @property
    def peer_disconnects(self) -> int:
        """Identified telemetry peers whose connection closed."""
        with self._conns_lock:
            return self._peer_disconnects

    @property
    def pending(self) -> int:
        return self._q.qsize()

    @property
    def active_connections(self) -> int:
        """Live remote actor-host connections (readers deregister on
        disconnect). Drivers use this for idle/termination checks — a
        drained queue does not mean producers are done."""
        with self._conns_lock:
            return len(self._conns)

    @property
    def ever_connected(self) -> bool:
        """True once ANY remote producer has SENT EXPERIENCE — drivers
        use this for their boot-grace check instead of polling
        active_connections, which can miss a producer that connected
        and vanished entirely inside a warmup/compile window. Latching
        on the first experience message (not on accept) keeps
        param-only probes from masquerading as producers."""
        with self._conns_lock:
            return self._ever_connected

    def quiesced(self) -> bool:
        """True when no remote producer is connected AND none has
        disconnected within the last idle_grace_s. The grace period
        debounces transient drops: SocketTransport reconnects a broken
        send inside the same call, so an actor host that blipped is
        back within milliseconds — an idle verdict taken in that window
        would terminate a multihost fleet whose producers all intend to
        return (round-2 advisor finding on local_idle)."""
        with self._conns_lock:
            if self._conns:
                return False
            if self._last_disconnect is None:
                return True
            return (time.monotonic() - self._last_disconnect
                    >= self._idle_grace_s)

    def stop(self) -> None:
        self._stop.set()
        self._accept_thread.join(timeout=2)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._listener.close()

    # internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._reader, args=(conn,),
                             name="ingest-reader", daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return  # peer closed: actor loss is tolerated
                mtype, payload = msg
                if mtype in (MSG_EXPERIENCE, MSG_EXPERIENCE_C):
                    # enqueue the payload with decode deferred (WireBatch):
                    # the ingest thread lands the bytes straight in its
                    # staging block with one copy instead of this reader
                    # materializing a full dict of array copies per
                    # message. Parse the header here so a corrupt frame
                    # faults THIS connection, not the consumer. Codec
                    # payloads (MSG_EXPERIENCE_C) are self-describing
                    # per leaf, so decode needs no per-connection state.
                    batch = WireBatch(payload)
                    batch.rows  # noqa: B018 - framing validation
                    raw = batch.raw_nbytes if mtype == MSG_EXPERIENCE_C \
                        else len(payload)
                    # ever_connected latches HERE, not on accept: a
                    # param-only probe (monitoring, or an actor host
                    # that died waiting for params) is not a producer,
                    # and counting it once terminated a remote-only
                    # learner 0.1s into run() — the probe had come and
                    # gone during construction, so boot grace was
                    # skipped and quiesced() read idle (observed in the
                    # round-4 soak)
                    # byte counters under the lock too: every reader
                    # thread increments them, and a bare `+=` interleaved
                    # across threads loses counts — they are the soak's
                    # link-budget accounting, so they must be exact
                    with self._conns_lock:
                        self._ever_connected = True
                        self._bytes_in += len(payload)
                        self._raw_bytes_in += raw
                    self.send_experience(batch)
                elif mtype == MSG_HELLO:
                    # codec negotiation: grant the configured codec iff
                    # the client offered it; else raw. An OLD client
                    # never sends a hello and keeps raw MSG_EXPERIENCE.
                    # Telemetry is a capability echo on the same
                    # exchange: granted iff the client offered it (an
                    # old client never does, so this server never
                    # expects frames from it).
                    try:
                        hello = json.loads(bytes(payload))
                        offered = hello.get("codecs", [])
                        wants_tel = bool(hello.get("telemetry"))
                    except (ValueError, AttributeError):
                        offered, wants_tel = [], False
                    grant = self._codec if self._codec in offered \
                        else "raw"
                    ack: dict[str, Any] = {"codec": grant}
                    if wants_tel:
                        ack["telemetry"] = True
                    _send_msg(conn, MSG_HELLO_ACK,
                              json.dumps(ack).encode())
                elif mtype == MSG_TELEMETRY:
                    # per-peer obs snapshot: remember which peer this
                    # connection is (disconnect attribution), count the
                    # frame, and hand it to the fleet aggregator hook.
                    # A garbled frame faults this connection like any
                    # misframed message.
                    frame = json.loads(bytes(payload))
                    if not isinstance(frame, dict):
                        raise ValueError("telemetry frame is not an object")
                    peer = str(frame.get("peer", "peer?"))
                    with self._conns_lock:
                        self._conn_peers[id(conn)] = peer
                        self._telemetry_frames += 1
                        self._telemetry_bytes_in += len(payload)
                    cb = self.on_telemetry
                    if cb is not None:
                        cb(peer, frame)
                elif mtype == MSG_PARAMS_REQ:
                    blob = self._param_blob()
                    with self._conns_lock:
                        self._bytes_out += len(blob)
                    _send_msg(conn, MSG_PARAMS, blob)
        except (OSError, ValueError):
            return  # dead/corrupt connection: drop it, keep serving others
        finally:
            with self._conns_lock:
                try:
                    self._conns.remove(conn)  # churn must not leak socks
                except ValueError:
                    pass
                self._last_disconnect = time.monotonic()
                peer = self._conn_peers.pop(id(conn), None)
                if peer is not None:
                    self._peer_disconnects += 1
            if peer is not None and not self._stop.is_set():
                # a lost actor is an attributed event, never silence
                logging.getLogger(__name__).warning(
                    "[fleet] telemetry peer %r disconnected — its actors "
                    "stop producing until it reconnects", peer)
                cb = self.on_disconnect
                if cb is not None:
                    cb(peer)
            try:
                conn.close()
            except OSError:
                pass


def jax_to_numpy(params: Any) -> Any:
    import jax
    return jax.tree.map(np.asarray, params) if params is not None else None


class _Bf16Wire:
    """Marker wrapping a leaf the SENDER downcast f32->bf16 for the
    wire. The receiver upcasts exactly these leaves back to float32 and
    leaves everything else — including params that are legitimately
    bfloat16 in the model — untouched, so the wire never silently
    changes a tree's native dtypes (round-3 advisor finding)."""

    __slots__ = ("a",)

    def __init__(self, a):
        self.a = a


def _downcast_f32(tree: Any) -> Any:
    """float32 leaves -> bf16 wrapped in _Bf16Wire for the wire (half
    the bytes; other dtypes — uint8 frames, ints, f64, native bf16 —
    pass through untouched and untagged)."""
    import jax
    import ml_dtypes

    def one(x):
        x = np.asarray(x)
        return _Bf16Wire(x.astype(ml_dtypes.bfloat16)) \
            if x.dtype == np.float32 else x

    return jax.tree.map(one, tree) if tree is not None else None


def _upcast_bf16(tree: Any) -> Any:
    """Restore sender-downcast leaves (_Bf16Wire markers) to float32;
    every other leaf keeps its wire dtype exactly (values carry the
    bf16 rounding; exactness is not a wire contract — see
    SocketIngestServer.param_wire_dtype)."""
    import jax

    def one(x):
        return np.asarray(x.a, dtype=np.float32) \
            if isinstance(x, _Bf16Wire) else x

    return jax.tree.map(one, tree) if tree is not None else None


# -- actor-host side --------------------------------------------------------


class SocketTransport:
    """Transport for a remote actor host: pushes experience, pulls params.

    send_experience never raises into the actor loop: on a broken
    connection it attempts one reconnect and otherwise counts the batch
    as dropped (Ape-X ingest is lossy-tolerant; the actor keeps
    generating experience for when the learner returns).

    wire_codec is OFFERED at connect time (MSG_HELLO) and used only if
    the server acks it; an old server ignores the hello, the ack read
    times out (hello_timeout), and the connection falls back to raw —
    negotiation reruns on every reconnect, so a learner restart onto a
    different build renegotiates transparently.
    """

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0,
                 wire_codec: str = "delta-deflate",
                 hello_timeout: float = 2.0, telemetry: bool = True):
        """telemetry: offer the fleet-telemetry capability in the
        connect-time hello. send_telemetry only ships frames after the
        server granted it, so leaving this on against an old server
        costs one hello-timeout per (re)connect and nothing after."""
        self._addr = (host, port)
        self._timeout = connect_timeout
        self._codec = _check_codec(wire_codec)
        self._hello_timeout = hello_timeout
        self._telemetry = bool(telemetry)
        self._negotiated: str = "raw"  # guarded-by: _send_lock
        self._telemetry_ok = False  # guarded-by: _send_lock
        self._telemetry_frames_out = 0  # guarded-by: _send_lock
        self._telemetry_bytes_out = 0  # guarded-by: _send_lock
        self._sock: socket.socket | None = None  # guarded-by: _send_lock
        self._param_sock: socket.socket | None = None  # guarded-by: _param_lock
        self._dropped = 0  # guarded-by: _send_lock
        self._bytes_out = 0  # guarded-by: _send_lock
        self._raw_bytes_out = 0  # guarded-by: _send_lock
        self._encode_ms = 0.0  # guarded-by: _send_lock
        self._bytes_in = 0  # guarded-by: _param_lock
        # independent locks: a param pull blocking on the network (up to
        # the connect timeout) must not stall the actor threads' experience
        # sends — they use different sockets and share no state.
        # (_bytes_out and friends: payload bytes shipped vs their
        # uncompressed size, cumulative encode wall-ms, param blob
        # bytes pulled — the soak's link-budget accounting)
        self._send_lock = make_lock("transport._send_lock")
        self._param_lock = make_lock("transport._param_lock")

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _connect_experience(self) -> socket.socket:
        """Connect the experience socket and negotiate the wire codec.
        Sets self._negotiated; any failure mode (old server ignoring
        the hello, timeout, garbled ack) degrades to raw, never to an
        error — raw MSG_EXPERIENCE is universally understood."""
        sock = self._connect()
        # only send_experience/send_telemetry call this, under _send_lock
        self._negotiated = "raw"  # apexlint: unguarded(caller holds _send_lock)
        self._telemetry_ok = False  # apexlint: unguarded(caller holds _send_lock)
        if self._codec != "raw" or self._telemetry:
            # the hello now also fires with a raw codec when telemetry
            # is wanted — an old server still just ignores it
            try:
                offer = {"codecs": [self._codec],
                         "telemetry": self._telemetry}
                _send_msg(sock, MSG_HELLO, json.dumps(offer).encode())
                sock.settimeout(self._hello_timeout)
                msg = _recv_msg(sock)
                if msg is not None and msg[0] == MSG_HELLO_ACK:
                    ack = json.loads(bytes(msg[1]))
                    grant = ack.get("codec")
                    if grant in WIRE_CODECS:
                        self._negotiated = grant  # apexlint: unguarded(caller holds _send_lock)
                    if self._telemetry and bool(ack.get("telemetry")):
                        self._telemetry_ok = True  # apexlint: unguarded(caller holds _send_lock)
            except (OSError, ValueError, AttributeError):
                pass  # old server / timeout / garbage ack -> raw
            finally:
                sock.settimeout(self._timeout)
        return sock

    def send_experience(self, batch: dict) -> None:
        # encode under the send lock: the payload's codec must match
        # THIS connection's negotiation, which a mid-call reconnect can
        # change (it re-encodes in that case — reconnects are rare)
        with self._send_lock:
            payload: bytes | None = None
            payload_codec: str | None = None
            for _ in range(2):  # current socket, then one reconnect
                try:
                    if self._sock is None:
                        self._sock = self._connect_experience()
                    codec = self._negotiated
                    if payload is None or payload_codec != codec:
                        t0 = time.perf_counter()
                        payload = encode_batch(batch, codec)
                        self._encode_ms += (time.perf_counter() - t0) * 1e3
                        payload_codec = codec
                    mtype = MSG_EXPERIENCE_C if codec != "raw" \
                        else MSG_EXPERIENCE
                    _send_msg(self._sock, mtype, payload)
                    self._bytes_out += len(payload)
                    self._raw_bytes_out += sum(
                        v.nbytes for v in batch.values()
                        if isinstance(v, np.ndarray))
                    return
                except OSError:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                    self._sock = None
            self._dropped += 1

    def send_telemetry(self, frame: dict) -> bool:
        """Best-effort ship of one obs snapshot frame (MSG_TELEMETRY,
        JSON). Returns False — never raises into the pump thread — when
        the server did not grant telemetry (old build), the connection
        is down and cannot be (re)established, or the send fails; the
        caller simply tries again at its next cadence."""
        with self._send_lock:
            try:
                if self._sock is None:
                    self._sock = self._connect_experience()
                if not self._telemetry_ok:
                    return False
                payload = json.dumps(frame).encode()
                _send_msg(self._sock, MSG_TELEMETRY, payload)
                self._telemetry_frames_out += 1
                self._telemetry_bytes_out += len(payload)
                return True
            except OSError:
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                self._sock = None
                return False

    def recv_experience(self, timeout: float | None = None) -> dict | None:
        raise RuntimeError("actor-side transport cannot receive experience")

    def publish_params(self, params: Any, version: int) -> None:
        raise RuntimeError("actor-side transport cannot publish params")

    def get_params(self) -> tuple[Any, int]:
        with self._param_lock:
            try:
                if self._param_sock is None:
                    self._param_sock = self._connect()
                _send_msg(self._param_sock, MSG_PARAMS_REQ, b"")
                msg = _recv_msg(self._param_sock)
                # a corrupt/misframed reply (ValueError from _recv_msg, or
                # an unexpected type) is treated like a dead connection:
                # reset the socket and report no params — the caller polls
                # again. It must never escape into the param-puller thread.
                if msg is not None and msg[0] != MSG_PARAMS:
                    raise ValueError(f"unexpected reply type {msg[0]}")
            except (OSError, ValueError):
                msg = None
            if msg is None:
                if self._param_sock is not None:
                    try:
                        self._param_sock.close()
                    except OSError:
                        pass
                self._param_sock = None
                return None, -1
        try:
            # the blob decode below deliberately runs outside
            # _param_lock; re-take it for the counter bump alone
            with self._param_lock:
                self._bytes_in += len(msg[1])
            params, version = pickle.loads(msg[1])
            return _upcast_bf16(params), version
        except Exception as e:
            # an undecodable blob usually means wire-format skew (e.g. a
            # learner host on a newer build): swallowing it silently
            # would leave the actor on stale params forever with a
            # healthy-looking connection — log once per process
            global _WARNED_BAD_BLOB
            if not _WARNED_BAD_BLOB:
                _WARNED_BAD_BLOB = True
                import logging
                logging.getLogger(__name__).warning(
                    "param blob undecodable (%r) — version skew between "
                    "actor and learner hosts? Actor continues on its "
                    "current params.", e)
            return None, -1

    @property
    def dropped(self) -> int:
        return self._dropped

    @property
    def bytes_out(self) -> int:
        """Experience payload bytes shipped to the learner host."""
        return self._bytes_out

    @property
    def raw_bytes_out(self) -> int:
        """Uncompressed array bytes of everything shipped — the
        numerator of wire_compression_ratio."""
        return self._raw_bytes_out

    @property
    def wire_compression_ratio(self) -> float:
        """raw/wire ratio over all experience shipped (1.0 = no
        savings; larger is better). 0.0 before any traffic."""
        return (self._raw_bytes_out / self._bytes_out
                if self._bytes_out else 0.0)

    @property
    def negotiated_codec(self) -> str:
        """Codec agreed with the current learner connection ("raw"
        until a hello/ack has succeeded)."""
        return self._negotiated

    @property
    def telemetry_negotiated(self) -> bool:
        """True iff the current connection's hello/ack granted the
        telemetry capability (always False against an old server)."""
        return self._telemetry_ok

    @property
    def telemetry_frames_out(self) -> int:
        """MSG_TELEMETRY frames shipped to the learner host."""
        return self._telemetry_frames_out

    @property
    def telemetry_bytes_out(self) -> int:
        """Telemetry payload bytes shipped (control-plane budget)."""
        return self._telemetry_bytes_out

    @property
    def encode_ms(self) -> float:
        """Cumulative wall-ms spent encoding experience payloads."""
        return self._encode_ms

    @property
    def bytes_in(self) -> int:
        """Param blob bytes pulled from the learner host."""
        return self._bytes_in

    @property
    def pending(self) -> int:
        return 0

    def close(self) -> None:
        with self._send_lock, self._param_lock:
            for s in (self._sock, self._param_sock):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
            self._sock = self._param_sock = None
