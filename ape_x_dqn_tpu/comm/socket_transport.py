"""TCP transport: actor hosts -> learner host over DCN.

The reference crosses hosts with gRPC (SURVEY.md §2.2 "Comm: gRPC",
§2.3 item 3); the TPU-native runtime keeps ICI for learner collectives
and weight publication (parallel/dist_learner.py) and uses this plain
TCP layer only for the host-side paths: experience ingest into the
learner host and parameter pulls by actor hosts.

Wire format (both directions), assembled/verified by the native codec
(comm/native.py -> cpp/framing.cpp, Python-fallback compatible):

    [u32 magic 'APEX'][u8 type][u32 crc32(payload)][u64 len][payload]

Experience payloads are pack_records([json header, raw array bytes...])
— zero pickle on the hot path. Parameter payloads (low-rate control
plane) are pickled pytrees.

Semantics match LoopbackTransport: ingest is lossy-tolerant (bounded
queue, drop-oldest under backpressure; a dead learner connection drops
batches rather than killing the actor), so actor loss / learner restart
degrade gracefully (SURVEY.md §5 failure detection).

WIRE-FORMAT COMPATIBILITY: the round-4 bf16 param wire is a pickle-level
break — param blobs now carry _Bf16Wire marker objects, which a PRE-bf16
actor-host build cannot unpickle (its get_params fails the load and the
actor silently stays on stale params; only builds at/after the change
log the skew warning). Mixed-build fleets must either upgrade actor
hosts first or run the learner with --param-wire-dtype float32, whose
blobs remain loadable by every build. Same-build fleets (the supported
deployment) are unaffected.

WIRE CODEC ("delta-deflate", default-on, CommConfig.wire_codec): the
ingest wire is the measured #1 live bottleneck (PERF.md round-4 re-soak:
10.5 MB/s sustained, ~9.7KB/transition), so experience leaves are
compressed per-leaf before framing: uint8 frame rows ship as XOR-delta
against the previous row in the block (temporally adjacent frames ->
mostly-zero deltas; native fast path in cpp/framing.cpp) followed by
stdlib zlib deflate; bool leaves bit-pack (np.packbits) + deflate;
integer leaves deflate (RLE-grade on action/done streams); float leaves
stay raw (incompressible). Each leaf's encoding rides the JSON meta
header ("enc" tag), with a per-leaf raw fallback whenever compression
would not shrink it — so a codec payload is fully self-describing.
Codec payloads use a distinct message type (MSG_EXPERIENCE_C) and are
only sent after a connect-time hello/ack negotiation: a new client
offers its codec (MSG_HELLO), a new server answers with the agreed
choice (MSG_HELLO_ACK), an OLD server silently ignores the hello (its
reader drops unknown types) and the client falls back to raw on the ack
timeout. Old clients never send a hello and keep sending raw
MSG_EXPERIENCE, which every server still accepts — old<->new peers
interoperate in both directions.

TELEMETRY (MSG_TELEMETRY): per-peer obs snapshot frames — JSON objects
carrying a peer id, heartbeat ages, counter/gauge scalars, histogram
snapshots, and span aggregates — ride the experience socket as a
low-rate control plane, so the learner's fleet aggregator
(obs/fleet.py) can merge every peer's instruments into the single run
JSONL and feed remote heartbeats to the stall watchdog. The capability
negotiates over the same hello/ack: a new client adds "telemetry" to
its offer (sending the hello even when its codec is raw), a new server
echoes the grant in the ack, an old server times the hello out (the
client then never ships frames), and an old client never offers it.
A connection that carried at least one telemetry frame is an
IDENTIFIED peer: its socket closing is attributed (peer_disconnects +
a warning naming the peer + the on_disconnect hook) instead of being
silent actor loss.

MEMBERSHIP EPOCH (MSG_HELLO_ACK "epoch"): every server incarnation
stamps a fresh epoch id into its hello ack, so a client can tell "the
same learner blipped" from "a NEW learner took the address" (restart,
upgrade, failover). The client's supervised reconnect loop (capped
jittered exponential backoff, per-reason drop accounting) reruns the
hello on every reconnect — codec and telemetry renegotiate for free —
and an epoch CHANGE additionally resets the push cell and warns, so
params re-converge to the live incarnation even when its version
counter restarted below the old one. Old peers never see the field
(an old client sends no hello; an old server sends no epoch) and keep
the pre-epoch poll/raw behavior — no protocol break.

PARAM VERSIONING (MSG_PARAMS header + MSG_PARAMS_PUSH): a new client's
MSG_PARAMS_REQ carries the (epoch, version) it already has as a JSON
payload; a new server answers MSG_PARAMS with a small
[magic, epoch, version] header, followed by the pickled blob only when
the client is actually behind — an up-to-date replica costs one
header-sized round-trip instead of re-shipping megabytes of weights.
Peers that negotiated "params_push" in the hello additionally receive
server-initiated MSG_PARAMS_PUSH frames (same header+blob shape) on
the experience socket at publish time, turning the param path from
per-actor polling into epoch-versioned publication. An old server
ignores the request payload and replies with the legacy raw pickle;
an old client sends an empty request and gets exactly that — the
param path interops both ways with pre-epoch builds.

PARAM CODEC ("delta-q8", CommConfig.param_codec, comm/param_codec.py):
the cross-host param broadcast — `model_bytes x peers x publish_rate`
of learner egress — was the last uncompressed high-volume wire path, so
it now negotiates a delta+quantized codec the way the experience wire
did in PR 4: params ship as per-leaf int8-quantized deltas against the
version the peer last received, with per-leaf and whole-payload
never-inflate guards and automatic full resync when a peer misses a
version, falls out of the delta window, or crosses an epoch bump. The
codec is granted per channel: pushes negotiate a "param_codecs" offer /
"param_codec" grant over the same hello/ack, pulls state a "codec"
field in the MSG_PARAMS_REQ JSON (the param socket has no hello; an
old server ignores the unknown key and replies the versioned/legacy
shape, which the client parses as before). Coded payloads lead with
their own magic ('APXC'), so every receiver sniffs the right parser —
old<->new interop degrades silently to the raw paths both ways, the
shm seqlock area always carries the raw blob (local bandwidth is
free), and param_codec="raw" keeps the TCP path bitwise identical to
the pre-codec build. One ParamBlobProvider owns the bytes for every
(epoch, version) — legacy blob, versioned replies, coded chain, shm
area and local get_params all read it, so pull and push can never
disagree about a version's bytes. Fan-out isolation rides the same
change: the push loop is now a dispatcher that deposits the target
version into per-subscriber one-deep latest-wins cells drained by
per-subscriber sender threads — a wedged peer wedges only its own
thread, and the versions it missed are counted as superseded drops
(param_push_queue_drops), never queued behind.

SHARED-MEMORY SAME-HOST PLANE (MSG_SHM_DOORBELL, comm/shm_transport.py):
a client whose hello carries an "shm" offer — boot id plus a namespace
probe segment the server must attach and read back, so only a true
same-host/same-IPC-namespace peer ever qualifies — is granted a
per-connection experience ring and the shared seqlock param area, named
in the hello ack. Experience then packs STRAIGHT into a claimed ring
slot (no codec, no sendall of the body; the actor-side pack is the one
copy, the learner-side staging landing the other half of the existing
invariant) and a ~24-byte MSG_SHM_DOORBELL frame on this same TCP
socket names the slot, so reconnect/backoff, epoch machinery,
backpressure latches, chaos injection and drop accounting all keep
working on the control plane they already own. The server validates
seq + crc before delivering — torn slots (writer died mid-write, wild
writes) are counted and freed, never delivered — and reclaims every
lease when the connection drops. Params publish once into the seqlock
area; granted clients read it locally (per-client MSG_PARAMS blob
pulls and params_push frames stop entirely for them). EVERY shm
failure mode — old peer (the offer/grant keys are ignored like any
unknown capability), cross-host peer, probe failure, full ring,
oversize batch or blob, torn read — degrades silently to the TCP paths
above, which remain bitwise unchanged when comm.shm is off.
"""

from __future__ import annotations

import json
import logging
import pickle
import queue
import random
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Any

import numpy as np

from ape_x_dqn_tpu.comm import native, shm_transport
from ape_x_dqn_tpu.comm.param_codec import (  # noqa: F401 - re-exports
    _PARAMS_HDR, PARAM_CODECS, PARAMS_CODEC_MAGIC, PARAMS_HDR_MAGIC,
    ParamBlobProvider, ParamChainDecoder, _Bf16Wire, _downcast_f32,
    _upcast_bf16, check_param_codec, jax_to_numpy)
from ape_x_dqn_tpu.obs.health import make_lock

MAGIC = 0x41504558  # 'APEX'
MSG_EXPERIENCE = 1
MSG_PARAMS_REQ = 2
MSG_PARAMS = 3
MSG_HELLO = 4          # client codec offer (JSON), sent on connect
MSG_HELLO_ACK = 5      # server's codec choice (JSON)
MSG_EXPERIENCE_C = 6   # experience payload with codec-encoded leaves
MSG_TELEMETRY = 7      # per-peer obs snapshot frame (JSON), negotiated
MSG_PARAMS_PUSH = 8    # server-initiated params (negotiated subscribers)
MSG_SHM_DOORBELL = 9   # same-host shm slot announcement (negotiated)

# doorbell payload: slot index, slot seq, payload nbytes, payload crc.
# ~24 bytes on the control socket announce a multi-MB slot — the whole
# experience body moved through shared memory (comm/shm_transport.py)
_DOORBELL = struct.Struct("<IQQI")

WIRE_CODECS = ("raw", "delta-deflate")

_HDR = struct.Struct("<IBIQ")  # magic, type, crc, payload_len
MAX_PAYLOAD = 1 << 31
_WARNED_BAD_BLOB = False
# _PARAMS_HDR / PARAMS_HDR_MAGIC (the versioned 'APXV' reply prefix)
# and PARAMS_CODEC_MAGIC (the coded 'APXC' payload prefix) live in
# comm/param_codec.py with the codec and are re-exported above — the
# three param payload shapes (legacy pickle 0x80, APXV, APXC) are
# sniffed by first bytes, none of which collide.
# samples kept for the reconnect/recovery-latency instrument
_RECONNECT_SAMPLES = 256

# delta+deflate only pays on frame-sized rows; small rows (actions,
# rewards) would spend more header than they save
_DELTA_MIN_ROW_BYTES = 1024
# Z_BEST_SPEED: the encoder runs on actor-host CPUs next to env
# stepping; on mostly-zero XOR deltas level 1 already collapses runs,
# higher levels buy single-digit % ratio for multiples of encode time
_DEFLATE_LEVEL = 1


def _check_codec(codec: str) -> str:
    if codec not in WIRE_CODECS:
        raise ValueError(
            f"wire_codec must be one of {WIRE_CODECS}, got {codec!r}")
    return codec


# -- codec ------------------------------------------------------------------


def _encode_leaf(v: np.ndarray) -> tuple[str, bytes] | None:
    """(enc tag, compressed bytes) for one array leaf under the
    delta-deflate codec, or None to ship it raw. Per-leaf policy:
    frame-like uint8 rows -> XOR-delta vs the previous row + deflate
    ("xd"); bools -> bit-pack + deflate ("bp"); other integers ->
    deflate ("d"); floats raw. Any leaf whose compressed form would not
    shrink falls back to raw — the codec can never inflate a message."""
    if v.dtype == np.uint8 and v.ndim >= 2 and v.shape[0] >= 2 \
            and v[0].nbytes >= _DELTA_MIN_ROW_BYTES:
        delta = native.delta_encode(v.reshape(v.shape[0], -1))
        comp = zlib.compress(delta, _DEFLATE_LEVEL)
        return ("xd", comp) if len(comp) < v.nbytes else None
    if v.dtype == np.bool_:
        comp = zlib.compress(np.packbits(v.reshape(-1)).tobytes(),
                             _DEFLATE_LEVEL)
        return ("bp", comp) if len(comp) < v.nbytes else None
    if np.issubdtype(v.dtype, np.integer):
        buf = memoryview(v).cast("B") if v.flags["WRITEABLE"] \
            else v.tobytes()
        comp = zlib.compress(buf, _DEFLATE_LEVEL)
        return ("d", comp) if len(comp) < v.nbytes else None
    return None


def encode_batch(batch: dict, codec: str = "raw") -> bytes:
    """Experience dict (numpy arrays + scalars) -> framed payload.

    Already-contiguous arrays hand their buffer straight to
    pack_records (which memcpys into the frame) — zero extra copies;
    the old ascontiguousarray + tobytes() path copied every array
    twice before the frame copy.

    codec="delta-deflate" compresses leaves per _encode_leaf's policy
    and tags each compressed leaf in the JSON meta ("enc"), keeping the
    payload self-describing; callers must only ship such payloads to
    peers that negotiated the codec (as MSG_EXPERIENCE_C)."""
    _check_codec(codec)
    meta, arrays = [], []
    for k, v in batch.items():
        if isinstance(v, np.ndarray):
            if not v.flags["C_CONTIGUOUS"]:
                v = np.ascontiguousarray(v)
            m = {"k": k, "nd": True, "dt": v.dtype.str, "sh": list(v.shape)}
            encoded = _encode_leaf(v) if codec != "raw" else None
            if encoded is not None:
                m["enc"] = encoded[0]
                arrays.append(encoded[1])
            else:
                arrays.append(memoryview(v).cast("B")
                              if v.flags["WRITEABLE"] else v.tobytes())
            meta.append(m)
        else:
            meta.append({"k": k, "nd": False, "v": v})
    return native.pack_records([json.dumps(meta).encode()] + arrays)


def _leaf_nbytes(m: dict) -> int:
    """Decoded (raw) byte size of an array leaf, from its meta alone."""
    return int(np.prod(m["sh"], dtype=np.int64)) * np.dtype(m["dt"]).itemsize


def _new_cache() -> dict:
    """Per-payload decode scratch for codec leaves: inflated deflate
    streams (reused by every decode_into split of the same payload),
    per-leaf delta continuation (next expected start row + the last
    decoded ABSOLUTE row — the XOR anchor when a batch splits across
    staging buffers), and fully-materialized small leaves."""
    return {"inflated": {}, "prev": {}, "full": {}}


def _inflate_leaf(cache: dict, m: dict, rec) -> bytes:
    """Inflate one compressed leaf record, cached per payload. The
    inflate OUTPUT takes over the wire buffer's role on the zero-copy
    path: landing it in the staging block stays the one copy per
    (decoded) byte. Truncated/corrupt streams reject with ValueError —
    the server reader drops such a connection like any misframed one."""
    key = m["k"]
    buf = cache["inflated"].get(key)
    if buf is None:
        expected = _leaf_nbytes(m) if m["enc"] != "bp" \
            else (int(np.prod(m["sh"], dtype=np.int64)) + 7) // 8
        try:
            buf = zlib.decompress(rec)
        except zlib.error as e:
            raise ValueError(f"corrupt codec stream for leaf {key!r}: {e}")
        if len(buf) != expected:
            raise ValueError(
                f"codec stream for leaf {key!r} inflates to {len(buf)} "
                f"bytes, expected {expected}")
        cache["inflated"][key] = buf
    return buf


def _decode_leaf_full(m: dict, rec, cache: dict | None = None) -> np.ndarray:
    """Materialize one array leaf (any encoding) as a fresh array."""
    dt, sh, enc = np.dtype(m["dt"]), m["sh"], m.get("enc")
    if enc is None:
        # the .copy() is load-bearing, not a convenience: the returned
        # array must OWN its memory because `rec` aliases a transport
        # buffer with a shorter lifetime — for a ShmSlotBatch it is a
        # ring slot that the writer REUSES the moment release() frees
        # it (a view would silently mutate under the consumer), and
        # even for TCP payloads a view would pin the entire multi-MB
        # frame alive for the lifetime of one decoded leaf. The
        # one-copy hot path is decode_into (no copy here, lands
        # straight in staging); this full-materialize path only serves
        # dict-protocol consumers. Pinned by test_comm.py
        # (test_decode_leaf_full_copies_are_load_bearing).
        return np.frombuffer(rec, dtype=dt).reshape(sh).copy()
    cache = cache if cache is not None else _new_cache()
    full = cache["full"].get(m["k"])
    if full is not None:
        return full
    buf = _inflate_leaf(cache, m, rec)
    if enc == "bp":
        n = int(np.prod(sh, dtype=np.int64))
        arr = np.unpackbits(np.frombuffer(buf, np.uint8),
                            count=n).view(np.bool_).reshape(sh)
    elif enc in ("d", "xd"):
        # load-bearing copy #2: zlib.decompress returns immutable
        # bytes, and the "xd" undo below XORs rows IN PLACE — the copy
        # is what buys writable memory. It doubles as ownership for
        # "d" leaves: `buf` lives in the per-payload cache, which this
        # returned array must outlive. Pinned by the same test as the
        # raw-leaf copy above.
        arr = np.frombuffer(buf, dtype=dt).reshape(sh).copy()
        if enc == "xd" and arr.shape[0] > 1:
            native.delta_undo_inplace(
                arr.reshape(arr.shape[0], -1).view(np.uint8))
    else:
        raise ValueError(f"unknown wire codec leaf encoding {enc!r}")
    cache["full"][m["k"]] = arr
    return arr


def decode_batch(payload) -> dict:
    meta, recs = _parse_payload(payload)
    out: dict = {}
    i = 1
    for m in meta:
        if m["nd"]:
            out[m["k"]] = _decode_leaf_full(m, recs[i])
            i += 1
        else:
            out[m["k"]] = m["v"]
    return out


def _parse_payload(payload) -> tuple[list, list[memoryview]]:
    """(meta, per-array memoryview records) of a wire payload — the
    zero-copy front half shared by every decode form."""
    recs = native.unpack_records_mv(payload)
    meta = json.loads(bytes(recs[0]))
    return meta, recs


def _land_delta_rows(m: dict, dslice: np.ndarray, buf: bytes, start: int,
                     k: int, cache: dict) -> None:
    """Land delta rows [start, start+k) of an "xd" leaf at dslice and
    undo the XOR IN PLACE in the staging memory: copy the inflated
    delta rows in (the one landing copy), XOR row 0 against the
    previous landed ABSOLUTE row when the batch split across staging
    buffers, then prefix-undo the rest (native fast path, numpy
    accumulate fallback)."""
    sh = m["sh"]
    dt = np.dtype(m["dt"])
    row = int(np.prod(sh[1:], dtype=np.int64))
    src = np.frombuffer(buf, dtype=dt, count=k * row,
                        offset=start * row * dt.itemsize)
    dslice[...] = src.reshape((k, *sh[1:]))
    flat = dslice.reshape(k, -1).view(np.uint8)
    if start > 0:
        prev = None
        cont = cache["prev"].get(m["k"])
        if cont is not None and cont[0] == start:
            prev = cont[1]
        if prev is None:
            # non-sequential access (no continuation): the absolute
            # row before `start` is the XOR-prefix of all delta rows
            # up to it — rare path, the stager always advances start
            # sequentially
            allrows = np.frombuffer(buf, dtype=np.uint8,
                                    count=start * row * dt.itemsize)
            prev = np.bitwise_xor.reduce(
                allrows.reshape(start, -1), axis=0)
        np.bitwise_xor(flat[0], prev, out=flat[0])
    native.delta_undo_inplace(flat)
    cache["prev"][m["k"]] = (start + k, flat[-1].copy())


def _decode_rows_into(meta: list, recs: list[memoryview], dest: dict,
                      offset: int, start: int, limit: int,
                      cache: dict | None = None) -> int:
    """Land rows [start, start+k) of every array record directly in
    dest[key][offset:offset+k] — ONE copy per (decoded) wire byte,
    contiguous by construction. Returns k (rows written). Wire arrays
    without a matching dest key are skipped (the legacy stage likewise
    only read the item keys it knew). Codec leaves ("enc" meta tag)
    inflate once per payload (cached) and land with the delta-undo
    applied in place in the staging rows."""
    written = None
    i = 1
    for m in meta:
        if not m["nd"]:
            continue
        rec, i = recs[i], i + 1
        d = dest.get(m["k"])
        if d is None:
            continue
        sh = m["sh"]
        total = int(sh[0]) if sh else 0
        k = max(min(limit, total - start), 0)
        enc = m.get("enc")
        if enc is None:
            dt = np.dtype(m["dt"])
            row = int(np.prod(sh[1:], dtype=np.int64))
            src = np.frombuffer(rec, dtype=dt, count=k * row,
                                offset=start * row * dt.itemsize)
            d[offset:offset + k] = src.reshape((k, *sh[1:]))
        elif k > 0:
            if cache is None:
                cache = _new_cache()
            if enc == "xd":
                buf = _inflate_leaf(cache, m, rec)
                _land_delta_rows(m, d[offset:offset + k], buf, start, k,
                                 cache)
            elif enc == "d":
                buf = _inflate_leaf(cache, m, rec)
                dt = np.dtype(m["dt"])
                row = int(np.prod(sh[1:], dtype=np.int64))
                src = np.frombuffer(buf, dtype=dt, count=k * row,
                                    offset=start * row * dt.itemsize)
                d[offset:offset + k] = src.reshape((k, *sh[1:]))
            else:
                # bit-packed bools (tiny leaves): materialize once per
                # payload, then row-slice — not worth a fused landing
                full = _decode_leaf_full(m, rec, cache)
                d[offset:offset + k] = full[start:start + k]
        written = k
    return written or 0


def decode_batch_into(payload, dest: dict, offset: int, start: int = 0,
                      limit: int | None = None) -> tuple[int, int, dict]:
    """Decode a wire experience payload DIRECTLY into preallocated
    staging arrays at a write cursor.

    dest maps array keys -> preallocated [cap, ...] numpy rows; rows
    [start, start+k) of the batch land at dest[key][offset:offset+k],
    where k = min(limit, rows-start). Returns (k, rows, scalars) —
    scalars are the non-array entries (e.g. "frames", "actor"). Callers
    split a batch across staging-buffer boundaries by calling again
    with an advanced `start` (use WireBatch.decode_into for split
    decodes of codec payloads — it carries the inflate + delta
    continuation cache across calls)."""
    meta, recs = _parse_payload(payload)
    rows = batch_rows_meta(meta)
    if limit is None:
        limit = rows
    k = _decode_rows_into(meta, recs, dest, offset, start, limit)
    scalars = {m["k"]: m["v"] for m in meta if not m["nd"]}
    return k, rows, scalars


def batch_rows_meta(meta: list) -> int:
    """Staging units in a wire batch: priorities' leading dim (the
    driver's unit count), falling back to the first array record."""
    first = None
    for m in meta:
        if m["nd"]:
            if first is None:
                first = int(m["sh"][0]) if m["sh"] else 0
            if m["k"] == "priorities":
                return int(m["sh"][0])
    return first or 0


class WireBatch:
    """A received experience payload, decoded lazily.

    The ingest staging fast path (runtime/ingest.py) calls decode_into
    to land the wire bytes straight in a staging block with one copy;
    every other consumer (the multihost driver's stage, tests reading
    the queue directly) treats it like the dict decode_batch used to
    return — item access materializes arrays on demand and caches them.
    Scalar metadata ("frames", "actor") and the row count come from the
    JSON header alone, with no array copies.

    Codec payloads (MSG_EXPERIENCE_C) decode through the same interface:
    _cache holds the per-leaf inflate output and the delta-undo
    continuation so a batch split across staging buffers inflates each
    leaf ONCE and chains the XOR across decode_into calls."""

    __slots__ = ("payload", "_meta", "_recs", "_arrays", "_cache")

    def __init__(self, payload):
        self.payload = payload
        self._meta: list | None = None
        self._recs: list[memoryview] | None = None
        self._arrays: dict = {}
        self._cache: dict | None = None

    def _parsed(self) -> tuple[list, list[memoryview]]:
        if self._meta is None:
            self._meta, self._recs = _parse_payload(self.payload)
        return self._meta, self._recs

    @property
    def rows(self) -> int:
        """Staging units in this batch (header-only, no array copies)."""
        meta, _ = self._parsed()
        return batch_rows_meta(meta)

    @property
    def wire_nbytes(self) -> int:
        """Bytes this batch occupied on the wire (payload size)."""
        return len(self.payload)

    @property
    def raw_nbytes(self) -> int:
        """Bytes the array leaves would occupy uncompressed — the
        numerator of the wire compression ratio (header-only)."""
        meta, _ = self._parsed()
        return sum(_leaf_nbytes(m) for m in meta if m["nd"])

    def decode_into(self, dest: dict, offset: int, start: int = 0,
                    limit: int | None = None) -> int:
        """One-copy landing of rows [start, start+k) at dest[...][offset:].
        Returns k. See decode_batch_into."""
        meta, recs = self._parsed()
        if limit is None:
            limit = self.rows
        if self._cache is None:
            self._cache = _new_cache()
        return _decode_rows_into(meta, recs, dest, offset, start, limit,
                                 self._cache)

    def __getitem__(self, key):
        if key in self._arrays:
            return self._arrays[key]
        meta, recs = self._parsed()
        i = 1
        for m in meta:
            if m["nd"]:
                if m["k"] == key:
                    if self._cache is None:
                        self._cache = _new_cache()
                    arr = _decode_leaf_full(m, recs[i], self._cache)
                    self._arrays[key] = arr
                    return arr
                i += 1
            elif m["k"] == key:
                return m["v"]
        raise KeyError(key)

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def keys(self):
        meta, _ = self._parsed()
        return [m["k"] for m in meta]

    def __contains__(self, key) -> bool:
        meta, _ = self._parsed()
        return any(m["k"] == key for m in meta)


class ShmSlotBatch(WireBatch):
    """An experience batch living in a server-owned shm ring slot.

    The payload memoryview aliases the shared segment (zero copies so
    far — the actor's pack into the slot was the only one); all the
    WireBatch decode machinery works unchanged because the slot holds
    an exact raw wire payload. release() hands the slot back to the
    writer once the consumer has landed the rows (IngestStager.put, the
    legacy stage path, or a queue drop-oldest eviction); it must drop
    every memoryview into the segment first, or the ring could never
    unmap after its connection dies. Idempotent, with a __del__ net so
    an exotic consumer that never releases (tests poking the queue)
    leaks a slot for a bounded time, not forever."""

    __slots__ = ("_ring", "_slot", "_released")

    def __init__(self, view: memoryview, ring, slot: int):
        super().__init__(view)
        self._ring = ring
        self._slot = slot
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        recs, self._recs = self._recs, None
        self._cache = None
        if recs is not None:
            for r in recs:
                try:
                    r.release()
                except BufferError:
                    pass  # aliased by a live array; __del__/GC frees it
        payload, self.payload = self.payload, b""
        try:
            payload.release()
        except (BufferError, AttributeError):
            pass
        self._ring.free(self._slot)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


def batch_rows(batch) -> int:
    """Staging units in an ingest message, cheap for both forms: wire
    batches read their JSON header; dict batches read priorities."""
    if isinstance(batch, WireBatch):
        return batch.rows
    return int(batch["priorities"].shape[0])


def _send_msg(sock: socket.socket, mtype: int, payload: bytes) -> None:
    hdr = _HDR.pack(MAGIC, mtype, native.crc32(payload), len(payload))
    sock.sendall(hdr + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray | None:
    """Read exactly n bytes into ONE preallocated buffer via recv_into —
    multi-MB experience frames land without per-chunk copies or
    bytearray regrowth. Returns the bytearray itself (crc32, struct
    unpack, and the record walk all take buffers, so no bytes() copy)."""
    buf = bytearray(n)
    mv = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(mv[got:], n - got)
        if r == 0:
            return None
        got += r
    return buf


def _recv_msg(sock: socket.socket) -> tuple[int, bytearray] | None:
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    magic, mtype, crc, ln = _HDR.unpack(hdr)
    if magic != MAGIC or ln > MAX_PAYLOAD:
        raise ValueError("bad frame header")
    payload = _recv_exact(sock, ln)
    if payload is None:
        return None
    if native.crc32(payload) != crc:
        raise ValueError("checksum mismatch")
    return mtype, payload


# -- learner-host side ------------------------------------------------------


class _PushSub:
    """Per-subscriber push fan-out state. The bounded send queue the
    drop-to-resync semantics call for is a ONE-DEEP latest-wins target
    cell: a param subscriber only ever needs the newest version (the
    codec's chain covers any gap, and a full resync covers the rest),
    so anything deeper would just delay it — depth-1 with supersede
    counting IS the bounded queue. `last` is what this subscriber last
    received (its delta base); sender-thread-private."""

    __slots__ = ("conn", "coded", "wake", "lock", "target", "last", "stop")

    def __init__(self, conn: socket.socket, coded: bool):
        self.conn = conn
        self.coded = bool(coded)
        self.wake = threading.Event()
        self.lock = make_lock("ingest_server.push_sub")
        self.target: tuple[int, int] | None = None  # guarded-by: lock
        self.last: tuple[int, int] = (-1, -1)
        self.stop = False


class SocketIngestServer:
    """Transport implementation that listens for remote actor hosts.

    Drop-in for LoopbackTransport on the learner host: recv_experience
    drains a bounded queue fed by per-connection reader threads;
    publish_params caches a pickled blob that MSG_PARAMS_REQ replies
    serve without re-serializing per client.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 max_pending: int = 64, idle_grace_s: float = 5.0,
                 param_wire_dtype: str = "bfloat16",
                 wire_codec: str = "delta-deflate",
                 param_codec: str = "delta-q8",
                 param_delta_window: int = 8,
                 epoch: int | None = None, shm: bool = False,
                 shm_slots: int = 8, shm_slot_bytes: int = 1 << 22,
                 shm_param_bytes: int = 1 << 26):
        """param_wire_dtype: dtype for float params on the wire.
        "bfloat16" (default) halves the weight-broadcast bytes — the
        round-3 soak measured param pulls saturating a bandwidth-
        constrained link (PERF.md "Live soak" item 3), and actors
        compute in bf16 anyway (the receiver upcasts to f32, so only
        the bf16 rounding of the values survives — a behavior-policy
        perturbation far below the eps-greedy noise floor). Set
        "float32" for bit-exact distribution.

        wire_codec: experience codec this server is willing to grant in
        the connect-time hello negotiation ("delta-deflate" default;
        "raw" is the escape hatch that forces every peer to plain
        payloads). Decode is always codec-capable — the setting only
        controls what MSG_HELLO_ACK offers.

        param_codec: param-plane codec this server is willing to grant
        ("delta-q8" default: per-leaf int8-quantized deltas vs the
        peer's last-received version, full resync on missed versions /
        epoch bumps — comm/param_codec.py). Granted only to peers that
        ASK (hello "param_codecs" offer for pushes, a "codec" field in
        MSG_PARAMS_REQ for pulls); "raw" keeps the whole param path
        bitwise identical to the pre-codec build. param_delta_window
        caps how many encoded delta segments are kept for catch-up — a
        peer further behind than the window gets a full resync.

        epoch: membership epoch id stamped into every MSG_HELLO_ACK
        and versioned params header. Defaults to a wall-clock-derived
        id, so a restarted server (a new incarnation at the same
        address) presents a different epoch and clients re-converge;
        pass an explicit value to pin it (tests, deterministic
        fleets).

        shm: grant same-host shared-memory transport to clients whose
        hello offer passes the boot-id + namespace probe
        (comm/shm_transport.py). shm_slots/shm_slot_bytes cap the
        per-connection experience ring a client may request;
        shm_param_bytes sizes the one shared seqlock param area. Off
        by default — TCP-only paths are bitwise unchanged when
        disabled."""
        if param_wire_dtype not in ("bfloat16", "float32"):
            raise ValueError(
                f"param_wire_dtype must be 'bfloat16' or 'float32', "
                f"got {param_wire_dtype!r}")
        self._wire_dtype = param_wire_dtype
        self._codec = _check_codec(wire_codec)
        self._param_codec = check_param_codec(param_codec)
        # membership epoch: wall-clock-derived by default so a restarted
        # incarnation at the same address stamps a DIFFERENT id (tests
        # pin it; collisions need two restarts in the same millisecond)
        self.epoch = (int(epoch) if epoch is not None
                      else (time.time_ns() // 1_000_000) & 0x7FFF_FFFF)
        self._q: queue.Queue[dict] = queue.Queue(maxsize=max_pending)
        self._dropped = 0  # guarded-by: _conns_lock
        # wire accounting (payload bytes; headers are ~17B noise):
        # lets a soak/driver publish the link's MB/s budget —
        # experience in vs params out is THE contended resource on
        # bandwidth-constrained links (PERF.md "Live soak")
        self._bytes_in = 0  # guarded-by: _conns_lock
        self._raw_bytes_in = 0  # guarded-by: _conns_lock
        self._bytes_out = 0  # guarded-by: _conns_lock
        # what the param replies WOULD have cost with no codec — the
        # numerator of param_compression_ratio (raw-path replies count
        # their own length, so the ratio is exactly 1.0 under
        # param_codec="raw" and >= 1.0 under the never-inflate guard)
        self._param_raw_bytes_out = 0  # guarded-by: _conns_lock
        # coded peers that held a real base yet needed a full payload
        # (missed version / out of window / epoch bump)
        self._param_resyncs = 0  # guarded-by: _conns_lock
        # push fan-out drops by reason: "superseded" (a deposited
        # version was overwritten before the subscriber's sender
        # consumed it — drop-to-resync, never queued behind) and
        # "disconnect" (send failed, subscriber dropped)
        self._push_drop_reasons = {"superseded": 0,
                                   "disconnect": 0}  # guarded-by: _conns_lock
        # the one versioned-blob provider (comm/param_codec.py): legacy
        # blob, versioned replies, coded chain, shm area writes and
        # local get_params all read IT, so pull and push can never
        # disagree about the bytes for a version (ISSUE 19 small fix —
        # get_params' cache and the push loop's dedupe previously held
        # independent state)
        self._provider = ParamBlobProvider(
            param_wire_dtype, param_codec, param_delta_window)
        self._lock = make_lock("ingest_server._lock")
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self._listener.settimeout(0.2)
        self.port = self._listener.getsockname()[1]
        # _conns is mutated by the accept thread and every reader thread
        # and read by the driver's idle/termination check — the check is
        # load-bearing for fleet lifetime (a stale read can terminate a
        # multihost run early), so mutations take an explicit lock
        # rather than leaning on the GIL's list-op atomicity
        self._conns: list[socket.socket] = []  # guarded-by: _conns_lock
        self._conns_lock = make_lock("ingest_server._conns_lock")
        self._idle_grace_s = idle_grace_s
        # fleet telemetry plane: a connection that ships at least one
        # MSG_TELEMETRY frame identifies itself as a peer; its loss is
        # then attributed (counter + warning + hook) instead of silent
        self._conn_peers: dict[int, str] = {}  # guarded-by: _conns_lock
        # serving-tier tenant tags: a connection that offered a serve
        # tag in its hello is attributed to (policy_id, priority
        # class) — the learner-side admission controller and report
        # can then name WHICH tenant's actors a connection carries
        self._conn_serve: dict[int, tuple[str, int]] = {}  # guarded-by: _conns_lock
        self._telemetry_frames = 0  # guarded-by: _conns_lock
        self._telemetry_bytes_in = 0  # guarded-by: _conns_lock
        self._peer_disconnects = 0  # guarded-by: _conns_lock
        # hooks the driver installs before traffic; called from reader
        # threads, so implementations must be thread-safe
        self.on_telemetry: Any = None  # (peer_id: str, frame: dict) -> None
        self.on_disconnect: Any = None  # (peer_id: str) -> None
        # byzantine-peer accounting: a truncated/garbled frame is an
        # attributed counter + hook call, not just a silently-ended
        # connection (a corrupting proxy or skewed build would
        # otherwise churn connections with no observable trace)
        self.on_decode_error: Any = None  # (peer_id: str, reason: str) -> None
        self._wire_decode_errors = 0  # guarded-by: _conns_lock
        self._last_disconnect: float | None = None  # guarded-by: _conns_lock
        self._ever_connected = False  # guarded-by: _conns_lock
        # params-push plane: subscribers registered at hello time. A
        # dispatcher thread (_push_loop) deposits the target
        # (epoch, version) into each subscriber's one-deep cell at
        # publish boundaries; PER-SUBSCRIBER sender threads
        # (_push_sender) build and ship that subscriber's payload — a
        # slow or wedged peer wedges only its own thread, never the
        # learner thread and never the other subscribers (ISSUE 19).
        # Per-connection send locks serialize the reader's replies
        # (acks, poll responses) against push writes.
        self._push_subs: dict[int, _PushSub] = {}  # guarded-by: _conns_lock
        self._conn_send_locks: dict[int, Any] = {}  # guarded-by: _conns_lock
        self._param_pushes = 0  # guarded-by: _conns_lock
        self._push_wake = threading.Event()
        self._push_thread: threading.Thread | None = None
        # same-host shm plane (comm/shm_transport.py): one experience
        # ring per granted connection, one param seqlock area for all
        self._shm_enabled = bool(shm)
        self._shm_slots = int(shm_slots)
        self._shm_slot_bytes = int(shm_slot_bytes)
        self._shm_param_bytes = int(shm_param_bytes)
        self._conn_shm: dict[int, Any] = {}  # guarded-by: _conns_lock
        self._shm_param_area: Any = None  # guarded-by: _lock
        self._shm_doorbells = 0  # guarded-by: _conns_lock
        self._shm_torn_slots = 0  # guarded-by: _conns_lock
        self._shm_fallbacks = 0  # guarded-by: _conns_lock
        self._shm_reclaimed = 0  # guarded-by: _conns_lock
        self._shm_dropped = 0  # guarded-by: _conns_lock
        self._shm_bytes_in = 0  # guarded-by: _conns_lock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ingest-accept", daemon=True)
        self._accept_thread.start()

    # Transport interface (learner side)

    def recv_experience(self, timeout: float | None = None) -> dict | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def send_experience(self, batch: dict) -> None:
        """Local actors on the learner host share the same queue."""
        while True:
            try:
                self._q.put_nowait(batch)
                return
            except queue.Full:
                try:
                    old = self._q.get_nowait()
                    # every reader thread and local actors land here on
                    # a full queue; a bare += across threads loses drops
                    with self._conns_lock:
                        self._dropped += 1
                        if isinstance(old, ShmSlotBatch):
                            self._shm_dropped += 1
                    # an evicted shm batch must hand its slot back, or
                    # backpressure would leak the writer's ring dry
                    rel = getattr(old, "release", None)
                    if rel is not None:
                        rel()
                except queue.Empty:
                    pass

    def publish_params(self, params: Any, version: int) -> None:
        # store the tree and serialize/encode lazily on the first reply
        # per version: device->host transfer + pickling a multi-MB CNN
        # tree would otherwise run synchronously on the learner thread at
        # every publish boundary, stalling training dispatches — and is
        # pure waste when no remote host is connected
        self._provider.publish(params, version)
        # wake the push dispatcher (no-op when nothing ever subscribed)
        self._push_wake.set()

    def bump_epoch(self) -> None:
        """Advance the membership epoch in place — the drill/test hook
        for 'a new incarnation took over' without tearing the listener
        down. New hellos and versioned param replies carry the new id;
        connected epoch-aware clients converge on their next exchange."""
        self.epoch += 1
        self._push_wake.set()

    def _param_blob(self) -> bytes:
        return self._provider.raw_blob()

    def _versioned_params_reply(self, have_epoch: int,
                                have_version: int) -> bytes:
        """Versioned MSG_PARAMS/MSG_PARAMS_PUSH payload:
        [magic, epoch, version] header, plus the pickled blob only when
        the client's (epoch, version) is behind — an up-to-date replica
        costs a header-sized reply instead of megabytes of weights."""
        payload, _kind, _ver, _raw = self._provider.versioned_reply(
            have_epoch, have_version, self.epoch)
        return payload

    def get_params(self) -> tuple[Any, int]:
        """Local loopback callers get the deserialized tree directly,
        cached per published version — no pickle round-trip per pull;
        the pickled blob stays wire-only. The cache still holds the
        BLOB-roundtripped values (bf16 wire rounding and all), so local
        and remote pulls see bit-identical params."""
        return self._provider.get_tree()

    @property
    def dropped(self) -> int:
        return self._dropped

    @property
    def bytes_in(self) -> int:
        """Experience payload bytes received from remote actor hosts."""
        return self._bytes_in

    @property
    def raw_bytes_in(self) -> int:
        """What bytes_in would have been with no wire codec (the
        decoded size of every received experience leaf)."""
        return self._raw_bytes_in

    @property
    def wire_compression_ratio(self) -> float:
        """raw/wire byte ratio over all experience received so far
        (1.0 = no savings; larger is better). 0.0 before any traffic."""
        with self._conns_lock:
            return (self._raw_bytes_in / self._bytes_in
                    if self._bytes_in else 0.0)

    @property
    def bytes_out(self) -> int:
        """Param blob bytes served to remote actor hosts."""
        return self._bytes_out

    @property
    def telemetry_frames(self) -> int:
        """MSG_TELEMETRY frames received from remote peers."""
        with self._conns_lock:
            return self._telemetry_frames

    @property
    def telemetry_bytes_in(self) -> int:
        """Telemetry payload bytes received (control-plane budget)."""
        with self._conns_lock:
            return self._telemetry_bytes_in

    @property
    def peer_disconnects(self) -> int:
        """Identified telemetry peers whose connection closed."""
        with self._conns_lock:
            return self._peer_disconnects

    @property
    def wire_decode_errors(self) -> int:
        """Truncated/garbled/misframed frames received (each one also
        dropped its connection and fired on_decode_error)."""
        with self._conns_lock:
            return self._wire_decode_errors

    @property
    def param_pushes(self) -> int:
        """MSG_PARAMS_PUSH frames shipped to subscribed peers."""
        with self._conns_lock:
            return self._param_pushes

    @property
    def param_bytes_out(self) -> int:
        """Param payload bytes served (poll replies + push frames) —
        the param plane's half of the link budget; bytes_out is its
        alias on this server (experience flows IN only)."""
        with self._conns_lock:
            return self._bytes_out

    @property
    def param_raw_bytes_out(self) -> int:
        """What the served param replies would have cost with no codec
        (the APXV header+blob equivalent of every reply)."""
        with self._conns_lock:
            return self._param_raw_bytes_out

    @property
    def param_compression_ratio(self) -> float:
        """raw/wire ratio over all param bytes served (exactly 1.0
        under param_codec="raw"; >= 1.0 always — the never-inflate
        guard degrades any coded reply that would not undercut the raw
        one). 0.0 before any param traffic."""
        with self._conns_lock:
            return (self._param_raw_bytes_out / self._bytes_out
                    if self._bytes_out else 0.0)

    @property
    def param_resyncs(self) -> int:
        """Full param payloads served to coded peers that held a REAL
        base (missed version, out of the delta window, epoch bump) —
        initial fulls to fresh peers don't count."""
        with self._conns_lock:
            return self._param_resyncs

    @property
    def param_push_queue_drops(self) -> dict[str, int]:
        """Per-reason push fan-out drops: "superseded" (a deposited
        version was overwritten by a newer one before that subscriber's
        sender consumed it — the slow peer skips straight to the newest
        version, by design) and "disconnect" (send failed)."""
        with self._conns_lock:
            return dict(self._push_drop_reasons)

    @property
    def push_subscribers(self) -> int:
        """Connections that negotiated params_push and are still up."""
        with self._conns_lock:
            return len(self._push_subs)

    @property
    def serve_peers(self) -> dict[str, int]:
        """Live connections per serving-tier tenant tag, as
        policy_id -> connection count (untagged connections — old
        clients, single-tenant fleets — simply don't appear)."""
        with self._conns_lock:
            out: dict[str, int] = {}
            for policy, _cls in self._conn_serve.values():
                out[policy] = out.get(policy, 0) + 1
            return out

    @property
    def shm_doorbells(self) -> int:
        """Experience batches delivered through shm ring slots."""
        with self._conns_lock:
            return self._shm_doorbells

    @property
    def shm_torn_slots(self) -> int:
        """Doorbells whose slot failed seq/crc/framing validation —
        detected torn, freed, never delivered."""
        with self._conns_lock:
            return self._shm_torn_slots

    @property
    def shm_fallbacks(self) -> int:
        """TCP experience frames received from connections that hold
        an shm grant (ring-full / oversize degradations)."""
        with self._conns_lock:
            return self._shm_fallbacks

    @property
    def shm_reclaimed(self) -> int:
        """Slot leases reclaimed from writers that disconnected with
        claims outstanding (died mid-write or before the doorbell)."""
        with self._conns_lock:
            return self._shm_reclaimed

    @property
    def shm_dropped(self) -> int:
        """Shm-delivered batches evicted by the drop-oldest queue
        policy (their slots were freed at eviction)."""
        with self._conns_lock:
            return self._shm_dropped

    @property
    def shm_bytes_in(self) -> int:
        """Experience payload bytes that crossed via shm slots (the
        loopback bytes the TCP accounting no longer sees)."""
        with self._conns_lock:
            return self._shm_bytes_in

    @property
    def shm_slots_inflight(self) -> int:
        """Ring slots currently claimed across all granted
        connections (writer-claimed + delivered-not-yet-freed)."""
        with self._conns_lock:
            rings = list(self._conn_shm.values())
        return sum(r.inflight for r in rings)

    @property
    def shm_rings(self) -> int:
        """Connections currently holding an shm grant."""
        with self._conns_lock:
            return len(self._conn_shm)

    @property
    def pending(self) -> int:
        return self._q.qsize()

    @property
    def active_connections(self) -> int:
        """Live remote actor-host connections (readers deregister on
        disconnect). Drivers use this for idle/termination checks — a
        drained queue does not mean producers are done."""
        with self._conns_lock:
            return len(self._conns)

    @property
    def ever_connected(self) -> bool:
        """True once ANY remote producer has SENT EXPERIENCE — drivers
        use this for their boot-grace check instead of polling
        active_connections, which can miss a producer that connected
        and vanished entirely inside a warmup/compile window. Latching
        on the first experience message (not on accept) keeps
        param-only probes from masquerading as producers."""
        with self._conns_lock:
            return self._ever_connected

    def quiesced(self) -> bool:
        """True when no remote producer is connected AND none has
        disconnected within the last idle_grace_s. The grace period
        debounces transient drops: SocketTransport reconnects a broken
        send inside the same call, so an actor host that blipped is
        back within milliseconds — an idle verdict taken in that window
        would terminate a multihost fleet whose producers all intend to
        return (round-2 advisor finding on local_idle).

        INVARIANT vs the supervised reconnect loop: the client's
        reconnect backoff cap (CommConfig.reconnect_cap_s, 2.0 default)
        must stay BELOW idle_grace_s (5.0 default). A client backing
        off from a connection this server dropped retries — and, with
        the server healthy, reconnects — within one cap interval, well
        inside the grace window that its own disconnect opened, so a
        fleet merely riding out a blip never reads as quiesced. Stretch
        the backoff cap past the grace and the debounce breaks; tests
        pin the ordering (test_chaos.py)."""
        with self._conns_lock:
            if self._conns:
                return False
            if self._last_disconnect is None:
                return True
            return (time.monotonic() - self._last_disconnect
                    >= self._idle_grace_s)

    def stop(self) -> None:
        self._stop.set()
        self._push_wake.set()  # unblock the push dispatcher's wait
        with self._conns_lock:
            subs = list(self._push_subs.values())
        for sub in subs:  # unblock every per-subscriber sender
            sub.stop = True
            sub.wake.set()
        self._accept_thread.join(timeout=2)
        if self._push_thread is not None:
            self._push_thread.join(timeout=2)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:  # apexlint: lossy(shutdown close best effort)
                pass
        self._listener.close()
        # drain the ingest queue: a batch parked at shutdown is never
        # consumed, and a parked ShmSlotBatch pins its ring slot (and
        # with it the mapping) until released — drain BEFORE destroying
        # the rings so every slot is handed back first
        while True:
            try:
                old = self._q.get_nowait()
            except queue.Empty:
                break
            rel = getattr(old, "release", None)
            if rel is not None:
                rel()
        # shm teardown: the server owns every segment it granted
        with self._conns_lock:
            rings = list(self._conn_shm.values())
            self._conn_shm.clear()
        for ring in rings:
            ring.destroy()
        with self._lock:
            area, self._shm_param_area = self._shm_param_area, None
        if area is not None:
            area.destroy()

    # internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:  # apexlint: lossy(idle accept tick, nothing lost)
                continue
            except OSError:  # apexlint: lossy(listener closed by stop())
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
                self._conn_send_locks[id(conn)] = make_lock(
                    "ingest_server.conn_send")
            # apexlint: detached(reader exits when its socket dies; stop() closes every conn)
            threading.Thread(target=self._reader, args=(conn,),
                             name="ingest-reader", daemon=True).start()

    def _send_on(self, conn: socket.socket, mtype: int,
                 payload: bytes) -> None:
        """Send one frame on a connection, serialized against the other
        writer (the reader's replies vs the push thread). The per-conn
        lock is fetched under _conns_lock but HELD WITHOUT it — a slow
        subscriber's sendall must never stall accept/disconnect
        bookkeeping for the whole fleet."""
        with self._conns_lock:
            lock = self._conn_send_locks.get(id(conn))
        if lock is None:  # connection already torn down
            raise OSError("connection closed")
        with lock:
            _send_msg(conn, mtype, payload)

    def _ensure_push_thread(self) -> None:
        """Lazily start the push thread on the first subscription —
        poll-only fleets (and every pre-push build's usage) never pay
        for it."""
        with self._conns_lock:
            if self._push_thread is not None or self._stop.is_set():
                return
            self._push_thread = threading.Thread(
                target=self._push_loop, name="params-push", daemon=True)
            self._push_thread.start()

    def _push_loop(self) -> None:
        """Push DISPATCHER: at publish/epoch boundaries, write the shm
        param area (always raw — local bandwidth is free) and deposit
        the target (epoch, version) into every subscriber's one-deep
        cell. No socket write happens on this thread anymore — the
        per-subscriber _push_sender threads own the sendall, so one
        wedged peer can no longer serialize the broadcast for everyone
        (the pre-ISSUE-19 loop did exactly that)."""
        while not self._stop.is_set():
            if not self._push_wake.wait(timeout=0.2):
                continue
            self._push_wake.clear()
            version = self._provider.version
            cur = (self.epoch, version)
            with self._lock:
                area = self._shm_param_area
            # the shm param area rides this thread (same serialization
            # cost, same publish boundary) but dedupes on ITS OWN held
            # (epoch, version): a grant arriving after the last publish
            # must still land current params for the new attacher, even
            # when every TCP subscriber is already up to date
            if area is not None and version >= 0 and area.holds != cur:
                blob, aver, _key = self._provider.raw_blob_versioned()
                area.write(blob, self.epoch, aver)
            if version < 0:
                continue
            with self._conns_lock:
                subs = list(self._push_subs.values())
            for sub in subs:
                self._deposit(sub, cur)

    def _deposit(self, sub: _PushSub, cur: tuple[int, int]) -> None:
        """Latest-wins deposit into one subscriber's target cell. An
        unconsumed DIFFERENT target getting overwritten means the
        subscriber was still sending (or wedged) when a newer version
        landed: that stale version is superseded — counted, never
        queued behind (the codec chain spans the gap; a resync covers
        the rest)."""
        with sub.lock:
            prev, sub.target = sub.target, cur
        if prev is not None and prev != cur:
            with self._conns_lock:
                self._push_drop_reasons["superseded"] += 1
        sub.wake.set()

    def _push_sender(self, sub: _PushSub) -> None:
        """One subscriber's sender: consume the latest deposited
        target, build THIS subscriber's payload — coded subscribers get
        a delta against what they last received (or a full resync),
        raw subscribers the versioned header+blob exactly as before —
        and ship it. Building per subscriber is the price of fan-out
        isolation; the provider's blob/chain/full caches make every
        subscriber in the same state share the encode cost."""
        while not self._stop.is_set() and not sub.stop:
            if not sub.wake.wait(timeout=0.2):
                continue
            sub.wake.clear()
            with sub.lock:
                target, sub.target = sub.target, None
            if target is None or target == sub.last:
                continue
            epoch = target[0]
            had_base = sub.coded and sub.last[1] >= 0
            try:
                if sub.coded:
                    payload, kind, ver, raw_cost = \
                        self._provider.coded_reply(
                            sub.last[0], sub.last[1], epoch)
                else:
                    payload, kind, ver, raw_cost = \
                        self._provider.versioned_reply(-1, -1, epoch)
                self._send_on(sub.conn, MSG_PARAMS_PUSH, payload)
            except OSError:  # apexlint: lossy(subscriber dropped; reader attributes the disconnect)
                with self._conns_lock:
                    self._push_subs.pop(id(sub.conn), None)
                    self._push_drop_reasons["disconnect"] += 1
                return
            sub.last = (epoch, ver)
            with self._conns_lock:
                self._param_pushes += 1
                self._bytes_out += len(payload)
                self._param_raw_bytes_out += raw_cost
                if had_base and kind in ("full", "raw_full"):
                    self._param_resyncs += 1

    def _grant_shm(self, conn: socket.socket,
                   req: dict) -> dict[str, Any] | None:
        """Verify a hello shm offer and, if it proves same-host, build
        the grant: a fresh per-connection experience ring plus the
        (shared, lazily created) param seqlock area. Any failure —
        probe refused, /dev/shm unavailable, garbage offer — returns
        None and the connection stays plain TCP."""
        try:
            if not shm_transport.check_probe(
                    str(req.get("probe", "")), str(req.get("token", "")),
                    str(req.get("boot", ""))):
                return None
            slots = max(1, min(int(req.get("slots") or self._shm_slots),
                               self._shm_slots))
            slot_bytes = max(1 << 16,
                             min(int(req.get("slot_bytes")
                                     or self._shm_slot_bytes),
                                 self._shm_slot_bytes))
            ring = shm_transport.ShmRingServer(slots, slot_bytes)
        except (OSError, ValueError, TypeError):  # apexlint: lossy(shm unavailable -> grant refused, TCP still works)
            return None
        with self._conns_lock:
            self._conn_shm[id(conn)] = ring
        grant: dict[str, Any] = {"ring": ring.name, "slots": ring.slots,
                                 "slot_bytes": ring.slot_bytes}
        area = self._ensure_param_area()
        if area is not None:
            grant["params"] = area.name
        return grant

    def _ensure_param_area(self) -> Any:
        """Create the shared param seqlock area on the first shm grant
        and (re)arm the push thread so CURRENT params land in it — a
        client attaching long after the last publish must not read an
        empty area until the next training publish."""
        with self._lock:
            if self._shm_param_area is None:
                try:
                    self._shm_param_area = shm_transport.ShmParamArea(
                        self._shm_param_bytes)
                except (OSError, ValueError):  # apexlint: lossy(area unavailable -> clients pull params over TCP)
                    return None
            area = self._shm_param_area
        self._ensure_push_thread()
        self._push_wake.set()
        return area

    def _reader(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return  # peer closed: actor loss is tolerated
                mtype, payload = msg
                if mtype in (MSG_EXPERIENCE, MSG_EXPERIENCE_C):
                    # enqueue the payload with decode deferred (WireBatch):
                    # the ingest thread lands the bytes straight in its
                    # staging block with one copy instead of this reader
                    # materializing a full dict of array copies per
                    # message. Parse the header here so a corrupt frame
                    # faults THIS connection, not the consumer. Codec
                    # payloads (MSG_EXPERIENCE_C) are self-describing
                    # per leaf, so decode needs no per-connection state.
                    batch = WireBatch(payload)
                    batch.rows  # noqa: B018 - framing validation
                    raw = batch.raw_nbytes if mtype == MSG_EXPERIENCE_C \
                        else len(payload)
                    # ever_connected latches HERE, not on accept: a
                    # param-only probe (monitoring, or an actor host
                    # that died waiting for params) is not a producer,
                    # and counting it once terminated a remote-only
                    # learner 0.1s into run() — the probe had come and
                    # gone during construction, so boot grace was
                    # skipped and quiesced() read idle (observed in the
                    # round-4 soak)
                    # byte counters under the lock too: every reader
                    # thread increments them, and a bare `+=` interleaved
                    # across threads loses counts — they are the soak's
                    # link-budget accounting, so they must be exact
                    with self._conns_lock:
                        self._ever_connected = True
                        self._bytes_in += len(payload)
                        self._raw_bytes_in += raw
                        # a TCP experience frame from a connection that
                        # holds an shm grant is a FALLBACK (ring full /
                        # oversize batch) — the server-visible half of
                        # the client's degradation accounting
                        if id(conn) in self._conn_shm:
                            self._shm_fallbacks += 1
                    self.send_experience(batch)
                elif mtype == MSG_SHM_DOORBELL:
                    # same-host data plane: the payload crossed in a
                    # shared-memory slot; this tiny frame only names it.
                    # Validation (seq + crc over the slot) runs before
                    # anything is delivered — a torn slot (writer died
                    # mid-write, wild write, stale doorbell) is counted
                    # and freed, never enqueued, and does NOT fault the
                    # connection: the control socket itself framed fine.
                    with self._conns_lock:
                        ring = self._conn_shm.get(id(conn))
                    if ring is None:
                        raise ValueError("shm doorbell without a grant")
                    try:
                        slot, seq, nbytes, crc = _DOORBELL.unpack(payload)
                    except struct.error:
                        raise ValueError("bad shm doorbell frame")
                    view = ring.take(slot, seq, nbytes, crc)
                    batch = None
                    if view is not None:
                        batch = ShmSlotBatch(view, ring, slot)
                        try:
                            batch.rows  # noqa: B018 - framing validation
                        except (ValueError, KeyError):
                            batch.release()  # frees the slot
                            batch = None
                    if batch is None:
                        with self._conns_lock:
                            self._shm_torn_slots += 1
                            who = self._conn_peers.get(
                                id(conn), "unidentified")
                        cb = self.on_decode_error
                        if cb is not None and not self._stop.is_set():
                            cb(who, "torn shm slot")
                        continue
                    with self._conns_lock:
                        self._ever_connected = True
                        self._shm_doorbells += 1
                        self._shm_bytes_in += nbytes
                    self.send_experience(batch)
                elif mtype == MSG_HELLO:
                    # codec negotiation: grant the configured codec iff
                    # the client offered it; else raw. An OLD client
                    # never sends a hello and keeps raw MSG_EXPERIENCE.
                    # Telemetry is a capability echo on the same
                    # exchange: granted iff the client offered it (an
                    # old client never does, so this server never
                    # expects frames from it).
                    serve_tag: tuple[str, int] | None = None
                    shm_req: dict | None = None
                    try:
                        hello = json.loads(bytes(payload))
                        offered = hello.get("codecs", [])
                        wants_tel = bool(hello.get("telemetry"))
                        wants_push = bool(hello.get("params_push"))
                        # param-plane codec offer (push channel only —
                        # pulls negotiate per-request in MSG_PARAMS_REQ
                        # since the param socket has no hello). Old
                        # clients never offer; old servers ignore the
                        # key — raw pushes both ways.
                        pc_offer = hello.get("param_codecs", [])
                        if not isinstance(pc_offer, list):
                            pc_offer = []
                        # serving-tier tenant tag, negotiated like the
                        # telemetry capability: an OLD client never
                        # offers one, an OLD server (this code absent)
                        # ignores unknown offer keys — both directions
                        # degrade to untagged traffic
                        serve = hello.get("serve")
                        if isinstance(serve, dict) and serve.get("policy"):
                            serve_tag = (str(serve["policy"]),
                                         int(serve.get("class", 0)))
                        # same-host shm offer (PR 4/6/13 capability
                        # idiom again): an old client never offers, an
                        # old server ignores the key — TCP either way
                        req = hello.get("shm")
                        if isinstance(req, dict):
                            shm_req = req
                    except (ValueError, AttributeError, TypeError):
                        offered, wants_tel, wants_push = [], False, False
                        pc_offer = []
                        serve_tag = None
                        shm_req = None
                    grant = self._codec if self._codec in offered \
                        else "raw"
                    pc_grant: str | None = None
                    if pc_offer:
                        pc_grant = self._param_codec \
                            if self._param_codec in pc_offer else "raw"
                    shm_grant = self._grant_shm(conn, shm_req) \
                        if self._shm_enabled and shm_req is not None \
                        else None
                    # the epoch rides every ack: an old client never
                    # hellos (never sees it), a new client uses it to
                    # distinguish a blip from a new incarnation
                    ack: dict[str, Any] = {"codec": grant,
                                           "epoch": self.epoch}
                    if wants_tel:
                        ack["telemetry"] = True
                    # the shm param area SUPERSEDES per-connection param
                    # pushes for a granted client: its get_params reads
                    # the seqlock area, so shipping the same blob down
                    # this socket too would be pure duplicate bytes
                    if wants_push and shm_grant is None:
                        ack["params_push"] = True
                    if pc_grant is not None:
                        ack["param_codec"] = pc_grant
                    if shm_grant is not None:
                        ack["shm"] = shm_grant
                    if serve_tag is not None:
                        with self._conns_lock:
                            self._conn_serve[id(conn)] = serve_tag
                        ack["serve"] = True
                    # ack FIRST, subscribe after: if a publish is already
                    # pending, a push thread registered before the ack is
                    # on the wire could win the conn's send lock and make
                    # MSG_PARAMS_PUSH the connection's first frame — the
                    # client reads that as a failed negotiation, degrades
                    # to raw, and never drains the pushes, eventually
                    # wedging the push thread in sendall on a full window
                    self._send_on(conn, MSG_HELLO_ACK,
                                  json.dumps(ack).encode())
                    if wants_push and shm_grant is None:
                        sub = _PushSub(
                            conn, pc_grant not in (None, "raw"))
                        with self._conns_lock:
                            self._push_subs[id(conn)] = sub
                        # apexlint: detached(per-subscriber sender exits on sub.stop, set by stop() and by disconnect)
                        threading.Thread(
                            target=self._push_sender, args=(sub,),
                            name="params-push-send",
                            daemon=True).start()
                        self._ensure_push_thread()
                        # deposit CURRENT params right away: a
                        # subscriber joining after the last publish
                        # used to wait for the next one; now its
                        # sender ships what's already published
                        self._push_wake.set()
                elif mtype == MSG_TELEMETRY:
                    # per-peer obs snapshot: remember which peer this
                    # connection is (disconnect attribution), count the
                    # frame, and hand it to the fleet aggregator hook.
                    # A garbled frame faults this connection like any
                    # misframed message.
                    frame = json.loads(bytes(payload))
                    if not isinstance(frame, dict):
                        raise ValueError("telemetry frame is not an object")
                    peer = str(frame.get("peer", "peer?"))
                    with self._conns_lock:
                        self._conn_peers[id(conn)] = peer
                        self._telemetry_frames += 1
                        self._telemetry_bytes_in += len(payload)
                    cb = self.on_telemetry
                    if cb is not None:
                        cb(peer, frame)
                elif mtype == MSG_PARAMS_REQ:
                    # empty payload = legacy client: raw pickled blob.
                    # JSON payload = epoch-aware client stating what it
                    # already has: versioned header, blob only if
                    # behind. A "codec" field is the pull channel's
                    # per-request codec negotiation (the param socket
                    # has no hello): the coded reply is served iff the
                    # client asked AND this server's param_codec
                    # matches — any other combination, including this
                    # code absent on either side, degrades to the
                    # versioned/legacy shapes the client already
                    # parses.
                    resync = False
                    if len(payload) == 0:
                        reply = self._param_blob()
                        raw_cost = len(reply)
                    else:
                        try:
                            req = json.loads(bytes(payload))
                            have_ep = int(req.get("epoch", -1))
                            have_v = int(req.get("v", -1))
                            want = str(req.get("codec", "raw"))
                        except (ValueError, AttributeError, TypeError):
                            have_ep, have_v, want = -1, -1, "raw"
                        if want != "raw" and want == self._param_codec:
                            reply, kind, _ver, raw_cost = \
                                self._provider.coded_reply(
                                    have_ep, have_v, self.epoch)
                            resync = (have_v >= 0
                                      and kind in ("full", "raw_full"))
                        else:
                            reply, _kind, _ver, raw_cost = \
                                self._provider.versioned_reply(
                                    have_ep, have_v, self.epoch)
                    with self._conns_lock:
                        self._bytes_out += len(reply)
                        self._param_raw_bytes_out += raw_cost
                        if resync:
                            self._param_resyncs += 1
                    self._send_on(conn, MSG_PARAMS, reply)
        except OSError:
            # dead connection: drop it, keep serving others — the loss
            # is accounted where it is attributable (peer_disconnects
            # in the finally path below)
            return  # apexlint: lossy(disconnect counted in reader finally)
        except ValueError as e:
            # truncated / garbled / misframed traffic: the connection
            # still drops (framing state is unrecoverable mid-stream),
            # but the fault is COUNTED and attributed so a byzantine or
            # proxied peer can't silently churn connections
            with self._conns_lock:
                self._wire_decode_errors += 1
                who = self._conn_peers.get(id(conn), "unidentified")
            cb = self.on_decode_error
            if cb is not None and not self._stop.is_set():
                cb(who, str(e))
            return
        finally:
            with self._conns_lock:
                try:
                    self._conns.remove(conn)  # churn must not leak socks
                except ValueError:
                    pass
                self._conn_send_locks.pop(id(conn), None)
                sub = self._push_subs.pop(id(conn), None)
                if sub is not None:
                    # stop this subscriber's sender thread (it may also
                    # have exited on its own after a failed send)
                    sub.stop = True
                    sub.wake.set()
                self._conn_serve.pop(id(conn), None)
                ring = self._conn_shm.pop(id(conn), None)
                self._last_disconnect = time.monotonic()
                peer = self._conn_peers.pop(id(conn), None)
                if peer is not None:
                    self._peer_disconnects += 1
            if ring is not None:
                # lease reclaim: a writer that died mid-write left
                # claimed slots no doorbell will ever name — retire()
                # counts them, unlinks the segment, and defers the
                # unmap until queued batches drain
                reclaimed = ring.retire()
                with self._conns_lock:
                    self._shm_reclaimed += reclaimed
            if peer is not None and not self._stop.is_set():
                # a lost actor is an attributed event, never silence
                logging.getLogger(__name__).warning(
                    "[fleet] telemetry peer %r disconnected — its actors "
                    "stop producing until it reconnects", peer)
                cb = self.on_disconnect
                if cb is not None:
                    cb(peer)
            try:
                conn.close()
            except OSError:  # apexlint: lossy(close of dead connection)
                pass


# jax_to_numpy / _Bf16Wire / _downcast_f32 / _upcast_bf16 moved to
# comm/param_codec.py with the param codec (re-exported at the top of
# this module for existing importers).


# -- actor-host side --------------------------------------------------------


class SocketTransport:
    """Transport for a remote actor host: pushes experience, pulls params.

    send_experience never raises into the actor loop: on a broken
    connection it runs a SUPERVISED RECONNECT LOOP — one immediate
    retry inside the failing call, then capped jittered exponential
    backoff across calls (reconnect_base_s doubling to reconnect_cap_s,
    full jitter so a restarted learner is not hit by the whole fleet at
    once). Batches that fall in a backoff window are dropped without
    touching the network; every drop is accounted by reason
    (refused / reset / timeout / backpressure / other) so a soak can
    tell a dead learner from a saturated link (Ape-X ingest is
    lossy-tolerant; the actor keeps generating experience for when the
    learner returns).

    wire_codec is OFFERED at connect time (MSG_HELLO) and used only if
    the server acks it; an old server ignores the hello, the ack read
    times out (hello_timeout), and the connection falls back to raw —
    negotiation reruns on every reconnect, so a learner restart onto a
    different build renegotiates transparently. The ack also carries
    the server's membership epoch: an epoch CHANGE (new incarnation)
    resets the pushed-params cell and is counted/logged, so the param
    path re-converges even when the new learner's version counter
    restarted below the old one.
    """

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0,
                 wire_codec: str = "delta-deflate",
                 hello_timeout: float = 2.0, telemetry: bool = True,
                 reconnect_base_s: float = 0.05,
                 reconnect_cap_s: float = 2.0,
                 params_push: bool = False,
                 param_codec: str = "delta-q8",
                 serve_policy: str = "", serve_class: int = 0,
                 shm: bool = False, shm_slots: int = 8,
                 shm_slot_bytes: int = 1 << 22):
        """telemetry: offer the fleet-telemetry capability in the
        connect-time hello. send_telemetry only ships frames after the
        server granted it, so leaving this on against an old server
        costs one hello-timeout per (re)connect and nothing after.

        reconnect_base_s/reconnect_cap_s: supervised-reconnect backoff
        window. The cap must stay below the server's idle_grace_s (see
        SocketIngestServer.quiesced) so a backing-off fleet never reads
        as quiesced.

        params_push: offer the server-initiated param publication
        capability; when granted, MSG_PARAMS_PUSH frames arrive on the
        experience socket and poll_pushed_params() hands them over —
        against an old server the offer is ignored and polling is the
        only path.

        param_codec: param-plane codec to ask for ("delta-q8" default
        — per-leaf int8-quantized deltas vs the version last received,
        comm/param_codec.py). Pulls state it per request in
        MSG_PARAMS_REQ; pushes offer it in the hello. A server that
        doesn't speak it (old build, or configured raw) replies the
        versioned/legacy shapes, which parse exactly as before — and
        param_codec="raw" here keeps the request bytes and the whole
        TCP param path bitwise identical to the pre-codec build.

        serve_policy/serve_class: serving-tier tenant tag offered in
        the hello ("" = untagged, the single-tenant default). A new
        server records the tag for per-tenant attribution and echoes
        the capability; an old server ignores the unknown offer key —
        experience flows untagged either way. The tag also arms
        set_backpressure: the serving tier's admission controller can
        then shed THIS host's sends during overload windows.

        shm: offer the same-host shared-memory transport in the hello
        (with a boot-id + namespace probe proving same-host). When the
        server grants it, experience packs straight into ring slots
        (MSG_SHM_DOORBELL on this socket names them) and params read
        from the server's seqlock area; every shm failure mode —
        cross-host peer, old server, full ring, oversize batch, torn
        read — degrades to the plain TCP paths, counted. shm_slots/
        shm_slot_bytes shape the ring requested from the server."""
        self._addr = (host, port)
        self._timeout = connect_timeout
        self._codec = _check_codec(wire_codec)
        self._hello_timeout = hello_timeout
        self._telemetry = bool(telemetry)
        self._params_push = bool(params_push)
        self._param_codec = check_param_codec(param_codec)
        self._serve_policy = str(serve_policy)
        self._serve_class = int(serve_class)
        self._reconnect_base_s = max(float(reconnect_base_s), 1e-3)
        self._reconnect_cap_s = max(float(reconnect_cap_s),
                                    self._reconnect_base_s)
        self._negotiated: str = "raw"  # guarded-by: _send_lock
        self._telemetry_ok = False  # guarded-by: _send_lock
        self._push_ok = False  # guarded-by: _send_lock
        self._serve_ok = False  # guarded-by: _send_lock
        # serving-tier backpressure latch: while engaged, experience
        # sends drop host-side (counted under the existing
        # "backpressure" drop reason) instead of deepening an already
        # over-SLO admission queue. A plain bool flipped by
        # set_backpressure from the tier's controller thread and read
        # in the send path — GIL-atomic, deliberately lock-free so the
        # controller never blocks on a slow send
        self._bp_engaged = False
        self._telemetry_frames_out = 0  # guarded-by: _send_lock
        self._telemetry_bytes_out = 0  # guarded-by: _send_lock
        self._sock: socket.socket | None = None  # guarded-by: _send_lock
        self._param_sock: socket.socket | None = None  # guarded-by: _param_lock
        # every client-side drop is attributed to exactly one reason
        # bucket — the fleet report's drop_reasons table sums to
        # `dropped` because lint proves it, not because tests noticed
        # apexlint: closure(_dropped == _drop_reasons)
        self._dropped = 0  # guarded-by: _send_lock
        self._bytes_out = 0  # guarded-by: _send_lock
        self._raw_bytes_out = 0  # guarded-by: _send_lock
        self._encode_ms = 0.0  # guarded-by: _send_lock
        # supervised-reconnect state (all guarded-by: _send_lock):
        # consecutive failures drive the exponential backoff; the
        # disconnect timestamp feeds the reconnect-latency instrument
        self._consec_fails = 0  # guarded-by: _send_lock
        self._backoff_until = 0.0  # guarded-by: _send_lock
        self._reconnects = 0  # guarded-by: _send_lock
        self._disconnected_at: float | None = None  # guarded-by: _send_lock
        self._reconnect_latencies: deque[float] = deque(
            maxlen=_RECONNECT_SAMPLES)  # guarded-by: _send_lock
        self._drop_reasons = {"refused": 0, "reset": 0, "timeout": 0,
                              "backpressure": 0, "other": 0}  # guarded-by: _send_lock
        self._bytes_in = 0  # guarded-by: _param_lock
        self._param_version = -1  # guarded-by: _param_lock
        self._param_epoch = -1  # guarded-by: _param_lock
        self._param_pull_errors = 0  # guarded-by: _param_lock
        self._param_unchanged = 0  # guarded-by: _param_lock
        # coded payloads whose base this decoder didn't hold (server
        # chain window overrun, epoch bump, state lost) — each one
        # reset the chain and re-pulled full
        self._param_resyncs = 0  # guarded-by: _param_lock
        # param-codec chain state: the float32 reconstruction coded
        # payloads advance. Its own lock because BOTH the pull path and
        # the push reader thread decode through it.
        self._param_decoder = ParamChainDecoder()  # guarded-by: _codec_lock
        # push-channel codec grant from the hello ack (the pull channel
        # negotiates per request and needs no latch)
        self._param_codec_ok = False  # guarded-by: _send_lock
        # membership epoch as last seen from any server message; its
        # own lock because both the send path (hello ack) and the param
        # path (versioned replies) update it
        self._epoch = -1  # guarded-by: _meta_lock
        self._epoch_changes = 0  # guarded-by: _meta_lock
        # server-pushed params land here (reader thread) until the
        # puller consumes them via poll_pushed_params
        self._pushed: tuple[Any, int, int] | None = None  # guarded-by: _push_lock
        self._param_pushes_in = 0  # guarded-by: _push_lock
        # independent locks: a param pull blocking on the network (up to
        # the connect timeout) must not stall the actor threads' experience
        # sends — they use different sockets and share no state.
        # (_bytes_out and friends: payload bytes shipped vs their
        # uncompressed size, cumulative encode wall-ms, param blob
        # bytes pulled — the soak's link-budget accounting)
        # same-host shm plane: the ring writer lives under _send_lock
        # with the socket it was negotiated with; the param reader is
        # assigned whole under _send_lock but READ lock-free in
        # get_params (GIL-atomic reference swap, the _bp_engaged idiom)
        # because the param path must never contend with sends
        self._shm_enabled = bool(shm)
        self._shm_slots = int(shm_slots)
        self._shm_slot_bytes = int(shm_slot_bytes)
        self._shm_boot_id = shm_transport.boot_id()  # test seam
        self._shm_ring: Any = None  # guarded-by: _send_lock
        self._shm_param_reader: Any = None
        self._shm_posts = 0  # guarded-by: _send_lock
        self._shm_fallbacks = 0  # guarded-by: _send_lock
        self._shm_bytes_out = 0  # guarded-by: _send_lock
        self._shm_param_reads = 0  # guarded-by: _param_lock
        self._shm_param_fallbacks = 0  # guarded-by: _param_lock
        self._send_lock = make_lock("transport._send_lock")
        self._param_lock = make_lock("transport._param_lock")
        self._meta_lock = make_lock("transport._meta_lock")
        self._push_lock = make_lock("transport._push_lock")
        self._codec_lock = make_lock("transport._codec_lock")

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    @staticmethod
    def _classify_drop(exc: BaseException) -> str:
        """Per-reason drop accounting bucket for a send/connect failure.
        socket.timeout is TimeoutError is an OSError subclass — test
        the narrow classes before the broad one."""
        if isinstance(exc, ConnectionRefusedError):
            return "refused"
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError)):
            return "reset"
        if isinstance(exc, (socket.timeout, TimeoutError)):
            return "timeout"
        return "other"

    def _note_send_failure(self, exc: BaseException) -> str:
        """Record one failed send/connect on the experience path and
        arm the backoff window (caller holds _send_lock). Exponential
        with FULL jitter: a fleet of actors that lost the same learner
        decorrelates instead of reconnect-storming the restarted one.
        Returns the drop-reason bucket."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # apexlint: lossy(close of an already-dead socket)
                pass
            self._sock = None  # apexlint: unguarded(caller holds _send_lock)
        # shm rode this connection's grant: the server reclaims the
        # segments once it notices the disconnect, so detach now and
        # renegotiate on reconnect
        self._detach_shm()
        if self._disconnected_at is None:
            self._disconnected_at = time.monotonic()  # apexlint: unguarded(caller holds _send_lock)
        self._consec_fails += 1  # apexlint: unguarded(caller holds _send_lock)
        backoff = min(self._reconnect_cap_s,
                      self._reconnect_base_s
                      * (2 ** min(self._consec_fails - 1, 16)))
        self._backoff_until = (time.monotonic()  # apexlint: unguarded(caller holds _send_lock)
                               + backoff * (0.5 + 0.5 * random.random()))
        return self._classify_drop(exc)

    def _note_connected(self) -> None:
        """Reset the backoff after a successful (re)connect and sample
        the outage length into the recovery-latency instrument (caller
        holds _send_lock)."""
        self._consec_fails = 0  # apexlint: unguarded(caller holds _send_lock)
        self._backoff_until = 0.0  # apexlint: unguarded(caller holds _send_lock)
        if self._disconnected_at is not None:
            self._reconnect_latencies.append(
                time.monotonic() - self._disconnected_at)
            self._disconnected_at = None  # apexlint: unguarded(caller holds _send_lock)
            self._reconnects += 1  # apexlint: unguarded(caller holds _send_lock)

    def _note_epoch(self, ep: int) -> None:
        """Record the server epoch from an ack / versioned reply; an
        epoch CHANGE (new server incarnation) clears the pushed-params
        cell (it came from the dead incarnation) and warns — version
        counters may have restarted, so downstream updates must key on
        the epoch, not on version monotonicity. The serving tier's
        backpressure latch clears for the same reason: it was engaged
        by the DEAD incarnation's admission controller, and left set it
        would shed every send into the new incarnation forever — the
        new controller re-engages within one SLO window if its queue
        really is over the line."""
        with self._meta_lock:
            old = self._epoch
            self._epoch = ep
            changed = old != -1 and old != ep
            if changed:
                self._epoch_changes += 1
        if changed:
            with self._push_lock:
                self._pushed = None
            self._bp_engaged = False
            logging.getLogger(__name__).warning(
                "[fleet] learner epoch changed %d -> %d (restart or "
                "failover); params will re-converge to the new "
                "incarnation and any stale backpressure latch is "
                "released", old, ep)

    def _connect_experience(self) -> socket.socket:
        """Connect the experience socket and negotiate codec, telemetry
        and params-push. Sets self._negotiated; any failure mode (old
        server ignoring the hello, timeout, garbled ack) degrades to
        raw, never to an error — raw MSG_EXPERIENCE is universally
        understood."""
        sock = self._connect()
        # only send_experience/send_telemetry call this, under _send_lock
        self._negotiated = "raw"  # apexlint: unguarded(caller holds _send_lock)
        self._telemetry_ok = False  # apexlint: unguarded(caller holds _send_lock)
        self._push_ok = False  # apexlint: unguarded(caller holds _send_lock)
        self._param_codec_ok = False  # apexlint: unguarded(caller holds _send_lock)
        self._serve_ok = False  # apexlint: unguarded(caller holds _send_lock)
        # shm attachments belong to the PREVIOUS connection's grant —
        # the server retires those segments on our disconnect, so a
        # reconnect always renegotiates fresh ones
        self._detach_shm()
        probe = None
        if self._shm_enabled:
            try:
                probe, probe_token = shm_transport.make_probe()
            except (OSError, ValueError):  # apexlint: lossy(/dev/shm unavailable -> offer skipped, TCP as before)
                probe = None
        if (self._codec != "raw" or self._telemetry
                or self._params_push or self._serve_policy
                or probe is not None):
            # the hello now also fires with a raw codec when telemetry
            # is wanted — an old server still just ignores it
            try:
                offer: dict[str, Any] = {"codecs": [self._codec],
                                         "telemetry": self._telemetry}
                if self._params_push:
                    offer["params_push"] = True
                    if self._param_codec != "raw":
                        # coded pushes ride the same subscription; a
                        # server without this key's code ignores it
                        offer["param_codecs"] = [self._param_codec]
                if self._serve_policy:
                    offer["serve"] = {"policy": self._serve_policy,
                                      "class": self._serve_class}
                if probe is not None:
                    offer["shm"] = {"boot": self._shm_boot_id,
                                    "probe": probe.name,
                                    "token": probe_token,
                                    "slots": self._shm_slots,
                                    "slot_bytes": self._shm_slot_bytes}
                _send_msg(sock, MSG_HELLO, json.dumps(offer).encode())
                sock.settimeout(self._hello_timeout)
                msg = _recv_msg(sock)
                if msg is not None and msg[0] == MSG_HELLO_ACK:
                    ack = json.loads(bytes(msg[1]))
                    grant = ack.get("codec")
                    if grant in WIRE_CODECS:
                        self._negotiated = grant  # apexlint: unguarded(caller holds _send_lock)
                    if self._telemetry and bool(ack.get("telemetry")):
                        self._telemetry_ok = True  # apexlint: unguarded(caller holds _send_lock)
                    if self._params_push and bool(ack.get("params_push")):
                        self._push_ok = True  # apexlint: unguarded(caller holds _send_lock)
                    if (self._param_codec != "raw"
                            and ack.get("param_codec")
                            == self._param_codec):
                        self._param_codec_ok = True  # apexlint: unguarded(caller holds _send_lock)
                    if self._serve_policy and bool(ack.get("serve")):
                        self._serve_ok = True  # apexlint: unguarded(caller holds _send_lock)
                    ep = ack.get("epoch")
                    if isinstance(ep, int):
                        self._note_epoch(ep)
                    if probe is not None:
                        self._attach_shm_grant(ack.get("shm"))
            except (OSError, ValueError, AttributeError):
                pass  # apexlint: lossy(old server / timeout / garbage ack -> raw fallback)
            finally:
                sock.settimeout(self._timeout)
                if probe is not None:
                    # the probe's job ended with the ack; unlink FIRST
                    # (needs only the name — the filesystem entry is
                    # what leaks), then close the mapping: a close()
                    # failure (BufferError on a stray export) must not
                    # leave the name behind in /dev/shm
                    try:
                        probe.unlink()
                        probe.close()
                    except (OSError, BufferError):  # apexlint: lossy(probe already gone)
                        pass
        self._note_connected()
        if self._push_ok:
            # apexlint: detached(push reader dies with its socket; close() and reconnect both close it)
            threading.Thread(target=self._push_reader, args=(sock,),
                             name="params-push-reader",
                             daemon=True).start()
        return sock

    def _attach_shm_grant(self, grant: Any) -> None:
        """Attach the segments a hello ack granted (caller holds
        _send_lock). Attach failure of either segment degrades that
        plane to TCP — never to an error."""
        if not isinstance(grant, dict):
            return
        try:
            self._shm_ring = shm_transport.ShmRingWriter(  # apexlint: unguarded(caller holds _send_lock)
                str(grant.get("ring", "")))
        except (OSError, ValueError):  # apexlint: lossy(ring unattachable -> TCP experience, counted at first send)
            self._shm_ring = None  # apexlint: unguarded(caller holds _send_lock)
        params = grant.get("params")
        if params:
            try:
                self._shm_param_reader = shm_transport.ShmParamReader(
                    str(params))
            except (OSError, ValueError):  # apexlint: lossy(area unattachable -> TCP param pulls)
                self._shm_param_reader = None

    def _detach_shm(self) -> None:
        """Drop shm attachments (caller holds _send_lock). Detach
        only — the segments are server-owned; it unlinks them when it
        notices our disconnect."""
        ring, self._shm_ring = self._shm_ring, None  # apexlint: unguarded(caller holds _send_lock)
        if ring is not None:
            ring.close()
        reader, self._shm_param_reader = self._shm_param_reader, None
        if reader is not None:
            reader.close()

    def _push_reader(self, sock: socket.socket) -> None:
        """Reader for server-initiated MSG_PARAMS_PUSH frames on the
        experience socket; one thread per negotiated connection, exits
        when that socket dies (the next reconnect spawns a fresh one).
        Waits on select so an idle socket never trips the IO timeout
        mid-frame; once bytes are available, a timeout inside the frame
        read means a wedged sender and drops the connection."""
        import select
        while True:
            try:
                ready, _, _ = select.select([sock], [], [], 0.25)
                if not ready:
                    if sock.fileno() < 0:
                        return
                    continue
                msg = _recv_msg(sock)
            except (OSError, ValueError):  # apexlint: lossy(push reader exits; reconnect respawns it)
                return
            if msg is None:
                return
            if msg[0] != MSG_PARAMS_PUSH:
                continue  # unexpected control traffic: ignore
            parsed = self._parse_params_payload(msg[1])
            if parsed is None:
                continue
            status, params, version, ep = parsed
            if ep is None:
                continue  # push frames are always versioned
            self._note_epoch(ep)
            if status == "resync":
                # a pushed delta's base is not what we hold (e.g. a
                # pull advanced the chain past the push channel's
                # last-sent): clear the held version so the next pull
                # asks baseless and comes back full
                self._note_param_resync()
                continue
            if params is not None:
                with self._push_lock:
                    self._pushed = (params, version, ep)
                    self._param_pushes_in += 1
            # the poll path now knows this (epoch, version) is in hand,
            # so its next conditional pull is a header-sized round-trip
            with self._param_lock:
                self._param_epoch = ep
                self._param_version = version

    def _note_param_resync(self) -> None:
        """A coded payload's base was not what the chain held: count
        it, drop the chain, and clear the held version so the next
        request states no base and the server answers full."""
        with self._codec_lock:
            self._param_decoder.reset()
        with self._param_lock:
            self._param_resyncs += 1
            self._param_version = -1

    def poll_pushed_params(self) -> tuple[Any, int]:
        """Consume the latest server-pushed params, if any arrived
        since the last call: (params, version), or (None, -1). Never
        blocks; safe alongside get_params polling (the push cell is
        epoch-cleared on incarnation change)."""
        with self._push_lock:
            cell, self._pushed = self._pushed, None
        if cell is None:
            return None, -1
        return cell[0], cell[1]

    def _parse_params_payload(self, payload) -> \
            tuple[str, Any, int, int | None] | None:
        """Parse a MSG_PARAMS / MSG_PARAMS_PUSH payload of any shape:
        ("unchanged"|"full"|"resync", params, version, epoch|None), or
        None when the blob is undecodable. The first bytes name the
        shape unambiguously: a coded payload leads with
        PARAMS_CODEC_MAGIC, a versioned reply with PARAMS_HDR_MAGIC,
        and a legacy raw pickle with neither (pickle streams start with
        the 0x80 opcode). "resync" means a coded payload's base is not
        what this decoder holds — the caller clears its held version
        and re-pulls; params is None."""
        if len(payload) >= 4:
            sniff = struct.unpack_from("<I", payload)[0]
            if sniff == PARAMS_CODEC_MAGIC:
                return self._parse_coded_payload(payload)
        if len(payload) >= _PARAMS_HDR.size:
            magic, ep, ver = _PARAMS_HDR.unpack_from(payload)
            if magic == PARAMS_HDR_MAGIC:
                if len(payload) == _PARAMS_HDR.size:
                    return "unchanged", None, ver, ep
                try:
                    params, version = pickle.loads(
                        memoryview(payload)[_PARAMS_HDR.size:])
                except Exception as e:
                    self._warn_bad_blob(e)
                    return None
                tree = _upcast_bf16(params)
                if self._param_codec != "raw":
                    # seed the delta chain from this raw-path full, so
                    # a client bootstrapped over APXV (never-inflate
                    # degradation, mixed negotiation) rides deltas
                    # afterwards
                    with self._codec_lock:
                        self._param_decoder.note_full(tree, version, ep)
                return "full", tree, version, ep
        try:
            params, version = pickle.loads(payload)
        except Exception as e:
            self._warn_bad_blob(e)
            return None
        return "full", _upcast_bf16(params), version, None

    def _parse_coded_payload(self, payload) -> \
            tuple[str, Any, int, int | None] | None:
        """Apply one coded (PARAMS_CODEC_MAGIC) payload through the
        chain decoder. A malformed payload warns like a bad blob and
        returns None; a base mismatch surfaces as "resync"."""
        try:
            with self._codec_lock:
                status, tree, ver, ep = self._param_decoder.apply(
                    payload)
        except Exception as e:
            self._warn_bad_blob(e)
            return None
        return status, tree, ver, ep

    @staticmethod
    def _warn_bad_blob(e: BaseException) -> None:
        # an undecodable blob usually means wire-format skew (e.g. a
        # learner host on a newer build): swallowing it silently would
        # leave the actor on stale params forever with a
        # healthy-looking connection — log once per process
        global _WARNED_BAD_BLOB
        if not _WARNED_BAD_BLOB:
            _WARNED_BAD_BLOB = True
            logging.getLogger(__name__).warning(
                "param blob undecodable (%r) — version skew between "
                "actor and learner hosts? Actor continues on its "
                "current params.", e)

    def send_experience(self, batch: dict) -> None:
        # encode under the send lock: the payload's codec must match
        # THIS connection's negotiation, which a mid-call reconnect can
        # change (it re-encodes in that case — reconnects are rare)
        with self._send_lock:
            # backoff gate: inside a backoff window the batch drops
            # WITHOUT touching the network — hammering a dead learner
            # from every actor thread at full send rate is how
            # reconnect storms start. The serving tier's backpressure
            # latch drops through the same accounted path: an over-SLO
            # learner asked this host to stop deepening the queue.
            if self._bp_engaged or (self._sock is None
                                    and time.monotonic()
                                    < self._backoff_until):
                self._dropped += 1
                self._drop_reasons["backpressure"] += 1
                return
            payload: bytes | None = None
            payload_codec: str | None = None
            reason = "other"
            for _ in range(2):  # current socket, then one reconnect
                try:
                    if self._sock is None:
                        self._sock = self._connect_experience()
                    ring = self._shm_ring
                    if ring is not None:
                        # same-host fast path: pack straight into a
                        # ring slot (the one copy — no codec, no
                        # sendall of the body) and ring the doorbell
                        # on this socket. A full ring or oversize
                        # batch falls through to TCP for THIS batch
                        # only, counted.
                        t0 = time.perf_counter()
                        post = ring.post(batch)
                        self._encode_ms += (time.perf_counter()
                                            - t0) * 1e3
                        if post is not None:
                            db = _DOORBELL.pack(*post)
                            try:
                                _send_msg(self._sock, MSG_SHM_DOORBELL,
                                          db)
                            except OSError:
                                # the doorbell never left: un-claim the
                                # slot before the reconnect path drops
                                # the whole ring attachment
                                ring.release(post[0])
                                raise
                            self._shm_posts += 1
                            # shm bytes stay OUT of the raw/wire codec
                            # ratio — only the doorbell touched TCP
                            self._shm_bytes_out += post[2]
                            self._bytes_out += len(db)
                            return
                        self._shm_fallbacks += 1
                    codec = self._negotiated
                    if payload is None or payload_codec != codec:
                        t0 = time.perf_counter()
                        payload = encode_batch(batch, codec)
                        self._encode_ms += (time.perf_counter() - t0) * 1e3
                        payload_codec = codec
                    mtype = MSG_EXPERIENCE_C if codec != "raw" \
                        else MSG_EXPERIENCE
                    _send_msg(self._sock, mtype, payload)
                    self._bytes_out += len(payload)
                    self._raw_bytes_out += sum(
                        v.nbytes for v in batch.values()
                        if isinstance(v, np.ndarray))
                    return
                except OSError as e:
                    reason = self._note_send_failure(e)
            self._dropped += 1
            self._drop_reasons[reason] += 1

    def set_backpressure(self, engaged: bool) -> None:
        """Engage/release the serving-tier backpressure latch: while
        engaged, send_experience drops host-side under the existing
        accounted "backpressure" reason instead of pushing more load
        at an over-SLO learner. Called by the admission controller's
        on_backpressure hook; thread-safe (plain bool flip)."""
        self._bp_engaged = bool(engaged)

    @property
    def backpressure_engaged(self) -> bool:
        """Current state of the serving-tier backpressure latch (read
        by the remediation plane's stale-controller watchdog and the
        chaos bench's remediated arm)."""
        return self._bp_engaged

    def kick(self) -> bool:
        """Remediation actuator: collapse the pending reconnect
        backoff so the NEXT send retries immediately, for a supervisor
        that has verified the learner is reachable again while this
        sender still sits out a backoff window armed during the
        outage. A driver-side slot restart gets this for free (a fresh
        transport has no backoff state); kick() is the same remedy
        without discarding the connection's negotiated codec and
        accounting. The backoff POLICY is untouched — the next failure
        re-arms it at the same escalation point. Returns False when no
        backoff was pending (outcome "skipped" in the remediation
        plane's attribution)."""
        with self._send_lock:
            if self._sock is not None \
                    or time.monotonic() >= self._backoff_until:
                return False
            self._backoff_until = 0.0  # apexlint: unguarded(holds _send_lock)
            return True

    def send_telemetry(self, frame: dict) -> bool:
        """Best-effort ship of one obs snapshot frame (MSG_TELEMETRY,
        JSON). Returns False — never raises into the pump thread — when
        the server did not grant telemetry (old build), the connection
        is down or backing off, or the send fails; the caller simply
        tries again at its next cadence."""
        with self._send_lock:
            if self._sock is None \
                    and time.monotonic() < self._backoff_until:
                return False  # backoff window: don't probe the learner
            try:
                if self._sock is None:
                    self._sock = self._connect_experience()
                if not self._telemetry_ok:
                    return False
                payload = json.dumps(frame).encode()
                _send_msg(self._sock, MSG_TELEMETRY, payload)
                self._telemetry_frames_out += 1
                self._telemetry_bytes_out += len(payload)
                return True
            except OSError as e:
                self._note_send_failure(e)
                return False

    def recv_experience(self, timeout: float | None = None) -> dict | None:
        raise RuntimeError("actor-side transport cannot receive experience")

    def publish_params(self, params: Any, version: int) -> None:
        raise RuntimeError("actor-side transport cannot publish params")

    def get_params(self) -> tuple[Any, int]:
        """Pull params, CONDITIONALLY when the server is epoch-aware:
        the request states the (epoch, version) already in hand, and an
        up-to-date puller gets back a header-sized "unchanged" reply —
        (None, current_version) — instead of megabytes of weights. An
        old server ignores the request payload and replies the legacy
        raw pickle, which parses through the same path (epoch stays
        unknown, every pull ships the full blob). Any failure returns
        (None, -1) and bumps param_pull_errors; it never raises into
        the puller thread.

        With an shm grant on the current connection, the pull is a
        LOCAL seqlock read of the server's param area — no socket, no
        per-client blob; torn/oversize/unpublished reads fall back to
        the TCP pull below, which is always correct."""
        reader = self._shm_param_reader
        if reader is not None:
            got = self._shm_get_params(reader)
            if got is not None:
                return got
        # two attempts: a "resync" reply (the server's delta chain no
        # longer reaches our base) clears the held version and retries
        # immediately — the second request states no base and comes
        # back full, so one poll cadence never leaves the actor a
        # version behind over a routine window overrun
        for attempt in (0, 1):
            with self._param_lock:
                req_obj: dict[str, Any] = {"v": self._param_version,
                                           "epoch": self._param_epoch}
                if self._param_codec != "raw":
                    # the pull channel's codec ask; absent under
                    # param_codec="raw" so the request bytes match the
                    # pre-codec build exactly
                    req_obj["codec"] = self._param_codec
                req = json.dumps(req_obj).encode()
                try:
                    if self._param_sock is None:
                        self._param_sock = self._connect()
                    _send_msg(self._param_sock, MSG_PARAMS_REQ, req)
                    msg = _recv_msg(self._param_sock)
                    # a corrupt/misframed reply (ValueError from
                    # _recv_msg, or an unexpected type) is treated like
                    # a dead connection: reset the socket and report no
                    # params — the caller polls again. It must never
                    # escape into the param-puller thread.
                    if msg is not None and msg[0] != MSG_PARAMS:
                        raise ValueError(
                            f"unexpected reply type {msg[0]}")
                except (OSError, ValueError):
                    msg = None  # apexlint: lossy(counted as param_pull_errors just below)
                if msg is None:
                    self._param_pull_errors += 1
                    if self._param_sock is not None:
                        try:
                            self._param_sock.close()
                        except OSError:  # apexlint: lossy(close of an already-dead socket)
                            pass
                    self._param_sock = None
                    return None, -1
                self._bytes_in += len(msg[1])
            # the blob decode deliberately runs outside _param_lock (it
            # can be hundreds of ms for a big tree); re-take the lock
            # only for the state updates
            parsed = self._parse_params_payload(msg[1])
            if parsed is None:
                with self._param_lock:
                    self._param_pull_errors += 1
                return None, -1
            status, params, version, ep = parsed
            if ep is not None:
                self._note_epoch(ep)
            if status == "resync":
                self._note_param_resync()
                if attempt == 0:
                    continue
                return None, -1
            with self._param_lock:
                if ep is not None:
                    self._param_epoch = ep
                    self._param_version = version
                if status == "unchanged":
                    self._param_unchanged += 1
            if status == "unchanged":
                return None, version
            return params, version
        return None, -1  # unreachable: the loop returns on attempt 1

    def _shm_get_params(self, reader: Any) -> tuple[Any, int] | None:
        """One attempt at a seqlock param read: (params, version) /
        (None, version) for "unchanged", or None meaning 'use the TCP
        pull' (nothing published to the area yet, blob oversize, torn
        reads exhausted, or an undecodable blob)."""
        with self._param_lock:
            have = (self._param_epoch, self._param_version)
        try:
            res = reader.read(*have)
        except (OSError, ValueError):  # apexlint: lossy(counted as shm_param_fallbacks below)
            res = None
        if res is None or res[0] in ("empty", "oversize"):
            with self._param_lock:
                self._shm_param_fallbacks += 1
            return None
        status, blob, ep, version = res
        self._note_epoch(ep)
        if status == "unchanged":
            with self._param_lock:
                self._param_unchanged += 1
                self._shm_param_reads += 1
            return None, version
        try:
            params, _ = pickle.loads(blob)
        except Exception as e:
            self._warn_bad_blob(e)
            with self._param_lock:
                self._shm_param_fallbacks += 1
            return None
        with self._param_lock:
            self._param_epoch = ep
            self._param_version = version
            self._shm_param_reads += 1
        return _upcast_bf16(params), version

    @property
    def dropped(self) -> int:
        return self._dropped

    @property
    def drop_reasons(self) -> dict[str, int]:
        """Per-reason breakdown of dropped experience batches:
        refused / reset / timeout / backpressure (dropped inside a
        backoff window without touching the network) / other. Sums to
        `dropped` for drops on the experience path."""
        with self._send_lock:
            return dict(self._drop_reasons)

    @property
    def reconnects(self) -> int:
        """Successful experience-socket reconnects after an outage."""
        with self._send_lock:
            return self._reconnects

    @property
    def reconnect_latencies(self) -> list[float]:
        """Outage lengths (seconds, first failure -> reconnect) for the
        last _RECONNECT_SAMPLES recoveries — the chaos lane's
        recovery-latency instrument."""
        with self._send_lock:
            return list(self._reconnect_latencies)

    @property
    def epoch(self) -> int:
        """Server membership epoch last seen (-1 before any epoch-aware
        exchange, e.g. against a pre-epoch server)."""
        with self._meta_lock:
            return self._epoch

    @property
    def epoch_changes(self) -> int:
        """Times the server's epoch CHANGED under us (learner restart
        or failover observed by this transport)."""
        with self._meta_lock:
            return self._epoch_changes

    @property
    def param_epoch(self) -> int:
        """Epoch the currently-held params came from (-1 when unknown;
        pullers key force-updates on changes of this, since a new
        incarnation's version counter may restart below the old one)."""
        with self._param_lock:
            return self._param_epoch

    @property
    def param_pull_errors(self) -> int:
        """get_params failures (connect/IO/decode) survived without
        raising into the puller thread."""
        with self._param_lock:
            return self._param_pull_errors

    @property
    def param_unchanged(self) -> int:
        """Conditional pulls answered with a header-only "unchanged"
        reply (bytes the versioned param path saved shipping)."""
        with self._param_lock:
            return self._param_unchanged

    @property
    def param_resyncs(self) -> int:
        """Coded param payloads whose delta base this client no longer
        held (server chain window overrun, epoch bump) — each one
        dropped the chain and re-pulled a full."""
        with self._param_lock:
            return self._param_resyncs

    @property
    def param_codec_negotiated(self) -> bool:
        """True iff the current connection's hello/ack granted the
        param codec on the PUSH channel (pulls negotiate per request
        and need no latch; False against an old server or under
        param_codec="raw")."""
        return self._param_codec_ok

    @property
    def params_push_negotiated(self) -> bool:
        """True iff the current connection's hello/ack granted
        server-initiated param publication."""
        return self._push_ok

    @property
    def param_pushes_in(self) -> int:
        """MSG_PARAMS_PUSH frames received from the learner."""
        with self._push_lock:
            return self._param_pushes_in

    @property
    def bytes_out(self) -> int:
        """Experience payload bytes shipped to the learner host."""
        return self._bytes_out

    @property
    def raw_bytes_out(self) -> int:
        """Uncompressed array bytes of everything shipped — the
        numerator of wire_compression_ratio."""
        return self._raw_bytes_out

    @property
    def wire_compression_ratio(self) -> float:
        """raw/wire ratio over all experience shipped (1.0 = no
        savings; larger is better). 0.0 before any traffic."""
        return (self._raw_bytes_out / self._bytes_out
                if self._bytes_out else 0.0)

    @property
    def negotiated_codec(self) -> str:
        """Codec agreed with the current learner connection ("raw"
        until a hello/ack has succeeded)."""
        return self._negotiated

    @property
    def shm_negotiated(self) -> bool:
        """True while the current connection holds an shm experience
        ring grant (False cross-host, against an old server, or after
        any connection failure until the reconnect renegotiates)."""
        return self._shm_ring is not None

    @property
    def shm_posts(self) -> int:
        """Experience batches shipped through shm ring slots."""
        with self._send_lock:
            return self._shm_posts

    @property
    def shm_fallbacks(self) -> int:
        """Batches that degraded to TCP despite a live shm grant
        (ring full or batch outsized a slot)."""
        with self._send_lock:
            return self._shm_fallbacks

    @property
    def shm_bytes_out(self) -> int:
        """Experience payload bytes that crossed via shm slots."""
        with self._send_lock:
            return self._shm_bytes_out

    @property
    def shm_param_reads(self) -> int:
        """Param pulls satisfied by the seqlock area (incl. header-
        only "unchanged" reads) — pulls that cost zero socket bytes."""
        with self._param_lock:
            return self._shm_param_reads

    @property
    def shm_param_fallbacks(self) -> int:
        """Param pulls that fell back to TCP with a reader attached
        (area unpublished/oversize, torn reads exhausted, bad blob)."""
        with self._param_lock:
            return self._shm_param_fallbacks

    @property
    def serve_negotiated(self) -> bool:
        """True when the server acknowledged this host's serving-tier
        tenant tag on the current connection (False against an old
        server or before the first send connects)."""
        with self._send_lock:
            return self._serve_ok

    @property
    def telemetry_negotiated(self) -> bool:
        """True iff the current connection's hello/ack granted the
        telemetry capability (always False against an old server)."""
        return self._telemetry_ok

    @property
    def telemetry_frames_out(self) -> int:
        """MSG_TELEMETRY frames shipped to the learner host."""
        return self._telemetry_frames_out

    @property
    def telemetry_bytes_out(self) -> int:
        """Telemetry payload bytes shipped (control-plane budget)."""
        return self._telemetry_bytes_out

    @property
    def encode_ms(self) -> float:
        """Cumulative wall-ms spent encoding experience payloads."""
        return self._encode_ms

    @property
    def bytes_in(self) -> int:
        """Param blob bytes pulled from the learner host."""
        return self._bytes_in

    @property
    def pending(self) -> int:
        return 0

    def close(self) -> None:
        with self._send_lock, self._param_lock:
            self._detach_shm()
            for s in (self._sock, self._param_sock):
                if s is not None:
                    try:
                        s.close()
                    except OSError:  # apexlint: lossy(close best effort)
                        pass
            self._sock = self._param_sock = None
