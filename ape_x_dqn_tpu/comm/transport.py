"""Transport layer: actor -> replay ingest and learner -> actor params.

The reference moves experience and parameters over gRPC and does learner
collectives over NCCL (SURVEY.md §2.2 "Comm"). The TPU-native mapping
(SURVEY.md §5 "distributed communication backend"):

- learner-internal collectives: XLA psum/all-gather over ICI (see
  parallel/dist_learner.py) — nothing to do here.
- learner -> inference-server weight publication: device-to-device
  resharding over ICI (DistDQNLearner.publish_params).
- actor <-> inference server and actor -> replay ingest: host-side
  message passing. In-process that's thread-safe queues (the
  `LoopbackTransport` below, also the deterministic test harness per
  SURVEY.md §4); across hosts the same interface runs over TCP sockets
  (`comm.socket_transport`) riding DCN.

Messages are pytrees of numpy arrays; an ingest message is a dict with
stacked transition fields plus "priorities".

A third, low-rate path rides the same interface: fleet telemetry.
`send_telemetry(frame)` ships a compact per-peer obs snapshot (JSON
dict); the receiving side exposes an `on_telemetry(peer_id, frame)`
hook the driver's fleet aggregator installs. On loopback the frame is
handed to the hook directly; over sockets it becomes MSG_TELEMETRY and
is subject to hello/ack capability negotiation (old peers drop it
cleanly — see comm.socket_transport).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Protocol


class Transport(Protocol):
    def send_experience(self, batch: dict) -> None: ...
    def recv_experience(self, timeout: float | None = None) -> dict | None: ...
    def publish_params(self, params: Any, version: int) -> None: ...
    def get_params(self) -> tuple[Any, int]: ...
    def send_telemetry(self, frame: dict) -> bool: ...


class LoopbackTransport:
    """In-process transport: bounded queue + versioned param cell."""

    def __init__(self, max_pending: int = 64):
        self._q: queue.Queue[dict] = queue.Queue(maxsize=max_pending)
        self._params: Any = None
        self._version = -1
        self._lock = threading.Lock()
        self._dropped = 0
        self._telemetry_frames = 0
        # fleet hook (set by the driver); called inline from the sender
        self.on_telemetry: Any = None  # (peer_id: str, frame: dict) -> None

    # experience path (actor -> replay ingest)

    def send_experience(self, batch: dict) -> None:
        """Non-blocking; drops oldest under backpressure (actors must
        never stall the env loop — matches Ape-X semantics where replay
        ingest is lossy-tolerant)."""
        while True:
            try:
                self._q.put_nowait(batch)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self._dropped += 1
                except queue.Empty:
                    pass

    def recv_experience(self, timeout: float | None = None) -> dict | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    @property
    def dropped(self) -> int:
        return self._dropped

    @property
    def pending(self) -> int:
        return self._q.qsize()

    def close(self) -> None:
        """Drain parked batches so their (potentially large) arrays
        are not pinned by a queue nobody will read again — loopback
        holds no OS handles, but drivers call close() on every
        transport symmetrically."""
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    # parameter path (learner -> actors/server)

    def publish_params(self, params: Any, version: int) -> None:
        with self._lock:
            self._params = params
            self._version = version

    def get_params(self) -> tuple[Any, int]:
        with self._lock:
            return self._params, self._version

    # telemetry path (peer obs snapshots -> fleet aggregator)

    def send_telemetry(self, frame: dict) -> bool:
        """In-process delivery straight to the aggregator hook; True
        iff a hook was installed (mirrors the socket transport's
        negotiated/not-negotiated return)."""
        cb = self.on_telemetry
        if cb is None:
            return False
        with self._lock:
            self._telemetry_frames += 1
        cb(str(frame.get("peer", "peer?")), frame)
        return True

    @property
    def telemetry_frames(self) -> int:
        with self._lock:
            return self._telemetry_frames
