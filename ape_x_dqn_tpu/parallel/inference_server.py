"""Batched TPU inference server for actor policy evaluation.

The reference evaluates policies per-actor on GPUs (SURVEY.md §2.3 item
4); here many actors RPC observations to one server thread that pads
them into fixed-size buckets and runs a single jitted forward on the
TPU, then scatters results back (BASELINE.json north_star: "actor policy
evaluation is batched onto a TPU inference server").

Dynamic batching: the server collects requests until `max_batch` are
waiting or the oldest has waited `deadline_ms` (latency/throughput
trade-off, SURVEY.md §7 hard part 3). Batches are padded to the next
power of two so XLA compiles a handful of bucket shapes once.

Generic over the request pytree: a request is (inputs_pytree,) and the
reply is outputs_pytree — plain Q-nets send obs and get Q-values;
recurrent nets send (obs, (c, h)) and get (q, (c', h')).

Mesh-sharded mode: pass `mesh` to shard each batch's leading axis across
every device of a `jax.sharding.Mesh` with the params replicated, so
forwards/s scales with chip count (SURVEY.md §5 "weight broadcast →
all-gather over ICI to inference-server shards"). Buckets round up to a
multiple of the mesh size so every shard gets identical work; the dist
learner's `publish_params` already hands over mesh-replicated buffers,
so a publication is exactly the ICI all-gather the survey names.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.obs.core import NULL_OBS
from ape_x_dqn_tpu.obs.health import make_lock
from ape_x_dqn_tpu.utils.misc import next_pow2


class _Request:
    __slots__ = ("inputs", "n", "event", "result", "t_enq")

    def __init__(self, inputs: Any, n: int = 0):
        """n == 0: single item, no batch dim on any leaf.
        n >= 1: a multi-item request whose leaves carry a leading [n]
        batch dim (vector actors ship one request per vector step)."""
        self.inputs = inputs
        self.n = n
        self.event = threading.Event()
        self.result: Any = None
        self.t_enq = time.perf_counter()  # serving-SLO latency anchor

    @property
    def items(self) -> int:
        return self.n if self.n else 1


class BatchedInferenceServer:
    def __init__(self, apply_fn: Callable, params: Any,
                 max_batch: int = 64, deadline_ms: float = 2.0,
                 mesh: Mesh | None = None, obs: Any = None):
        """apply_fn(params, batched_inputs_pytree) -> batched outputs.

        mesh: optional — shard every batch's leading axis over all mesh
        devices (params replicated); see module docstring.
        obs: optional obs.core.Obs facade — per-batch span + batch-fill
        / param-lag / queue-depth instruments and the server heartbeat
        (NULL_OBS when omitted, so the hot loop stays branch-free).
        """
        if mesh is not None:
            # One sharding as a pytree prefix: dim 0 of every input and
            # output leaf is split over the flattened (dp, tp) device
            # grid; params replicate. Numpy inputs commit to these
            # shardings at dispatch, replies gather back host-side.
            batched = NamedSharding(mesh, P(tuple(mesh.axis_names)))
            self._apply = jax.jit(
                apply_fn,
                in_shardings=(NamedSharding(mesh, P()), batched),
                out_shardings=batched)
            # explicit placement before dispatch: under a multi-process
            # runtime, jit rejects numpy args with non-trivial shardings
            # (it cannot tell process-local from global data); device_put
            # onto the (all-addressable) local mesh is unambiguous
            self._batched_sharding = batched
            self._min_bucket = int(mesh.size)
        else:
            self._apply = jax.jit(apply_fn)
            self._batched_sharding = None
            self._min_bucket = 1
        self._params = params  # guarded-by: _lock
        self._params_version = 0  # guarded-by: _lock
        self._max_batch = max_batch
        self._deadline_s = deadline_ms / 1000.0
        self._q: queue.Queue[_Request] = queue.Queue()
        # a popped-but-not-admitted request (would overflow max_batch)
        # held for the next batch — only the serve thread touches it
        self._held: _Request | None = None
        self._stop = threading.Event()
        # _lock guards the published params (swapped by the driver's
        # ingest thread, read by the serve thread) and the served-stat
        # counters (bumped by the serve thread, read by stats callers)
        self._lock = make_lock("inference_server._lock")
        self._batches_served = 0  # guarded-by: _lock
        self._items_served = 0  # guarded-by: _lock
        self._obs = obs if obs is not None else NULL_OBS
        self._obs.register("inference-server")
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="inference-server", daemon=True)
        self._thread.start()

    # -- client side -------------------------------------------------------

    def query(self, inputs: Any, timeout: float = 60.0) -> Any:
        """Blocking single-item query. inputs: pytree WITHOUT batch dim.

        Default timeout 60s (round 5, was 30): on tunneled hosts the
        device link occasionally stalls for tens of seconds; a 30s
        timeout turned one such stall into a fleet-wide cascade
        (actors exhausted restarts, the eval rotation died) in the
        round-5 live rotation run. Genuine server death still surfaces
        — just one stall-length later."""
        req = _Request(inputs)
        self._q.put(req)
        if not req.event.wait(timeout):
            raise TimeoutError("inference server did not reply")
        if isinstance(req.result, Exception):
            raise req.result
        return req.result

    def query_batch(self, inputs: Any, n: int, timeout: float = 60.0) -> Any:
        """Blocking multi-item query: every leaf of `inputs` carries a
        leading [n] batch dim; the reply's leaves do too. One request
        per vector-actor step — K env observations ride one queue entry
        and one scatter instead of K (SURVEY.md §2.4 "inference batching
        parallelism")."""
        assert n >= 1
        req = _Request(inputs, n)
        self._q.put(req)
        if not req.event.wait(timeout):
            raise TimeoutError("inference server did not reply")
        if isinstance(req.result, Exception):
            raise req.result
        return req.result

    def warmup(self, example_input: Any,
               extra_sizes: tuple[int, ...] = ()) -> None:
        """AOT-compile the batched forward at bucket sizes 1 and
        max_batch before actors start querying. On TPU the first compile
        takes 10-40s — longer than a reasonable query timeout — so an
        unwarmed server's first trickle of batch-1 queries times actors
        out (observed live: actor restart on 'inference server did not
        reply' during startup). Intermediate pow2 buckets still compile
        on first use, inside the 60s default query timeout.

        example_input: one request pytree WITHOUT the batch dim (content
        irrelevant; only shapes/dtypes feed the compile cache).
        extra_sizes: additional request sizes to pre-bucket (drivers pass
        envs_per_actor; a vector request larger than max_batch serves
        alone in its own bucket, which must therefore be warm too)."""
        with self._lock:
            params = self._params
        # every bucket a pow2 REQUEST size up to max_batch can land in:
        # coalesced batches hit any of them (e.g. 2-3 K-item vector
        # requests -> bucket 2K/4K, truncation flushes -> small
        # buckets), and a cold intermediate bucket under load stalls
        # every queued actor behind one compile. Mapping _bucket over
        # request sizes (not doubling _bucket(1)) matters when the mesh
        # size is not a power of two: buckets are pow2 rounded up to a
        # mesh-size multiple, which doubling would skip.
        sizes = set()
        n = 1
        while n < self._max_batch:
            sizes.add(self._bucket(n))
            n *= 2
        sizes.add(self._bucket(self._max_batch))
        sizes.update(self._bucket(s) for s in extra_sizes if s >= 1)
        for b in sorted(sizes):
            stacked = jax.tree.map(
                lambda x: np.zeros((b, *np.asarray(x).shape),
                                   np.asarray(x).dtype), example_input)
            if self._batched_sharding is not None:
                stacked = jax.device_put(stacked, self._batched_sharding)
            self._apply.lower(params, stacked).compile()

    # -- learner side ------------------------------------------------------

    def update_params(self, params: Any, version: int) -> None:
        with self._lock:
            self._params = params
            self._params_version = version

    @property
    def params_version(self) -> int:
        with self._lock:
            return self._params_version

    @property
    def queue_depth(self) -> int:
        """Requests waiting right now — drivers log this around eval
        episodes to surface eval-induced actor back-pressure (the eval
        worker shares this server with the actors)."""
        return self._q.qsize()

    @property
    def stats(self) -> dict:
        return {"batches": self._batches_served,
                "items": self._items_served,
                "avg_batch": (self._items_served
                              / max(self._batches_served, 1))}

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # -- server loop -------------------------------------------------------

    def _collect(self) -> list[_Request]:
        if self._held is not None:
            first, self._held = self._held, None
        else:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                return []
        reqs = [first]
        items = first.items
        deadline = time.monotonic() + self._deadline_s
        # max_batch counts ITEMS, not requests: a vector actor's K-item
        # request fills K slots of the batch budget. A request that
        # would overflow the budget is HELD for the next batch (never
        # split) — otherwise a coalesced batch could exceed max_batch
        # and land in a bucket warmup never compiled (a 10-40s TPU
        # stall that times out every waiting actor). A single oversized
        # request still serves alone: its own bucket was warmed via
        # warmup's extra_sizes.
        while items < self._max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                r = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if items + r.items > self._max_batch:
                self._held = r
                break
            reqs.append(r)
            items += r.items
        return reqs

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            reqs = self._collect()
            if not reqs:
                # an idle-but-polling server is alive, not stalled: beat
                # so a wedged ACTOR gets the stall attribution instead of
                # the server it simply stopped querying
                self._obs.beat("inference-server", "idle")
                continue
            try:
                self._serve_batch(reqs)
            except Exception as e:  # propagate to callers, keep serving
                for r in reqs:
                    r.result = e
                    r.event.set()

    def _bucket(self, n: int) -> int:
        """Padded batch size: next pow2, rounded up to a multiple of the
        mesh size in sharded mode so every shard gets identical work."""
        b = next_pow2(max(n, 1))
        if b % self._min_bucket:
            b = -(-b // self._min_bucket) * self._min_bucket
        return b

    def _serve_batch(self, reqs: list[_Request]) -> None:
        n = sum(r.items for r in reqs)
        padded = self._bucket(n)
        with self._obs.span("server.batch", items=n, padded=padded):
            # every request's leaves get a leading batch dim (single-
            # item requests gain one), then requests concatenate
            leads = [r.inputs if r.n else
                     jax.tree.map(lambda x: np.asarray(x)[None], r.inputs)
                     for r in reqs]
            stacked = jax.tree.map(lambda *xs: _pad_concat(xs, padded),
                                   *leads)
            if self._batched_sharding is not None:
                stacked = jax.device_put(stacked, self._batched_sharding)
            with self._lock:
                params = self._params
                version = self._params_version
            out = self._apply(params, stacked)
            out_np = jax.tree.map(np.asarray, out)
        off = 0
        t_done = time.perf_counter()
        for r in reqs:
            if r.n:
                lo, hi = off, off + r.n
                r.result = jax.tree.map(lambda x: x[lo:hi], out_np)
            else:
                idx = off
                r.result = jax.tree.map(lambda x: x[idx], out_np)
            off += r.items
            # end-to-end request latency (enqueue -> result ready):
            # the serving SLO — covers queue wait, batching deadline,
            # the forward, and the scatter, which is what an actor
            # actually blocks on
            self._obs.observe("infer_latency_ms",
                              (t_done - r.t_enq) * 1e3)
            r.event.set()
        # stats() reads these from other threads; the serve thread is
        # the only writer but += is still a read-modify-write
        with self._lock:
            self._batches_served += 1
            self._items_served += n
        self._obs.on_server_batch(n, version, self._q.qsize())


def _pad_concat(xs: tuple, padded: int) -> np.ndarray:
    arr = (np.asarray(xs[0]) if len(xs) == 1
           else np.concatenate([np.asarray(x) for x in xs]))
    if arr.shape[0] < padded:
        pad_width = [(0, padded - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
        arr = np.pad(arr, pad_width)
    return arr
