"""Batched TPU inference server for actor policy evaluation.

The reference evaluates policies per-actor on GPUs (SURVEY.md §2.3 item
4); here many actors RPC observations to one server thread that pads
them into fixed-size buckets and runs a single jitted forward on the
TPU, then scatters results back (BASELINE.json north_star: "actor policy
evaluation is batched onto a TPU inference server").

Dynamic batching: the server collects requests until `max_batch` are
waiting or the oldest has waited `deadline_ms` (latency/throughput
trade-off, SURVEY.md §7 hard part 3). Batches are padded to the next
power of two so XLA compiles a handful of bucket shapes once.

Generic over the request pytree: a request is (inputs_pytree,) and the
reply is outputs_pytree — plain Q-nets send obs and get Q-values;
recurrent nets send (obs, (c, h)) and get (q, (c', h')).

Mesh-sharded mode: pass `mesh` to shard each batch's leading axis across
every device of a `jax.sharding.Mesh` with the params replicated, so
forwards/s scales with chip count (SURVEY.md §5 "weight broadcast →
all-gather over ICI to inference-server shards"). Buckets round up to a
multiple of the mesh size so every shard gets identical work; the dist
learner's `publish_params` already hands over mesh-replicated buffers,
so a publication is exactly the ICI all-gather the survey names.

Multi-tenant serving tier (ISSUE 13): `MultiPolicyInferenceServer`
serves MANY policies from one chip behind a single continuous-batching
admission queue. Requests are tagged (policy_id, priority class);
an admission thread moves them into per-family priority deques while
the dispatch thread is forwarding — admission never waits on a
collect-then-serve round. Same-family tenants coalesce into one
stacked/gather-indexed forward (`vmap` over per-example params rows),
so 57 heads cost one dispatch, not 57. The admission controller sheds
load from the lowest priority class first when queue depth crosses the
SLO line (class 0 is never shed), expires requests past their deadline
with errors attributed to the policy_id, and raises/clears a
backpressure signal the transport layer can act on. Drivers talk to
the tier through `register_policy`'s TenantClient, which keeps the
exact BatchedInferenceServer client surface.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.obs.core import NULL_OBS
from ape_x_dqn_tpu.obs.health import make_lock
from ape_x_dqn_tpu.utils.misc import next_pow2


class _Request:
    __slots__ = ("inputs", "n", "event", "result", "t_enq")

    def __init__(self, inputs: Any, n: int = 0):
        """n == 0: single item, no batch dim on any leaf.
        n >= 1: a multi-item request whose leaves carry a leading [n]
        batch dim (vector actors ship one request per vector step)."""
        self.inputs = inputs
        self.n = n
        self.event = threading.Event()
        self.result: Any = None
        self.t_enq = time.perf_counter()  # serving-SLO latency anchor

    @property
    def items(self) -> int:
        return self.n if self.n else 1


class BatchedInferenceServer:
    def __init__(self, apply_fn: Callable, params: Any,
                 max_batch: int = 64, deadline_ms: float = 2.0,
                 mesh: Mesh | None = None, obs: Any = None):
        """apply_fn(params, batched_inputs_pytree) -> batched outputs.

        mesh: optional — shard every batch's leading axis over all mesh
        devices (params replicated); see module docstring.
        obs: optional obs.core.Obs facade — per-batch span + batch-fill
        / param-lag / queue-depth instruments and the server heartbeat
        (NULL_OBS when omitted, so the hot loop stays branch-free).
        """
        if mesh is not None:
            # One sharding as a pytree prefix: dim 0 of every input and
            # output leaf is split over the flattened (dp, tp) device
            # grid; params replicate. Numpy inputs commit to these
            # shardings at dispatch, replies gather back host-side.
            batched = NamedSharding(mesh, P(tuple(mesh.axis_names)))
            self._apply = jax.jit(
                apply_fn,
                in_shardings=(NamedSharding(mesh, P()), batched),
                out_shardings=batched)
            # explicit placement before dispatch: under a multi-process
            # runtime, jit rejects numpy args with non-trivial shardings
            # (it cannot tell process-local from global data); device_put
            # onto the (all-addressable) local mesh is unambiguous
            self._batched_sharding = batched
            self._min_bucket = int(mesh.size)
        else:
            self._apply = jax.jit(apply_fn)
            self._batched_sharding = None
            self._min_bucket = 1
        self._params = params  # guarded-by: _lock
        self._params_version = 0  # guarded-by: _lock
        self._max_batch = max_batch
        self._deadline_s = deadline_ms / 1000.0
        self._q: queue.Queue[_Request] = queue.Queue()
        # popped-but-not-admitted requests (would overflow max_batch)
        # held in arrival order for later batches — only the serve
        # thread touches it
        self._held: deque[_Request] = deque()
        # bucket sizes already AOT-compiled: warmup() is re-entrant
        # across update_params epochs without re-paying compiles —
        # only the caller's thread touches it (warmup is pre-traffic)
        self._warm_buckets: set[int] = set()
        self._stop = threading.Event()
        # _lock guards the published params (swapped by the driver's
        # ingest thread, read by the serve thread) and the served-stat
        # counters (bumped by the serve thread, read by stats callers)
        self._lock = make_lock("inference_server._lock")
        self._batches_served = 0  # guarded-by: _lock
        self._items_served = 0  # guarded-by: _lock
        self._obs = obs if obs is not None else NULL_OBS
        self._obs.register("inference-server")
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="inference-server", daemon=True)
        self._thread.start()

    # -- client side -------------------------------------------------------

    def query(self, inputs: Any, timeout: float = 60.0) -> Any:
        """Blocking single-item query. inputs: pytree WITHOUT batch dim.

        Default timeout 60s (round 5, was 30): on tunneled hosts the
        device link occasionally stalls for tens of seconds; a 30s
        timeout turned one such stall into a fleet-wide cascade
        (actors exhausted restarts, the eval rotation died) in the
        round-5 live rotation run. Genuine server death still surfaces
        — just one stall-length later."""
        req = _Request(inputs)
        self._q.put(req)
        if not req.event.wait(timeout):
            raise TimeoutError("inference server did not reply")
        if isinstance(req.result, Exception):
            raise req.result
        return req.result

    def query_batch(self, inputs: Any, n: int, timeout: float = 60.0) -> Any:
        """Blocking multi-item query: every leaf of `inputs` carries a
        leading [n] batch dim; the reply's leaves do too. One request
        per vector-actor step — K env observations ride one queue entry
        and one scatter instead of K (SURVEY.md §2.4 "inference batching
        parallelism")."""
        assert n >= 1
        req = _Request(inputs, n)
        self._q.put(req)
        if not req.event.wait(timeout):
            raise TimeoutError("inference server did not reply")
        if isinstance(req.result, Exception):
            raise req.result
        return req.result

    def warmup(self, example_input: Any,
               extra_sizes: tuple[int, ...] = ()) -> None:
        """AOT-compile the batched forward at bucket sizes 1 and
        max_batch before actors start querying. On TPU the first compile
        takes 10-40s — longer than a reasonable query timeout — so an
        unwarmed server's first trickle of batch-1 queries times actors
        out (observed live: actor restart on 'inference server did not
        reply' during startup). Intermediate pow2 buckets still compile
        on first use, inside the 60s default query timeout.

        example_input: one request pytree WITHOUT the batch dim (content
        irrelevant; only shapes/dtypes feed the compile cache).
        extra_sizes: additional request sizes to pre-bucket (drivers pass
        envs_per_actor; a vector request larger than max_batch serves
        alone in its own bucket, which must therefore be warm too)."""
        with self._lock:
            params = self._params
        # every bucket a pow2 REQUEST size up to max_batch can land in:
        # coalesced batches hit any of them (e.g. 2-3 K-item vector
        # requests -> bucket 2K/4K, truncation flushes -> small
        # buckets), and a cold intermediate bucket under load stalls
        # every queued actor behind one compile. Mapping _bucket over
        # request sizes (not doubling _bucket(1)) matters when the mesh
        # size is not a power of two: buckets are pow2 rounded up to a
        # mesh-size multiple, which doubling would skip.
        sizes = _pow2_bucket_sizes(self._bucket, self._max_batch,
                                   extra_sizes)
        # dedupe against already-warm buckets: an update_params epoch
        # bump changes VALUES, not shapes/dtypes, so re-warming after a
        # publication would re-pay every AOT compile for nothing
        # (asserted via the jit_compiles compile-telemetry delta)
        for b in sorted(sizes - self._warm_buckets):
            stacked = jax.tree.map(
                lambda x: np.zeros((b, *np.asarray(x).shape),
                                   np.asarray(x).dtype), example_input)
            if self._batched_sharding is not None:
                stacked = jax.device_put(stacked, self._batched_sharding)
            self._apply.lower(params, stacked).compile()
            self._warm_buckets.add(b)

    # -- learner side ------------------------------------------------------

    def update_params(self, params: Any, version: int) -> None:
        with self._lock:
            self._params = params
            self._params_version = version

    @property
    def params_version(self) -> int:
        with self._lock:
            return self._params_version

    @property
    def queue_depth(self) -> int:
        """Requests waiting right now — drivers log this around eval
        episodes to surface eval-induced actor back-pressure (the eval
        worker shares this server with the actors)."""
        return self._q.qsize()

    @property
    def stats(self) -> dict:
        return {"batches": self._batches_served,
                "items": self._items_served,
                "avg_batch": (self._items_served
                              / max(self._batches_served, 1))}

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    # -- server loop -------------------------------------------------------

    def _collect(self) -> list[_Request]:
        # max_batch counts ITEMS, not requests: a vector actor's K-item
        # request fills K slots of the batch budget. A request that
        # would overflow the budget is HELD for a later batch (never
        # split) — otherwise a coalesced batch could exceed max_batch
        # and land in a bucket warmup never compiled (a 10-40s TPU
        # stall that times out every waiting actor). A single oversized
        # request still serves alone: its own bucket was warmed via
        # warmup's extra_sizes. Holding is NOT a barrier: a held-back
        # oversize request must not starve smaller requests that still
        # fit the current bucket, so non-fitting requests are parked
        # (arrival order preserved) while collection keeps admitting.
        reqs: list[_Request] = []
        items = 0
        kept: deque[_Request] = deque()
        while self._held:
            r = self._held.popleft()
            if (items + r.items <= self._max_batch
                    or (not reqs and r.items >= self._max_batch)):
                reqs.append(r)
                items += r.items
            else:
                kept.append(r)
        self._held = kept
        if not reqs:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                return []
            reqs.append(first)
            items = first.items
        deadline = time.monotonic() + self._deadline_s
        while items < self._max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                r = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if items + r.items > self._max_batch:
                self._held.append(r)
                continue
            reqs.append(r)
            items += r.items
        return reqs

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            reqs = self._collect()
            if not reqs:
                # an idle-but-polling server is alive, not stalled: beat
                # so a wedged ACTOR gets the stall attribution instead of
                # the server it simply stopped querying
                self._obs.beat("inference-server", "idle")
                continue
            try:
                self._serve_batch(reqs)
            except Exception as e:  # propagate to callers, keep serving
                # forensics: the error surfaces in the CALLERS' threads;
                # the ring keeps the server-side attribution
                self._obs.blackbox.record(
                    "serve_error", component="inference-server",
                    error=repr(e)[:200])
                for r in reqs:
                    r.result = e
                    r.event.set()

    def _bucket(self, n: int) -> int:
        """Padded batch size: next pow2, rounded up to a multiple of the
        mesh size in sharded mode so every shard gets identical work."""
        b = next_pow2(max(n, 1))
        if b % self._min_bucket:
            b = -(-b // self._min_bucket) * self._min_bucket
        return b

    def _serve_batch(self, reqs: list[_Request]) -> None:
        n = sum(r.items for r in reqs)
        padded = self._bucket(n)
        with self._obs.span("server.batch", items=n, padded=padded):
            # every request's leaves get a leading batch dim (single-
            # item requests gain one), then requests concatenate
            leads = [r.inputs if r.n else
                     jax.tree.map(lambda x: np.asarray(x)[None], r.inputs)
                     for r in reqs]
            stacked = jax.tree.map(lambda *xs: _pad_concat(xs, padded),
                                   *leads)
            if self._batched_sharding is not None:
                stacked = jax.device_put(stacked, self._batched_sharding)
            with self._lock:
                params = self._params
                version = self._params_version
            out = self._apply(params, stacked)
            out_np = jax.tree.map(np.asarray, out)
        off = 0
        t_done = time.perf_counter()
        for r in reqs:
            if r.n:
                lo, hi = off, off + r.n
                r.result = jax.tree.map(lambda x: x[lo:hi], out_np)
            else:
                idx = off
                r.result = jax.tree.map(lambda x: x[idx], out_np)
            off += r.items
            # end-to-end request latency (enqueue -> result ready):
            # the serving SLO — covers queue wait, batching deadline,
            # the forward, and the scatter, which is what an actor
            # actually blocks on
            self._obs.observe("infer_latency_ms",
                              (t_done - r.t_enq) * 1e3)
            r.event.set()
        # stats() reads these from other threads; the serve thread is
        # the only writer but += is still a read-modify-write
        with self._lock:
            self._batches_served += 1
            self._items_served += n
        self._obs.on_server_batch(n, version, self._q.qsize())


def _pad_concat(xs: tuple, padded: int) -> np.ndarray:
    arr = (np.asarray(xs[0]) if len(xs) == 1
           else np.concatenate([np.asarray(x) for x in xs]))
    if arr.shape[0] < padded:
        pad_width = [(0, padded - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
        arr = np.pad(arr, pad_width)
    return arr


def _pow2_bucket_sizes(bucket_fn: Callable[[int], int], max_batch: int,
                       extra_sizes: tuple[int, ...]) -> set[int]:
    """Every bucket a pow2 REQUEST size up to max_batch can land in:
    coalesced batches hit any of them (e.g. 2-3 K-item vector requests
    -> bucket 2K/4K, truncation flushes -> small buckets), and a cold
    intermediate bucket under load stalls every queued actor behind one
    compile. Mapping the bucket fn over request sizes (not doubling
    bucket(1)) matters when the mesh size is not a power of two:
    buckets are pow2 rounded up to a mesh-size multiple, which doubling
    would skip."""
    sizes = set()
    n = 1
    while n < max_batch:
        sizes.add(bucket_fn(n))
        n *= 2
    sizes.add(bucket_fn(max_batch))
    sizes.update(bucket_fn(s) for s in extra_sizes if s >= 1)
    return sizes


# -- multi-tenant serving tier (ISSUE 13) ----------------------------------


class ServeShed(RuntimeError):
    """Request shed by the admission controller: queue depth crossed
    the SLO line and this request sat in a sheddable (non-top) priority
    class. Attributed so the caller knows WHICH tenant lost work."""

    def __init__(self, policy_id: str, priority: int):
        super().__init__(
            f"request for policy {policy_id!r} (class {priority}) shed: "
            f"admission queue over the SLO line")
        self.policy_id = policy_id
        self.priority = priority


class ServeDeadlineExceeded(TimeoutError):
    """Request expired in the admission queue before dispatch. The
    timeout is ATTRIBUTED — it names the policy_id and class — so an
    overloaded tenant shows up in actor logs as itself, not as a
    generic server stall."""

    def __init__(self, policy_id: str, priority: int, waited_ms: float):
        super().__init__(
            f"request for policy {policy_id!r} (class {priority}) "
            f"expired after {waited_ms:.0f}ms in the admission queue")
        self.policy_id = policy_id
        self.priority = priority


class _ServeRequest:
    __slots__ = ("policy", "prio", "inputs", "n", "event", "result",
                 "t_enq")

    def __init__(self, policy: str, prio: int, inputs: Any, n: int = 0):
        self.policy = policy
        self.prio = prio
        self.inputs = inputs
        self.n = n
        self.event = threading.Event()
        self.result: Any = None
        self.t_enq = time.perf_counter()

    @property
    def items(self) -> int:
        return self.n if self.n else 1

    def wait(self, timeout: float = 60.0) -> Any:
        """Block until served; raises the attributed shed/deadline
        error if the admission controller rejected the request."""
        if not self.event.wait(timeout):
            raise TimeoutError("inference server did not reply")
        if isinstance(self.result, Exception):
            raise self.result
        return self.result


class _Policy:
    """One registered tenant: epoch-versioned params plus its row in
    the family's stacked param tree and per-tenant accounting."""

    __slots__ = ("policy_id", "family", "params", "version", "row",
                 "offered", "admitted", "shed", "pending_items",
                 "lat_ms")

    def __init__(self, policy_id: str, family: str, params: Any,
                 version: int, row: int):
        self.policy_id = policy_id
        self.family = family
        self.params = params
        self.version = version
        self.row = row
        self.offered = 0
        self.admitted = 0
        self.shed = 0
        self.pending_items = 0
        # recent end-to-end latencies (ms) for the per-tenant p50/p99
        # gauges; a bounded reservoir, appended only by the dispatch
        # thread, snapshotted by the stats publisher
        self.lat_ms: deque[float] = deque(maxlen=512)


class _Family:
    """One apply-fn family: the tenants it serves, their stacked param
    cache for the coalesced forward, per-class pending deques, and the
    warm-bucket memo for both forward paths."""

    __slots__ = ("name", "apply_plain", "apply_gather", "policies",
                 "stacked", "dirty", "pending", "pending_items",
                 "warm_plain", "warm_gather")

    def __init__(self, name: str, apply_plain: Callable,
                 apply_gather: Callable, classes: int):
        self.name = name
        self.apply_plain = apply_plain
        self.apply_gather = apply_gather
        self.policies: list[_Policy] = []
        self.stacked: Any = None
        self.dirty = True
        self.pending: list[deque[_ServeRequest]] = [
            deque() for _ in range(classes)]
        self.pending_items = 0
        self.warm_plain: set[int] = set()
        self.warm_gather: set[int] = set()


def _make_gather_apply(apply_fn: Callable) -> Callable:
    """Coalesced multi-tenant forward: params leaves carry a leading
    [n_policies] axis, `rows` maps each batch item to its tenant's
    row, and vmap over (gathered per-example params, batch) runs every
    head in ONE dispatch — 57 tenants never mean 57 forwards. The
    gather materializes per-example param rows, so it pays ~batch x
    head-params HBM; the intended regime is many small per-tenant
    heads over a shared torso."""

    def one(p: Any, x: Any) -> Any:
        out = apply_fn(p, jax.tree.map(lambda leaf: leaf[None], x))
        return jax.tree.map(lambda leaf: leaf[0], out)

    def run(stacked_params: Any, rows: Any, batch: Any) -> Any:
        per = jax.tree.map(lambda p: p[rows], stacked_params)
        return jax.vmap(one)(per, batch)

    return run


class TenantClient:
    """Per-tenant view of a MultiPolicyInferenceServer with the exact
    BatchedInferenceServer client/learner surface (query, query_batch,
    warmup, update_params, params_version, queue_depth, stats, stop),
    so drivers, actor hosts and the eval worker are tenant-tagged
    without signature changes. Every query it submits carries this
    view's (policy_id, priority class)."""

    def __init__(self, tier: "MultiPolicyInferenceServer",
                 policy_id: str, priority: int):
        self._tier = tier
        self.policy_id = policy_id
        self.priority = priority

    def submit(self, inputs: Any, n: int = 0) -> _ServeRequest:
        """Non-blocking admission: returns a ticket whose .wait()
        yields the result (or raises the attributed shed/deadline
        error). The open-loop path for benches and load generators."""
        return self._tier.submit(self.policy_id, self.priority,
                                 inputs, n)

    def query(self, inputs: Any, timeout: float = 60.0) -> Any:
        return self.submit(inputs).wait(timeout)

    def query_batch(self, inputs: Any, n: int,
                    timeout: float = 60.0) -> Any:
        assert n >= 1
        return self.submit(inputs, n).wait(timeout)

    def warmup(self, example_input: Any,
               extra_sizes: tuple[int, ...] = ()) -> None:
        self._tier.warmup(self.policy_id, example_input,
                          extra_sizes=extra_sizes)

    def update_params(self, params: Any, version: int) -> None:
        self._tier.update_params(self.policy_id, params, version)

    @property
    def params_version(self) -> int:
        return self._tier.policy_version(self.policy_id)

    @property
    def queue_depth(self) -> int:
        return self._tier.queue_depth

    @property
    def stats(self) -> dict:
        return self._tier.tenant_stats(self.policy_id)

    def stop(self) -> None:
        # views share the tier; stop is idempotent there
        self._tier.stop()


class MultiPolicyInferenceServer:
    """Continuous-batching multi-policy serving tier (module docstring
    has the architecture sketch).

    Threads: "serving-admission" drains the intake queue into
    per-family per-class pending deques, shedding from the lowest
    class when depth crosses `queue_slo_items` and driving the
    backpressure signal; "serving-dispatch" builds priority-ordered
    batches (class 0 first, FIFO within a class, oversize requests
    parked without head-of-line blocking) and runs one forward per
    batch — plain jit when the batch is single-tenant, the stacked/
    gather-indexed coalesced forward when tenants mix. Admission keeps
    running while a forward is in flight: capacity freeing IS the
    admission signal, there are no collect-then-serve rounds."""

    def __init__(self, max_batch: int = 64, deadline_ms: float = 2.0,
                 *, mesh: Mesh | None = None, obs: Any = None,
                 priority_classes: int = 3, queue_slo_items: int = 256,
                 request_deadline_ms: float = 0.0,
                 stats_every_s: float = 1.0, coalesce: bool = True):
        """priority_classes: number of admission classes; class 0 is
        the top class and is NEVER shed. queue_slo_items: pending-item
        depth above which the admission controller sheds lower classes
        and engages backpressure (hysteresis: disengages at half).
        request_deadline_ms: per-request admission-queue deadline
        (0 disables); expiry raises ServeDeadlineExceeded naming the
        policy_id. coalesce: allow the stacked/gather-indexed
        multi-tenant forward (single-tenant batches always take the
        plain path). Mesh mode shards the plain path exactly like
        BatchedInferenceServer; the coalesced path runs unsharded."""
        assert priority_classes >= 1
        self._classes = int(priority_classes)
        self._max_batch = max_batch
        self._deadline_s = deadline_ms / 1000.0
        self._slo_items = int(queue_slo_items)
        self._req_deadline_s = request_deadline_ms / 1000.0
        self._stats_every_s = float(stats_every_s)
        self._coalesce = bool(coalesce)
        self._mesh = mesh
        if mesh is not None:
            self._batched_sharding = NamedSharding(
                mesh, P(tuple(mesh.axis_names)))
            self._params_sharding = NamedSharding(mesh, P())
            self._min_bucket = int(mesh.size)
        else:
            self._batched_sharding = None
            self._params_sharding = None
            self._min_bucket = 1
        self._q: queue.Queue[_ServeRequest] = queue.Queue()
        # _lock guards the registry, every pending deque, the stacked
        # param caches and all serve accounting; admission, dispatch,
        # register/update and stats readers all cross it
        self._lock = make_lock("serving_tier._lock")
        self._policies: dict[str, _Policy] = {}  # guarded-by: _lock
        self._families: dict[str, _Family] = {}  # guarded-by: _lock
        self._pending_items = 0  # guarded-by: _lock
        self._offered = 0  # guarded-by: _lock
        self._admitted = 0  # guarded-by: _lock
        self._shed_by_class = [0] * self._classes  # guarded-by: _lock
        self._expired = 0  # guarded-by: _lock
        self._batches_served = 0  # guarded-by: _lock
        self._items_served = 0  # guarded-by: _lock
        self._bp_engaged = False  # guarded-by: _lock
        self._stats_last = time.monotonic()  # dispatch thread only
        # transport hook: called with True/False on backpressure
        # transitions (engage when depth crosses the SLO line, release
        # at half); installed by the host before traffic, called from
        # the admission/dispatch threads
        self.on_backpressure: Callable[[bool], None] | None = None
        self._stop_evt = threading.Event()
        self._work = threading.Event()
        self._obs = obs if obs is not None else NULL_OBS
        self._obs.register("inference-server")
        self._admit_thread = threading.Thread(
            target=self._admit_loop, name="serving-admission",
            daemon=True)
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="serving-dispatch",
            daemon=True)
        self._admit_thread.start()
        self._dispatch_thread.start()

    # -- registry ----------------------------------------------------------

    def register_policy(self, policy_id: str, apply_fn: Callable,
                        params: Any, *, family: str = "default",
                        priority: int = 0,
                        version: int = 0) -> TenantClient:
        """Register one tenant and return its TenantClient view.

        Tenants sharing `family` must share apply semantics (same net
        applied to per-tenant params) — the family's jitted forwards
        come from the FIRST registration; only params differ per
        tenant. Registration invalidates the family's stacked-param
        cache and coalesced warm set (the stack gains a row, which is
        a new compile shape)."""
        with self._lock:
            if policy_id in self._policies:
                raise ValueError(f"policy {policy_id!r} already "
                                 f"registered")
            fam = self._families.get(family)
            if fam is None:
                if self._params_sharding is not None:
                    plain = jax.jit(
                        apply_fn,
                        in_shardings=(self._params_sharding,
                                      self._batched_sharding),
                        out_shardings=self._batched_sharding)
                else:
                    plain = jax.jit(apply_fn)
                fam = _Family(family, plain,
                              jax.jit(_make_gather_apply(apply_fn)),
                              self._classes)
                self._families[family] = fam
            pol = _Policy(policy_id, family, params, version,
                          row=len(fam.policies))
            fam.policies.append(pol)
            fam.dirty = True
            fam.warm_gather.clear()
            self._policies[policy_id] = pol
            n_tenants = len(self._policies)
        self._obs.gauge("serve_tenants", float(n_tenants))
        prio = min(max(int(priority), 0), self._classes - 1)
        return TenantClient(self, policy_id, prio)

    def update_params(self, policy_id: str, params: Any,
                      version: int) -> None:
        with self._lock:
            pol = self._policies[policy_id]
            pol.params = params
            pol.version = version
            # values changed, shapes did not: the stacked cache must
            # rebuild, the warm-bucket memos stay valid
            self._families[pol.family].dirty = True

    def policy_version(self, policy_id: str) -> int:
        with self._lock:
            return self._policies[policy_id].version

    def warmup(self, policy_id: str, example_input: Any,
               extra_sizes: tuple[int, ...] = ()) -> None:
        """AOT-compile this tenant's family at every bucket size a
        request can land in, deduped against the family's warm sets —
        re-warming after an epoch bump or for a same-family sibling
        tenant costs nothing. Warms the plain path always and the
        coalesced path once the family has >1 tenant (its stack shape
        includes the tenant count, so warm AFTER registering all
        same-family tenants)."""
        with self._lock:
            pol = self._policies[policy_id]
            fam = self._families[pol.family]
            params = pol.params
            n_pols = len(fam.policies)
            stacked = (self._stacked_locked(fam)
                       if self._coalesce and n_pols > 1 else None)
        sizes = _pow2_bucket_sizes(self._bucket, self._max_batch,
                                   extra_sizes)
        for b in sorted(sizes - fam.warm_plain):
            zeros = _zeros_like_batch(example_input, b)
            if self._batched_sharding is not None:
                zeros = jax.device_put(zeros, self._batched_sharding)
            fam.apply_plain.lower(params, zeros).compile()
            fam.warm_plain.add(b)
        if self._coalesce and n_pols > 1:
            for b in sorted(sizes - fam.warm_gather):
                zeros = _zeros_like_batch(example_input, b)
                rows = np.zeros(b, np.int32)
                fam.apply_gather.lower(stacked, rows, zeros).compile()
                fam.warm_gather.add(b)

    # -- client side -------------------------------------------------------

    def submit(self, policy_id: str, priority: int, inputs: Any,
               n: int = 0) -> _ServeRequest:
        prio = min(max(int(priority), 0), self._classes - 1)
        req = _ServeRequest(policy_id, prio, inputs, n)
        self._q.put(req)
        return req

    # -- admission controller ----------------------------------------------

    def _admit_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                r = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            with self._lock:
                pol = self._policies.get(r.policy)
            if pol is None:
                r.result = KeyError(
                    f"unknown policy {r.policy!r}: not registered "
                    f"with this serving tier")
                r.event.set()
                continue
            shed: list[_ServeRequest] = []
            with self._lock:
                fam = self._families[pol.family]
                fam.pending[r.prio].append(r)
                fam.pending_items += r.items
                self._pending_items += r.items
                pol.pending_items += r.items
                pol.offered += 1
                self._offered += 1
                if self._pending_items > self._slo_items:
                    shed = self._shed_locked()
                transition = self._bp_transition_locked(bool(shed))
                depth = self._pending_items
            self._obs.count("serve_offered", 1)
            for s in shed:
                s.result = ServeShed(s.policy, s.prio)
                s.event.set()
                self._obs.count("serve_shed", 1)
            self._obs.gauge("serve_queue_items", float(depth))
            if transition is not None:
                self._fire_backpressure(transition)
            self._work.set()

    def _shed_locked(self) -> list[_ServeRequest]:
        """Shed newest-first from the lowest priority class until the
        pending depth is back under the SLO line. Class 0 is never
        shed: under pure top-class overload the queue stays deep and
        backpressure is the only relief valve."""
        shed: list[_ServeRequest] = []
        for cls in range(self._classes - 1, 0, -1):
            for fam in self._families.values():
                dq = fam.pending[cls]
                while dq and self._pending_items > self._slo_items:
                    r = dq.pop()
                    fam.pending_items -= r.items
                    self._pending_items -= r.items  # apexlint: unguarded(caller holds _lock)
                    pol = self._policies[r.policy]
                    pol.pending_items -= r.items
                    pol.shed += 1
                    self._shed_by_class[cls] += 1  # apexlint: unguarded(caller holds _lock)
                    shed.append(r)
            if self._pending_items <= self._slo_items:
                break
        return shed

    def _bp_transition_locked(self, shed_now: bool) -> bool | None:
        """Hysteresis on the backpressure signal: engage when depth
        crosses the SLO line (or shedding fired), release only once
        the queue drains to half the line. Returns the new state on a
        transition, None otherwise."""
        depth = self._pending_items
        if not self._bp_engaged and (shed_now
                                     or depth > self._slo_items):
            self._bp_engaged = True  # apexlint: unguarded(caller holds _lock)
            return True
        if self._bp_engaged and depth <= self._slo_items // 2:
            self._bp_engaged = False  # apexlint: unguarded(caller holds _lock)
            return False
        return None

    def _fire_backpressure(self, engaged: bool) -> None:
        self._obs.gauge("serve_backpressure", 1.0 if engaged else 0.0)
        # backpressure flips are exactly the "significant recent
        # events" a post-crash ring should narrate
        self._obs.blackbox.record("backpressure",
                                  component="inference-server",
                                  engaged=bool(engaged))
        cb = self.on_backpressure
        if cb is not None:
            cb(engaged)

    # -- dispatch ----------------------------------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop_evt.is_set():
            picked = self._take_batch()
            if picked is None:
                self._work.wait(timeout=0.005)
                self._work.clear()
                self._obs.beat("inference-server", "idle")
                self._maybe_publish_stats()
                continue
            fam, reqs, items = picked
            try:
                self._forward(fam, reqs, items)
            except Exception as e:  # propagate to callers, keep serving
                self._obs.blackbox.record(
                    "serve_error", component="inference-server",
                    error=repr(e)[:200])
                for r in reqs:
                    r.result = e
                    r.event.set()
            self._maybe_publish_stats()

    def _take_batch(self) -> tuple[_Family, list[_ServeRequest],
                                   int] | None:
        """Pick the family whose head-of-queue request is most urgent
        (highest class, then oldest) and build a batch from its
        pending deques, class 0 first, FIFO within a class, parking
        non-fitting requests in place (no head-of-line blocking).
        Dispatches immediately on a full batch; otherwise waits out
        the batching deadline from the oldest pending admit."""
        now = time.perf_counter()
        expired: list[_ServeRequest] = []
        batch: tuple[_Family, list[_ServeRequest], int] | None = None
        transition: bool | None = None
        with self._lock:
            expired = self._sweep_expired_locked(now)
            best: tuple[int, float, _Family] | None = None
            for fam in self._families.values():
                for cls, dq in enumerate(fam.pending):
                    if dq:
                        if (best is None
                                or (cls, dq[0].t_enq) < best[:2]):
                            best = (cls, dq[0].t_enq, fam)
                        break
            if best is not None:
                fam = best[2]
                oldest = min(dq[0].t_enq
                             for dq in fam.pending if dq)
                if (fam.pending_items >= self._max_batch
                        or now - oldest >= self._deadline_s
                        or self._stop_evt.is_set()):
                    reqs: list[_ServeRequest] = []
                    items = 0
                    for dq in fam.pending:
                        kept: deque[_ServeRequest] = deque()
                        while dq:
                            r = dq.popleft()
                            if (items + r.items <= self._max_batch
                                    or (not reqs
                                        and r.items >= self._max_batch)):
                                reqs.append(r)
                                items += r.items
                            else:
                                kept.append(r)
                        dq.extend(kept)
                        if items >= self._max_batch:
                            break
                    fam.pending_items -= items
                    self._pending_items -= items
                    for r in reqs:
                        pol = self._policies[r.policy]
                        pol.pending_items -= r.items
                        pol.admitted += 1
                    self._admitted += len(reqs)
                    batch = (fam, reqs, items)
            if expired or batch:
                transition = self._bp_transition_locked(False)
        for r in expired:
            r.result = ServeDeadlineExceeded(
                r.policy, r.prio, (now - r.t_enq) * 1e3)
            r.event.set()
            self._obs.count("serve_expired", 1)
            self._obs.count("serve_shed", 1)
        if batch is not None:
            self._obs.count("serve_admitted", len(batch[1]))
        if transition is not None:
            self._fire_backpressure(transition)
        return batch

    def _sweep_expired_locked(self, now: float) -> list[_ServeRequest]:
        """Deadline-aware shedding: pending deques are FIFO, so the
        expired requests are exactly the stale heads."""
        if self._req_deadline_s <= 0:
            return []
        expired: list[_ServeRequest] = []
        for fam in self._families.values():
            for cls, dq in enumerate(fam.pending):
                while dq and now - dq[0].t_enq > self._req_deadline_s:
                    r = dq.popleft()
                    fam.pending_items -= r.items
                    self._pending_items -= r.items  # apexlint: unguarded(caller holds _lock)
                    pol = self._policies[r.policy]
                    pol.pending_items -= r.items
                    pol.shed += 1
                    self._shed_by_class[cls] += 1  # apexlint: unguarded(caller holds _lock)
                    self._expired += 1  # apexlint: unguarded(caller holds _lock)
                    expired.append(r)
        return expired

    def _bucket(self, n: int) -> int:
        b = next_pow2(max(n, 1))
        if b % self._min_bucket:
            b = -(-b // self._min_bucket) * self._min_bucket
        return b

    def _stacked_locked(self, fam: _Family) -> Any:
        """(Re)build the family's stacked param cache if a tenant
        registered or published since the last forward. One jnp.stack
        per leaf per publication — never per batch. Caller holds
        _lock; update_params contention is publication-rate, so the
        device work under the lock is bounded and rare."""
        if fam.dirty:
            if len(fam.policies) == 1:
                fam.stacked = jax.tree.map(
                    lambda x: jnp.asarray(x)[None],
                    fam.policies[0].params)
            else:
                fam.stacked = jax.tree.map(
                    lambda *xs: jnp.stack(
                        [jnp.asarray(x) for x in xs]),
                    *[p.params for p in fam.policies])
            fam.dirty = False
        return fam.stacked

    def _forward(self, fam: _Family, reqs: list[_ServeRequest],
                 items: int) -> None:
        padded = self._bucket(items)
        with self._obs.span("server.batch", items=items,
                            padded=padded):
            leads = [r.inputs if r.n else
                     jax.tree.map(lambda x: np.asarray(x)[None],
                                  r.inputs)
                     for r in reqs]
            stacked = jax.tree.map(
                lambda *xs: _pad_concat(xs, padded), *leads)
            with self._lock:
                pols = [self._policies[r.policy] for r in reqs]
                version = max(p.version for p in pols)
                single = len({p.policy_id for p in pols}) == 1
                if single or not self._coalesce:
                    params = pols[0].params
                    stacked_params = None
                else:
                    params = None
                    stacked_params = self._stacked_locked(fam)
            if stacked_params is None:
                # single-tenant batch: plain (optionally mesh-sharded)
                # forward — identical to BatchedInferenceServer
                if self._batched_sharding is not None:
                    stacked = jax.device_put(stacked,
                                             self._batched_sharding)
                out = fam.apply_plain(params, stacked)
            else:
                # mixed tenants: one gather-indexed forward; padding
                # rows point at row 0 and compute discarded garbage
                rows = np.zeros(padded, np.int32)
                off = 0
                for r, p in zip(reqs, pols):
                    rows[off:off + r.items] = p.row
                    off += r.items
                out = fam.apply_gather(stacked_params, rows, stacked)
            out_np = jax.tree.map(np.asarray, out)
        off = 0
        t_done = time.perf_counter()
        for r, p in zip(reqs, pols):
            if r.n:
                lo, hi = off, off + r.n
                r.result = jax.tree.map(lambda x: x[lo:hi], out_np)
            else:
                idx = off
                r.result = jax.tree.map(lambda x: x[idx], out_np)
            off += r.items
            lat_ms = (t_done - r.t_enq) * 1e3
            self._obs.observe("infer_latency_ms", lat_ms)
            p.lat_ms.append(lat_ms)
            r.event.set()
        with self._lock:
            self._batches_served += 1
            self._items_served += items
            depth = self._pending_items
        self._obs.on_server_batch(items, version,
                                  depth + self._q.qsize())

    def _maybe_publish_stats(self) -> None:
        """Per-tenant serve/<tenant>/ gauges at stats cadence: p50/p99
        of the latency reservoir, pending depth, offered/admitted/shed
        counts. Dynamic keys by design (same policy as learn/<tenant>/
        — the report regroups them; apexlint cross-references only
        literal names)."""
        now = time.monotonic()
        if now - self._stats_last < self._stats_every_s:
            return
        self._stats_last = now
        with self._lock:
            snap = [(p.policy_id, list(p.lat_ms), p.pending_items,
                     p.offered, p.admitted, p.shed)
                    for p in self._policies.values()]
        for pid, lats, depth, offered, admitted, shed in snap:
            if lats:
                q50, q99 = np.percentile(np.asarray(lats), (50, 99))
                self._obs.gauge(f"serve/{pid}/p50_ms", float(q50))
                self._obs.gauge(f"serve/{pid}/p99_ms", float(q99))
            self._obs.gauge(f"serve/{pid}/queue_depth", float(depth))
            self._obs.gauge(f"serve/{pid}/offered", float(offered))
            self._obs.gauge(f"serve/{pid}/admitted", float(admitted))
            self._obs.gauge(f"serve/{pid}/shed", float(shed))

    # -- aggregate surface -------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._lock:
            pending = self._pending_items
        return pending + self._q.qsize()

    @property
    def backpressure_engaged(self) -> bool:
        with self._lock:
            return self._bp_engaged

    def force_backpressure(self, engaged: bool) -> bool:
        """Externally set the backpressure flag (the remediation
        plane's queue-SLO actuator, runtime/remediation.py). Fires the
        same gauge + transport callback as the admission controller's
        own transitions; the controller keeps running, so if its
        depth-based hysteresis disagrees it re-transitions on the next
        shed/drain — the external setting is a nudge with a live
        fallback, not an override that can wedge. Returns False on a
        no-op (already in the requested state)."""
        with self._lock:
            if self._bp_engaged == bool(engaged):
                return False
            self._bp_engaged = bool(engaged)
        self._fire_backpressure(bool(engaged))
        return True

    @property
    def stats(self) -> dict:
        with self._lock:
            return {"offered": self._offered,
                    "admitted": self._admitted,
                    "shed": sum(self._shed_by_class),
                    "shed_by_class": list(self._shed_by_class),
                    "expired": self._expired,
                    "batches": self._batches_served,
                    "items": self._items_served,
                    "avg_batch": (self._items_served
                                  / max(self._batches_served, 1)),
                    "tenants": len(self._policies)}

    def tenant_stats(self, policy_id: str) -> dict:
        with self._lock:
            pol = self._policies[policy_id]
            lats = list(pol.lat_ms)
            out = {"offered": pol.offered, "admitted": pol.admitted,
                   "shed": pol.shed, "pending": pol.pending_items,
                   "version": pol.version}
        if lats:
            q50, q99 = np.percentile(np.asarray(lats), (50, 99))
            out["p50_ms"], out["p99_ms"] = float(q50), float(q99)
        return out

    def stop(self) -> None:
        if self._stop_evt.is_set():
            return
        self._stop_evt.set()
        self._work.set()
        self._admit_thread.join(timeout=5)
        self._dispatch_thread.join(timeout=5)
        # unblock anyone still waiting: queued and pending requests
        # fail loudly instead of hitting their full client timeout
        leftovers: list[_ServeRequest] = []
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        with self._lock:
            for fam in self._families.values():
                for dq in fam.pending:
                    leftovers.extend(dq)
                    dq.clear()
                fam.pending_items = 0
            self._pending_items = 0
        for r in leftovers:
            if not r.event.is_set():
                r.result = RuntimeError("serving tier stopped")
                r.event.set()


def _zeros_like_batch(example_input: Any, b: int) -> Any:
    return jax.tree.map(
        lambda x: np.zeros((b, *np.asarray(x).shape),
                           np.asarray(x).dtype), example_input)


def build_serving_tier(serving: Any, *, max_batch: int,
                       deadline_ms: float, mesh: Mesh | None = None,
                       obs: Any = None) -> MultiPolicyInferenceServer:
    """Construct the serving tier from a configs.ServingConfig — the
    single place every serving knob is consumed, so drivers and actor
    hosts stay one-call sites."""
    return MultiPolicyInferenceServer(
        max_batch=max_batch, deadline_ms=deadline_ms, mesh=mesh,
        obs=obs,
        priority_classes=serving.priority_classes,
        queue_slo_items=serving.queue_slo_items,
        request_deadline_ms=serving.request_deadline_ms,
        stats_every_s=serving.stats_every_s,
        coalesce=serving.coalesce)
