"""Parameter sharding rules (tensor parallelism for dense layers).

The Nature-CNN's FLOPs concentrate in the flatten->512 dense layer
(3136x512) and the LSTM kernels; those shard over the "tp" mesh axis
(column-parallel: output features split, XLA all-gathers activations as
needed). Conv kernels and small heads replicate — sharding them would
cost more in collectives than it saves.

This follows the standard JAX recipe: annotate param shardings, let
GSPMD insert the collectives over ICI.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def param_pspec(path: tuple, leaf, tp: int, min_dim: int = 256) -> P:
    """PartitionSpec for one parameter.

    Column-shard 2D dense kernels whose output dim is large and divisible
    by tp; shard matching biases; replicate everything else.
    """
    if tp <= 1:
        return P()
    shape = leaf.shape
    if len(shape) == 2 and shape[1] % tp == 0 and shape[1] >= min_dim:
        return P(None, "tp")
    if len(shape) == 1 and shape[0] % tp == 0 and shape[0] >= min_dim:
        return P("tp")
    return P()


def make_param_shardings(params: Any, mesh: Mesh,
                         min_dim: int = 256) -> Any:
    """Pytree of NamedShardings matching `params`."""
    tp = mesh.shape.get("tp", 1)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(
            mesh, param_pspec(path, leaf, tp, min_dim)),
        params)


def shard_params(params: Any, mesh: Mesh, min_dim: int = 256) -> Any:
    shardings = make_param_shardings(params, mesh, min_dim)
    return jax.tree.map(jax.device_put, params, shardings)
