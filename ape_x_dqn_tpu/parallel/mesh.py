"""Device mesh construction.

The reference scales with NCCL data-parallel collectives (SURVEY.md §2.3
item 2); here the learner scales over a `jax.sharding.Mesh` with named
axes and XLA-inserted collectives over ICI:

- "dp": data parallel — replay shards + batch shards + gradient psum.
- "tp": tensor parallel — large dense kernels column/row-sharded.

An Ape-X system has no pipeline/sequence/expert parallelism to express
(SURVEY.md §2.4): networks are small CNNs/LSTMs, so dp x tp is the
complete, honest mesh. R2D2 sequences shard across the batch axis (dp),
never time.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: int | None = None, tp: int = 1,
              devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if dp is None:
        dp = n // tp
    assert 1 <= dp * tp <= n, f"dp({dp}) * tp({tp}) > device count ({n})"
    arr = np.asarray(devices[:dp * tp]).reshape(dp, tp)
    return Mesh(arr, axis_names=("dp", "tp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding over dp (replay shards, batches)."""
    return NamedSharding(mesh, P("dp"))
