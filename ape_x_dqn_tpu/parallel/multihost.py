"""Multi-host (multi-process) learner support over JAX's distributed
runtime.

The reference scales its learner across hosts with NCCL/MPI process
groups (SURVEY.md §2.2 "Comm: NCCL", §5 "distributed communication
backend"); the TPU-native equivalent is `jax.distributed` + GSPMD: every
learner process calls `init_multihost` (which wires the coordination
service), builds ONE global `(dp, tp)` mesh over all processes' devices,
and then executes the SAME jitted programs on globally-sharded arrays —
XLA inserts the cross-host collectives (grad psum, publication
all-gather) over ICI within a host and DCN between hosts (Gloo on CPU
test rigs).

The host-side contract this module provides to the multihost driver
(runtime/multihost_driver.py):

- `process_rows(mesh)`: which contiguous dp rows this process owns —
  ingest routes each host's actor experience into its own replay shards
  (no cross-host experience traffic, mirroring the reference's
  per-learner replay locality).
- `make_global(mesh, local)`: wrap this process's [dp_local, ...] block
  into the global [dp, ...] array GSPMD programs consume.
- `global_sum` / `global_min`: tiny collective reductions of host-local
  scalars (frame counts, stage depths). Every control-flow decision in
  the multihost driver derives from these or from global jit outputs,
  which is what keeps all processes' call sequences in lockstep — a
  process branching on a host-local value would deadlock the others
  inside a collective.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_multihost(coordinator: str, num_processes: int,
                   process_id: int) -> None:
    """Join the JAX distributed coordination service. Must run before
    any backend use (the CLI calls it first thing).

    Honors a JAX_PLATFORMS env override through jax.config: interpreter
    startup hooks (e.g. a sitecustomize registering an experimental TPU
    plugin) can import jax before this runs, and the env var alone is
    then too late — the config update still wins as long as no backend
    has been initialized."""
    import os
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def process_rows(mesh: Mesh) -> tuple[int, int]:
    """[start, stop) dp rows owned by this process.

    Mesh rows are process-contiguous because make_mesh reshapes
    jax.devices() (globally ordered by process) into (dp, tp); asserts
    that a row never straddles processes (tp must divide the local
    device count)."""
    dp = mesh.shape["dp"]
    tp = mesh.shape.get("tp", 1)
    local = jax.local_device_count()
    nproc = jax.process_count()
    assert local % tp == 0, \
        f"tp={tp} must divide local device count {local} (a tensor-" \
        f"parallel row cannot straddle hosts: tp collectives ride ICI)"
    rows_per_proc = dp // nproc
    assert rows_per_proc * nproc == dp, \
        f"dp={dp} must divide by process count {nproc}"
    start = jax.process_index() * rows_per_proc
    return start, start + rows_per_proc


def make_global(mesh: Mesh, local: Any) -> Any:
    """Per-process [dp_local, ...] pytree -> global [dp, ...] arrays
    sharded P('dp') (each process contributes its own rows)."""
    dp = mesh.shape["dp"]
    sharding = NamedSharding(mesh, P("dp"))

    def one(x):
        x = np.asarray(x)
        global_shape = (dp,) + x.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape)

    return jax.tree.map(one, local)


_LIMB = 1 << 20  # see global_sum


def _rows(mesh: Mesh, row_value: np.ndarray) -> Any:
    """Each process fills its dp rows with row_value -> global [dp, ...]
    array for a replicated-out reduction. Deterministic and identical
    on every process."""
    start, stop = process_rows(mesh)
    return make_global(
        mesh, np.tile(row_value[None], (stop - start,) + (1,) *
                      row_value.ndim))


def global_sum(mesh: Mesh, value: float) -> float:
    """Exact sum of each PROCESS's non-negative integer-valued scalar.

    f32 device arrays round integers above 2^24 (frame counts reach
    billions at atari57 scale, and a rounded-down global count would
    stall the frame-budget termination forever), so the value rides as
    two base-2^20 limbs — each limb and each limb-sum stays well inside
    f32's exact-integer range for any sane process count — and the
    limbs recombine exactly in Python ints."""
    v = int(value)
    limbs = np.asarray([v // _LIMB, v % _LIMB], np.float32)
    arr = _rows(mesh, limbs)  # [dp, 2]
    repl = NamedSharding(mesh, P())
    fn = jax.jit(partial(jnp.sum, axis=0), out_shardings=repl)
    start, stop = process_rows(mesh)
    hi, lo = (np.asarray(fn(arr)) / (stop - start)).tolist()
    return float(int(round(hi)) * _LIMB + int(round(lo)))


def global_min(mesh: Mesh, value: float) -> float:
    """Min of each process's scalar (used for 0/1 readiness flags)."""
    arr = _rows(mesh, np.asarray([np.float32(value)]))
    repl = NamedSharding(mesh, P())
    fn = jax.jit(jnp.min, out_shardings=repl)
    return float(fn(arr))
