"""Multi-host (multi-process) learner support over JAX's distributed
runtime.

The reference scales its learner across hosts with NCCL/MPI process
groups (SURVEY.md §2.2 "Comm: NCCL", §5 "distributed communication
backend"); the TPU-native equivalent is `jax.distributed` + GSPMD: every
learner process calls `init_multihost` (which wires the coordination
service), builds ONE global `(dp, tp)` mesh over all processes' devices,
and then executes the SAME jitted programs on globally-sharded arrays —
XLA inserts the cross-host collectives (grad psum, publication
all-gather) over ICI within a host and DCN between hosts (Gloo on CPU
test rigs).

The host-side contract this module provides to the multihost driver
(runtime/multihost_driver.py):

- `process_rows(mesh)`: which contiguous dp rows this process owns —
  ingest routes each host's actor experience into its own replay shards
  (no cross-host experience traffic, mirroring the reference's
  per-learner replay locality).
- `make_global(mesh, local)`: wrap this process's [dp_local, ...] block
  into the global [dp, ...] array GSPMD programs consume.
- `global_stats`: ONE packed collective reduction per round of the
  host-local control scalars (ingest readiness, idleness, frame
  counts). Every control-flow decision in the multihost driver derives
  from it or from global jit outputs, which is what keeps all
  processes' call sequences in lockstep — a process branching on a
  host-local value would deadlock the others inside a collective.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_multihost(coordinator: str, num_processes: int,
                   process_id: int) -> None:
    """Join the JAX distributed coordination service. Must run before
    any backend use (the CLI calls it first thing).

    Honors a JAX_PLATFORMS env override through jax.config: interpreter
    startup hooks (e.g. a sitecustomize registering an experimental TPU
    plugin) can import jax before this runs, and the env var alone is
    then too late — the config update still wins as long as no backend
    has been initialized."""
    import os
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def process_rows(mesh: Mesh) -> tuple[int, int]:
    """[start, stop) dp rows owned by this process.

    Mesh rows are process-contiguous because make_mesh reshapes
    jax.devices() (globally ordered by process) into (dp, tp); asserts
    that a row never straddles processes (tp must divide the local
    device count)."""
    dp = mesh.shape["dp"]
    tp = mesh.shape.get("tp", 1)
    local = jax.local_device_count()
    nproc = jax.process_count()
    assert dp * tp == len(jax.devices()), \
        f"multihost mesh must cover every global device: dp*tp=" \
        f"{dp * tp} != {len(jax.devices())} (make_mesh takes the first " \
        f"dp*tp devices, so a partial mesh would assign this process " \
        f"rows living on another process's chips)"
    assert local % tp == 0, \
        f"tp={tp} must divide local device count {local} (a tensor-" \
        f"parallel row cannot straddle hosts: tp collectives ride ICI)"
    rows_per_proc = dp // nproc
    assert rows_per_proc * nproc == dp, \
        f"dp={dp} must divide by process count {nproc}"
    start = jax.process_index() * rows_per_proc
    return start, start + rows_per_proc


def make_global(mesh: Mesh, local: Any) -> Any:
    """Per-process [dp_local, ...] pytree -> global [dp, ...] arrays
    sharded P('dp') (each process contributes its own rows)."""
    dp = mesh.shape["dp"]
    sharding = NamedSharding(mesh, P("dp"))

    def one(x):
        x = np.asarray(x)
        global_shape = (dp,) + x.shape[1:]
        return jax.make_array_from_process_local_data(
            sharding, x, global_shape)

    return jax.tree.map(one, local)


_reduce_jits: dict[Any, Any] = {}


def global_min_scalar(mesh: Mesh, value: int) -> int:
    """Min of each process's integer scalar — one-off agreement values
    outside the hot loop (e.g. which checkpoint step every process can
    restore; min handles a host whose filesystem lacks the files).

    int32 lanes, NOT f32: checkpoint steps exceed f32's 2^24 exact
    range within hours at the measured learner rate, and a rounded
    step number would name a checkpoint that was never written.
    Values must fit int32 (|v| < 2^31 — ~50 days of grad steps)."""
    assert -(2**31) < value < 2**31, value
    start, stop = process_rows(mesh)
    block = np.full((stop - start, 1), value, np.int32)
    arr = make_global(mesh, block)
    fn = _reduce_jits.get((mesh, "min"))
    if fn is None:
        fn = jax.jit(jnp.min, out_shardings=NamedSharding(mesh, P()))
        _reduce_jits[(mesh, "min")] = fn
    return int(fn(arr))


def global_stats(mesh: Mesh, ready: float, idle: float,
                 frames: float) -> tuple[bool, bool, float]:
    """One packed per-round reduction: (all_ready, all_idle,
    frames_total).

    The lockstep round loop needs three global quantities per round;
    issuing them as separate reductions would cost three sequential DCN
    barrier round-trips, so they ride one [dp, 5] array through a
    single cached jit (a fresh jax.jit wrapper per call would retrace
    every round) that returns both the row-min (flags) and the row-sum
    (frame limbs).

    Exactness: frame counts reach billions at atari57 scale — a
    rounded-down global count would stall the frame-budget termination
    forever. The lanes are int32 (like global_min_scalar; f32 rounds
    integers above 2^24, which a 256-process fleet's limb sums would
    already exceed): the per-process count rides as three base-2^16
    limbs on ONE row per process (zeros on its other rows, so limb sums
    scale with process count, not dp). Each limb < 2^16, so int32
    limb-sums stay exact through 2^15 processes and counts to 2^48, and
    the limbs recombine exactly in Python ints. Flags tile across all
    the process's rows (min is idempotent over copies).
    """
    v = int(frames)
    flags = [int(ready), int(idle)]
    limbs = [(v >> 32) & 0xFFFF, (v >> 16) & 0xFFFF, v & 0xFFFF]
    start, stop = process_rows(mesh)
    block = np.zeros((stop - start, 5), np.int32)
    block[:, :2] = flags
    block[0, 2:] = limbs
    arr = make_global(mesh, block)
    fn = _reduce_jits.get(mesh)
    if fn is None:
        repl = NamedSharding(mesh, P())
        fn = jax.jit(lambda a: (jnp.min(a, axis=0), jnp.sum(a, axis=0)),
                     out_shardings=(repl, repl))
        _reduce_jits[mesh] = fn
    mins, sums = fn(arr)
    mins, sums = np.asarray(mins), np.asarray(sums)
    l2, l1, l0 = (int(s) for s in sums[2:])
    total = float((l2 << 32) + (l1 << 16) + l0)
    return bool(mins[0] >= 1), bool(mins[1] >= 1), total
