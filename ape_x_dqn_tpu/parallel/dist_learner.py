"""Multi-chip learners: replay-sharded data parallelism + tensor-parallel
dense layers over a (dp, tp) mesh.

Reference parity (SURVEY.md §2.3): the reference's NCCL grad all-reduce
becomes an XLA-inserted psum over ICI; its host sum-tree becomes dp
per-shard device sum-trees.

Design (the "pick a mesh, annotate shardings, let XLA insert
collectives" recipe):
- Replay state carries a leading [dp] axis on every array (storage
  [dp, cap_shard, ...], tree [dp, 2*cap_shard], pos/size/rng [dp]),
  sharded `P("dp")`. Replay ops are `jax.vmap`s of the single-shard
  pure functions — under GSPMD each mesh row executes only its own
  slice, so sampling/priority-updates never cross ICI.
- Each shard draws batch/dp samples from its own tree (stratified
  within shard); IS weights use the global fill N = sum of shard sizes
  and a global max-normalization (one tiny psum).
- The loss/grad runs on the flattened [dp*b_local] batch with a
  sharding constraint P("dp"); the batch-mean makes GSPMD emit the
  gradient psum over "dp" — the NCCL all-reduce equivalent.
- Large dense kernels are column-sharded over "tp"
  (parallel.sharding.make_param_shardings); optimizer state inherits
  param shardings by initializing it under jit with sharded inputs.

Ingest expects items pre-split per shard: [dp, B_ingest, ...]. The
host-side driver round-robins actor staging units across shards.

Two concrete learners share the machinery via _DistLearnerBase:
DistDQNLearner (flat n-step transitions, SURVEY.md §3.3) and
DistSequenceLearner (R2D2 stored-state sequences, §3.4 — the r2d2
config attests dp=4 x tp=2). They differ only in the loss and how
sampled items become a loss batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.obs import learning as learn_obs
from ape_x_dqn_tpu.ops.losses import (
    TransitionBatch, make_dqn_loss, make_r2d2_loss)
from ape_x_dqn_tpu.replay.prioritized import PrioritizedReplay, ReplayState
from ape_x_dqn_tpu.replay.sequence import batch_to_sequence_batch
from ape_x_dqn_tpu.parallel.sharding import make_param_shardings
from ape_x_dqn_tpu.runtime.learner import make_optimizer


class DistTrainState(NamedTuple):
    params: Any
    target_params: Any
    opt_state: Any
    replay: ReplayState   # every leaf has a leading [dp] axis
    rng: jax.Array        # [dp] keys
    step: jax.Array       # scalar int32


class _DistLearnerBase:
    """Shared (dp, tp) machinery; subclasses set self.loss_fn and
    override _make_batch(flattened items) -> loss batch."""

    def __init__(self, replay: PrioritizedReplay, lcfg, mesh: Mesh,
                 optimizer: optax.GradientTransformation | None = None):
        """`replay` is configured with the PER-SHARD capacity."""
        self.replay = replay
        self.lcfg = lcfg
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        assert lcfg.batch_size % self.dp == 0, \
            "batch_size must divide by dp"
        self.b_local = lcfg.batch_size // self.dp
        self.optimizer = optimizer or make_optimizer(lcfg)
        self._dp_sharding = NamedSharding(mesh, P("dp"))
        # coalesced ingest groups [g, dp, ...]: replicate the group
        # axis, shard the dp axis (add_many)
        self._group_sharding = NamedSharding(mesh, P(None, "dp"))
        self._repl_sharding = NamedSharding(mesh, P())
        self._reshard = None  # publish_params' cached jit (built once)

    def _make_batch(self, items: Any) -> Any:
        raise NotImplementedError

    # -- state construction ------------------------------------------------

    def init(self, params: Any, item_spec: Any,
             rng: jax.Array) -> DistTrainState:
        param_shardings = make_param_shardings(params, self.mesh)

        # make_array_from_callback instead of device_put: the mesh may
        # span processes (multihost), where device_put to a non-
        # addressable sharding is an error; the callback hands each
        # process the slices it owns from its (identical, same-seed)
        # host copy
        def put(x, sharding):
            x = np.asarray(x)  # apexlint: host-sync(one-time init: host copy feeding make_array_from_callback)
            return jax.make_array_from_callback(
                x.shape, sharding, lambda idx: x[idx])

        params = jax.tree.map(put, params, param_shardings)
        target = jax.jit(partial(jax.tree.map, jnp.copy))(params)
        opt_state = jax.jit(self.optimizer.init)(params)

        def one_shard_replay(_):
            return self.replay.init(item_spec)

        # out_shardings avoids ever materializing the full replicated
        # buffer: each shard's storage is allocated on its own mesh row
        replay0 = jax.jit(
            jax.vmap(one_shard_replay),
            out_shardings=jax.tree.map(lambda _: self._dp_sharding,
                                       jax.eval_shape(
                                           jax.vmap(one_shard_replay),
                                           jnp.arange(self.dp))),
        )(jnp.arange(self.dp))
        rngs = jax.jit(lambda k: jax.random.split(k, self.dp),
                       out_shardings=self._dp_sharding)(rng)
        return DistTrainState(params, target, opt_state, replay0, rngs,
                              jnp.int32(0))

    # -- pure step ---------------------------------------------------------

    def _sample_weighted(self, replay_state: ReplayState, sk,
                         n_per_shard):
        """Per-shard stratified sample of n_per_shard items + global IS
        weights over the [dp, n_per_shard] pool.

        sample_items delegates storage reconstruction to the replay —
        flat layouts gather rows, the frame-ring layout rebuilds stacks
        from single frames (replay/frame_ring.py); the size clamp keeps
        a sparsely-filled shard's descent off zero-priority leaves.

        IS weights against the ACTUAL sampling distribution: a draw
        lands in each shard with probability 1/dp (stratified — every
        shard contributes exactly n_per_shard draws) and within shard d
        on item i with probs = p_i/m_d, so P(i) = probs/dp EXACTLY, even
        with skewed shard masses. At beta=1 the weighted estimate is
        therefore unbiased toward the uniform target regardless of
        skew (tests/test_parallel.py::test_skewed_shard_is_weights —
        weighting by the single-global-tree probability p_i/M instead
        would bias each shard's contribution by M/(dp*m_d)). What
        skew DOES perturb is the sampling distribution itself: items
        in a starved shard are over-sampled (and down-weighted);
        round-robin ingest keeps masses balanced in expectation, so
        the effective prioritization tracks the single-tree recipe.

        Takes the replay state alone (not the full train state): like
        the single-chip replay's sample_state it reads only
        storage/tree/size, so a prefetched call commutes with an
        in-flight per-shard priority write-back (the double-buffering
        contract, runtime/learner.py).

        Returns (items [dp, n, ...], idx [dp, n], w [dp, n]) with w
        NOT yet max-normalized (callers normalize per training batch).
        """
        def shard_sample(rstate: ReplayState, key):
            return self.replay.sample_items(rstate, key, n_per_shard)

        items, idx, probs = jax.vmap(shard_sample)(replay_state, sk)
        n_global = jnp.maximum(
            replay_state.size.astype(jnp.float32).sum(), 1.0)
        w = (n_global * jnp.maximum(probs / self.dp, 1e-12)
             ) ** (-self.replay.beta)
        # dead frame-ring pad slots (prob ~0) would dominate the max-
        # normalization; they train with weight 0 instead
        w = w * jax.vmap(self.replay.valid_mask)(replay_state, idx)
        return items, idx, w

    def _flat(self, x):
        y = x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
        return jax.lax.with_sharding_constraint(y, self._dp_sharding)

    def _sgd_step(self, params, target_params, opt_state, step,
                  items, w):
        """One loss/grad/optimizer/target-sync update on an
        already-sampled [dp, b_local] batch (shared by the exact
        per-step path and the K-batch relaxation). `w` is the raw IS
        weight ([dp, b_local]); max-normalization happens here so each
        training batch is normalized over exactly its own draws."""
        w = w / jnp.maximum(w.max(), 1e-12)
        batch = self._make_batch(jax.tree.map(self._flat, items))
        wf = self._flat(w)
        (loss, aux), grads = jax.value_and_grad(
            self.loss_fn, has_aux=True)(
            params, target_params, batch, wf)
        updates, opt_state = self.optimizer.update(
            grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        step = step + 1
        sync = (step % self.lcfg.target_sync_every == 0)
        target_params = jax.tree.map(
            lambda t, p: jnp.where(sync, p, t), target_params, params)
        td_shard = aux["td_abs"].reshape(self.dp, self.b_local)
        # learning-health scalars: the flat reductions inside sgd_diag
        # run over the [dp]-sharded batch, so GSPMD lowers them to the
        # psum'd GLOBAL statistics; the per-shard mean-|TD| min/max
        # exposes shard skew the global mean would average away
        shard_means = td_shard.mean(axis=1)
        diag = learn_obs.sgd_diag(aux, wf, grads, updates, params)
        diag["shard_td_mean_min"] = shard_means.min()
        diag["shard_td_mean_max"] = shard_means.max()
        metrics = {"loss": loss, "q_mean": aux["q_mean"],
                   "td_abs_mean": aux["td_abs"].mean(),
                   "grad_norm": optax.global_norm(grads),
                   "diag": diag}
        return params, target_params, opt_state, step, td_shard, metrics

    def _train_step(self, state: DistTrainState
                    ) -> tuple[DistTrainState, dict]:
        rng, sk = self._split_rng(state.rng)
        items, idx, w = self._sample_weighted(state.replay, sk,
                                              self.b_local)
        params, target_params, opt_state, step, td_shard, metrics = \
            self._sgd_step(state.params, state.target_params,
                           state.opt_state, state.step, items, w)
        # fused path: draw and write-back see the same shard trees, so
        # the priority-staleness delta is identically 0 (pri_then=None)
        metrics["diag"] = {**metrics.get("diag", {}),
                           **learn_obs.replay_health_sharded(
                               self.replay, state.replay, idx, None)}
        # per-shard priority write-back
        new_replay = jax.vmap(
            lambda rs, i, td: self.replay.update_priorities(rs, i, td)
        )(state.replay, idx, td_shard)
        return DistTrainState(params, target_params, opt_state, new_replay,
                              rng, step), metrics

    def _sample_stage(self, replay_state: ReplayState, sk, k: int):
        """Pure SAMPLE stage of the split K-batch cycle, dist form of
        runtime/learner.py:SingleChipLearner._sample_stage: one
        per-shard stratified K*b_local descent + gather + global IS
        weights, chunked for the K SGD steps.

        -> (items_k [K, dp, b_local, ...], idx [dp, K*b_local]
        UN-chunked for the per-shard write-back, w_k [K, dp, b_local]
        raw — _sgd_step max-normalizes per training batch, and
        pri [dp, K*b_local] descent-time leaf priorities appended LAST
        for the staleness delta — positional readers of the tuple's
        stable prefix are unmoved)."""
        items, idx, w = self._sample_weighted(replay_state, sk,
                                              k * self.b_local)
        pri = jax.vmap(self.replay.leaf_priorities)(replay_state, idx)

        def chunked(x):
            # [dp, b_local*k, ...] -> [k, dp, b_local, ...] with chunk
            # j = strata {j, j+k, ...} (stratum s = i*k + j at [j, :, i])
            y = x.reshape(x.shape[0], self.b_local, k, *x.shape[2:])
            return jnp.moveaxis(y, 2, 0)

        items_k = jax.tree.map(chunked, items)
        w_k = chunked(w)
        return items_k, idx, w_k, pri

    def _learn_stage(self, state: DistTrainState, sample,
                     k: int) -> tuple[DistTrainState, dict]:
        """Pure LEARN stage: K SGD steps over an already-drawn sample
        + ONE vmapped per-shard write-back + target sync (static
        unrolled loop — lax.scan conv bodies are pathologically slow
        on CPU). `state.rng` must already be advanced past the draw."""
        items_k, idx, w_k, pri_k = sample
        params, target_params, opt_state, step = (
            state.params, state.target_params, state.opt_state,
            state.step)
        td_parts = []
        metrics = None
        for j in range(k):
            it = jax.tree.map(lambda x: x[j], items_k)
            params, target_params, opt_state, step, td_shard, metrics = \
                self._sgd_step(params, target_params, opt_state, step,
                               it, w_k[j])
            td_parts.append(td_shard)
        # write-back-time replay health: the shard trees NOW vs the
        # descent-time priorities pri_k — the measured staleness the
        # prefetch/K-batch relaxations accept (ROADMAP item 3)
        metrics["diag"] = {**metrics.get("diag", {}),
                           **learn_obs.replay_health_sharded(
                               self.replay, state.replay, idx, pri_k)}
        # invert the chunk transform: td_all[d, i*k + j] = parts[j][d, i]
        td_all = jnp.moveaxis(jnp.stack(td_parts, axis=0), 0, 2) \
            .reshape(self.dp, k * self.b_local)
        new_replay = jax.vmap(
            lambda rs, i, td: self.replay.update_priorities(rs, i, td)
        )(state.replay, idx, td_all)
        return DistTrainState(params, target_params, opt_state,
                              new_replay, state.rng, step), metrics

    def _split_rng(self, rng):
        """[dp] keys -> ([dp] advanced, [dp] subkeys)."""
        keys = jax.vmap(lambda kk: jax.random.split(kk, 2))(rng)
        return keys[:, 0], keys[:, 1]

    def _train_step_k(self, state: DistTrainState,
                      k: int) -> tuple[DistTrainState, dict]:
        """K grad-steps from ONE per-shard stratified sample + ONE
        priority write-back — the K-batch relaxation
        (LearnerConfig.sample_chunk), dist form of
        runtime/learner.py:DQNLearner._train_step_k; same staleness
        semantics, same interleaved-strata chunking (chunk j takes
        strata {j, j+K, ...} within every shard so each chunk spans
        the full per-shard priority range). Composed from the split
        _sample_stage/_learn_stage so the fused and double-buffered
        paths cannot drift."""
        rng, sk = self._split_rng(state.rng)
        sample = self._sample_stage(state.replay, sk, k)
        return self._learn_stage(state._replace(rng=rng), sample, k)

    # -- jitted endpoints --------------------------------------------------

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def train_step(self, state: DistTrainState):
        return self._train_step(state)

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=1)
    def train_step_k(self, state: DistTrainState, k: int):
        """Scan-free K-batch macro-step (see DQNLearner.train_step_k)."""
        return self._train_step_k(state, k)

    @partial(jax.jit, static_argnums=(0, 2))
    def sample_k(self, state: DistTrainState, k: int):
        """Standalone SAMPLE dispatch (host-side double-buffering, see
        SingleChipLearner.sample_k) — NOT donated; the caller still
        owns `state` for the learn_k on the previous draw.
        -> (sample, advanced [dp] rng)."""
        rng, sk = self._split_rng(state.rng)
        return self._sample_stage(state.replay, sk, k), rng

    @partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
    def learn_k(self, state: DistTrainState, sample, k: int):
        """Standalone LEARN dispatch on a sample drawn earlier by
        sample_k (see SingleChipLearner.learn_k; sample not donated —
        its buffers match no output shape)."""
        return self._learn_stage(state, sample, k)

    @partial(jax.jit, static_argnums=(0, 2), donate_argnums=1)
    def train_many(self, state: DistTrainState, n: int):
        """n grad-steps per dispatch; with sample_chunk=K>1 runs n//K
        K-batch macro-steps plus exact singles for any remainder; with
        sample_prefetch the macro-steps run double-buffered (next
        per-shard descent drawn before this macro-step's write-back —
        see SingleChipLearner._train_many_prefetch for the staleness
        contract)."""
        k = getattr(self.lcfg, "sample_chunk", 1)

        def body(s, _):
            s, m = self._train_step(s)
            return s, m

        if getattr(self.lcfg, "sample_prefetch", False):
            return self._train_many_prefetch(state, n, max(k, 1), body)

        if k <= 1:
            state, metrics = jax.lax.scan(body, state, None, length=n)
            return state, jax.tree.map(lambda x: x[-1], metrics)

        def body_k(s, _):
            s, m = self._train_step_k(s, k)
            return s, m

        # remainder singles FIRST: the returned last-step metrics then
        # come from the K-batch macro-steps that did the bulk of the
        # work (see DQNLearner.train_many)
        metrics = None
        if n % k:
            state, metrics = jax.lax.scan(body, state, None,
                                          length=n % k)
        if n // k:
            state, metrics = jax.lax.scan(body_k, state, None,
                                          length=n // k)
        return state, jax.tree.map(lambda x: x[-1], metrics)

    def _train_many_prefetch(self, state: DistTrainState, n: int,
                             k: int, body):
        """Dist mirror of SingleChipLearner._train_many_prefetch: the
        scan body draws macro-step i+1's per-shard sample from the
        shard trees BEFORE macro-step i's vmapped write-back, so XLA
        overlaps the next descent/gather with the K SGD steps; one
        macro-dispatch of priority staleness, prologue-fresh first
        step, final prefetched sample discarded."""
        metrics = None
        if n % k:
            state, metrics = jax.lax.scan(body, state, None,
                                          length=n % k)
        if n // k:
            rng, sk = self._split_rng(state.rng)
            pending = self._sample_stage(state.replay, sk, k)
            state = state._replace(rng=rng)

            def body_pf(carry, _):
                s, pend = carry
                rng, sk = self._split_rng(s.rng)
                nxt = self._sample_stage(s.replay, sk, k)
                s, m = self._learn_stage(s._replace(rng=rng), pend, k)
                return (s, nxt), m

            (state, _), metrics = jax.lax.scan(
                body_pf, (state, pending), None, length=n // k)
        return state, jax.tree.map(lambda x: x[-1], metrics)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add(self, state: DistTrainState, items: Any,
            td_abs: jax.Array) -> DistTrainState:
        """items: pytree of [dp, B, ...]; td_abs: [dp, B].

        add_lockstep, NOT jax.vmap(add): vmap batches the in-place
        dynamic_update_slice ring write into a lax.scatter, which
        materializes a full shard-storage copy per add (the exact HLO
        temp the byte-row layout eliminated — replay/packing.py). The
        lockstep form exploits the dist ingest contract (equal [dp, B]
        blocks every add -> equal shard cursors) to write all shards
        with one in-place multi-axis DUS.
        """
        items = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                jnp.asarray(x), self._dp_sharding), items)
        return state._replace(
            replay=self.replay.add_lockstep(state.replay, items, td_abs))

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add_many(self, state: DistTrainState, items: Any,
                 td_abs: jax.Array) -> DistTrainState:
        """Coalesced ingest: items [g, dp, B, ...], td_abs [g, dp, B] —
        g staged blocks fused into ONE donated dispatch, so the driver
        takes _state_lock once per group instead of once per block and
        a burst of ingest stops interleaving small add dispatches with
        train_many (runtime/ingest.py).

        UNROLLED Python loop over the static g axis, not lax.scan: a
        scan carrying the replay storage re-materializes the full
        storage per iteration on the CPU backend (PERF.md "CPU scan
        pathology"), while the unrolled chain keeps each add_lockstep's
        in-place multi-axis DUS aliasing on every backend. g is small
        (ingest_coalesce), so trace/compile cost is negligible.
        """
        items = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                jnp.asarray(x), self._group_sharding), items)
        rs = state.replay
        for j in range(td_abs.shape[0]):
            rs = self.replay.add_lockstep(
                rs, jax.tree.map(lambda x, j=j: x[j], items), td_abs[j])
        return state._replace(replay=rs)

    # -- tiered cold store endpoints (runtime/driver.py eviction cycle;
    # per-shard directed form — each shard evicts its OWN lowest-mass
    # region, so the tier runs on the dp-sharded ring) -------------------

    @partial(jax.jit, static_argnums=(0, 2))
    def evict_region(self, state: DistTrainState, block: int):
        """-> (start [dp], staging-layout items [dp, block, ...],
        stored leaf priorities [dp, ...]) — shard d's lowest-priority-
        mass `block`-unit region, planned independently per shard. NOT
        donated: the driver fetches the result to host (ColdStore.put
        per shard) before add_at overwrites the regions in place.
        evict_plan/read_region are pure reads, so jax.vmap is safe
        here — the scatter-rebatch hazard only bites donated in-place
        writes (add_at below uses the unrolled per-shard DUS form)."""
        def plan_read(rs):
            start = self.replay.evict_plan(rs, block)
            items, pri = self.replay.read_region(rs, start, block)
            return start, items, pri
        return jax.vmap(plan_read)(state.replay)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def add_at(self, state: DistTrainState, items: Any,
               td_abs: jax.Array, start: jax.Array) -> DistTrainState:
        """Directed ingest add: shard d overwrites its evict_region
        start[d] instead of the lockstep FIFO cursor (cold tier on +
        ring full; the default path never calls this)."""
        items = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                jnp.asarray(x), self._dp_sharding), items)
        return state._replace(
            replay=self.replay.add_at_lockstep(state.replay, items,
                                               td_abs, start))

    # -- weight publication (learner -> inference server over ICI) --------

    def publish_params(self, state: DistTrainState) -> Any:
        """Fully-replicated param copy for the actor inference server.

        The tp all-gather happens over ICI (XLA resharding), mirroring
        the reference's learner->actor weight broadcast (SURVEY.md §2.3
        item 3), without interrupting train_many dispatches.

        The resharding runs under jit with replicated out_shardings —
        the multihost-safe form (device_put cannot target non-
        addressable shardings), and the jit's fresh output buffers also
        make the copy donation-safe: the learner jits donate the
        TrainState, so an aliased publication would hand the inference
        server buffers that the next add/train_step deletes.
        """
        if self._reshard is None:
            # built once: a fresh jax.jit wrapper per publish would
            # retrace/recompile on the hot weight-broadcast path
            self._reshard = jax.jit(
                partial(jax.tree.map, jnp.copy),
                out_shardings=jax.tree.map(
                    lambda _: self._repl_sharding, state.params))
        return self._reshard(state.params)

    # -- per-shard observability -------------------------------------------

    def shard_stats(self, state: DistTrainState) -> dict:  # apexlint: host-sync(documented off the hot loop: teardown, publish boundaries, bench epilogues)
        """Per-shard replay fill/sample statistics for the obs plane
        and the multichip bench lane (bench.py --multichip):

        - sizes: ring occupancy per shard in the replay's native item
          units (transitions for flat/frame-ring, sequences for R2D2);
        - live: live item count per shard (frame-ring layouts exclude
          dead episode-pad slots via `live_transitions`; other layouts
          report sizes);
        - fill: sizes / per-shard capacity;
        - tree_mass: per-shard sum-tree root — the stratified-sampling
          denominator. Skew here is IS-weight skew (down-weighted by
          the global-N recipe in _sample_weighted), not an error.

        Host-side device fetch; call off the hot loop (teardown,
        publish boundaries, bench epilogues)."""
        rs = state.replay
        sizes = np.asarray(rs.size).reshape(-1).astype(np.int64)
        live = sizes
        if hasattr(self.replay, "live_transitions"):
            live = np.asarray(self.replay.live_transitions(rs)
                              ).reshape(-1).astype(np.int64)
        cap = float(max(int(self.replay.capacity), 1))
        # tree layout is [dp, 2*cap] with the root mass at index 1
        mass = np.asarray(rs.tree[:, 1]).astype(np.float64)
        fill = sizes / cap
        return {
            "sizes": sizes.tolist(),
            "live": live.tolist(),
            "fill": [round(float(f), 6) for f in fill],
            "tree_mass": [round(float(m), 4) for m in mass],
            "fill_min": float(fill.min()),
            "fill_max": float(fill.max()),
        }


class DistDQNLearner(_DistLearnerBase):
    """Flat n-step double-DQN over the mesh (SURVEY.md §3.3)."""

    def __init__(self, net_apply: Callable, replay: PrioritizedReplay,
                 lcfg, mesh: Mesh,
                 optimizer: optax.GradientTransformation | None = None):
        super().__init__(replay, lcfg, mesh, optimizer)
        self.net_apply = net_apply
        self.loss_fn = make_dqn_loss(
            net_apply, double=lcfg.double_dqn, huber_delta=lcfg.huber_delta,
            rescale=lcfg.value_rescale)

    def _make_batch(self, items: Any) -> TransitionBatch:
        return TransitionBatch(
            obs=items["obs"], actions=items["action"],
            rewards=items["reward"], next_obs=items["next_obs"],
            discounts=items["discount"])


class DistSequenceLearner(_DistLearnerBase):
    """R2D2 stored-state sequences over the mesh (SURVEY.md §3.4; the
    r2d2 config attests dp=4 x tp=2).

    Replay shards hold whole sequences as items (same per-shard trees);
    the burn-in unroll + n-step sequence loss runs on the flattened
    [dp*b_local] sequence batch — the LSTM time axis stays unsharded
    (SURVEY.md §5 long-context: shard the batch axis, scan the time
    axis), and the per-SEQUENCE eta-mixed |TD| writes back per shard.
    """

    def __init__(self, net_apply_seq: Callable, replay: PrioritizedReplay,
                 lcfg, rcfg, mesh: Mesh,
                 optimizer: optax.GradientTransformation | None = None):
        super().__init__(replay, lcfg, mesh, optimizer)
        self.net_apply_seq = net_apply_seq
        self.loss_fn = make_r2d2_loss(
            net_apply_seq, burn_in=rcfg.burn_in, n_step=lcfg.n_step,
            gamma=lcfg.gamma, huber_delta=lcfg.huber_delta,
            double=lcfg.double_dqn, rescale=lcfg.value_rescale,
            priority_eta=rcfg.priority_eta)

    def _make_batch(self, items: Any):
        return batch_to_sequence_batch(items)
