"""Metric registry: counters, gauges, fixed-bucket histograms.

`utils/metrics.py` is a flat scalar JSONL sink; what Ape-X health
actually needs are DISTRIBUTIONS — sampled-transition age, actor
parameter lag, |TD| priorities — whose tails (not means) are where the
staleness pathologies live (Horgan et al. 2018 §4; Kapturowski et al.
2019 on recency). This module adds the distribution layer while keeping
the JSONL stream canonical: a registry `publish()` snapshots every
instrument into one metrics record (`ctr/...`, `gauge/...` scalars and
`hist/...` plain-dict snapshots with precomputed percentiles), so a
run's JSONL remains a single self-contained artifact that
`obs/report.py` can summarize offline.

Hot-path cost: a scalar `observe()` is a `bisect` on a Python tuple of
edges plus integer bumps — no numpy allocation; the bulk `observe_many`
pays one `searchsorted` + `bincount` per call, amortized over the batch
(both hold a small per-instrument lock, uncontended in practice because
each component owns its instruments).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Iterable

import numpy as np

from ape_x_dqn_tpu.obs.health import make_lock


def geometric_edges(lo: float = 1.0, hi: float = 1e6,
                    per_decade: int = 4) -> tuple[float, ...]:
    """Geometric bucket edges covering [lo, hi] — the right shape for
    age/lag/priority distributions whose interesting structure spans
    orders of magnitude."""
    n = max(int(np.ceil(np.log10(hi / lo) * per_decade)), 1)
    return tuple(float(lo * (hi / lo) ** (i / n)) for i in range(n + 1))


class Counter:
    """Monotonic event counter."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0  # guarded-by: _lock
        self._lock = make_lock("registry.instrument")

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0  # guarded-by: _lock
        self._lock = make_lock("registry.instrument")

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Fixed-bucket histogram: bucket i counts values in
    (edges[i-1], edges[i]]; bucket 0 is the underflow (<= edges[0]) and
    the last bucket the overflow (> edges[-1])."""

    __slots__ = ("name", "_edges", "_edges_np", "_counts", "_count",
                 "_sum", "_min", "_max", "_lock")

    def __init__(self, name: str, edges: Iterable[float]):
        self.name = name
        self._edges = tuple(float(e) for e in edges)
        assert self._edges == tuple(sorted(self._edges)) and self._edges, \
            f"histogram {name!r} needs ascending, non-empty edges"
        self._edges_np = np.asarray(self._edges, np.float64)
        self._counts = np.zeros(len(self._edges) + 1, np.int64)  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock
        self._sum = 0.0  # guarded-by: _lock
        self._min = float("inf")  # guarded-by: _lock
        self._max = float("-inf")  # guarded-by: _lock
        self._lock = make_lock("registry.instrument")

    def observe(self, v: float) -> None:
        v = float(v)
        if v != v:  # NaN: a diverged TD must not poison the buckets
            return
        i = bisect_left(self._edges, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    def observe_many(self, values: np.ndarray) -> None:
        v = np.asarray(values, np.float64).ravel()
        v = v[~np.isnan(v)]
        if not v.size:
            return
        idx = np.searchsorted(self._edges_np, v, side="left")
        binned = np.bincount(idx, minlength=self._counts.size)
        with self._lock:
            self._counts += binned
            self._count += int(v.size)
            self._sum += float(v.sum())
            self._min = min(self._min, float(v.min()))
            self._max = max(self._max, float(v.max()))

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, q: float) -> float | None:
        """Approximate q-th percentile (q in [0, 100]) from the bucket
        upper edges — the resolution is the bucket width, which is what
        fixed buckets buy. None when empty."""
        with self._lock:
            return self._percentile_locked(q)

    def _percentile_locked(self, q: float) -> float | None:
        if self._count == 0:
            return None
        target = self._count * q / 100.0
        cum = 0
        for i, c in enumerate(self._counts):
            cum += int(c)
            if cum >= target:
                if i == 0:
                    return min(self._edges[0], self._max)
                if i >= len(self._edges):
                    return self._max
                return min(self._edges[i], self._max)
        return self._max

    def snapshot(self) -> dict[str, Any]:
        """Plain-python dict (JSON-safe: no numpy scalars, no NaN/Inf)
        for the metrics JSONL stream."""
        with self._lock:
            empty = self._count == 0
            return {
                "count": int(self._count),
                "sum": float(self._sum),
                "min": None if empty else float(self._min),
                "max": None if empty else float(self._max),
                "edges": list(self._edges),
                "counts": [int(c) for c in self._counts],
                "p50": self._percentile_locked(50),
                "p90": self._percentile_locked(90),
                "p99": self._percentile_locked(99),
            }


class MetricRegistry:
    """Get-or-create instrument registry + one-record JSONL publish."""

    def __init__(self):
        self._lock = make_lock("registry.tables")
        self._counters: dict[str, Counter] = {}  # guarded-by: _lock
        self._gauges: dict[str, Gauge] = {}  # guarded-by: _lock
        self._hists: dict[str, Histogram] = {}  # guarded-by: _lock

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str,
                  edges: Iterable[float] | None = None) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(
                    name, edges if edges is not None else geometric_edges())
            return h

    def snapshot_frame(self) -> dict[str, Any]:
        """Every instrument's current value as one JSON-safe dict
        (`{"ctr": {...}, "gauge": {...}, "hist": {...}}`) — the
        instrument payload of a fleet telemetry frame (obs/fleet.py).
        Same snapshots publish() folds into the JSONL, minus the
        kind-prefix flattening: the frame keeps them nested so the
        aggregator can re-prefix them per peer."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._hists.values())
        return {
            "ctr": {c.name: c.value for c in counters},
            "gauge": {g.name: g.value for g in gauges},
            "hist": {h.name: h.snapshot() for h in hists},
        }

    def publish(self, metrics, step: int,
                extra: dict[str, Any] | None = None) -> None:
        """One JSONL record carrying every instrument's current value:
        `ctr/<n>` and `gauge/<n>` scalars, `hist/<n>` snapshot dicts
        (the Metrics sink passes dicts through to JSON untouched)."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._hists.values())
        payload: dict[str, Any] = dict(extra or {})
        for c in counters:
            payload[f"ctr/{c.name}"] = c.value
        for g in gauges:
            payload[f"gauge/{g.name}"] = g.value
        for h in hists:
            payload[f"hist/{h.name}"] = h.snapshot()
        if payload:
            metrics.log(step, **payload)
